"""Headline benchmark: hash aggregate with grouping keys, rows/sec.

Reference baseline: Spark Tungsten "codegen + vectorized hashmap" path at
93.5 M rows/s (`sql/core/src/test/.../benchmark/AggregateBenchmark.scala:125-131`,
i7-4960HQ) — see BASELINE.md. Same workload shape: N rows, grouped sum/count
over a keyed column, executed as one fused XLA program on the device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_ROWS_PER_S = 93.5e6


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spark_tpu.kernels import grouped_aggregate  # noqa: F401
    from spark_tpu.sql.session import SparkSession
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import physical as P
    from spark_tpu.sql.planner import QueryExecution
    from spark_tpu.kernels import compact

    n = 1 << 22  # 4.19M rows per iteration (static-shape batch)
    rng = np.random.default_rng(7)

    session = SparkSession.builder.appName("bench").getOrCreate()
    session.conf.set("spark.tpu.mesh.shards", "1")
    df = session.createDataFrame({
        "k": rng.integers(0, 1024, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    q = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("*").alias("c"))

    qe = QueryExecution(session, q._plan)
    pq = qe.planned
    physical = pq.physical

    def run(leaves):
        ctx = P.ExecContext(jnp, list(leaves))
        out = physical.run(ctx)
        c = compact(jnp, out)
        return c, c.num_rows()

    fn = jax.jit(run)
    dev_leaves = tuple(b.to_device() for b in pq.leaves)

    # warmup / compile
    out, nr = fn(dev_leaves)
    jax.block_until_ready(out.vectors[0].data)
    assert int(np.asarray(nr)) == 1024, int(np.asarray(nr))

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out, nr = fn(dev_leaves)
    jax.block_until_ready(out.vectors[0].data)
    dt = time.perf_counter() - t0

    rows_per_s = n * iters / dt
    print(json.dumps({
        "metric": "hash_agg_keys_rows_per_sec",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / BASELINE_ROWS_PER_S, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
