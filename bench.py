"""Headline benchmark: hash aggregate with grouping keys, rows/sec.

Reference baseline: Spark Tungsten "codegen + vectorized hashmap" path at
93.5 M rows/s (`sql/core/src/test/.../benchmark/AggregateBenchmark.scala:125-131`,
i7-4960HQ) — see BASELINE.md.  Same workload shape: N rows, grouped sum/count
over a keyed column, executed through the planner as one fused XLA program.
The aggregation itself runs on the MXU (`kernels._mxu_grouped_aggregate`:
one-hot matmul over 8-bit limb planes, bit-exact int64 sums).

Timing methodology: the per-batch step runs ITERS times inside a single
`lax.fori_loop` with a carried dependency on both the group count and the
aggregated sums (so no iteration can be hoisted or dead-code-eliminated),
and one scalar is fetched at the end — device-dispatch and host-link
round-trips are amortized over all iterations, the way a real pipeline
amortizes them over a stream of batches.  Inputs are perturbed per
iteration from the carried index.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

BASELINE_ROWS_PER_S = 93.5e6

N = 1 << 22          # rows per iteration (static-shape batch)
ITERS = 20
GROUPS = 1024
RESULT_CAP = 8192    # static result capacity (>= bucket cap of the MXU path)


def _slice_batch(batch, cap: int):
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    vecs = [ColumnVector(v.data[:cap], v.dtype,
                         None if v.valid is None else v.valid[:cap],
                         v.dictionary) for v in batch.vectors]
    rv = None if batch.row_valid is None else batch.row_valid[:cap]
    return ColumnBatch(batch.names, vecs, rv, cap)


def main() -> None:
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.kernels import compact
    from spark_tpu.sql.session import SparkSession
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import physical as P
    from spark_tpu.sql.planner import QueryExecution

    rng = np.random.default_rng(7)
    session = SparkSession.builder.appName("bench").getOrCreate()
    session.conf.set("spark.tpu.mesh.shards", "1")
    keys = rng.integers(0, GROUPS, N).astype(np.int64)
    vals = rng.integers(0, 100, N).astype(np.int64)
    df = session.createDataFrame({"k": keys, "v": vals})
    q = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("*").alias("c"))

    qe = QueryExecution(session, q._plan)
    pq = qe.planned
    physical = pq.physical

    def step(leaves, bump):
        """One planner-built aggregation over the (perturbed) input batch.

        BOTH columns depend on the carried index — keys via an XOR that
        preserves the [0, GROUPS) range — so no reduction, bucket-code, or
        plane computation is loop-invariant and hoistable."""
        perturbed = []
        for b in leaves:
            vecs = []
            for name, v in zip(b.names, b.vectors):
                if name == "v":
                    data = v.data + bump
                elif name == "k":
                    data = v.data ^ (bump & jnp.int64(GROUPS - 1))
                else:
                    data = v.data
                vecs.append(ColumnVector(data, v.dtype, v.valid, v.dictionary))
            perturbed.append(ColumnBatch(b.names, vecs, b.row_valid,
                                         b.capacity))
        ctx = P.ExecContext(jnp, perturbed)
        out = physical.run(ctx)
        c = compact(jnp, _slice_batch(out, RESULT_CAP))
        return c, c.num_rows()

    def run_loop(leaves):
        def body(i, acc):
            c, nr = step(leaves, i.astype(jnp.int64))
            # depend on counts AND sums: nothing may be hoisted or DCE'd
            s_dep = c.vectors[1].data.sum()
            return acc + nr + (s_dep & jnp.int64(1))
        return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))

    dev_leaves = tuple(b.to_device() for b in pq.leaves)

    # correctness gate: one un-perturbed run vs the numpy oracle
    c0, nr0 = jax.jit(lambda l: step(l, jnp.int64(0)))(dev_leaves)
    assert int(np.asarray(nr0)) == GROUPS, int(np.asarray(nr0))
    got_k = np.asarray(c0.vectors[0].data)[:GROUPS]
    got_s = np.asarray(c0.vectors[1].data)[:GROUPS]
    expect = np.zeros(GROUPS, np.int64)
    np.add.at(expect, keys, vals)
    order = np.argsort(got_k)
    assert np.array_equal(got_s[order], expect), "sum mismatch vs oracle"

    loop = jax.jit(run_loop)
    _ = int(np.asarray(loop(dev_leaves)))          # compile + warm
    t0 = time.perf_counter()
    acc = int(np.asarray(loop(dev_leaves)))        # one fetch syncs all iters
    dt = time.perf_counter() - t0
    assert acc >= GROUPS * ITERS, acc

    rows_per_s = N * ITERS / dt
    print(json.dumps({
        "metric": "hash_agg_keys_rows_per_sec",
        "value": round(rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_s / BASELINE_ROWS_PER_S, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
