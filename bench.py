"""Headline benchmarks, hardened against flaky TPU-backend initialization.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Primary metric — hash aggregate with grouping keys, rows/sec.  Reference
baseline: Spark Tungsten "codegen + vectorized hashmap" at 93.5 M rows/s
(`sql/core/src/test/.../benchmark/AggregateBenchmark.scala:125-131`,
i7-4960HQ) — see BASELINE.md.  Same workload shape: N rows, grouped
sum/count over a keyed column, executed through the planner as one fused
XLA program; the aggregation runs on the MXU
(`kernels._mxu_grouped_aggregate`: one-hot matmul over 8-bit limb planes,
bit-exact int64 sums).

Secondary metric (reported in the same JSON object) — a TPC-DS q3-shaped
pipeline: fact⋈dim broadcast join → filter → grouped sum → sort, vs the
Spark broadcast-hash-join baseline of 65.3 M rows/s
(`JoinBenchmark.scala:42-47`).

Timing methodology: the per-batch step runs ITERS times inside one
`lax.fori_loop` with a carried dependency on both the row count and the
aggregated values (nothing can be hoisted or dead-code-eliminated), and
one scalar is fetched at the end — dispatch and host-link round-trips are
amortized the way a real pipeline amortizes them over a stream of batches.
Inputs are perturbed per iteration from the carried index.

Robustness (round-1 failure was `RuntimeError: Unable to initialize
backend 'axon'` before any measurement): the default entry point is an
ORCHESTRATOR that runs the actual benchmark in a child process, because a
failed backend init poisons the parent's jax process state.  It retries
the TPU child with backoff, then falls back to CPU (reported via the
"backend" key), and on total failure still prints a well-formed JSON line
carrying the error tail instead of a raw traceback.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import threading
import time

BASELINE_AGG_ROWS_PER_S = 93.5e6    # AggregateBenchmark.scala:125-131
BASELINE_JOIN_ROWS_PER_S = 65.3e6   # JoinBenchmark.scala:42-47
BASELINE_SORT_ROWS_PER_S = 188.4e6  # SortBenchmark.scala:120-128 (radix)
BASELINE_SCAN_ROWS_PER_S = 73.0e6   # ParquetReadBenchmark.scala:140-143

N = 1 << 22          # rows per iteration for the agg bench (static batch)
ITERS = 20
GROUPS = 1024
RESULT_CAP = 8192    # static result capacity (>= bucket cap of MXU path)

J_FACT = 1 << 21     # q3-shape: fact rows per iteration
J_DIM = 2048         # q3-shape: dimension rows (broadcast side)
J_BRANDS = 64
J_ITERS = 10

S_ROWS = 1 << 22     # sort lane: rows per iteration (25M-longs baseline shape)
S_ITERS = 10

P_ROWS = 1 << 22     # parquet scan lane: rows in the generated file
P_COLS = 10          # wide file; pruning must read only the summed column
P_REPS = 4

SH_CAP = 1 << 18     # shuffle lane: rows per source batch
SH_BATCHES = 8       # source batches per exchange pass
SH_RECEIVERS = 8     # fan-out (the repo's 8-process world)
SH_THREADS = 4       # fetch-pool width (shuffle.io.fetchThreads default)

DJ_ROWS = 1 << 17    # distributed-join lane: rows per table (full dataset)
DJ_KEYS = 1 << 14    # join-key cardinality (multiplicity 8 per side)
DS_ROWS = 1 << 18    # distsort lane: probe rows (full dataset, SKEWED keys)
DS_BUILD = 1 << 16   # distsort lane: build rows (uniform, multiplicity 16)
DS_KEYS = 1 << 12    # distsort key cardinality; half the probe mass sits
DS_HOT = 77          # on this ONE hot key (the skew under test)
DD_ROWS = 24000      # distdict lane: rows per table (low-cardinality keys)
DD_KEYS = 2500       # distinct fat words (~30 B each: dict ~75 KiB/column)
DR_ROWS = 1 << 18    # distrle lane: time-series rows (full dataset) —
                     # sized so the exchange dwarfs the barrier overhead
DR_KEYS = 256        # distinct timestamps — each repeats 1024x, so the
                     # sorted spans carry long runs in ts/sensor/status
DA_ROWS = 1 << 20    # distadapt lane: rows per table (full dataset)
DA_KEYS = 1 << 13    # join-key cardinality
DA_CUT = 3           # right-side filter: bonus < 3 keeps ~2% of rows, a
                     # ~50x misestimate vs the plan-time raw-leaf probe
DA_PAY = 12          # left payload columns: the mass the frozen hash
                     # shuffle ships and the demoted broadcast never does
SC_ROWS = 1 << 14    # stagecache lane: fact rows (full dataset) — sized
                     # for compile-vs-dispatch accounting, not throughput
SC_KEYS = 1 << 10    # dim-key cardinality (dim side UNIQUE: fanout 1, so
                     # the per-op baseline replays without overflow retry)
GG_ROWS = 1 << 15    # distgrace lane: rows per table (full dataset)
GG_KEYS = 1 << 11    # join-key cardinality (multiplicity 16 on the right)
GG_BUDGET = 96 << 10  # host budget: below EVERY reducer's drained share
                      # (~128 KiB/side at 2 procs) but above each of the
                      # 32 grace buckets (~24 KiB both sides)

#: cold axon compiles of the fused agg/join programs run several minutes
#: (f64/i64 emulation); the persistent jax compile cache under /tmp makes
#: warm runs fast, but the timeout must cover a cold one
CHILD_TIMEOUT_S = int(os.environ.get("SPARK_TPU_BENCH_CHILD_TIMEOUT", "900"))
TPU_ATTEMPTS = int(os.environ.get("SPARK_TPU_BENCH_TPU_ATTEMPTS", "2"))
#: timed repetitions per lane; the reported figure is the MEDIAN of the
#: runs, which shields the tracked metric from one-off host stalls
#: (GC pause, cron neighbor, tunnel hiccup) that a single sample eats
BENCH_RUNS = max(3, int(os.environ.get("SPARK_TPU_BENCH_RUNS", "3")))
#: pinned BLAS/OpenMP pool width for the child: unpinned pools size to
#: the container's nproc, making run-to-run numbers depend on co-tenant
#: load; the pin is recorded in the output JSON for comparability
BENCH_THREADS = int(os.environ.get("SPARK_TPU_BENCH_THREADS", "4"))
_THREAD_ENV_VARS = ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS",
                    "MKL_NUM_THREADS", "NUMEXPR_NUM_THREADS")
BACKOFFS_S = [20, 60, 120]
#: a DOWN tunnel makes jax.devices() hang rather than raise; a child-side
#: watchdog turns that into a fast rc=3 so the orchestrator recycles
#: instead of burning the whole child timeout
PREFLIGHT_HANG_S = int(os.environ.get("SPARK_TPU_BENCH_PREFLIGHT_HANG",
                                      "150"))


# ======================================================================
# orchestrator
# ======================================================================

def _run_child(platform: str | None,
               disable_pallas: bool = False) -> tuple[int, str, str]:
    # NB: the axon plugin's sitecustomize force-sets jax_platforms and
    # ignores the JAX_PLATFORMS env var, so the platform is passed as an
    # argv flag and applied via jax.config inside the child.
    argv = [sys.executable, os.path.abspath(__file__), "--child"]
    env = dict(os.environ)
    # SPARK_TPU_PLATFORM (honored by spark_tpu at import) must not
    # override the orchestrator's per-attempt platform choice
    env.pop("SPARK_TPU_PLATFORM", None)
    if platform is not None:
        argv.append(f"--platform={platform}")
        env["SPARK_TPU_PLATFORM"] = platform
    if disable_pallas:
        env["SPARK_TPU_DISABLE_PALLAS"] = "1"
    else:
        env.pop("SPARK_TPU_DISABLE_PALLAS", None)
    for var in _THREAD_ENV_VARS:
        env[var] = str(BENCH_THREADS)
    try:
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=CHILD_TIMEOUT_S, env=env)
        return proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries bytes even under text=True
        out = e.stdout.decode(errors="replace") if e.stdout else ""
        err = e.stderr.decode(errors="replace") if e.stderr else ""
        return -1, out, err + "\n[child timed out]"


def _extract_json(stdout: str) -> dict | None:
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
                if isinstance(obj, dict) and "metric" in obj:
                    return obj
            except json.JSONDecodeError:
                continue
    return None


def _run_tpu_probes() -> None:
    """Spend a bounded budget on the window-readiness probes after a
    successful TPU bench (tools/prof_agg2.py: loop-amortized per-piece agg
    profile; tools/bisect_q3.py: remote-compile failure bisect), so a rare
    tunnel window is never wasted on manual steps.  Probe output goes to
    repo files + stderr; stdout stays one JSON line for the driver."""
    # the budget is post-metric wall-clock; the orchestrator's own worst
    # case (TPU children + backoffs) already far exceeds it, so a driver
    # timeout generous enough for the bench covers the probes too
    budget = float(os.environ.get("SPARK_TPU_BENCH_PROBE_BUDGET", "1200"))
    if budget <= 0:
        return
    here = os.path.dirname(os.path.abspath(__file__))
    t_end = time.time() + budget
    for script, out_name in [("tools/prof_agg2.py", "TPU_PROFILE_LATEST.txt"),
                             ("tools/prof_join.py", "TPU_JOIN_PROFILE_LATEST.txt"),
                             ("tools/prof_ici.py", "TPU_ICI_PROFILE_LATEST.txt"),
                             ("tools/prof_runs.py", "TPU_RUNS_PROFILE_LATEST.txt"),
                             ("tools/bisect_q3.py", "TPU_BISECT_LATEST.txt")]:
        left = t_end - time.time()
        if left < 60:
            break
        path = os.path.join(here, script)
        if not os.path.exists(path):
            continue
        out_path = os.path.join(here, out_name)
        print(f"[bench] window probe {script} (budget {int(left)}s) "
              f"-> {out_name}", file=sys.stderr)
        try:
            # append — a crashed probe must not clobber a previous
            # window's good capture
            with open(out_path, "a") as fh:
                fh.write(f"\n# {script} @ {time.strftime('%F %T')}\n")
                fh.flush()
                subprocess.run([sys.executable, path], stdout=fh,
                               stderr=subprocess.STDOUT, timeout=left)
        except subprocess.TimeoutExpired:
            print(f"[bench] probe {script} hit budget", file=sys.stderr)
        except Exception as e:  # probes must never sink the bench result
            print(f"[bench] probe {script} failed: {e}", file=sys.stderr)


def orchestrate() -> int:
    tails: list[str] = []
    # TPU attempts with the Pallas agg kernel, then one TPU attempt with
    # it disabled (Mosaic regression safety), then the CPU fallback
    attempts: list[tuple[str | None, bool]] = \
        [(None, False)] * TPU_ATTEMPTS + [(None, True), ("cpu", False)]
    for i, (platform, no_pallas) in enumerate(attempts):
        label = (platform or "tpu") + (" no-pallas" if no_pallas else "")
        print(f"[bench] attempt {i + 1}/{len(attempts)} (platform={label})",
              file=sys.stderr)
        rc, out, err = _run_child(platform, disable_pallas=no_pallas)
        obj = _extract_json(out)
        if rc == 0 and obj is not None:
            if no_pallas:
                # make a Mosaic regression VISIBLE in the tracked metric
                obj["backend"] = "tpu-no-pallas"
            if platform == "cpu":
                obj["backend"] = "cpu-fallback"
            print(json.dumps(obj))
            sys.stdout.flush()
            if str(obj.get("backend", "")).startswith("tpu"):
                _run_tpu_probes()
            return 0
        tail = (err or out).strip().splitlines()[-6:]
        tails.append(f"[{label} rc={rc}] " + " | ".join(tail))
        print(f"[bench] attempt failed (rc={rc}); tail: {tail}",
              file=sys.stderr)
        # back off only before another TPU attempt; the CPU fallback does
        # not depend on TPU recovery
        if i + 1 < len(attempts) and attempts[i + 1][0] is None:
            delay = BACKOFFS_S[min(i, len(BACKOFFS_S) - 1)]
            print(f"[bench] backing off {delay}s", file=sys.stderr)
            time.sleep(delay)
    print(json.dumps({
        "metric": "hash_agg_keys_rows_per_sec",
        "value": 0.0,
        "unit": "rows/s",
        "vs_baseline": 0.0,
        "error": " || ".join(tails)[-1500:],
    }))
    return 0


# ======================================================================
# child: the actual measurement
# ======================================================================

def _slice_batch(batch, cap: int):
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    vecs = [ColumnVector(v.data[:cap], v.dtype,
                         None if v.valid is None else v.valid[:cap],
                         v.dictionary) for v in batch.vectors]
    rv = None if batch.row_valid is None else batch.row_valid[:cap]
    return ColumnBatch(batch.names, vecs, rv, cap)


def _median_rate(timed_fn, work_items: int) -> float:
    """One warm call (compile/populate caches), then ``BENCH_RUNS`` timed
    calls; returns the MEDIAN rows/sec so a single stalled run cannot
    move the tracked metric."""
    timed_fn()
    rates = []
    for _ in range(BENCH_RUNS):
        t0 = time.perf_counter()
        timed_fn()
        rates.append(work_items / (time.perf_counter() - t0))
    return statistics.median(rates)


def _preflight():
    """Backend init with in-process retry; returns the platform name.

    Runs jax.devices() on a watchdog thread: a down tunnel HANGS instead
    of raising, and the child must fail fast (rc=3) so the orchestrator
    can back off and retry rather than eat the whole child timeout."""
    import threading

    import jax
    last = None
    for attempt in range(3):
        box: list = []

        def probe():
            try:
                box.append(jax.devices())
            except BaseException as e:      # noqa: BLE001
                box.append(e)

        th = threading.Thread(target=probe, daemon=True)
        th.start()
        th.join(PREFLIGHT_HANG_S)
        if not box:
            print("[bench-child] jax.devices() hung "
                  f"{PREFLIGHT_HANG_S}s: backend tunnel down", file=sys.stderr)
            os._exit(3)                     # thread may be stuck in C++
        if not isinstance(box[0], BaseException):
            devs = box[0]
            print(f"[bench-child] devices: {devs}", file=sys.stderr)
            return devs[0].platform
        if not isinstance(box[0], RuntimeError):
            raise box[0]    # deterministic (bad platform, etc): no retry
        last = box[0]
        print(f"[bench-child] jax.devices() failed "
              f"(attempt {attempt + 1}): {last}", file=sys.stderr)
        if attempt < 2:
            time.sleep(5 * (attempt + 1))
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                pass
    raise last


def _bench_hash_agg(jax, jnp, np, session):
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.kernels import compact
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import physical as P
    from spark_tpu.sql.planner import QueryExecution

    rng = np.random.default_rng(7)
    keys = rng.integers(0, GROUPS, N).astype(np.int64)
    vals = rng.integers(0, 100, N).astype(np.int64)
    df = session.createDataFrame({"k": keys, "v": vals})
    q = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("*").alias("c"))
    pq = QueryExecution(session, q._plan).planned
    physical = pq.physical

    def step(leaves, bump):
        # BOTH columns depend on the carried index — keys via an XOR that
        # preserves [0, GROUPS) — so nothing is loop-invariant.
        perturbed = []
        for b in leaves:
            vecs = []
            for name, v in zip(b.names, b.vectors):
                if name == "v":
                    data = v.data + bump
                elif name == "k":
                    data = v.data ^ (bump & jnp.int64(GROUPS - 1))
                else:
                    data = v.data
                vecs.append(ColumnVector(data, v.dtype, v.valid, v.dictionary))
            perturbed.append(ColumnBatch(b.names, vecs, b.row_valid,
                                         b.capacity))
        ctx = P.ExecContext(jnp, perturbed)
        out = physical.run(ctx)
        c = compact(jnp, _slice_batch(out, RESULT_CAP))
        return c, c.num_rows()

    def run_loop(leaves):
        def body(i, acc):
            c, nr = step(leaves, i.astype(jnp.int64))
            s_dep = c.vectors[1].data.sum()
            return acc + nr + (s_dep & jnp.int64(1))
        return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))

    dev_leaves = tuple(b.to_device() for b in pq.leaves)

    # correctness gate: one un-perturbed run vs the numpy oracle
    c0, nr0 = jax.jit(lambda l: step(l, jnp.int64(0)))(dev_leaves)
    assert int(np.asarray(nr0)) == GROUPS, int(np.asarray(nr0))
    got_k = np.asarray(c0.vectors[0].data)[:GROUPS]
    got_s = np.asarray(c0.vectors[1].data)[:GROUPS]
    expect = np.zeros(GROUPS, np.int64)
    np.add.at(expect, keys, vals)
    order = np.argsort(got_k)
    assert np.array_equal(got_s[order], expect), "sum mismatch vs oracle"

    loop = jax.jit(run_loop)

    def timed():
        acc = int(np.asarray(loop(dev_leaves)))    # one fetch syncs all iters
        assert acc >= GROUPS * ITERS, acc
    return _median_rate(timed, N * ITERS)


def _bench_q3_join(jax, jnp, np, session, with_sort: bool = True):
    """TPC-DS q3 shape: fact ⋈ dim (broadcast) → filter → group-sum → sort.

    ``with_sort=False`` drops the final orderBy — the fallback program
    when the full plan crashes a remote compiler (round-1 HTTP 500), so
    the lane still lands a join+agg number with the failure on record."""
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.kernels import compact
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import physical as P
    from spark_tpu.sql.planner import QueryExecution

    rng = np.random.default_rng(11)
    f_sk = rng.integers(0, J_DIM, J_FACT).astype(np.int64)
    f_price = rng.integers(1, 1000, J_FACT).astype(np.int64)
    d_sk = np.arange(J_DIM, dtype=np.int64)
    d_brand = rng.integers(0, J_BRANDS, J_DIM).astype(np.int64)
    d_year = rng.integers(1998, 2003, J_DIM).astype(np.int64)

    fact = session.createDataFrame({"sk": f_sk, "price": f_price})
    dim = session.createDataFrame({"d_sk": d_sk, "brand": d_brand,
                                   "year": d_year})
    q = (fact.join(dim, fact["sk"] == dim["d_sk"])
             .filter(dim["year"] == 2000)
             .groupBy("brand").agg(F.sum("price").alias("rev")))
    if with_sort:
        q = q.orderBy(F.col("rev").desc())
    pq = QueryExecution(session, q._plan).planned
    physical = pq.physical

    def step(leaves, bump):
        # fact keys AND values depend on the carried index (key XOR
        # preserves [0, J_DIM)) so the join build/probe cannot be hoisted
        # out of the timing loop as loop-invariant code.
        perturbed = []
        for b in leaves:
            vecs = []
            for name, v in zip(b.names, b.vectors):
                if name == "price":
                    data = v.data + bump
                elif name == "sk":
                    data = v.data ^ (bump & jnp.int64(J_DIM - 1))
                else:
                    data = v.data
                vecs.append(ColumnVector(data, v.dtype, v.valid, v.dictionary))
            perturbed.append(ColumnBatch(b.names, vecs, b.row_valid,
                                         b.capacity))
        ctx = P.ExecContext(jnp, perturbed)
        out = physical.run(ctx)
        c = compact(jnp, _slice_batch(out, RESULT_CAP))
        return c, c.num_rows()

    def run_loop(leaves):
        def body(i, acc):
            c, nr = step(leaves, i.astype(jnp.int64))
            s_dep = c.vectors[1].data.sum()
            return acc + nr + (s_dep & jnp.int64(1))
        return jax.lax.fori_loop(0, J_ITERS, body, jnp.int64(0))

    dev_leaves = tuple(b.to_device() for b in pq.leaves)

    # correctness gate vs numpy oracle
    c0, nr0 = jax.jit(lambda l: step(l, jnp.int64(0)))(dev_leaves)
    sel = d_year[f_sk] == 2000
    expect = np.zeros(J_BRANDS, np.int64)
    np.add.at(expect, d_brand[f_sk[sel]], f_price[sel])
    # prices are >= 1, so sum > 0 iff the brand matched any fact row
    n_expected = int((expect > 0).sum())
    got_n = int(np.asarray(nr0))
    got_rev = np.asarray(c0.vectors[1].data)[:got_n]
    exp_rev = np.sort(expect[expect > 0])[::-1]
    assert got_n == n_expected, (got_n, n_expected)
    assert np.array_equal(np.sort(got_rev)[::-1], exp_rev), "q3 rev mismatch"

    loop = jax.jit(run_loop)
    return _median_rate(lambda: int(np.asarray(loop(dev_leaves))),
                        J_FACT * J_ITERS)


def _bench_sort(jax, jnp, np, session):
    """Global sort of S_ROWS random int64 keys through the planner, vs the
    reference radix sort at 188.4 M rows/s (`SortBenchmark.scala:120-128`).
    """
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import physical as P
    from spark_tpu.sql.planner import QueryExecution

    rng = np.random.default_rng(13)
    xs = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max,
                      S_ROWS, dtype=np.int64)
    df = session.createDataFrame({"x": xs}).orderBy(F.col("x"))
    pq = QueryExecution(session, df._plan).planned
    physical = pq.physical

    def step(leaves, bump):
        perturbed = []
        for b in leaves:
            vecs = [ColumnVector(v.data ^ bump, v.dtype, v.valid,
                                 v.dictionary) for v in b.vectors]
            perturbed.append(ColumnBatch(b.names, vecs, b.row_valid,
                                         b.capacity))
        ctx = P.ExecContext(jnp, perturbed)
        out = physical.run(ctx)
        return out.vectors[0].data

    def run_loop(leaves):
        def body(i, acc):
            s = step(leaves, i.astype(jnp.int64))
            # every 64k-th element of the SORTED output feeds the carry:
            # the whole permutation is live, nothing hoists
            return acc + s[:: 1 << 16].sum() + s[0] + s[-1]
        return jax.lax.fori_loop(0, S_ITERS, body, jnp.int64(0))

    dev_leaves = tuple(b.to_device() for b in pq.leaves)

    # correctness gate
    s0 = np.asarray(jax.jit(lambda l: step(l, jnp.int64(0)))(dev_leaves))
    assert np.array_equal(s0, np.sort(xs)), "sort mismatch vs numpy"

    loop = jax.jit(run_loop)
    return _median_rate(lambda: int(np.asarray(loop(dev_leaves))),
                        S_ROWS * S_ITERS)


def _bench_parquet_scan(np, session):
    """End-to-end parquet scan+sum of one int column out of a P_COLS-wide
    file (pruned read), vs the vectorized reader at 73 M rows/s
    (`ParquetReadBenchmark.scala:140-143`).  Wall-clock includes file IO —
    the relation cache is cleared per repetition."""
    import pandas as pd

    from spark_tpu import io as tio
    from spark_tpu.sql import functions as F

    path = f"/tmp/spark_tpu_bench_scan_{P_ROWS}x{P_COLS}.parquet"
    marker = os.path.join(path, "_SUCCESS")
    if not os.path.exists(marker):
        rng = np.random.default_rng(17)
        cols = {"x": rng.integers(0, 1 << 30, P_ROWS).astype(np.int64)}
        for i in range(P_COLS - 1):
            cols[f"pad{i}"] = rng.integers(0, 1000, P_ROWS).astype(np.int64)
        os.makedirs(path, exist_ok=True)
        pd.DataFrame(cols).to_parquet(
            os.path.join(path, "part-000.parquet"), index=False,
            row_group_size=1 << 20)
        open(marker, "w").close()

    df = session.read.parquet(path).agg(F.sum("x").alias("s"))
    tio._relation_cache.clear()
    (expect,), = df.collect()               # warm-up + self-consistency

    def timed():
        for _ in range(P_REPS):
            tio._relation_cache.clear()
            (s,), = df.collect()
            assert s == expect
    return _median_rate(timed, P_ROWS * P_REPS)


def _bench_shuffle(np):
    """Shuffle data-plane lane: one routed exchange, new plane vs seed.

    SH_BATCHES source batches route to SH_RECEIVERS receivers.  The NEW
    plane buckets each source batch once (``kernels.partition_bucket``,
    untimed here — it rides the device exchange step in production),
    then times encode→write→read→decode of the compact slices through
    a ``SH_THREADS``-wide pool (the wire codec + fetch-pool path of
    ``hostshuffle``).  The SEED plane is timed over the SAME logical
    rows the way the old ``put()``/``collect()`` shipped them: pickle
    of fully-padded static-capacity batches, written and read serially.
    Rows/sec counts live rows for both, so the ratio is a pure
    data-plane speedup for identical exchange content."""
    import pickle
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from spark_tpu import kernels, types as T, wire
    from spark_tpu.columnar import ColumnBatch, ColumnVector

    rng = np.random.default_rng(23)
    routed, padded = [], []
    for _ in range(SH_BATCHES):
        vecs = [
            ColumnVector(rng.integers(0, 1024, SH_CAP).astype(np.int64),
                         T.int64, None, None),
            ColumnVector(rng.integers(0, 100, SH_CAP).astype(np.int64),
                         T.int64, None, None),
            ColumnVector(rng.random(SH_CAP), T.float64, None, None),
            ColumnVector(rng.integers(0, 8, SH_CAP).astype(np.int32),
                         T.string, None,
                         tuple(f"cat{j}" for j in range(8))),
        ]
        src = ColumnBatch(["k", "v", "f", "s"], vecs, None, SH_CAP)
        pids = (np.asarray(src.vectors[0].data)
                % SH_RECEIVERS).astype(np.int32)
        b, off, cnt = kernels.partition_bucket(np, src, pids, SH_RECEIVERS)
        b = b.to_host()
        for r in range(SH_RECEIVERS):
            sl = kernels.slice_rows(b, int(off[r]), int(cnt[r]))
            routed.append(sl)
            # the same rows as the seed plane shipped them: padded back
            # to the full static capacity with a row-validity mask
            rv = np.zeros(SH_CAP, bool)
            rv[: int(cnt[r])] = True
            pv = [ColumnVector(np.resize(np.asarray(v.data), SH_CAP),
                               v.dtype, None, v.dictionary)
                  for v in sl.vectors]
            padded.append(ColumnBatch(list(sl.names), pv, rv, SH_CAP))
    live = sum(b.capacity for b in routed)
    raw_bytes = wire.raw_nbytes(routed)

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_shuffle_")
    pool = ThreadPoolExecutor(SH_THREADS)
    try:
        def wire_write(i):
            buf = wire.encode_batches([wire.trim_host(routed[i])])
            path = os.path.join(d, f"w{i:03d}.blk")
            with open(path, "wb") as f:
                f.write(buf)
            return path, len(buf)

        def wire_read(path):
            with open(path, "rb") as f:
                data = f.read()
            return wire.decode_batches(data)

        def wire_pass():
            written = list(pool.map(wire_write, range(len(routed))))
            for out in pool.map(wire_read, (p for p, _ in written)):
                assert out[0].capacity >= 0
            return sum(n for _, n in written)

        def pickle_pass():
            for i, b in enumerate(padded):
                with open(os.path.join(d, f"p{i:03d}.blk"), "wb") as f:
                    pickle.dump([b], f, protocol=pickle.HIGHEST_PROTOCOL)
            for i in range(len(padded)):
                with open(os.path.join(d, f"p{i:03d}.blk"), "rb") as f:
                    pickle.load(f)

        wire_bytes = wire_pass()            # also the warm-up
        pickle_pass()
        pickle_bytes = sum(
            os.path.getsize(os.path.join(d, f"p{i:03d}.blk"))
            for i in range(len(padded)))
        wire_rate = _median_rate(wire_pass, live)
        pickle_rate = _median_rate(pickle_pass, live)
    finally:
        pool.shutdown()
        shutil.rmtree(d, ignore_errors=True)
    return {
        "shuffle_rows_per_sec": round(wire_rate, 1),
        "shuffle_bytes_per_sec": round(wire_rate * wire_bytes / live, 1),
        "shuffle_vs_scan_baseline": round(
            wire_rate / BASELINE_SCAN_ROWS_PER_S, 3),
        "shuffle_pickle_rows_per_sec": round(pickle_rate, 1),
        "shuffle_vs_pickle": round(wire_rate / pickle_rate, 2),
        "shuffle_wire_bytes": wire_bytes,
        "shuffle_pickle_bytes": pickle_bytes,
        "shuffle_wire_vs_pickle_bytes": round(
            pickle_bytes / max(1, wire_bytes), 2),
        "shuffle_compression_ratio": round(raw_bytes / max(1, wire_bytes),
                                           3),
    }


def _bench_dist_join() -> dict:
    """Distributed-join lane: a 2-process equi-join + group-by through the
    host-shuffle data plane, shuffled hash join vs the forced gather path.

    Two REAL worker processes (``--distjoin-worker``) share one shuffle
    root; each holds a strided half of both fact tables and runs the same
    query twice — ``spark.tpu.crossproc.shuffledJoin`` on, then off on a
    fresh exchange root.  Each worker reports warm-run wall time and its
    service's DCN byte/row counters; this parent sums bytes across both
    workers and cross-checks that the two paths produced identical
    aggregates.  The byte reduction is structural: the shuffled path runs
    each side's subtree (pushed-down filters, pruned columns) BEFORE
    shipping and keeps its own key range in memory, while the gather path
    ships raw leaves."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_dj_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distjoin-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distjoin worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # both paths, both processes: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("shuffled",
                                                         "gather")}
        if len(sums) != 1:
            raise RuntimeError(f"shuffled/gather results diverge: {objs}")
        if not all(o["shuffled"]["shuffled_joins"] > 0 for o in objs):
            raise RuntimeError(f"shuffled path did not run: {objs}")
        if any(o["gather"]["shuffled_joins"] > 0 for o in objs):
            raise RuntimeError(f"gather run took the shuffled path: {objs}")
        rows = objs[0]["rows_total"]
        sh_s = max(o["shuffled"]["seconds"] for o in objs)
        ga_s = max(o["gather"]["seconds"] for o in objs)
        sh_b = sum(o["shuffled"]["bytes_written"] for o in objs)
        ga_b = sum(o["gather"]["bytes_written"] for o in objs)
        return {
            "distjoin_rows_per_sec": round(rows / sh_s, 1),
            "distjoin_gather_rows_per_sec": round(rows / ga_s, 1),
            "distjoin_speedup_vs_gather": round(ga_s / sh_s, 3),
            "distjoin_dcn_bytes": sh_b,
            "distjoin_gather_dcn_bytes": ga_b,
            "distjoin_dcn_byte_reduction": round(ga_b / max(1, sh_b), 2),
            "distjoin_rows_shipped": sum(
                o["shuffled"]["rows_shipped"] for o in objs),
            "distjoin_gather_rows_shipped": sum(
                o["gather"]["rows_shipped"] for o in objs),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distjoin_worker_main() -> None:
    """One process of the distributed-join lane (see ``_bench_dist_join``).

    argv: --distjoin-worker <pid> <root>.  Prints ONE JSON line with warm
    wall-clock and service counters for the shuffled and gather modes."""
    i = sys.argv.index("--distjoin-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    # both workers draw the SAME dataset, keep a strided half: every key
    # range lives on both processes (worst case for a local join)
    rng = np.random.default_rng(31)
    sk = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    price = rng.integers(1, 201, DJ_ROWS).astype(np.int64)
    k2 = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    bonus = rng.integers(1, 101, DJ_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
         "JOIN fact2 ON sk = k2 WHERE price < 100 AND bonus < 50 "
         "GROUP BY sk")

    session = SparkSession.builder.appName(f"bench-dj-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * DJ_ROWS)}
    for mode in ("shuffled", "gather"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key,
                    "true" if mode == "shuffled" else "false")
        # this lane measures hash-vs-gather; the range sort-merge and
        # broadcast planners must not preempt it (distsort lane covers
        # range-vs-hash)
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"sk": sk[mine], "price": price[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        base_bytes = int(svc.counters["bytes_written"])
        base_rows = int(svc.counters["rows_shipped"])
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        elapsed = time.perf_counter() - t0
        out[mode] = {
            "seconds": round(elapsed, 3),
            "bytes_written": int(svc.counters["bytes_written"]) - base_bytes,
            "rows_shipped": int(svc.counters["rows_shipped"]) - base_rows,
            "groups": len(rows),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) for r in rows)),
            "shuffled_joins": int(svc.counters["shuffled_joins"]),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_ici() -> dict:
    """Distici lane: the two-tier exchange (ICI device tier over the
    host/DCN wire tier).

    Phase one, 2 REAL worker processes (``--distici-worker``): the
    dict-free distjoin workload runs with the device tier armed (one
    ICI domain spanning both pids, zero byte floor) and then disarmed
    on a fresh root.  jax CPU backends cannot span two OS processes, so
    every armed attempt must fold back structured onto the host tier —
    the lane pins that ladder: fallbacks counted in tiered mode, zero
    in host mode, aggregates byte-identical, and the fallback overhead
    (pack + probe per exchange) measured as a wall-clock ratio.

    Phase two, one forced 4-device CPU mesh (``--distici-mesh``): the
    SAME pack/collective/unpack that ships HBM→HBM moves real bucketed
    spans device-to-device and is timed against the host wire plane
    (encode + decode of identical outboxes) — the structural number the
    tier exists for, portable to a TPU window unchanged."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_di_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distici-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distici worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        sums = {o[m]["checksum"] for o in objs for m in ("tiered",
                                                         "host")}
        if len(sums) != 1:
            raise RuntimeError(f"tiered/host results diverge: {objs}")
        if not all(o["tiered"]["dcn_fallbacks"] > 0 for o in objs):
            raise RuntimeError(f"armed tier never attempted: {objs}")
        if any(o["host"]["dcn_fallbacks"] > 0 for o in objs):
            raise RuntimeError(f"disarmed tier attempted: {objs}")
        ti_s = max(o["tiered"]["seconds"] for o in objs)
        ho_s = max(o["host"]["seconds"] for o in objs)
        res = {
            "distici_fallback_rows_per_sec": round(
                objs[0]["rows_total"] / ti_s, 1),
            "distici_host_rows_per_sec": round(
                objs[0]["rows_total"] / ho_s, 1),
            # armed-but-degraded vs never-armed: the price of probing
            # the device tier when it cannot serve (should stay ~1.0)
            "distici_fallback_overhead": round(ti_s / ho_s, 3),
            "distici_dcn_fallbacks": sum(
                o["tiered"]["dcn_fallbacks"] for o in objs),
        }
        mesh_env = dict(env,
                        XLA_FLAGS="--xla_force_host_platform_device_count=4")
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--distici-mesh"],
            capture_output=True, text=True, env=mesh_env,
            timeout=CHILD_TIMEOUT_S)
        if p.returncode != 0:
            raise RuntimeError(
                f"distici mesh rc={p.returncode}: "
                f"{(p.stderr or p.stdout).strip().splitlines()[-3:]}")
        line = [ln for ln in p.stdout.splitlines()
                if ln.strip().startswith("{")][-1]
        res.update(json.loads(line))
        return res
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distici_worker_main() -> None:
    """One process of the distici lane's 2-process phase (see
    ``_bench_dist_ici``).

    argv: --distici-worker <pid> <root>.  Prints ONE JSON line with
    warm wall-clock and tier counters for the armed (tiered) and
    disarmed (host) modes."""
    i = sys.argv.index("--distici-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    rng = np.random.default_rng(47)
    sk = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    k2 = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    bonus = rng.integers(1, 101, DJ_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    # projected int-only sides: the shape the device tier accepts (a
    # dictionary column would pin the exchange to the host tier)
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb "
         "FROM (SELECT sk FROM fact) f "
         "JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
         "GROUP BY sk")

    session = SparkSession.builder.appName(f"bench-di-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * DJ_ROWS)}
    for mode in ("tiered", "host"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        if mode == "tiered":
            xs.conf.set(C.SHUFFLE_ICI_ENABLED.key, "true")
            xs.conf.set(C.SHUFFLE_ICI_MIN_BYTES.key, "0")
            xs.conf.set(C.SHUFFLE_ICI_TIER_OVERRIDE.key, "0,1")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"sk": sk[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        base_fb = int(svc.counters["dcn_fallback_exchanges"])
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        elapsed = time.perf_counter() - t0
        out[mode] = {
            "seconds": round(elapsed, 3),
            "dcn_fallbacks": int(svc.counters["dcn_fallback_exchanges"])
            - base_fb,
            "ici_exchanges": int(svc.counters["ici_exchanges"]),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) for r in rows)),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def distici_mesh_main() -> None:
    """The distici lane's forced-mesh phase: device all-to-all vs the
    host wire plane over identical bucketed spans.

    argv: --distici-mesh (XLA_FLAGS forces a 4-device CPU world).
    Prints ONE JSON line: MB/s through ``local_device_exchange`` (pack
    + collective + unpack, warm stage cache) and through wire encode +
    decode of the same outboxes, plus the ratio."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import types as T
    from spark_tpu import wire
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.parallel import ici

    n = 4
    per = 1 << 13                        # rows per sender→receiver span
    rng = np.random.default_rng(53)

    def batch(m):
        vals = rng.integers(-(1 << 40), 1 << 40, m)
        return ColumnBatch(
            ["k"], [ColumnVector(vals, T.LongType(), None, None)],
            None, m)

    outboxes = [{r: [batch(per)] for r in range(n)} for _s in range(n)]
    tpl = batch(1)
    total = sum(wire.raw_nbytes(bs) for ob in outboxes
                for bs in ob.values())

    ici.local_device_exchange(outboxes, tpl)       # warm: trace+compile
    t0 = time.perf_counter()
    for _ in range(BENCH_RUNS):
        ici.local_device_exchange(outboxes, tpl)
    dev_s = (time.perf_counter() - t0) / BENCH_RUNS

    def wire_pass():
        for ob in outboxes:
            for bs in ob.values():
                wire.decode_batches(wire.encode_batches(bs))

    wire_pass()                                    # warm codec paths
    t0 = time.perf_counter()
    for _ in range(BENCH_RUNS):
        wire_pass()
    host_s = (time.perf_counter() - t0) / BENCH_RUNS

    print(json.dumps({
        "distici_mesh_device_mb_per_s": round(total / dev_s / 1e6, 1),
        "distici_mesh_wire_mb_per_s": round(total / host_s / 1e6, 1),
        "distici_mesh_device_vs_wire": round(host_s / dev_s, 3),
        "distici_mesh_bytes": int(total),
    }))
    sys.stdout.flush()


def _bench_stagecache() -> dict:
    """Stagecache lane: whole-stage compilation vs per-operator dispatch,
    and cold vs warm stage-executable cache, on a 2-process join + agg.

    Two REAL worker processes (``--stagecache-worker``) share a shuffle
    root and run the same fact⋈dim + group-by statement cold (first
    execution: every stage traces and compiles through the process
    StageCache) and then warm three times (median; executables must come
    back as cache hits with ZERO new builds).  Worker 0 additionally
    replays the same planned shape single-process both ways: fused (one
    jitted program per stage, dispatch count from StageCache counters)
    and per-operator (``stagecompile.run_per_op``, one device dispatch
    per physical operator — the pre-fusion baseline).  The parent pins
    checksum parity across processes and across dispatch modes, requires
    the >=3x dispatch reduction, and reports compile-ms / hit-count /
    wall-clock figures."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_sc_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--stagecache-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"stagecache worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # distributed statement: byte-identical aggregates on both
        # processes, cold and warm
        sums = {o["dist"]["checksum"] for o in objs}
        if len(sums) != 1:
            raise RuntimeError(f"worker results diverge: {objs}")
        if not all(o["dist"]["warm_hits"] > 0 for o in objs):
            raise RuntimeError(f"warm runs never hit the stage cache: "
                               f"{objs}")
        if any(o["dist"]["warm_builds"] > 0 for o in objs):
            raise RuntimeError(
                f"warm runs recompiled stages (stale cache key?): {objs}")
        cold_s = max(o["dist"]["cold_s"] for o in objs)
        warm_s = max(o["dist"]["warm_s"] for o in objs)
        if warm_s >= cold_s:
            raise RuntimeError(
                f"warm stage cache not faster than cold: {cold_s=} "
                f"{warm_s=}")
        # dispatch-mode comparison (worker 0's local replay)
        lo = objs[0]["local"]
        if lo["fused_checksum"] != lo["per_op_checksum"]:
            raise RuntimeError(f"fused/per-op results diverge: {lo}")
        if lo["per_op_overflow"]:
            raise RuntimeError(f"per-op baseline overflowed: {lo}")
        reduction = lo["per_op_dispatches"] / max(1,
                                                  lo["fused_dispatches"])
        if reduction < 3.0:
            raise RuntimeError(
                f"dispatch reduction {reduction:.2f}x < 3x: {lo}")
        return {
            "stagecache_cold_s": cold_s,
            "stagecache_warm_s": warm_s,
            "stagecache_warm_vs_cold_speedup": round(cold_s / warm_s, 3),
            "stagecache_compile_ms": round(
                sum(o["dist"]["compile_ms"] for o in objs), 1),
            "stagecache_stage_builds": sum(
                o["dist"]["builds"] for o in objs),
            "stagecache_warm_hits": sum(
                o["dist"]["warm_hits"] for o in objs),
            "stagecache_fused_dispatches": lo["fused_dispatches"],
            "stagecache_per_op_dispatches": lo["per_op_dispatches"],
            "stagecache_dispatch_reduction": round(reduction, 2),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def stagecache_worker_main() -> None:
    """One process of the stagecache lane (see ``_bench_stagecache``).

    argv: --stagecache-worker <pid> <root>.  Prints ONE JSON line with
    cold/warm wall clocks + StageCache counter deltas for the 2-process
    statement, and (worker 0) fused-vs-per-op dispatch counts with
    checksums on a single-process replay of the same shape."""
    i = sys.argv.index("--stagecache-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.sql import stagecompile as SC
    from spark_tpu.sql.session import SparkSession

    # both workers draw the SAME dataset, keep a strided half; the dim
    # side is UNIQUE-keyed so join fanout is exactly 1 and the per-op
    # replay cannot overflow the planned capacities
    rng = np.random.default_rng(47)
    sk = rng.integers(0, SC_KEYS, SC_ROWS).astype(np.int64)
    price = rng.integers(1, 201, SC_ROWS).astype(np.int64)
    k2 = np.arange(SC_KEYS, dtype=np.int64)
    bonus = rng.integers(1, 101, SC_KEYS).astype(np.int64)
    mine = slice(pid, None, 2)
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
         "JOIN dim ON sk = k2 WHERE price < 100 GROUP BY sk")

    def _ck(rows):
        return int(sum(int(r[1]) * 7 + int(r[2]) for r in rows))

    session = SparkSession.builder.appName(f"bench-sc-{pid}").getOrCreate()
    cache = SC.stage_cache()
    out = {"pid": pid, "rows_total": int(SC_ROWS)}

    xs = session.newSession()
    xs.conf.set(C.MESH_SHARDS.key, "1")
    xs.enableHostShuffle(os.path.join(root, "x"), process_id=pid,
                         n_processes=2, timeout_s=300.0)
    xs.createDataFrame({"sk": sk[mine], "price": price[mine]}) \
        .createOrReplaceTempView("fact")
    xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
        .createOrReplaceTempView("dim")

    s0 = cache.stats()
    t0 = time.perf_counter()
    rows = xs.sql(Q).collect()
    cold_s = time.perf_counter() - t0
    s1 = cache.stats()
    checksum = _ck(rows)
    warm = []
    for _ in range(3):
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        warm.append(time.perf_counter() - t0)
        if _ck(rows) != checksum:
            raise RuntimeError("warm run diverged from cold result")
    s2 = cache.stats()
    warm.sort()
    out["dist"] = {
        "cold_s": round(cold_s, 3),
        "warm_s": round(warm[len(warm) // 2], 3),
        "checksum": checksum,
        "builds": s1["builds"] - s0["builds"],
        "compile_ms": round(s1["compile_ms"] - s0["compile_ms"], 1),
        "warm_hits": s2["hits"] - s1["hits"],
        "warm_builds": s2["builds"] - s1["builds"],
    }

    if pid == 0:
        # single-process replay of the same shape: fused dispatch count
        # (StageCache counters) vs the per-operator baseline
        from spark_tpu.sql.planner import (Planner, QueryExecution,
                                           _slice_to_host)
        ls = session.newSession()
        ls.conf.set(C.MESH_SHARDS.key, "1")
        ls.createDataFrame({"sk": sk, "price": price}) \
            .createOrReplaceTempView("fact")
        ls.createDataFrame({"k2": k2, "bonus": bonus}) \
            .createOrReplaceTempView("dim")
        b0 = cache.stats()
        fused_ck = _ck(ls.sql(Q).collect())
        b1 = cache.stats()
        pq = Planner(ls).plan(QueryExecution(ls, ls.sql(Q)._plan)
                              .optimized)
        dev, n_rows, n_disp, flags, _caps, _kinds = SC.run_per_op(
            pq.physical, pq.leaves)
        host = _slice_to_host(dev, n_rows)
        cols = [np.asarray(v.data)[:n_rows] for v in host.vectors]
        out["local"] = {
            "fused_dispatches": b1["dispatches"] - b0["dispatches"],
            "fused_checksum": fused_ck,
            "per_op_dispatches": n_disp,
            "per_op_checksum": _ck(list(zip(*cols))),
            "per_op_overflow": bool(any(f > 0 for f in flags)),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_adapt() -> dict:
    """Distadapt lane: adaptive re-planning from observed exchange stats.

    A 2-process join whose RIGHT side the plan-time probe misestimates
    by ~20x: the leaf is ~5 MB raw, but a selective pushed-down filter
    keeps ~5% of its rows, far under the broadcast threshold.  Each
    worker runs the same query with ``adaptiveReplan`` off (frozen: the
    full hash shuffle ships the fat left side) and on (the stats
    barrier demotes to a broadcast before any data block ships).  The
    parent cross-checks byte-identical aggregates, that the adaptive
    run actually demoted (and the frozen run actually shuffled), and
    reports wall-clock speedup + DCN byte reduction."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_da_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distadapt-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distadapt worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        sums = {o[m]["checksum"] for o in objs for m in ("adaptive",
                                                         "frozen")}
        if len(sums) != 1:
            raise RuntimeError(f"adaptive/frozen results diverge: {objs}")
        if not all(o["adaptive"]["strategy_demotions"] > 0 for o in objs):
            raise RuntimeError(f"adaptive run did not demote: {objs}")
        if not all(o["frozen"]["shuffled_joins"] > 0
                   and o["frozen"]["strategy_demotions"] == 0
                   for o in objs):
            raise RuntimeError(f"frozen run did not hash-shuffle: {objs}")
        rows = objs[0]["rows_total"]
        ad_s = max(o["adaptive"]["seconds"] for o in objs)
        fz_s = max(o["frozen"]["seconds"] for o in objs)
        ad_b = sum(o["adaptive"]["bytes_written"] for o in objs)
        fz_b = sum(o["frozen"]["bytes_written"] for o in objs)
        return {
            "distadapt_rows_per_sec": round(rows / ad_s, 1),
            "distadapt_frozen_rows_per_sec": round(rows / fz_s, 1),
            "distadapt_speedup_vs_frozen": round(fz_s / ad_s, 3),
            "distadapt_dcn_bytes": ad_b,
            "distadapt_frozen_dcn_bytes": fz_b,
            "distadapt_dcn_byte_reduction": round(fz_b / max(1, ad_b), 2),
            "distadapt_demotions": sum(
                o["adaptive"]["strategy_demotions"] for o in objs),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distadapt_worker_main() -> None:
    """One process of the distadapt lane (see ``_bench_dist_adapt``).

    argv: --distadapt-worker <pid> <root>.  Prints ONE JSON line with
    warm wall-clock and service counters for the adaptive and frozen
    modes.  The measured adaptive run must exercise the DEMOTION (the
    stats barrier), not the feedback shortcut, so the warm run's
    recorded cardinalities are cleared before timing."""
    i = sys.argv.index("--distadapt-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    # both workers draw the SAME dataset, keep a strided half.  The left
    # side is WIDE (five payload columns, all live in the output) — the
    # mass the frozen hash shuffle ships and the demoted broadcast keeps
    # local.  The right side's filter keeps ~5% of its rows.
    rng = np.random.default_rng(47)
    sk = rng.integers(0, DA_KEYS, DA_ROWS).astype(np.int64)
    pay = [rng.integers(1, 201, DA_ROWS).astype(np.int64)
           for _ in range(DA_PAY)]
    k2 = rng.integers(0, DA_KEYS, DA_ROWS).astype(np.int64)
    bonus = rng.integers(1, 101, DA_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    spay = " + ".join(f"p{j}" for j in range(DA_PAY))
    Q = ("SELECT sk, count(*) AS c, "
         f"sum({spay}) AS sp, sum(bonus) AS sb "
         f"FROM fact JOIN fact2 ON sk = k2 WHERE bonus < {DA_CUT} "
         "GROUP BY sk")

    session = SparkSession.builder.appName(f"bench-da-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * DA_ROWS)}
    for mode in ("adaptive", "frozen"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
        # between the observed right side (~5% of the leaf) and the
        # plan-time probe (the raw leaf): freeze hash, observe broadcast
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, str(1 << 20))
        xs.conf.set(C.CROSSPROC_ADAPTIVE_REPLAN.key,
                    "true" if mode == "adaptive" else "false")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame(dict(
            {"sk": sk[mine]},
            **{f"p{j}": p[mine] for j, p in enumerate(pay)})) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        xs.statsFeedback.clear()             # measure the demotion path
        base_bytes = int(svc.counters["bytes_written"])
        base_rows = int(svc.counters["rows_shipped"])
        base_dem = int(svc.counters["strategy_demotions"])
        base_shj = int(svc.counters["shuffled_joins"])
        # median-of-3: filesystem-barrier jitter dominates run-to-run
        # variance, and both processes must repeat in lockstep anyway
        # (every iteration is a fresh exchange round)
        iters = []
        for _ in range(3):
            xs.statsFeedback.clear()         # re-demote, don't shortcut
            it_bytes = int(svc.counters["bytes_written"])
            it_rows = int(svc.counters["rows_shipped"])
            t0 = time.perf_counter()
            rows = xs.sql(Q).collect()
            iters.append((time.perf_counter() - t0,
                          int(svc.counters["bytes_written"]) - it_bytes,
                          int(svc.counters["rows_shipped"]) - it_rows))
        elapsed, it_bytes, it_rows = sorted(iters)[1]
        out[mode] = {
            "seconds": round(elapsed, 3),
            "bytes_written": it_bytes,
            "rows_shipped": it_rows,
            "groups": len(rows),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) * 3 + int(r[3])
                                for r in rows)),
            "strategy_demotions":
                int(svc.counters["strategy_demotions"]) - base_dem,
            "shuffled_joins": int(svc.counters["shuffled_joins"]) - base_shj,
            "adaptive_replans": int(svc.counters["adaptive_replans"]),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_dict() -> dict:
    """Distdict lane: encoded execution over the DCN exchange.  A
    2-process low-cardinality string-key join + group-by runs twice with
    only ``spark.tpu.shuffle.wire.dictCodes`` toggled: "codes" ships each
    fat dictionary ONCE per (exchange, sender) in the framed sidecar and
    the blocks carry int32 codes + an 8-byte fingerprint, "words" inlines
    the full dictionary into EVERY block frame (the legacy wire).  Same
    shuffled-hash path, identical results cross-checked; the byte
    reduction is the dictionary dedup, measured end to end."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_dd_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distdict-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distdict worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # both wire formats, both processes: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("codes", "words")}
        if len(sums) != 1:
            raise RuntimeError(f"codes/words results diverge: {objs}")
        if not all(o["codes"]["dict_columns_encoded"] > 0 for o in objs):
            raise RuntimeError(f"codes run never framed a dictionary: {objs}")
        rows = objs[0]["rows_total"]
        co_s = max(o["codes"]["seconds"] for o in objs)
        wo_s = max(o["words"]["seconds"] for o in objs)
        co_b = sum(o["codes"]["bytes_written"] for o in objs)
        wo_b = sum(o["words"]["bytes_written"] for o in objs)
        return {
            "distdict_rows_per_sec": round(rows / co_s, 1),
            "distdict_words_rows_per_sec": round(rows / wo_s, 1),
            "distdict_speedup_vs_words": round(wo_s / co_s, 3),
            "distdict_dcn_bytes": co_b,
            "distdict_words_dcn_bytes": wo_b,
            "distdict_dcn_byte_reduction": round(wo_b / max(1, co_b), 2),
            "distdict_dict_bytes_saved": sum(
                o["codes"]["dict_bytes_saved"] for o in objs),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distdict_worker_main() -> None:
    """One process of the distdict lane (see ``_bench_dist_dict``).

    argv: --distdict-worker <pid> <root>.  Prints ONE JSON line with warm
    wall-clock and service counters for the codes and words wire modes."""
    i = sys.argv.index("--distdict-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import zlib

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    # fat words, low cardinality: the per-column dictionary (~75 KiB)
    # dwarfs a fine partition's code payload, so inlining it per block
    # frame vs once per sender is the measured difference
    words = np.array([f"sku-{j:06d}-lot-{j % 97:02d}-aisle-{j % 13:02d}"
                      for j in range(DD_KEYS)])
    rng = np.random.default_rng(53)
    g = words[rng.integers(0, DD_KEYS, DD_ROWS)]
    v = rng.integers(1, 100, DD_ROWS).astype(np.int64)
    g2 = words[rng.integers(0, DD_KEYS, DD_ROWS)]
    w = rng.integers(1, 100, DD_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    Q = ("SELECT g, count(*) AS c, sum(w) AS sw FROM fact "
         "JOIN fact2 ON g = g2 GROUP BY g ORDER BY g")

    session = SparkSession.builder.appName(f"bench-dd-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * DD_ROWS)}
    for mode in ("codes", "words"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.SHUFFLE_WIRE_DICT_CODES.key,
                    "true" if mode == "codes" else "false")
        # pin the range sort-merge path both runs (string keys are
        # range-eligible now): this lane measures the WIRE format, not a
        # join-strategy difference.  Range routing ships one batch frame
        # PER SPAN per receiver — the words wire pays the dictionary in
        # each frame, the codes wire once per sender in the sidecar.
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "false")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        xs.conf.set(C.SHUFFLE_FINE_PARTITIONS.key, "32")
        xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "4096")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"g": g[mine], "v": v[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"g2": g2[mine], "w": w[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        base_bytes = int(svc.counters["bytes_written"])
        base_rows = int(svc.counters["rows_shipped"])
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        elapsed = time.perf_counter() - t0
        chk = 0
        for r in rows:                       # order pinned by ORDER BY g
            chk = (chk * 1000003 + zlib.crc32(str(r[0]).encode())
                   + 7 * int(r[1]) + int(r[2])) & 0xFFFFFFFF
        out[mode] = {
            "seconds": round(elapsed, 3),
            "bytes_written": int(svc.counters["bytes_written"]) - base_bytes,
            "rows_shipped": int(svc.counters["rows_shipped"]) - base_rows,
            "groups": len(rows),
            "checksum": chk,
            "dict_columns_encoded": int(
                svc.counters["dict_columns_encoded"]),
            "dict_bytes_saved": int(svc.counters["dict_bytes_saved"]),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_rle() -> dict:
    """Distrle lane: run-length/delta encoded execution over the DCN
    exchange.  A 2-process time-series join + group-by runs twice with
    only ``spark.tpu.shuffle.wire.runCodes`` toggled: "runs" lets the
    sampled-benefit probe RLE/delta-encode each block column (and the
    range sort-merge path emit its presorted span slices as free runs),
    "raw" ships every column dense (the legacy wire).  Same range
    sort-merge path, identical results cross-checked; the byte
    reduction is the run compression of the sorted ts/sensor/status
    planes, measured end to end against an incompressible payload
    column that ships dense in both modes.

    Acceptance (raises into ``distrle_error`` when missed): >=2x DCN
    byte reduction, runs wall clock <= 1.1x the raw wall, checksums
    byte-identical across modes and processes."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_dr_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distrle-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distrle worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # both wire formats, both processes: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("runs", "raw")}
        if len(sums) != 1:
            raise RuntimeError(f"runs/raw results diverge: {objs}")
        # the plane pair runs its own (filter+agg) query: planes on vs
        # off must be byte-identical across modes AND processes
        psums = {o[m]["checksum"] for o in objs
                 for m in ("plane", "noplane")}
        if len(psums) != 1:
            raise RuntimeError(f"plane/noplane results diverge: {objs}")
        # the r20 contract: with runPlanes on the jitted stage lane ran
        # the eligible query compressed — stages entered as planes, and
        # not one run expanded on the host during the timed iterations
        if sum(o["plane"]["run_plane_stages"] for o in objs) == 0:
            raise RuntimeError(
                f"plane run never entered a stage compressed: {objs}")
        mat = sum(o["plane"]["runs_materialized_delta"] for o in objs)
        if mat != 0:
            raise RuntimeError(
                f"plane run materialized {mat} run rows on the host "
                f"(want 0): {objs}")
        pl_s = max(o["plane"]["seconds"] for o in objs)
        npl_s = max(o["noplane"]["seconds"] for o in objs)
        plane_ratio = pl_s / max(1e-9, npl_s)
        if plane_ratio > 1.1:
            raise RuntimeError(
                f"plane wall {pl_s:.3f}s is {plane_ratio:.2f}x the "
                f"materializing path {npl_s:.3f}s (> 1.1x budget)")
        # span ownership need not balance, so a process that keeps its
        # shard local frames nothing — the EXCHANGE must run-encode
        if sum(o["runs"]["rle_columns_encoded"] for o in objs) == 0:
            raise RuntimeError(f"runs run never run-encoded a column: {objs}")
        if not all(o["raw"]["rle_columns_encoded"] == 0 for o in objs):
            raise RuntimeError(f"raw run framed run codes: {objs}")
        rows = objs[0]["rows_total"]
        ru_s = max(o["runs"]["seconds"] for o in objs)
        ra_s = max(o["raw"]["seconds"] for o in objs)
        ru_b = sum(o["runs"]["bytes_written"] for o in objs)
        ra_b = sum(o["raw"]["bytes_written"] for o in objs)
        reduction = ra_b / max(1, ru_b)
        wall_ratio = ru_s / max(1e-9, ra_s)
        if reduction < 2.0:
            raise RuntimeError(
                f"DCN byte reduction {reduction:.2f}x < 2x "
                f"(runs {ru_b} B vs raw {ra_b} B)")
        if wall_ratio > 1.1:
            raise RuntimeError(
                f"runs wall {ru_s:.3f}s is {wall_ratio:.2f}x raw "
                f"{ra_s:.3f}s (> 1.1x budget)")
        return {
            "distrle_rows_per_sec": round(rows / ru_s, 1),
            "distrle_raw_rows_per_sec": round(rows / ra_s, 1),
            "distrle_wall_vs_raw": round(wall_ratio, 3),
            "distrle_dcn_bytes": ru_b,
            "distrle_raw_dcn_bytes": ra_b,
            "distrle_dcn_byte_reduction": round(reduction, 2),
            "distrle_run_bytes_saved": sum(
                o["runs"]["run_bytes_saved"] for o in objs),
            "distrleplane_wall_vs_dense": round(plane_ratio, 3),
            "distrleplane_rows_per_sec": round(rows / pl_s, 1),
            "distrleplane_stages": sum(
                o["plane"]["run_plane_stages"] for o in objs),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distrle_worker_main() -> None:
    """One process of the distrle lane (see ``_bench_dist_rle``).

    argv: --distrle-worker <pid> <root>.  Prints ONE JSON line with warm
    wall-clock and service counters for the runs and raw wire modes."""
    i = sys.argv.index("--distrle-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import zlib

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    # time-series shape: ts repeats in long blocks, sensor and status
    # follow ts (long runs after the range sort), v is an incompressible
    # random payload that ships dense in both modes — the honest floor
    rep = DR_ROWS // DR_KEYS
    ts = np.repeat(np.arange(DR_KEYS, dtype=np.int64), rep)
    sensor = (ts // 4).astype(np.int64)
    status = np.array(["ok", "warn", "err"])[
        (np.arange(DR_ROWS) // 512) % 3]
    rng = np.random.default_rng(59)
    v = rng.integers(1, 1 << 30, DR_ROWS).astype(np.int64)
    dk = np.arange(DR_KEYS, dtype=np.int64)
    bonus = (dk * 3 + 7).astype(np.int64)
    mine = slice(pid, None, 2)
    Q = ("SELECT status, count(*) AS c, sum(v) AS sv, "
         "sum(sensor) AS ss, sum(bonus) AS sb FROM ev "
         "JOIN dm ON ts = dk GROUP BY status ORDER BY status")
    # the plane modes run the eligible filter+agg shape over the sorted
    # key: on the encoded wire the reduce-side shards arrive run-encoded,
    # and with runPlanes on the jitted stage lane must execute this query
    # without materializing a single run on the host
    QP = (f"SELECT ts, count(*) AS c, sum(v) AS sv FROM ev "
          f"JOIN dm ON ts = dk WHERE ts < {DR_KEYS // 2} "
          f"GROUP BY ts ORDER BY ts")

    from spark_tpu import columnar as _col
    session = SparkSession.builder.appName(f"bench-dr-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(DR_ROWS)}
    for mode in ("runs", "raw", "plane", "noplane"):
        q = QP if mode in ("plane", "noplane") else Q
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.SHUFFLE_WIRE_RUN_CODES.key,
                    "false" if mode == "raw" else "true")
        xs.conf.set(C.STAGE_RUN_PLANES.key,
                    "false" if mode == "noplane" else "true")
        # runs/raw pin the range sort-merge path: the sorted spans are
        # where presorted-slice RLE is free, and that pair measures the
        # WIRE format, not a join-strategy difference.  The plane pair
        # pins the shuffled hash path instead — under the presorted
        # merge ev never leaves the process (only dm is gathered), so
        # only a real shuffle makes the run-shaped probe side cross the
        # encoded wire and arrive at the reduce-side stage as run
        # vectors, the boundary the planes compress
        smj = mode in ("runs", "raw")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key,
                    "true" if smj else "false")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key,
                    "false" if smj else "true")
        if not smj:
            # the reducer's own map output normally short-circuits the
            # wire as a dense slice, and one dense piece in the drain
            # union forces the whole column dense — the forced-spill
            # threshold stages EVERY piece through the encoded frames
            # (the parity battery's configuration), so the reduce-side
            # union stays run-encoded and the stage boundary sees run
            # vectors.  The small advisory target keeps both processes
            # reducing instead of coalescing every fine partition onto
            # process 0 (the filtered side is ~2 MiB, under the 4 MiB
            # default)
            xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, "1024")
            xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "65536")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        xs.conf.set(C.SHUFFLE_FINE_PARTITIONS.key, "16")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"ts": ts[mine], "sensor": sensor[mine],
                            "status": status[mine], "v": v[mine]}) \
            .createOrReplaceTempView("ev")
        xs.createDataFrame({"dk": dk[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("dm")
        xs.sql(q).collect()                  # warm: compile + caches
        # median-of-3: filesystem-barrier jitter dominates run-to-run
        # variance, and both processes must repeat in lockstep anyway
        iters = []
        mat0 = _col.runs_materialized()
        stages0 = _col.run_plane_stages()
        for _ in range(3):
            it_bytes = int(svc.counters["bytes_written"])
            it_rows = int(svc.counters["rows_shipped"])
            t0 = time.perf_counter()
            rows = xs.sql(q).collect()
            iters.append((time.perf_counter() - t0,
                          int(svc.counters["bytes_written"]) - it_bytes,
                          int(svc.counters["rows_shipped"]) - it_rows))
        elapsed, it_bytes, it_rows = sorted(iters)[1]
        chk = 0
        for r in rows:                 # order pinned by the ORDER BY
            chk = (chk * 1000003 + zlib.crc32(str(r[0]).encode())
                   + sum((3 + 2 * i) * int(r[i])
                         for i in range(1, len(r)))) & 0xFFFFFFFF
        out[mode] = {
            "seconds": round(elapsed, 3),
            "bytes_written": it_bytes,
            "rows_shipped": it_rows,
            "groups": len(rows),
            "checksum": chk,
            "rle_columns_encoded": int(
                svc.counters["rle_columns_encoded"]),
            "run_bytes_saved": int(svc.counters["run_bytes_saved"]),
            "runs_materialized_delta": int(
                _col.runs_materialized() - mat0),
            "run_plane_stages": int(_col.run_plane_stages() - stages0),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_sort() -> dict:
    """Distsort lane: the SKEWED 2-process equi-join, range-partitioned
    sort-merge (with skew-span splitting) vs the shuffled hash path.

    Half the probe mass sits on one hot key.  Under hash partitioning
    that key's fine partition is indivisible — one reducer does all the
    hot join work while its peer idles.  The range planner detects the
    hot span from the sample round and SPLITS its probe rows across both
    reducers (build replicated for that span), so the work balances.

    The headline figure is the CRITICAL PATH: max over the two workers
    of per-process CPU seconds in the timed run.  On a real multi-host
    pod that IS the exchange's wall clock; on this single-host CI
    simulator the two workers timeshare the same cores, so raw
    end-to-end wall clock only measures TOTAL work (the idle hash peer
    donates its core to the hot one) and is reported separately.  The
    lane also reports the reducer-balance evidence (max/median partition
    bytes of the range data plan, captured at plan time)."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_ds_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distsort-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distsort worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # both paths, both processes: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("range", "hash")}
        if len(sums) != 1:
            raise RuntimeError(f"range/hash results diverge: {objs}")
        if not all(o["range"]["range_merge_joins"] > 0 for o in objs):
            raise RuntimeError(f"range path did not run: {objs}")
        if not all(o["range"]["spans_split"] > 0 for o in objs):
            raise RuntimeError(f"hot span was not split: {objs}")
        if not all(o["hash"]["shuffled_joins"] > 0 for o in objs):
            raise RuntimeError(f"hash path did not run: {objs}")
        # reducer balance: the range DATA plan (captured at plan time,
        # before the agg round overwrites the gauge) must not hand any
        # reducer more than 2x the median partition bytes
        loads = sorted(objs[0]["range"]["partition_bytes"])
        p_max = loads[-1]
        mid = len(loads) // 2
        p_med = float(loads[mid]) if len(loads) % 2 \
            else (loads[mid - 1] + loads[mid]) / 2.0
        if p_max > 2 * p_med:
            raise RuntimeError(f"skew survived the split: {loads}")
        rows = objs[0]["rows_total"]
        # critical path: the slowest reducer's CPU time = multi-host wall
        # clock; barrier sleeps (waiting for the peer) cost no CPU
        rg_s = max(o["range"]["cpu_seconds"] for o in objs)
        ha_s = max(o["hash"]["cpu_seconds"] for o in objs)
        return {
            "distsort_rows_per_sec": round(rows / rg_s, 1),
            "distsort_hash_rows_per_sec": round(rows / ha_s, 1),
            "distsort_speedup_vs_hash": round(ha_s / rg_s, 3),
            "distsort_wall_seconds": max(
                o["range"]["seconds"] for o in objs),
            "distsort_hash_wall_seconds": max(
                o["hash"]["seconds"] for o in objs),
            "distsort_dcn_bytes": sum(
                o["range"]["bytes_written"] for o in objs),
            "distsort_hash_dcn_bytes": sum(
                o["hash"]["bytes_written"] for o in objs),
            "distsort_spans_split": objs[0]["range"]["spans_split"],
            "distsort_partition_bytes_max": int(p_max),
            "distsort_partition_bytes_median": int(p_med),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distsort_worker_main() -> None:
    """One process of the distsort lane (see ``_bench_dist_sort``).

    argv: --distsort-worker <pid> <root>.  Prints ONE JSON line with warm
    wall-clock, service counters, and the range data plan's per-reducer
    byte loads for the range and hash modes."""
    i = sys.argv.index("--distsort-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.sql.session import SparkSession

    # same full dataset on both workers, strided halves; HALF the probe
    # mass on one hot key — the indivisible-under-hash partition
    rng = np.random.default_rng(47)
    sk = rng.integers(0, DS_KEYS, DS_ROWS).astype(np.int64)
    sk[rng.random(DS_ROWS) < 0.5] = DS_HOT
    price = rng.integers(1, 201, DS_ROWS).astype(np.int64)
    k2 = rng.integers(0, DS_KEYS, DS_BUILD).astype(np.int64)
    k2[:96] = DS_HOT        # hot key matches ~112 build rows: the join
    bonus = rng.integers(1, 101, DS_BUILD).astype(np.int64)  # OUTPUT skews
    mine = slice(pid, None, 2)
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
         "JOIN fact2 ON sk = k2 GROUP BY sk")

    session = SparkSession.builder.appName(f"bench-ds-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(DS_ROWS + DS_BUILD)}
    for mode in ("range", "hash"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key,
                    "true" if mode == "range" else "false")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        # small advisory target: non-hot spans spread over many runs
        # (balance) and the hot span's bytes far exceed it (split k=2)
        xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, str(1 << 16))
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        # tight barrier polling: this lane measures partitioning quality,
        # and the default 50ms poll quantum would swamp the compute delta
        svc.poll_s = 0.005
        # capture the DATA-plan reducer loads at plan time — the keyed
        # aggregate's later size round overwrites the shared gauge
        plan_loads: list = []

        def prr(probe, build, target, _svc=svc,
                _orig=svc.plan_range_reducers, _sink=plan_loads):
            owners = _orig(probe, build, target)
            _sink.append([int(b) for b in (_svc.last_partition_bytes or [])])
            return owners
        svc.plan_range_reducers = prr
        xs.createDataFrame({"sk": sk[mine], "price": price[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        base_bytes = int(svc.counters["bytes_written"])
        t0 = time.perf_counter()
        c0 = time.process_time()
        rows = xs.sql(Q).collect()
        cpu = time.process_time() - c0
        elapsed = time.perf_counter() - t0
        out[mode] = {
            "seconds": round(elapsed, 3),
            "cpu_seconds": round(cpu, 3),
            "bytes_written": int(svc.counters["bytes_written"]) - base_bytes,
            "groups": len(rows),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) for r in rows)),
            "range_merge_joins": int(svc.counters["range_merge_joins"]),
            "shuffled_joins": int(svc.counters["shuffled_joins"]),
            "spans_split": int(svc.counters["spans_split"]),
            "partition_bytes": plan_loads[-1] if plan_loads else [],
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_spill() -> dict:
    """Distspill lane: the memory-pressure path of the distributed join.

    The distjoin workload reruns with the host budget capped BELOW the
    input working set and a tiny spill threshold, so map output and
    fetched blocks take the wire-format spill files instead of RAM.  The
    lane pins the robustness contract as a number: the capped run must
    COMPLETE with the same aggregates as the uncapped run, report
    nonzero spill bytes, keep its ledger peak under the cap — and the
    wall-clock overhead of spilling is the tracked figure."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_dspill_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distspill-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distspill worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # under pressure or not: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("uncapped",
                                                         "capped")}
        if len(sums) != 1:
            raise RuntimeError(f"capped/uncapped results diverge: {objs}")
        if not all(o["capped"]["spill_bytes"] > 0 for o in objs):
            raise RuntimeError(f"capped run did not spill: {objs}")
        for o in objs:
            if o["capped"]["peak_host_bytes"] > o["capped"]["budget_bytes"]:
                raise RuntimeError(f"ledger peak blew the cap: {objs}")
        rows = objs[0]["rows_total"]
        cap_s = max(o["capped"]["seconds"] for o in objs)
        unc_s = max(o["uncapped"]["seconds"] for o in objs)
        return {
            "distspill_rows_per_sec": round(rows / cap_s, 1),
            "distspill_overhead_vs_uncapped": round(cap_s / unc_s, 3),
            "distspill_bytes": sum(
                o["capped"]["spill_bytes"] for o in objs),
            "distspill_events": sum(
                o["capped"]["spill_events"] for o in objs),
            "distspill_peak_host_bytes": max(
                o["capped"]["peak_host_bytes"] for o in objs),
            "distspill_budget_bytes": objs[0]["capped"]["budget_bytes"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distspill_worker_main() -> None:
    """One process of the distspill lane (see ``_bench_dist_spill``).

    argv: --distspill-worker <pid> <root>.  Runs the distjoin query
    uncapped, then with the host budget capped below the input working
    set and a tiny spill threshold; prints ONE JSON line with both warm
    wall-clocks and the capped run's spill/ledger figures."""
    i = sys.argv.index("--distspill-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.memory import HOST_BUDGET
    from spark_tpu.sql.session import SparkSession

    rng = np.random.default_rng(31)
    sk = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    price = rng.integers(1, 201, DJ_ROWS).astype(np.int64)
    k2 = rng.integers(0, DJ_KEYS, DJ_ROWS).astype(np.int64)
    bonus = rng.integers(1, 101, DJ_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
         "JOIN fact2 ON sk = k2 WHERE price < 100 AND bonus < 50 "
         "GROUP BY sk")
    # below the per-process input working set (2 tables x 2 int64 cols),
    # above the post-filter resident shards the join must hold to finish
    budget = DJ_ROWS * 20

    session = SparkSession.builder.appName(
        f"bench-dspill-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * DJ_ROWS)}
    for mode in ("uncapped", "capped"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        if mode == "capped":
            xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, str(64 << 10))
            xs.conf.set(HOST_BUDGET.key, str(budget))
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"sk": sk[mine], "price": price[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        xs.sql(Q).collect()                  # warm: compile + caches
        base_spill = int(svc.counters["spill_bytes"])
        base_events = int(svc.counters["spill_events"])
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        elapsed = time.perf_counter() - t0
        out[mode] = {
            "seconds": round(elapsed, 3),
            "groups": len(rows),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) for r in rows)),
            "spill_bytes": int(svc.counters["spill_bytes"]) - base_spill,
            "spill_events": int(svc.counters["spill_events"]) - base_events,
            "peak_host_bytes": int(svc.ledger.peak),
            "budget_bytes": int(svc.ledger.budget),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_grace() -> dict:
    """Distgrace lane: graceful degradation past the exchange.

    A 2-process join+group-by runs with the host budget capped below
    EVERY reducer's drained working set — a budget the plain spill path
    cannot absorb, because the fetched shard itself does not fit.  With
    grace buckets enabled the reducers re-bucket the drained runs into
    spill files and join bucket-by-bucket: the lane pins that the capped
    run COMPLETES byte-identical to the uncapped run, reports nonzero
    grace buckets/spill, keeps the ledger peak under the cap — and the
    wall-clock overhead of degrading is the tracked figure.  With
    ``graceBuckets=0`` the same query must abort with the structured
    ``HostMemoryError`` (the pre-grace contract), never a wrong
    answer."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_dgrace_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        procs = [subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distgrace-worker", str(pid), d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env) for pid in (0, 1)]
        outs = [p.communicate(timeout=CHILD_TIMEOUT_S) for p in procs]
        objs = []
        for p, (out, err) in zip(procs, outs):
            if p.returncode != 0:
                raise RuntimeError(
                    f"distgrace worker rc={p.returncode}: "
                    f"{(err or out).strip().splitlines()[-3:]}")
            line = [ln for ln in out.splitlines()
                    if ln.strip().startswith("{")][-1]
            objs.append(json.loads(line))
        # degraded or not: byte-identical aggregates
        sums = {o[m]["checksum"] for o in objs for m in ("uncapped",
                                                         "grace")}
        if len(sums) != 1:
            raise RuntimeError(f"grace/uncapped results diverge: {objs}")
        for o in objs:
            if o["grace"]["grace_buckets_used"] <= 0:
                raise RuntimeError(f"capped run never graced: {objs}")
            if o["grace"]["peak_host_bytes"] > o["grace"]["budget_bytes"]:
                raise RuntimeError(f"ledger peak blew the cap: {objs}")
            if not o["nograce"]["aborted"]:
                raise RuntimeError(
                    f"graceBuckets=0 run did not abort bounded: {objs}")
        rows = objs[0]["rows_total"]
        gra_s = max(o["grace"]["seconds"] for o in objs)
        unc_s = max(o["uncapped"]["seconds"] for o in objs)
        return {
            "distgrace_rows_per_sec": round(rows / gra_s, 1),
            "distgrace_overhead_vs_uncapped": round(gra_s / unc_s, 3),
            "distgrace_buckets": sum(
                o["grace"]["grace_buckets_used"] for o in objs),
            "distgrace_spill_bytes": sum(
                o["grace"]["grace_spill_bytes"] for o in objs),
            "distgrace_peak_host_bytes": max(
                o["grace"]["peak_host_bytes"] for o in objs),
            "distgrace_budget_bytes": objs[0]["grace"]["budget_bytes"],
            "distgrace_nograce_aborts": sum(
                1 for o in objs if o["nograce"]["aborted"]),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distgrace_worker_main() -> None:
    """One process of the distgrace lane (see ``_bench_dist_grace``).

    argv: --distgrace-worker <pid> <root>.  Runs the join uncapped,
    then capped below the reducers' drained working set with grace
    buckets on (must complete via grace), then the same cap with
    ``graceBuckets=0`` (must abort with the structured HostMemoryError);
    prints ONE JSON line."""
    i = sys.argv.index("--distgrace-worker")
    pid, root = int(sys.argv[i + 1]), sys.argv[i + 2]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_tpu import config as C
    from spark_tpu.memory import HOST_BUDGET, HostMemoryError
    from spark_tpu.sql.session import SparkSession

    rng = np.random.default_rng(47)
    sk = rng.integers(0, GG_KEYS, GG_ROWS).astype(np.int64)
    price = rng.integers(1, 201, GG_ROWS).astype(np.int64)
    k2 = rng.integers(0, GG_KEYS, GG_ROWS).astype(np.int64)
    bonus = rng.integers(1, 101, GG_ROWS).astype(np.int64)
    mine = slice(pid, None, 2)
    # projection subqueries: sides ship ONLY the joined/aggregated
    # columns, so the shipped working set (and the grace buckets) stay
    # deliberately sized against GG_BUDGET
    Q = ("SELECT sk, count(*) AS c, sum(bonus) AS sb "
         "FROM (SELECT sk FROM fact) f "
         "JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
         "GROUP BY sk")

    session = SparkSession.builder.appName(
        f"bench-dgrace-{pid}").getOrCreate()
    out = {"pid": pid, "rows_total": int(2 * GG_ROWS)}
    for mode in ("uncapped", "grace", "nograce"):
        xs = session.newSession()
        xs.conf.set(C.MESH_SHARDS.key, "1")
        xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        # balance the two reducer shards: greedy span packing to half
        # the shipped working set (fact ships sk at 8 B/row, fact2
        # ships k2+bonus at 16 B/row)
        xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key,
                    str(GG_ROWS * 24 // 2))
        if mode != "uncapped":
            xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, str(8 << 10))
            xs.conf.set(HOST_BUDGET.key, str(GG_BUDGET))
        if mode == "nograce":
            xs.conf.set(C.CROSSPROC_GRACE_BUCKETS.key, "0")
        svc = xs.enableHostShuffle(os.path.join(root, mode),
                                   process_id=pid, n_processes=2,
                                   timeout_s=300.0)
        xs.createDataFrame({"sk": sk[mine], "price": price[mine]}) \
            .createOrReplaceTempView("fact")
        xs.createDataFrame({"k2": k2[mine], "bonus": bonus[mine]}) \
            .createOrReplaceTempView("fact2")
        if mode == "nograce":
            # the pre-grace contract: a shard that cannot be staged is a
            # STRUCTURED bounded failure, never a wrong answer
            t0 = time.perf_counter()
            try:
                xs.sql(Q).collect()
                aborted, detail = False, ""
            except HostMemoryError as e:
                aborted, detail = True, str(e)[:200]
            out[mode] = {
                "seconds": round(time.perf_counter() - t0, 3),
                "aborted": aborted,
                "error": detail,
            }
            continue
        xs.sql(Q).collect()                  # warm: compile + caches
        base_gb = int(svc.counters["grace_buckets_used"])
        base_gs = int(svc.counters["grace_spill_bytes"])
        t0 = time.perf_counter()
        rows = xs.sql(Q).collect()
        elapsed = time.perf_counter() - t0
        out[mode] = {
            "seconds": round(elapsed, 3),
            "groups": len(rows),
            "checksum": int(sum(int(r[1]) * 7 + int(r[2]) for r in rows)),
            "grace_buckets_used":
                int(svc.counters["grace_buckets_used"]) - base_gb,
            "grace_spill_bytes":
                int(svc.counters["grace_spill_bytes"]) - base_gs,
            "peak_host_bytes": int(svc.ledger.peak),
            "budget_bytes": int(svc.ledger.budget),
        }
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_servebench() -> dict:
    """Servebench lane: multi-tenant serving throughput, plan cache on/off.

    One CPU worker process runs an in-process SQL server twice — plan
    cache disabled, then enabled — with 4 concurrent HTTP sessions each
    replaying the same mix of parameterized query variants.  Cache off,
    every (session, literal-variant) pays its own trace+compile; cache
    on, literal slotting folds all variants of a template into ONE
    shared executable, so the first session's compile serves everyone.
    The lane pins result equality across modes and reports the
    throughput/latency delta the cache buys."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_serve_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--servebench-worker", d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        out, err = p.communicate(timeout=CHILD_TIMEOUT_S)
        if p.returncode != 0:
            raise RuntimeError(
                f"servebench worker rc={p.returncode}: "
                f"{(err or out).strip().splitlines()[-3:]}")
        o = json.loads([ln for ln in out.splitlines()
                        if ln.strip().startswith("{")][-1])
        if o["off"]["checksum"] != o["on"]["checksum"]:
            raise RuntimeError(f"cache on/off results diverge: {o}")
        if o["on"]["cache_hits"] <= 0:
            raise RuntimeError(f"plan cache never hit: {o}")
        mb = o["multibatch"]
        if not mb["checksum_equal"]:
            raise RuntimeError(f"multibatch sessions diverge: {mb}")
        if mb["first_cache_hit"] or not mb["second_cache_hit"]:
            raise RuntimeError(
                f"multibatch statement not cached cross-session: {mb}")
        if mb["stage_cache_hits"] <= 0 or mb["stage_builds"] > 0:
            raise RuntimeError(
                f"second session recompiled multibatch stages: {mb}")
        return {
            "servebench_sessions": o["sessions"],
            "servebench_statements": o["off"]["statements"],
            "servebench_stmts_per_sec_cache_off":
                o["off"]["stmts_per_sec"],
            "servebench_stmts_per_sec_cache_on":
                o["on"]["stmts_per_sec"],
            "servebench_cache_speedup": round(
                o["on"]["stmts_per_sec"]
                / max(o["off"]["stmts_per_sec"], 1e-9), 3),
            "servebench_p50_ms_cache_off": o["off"]["p50_ms"],
            "servebench_p95_ms_cache_off": o["off"]["p95_ms"],
            "servebench_p50_ms_cache_on": o["on"]["p50_ms"],
            "servebench_p95_ms_cache_on": o["on"]["p95_ms"],
            "servebench_cache_hits": o["on"]["cache_hits"],
            "servebench_multibatch_second_session_hit":
                mb["second_cache_hit"],
            "servebench_multibatch_stage_hits": mb["stage_cache_hits"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def servebench_worker_main() -> None:
    """The servebench lane's single worker (see ``_bench_servebench``).

    argv: --servebench-worker <root>.  Starts an in-process SQLServer on
    a loopback port, opens 4 HTTP sessions, and replays 2 query
    templates x 3 literal variants per session, cache off then on.
    Prints ONE JSON line with per-mode throughput, latency percentiles,
    a result checksum, and the cache-on hit count."""
    import tempfile
    import urllib.request

    i = sys.argv.index("--servebench-worker")
    root = sys.argv[i + 1]
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    jax.config.update("jax_platforms", "cpu")
    # fresh compilation cache: the persistent one would hand cache-off
    # its compiles back and fake the comparison
    jax.config.update("jax_compilation_cache_dir",
                      tempfile.mkdtemp(prefix="jaxcache_", dir=root))

    from spark_tpu.server import SQLServer
    from spark_tpu.sql.session import SparkSession

    def _http(port, method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=(json.dumps(body).encode() if body is not None else None),
            method=method)
        with urllib.request.urlopen(req, timeout=300) as resp:
            return json.loads(resp.read().decode())

    N_SESSIONS, N_VARIANTS = 4, 3
    TEMPLATES = [
        "SELECT k % 10 AS g, sum(v) AS sv, count(*) AS c FROM f "
        "WHERE v < {lit} GROUP BY k % 10 ORDER BY g",
        "SELECT count(*) AS c, sum(v) AS sv FROM f WHERE k % 7 = {lit}",
    ]
    base = SparkSession.builder.appName("servebench").getOrCreate()
    out = {"sessions": N_SESSIONS}
    for mode in ("off", "on"):
        srv_sess = base.newSession()
        srv_sess.conf.set("spark.tpu.mesh.shards", "1")
        srv_sess.conf.set("spark.sql.warehouse.dir",
                          os.path.join(root, f"wh_{mode}"))
        srv_sess.conf.set("spark.tpu.server.planCache.enabled",
                          "true" if mode == "on" else "false")
        srv_sess.sql("CREATE TABLE f AS SELECT id AS k, "
                     "(id * 7) % 1000 AS v FROM range(65536)")
        srv = SQLServer(srv_sess, port=0, workers=N_SESSIONS).start()
        try:
            lat_ms, sums, errs = [], [], []
            lock = threading.Lock()

            def client(_cid):
                try:
                    sid = _http(srv.port, "POST", "/session")["sessionId"]
                    for rep in range(N_VARIANTS):
                        for t_i, tpl in enumerate(TEMPLATES):
                            q = tpl.format(lit=101 + 13 * rep + t_i)
                            t0 = time.perf_counter()
                            r = _http(srv.port, "POST", "/sql",
                                      {"query": q, "session": sid})
                            dt = (time.perf_counter() - t0) * 1000
                            s = sum(c for row in r["rows"] for c in row
                                    if isinstance(c, int))
                            with lock:
                                lat_ms.append(dt)
                                sums.append(s)
                    _http(srv.port, "DELETE", f"/session/{sid}")
                except Exception as e:   # noqa: BLE001 — report, not hang
                    with lock:
                        errs.append(f"{type(e).__name__}: {e}")

            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(N_SESSIONS)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            if errs:
                raise RuntimeError(f"servebench {mode}: {errs[:3]}")
            lat_ms.sort()
            pc = srv._plan_cache.stats() if srv._plan_cache else {}
            out[mode] = {
                "statements": len(lat_ms),
                "stmts_per_sec": round(len(lat_ms) / wall, 2),
                "p50_ms": round(lat_ms[len(lat_ms) // 2], 1),
                "p95_ms": round(lat_ms[int(len(lat_ms) * 0.95)
                                       - 1], 1),
                "checksum": int(sum(sums)),
                "cache_hits": int(pc.get("hits", 0)),
            }
        finally:
            srv.stop()

    # cross-session STAGE cache: a multibatch statement (scan split into
    # device batches — previously a plan-cache bailout) repeated from a
    # SECOND session must report cacheHit with the stage executables
    # served from the shared stage cache, not recompiled
    mb_sess = base.newSession()
    mb_sess.conf.set("spark.tpu.mesh.shards", "1")
    mb_sess.conf.set("spark.sql.warehouse.dir", os.path.join(root, "wh_mb"))
    mb_sess.conf.set("spark.tpu.server.planCache.enabled", "true")
    mb_sess.conf.set("spark.tpu.scan.maxBatchRows", "256")
    mb_sess.sql("CREATE TABLE mb AS SELECT id AS k, (id * 13) % 997 AS v "
                "FROM range(2000)")
    MQ = ("SELECT k % 8 AS g, sum(v) AS sv, count(*) AS c FROM mb "
          "GROUP BY k % 8 ORDER BY g")
    srv = SQLServer(mb_sess, port=0, workers=2).start()
    try:
        runs, stats = [], []
        for _ in range(2):
            sid = _http(srv.port, "POST", "/session")["sessionId"]
            runs.append(_http(srv.port, "POST", "/sql",
                              {"query": MQ, "session": sid}))
            stats.append(_http(srv.port, "GET", "/status"))
            _http(srv.port, "DELETE", f"/session/{sid}")
        sc0 = stats[0]["stageCache"]
        sc1 = stats[1]["stageCache"]
        out["multibatch"] = {
            "first_cache_hit": bool(runs[0].get("cacheHit")),
            "second_cache_hit": bool(runs[1].get("cacheHit")),
            "checksum_equal": runs[0]["rows"] == runs[1]["rows"],
            "stage_entries": int(
                stats[1]["planCache"].get("stage_entries", 0)),
            # second-session deltas: executables must come back as stage
            # cache hits, never fresh builds
            "stage_cache_hits": int(sc1["hits"]) - int(sc0["hits"]),
            "stage_builds": int(sc1["builds"]) - int(sc0["builds"]),
        }
    finally:
        srv.stop()
    print(json.dumps(out))
    sys.stdout.flush()


def _bench_dist_pool() -> dict:
    """Distpool lane: burst admission, fixed server vs elastic pool.

    One CPU worker process runs an in-process SQL server twice under the
    same burst — 6 concurrent HTTP clients hammering SELECTs through a
    maxConcurrentStatements=4 admission cap with a single local executor
    thread.  Fixed mode has only that thread, so the burst piles up
    behind admission and clients eat 429 + retry; elastic mode lets the
    supervisor spawn real pool workers off the demand signal and offload
    admitted SELECTs to them, so slots drain faster.  The lane pins
    result equality across modes and proves the whole elastic loop in
    one number set: workers spawned under burst, statements served by
    the pool, and the idle pool reaped back to zero."""
    import shutil
    import tempfile

    d = tempfile.mkdtemp(prefix="spark_tpu_bench_pool_")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("SPARK_TPU_FAULT_PLAN", None)
        env.pop("SPARK_TPU_PLATFORM", None)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--distpool-worker", d],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        out, err = p.communicate(timeout=CHILD_TIMEOUT_S)
        if p.returncode != 0:
            raise RuntimeError(
                f"distpool worker rc={p.returncode}: "
                f"{(err or out).strip().splitlines()[-3:]}")
        o = json.loads([ln for ln in out.splitlines()
                        if ln.strip().startswith("{")][-1])
        if o["fixed"]["checksum"] != o["elastic"]["checksum"]:
            raise RuntimeError(f"fixed/elastic results diverge: {o}")
        el = o["elastic"]
        if el["workers_spawned"] <= 0:
            raise RuntimeError(f"pool never spawned under burst: {o}")
        if el["pool_served"] <= 0:
            raise RuntimeError(f"pool served no statements: {o}")
        # self-exited workers are collected without a reap count, so
        # reaped==spawned is not guaranteed — but an idle pool must
        # shed at least one worker and end empty
        if el["workers_reaped"] <= 0 or el["pool_live_end"] != 0:
            raise RuntimeError(f"idle pool never reaped: {o}")
        return {
            "distpool_clients": o["clients"],
            "distpool_statements": o["fixed"]["statements"],
            "distpool_stmts_per_sec_fixed": o["fixed"]["stmts_per_sec"],
            "distpool_stmts_per_sec_elastic": el["stmts_per_sec"],
            "distpool_p95_ms_fixed": o["fixed"]["p95_ms"],
            "distpool_p95_ms_elastic": el["p95_ms"],
            "distpool_429_rate_fixed": o["fixed"]["rate_429"],
            "distpool_429_rate_elastic": el["rate_429"],
            "distpool_workers_spawned": el["workers_spawned"],
            "distpool_workers_reaped": el["workers_reaped"],
            "distpool_pool_served": el["pool_served"],
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def distpool_worker_main() -> None:
    """The distpool lane's single worker (see ``_bench_dist_pool``).

    argv: --distpool-worker <root>.  Starts an in-process SQLServer
    twice — pool off then pool on — with 6 concurrent HTTP clients each
    replaying the same SELECT burst through a tight admission cap.
    Clients retry on 429 and count every rejection; latency is measured
    end to end INCLUDING retry waits, because that is what a
    backpressured client actually experiences.  Prints ONE JSON line
    with per-mode latency/throughput/429 stats, a result checksum, and
    the elastic mode's pool counters."""
    import tempfile
    import urllib.error
    import urllib.request

    i = sys.argv.index("--distpool-worker")
    root = sys.argv[i + 1]
    os.environ["JAX_PLATFORMS"] = "cpu"

    # env var, not jax.config: pool WORKER processes inherit it, so a
    # compile done by any process (this one or a worker) serves the rest
    cache_dir = tempfile.mkdtemp(prefix="jaxcache_", dir=root)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", cache_dir)

    from spark_tpu.server import SQLServer
    from spark_tpu.sql.session import SparkSession

    def _http(port, method, path, body=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=(json.dumps(body).encode() if body is not None else None),
            method=method)
        with urllib.request.urlopen(req, timeout=300) as resp:
            return json.loads(resp.read().decode())

    N_CLIENTS, N_STMTS = 6, 6
    QUERY = ("SELECT k % 16 AS g, sum(v) AS sv, count(*) AS c "
             "FROM pool_f GROUP BY k % 16 ORDER BY g")
    base = SparkSession.builder.appName("distpool").getOrCreate()
    out = {"clients": N_CLIENTS}
    for mode in ("fixed", "elastic"):
        srv_sess = base.newSession()
        srv_sess.conf.set("spark.tpu.mesh.shards", "1")
        srv_sess.conf.set("spark.sql.warehouse.dir",
                          os.path.join(root, f"wh_{mode}"))
        # tight global admission cap + ONE local executor thread: the
        # fixed server's whole capacity.  The elastic pool's workers are
        # the only way mode two gets more parallelism.
        srv_sess.conf.set("spark.tpu.server.maxConcurrentStatements", "4")
        if mode == "elastic":
            srv_sess.conf.set("spark.tpu.server.pool.enabled", "true")
            srv_sess.conf.set("spark.tpu.server.pool.maxWorkers", "3")
            srv_sess.conf.set(
                "spark.tpu.server.pool.statementsPerWorker", "1")
            srv_sess.conf.set("spark.tpu.server.pool.cooldownSeconds", "0")
            srv_sess.conf.set("spark.tpu.server.pool.pollSeconds", "0.05")
            # 2s of continuous idle before the first reap: long enough
            # to survive the gap between the warm-up and measured
            # bursts, short enough to drain well inside the post-run
            # reap wait below
            srv_sess.conf.set(
                "spark.tpu.server.pool.scaleDownRounds", "40")
        srv_sess.sql("CREATE TABLE pool_f AS SELECT id AS k, "
                     "(id * 7) % 1000 AS v FROM range(120000)")
        srv = SQLServer(srv_sess, port=0, workers=1).start()
        try:
            def burst():
                lat_ms, sums, errs = [], [], []
                n429 = [0]
                lock = threading.Lock()

                def client(_cid):
                    try:
                        sid = _http(srv.port, "POST",
                                    "/session")["sessionId"]
                        for _rep in range(N_STMTS):
                            t0 = time.perf_counter()
                            for _attempt in range(400):
                                try:
                                    r = _http(srv.port, "POST", "/sql",
                                              {"query": QUERY,
                                               "session": sid})
                                    break
                                except urllib.error.HTTPError as e:
                                    if e.code != 429:
                                        raise
                                    with lock:
                                        n429[0] += 1
                                    time.sleep(0.05)
                            else:
                                raise RuntimeError(
                                    "429 retry budget exhausted")
                            dt = (time.perf_counter() - t0) * 1000
                            s = sum(c for row in r["rows"] for c in row
                                    if isinstance(c, int))
                            with lock:
                                lat_ms.append(dt)
                                sums.append(s)
                        _http(srv.port, "DELETE", f"/session/{sid}")
                    except Exception as e:   # noqa: BLE001 — report
                        with lock:
                            errs.append(f"{type(e).__name__}: {e}")

                t0 = time.perf_counter()
                threads = [threading.Thread(target=client, args=(c,))
                           for c in range(N_CLIENTS)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                if errs:
                    raise RuntimeError(f"distpool {mode}: {errs[:3]}")
                return lat_ms, sums, n429[0], wall

            # warm-up burst (unmeasured): pays the first-compile in
            # both modes, and in elastic mode gives the supervisor a
            # demand spike to scale up on so the MEASURED burst hits a
            # warm pool — steady-state elasticity, not boot cost
            burst()
            lat_ms, sums, n429, wall = burst()
            lat_ms.sort()
            spawned = reaped = served = live_end = 0
            sup = srv._pool_supervisor
            if sup is not None:
                # demand is gone; give the reconcile loop time to walk
                # the pool back down so the lane can report a full
                # spawn->serve->reap cycle
                deadline = time.time() + 20.0
                while time.time() < deadline:
                    c = sup.counters
                    if int(c["workers_spawned"]) > 0 \
                            and sup.stats()["live"] == 0:
                        break
                    time.sleep(0.1)
                c = sup.counters
                spawned = int(c["workers_spawned"])
                reaped = int(c["workers_reaped"])
                served = int(c["pool_statements_served"])
                live_end = int(sup.stats()["live"])
            out[mode] = {
                "statements": len(lat_ms),
                "stmts_per_sec": round(len(lat_ms) / wall, 2),
                "p50_ms": round(lat_ms[len(lat_ms) // 2], 1),
                "p95_ms": round(lat_ms[int(len(lat_ms) * 0.95) - 1], 1),
                "rate_429": round(n429 / max(n429 + len(lat_ms), 1), 3),
                "checksum": int(sum(sums)),
                "workers_spawned": spawned,
                "workers_reaped": reaped,
                "pool_served": served,
                "pool_live_end": live_end,
            }
        finally:
            srv.stop()
    print(json.dumps(out))
    sys.stdout.flush()


def child_main() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    forced = [a.split("=", 1)[1] for a in sys.argv if a.startswith("--platform=")]
    if forced:
        jax.config.update("jax_platforms", forced[0])
        if forced[0] == "cpu":
            # CPU fallback exists to land *a* number when the TPU tunnel is
            # down; scale the workload so it finishes inside the timeout,
            # and use the sort-based aggregation (the MXU one-hot matmul
            # kernel is a systolic-array design — pathological on CPU).
            global N, ITERS, J_FACT, J_ITERS, S_ROWS, S_ITERS, P_ROWS, P_REPS
            global SH_CAP, SH_BATCHES
            N, ITERS, J_FACT, J_ITERS = 1 << 19, 5, 1 << 18, 3
            S_ROWS, S_ITERS, P_ROWS, P_REPS = 1 << 19, 3, 1 << 20, 2
            SH_CAP, SH_BATCHES = 1 << 17, 4

    platform = _preflight()

    from spark_tpu.sql.session import SparkSession
    session = SparkSession.builder.appName("bench").getOrCreate()
    session.conf.set("spark.tpu.mesh.shards", "1")

    agg_rows_per_s = _bench_hash_agg(jax, jnp, np, session)

    extras = {}

    def lane(label, fn, baseline, value_key, ratio_key):
        try:
            rps = fn()
            extras[value_key] = round(rps, 1)
            extras[ratio_key] = round(rps / baseline, 3)
        except Exception as e:   # secondary must not sink the primary
            print(f"[bench-child] {label} bench failed: {e}",
                  file=sys.stderr)
            extras[f"{label}_error"] = str(e)[:300]

    lane("q3", lambda: _bench_q3_join(jax, jnp, np, session),
         BASELINE_JOIN_ROWS_PER_S,
         "q3_join_agg_sort_rows_per_sec", "q3_vs_join_baseline")
    if "q3_error" in extras:
        # full q3 crashed (remote-compile HTTP 500 class): land the
        # join+agg number without the final sort, keep the error on
        # record so the regression stays visible
        lane("q3_nosort",
             lambda: _bench_q3_join(jax, jnp, np, session,
                                    with_sort=False),
             BASELINE_JOIN_ROWS_PER_S,
             "q3_join_agg_rows_per_sec_nosort",
             "q3_nosort_vs_join_baseline")
    lane("sort", lambda: _bench_sort(jax, jnp, np, session),
         BASELINE_SORT_ROWS_PER_S,
         "sort_rows_per_sec", "sort_vs_baseline")
    lane("scan", lambda: _bench_parquet_scan(np, session),
         BASELINE_SCAN_ROWS_PER_S,
         "parquet_scan_rows_per_sec", "scan_vs_baseline")
    try:
        # host-side data plane: one lane, several figures (wire vs the
        # seed pickle plane in the same run, so the ratio is apples to
        # apples on this machine's filesystem)
        extras.update(_bench_shuffle(np))
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] shuffle bench failed: {e}", file=sys.stderr)
        extras["shuffle_error"] = str(e)[:300]
    try:
        # distributed join: 2 real worker processes (always CPU — they
        # must not contend for the accelerator), shuffled vs gather
        extras.update(_bench_dist_join())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distjoin bench failed: {e}", file=sys.stderr)
        extras["distjoin_error"] = str(e)[:300]
    try:
        # skewed distributed sort-merge join: 2 real worker processes,
        # range partitioning + skew split vs the shuffled hash path
        extras.update(_bench_dist_sort())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distsort bench failed: {e}", file=sys.stderr)
        extras["distsort_error"] = str(e)[:300]
    try:
        # adaptive execution: 2 real worker processes, a ~20x
        # misestimated join side, frozen hash shuffle vs the observed-
        # stats demotion to broadcast
        extras.update(_bench_dist_adapt())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distadapt bench failed: {e}", file=sys.stderr)
        extras["distadapt_error"] = str(e)[:300]
    try:
        # encoded execution: 2 real worker processes, low-cardinality
        # string-key join, dictionary-dedup wire vs words-per-block
        extras.update(_bench_dist_dict())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distdict bench failed: {e}", file=sys.stderr)
        extras["distdict_error"] = str(e)[:300]
    try:
        # run-length encoded execution: 2 real worker processes,
        # sorted time-series join, run-coded wire vs dense blocks
        extras.update(_bench_dist_rle())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distrle bench failed: {e}", file=sys.stderr)
        extras["distrle_error"] = str(e)[:300]
    try:
        # memory-pressure path: the distjoin workload with the host
        # budget capped below the working set — must complete, spill,
        # and match the uncapped aggregates
        extras.update(_bench_dist_spill())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distspill bench failed: {e}", file=sys.stderr)
        extras["distspill_error"] = str(e)[:300]
    try:
        # graceful degradation: the join with the host budget capped
        # below the reducers' drained shard — must complete via grace
        # partitioning, match the uncapped aggregates, and abort
        # structured when grace is disabled
        extras.update(_bench_dist_grace())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distgrace bench failed: {e}", file=sys.stderr)
        extras["distgrace_error"] = str(e)[:300]
    try:
        # two-tier exchange: armed-vs-disarmed device tier across 2 real
        # processes (structured fallback ladder), plus the forced-mesh
        # device-vs-wire data-plane comparison
        extras.update(_bench_dist_ici())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distici bench failed: {e}", file=sys.stderr)
        extras["distici_error"] = str(e)[:300]
    try:
        # whole-stage compilation: 2 real worker processes, fused vs
        # per-operator dispatch and cold vs warm stage-executable cache
        extras.update(_bench_stagecache())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] stagecache bench failed: {e}",
              file=sys.stderr)
        extras["stagecache_error"] = str(e)[:300]
    try:
        # multi-tenant serving: concurrent HTTP sessions replaying a
        # parameterized query mix, shared plan cache off vs on
        extras.update(_bench_servebench())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] servebench failed: {e}", file=sys.stderr)
        extras["servebench_error"] = str(e)[:300]
    try:
        # elastic worker pool: burst of concurrent HTTP clients through
        # a tight admission cap, fixed server vs demand-driven pool
        extras.update(_bench_dist_pool())
    except Exception as e:   # secondary must not sink the primary
        print(f"[bench-child] distpool failed: {e}", file=sys.stderr)
        extras["distpool_error"] = str(e)[:300]

    try:
        load_1m = round(os.getloadavg()[0], 2)
    except OSError:
        load_1m = None
    print(json.dumps({
        "metric": "hash_agg_keys_rows_per_sec",
        "value": round(agg_rows_per_s, 1),
        "unit": "rows/s",
        "vs_baseline": round(agg_rows_per_s / BASELINE_AGG_ROWS_PER_S, 3),
        "backend": platform,
        # measurement conditions: median-of-N protocol, pinned host
        # thread pools, and ambient load at report time — so two BENCH
        # lines are comparable before their values are
        "runs_per_lane": BENCH_RUNS,
        "threads_pinned": int(os.environ.get("OMP_NUM_THREADS", 0)
                              or BENCH_THREADS),
        "loadavg_1m": load_1m,
        **extras,
    }))


if __name__ == "__main__":
    if "--distjoin-worker" in sys.argv:
        distjoin_worker_main()
    elif "--distadapt-worker" in sys.argv:
        distadapt_worker_main()
    elif "--distsort-worker" in sys.argv:
        distsort_worker_main()
    elif "--distdict-worker" in sys.argv:
        distdict_worker_main()
    elif "--distrle-worker" in sys.argv:
        distrle_worker_main()
    elif "--distspill-worker" in sys.argv:
        distspill_worker_main()
    elif "--distgrace-worker" in sys.argv:
        distgrace_worker_main()
    elif "--distici-worker" in sys.argv:
        distici_worker_main()
    elif "--distici-mesh" in sys.argv:
        distici_mesh_main()
    elif "--stagecache-worker" in sys.argv:
        stagecache_worker_main()
    elif "--servebench-worker" in sys.argv:
        servebench_worker_main()
    elif "--distpool-worker" in sys.argv:
        distpool_worker_main()
    elif "--child" in sys.argv:
        child_main()
    else:
        sys.exit(orchestrate())
