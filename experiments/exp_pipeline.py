"""Find where the 550ms/iter goes in the bench hash-agg pipeline.

Times, with the same fori_loop+perturb methodology:
  1. full physical.run (as bench does, minus compact/slice)
  2. grouped_aggregate kernel alone on a prebuilt device batch
  3. MXU fast path manually: bucket+limb extraction+einsum (no cond)
  4. limb extraction alone
  5. bucket-code computation alone
"""
import sys
import time

sys.path.append("/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.kernels import compact, grouped_aggregate, _mxu_grouped_aggregate
from spark_tpu.sql import functions as F
from spark_tpu.sql import physical as P
from spark_tpu.sql.planner import QueryExecution
from spark_tpu.sql.session import SparkSession

N = 1 << 22
GROUPS = 1024
ITERS = 5

rng = np.random.default_rng(7)
keys = rng.integers(0, GROUPS, N).astype(np.int64)
vals = rng.integers(0, 100, N).astype(np.int64)

session = SparkSession.builder.appName("exp").getOrCreate()
session.conf.set("spark.tpu.mesh.shards", "1")
df = session.createDataFrame({"k": keys, "v": vals})
q = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("*").alias("c"))
pq = QueryExecution(session, q._plan).planned
physical = pq.physical
dev_leaves = tuple(b.to_device() for b in pq.leaves)


def perturb(leaves, bump):
    out = []
    for b in leaves:
        vecs = []
        for name, v in zip(b.names, b.vectors):
            if name == "v":
                data = v.data + bump
            elif name == "k":
                data = v.data ^ (bump & jnp.int64(GROUPS - 1))
            else:
                data = v.data
            vecs.append(ColumnVector(data, v.dtype, v.valid, v.dictionary))
        out.append(ColumnBatch(b.names, vecs, b.row_valid, b.capacity))
    return tuple(out)


def loop_time(name, step_fn):
    """step_fn(leaves, bump) -> scalar dependency"""
    @jax.jit
    def run(leaves):
        def body(i, acc):
            return acc + step_fn(leaves, i.astype(jnp.int64))
        return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))
    r = jax.block_until_ready(run(dev_leaves))
    t0 = time.perf_counter()
    r = jax.block_until_ready(run(dev_leaves))
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:34s} {dt*1e3:9.3f} ms/iter   {N/dt/1e6:10.1f} M rows/s",
          flush=True)


# 1. full plan
def step_full(leaves, bump):
    pb = perturb(leaves, bump)
    ctx = P.ExecContext(jnp, pb)
    out = physical.run(ctx)
    return out.vectors[1].data[:32].sum() & jnp.int64(1)

# 2. grouped_aggregate alone
agg_node = None
node = physical
while node is not None:
    if node.__class__.__name__ in ("PAggregate", "PHashAggregate"):
        agg_node = node
        break
    node = getattr(node, "child", None)
pass
  

def get_chain(n):
    out = []
    while n is not None:
        out.append(n.__class__.__name__)
        n = getattr(n, "child", None)
    return out
print("plan chain:", get_chain(physical))

keys_j = jnp.asarray(keys)
vals_j = jnp.asarray(vals)
from spark_tpu import types as T
batch0 = ColumnBatch(
    ["k", "v"],
    [ColumnVector(keys_j, T.LongType(), None, None),
     ColumnVector(vals_j, T.LongType(), None, None)],
    None, N)

from spark_tpu.expressions import col
from spark_tpu.aggregates import Sum, CountStar
key_exprs = [col("k")]
slots = [(Sum(col("v")), "s"), (CountStar(), "c")]

def step_agg(leaves, bump):
    b = ColumnBatch(
        ["k", "v"],
        [ColumnVector(keys_j ^ (bump & jnp.int64(GROUPS - 1)), T.LongType(),
                      None, None),
         ColumnVector(vals_j + bump, T.LongType(), None, None)],
        None, N)
    out = grouped_aggregate(jnp, b, key_exprs, slots)
    return out.vectors[1].data[:32].sum() & jnp.int64(1)

# 4. limb extraction alone (8 limbs, uint64 emulation)
def step_limbs(leaves, bump):
    data = vals_j + bump
    shifted = data.astype(jnp.uint64) + jnp.uint64(1 << 63)
    acc = jnp.zeros((), jnp.bfloat16)
    planes = []
    for i in range(8):
        limb = ((shifted >> jnp.uint64(8 * i)) & jnp.uint64(0xFF))
        planes.append(limb.astype(jnp.bfloat16))
    return jnp.stack(planes, -1)[::65536].sum().astype(jnp.int64) & jnp.int64(1)

# 5. bucket codes alone
def step_bucket(leaves, bump):
    data = keys_j ^ (bump & jnp.int64(GROUPS - 1))
    kmin = data.min()
    kmax = data.max()
    code = data - kmin
    b32 = jnp.clip(code, 0, 4095).astype(jnp.int32)
    return b32[::65536].sum().astype(jnp.int64) & jnp.int64(1)


loop_time("bucket codes alone", step_bucket)
loop_time("limb extraction alone", step_limbs)
loop_time("grouped_aggregate kernel", step_agg)
loop_time("full physical.run", step_full)
