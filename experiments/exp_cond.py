"""Is the lax.cond in _mxu_grouped_aggregate executing the slow branch?

Times grouped_aggregate with the cond monkeypatched to always take the
fast branch, vs stock.
"""
import sys
import time

sys.path.append("/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu import types as T
from spark_tpu.kernels import grouped_aggregate
import spark_tpu.kernels as K
from spark_tpu.expressions import col
from spark_tpu.aggregates import Sum, CountStar

N = 1 << 22
GROUPS = 1024
ITERS = 5

rng = np.random.default_rng(7)
keys_j = jnp.asarray(rng.integers(0, GROUPS, N).astype(np.int64))
vals_j = jnp.asarray(rng.integers(0, 100, N).astype(np.int64))

key_exprs = [col("k")]
slots = [(Sum(col("v")), "s"), (CountStar(), "c")]


def step(bump):
    b = ColumnBatch(
        ["k", "v"],
        [ColumnVector(keys_j ^ (bump & jnp.int64(GROUPS - 1)), T.LongType(),
                      None, None),
         ColumnVector(vals_j + bump, T.LongType(), None, None)],
        None, N)
    out = grouped_aggregate(jnp, b, key_exprs, slots)
    return out.vectors[1].data[:32].sum() & jnp.int64(1)


def loop_time(name):
    @jax.jit
    def run(_x):
        def body(i, acc):
            return acc + step(i.astype(jnp.int64))
        return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))
    r = jax.block_until_ready(run(0))
    t0 = time.perf_counter()
    r = jax.block_until_ready(run(0))
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:34s} {dt*1e3:9.3f} ms/iter   {N/dt/1e6:10.1f} M rows/s",
          flush=True)


which = sys.argv[1] if len(sys.argv) > 1 else "fast"
if which == "fast":
    # monkeypatch: always take branch index 0 path = true_fn? lax.cond(pred, t, f)
    real_cond = jax.lax.cond
    def fast_cond(pred, true_fn, false_fn, *ops):
        return true_fn(*ops)
    jax.lax.cond = fast_cond
    loop_time("fast branch only (no cond)")
elif which == "slow":
    real_cond = jax.lax.cond
    def slow_cond(pred, true_fn, false_fn, *ops):
        return false_fn(*ops)
    jax.lax.cond = slow_cond
    loop_time("slow branch only (sort-based)")
else:
    loop_time("stock (lax.cond)")
