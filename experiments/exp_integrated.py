"""Time the INTEGRATED grouped_aggregate (pallas path) on real TPU,
same methodology as bench.py."""
import sys
import time

sys.path.append("/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu import types as T
from spark_tpu.kernels import grouped_aggregate
from spark_tpu.expressions import Col
from spark_tpu.aggregates import Sum, CountStar

N = 1 << 22
GROUPS = 1024
ITERS = 10

rng = np.random.default_rng(7)
keys_np = rng.integers(0, GROUPS, N).astype(np.int64)
vals_np = rng.integers(0, 100, N).astype(np.int64)
keys_j = jnp.asarray(keys_np)
vals_j = jnp.asarray(vals_np)

key_exprs = [Col("k")]
slots = [(Sum(Col("v")), "s"), (CountStar(), "c")]


def step(bump):
    b = ColumnBatch(
        ["k", "v"],
        [ColumnVector(keys_j ^ (bump & jnp.int64(GROUPS - 1)), T.LongType(),
                      None, None),
         ColumnVector(vals_j + bump, T.LongType(), None, None)],
        None, N)
    out = grouped_aggregate(jnp, b, key_exprs, slots)
    return out


# correctness gate vs numpy oracle (unperturbed)
print("compiling correctness gate...", flush=True)
out0 = jax.jit(lambda: step(jnp.int64(0)))()
got_k = np.asarray(out0.vectors[0].data)
got_s = np.asarray(out0.vectors[1].data)
rv = np.asarray(out0.row_valid_or_true())
live_k = got_k[rv][:GROUPS]
live_s = got_s[rv][:GROUPS]
expect = np.zeros(GROUPS, np.int64)
np.add.at(expect, keys_np, vals_np)
order = np.argsort(live_k)
assert len(live_k) == GROUPS, len(live_k)
assert np.array_equal(live_s[order], expect), "sum mismatch vs oracle"
print("correctness OK", flush=True)


@jax.jit
def run(_x):
    def body(i, acc):
        out = step(i.astype(jnp.int64))
        return acc + (out.vectors[1].data[:32].sum() & jnp.int64(1))
    return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))


print("compiling loop...", flush=True)
r = jax.block_until_ready(run(0))
t0 = time.perf_counter()
r = jax.block_until_ready(run(0))
dt = (time.perf_counter() - t0) / ITERS
print(f"integrated pallas agg: {dt*1e3:.3f} ms/iter  "
      f"{N/dt/1e6:.1f} M rows/s  vs_baseline={N/dt/93.5e6:.2f}", flush=True)
