"""Test: does XLA fuse limb-extraction into the einsum, recomputing per
bucket tile?  Compare with/without optimization_barrier, plus einsum from
int64-derived planes.
"""
import sys
import time

sys.path.append("/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_enable_x64", True)

N = 1 << 22
B = 4096
GROUPS = 1024
ITERS = 5
L = 2048

rng = np.random.default_rng(7)
keys_j = jnp.asarray(rng.integers(0, GROUPS, N).astype(np.int64))
vals_j = jnp.asarray(rng.integers(0, 100, N).astype(np.int64))


def build(bump, barrier):
    kdata = keys_j ^ (bump & jnp.int64(GROUPS - 1))
    vdata = vals_j + bump
    kmin = kdata.min()
    bucket32 = jnp.clip(kdata - kmin, 0, B - 1).astype(jnp.int32)
    live = jnp.ones(N, jnp.bfloat16)
    shifted = vdata.astype(jnp.uint64) + jnp.uint64(1 << 63)
    planes = [live]
    for i in range(8):
        limb = ((shifted >> jnp.uint64(8 * i)) & jnp.uint64(0xFF))
        planes.append(limb.astype(jnp.bfloat16))
    planes.append(live)
    plane_mat = jnp.stack(planes, -1)            # (N, 11)
    if barrier:
        plane_mat, bucket32 = jax.lax.optimization_barrier(
            (plane_mat, bucket32))
    T_t = N // L
    bb = bucket32.reshape(T_t, L)
    pp = plane_mat.reshape(T_t, L, 10)
    oh = jax.nn.one_hot(bb, B, dtype=jnp.bfloat16)
    if barrier == 2:
        oh = jax.lax.optimization_barrier(oh)
    per_tile = jnp.einsum("tlb,tlp->tbp", oh, pp,
                          preferred_element_type=jnp.float32)
    tot = per_tile.astype(jnp.int32).sum(0)
    return tot[:32].sum().astype(jnp.int64) & jnp.int64(1)


def loop_time(name, barrier):
    @jax.jit
    def run(_x):
        def body(i, acc):
            return acc + build(i.astype(jnp.int64), barrier)
        return jax.lax.fori_loop(0, ITERS, body, jnp.int64(0))
    r = jax.block_until_ready(run(0))
    t0 = time.perf_counter()
    r = jax.block_until_ready(run(0))
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:34s} {dt*1e3:9.3f} ms/iter   {N/dt/1e6:10.1f} M rows/s",
          flush=True)


loop_time("no barrier (kernel-like)", 0)
loop_time("barrier before one_hot", 1)
loop_time("barrier incl oh", 2)
