"""Timing experiments for the grouped-aggregate hot loop on real TPU.

Methodology matches bench.py: ITERS iterations inside one lax.fori_loop,
inputs perturbed from the carried index, scalar dependency carried out.
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

N = 1 << 22
B = 4096
P = 11
GROUPS = 1024
ITERS = 10

rng = np.random.default_rng(0)
bucket_np = rng.integers(0, GROUPS, N).astype(np.int32)
planes_np = rng.integers(0, 256, (N, P)).astype(np.float32)


def loop_time(name, step):
    """step(bucket, planes) -> (B,P) i32; time ITERS perturbed iterations."""
    @jax.jit
    def run(bucket, planes):
        def body(i, acc):
            b = bucket ^ (i & jnp.int32(GROUPS - 1))
            p = planes + i.astype(jnp.float32) * 0.0   # keep values exact
            out = step(b, p)
            return acc + out[0, 0] + out[GROUPS - 1, P - 1]
        return jax.lax.fori_loop(0, ITERS, body, jnp.int32(0))

    bucket = jnp.asarray(bucket_np)
    planes = jnp.asarray(planes_np)
    r = jax.block_until_ready(run(bucket, planes))   # compile+warm
    t0 = time.perf_counter()
    r = jax.block_until_ready(run(bucket, planes))
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:30s} {dt*1e3:9.3f} ms/iter   {N/dt/1e6:10.1f} M rows/s")


# ---------------------------------------------------------------- a) einsum
def step_einsum(bucket, planes):
    L_E = 2048
    T = N // L_E
    bb = bucket.reshape(T, L_E)
    pp = planes.astype(jnp.bfloat16).reshape(T, L_E, P)
    oh = jax.nn.one_hot(bb, B, dtype=jnp.bfloat16)
    per_tile = jnp.einsum("tlb,tlp->tbp", oh, pp,
                          preferred_element_type=jnp.float32)
    return per_tile.astype(jnp.int32).sum(0)


# ---------------------------------------------------------------- b) pallas
def make_pallas_step(L, BB, n_active, in_dtype=jnp.bfloat16):
    T = N // L
    BCH = B // BB

    def kernel(nact_ref, bucket_ref, planes_ref, out_ref, acc_ref):
        t = pl.program_id(0)
        bj = pl.program_id(1)

        @pl.when(t == 0)
        def _init():
            acc_ref[pl.ds(bj * BB, BB), :] = jnp.zeros((BB, P), jnp.int32)

        @pl.when(bj < nact_ref[0])
        def _active():
            b = bucket_ref[0, :]
            base = bj * BB
            iota = jax.lax.broadcasted_iota(jnp.int32, (L, BB), 1) + base
            oh = (b[:, None] == iota).astype(in_dtype)
            pt = jax.lax.dot_general(
                oh, planes_ref[:],
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc_ref[pl.ds(base, BB), :] += pt.astype(jnp.int32)

        @pl.when((t == T - 1) & (bj == BCH - 1))
        def _fin():
            out_ref[:] = acc_ref[:]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T, BCH),
        in_specs=[
            pl.BlockSpec((1, L), lambda t, bj, n: (0, t)),
            pl.BlockSpec((L, P), lambda t, bj, n: (t, 0)),
        ],
        out_specs=pl.BlockSpec((B, P), lambda t, bj, n: (0, 0)),
        scratch_shapes=[pltpu.VMEM((B, P), jnp.int32)],
    )

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, P), jnp.int32),
    )

    def step(bucket, planes):
        return call(jnp.array([n_active], jnp.int32),
                    bucket.reshape(1, N),
                    planes.astype(in_dtype))
    return step


# ---------------------------------------------------------------- c) sort
def step_sort(bucket, planes):
    order = jnp.argsort(bucket)
    sp = planes[order]
    return jax.ops.segment_sum(sp, bucket[order],
                               num_segments=B).astype(jnp.int32)


def check(step):
    """one un-perturbed run vs numpy oracle"""
    out = np.asarray(jax.jit(step)(jnp.asarray(bucket_np),
                                   jnp.asarray(planes_np)))
    expect = np.zeros((B, P), np.int64)
    np.add.at(expect, bucket_np, planes_np.astype(np.int64))
    assert np.array_equal(out.astype(np.int64), expect), "WRONG RESULT"


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "einsum"):
        check(step_einsum)
        loop_time("xla-einsum B=4096", step_einsum)
    if which in ("all", "pallas"):
        for (L, BB) in [(1024, 512)]:
            try:
                step = make_pallas_step(L, BB, B // BB)
                check(step)
                loop_time(f"pallas L={L} BB={BB} full", step)
                nact = (GROUPS + BB - 1) // BB
                step2 = make_pallas_step(L, BB, nact)
                loop_time(f"pallas L={L} BB={BB} act={nact}", step2)
            except Exception as e:
                print(f"pallas L={L} BB={BB} FAILED: {type(e).__name__}: "
                      f"{str(e)[:300]}")
    if which in ("all", "sort"):
        check(step_sort)
        loop_time("sort+segment_sum", step_sort)


if __name__ == "__main__":
    main()
