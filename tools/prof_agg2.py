"""Profile TPU agg pieces with the bench's honest methodology:
ITERS inside one fori_loop with carried dependency, one scalar fetch."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import spark_tpu  # noqa
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), "backend:", jax.default_backend())

N = 1 << 22
GROUPS = 1024
B = 4096
ITERS = 20

rng = np.random.default_rng(7)
kd = jnp.asarray(rng.integers(0, GROUPS, N).astype(np.int64))
vd = jnp.asarray(rng.integers(0, 100, N).astype(np.int64))


def loop_time(name, step, *args, iters=None):
    """step(i, *args) -> scalar contribution; fori_loop of ITERS.

    Each variant is isolated: a compile failure (e.g. a Mosaic
    regression in the Pallas step) must not abort the remaining
    measurements — a rare tunnel window has to yield the full profile."""
    it = iters or ITERS

    def run(args):
        def body(i, acc):
            return acc + step(i.astype(jnp.int64), *args)
        return jax.lax.fori_loop(0, it, body, jnp.int64(0))
    try:
        f = jax.jit(run)
        _ = int(np.asarray(f(args)))          # compile+warm
        t0 = time.perf_counter()
        acc = int(np.asarray(f(args)))
        dt = (time.perf_counter() - t0) / it
        print(f"{name:44s} {dt*1e3:9.2f} ms/iter {N/dt/1e6:9.1f} Mrows/s",
              flush=True)
        return dt
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:300]}", flush=True)
        import traceback
        traceback.print_exc(limit=3)
        return None


from spark_tpu import pallas_agg, kernels
from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.expressions import Col
from spark_tpu.aggregates import Sum, CountStar

# 1. perturb only (baseline: the bench's input mutation)
def perturb(i, k, v):
    k2 = k ^ (i & jnp.int64(GROUPS - 1))
    v2 = v + i
    return (k2.sum() & jnp.int64(1)) + (v2.sum() & jnp.int64(1))

loop_time("perturb + 2 sums (baseline)", perturb, kd, vd)

# 2. plane assembly + pallas accumulate
def pal_step(i, k, v):
    k2 = k ^ (i & jnp.int64(GROUPS - 1))
    v2 = v + i
    b32 = jnp.clip(k2.astype(jnp.int32), 0, B - 1)
    lo = (v2 & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    planes = jnp.stack([jnp.ones(N, jnp.bfloat16)] +
                       [((lo >> jnp.uint32(8 * j)) & jnp.uint32(0xFF)
                         ).astype(jnp.bfloat16) for j in range(4)], axis=-1)
    tot = pallas_agg.grouped_accumulate(b32, planes, jnp.int32(B // 512), B)
    return tot.sum() & jnp.int64(1)

loop_time("assemble + pallas accumulate", pal_step, kd, vd)

# 3. full kernels.grouped_aggregate (MXU/pallas path)
def full_step(i, k, v):
    k2 = k ^ (i & jnp.int64(GROUPS - 1))
    v2 = v + i
    batch = ColumnBatch(
        ["k", "v"],
        [ColumnVector(k2, spark_tpu.types.int64, None, None),
         ColumnVector(v2, spark_tpu.types.int64, None, None)], None, N)
    out = kernels.grouped_aggregate(
        jnp, batch, [Col("k")], [(Sum(Col("v")), "s"), (CountStar(), "c")],
        bucket_cap=B)
    return out.vectors[1].data.sum() & jnp.int64(1)

loop_time("kernels.grouped_aggregate (auto path)", full_step, kd, vd)

# 4. sorted path
kernels.MXU_AGG_ENABLED = False
loop_time("kernels.grouped_aggregate (sorted)", full_step, kd, vd)
kernels.MXU_AGG_ENABLED = None

# 5. primitives under the same loop
loop_time("lax.sort int64",
          lambda i, k, v: jax.lax.sort(v + i)[0] & jnp.int64(1), kd, vd)
loop_time("lax.sort int32",
          lambda i, k, v: jax.lax.sort(
              (v + i).astype(jnp.int32))[0].astype(jnp.int64) & jnp.int64(1),
          kd, vd)
loop_time("argsort int64",
          lambda i, k, v: jnp.argsort(v + i)[0] & jnp.int64(1), kd, vd)
loop_time("2-col sort (key+perm) int64",
          lambda i, k, v: jax.lax.sort((v + i, k))[1][0] & jnp.int64(1),
          kd, vd)

# 6. radix argsort candidate vs the bitonic (the sort-lane decision
# point: 0.22x baseline today; radix is dense one-hot/cumsum/scatter)
loop_time("radix_argsort bits=4",
          lambda i, k, v: kernels.radix_argsort(
              jnp, v + i).astype(jnp.int64)[0] & jnp.int64(1), kd, vd,
          iters=3)
loop_time("radix_argsort bits=8",
          lambda i, k, v: kernels.radix_argsort(
              jnp, v + i, bits=8).astype(jnp.int64)[0] & jnp.int64(1),
          kd, vd, iters=3)
loop_time("lax.sort argsort baseline (2-op)",
          lambda i, k, v: jax.lax.sort(
              (v + i, jnp.arange(N, dtype=jnp.int32)),
              num_keys=1)[1][0].astype(jnp.int64) & jnp.int64(1), kd, vd)
print("done")
