"""Profile the standing-query micro-batch commit path end to end on
one process: per-batch wall time through the full exactly-once
protocol (offsets WAL -> compute -> state snapshot -> sink -> atomic
commit entry), the plan-once claim (batch 0 pays the stage build,
batch 1+ must report zero rebuilds), cold-restart recovery cost over a
fully committed checkpoint, one-batch replay cost after a torn commit
tail, and the wire-format spill path under a capped HostMemoryLedger
with sink byte-parity against the uncapped run.

Run: JAX_PLATFORMS=cpu python tools/prof_stream.py [n_batches rows_per_batch]
(defaults 8 x 20000; CPU is fine — the protocol cost, not the kernel
cost, is what this measures)."""
import glob
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import numpy as np

from spark_tpu import types as T
from spark_tpu.sql import functions as F
from spark_tpu.sql.dataframe import DataFrame
from spark_tpu.sql.session import SparkSession
from spark_tpu.streaming.core import (
    FileSink, FileStreamSource, StreamExecution, StreamingRelation,
)

N_BATCHES = int(sys.argv[1]) if len(sys.argv) > 1 else 8
ROWS = int(sys.argv[2]) if len(sys.argv) > 2 else 20_000
N_KEYS = 256

SCHEMA = T.StructType([
    T.StructField("ts", T.timestamp),
    T.StructField("k", T.string),
    T.StructField("v", T.int64),
])


def write_feeds(spark, in_dir):
    """One parquet file per micro-batch (maxFilesPerTrigger=1); feed i
    covers event-time seconds [10i, 10i+10) so the watermark advances
    every batch and closed windows get finalized + evicted."""
    os.makedirs(in_dir, exist_ok=True)
    rng = np.random.default_rng(11)
    keys = np.array([f"k{j:04d}" for j in range(N_KEYS)])
    for i in range(N_BATCHES):
        ts = (10_000_000 * i
              + rng.integers(0, 10_000_000, ROWS)).astype("datetime64[us]")
        spark.createDataFrame({
            "ts": np.sort(ts),
            "k": keys[rng.integers(0, N_KEYS, ROWS)],
            "v": rng.integers(0, 100, ROWS).astype(np.int64),
        }).write.parquet(os.path.join(in_dir, f"f{i:03d}"))


def build(spark, in_dir, ckpt, out):
    src = FileStreamSource("parquet", in_dir, SCHEMA,
                          {"maxfilespertrigger": "1"})
    df = (DataFrame(spark, StreamingRelation(src))
          .withWatermark("ts", "5 seconds")
          .groupBy(F.window("ts", "10 seconds").alias("w"),
                   F.col("k"))
          .agg(F.sum("v").alias("s")))
    return StreamExecution(spark, df._plan, FileSink("json", out, {}),
                           "append", ckpt, 0.1, None)


def drain_timed(ex):
    """process_all_available with a wall clock around every committed
    batch (the public drain loop just calls _run_one_batch until dry)."""
    times = []
    while True:
        t0 = time.perf_counter()
        if not ex._run_one_batch():
            break
        times.append(time.perf_counter() - t0)
    return times


def sink_files(out):
    return {os.path.basename(p): open(p, "rb").read()
            for p in sorted(glob.glob(os.path.join(out, "part-*")))}


def report(name, dt, extra=""):
    print(f"{name:44s} {dt * 1e3:9.2f} ms  {extra}", flush=True)


def main():
    root = tempfile.mkdtemp(prefix="prof_stream_")
    spark = SparkSession.builder.appName("prof_stream").getOrCreate()
    spark.conf.set("spark.tpu.mesh.shards", "1")
    in_dir = os.path.join(root, "in")
    write_feeds(spark, in_dir)
    print(f"standing-query bench: {N_BATCHES} batches x {ROWS} rows, "
          f"{N_KEYS} keys, windowed sum + watermark eviction", flush=True)

    # -- steady state: the whole commit protocol, per batch ---------------
    ckpt, out = os.path.join(root, "ckpt"), os.path.join(root, "out")
    ex = build(spark, in_dir, ckpt, out)
    times = drain_timed(ex)
    assert len(times) == N_BATCHES, (len(times), ex.exception)
    rebuilds = [p["stageRebuilds"] for p in ex.progress]
    # the cache needs a warmup window while state/padding shape buckets
    # stabilize; after that every batch must run fully cached
    steady = [t for t, r in zip(times, rebuilds) if r == 0] or times
    warm = [t for t, r in zip(times, rebuilds) if r > 0]
    report("warmup batches (stage builds)",
           sum(warm) / max(len(warm), 1),
           f"n={len(warm)} rebuilds/batch={rebuilds}")
    report("converged steady-state commit", sum(steady) / len(steady),
           f"n={len(steady)} "
           f"({ROWS / (sum(steady) / len(steady)) / 1e6:.2f} Mrows/s)")
    assert rebuilds[-1] == 0, "stage cache never converged: %s" % rebuilds
    m = ex.metrics
    print(f"{'':44s} state={m['state_bytes']}B/{m['state_rows']}rows "
          f"evicted={m['evicted_rows']} spills={m['spill_events']}",
          flush=True)
    oracle = sink_files(out)
    ex.stop()

    # -- cold restart over a fully committed checkpoint -------------------
    ex2 = build(spark, in_dir, ckpt, out)
    t0 = time.perf_counter()
    ex2.process_all_available()
    report("cold restart, nothing to replay", time.perf_counter() - t0,
           f"replayed={ex2.metrics['replayed_batches']}")
    ex2.stop()

    # -- torn commit tail: one-batch replay -------------------------------
    last = N_BATCHES - 1
    tail = os.path.join(ckpt, "commits", str(last))
    blob = open(tail, "rb").read()
    with open(tail, "wb") as f:
        f.write(blob[:9])       # torn mid-write = uncommitted
    ex3 = build(spark, in_dir, ckpt, out)
    t0 = time.perf_counter()
    ex3.process_all_available()
    report("restart after torn commit (1-batch replay)",
           time.perf_counter() - t0,
           f"replayed={ex3.metrics['replayed_batches']}")
    assert ex3.metrics["replayed_batches"] >= 1
    assert sink_files(out) == oracle, "replay broke sink byte-parity"
    ex3.stop()

    # -- capped ledger: wire-format state spill at parity ------------------
    from spark_tpu.memory import HostMemoryLedger
    prev = getattr(spark, "_host_ledger", None)
    spark._host_ledger = HostMemoryLedger(budget=4096)
    try:
        ckpt_c, out_c = os.path.join(root, "ckpt_c"), os.path.join(root, "out_c")
        ex4 = build(spark, in_dir, ckpt_c, out_c)
        t0 = time.perf_counter()
        ex4.process_all_available()
        mc = ex4.metrics
        report("capped ledger (4KB), spill path",
               time.perf_counter() - t0,
               f"spills={mc['spill_events']} spill_bytes={mc['spill_bytes']}")
        assert mc["spill_events"] > 0, "4KB budget should force spill"
        parity = sink_files(out_c) == oracle
        print(f"{'':44s} sink parity vs uncapped: "
              f"{'OK' if parity else 'MISMATCH'}", flush=True)
        assert parity
        ex4.stop()
    finally:
        spark._host_ledger = prev

    shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
