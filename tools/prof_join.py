"""Profile the device-side pieces of the cross-process JOIN lanes with
the bench's honest methodology (ITERS inside one fori_loop with a
carried dependency, one scalar fetch): the hash-bucket and range-span
routers, the (null_flag, key) tie sort that makes span slices sorted
runs, the build-side sort the presorted-merge path skips, and the
probe searchsorted + output gather that both local joins share.

Run inside a TPU window (bench.py schedules it as a window probe next
to prof_agg2.py); falls back to whatever backend jax gives."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import spark_tpu  # noqa
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), "backend:", jax.default_backend())

N = 1 << 21          # probe rows
M = 1 << 19          # build rows
N_FINE = 64          # fine hash partitions (8/proc x 8 procs)
N_CUTS = 63          # range cut points (64 spans)
ITERS = 20

rng = np.random.default_rng(7)
pk = jnp.asarray(rng.integers(0, 1 << 20, N).astype(np.int64))
bk = jnp.asarray(np.sort(rng.integers(0, 1 << 20, M)).astype(np.int64))
cuts = jnp.asarray(np.linspace(0, 1 << 20, N_CUTS).astype(np.int64))


def loop_time(name, step, *args, iters=None):
    """step(i, *args) -> scalar contribution; fori_loop of ITERS.
    Variants are isolated: one Mosaic/compile failure must not abort
    the rest of a rare tunnel window's profile."""
    it = iters or ITERS

    def run(args):
        def body(i, acc):
            return acc + step(i.astype(jnp.int64), *args)
        return jax.lax.fori_loop(0, it, body, jnp.int64(0))
    try:
        f = jax.jit(run)
        _ = int(np.asarray(f(args)))          # compile+warm
        t0 = time.perf_counter()
        _ = int(np.asarray(f(args)))
        dt = (time.perf_counter() - t0) / it
        print(f"{name:44s} {dt*1e3:9.2f} ms/iter {N/dt/1e6:9.1f} Mrows/s",
              flush=True)
        return dt
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:300]}", flush=True)
        import traceback
        traceback.print_exc(limit=3)
        return None


from spark_tpu import kernels
from spark_tpu.expressions import Hash64

# 1. baseline: input perturbation only (subtract from everything else)
loop_time("perturb + sum (baseline)",
          lambda i, p, b: ((p ^ i).sum() & jnp.int64(1)), pk, bk)

# 2. routers: hash bucketing vs range span assignment (searchsorted)
loop_time("hash bucket (Hash64 mix %% n_fine)",
          lambda i, p, b: (Hash64._mix(jnp, p ^ i).astype(jnp.uint64)
                           % jnp.uint64(N_FINE)).astype(jnp.int32)
          .sum().astype(jnp.int64) & jnp.int64(1), pk, bk)
loop_time("range_bucket (searchsorted vs cuts)",
          lambda i, p, b: kernels.range_bucket(jnp, p ^ i, cuts)
          .sum().astype(jnp.int64) & jnp.int64(1), pk, bk)

# 3. the routing sort: 1-key (hash path) vs 3-key tie sort (range path:
# pid + null_flag + encoded key -> per-span SORTED runs, one device sort)
loop_time("argsort 1 key (span id)",
          lambda i, p, b: kernels.multi_key_argsort(
              jnp, [kernels.range_bucket(jnp, p ^ i, cuts)], N)[0]
          .astype(jnp.int64) & jnp.int64(1), pk, bk)
loop_time("argsort 3 keys (span,flag,key tie sort)",
          lambda i, p, b: kernels.multi_key_argsort(
              jnp, [kernels.range_bucket(jnp, p ^ i, cuts),
                    (p & jnp.int64(1)).astype(jnp.int8), p ^ i], N)[0]
          .astype(jnp.int64) & jnp.int64(1), pk, bk)

# 4. the build-side sort PMergeJoin SKIPS (presorted runs merge on host):
# what the hash join pays per local join to order its build side
loop_time("build argsort 2 keys (what merge skips)",
          lambda i, p, b: kernels.multi_key_argsort(
              jnp, [(b & jnp.int64(1)).astype(jnp.int8), b ^ i], M)[0]
          .astype(jnp.int64) & jnp.int64(1), pk, bk, iters=ITERS)

# 5. shared local-join core: probe searchsorted + first-match gather
def probe_step(i, p, b):
    lo = kernels.searchsorted(jnp, b, p + i, side="left")
    return lo.sum().astype(jnp.int64) & jnp.int64(1)

loop_time("probe searchsorted (sorted build)", probe_step, pk, bk)
loop_time("output gather (take rows)",
          lambda i, p, b: p[jnp.clip(p ^ i, 0, N - 1) % N]
          .sum() & jnp.int64(1), pk, bk)
print("done")
