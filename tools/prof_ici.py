"""Profile the ICI device-exchange tier's pieces inside a TPU window
(bench.py schedules this as a window probe next to prof_join.py; falls
back to whatever backend jax gives).

Three groups, each isolated so one Mosaic/compile failure cannot abort
the rest of a rare window's profile:

1. the collective primitives over the exchange axis at pack-plane
   shapes — ``lax.all_to_all`` (the portable path) vs the Pallas
   ``make_async_remote_copy`` direct all-to-all (the TPU path), so a
   window tells us what the remote-DMA kernel actually buys over XLA's
   collective at each buffer size;
2. the end-to-end ``local_device_exchange`` (pack → stage-cached
   collective → unpack) in host wall-clock MB/s — the figure the
   distici bench lane's forced-CPU mesh approximates and a window
   makes real;
3. the host wire plane (encode + decode of identical outboxes) as the
   DCN-tier baseline the device tier is meant to beat.

Multi-device on a single host: the collective crosses the chips' ICI
links even though every participant is one process — exactly the
intra-pod data plane, minus process boundaries.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import spark_tpu  # noqa
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), "backend:", jax.default_backend())

DEVS = jax.local_devices()
N_M = min(4, len(DEVS))
ITERS = 20

if N_M < 2:
    print(f"only {len(DEVS)} device(s): the exchange collective needs "
          "2+; nothing to profile")
    sys.exit(0)

from jax.sharding import PartitionSpec
from spark_tpu import types as T
from spark_tpu import wire
from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.parallel import ici
from spark_tpu.parallel.mesh import Mesh

mesh = Mesh(np.asarray(DEVS[:N_M]), (ici.ICI_AXIS,))
sharding = jax.sharding.NamedSharding(mesh, PartitionSpec(ici.ICI_AXIS))
rng = np.random.default_rng(7)


def coll_time(name, use_pallas, rows):
    """One packed data plane ((n_m*n_m, rows) int64, device i holding
    its (n_m, rows) outbound block), ITERS exchanges inside a fori_loop
    with a carried perturbation, one scalar fetch."""
    import inspect
    try:
        sm = ici._shard_map()
        ck = ("check_vma" if "check_vma"
              in inspect.signature(sm).parameters else "check_rep")
        step = ici._a2a_arrays_traceable(N_M, use_pallas)

        def body(x):
            def it(i, carry):
                moved, = step(carry + i)
                return moved
            return jax.lax.fori_loop(0, ITERS, it, x)[0, 0]

        fn = jax.jit(sm(body, mesh=mesh, in_specs=PartitionSpec(ici.ICI_AXIS),
                        out_specs=PartitionSpec(), **{ck: False}))
        x = jax.device_put(
            rng.integers(-99, 99, (N_M * N_M, rows)).astype(np.int64),
            sharding)
        _ = int(np.asarray(fn(x)))            # compile+warm
        t0 = time.perf_counter()
        _ = int(np.asarray(fn(x)))
        dt = (time.perf_counter() - t0) / ITERS
        mb = N_M * N_M * rows * 8 / 1e6
        print(f"{name:44s} {dt*1e3:9.3f} ms/iter {mb/dt/1e3:9.2f} GB/s",
              flush=True)
        return dt
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:300]}", flush=True)
        import traceback
        traceback.print_exc(limit=3)
        return None


ON_TPU = any("TPU" in str(getattr(d, "device_kind", ""))
             for d in mesh.devices.flat)

# 1. the collective at the pack-plane sizes the exchange actually ships
for rows in (1 << 10, 1 << 14, 1 << 18):
    coll_time(f"lax.all_to_all  rows/peer={rows}", False, rows)
    if ON_TPU:
        coll_time(f"pallas remote-DMA a2a rows/peer={rows}", True, rows)
    else:
        print(f"{'pallas remote-DMA a2a rows/peer=' + str(rows):44s} "
              "SKIPPED (no TPU)", flush=True)


# 2/3. end-to-end exchange vs the host wire plane on identical outboxes
def batch(m):
    vals = rng.integers(-(1 << 40), 1 << 40, m)
    return ColumnBatch(["k"], [ColumnVector(vals, T.LongType(), None,
                                            None)], None, m)


for per in (1 << 12, 1 << 15):
    outboxes = [{r: [batch(per)] for r in range(N_M)}
                for _s in range(N_M)]
    tpl = batch(1)
    total = sum(wire.raw_nbytes(bs) for ob in outboxes
                for bs in ob.values())
    try:
        ici.local_device_exchange(outboxes, tpl)          # warm
        t0 = time.perf_counter()
        for _ in range(max(3, ITERS // 4)):
            ici.local_device_exchange(outboxes, tpl)
        dt = (time.perf_counter() - t0) / max(3, ITERS // 4)
        print(f"{'local_device_exchange rows/span=' + str(per):44s} "
              f"{dt*1e3:9.2f} ms/iter {total/dt/1e6:9.1f} MB/s",
              flush=True)
    except Exception as e:
        print(f"{'local_device_exchange rows/span=' + str(per):44s} "
              f"FAILED: {str(e)[:300]}", flush=True)
    try:
        t0 = time.perf_counter()
        for _ in range(max(3, ITERS // 4)):
            for ob in outboxes:
                for bs in ob.values():
                    wire.decode_batches(wire.encode_batches(bs))
        dt = (time.perf_counter() - t0) / max(3, ITERS // 4)
        print(f"{'wire encode+decode rows/span=' + str(per):44s} "
              f"{dt*1e3:9.2f} ms/iter {total/dt/1e6:9.1f} MB/s",
              flush=True)
    except Exception as e:
        print(f"{'wire encode+decode rows/span=' + str(per):44s} "
              f"FAILED: {str(e)[:300]}", flush=True)

print("done")
