"""Bisect the q3 remote-compile HTTP 500 on real TPU hardware.

Three layers, coarsest first, so even a short tunnel window produces a
verdict:

1. PRIMITIVES — each join building block compiled alone (sorts of every
   arity the engine emits, searchsorted in both lowerings, i64 cumsum,
   gathers, scatters).  The round-5 off-hardware analysis found exactly
   one structural feature unique to the q3 program vs the TPU-compiling
   agg/sort programs: ``stablehlo.while`` from jnp.searchsorted's default
   binary-search scan.  primitives[searchsorted_scan] failing while
   [searchsorted_unrolled] compiles would confirm it in one step.
2. STAGES — the planner's q3 program cut after join / +filter / +agg /
   full, compiled with the engine default (unrolled on TPU since r5).
3. STAGES x scan — the same stages with SPARK_TPU_SEARCHSORTED=scan
   forcing the historical while-loop form, to reproduce the original
   crash for the record.

Run only when the tunnel is up (bench.py runs this automatically after a
successful TPU bench).
"""
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import spark_tpu  # noqa: F401  (enables x64, pins platform handling)
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), flush=True)

C = 1 << 21
D = 2048


def try_compile(name, fn, *args):
    t0 = time.perf_counter()
    try:
        jax.jit(fn).lower(*args).compile()
        print(f"[OK]   {name}: {time.perf_counter() - t0:.1f}s", flush=True)
        return True
    except Exception as e:
        print(f"[FAIL] {name} after {time.perf_counter() - t0:.1f}s: "
              f"{str(e)[:400]}", flush=True)
        traceback.print_exc(limit=2)
        return False


# ---------------------------------------------------------------- layer 1
print("\n=== layer 1: primitives ===", flush=True)
rng = np.random.default_rng(3)
big_i64 = jnp.asarray(rng.integers(0, D, C).astype(np.int64))
small_i64 = jnp.asarray(np.sort(rng.integers(0, D, D)).astype(np.int64))
flags_i8 = jnp.asarray((rng.integers(0, 2, D)).astype(np.int8))

try_compile("sort1_i64", lambda x: jax.lax.sort(x), big_i64)
try_compile("sort2_i8_i64_iota",
            lambda f, k: jax.lax.sort(
                (f, k, jnp.arange(D, dtype=np.int32)), num_keys=2,
                is_stable=True)[-1], flags_i8, small_i64)
try_compile("sort3_i64x2_iota",
            lambda k: jax.lax.sort(
                (k, k + 1, jnp.arange(C, dtype=np.int32)), num_keys=2,
                is_stable=True)[-1], big_i64)
try_compile("searchsorted_scan (while loop)",
            lambda a, v: jnp.searchsorted(a, v, method="scan"),
            small_i64, big_i64)
try_compile("searchsorted_unrolled",
            lambda a, v: jnp.searchsorted(a, v, method="scan_unrolled"),
            small_i64, big_i64)
try_compile("searchsorted_scan_big_target",
            lambda a, v: jnp.searchsorted(a, v, method="scan"),
            big_i64, jnp.arange(C, dtype=np.int64))
try_compile("cumsum_i64", lambda x: jnp.cumsum(x), big_i64)
try_compile("gather_i64",
            lambda x, i: x[jnp.clip(i, 0, C - 1)], big_i64, big_i64)
try_compile("scatter_add_i64",
            lambda x, i: jnp.zeros(D, np.int64).at[
                jnp.clip(i, 0, D - 1)].add(x), big_i64, big_i64)

# ---------------------------------------------------------------- layer 2+3
from spark_tpu.sql import functions as F
from spark_tpu.sql import physical as P
from spark_tpu.sql.planner import QueryExecution

J_FACT, J_DIM, J_BRANDS = 1 << 21, 2048, 64
rng = np.random.default_rng(11)
spark = spark_tpu.sql.session.SparkSession.builder.getOrCreate()
fact = spark.createDataFrame({
    "sk": rng.integers(0, J_DIM, J_FACT).astype(np.int64),
    "price": rng.integers(1, 1000, J_FACT).astype(np.int64)})
dim = spark.createDataFrame({
    "d_sk": np.arange(J_DIM, dtype=np.int64),
    "brand": rng.integers(0, J_BRANDS, J_DIM).astype(np.int64),
    "year": rng.integers(1998, 2003, J_DIM).astype(np.int64)})

stages = {
    "join": lambda: fact.join(dim, fact["sk"] == dim["d_sk"]),
    "join+filter": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000),
    "join+filter+agg": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000)
        .groupBy("brand").agg(F.sum("price").alias("rev")),
    "full_q3": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000)
        .groupBy("brand").agg(F.sum("price").alias("rev"))
        .orderBy(F.col("rev").desc()),
}


def compile_stage(name, build):
    q = build()
    pq = QueryExecution(spark, q._plan).planned
    physical = pq.physical

    def run(leaves):
        ctx = P.ExecContext(jnp, list(leaves))
        out = physical.run(ctx)
        return out.vectors[0].data, out.num_rows()

    return try_compile(name, run, tuple(b.to_device() for b in pq.leaves))


print("\n=== layer 2: planner stages (engine-default searchsorted) ===",
      flush=True)
spark._jit_cache.clear()
for name, build in stages.items():
    compile_stage(name, build)

print("\n=== layer 3: planner stages with the historical while-loop "
      "searchsorted (expected to reproduce the HTTP 500) ===", flush=True)
os.environ["SPARK_TPU_SEARCHSORTED"] = "scan"
spark._jit_cache.clear()
for name, build in stages.items():
    compile_stage(name + " [scan]", build)
os.environ.pop("SPARK_TPU_SEARCHSORTED", None)
print("bisect done", flush=True)
