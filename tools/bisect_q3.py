"""Bisect the q3 remote-compile HTTP 500: compile the q3 program piece
by piece on the TPU and report the first stage that fails.  Run only
when the tunnel is up."""
import sys, time, traceback
sys.path.insert(0, "/root/repo")
import numpy as np
import spark_tpu  # noqa
import jax
import jax.numpy as jnp

print("devices:", jax.devices())

from spark_tpu.sql.session import SparkSession
from spark_tpu.sql import functions as F
from spark_tpu.sql import physical as P
from spark_tpu.sql.planner import QueryExecution

J_FACT, J_DIM, J_BRANDS = 1 << 21, 2048, 64
rng = np.random.default_rng(11)
spark = SparkSession.builder.getOrCreate()
fact = spark.createDataFrame({
    "sk": rng.integers(0, J_DIM, J_FACT).astype(np.int64),
    "price": rng.integers(1, 1000, J_FACT).astype(np.int64)})
dim = spark.createDataFrame({
    "d_sk": np.arange(J_DIM, dtype=np.int64),
    "brand": rng.integers(0, J_BRANDS, J_DIM).astype(np.int64),
    "year": rng.integers(1998, 2003, J_DIM).astype(np.int64)})

stages = {
    "join": lambda: fact.join(dim, fact["sk"] == dim["d_sk"]),
    "join+filter": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000),
    "join+filter+agg": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000)
        .groupBy("brand").agg(F.sum("price").alias("rev")),
    "full_q3": lambda: fact.join(dim, fact["sk"] == dim["d_sk"])
        .filter(dim["year"] == 2000)
        .groupBy("brand").agg(F.sum("price").alias("rev"))
        .orderBy(F.col("rev").desc()),
}

for name, build in stages.items():
    q = build()
    pq = QueryExecution(spark, q._plan).planned
    physical = pq.physical

    def run(leaves):
        ctx = P.ExecContext(jnp, list(leaves))
        out = physical.run(ctx)
        return out.vectors[0].data, out.num_rows()

    t0 = time.perf_counter()
    try:
        lowered = jax.jit(run).lower(tuple(b.to_device() for b in pq.leaves))
        compiled = lowered.compile()
        print(f"[OK]   {name}: compiled in {time.perf_counter()-t0:.1f}s")
    except Exception as e:
        print(f"[FAIL] {name} after {time.perf_counter()-t0:.1f}s: "
              f"{str(e)[:500]}")
        traceback.print_exc(limit=3)
        # keep going: later stages may fail differently / identically
print("bisect done")
