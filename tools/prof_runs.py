"""Profile the run-plane device lane inside a TPU window (bench.py
schedules this as a window probe next to prof_ici.py; falls back to
whatever backend jax gives).

Three groups, each isolated so one compile failure cannot abort the
rest of a rare window's profile:

1. plane expansion: the shape-stable searchsorted-gather
   (``run_expand``, the jit-lane form an untaught operator triggers)
   vs ``jnp.repeat(total_repeat_length=...)`` (the ``to_device`` form)
   vs the counted host ``np.repeat`` baseline — the figure that says
   what an in-trace expansion costs when a stage is NOT fully taught;
2. the keyless plane aggregate (segment-sum of a row mask over
   ``run_row_ids``, then values × live-counts — no arithmetic on
   expanded rows) vs the same masked sum over the expanded dense
   column, at plane shapes the distrle bench ships;
3. the stage lane end to end: an eligible filter+aggregate SQL query
   over a run leaf with ``spark.tpu.stage.runPlanes`` on vs off —
   the single-process twin of the distrleplane bench pair.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import spark_tpu  # noqa
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), "backend:", jax.default_backend())

ITERS = 20
CAP = 1 << 18
rng = np.random.default_rng(11)

from spark_tpu import kernels as K
from spark_tpu import types as T
from spark_tpu.columnar import (ColumnBatch, ColumnVector, RunColumnVector,
                                PlaneColumnVector, pad_capacity)


def timed(name, fn, *args):
    """Compile+warm once, then ITERS dispatches with one scalar fetch."""
    try:
        _ = jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _i in range(ITERS):
            out = fn(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / ITERS
        print(f"{name:44s} {dt*1e3:9.3f} ms/iter", flush=True)
        return dt
    except Exception as e:
        print(f"{name:44s} FAILED: {str(e)[:300]}", flush=True)
        import traceback
        traceback.print_exc(limit=3)
        return None


def plane(n_runs):
    """A full-capacity plane: n_runs values, equal lengths summing to
    CAP, zero-padded to the pad_capacity bucket."""
    pc = pad_capacity(n_runs)
    vals = np.zeros(pc, np.int64)
    vals[:n_runs] = rng.integers(0, 1 << 20, n_runs)
    lens = np.zeros(pc, np.int64)
    lens[:n_runs] = CAP // n_runs
    return jnp.asarray(vals), jnp.asarray(lens)


# 1. expansion forms at run counts the distrle shape actually ships
for n_runs in (256, 4096):
    pv, pl = plane(n_runs)

    @jax.jit
    def gather_expand(v, l):
        return K.run_expand(jnp, v, l, CAP)

    @jax.jit
    def repeat_expand(v, l):
        return jnp.repeat(v, l, total_repeat_length=CAP)

    timed(f"searchsorted-gather expand runs={n_runs}", gather_expand, pv, pl)
    timed(f"jnp.repeat expand      runs={n_runs}", repeat_expand, pv, pl)
    hv, hl = np.asarray(pv), np.asarray(pl)
    t0 = time.perf_counter()
    for _i in range(ITERS):
        _ = np.repeat(hv, hl)
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{'host np.repeat expand  runs=' + str(n_runs):44s} "
          f"{dt*1e3:9.3f} ms/iter", flush=True)


# 2. the keyless plane aggregate vs the expanded dense sum, both under
#    a data-dependent row mask (the post-filter shape in the stage lane)
for n_runs in (256, 4096):
    pv, pl = plane(n_runs)
    mask = jnp.asarray(rng.random(CAP) < 0.5)

    @jax.jit
    def plane_sum(v, l, m):
        ids = K.run_row_ids(jnp, l, CAP)
        live = jax.ops.segment_sum(m.astype(jnp.int64), ids,
                                   num_segments=int(v.shape[0]))
        return jnp.sum(v * live), jnp.sum(live)

    @jax.jit
    def dense_sum(v, l, m):
        d = jnp.repeat(v, l, total_repeat_length=CAP)
        return jnp.sum(jnp.where(m, d, 0)), jnp.sum(m.astype(jnp.int64))

    timed(f"plane segsum agg       runs={n_runs}", plane_sum, pv, pl, mask)
    timed(f"expand-then-sum agg    runs={n_runs}", dense_sum, pv, pl, mask)


# 3. the stage lane end to end: runPlanes on vs off over one run leaf
try:
    import spark_tpu.config as C
    from spark_tpu.sql.session import SparkSession
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.dataframe import DataFrame

    N_RUNS, REP = 256, CAP // 256
    heads = np.arange(N_RUNS, dtype=np.int64)
    rv = RunColumnVector(heads, np.full(N_RUNS, REP, np.int64), T.int64)
    vv = ColumnVector(np.arange(CAP, dtype=np.int64) % 7, T.int64)
    leaf = ColumnBatch(["ts", "v"], [rv, vv], None, CAP)
    q = (f"SELECT count(*) AS c, sum(ts) AS st FROM pr_ev "
         f"WHERE ts < {N_RUNS // 2}")

    s = SparkSession.builder.appName("prof_runs").getOrCreate()
    s.conf.set("spark.tpu.mesh.shards", "1")
    DataFrame(s, L.LocalRelation(leaf)).createOrReplaceTempView("pr_ev")
    for mode, on in (("planes-on", "true"), ("planes-off", "false")):
        s.conf.set(C.STAGE_RUN_PLANES.key, on)
        _ = s.sql(q).collect()                        # compile+warm
        t0 = time.perf_counter()
        for _i in range(max(3, ITERS // 4)):
            rows = s.sql(q).collect()
        dt = (time.perf_counter() - t0) / max(3, ITERS // 4)
        print(f"{'stage lane filter+agg ' + mode:44s} {dt*1e3:9.3f} ms/iter"
              f"  (c={rows[0]['c']}, st={rows[0]['st']})", flush=True)
    s.conf.set(C.STAGE_RUN_PLANES.key, "true")
except Exception as e:
    print(f"{'stage lane filter+agg':44s} FAILED: {str(e)[:300]}", flush=True)
    import traceback
    traceback.print_exc(limit=3)

print("done")
