"""Randomized datatype/expression fuzzing over the dual-path oracle.

SURVEY §4's prescription: generate random batches across every scalar
type (with NULLs), build random expression trees, and require the
numpy-interpreted lane and the jit lane to agree bit-for-bit.  Seeds are
fixed per test run id so failures replay.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from spark_tpu import types as T
from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.expressions import (
    Add, And, Between, Cast, Coalesce, Col, EQ, EvalContext, GT, Greatest,
    If, IsNull, LT, Least, Literal, Mod, Mul, Neg, Not, Or, Sub, UnaryMath,
)

N = 257          # deliberately not a multiple of 8/128


def _rand_column(rng, dt, n):
    if isinstance(dt, T.BooleanType):
        data = rng.integers(0, 2, n).astype(bool)
    elif dt.is_integral:
        info = np.iinfo(dt.np_dtype)
        data = rng.integers(info.min // 2, info.max // 2, n,
                            dtype=dt.np_dtype)
    else:
        data = rng.normal(scale=1e3, size=n).astype(dt.np_dtype)
    valid = rng.random(n) > 0.15
    return ColumnVector(data, dt, valid, None)


SCALARS = [T.int8, T.int16, T.int32, T.int64, T.float32, T.float64,
           T.boolean]


def _rand_expr(rng, cols, depth):
    """Random expression over numeric/boolean columns."""
    if depth == 0 or rng.random() < 0.25:
        kind = rng.integers(0, 3)
        if kind == 0:
            return Col(cols[rng.integers(0, len(cols))])
        if kind == 1:
            return Literal(int(rng.integers(-100, 100)))
        return Literal(float(np.round(rng.normal(), 3)))
    ops = [Add, Sub, Mul, lambda a, b: Mod(a, Coalesce(b, Literal(7))),
           lambda a, b: If(GT(a, b), a, b),
           lambda a, b: Coalesce(a, b),
           lambda a, b: Greatest(a, b), lambda a, b: Least(a, b)]
    op = ops[rng.integers(0, len(ops))]
    return op(_rand_expr(rng, cols, depth - 1),
              _rand_expr(rng, cols, depth - 1))


def _rand_pred(rng, cols, depth):
    if depth == 0:
        a = _rand_expr(rng, cols, 1)
        b = _rand_expr(rng, cols, 1)
        return [EQ, LT, GT][rng.integers(0, 3)](a, b)
    ops = [And, Or]
    op = ops[rng.integers(0, 2)]
    left = _rand_pred(rng, cols, depth - 1)
    if rng.random() < 0.3:
        left = Not(left)
    return op(left, _rand_pred(rng, cols, depth - 1))


def _eval_both(batch, expr):
    host = EvalContext(batch, np)
    dev = EvalContext(batch.to_device(), jnp)
    hv = host.broadcast(expr.eval(host))
    dv = dev.broadcast(expr.eval(dev))
    return hv, dv


def _assert_agree(hv, dv, seed_info):
    hd = np.asarray(hv.data)
    dd = np.asarray(dv.data)
    hvalid = np.ones(len(hd), bool) if hv.valid is None \
        else np.asarray(hv.valid)
    dvalid = np.ones(len(dd), bool) if dv.valid is None \
        else np.asarray(dv.valid)
    assert np.array_equal(hvalid, dvalid), f"validity drift ({seed_info})"
    live_h = hd[hvalid]
    live_d = dd[hvalid]
    if live_h.dtype.kind == "f":
        assert np.allclose(live_h, live_d, rtol=1e-9, atol=1e-9,
                           equal_nan=True), f"value drift ({seed_info})"
    else:
        assert np.array_equal(live_h, live_d), f"value drift ({seed_info})"


@pytest.mark.parametrize("seed", range(30))
def test_fuzz_numeric_exprs(seed):
    rng = np.random.default_rng(1000 + seed)
    dts = [SCALARS[i] for i in rng.integers(0, len(SCALARS), 4)]
    names = [f"c{i}" for i in range(4)]
    batch = ColumnBatch(names,
                        [_rand_column(rng, dt, N) for dt in dts],
                        None, N)
    numeric = [n for n, dt in zip(names, dts)
               if not isinstance(dt, T.BooleanType)]
    if not numeric:
        numeric = names[:1]
    expr = _rand_expr(rng, numeric, depth=int(rng.integers(1, 4)))
    hv, dv = _eval_both(batch, expr)
    _assert_agree(hv, dv, f"seed={seed} expr={expr!r}")


@pytest.mark.parametrize("seed", range(15))
def test_fuzz_predicates(seed):
    rng = np.random.default_rng(2000 + seed)
    dts = [SCALARS[i] for i in rng.integers(0, len(SCALARS) - 1, 3)]
    names = [f"c{i}" for i in range(3)]
    batch = ColumnBatch(names,
                        [_rand_column(rng, dt, N) for dt in dts],
                        None, N)
    pred = _rand_pred(rng, names, depth=int(rng.integers(1, 3)))
    hv, dv = _eval_both(batch, pred)
    _assert_agree(hv, dv, f"seed={seed} pred={pred!r}")


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_casts(seed):
    rng = np.random.default_rng(3000 + seed)
    src = SCALARS[rng.integers(0, len(SCALARS))]
    dst = SCALARS[rng.integers(0, len(SCALARS))]
    batch = ColumnBatch(["c"], [_rand_column(rng, src, N)], None, N)
    expr = Cast(Col("c"), dst)
    hv, dv = _eval_both(batch, expr)
    _assert_agree(hv, dv, f"seed={seed} cast {src}->{dst}")


@pytest.mark.parametrize("seed", range(10))
def test_fuzz_groupby_sql(seed):
    """End-to-end: random grouped aggregation, engine vs pandas oracle."""
    import pandas as pd
    from spark_tpu.sql.session import SparkSession
    from spark_tpu.sql import functions as F
    spark = SparkSession.getActiveSession() or SparkSession()
    rng = np.random.default_rng(4000 + seed)
    n = int(rng.integers(50, 800))
    pdf = pd.DataFrame({
        "k": rng.integers(-5, 5, n).astype(np.int64),
        "v": rng.integers(-1000, 1000, n).astype(np.int64),
        "f": rng.normal(size=n)})
    df = spark.createDataFrame(pdf)
    got = {r["k"]: (r["s"], r["c"], r["m"]) for r in
           df.groupBy("k").agg(F.sum("v").alias("s"),
                               F.count("*").alias("c"),
                               F.max("f").alias("m")).collect()}
    exp = pdf.groupby("k").agg(s=("v", "sum"), c=("v", "size"),
                               m=("f", "max"))
    assert set(got) == set(exp.index)
    for k, row in exp.iterrows():
        s, c, m = got[k]
        assert s == row["s"] and c == row["c"]
        assert np.isclose(m, row["m"])
