"""Exact join semantics (`SortMergeJoinExec.scala:36` parity).

Joins must be EXACT, not hash-probabilistic: single-key joins search on
exact value encodings; every candidate pair is verified by value; semi/
anti existence and outer null-extension derive from verified pairs.
"""

import numpy as np
import pandas as pd
import pytest

import spark_tpu.sql.functions as F


I64_MAX = np.iinfo(np.int64).max
I64_MIN = np.iinfo(np.int64).min


def rows(df):
    def key(t):
        return tuple((v is None, 0 if v is None else v) for v in t)
    return sorted((tuple(r) for r in df.collect()), key=key)


def test_extreme_int64_keys(spark):
    """INT64_MAX collides with the null/dead sentinel suffix of the exact
    search path; verification must still produce the exact answer."""
    left = spark.createDataFrame(
        {"k": np.array([I64_MAX, I64_MIN, 0, 7], np.int64),
         "l": np.array([1, 2, 3, 4], np.int64)})
    right = spark.createDataFrame(
        {"k": np.array([I64_MAX, 5, I64_MIN], np.int64),
         "r": np.array([10, 20, 30], np.int64)})
    got = rows(left.join(right, "k"))
    assert got == [(I64_MIN, 2, 30), (I64_MAX, 1, 10)]


def test_negative_zero_normalization_and_nan_as_null(spark):
    """-0.0 == 0.0 on join keys (NormalizeFloatingNumbers contract).
    NaN is NULL in this engine's ingestion semantics (columnar.py NaN→NULL
    by design), so NaN-keyed rows never match — like NULL keys."""
    left = spark.createDataFrame(
        {"k": np.array([np.nan, -0.0, 1.5], np.float64),
         "l": np.array([1, 2, 3], np.int64)})
    right = spark.createDataFrame(
        {"k": np.array([np.nan, 0.0], np.float64),
         "r": np.array([10, 20], np.int64)})
    out = rows(left.join(right, "k").select("l", "r"))
    assert out == [(2, 20)]


def test_string_join_disjoint_dictionaries(spark):
    """Each side dictionary-encodes independently; equality must compare
    word VALUES through the canonical id space, not codes."""
    left = spark.createDataFrame(
        [("zebra", 1), ("apple", 2), ("mango", 3)], ["k", "l"])
    right = spark.createDataFrame(
        [("apple", 10), ("zebra", 20), ("kiwi", 30)], ["k", "r"])
    got = rows(left.join(right, "k").select("k", "l", "r"))
    assert got == [("apple", 2, 10), ("zebra", 1, 20)]


def test_null_keys_never_match(spark):
    left = spark.createDataFrame([(None, 1), (5, 2)], ["k", "l"])
    right = spark.createDataFrame([(None, 10), (5, 20)], ["k", "r"])
    assert rows(left.join(right, "k").select("l", "r")) == [(2, 20)]
    # left outer: null-key row null-extends
    got = rows(left.join(right, "k", "left").select("l", "r"))
    assert got == [(1, None), (2, 20)]
    # semi/anti exact
    assert rows(left.join(right, "k", "left_semi").select("l")) == [(2,)]
    assert rows(left.join(right, "k", "left_anti").select("l")) == [(1,)]


def test_semi_anti_with_duplicate_build_keys(spark):
    """The old dup-range shortcut trusted hashA alone when the build range
    had duplicates; existence must come from verified pairs."""
    left = spark.createDataFrame(
        {"k": np.array([1, 2, 3], np.int64), "l": np.array([1, 2, 3], np.int64)})
    right = spark.createDataFrame(
        {"k": np.array([2, 2, 2, 9, 9], np.int64),
         "r": np.arange(5, dtype=np.int64)})
    assert rows(left.join(right, "k", "left_semi").select("l")) == [(2,)]
    assert rows(left.join(right, "k", "left_anti").select("l")) == [(1,), (3,)]


@pytest.mark.parametrize("how", ["inner", "left", "right", "full"])
def test_property_vs_pandas(spark, how):
    rng = np.random.default_rng(hash(how) % 2**31)
    n, m = 300, 200
    lk = rng.integers(0, 50, n).astype(np.int64)
    rk = rng.integers(25, 75, m).astype(np.int64)
    lv = rng.integers(0, 1000, n).astype(np.int64)
    rv = rng.integers(0, 1000, m).astype(np.int64)
    left = spark.createDataFrame({"k": lk, "l": lv})
    right = spark.createDataFrame({"k2": rk, "r": rv})
    got = rows(left.join(right, left["k"] == right["k2"], how)
               .select("l", "r"))
    pdf = pd.DataFrame({"k": lk, "l": lv}).merge(
        pd.DataFrame({"k": rk, "r": rv}), on="k",
        how={"inner": "inner", "left": "left", "right": "right",
             "full": "outer"}[how])
    def key(t):
        return tuple((v is None, 0 if v is None else v) for v in t)
    exp = sorted(((None if pd.isna(a) else int(a),
                   None if pd.isna(b) else int(b))
                  for a, b in zip(pdf["l"], pdf["r"])), key=key)
    assert got == exp


def test_property_multi_key_vs_pandas(spark):
    rng = np.random.default_rng(99)
    n, m = 250, 250
    lk1 = rng.integers(0, 10, n).astype(np.int64)
    lk2 = rng.integers(0, 10, n).astype(np.int64)
    rk1 = rng.integers(0, 10, m).astype(np.int64)
    rk2 = rng.integers(0, 10, m).astype(np.int64)
    lv = np.arange(n, dtype=np.int64)
    rv = np.arange(m, dtype=np.int64)
    left = spark.createDataFrame({"a": lk1, "b": lk2, "l": lv})
    right = spark.createDataFrame({"c": rk1, "d": rk2, "r": rv})
    cond = (left["a"] == right["c"]) & (left["b"] == right["d"])
    got = rows(left.join(right, cond).select("l", "r"))
    pdf = pd.DataFrame({"k1": lk1, "k2": lk2, "l": lv}).merge(
        pd.DataFrame({"k1": rk1, "k2": rk2, "r": rv}), on=["k1", "k2"])
    exp = sorted((int(a), int(b)) for a, b in zip(pdf["l"], pdf["r"]))
    assert got == exp


def test_dist_join_exact_matches_local(spark):
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rng = np.random.default_rng(7)
    n = 2000
    lk = rng.integers(0, 100, n).astype(np.int64)
    rk = rng.integers(50, 150, n).astype(np.int64)
    left_d = {"k": lk, "l": np.arange(n, dtype=np.int64)}
    right_d = {"k2": rk, "r": np.arange(n, dtype=np.int64)}

    def run():
        left = spark.createDataFrame(left_d)
        right = spark.createDataFrame(right_d)
        return rows(left.join(right, left["k"] == right["k2"], "left")
                    .select("l", "r"))

    spark.conf.set("spark.tpu.mesh.shards", "8")
    try:
        got = run()
    finally:
        spark.conf.set("spark.tpu.mesh.shards", "1")
    assert got == run()


def test_residual_condition_in_semi_anti(spark):
    """Non-equi ON conjuncts are part of the MATCH condition: semi/anti
    existence must respect them, not just the equi keys."""
    left = spark.createDataFrame([(1, 5), (2, 50)], ["k", "v"])
    right = spark.createDataFrame([(1, 10), (2, 10)], ["k2", "w"])
    cond = (left["k"] == right["k2"]) & (left["v"] < right["w"])
    assert rows(left.join(right, cond, "left_semi").select("k")) == [(1,)]
    assert rows(left.join(right, cond, "left_anti").select("k")) == [(2,)]


def test_residual_condition_null_extends_outer(spark):
    """A probe row whose only equi-match fails the residual is UNMATCHED:
    it must appear null-extended in a left join, not be dropped."""
    left = spark.createDataFrame([(1, 5), (2, 50)], ["k", "v"])
    right = spark.createDataFrame([(1, 10), (2, 10)], ["k2", "w"])
    cond = (left["k"] == right["k2"]) & (left["v"] < right["w"])
    got = rows(left.join(right, cond, "left").select("k", "v", "w"))
    assert got == [(1, 5, 10), (2, 50, None)]
    # full outer: the refused build row appears null-extended too
    got_full = rows(left.join(right, cond, "full").select("k", "v", "k2", "w"))
    assert got_full == [(1, 5, 1, 10), (2, 50, None, None),
                        (None, None, 2, 10)]


def test_multikey_join_mixed_int_float_keys(spark):
    """int64=float64 key pairs must match cross-typed values (review find:
    the combined hash hashed raw bits per side, dropping every match)."""
    import numpy as np
    import pandas as pd
    a = spark.createDataFrame(pd.DataFrame({
        "k1": np.array([1, 2, 3], np.int64),
        "k2": np.array([10, 20, 30], np.int64)}))
    b = spark.createDataFrame(pd.DataFrame({
        "j1": np.array([1.0, 2.0, 9.0], np.float64),
        "j2": np.array([10.0, 20.0, 90.0], np.float64),
        "v": np.array([100, 200, 900], np.int64)}))
    a.createOrReplaceTempView("mixa")
    b.createOrReplaceTempView("mixb")
    rows = spark.sql(
        "SELECT k1, v FROM mixa JOIN mixb ON k1 = j1 AND k2 = j2 "
        "ORDER BY k1").collect()
    assert [(r["k1"], r["v"]) for r in rows] == [(1, 100), (2, 200)]


def test_literal_equality_is_filter_not_join_key(spark):
    """`col = -7` in an ON clause is a filter conjunct; it must not become
    a constant 'join key' (review find via TPC-DS q91)."""
    import numpy as np
    import pandas as pd
    a = spark.createDataFrame(pd.DataFrame({"x": np.arange(4, dtype=np.int64)}))
    b = spark.createDataFrame(pd.DataFrame({
        "y": np.arange(4, dtype=np.int64),
        "g": np.array([-7.0, -7.0, -5.0, -5.0])}))
    a.createOrReplaceTempView("lita")
    b.createOrReplaceTempView("litb")
    rows = spark.sql(
        "SELECT x FROM lita JOIN litb ON x = y AND g = -7 ORDER BY x"
    ).collect()
    assert [r["x"] for r in rows] == [0, 1]
