"""HBM memory accounting, codecs, and the device cache manager.

Reference behaviors pinned: UnifiedMemoryManager's storage-eviction-for-
execution contract, CacheManager's cached-subtree substitution, and the
compressed-cache demotion ladder.
"""
import numpy as np
import pytest

from spark_tpu import codec as codec_mod
from spark_tpu import config as C
from spark_tpu.columnar import ColumnBatch
from spark_tpu.memory import (
    DeviceCacheManager, HBMOutOfMemoryError, MemoryManager, StorageLevel,
    batch_nbytes,
)
from spark_tpu.sql.session import SparkSession


# ---------------------------------------------------------------- codecs

@pytest.mark.parametrize("name", ["none", "zlib", "lzma", "bz2"])
def test_byte_codec_roundtrip(name):
    data = np.random.default_rng(0).integers(0, 5, 10000).astype(
        np.int64).tobytes()
    packed = codec_mod.compress(data, name)
    assert codec_mod.decompress(packed, name) == data


def test_rle_encoding_picked_for_runs():
    arr = np.repeat(np.arange(20, dtype=np.int64), 500)
    enc = codec_mod.encode_column(arr)
    assert enc.scheme == "rle"
    assert enc.nbytes < arr.nbytes // 10
    assert np.array_equal(codec_mod.decode_column(enc), arr)


def test_low_cardinality_compresses_well():
    rng = np.random.default_rng(1)
    arr = rng.choice(np.array([7, 99, 123456789], np.int64), 5000)
    enc = codec_mod.encode_column(arr)
    assert enc.nbytes < arr.nbytes // 3   # dict, rle, or codec — must shrink
    assert np.array_equal(codec_mod.decode_column(enc), arr)


def test_dict_encoding_roundtrip():
    rng = np.random.default_rng(4)
    arr = rng.choice(np.array([7, 99, 123456789], np.int64), 5000)
    forced = codec_mod.EncodedColumn(
        "dict", arr.dtype, len(arr), (
            np.searchsorted(np.unique(arr), arr).astype(np.uint16),
            np.unique(arr)))
    assert np.array_equal(codec_mod.decode_column(forced), arr)


def test_float_column_falls_back_to_codec():
    arr = np.random.default_rng(2).normal(size=3000)
    enc = codec_mod.encode_column(arr)
    assert np.array_equal(codec_mod.decode_column(enc), arr)


# ---------------------------------------------------------------- manager

def _conf(budget, frac=0.3):
    conf = C.Conf()
    conf.set("spark.tpu.memory.hbmBudget", str(budget))
    conf.set("spark.tpu.memory.storageFraction", str(frac))
    return conf


def test_execution_reservation_and_oom():
    mm = MemoryManager(_conf(1000))
    mm.acquire_execution("q1", 600)
    with pytest.raises(HBMOutOfMemoryError):
        mm.acquire_execution("q2", 600)
    mm.release_execution("q1")
    mm.acquire_execution("q2", 600)


def test_execution_evicts_storage_to_floor():
    mm = MemoryManager(_conf(1000, frac=0.2))
    released = {}

    def evict(n):
        released["n"] = n
        mm.release_storage("blk")
        return 500

    mm.set_eviction_callback(evict)
    assert mm.try_acquire_storage("blk", 500)
    mm.acquire_execution("q", 800)          # needs 300 of storage's 500
    assert released["n"] >= 300
    assert mm.execution_used == 800


def _batch(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnBatch.from_arrays({
        "a": rng.integers(0, 10, n).astype(np.int64),
        "b": rng.normal(size=n),
    })


def test_cache_put_get_roundtrip_device():
    conf = _conf(1 << 30)
    mm = MemoryManager(conf)
    cm = DeviceCacheManager(mm, conf)
    b = _batch()
    cm.put("k", b)
    got = cm.get("k")
    assert got is not None
    assert np.array_equal(np.asarray(got.vectors[0].data),
                          np.asarray(b.vectors[0].data))
    assert mm.storage_used == batch_nbytes(b)
    cm.remove("k")
    assert mm.storage_used == 0


def test_cache_demotes_under_pressure_and_stays_correct():
    b = _batch(2000, seed=3)
    conf = _conf(batch_nbytes(b) + 200, frac=0.0)  # one batch fits
    mm = MemoryManager(conf)
    cm = DeviceCacheManager(mm, conf)
    cm.put("k1", b)
    assert cm.entries()[0]["level"] == StorageLevel.DEVICE
    # execution demand forces demotion to HOST_COMPRESSED
    mm.acquire_execution("q", batch_nbytes(b))
    levels = {e["key"]: e["level"] for e in cm.entries()}
    assert levels["k1"] == StorageLevel.HOST_COMPRESSED
    got = cm.get("k1")     # decompress serves the read
    assert np.array_equal(np.asarray(got.vectors[0].data),
                          np.asarray(b.vectors[0].data))
    assert np.allclose(np.asarray(got.vectors[1].data),
                       np.asarray(b.vectors[1].data))


# ------------------------------------------------------- end-to-end cache

def test_dataframe_cache_substitution_across_dataframes():
    spark = SparkSession()          # fresh session: isolated cache/config
    import pandas as pd  # noqa: F401  (ensures arrow stack present)
    rng = np.random.default_rng(5)
    df = spark.createDataFrame({
        "k": rng.integers(0, 4, 500).astype(np.int64),
        "v": rng.integers(0, 100, 500).astype(np.int64)})
    from spark_tpu.sql import functions as F
    agg = df.groupBy("k").agg(F.sum("v").alias("s"))
    agg.cache()
    assert spark.cacheManager.entries()
    # PROVE substitution happens: poison the cached entry with a marker
    # batch — an equivalent NEW DataFrame must return the marker, which
    # recomputation could never produce
    key = spark.cacheManager.entries()[0]["key"]
    marker = spark.createDataFrame({
        "k": np.array([111, 222], np.int64),
        "s": np.array([1, 2], np.int64)})._execute()
    spark.cacheManager.put(key, marker)
    agg2 = df.groupBy("k").agg(F.sum("v").alias("s"))
    rows2 = sorted((r["k"], r["s"]) for r in agg2.collect())
    assert rows2 == [(111, 1), (222, 2)]
    spark.cacheManager.remove(key)
    # recompute (cache cleared) returns the true aggregation
    rows3 = {r["k"]: r["s"] for r in agg2.collect()}
    expect = {}
    for k, v in zip(np.asarray(df._execute().vectors[0].data),
                    np.asarray(df._execute().vectors[1].data)):
        expect[int(k)] = expect.get(int(k), 0) + int(v)
    assert rows3 == expect
    agg.unpersist()
    assert not spark.cacheManager.entries()


def test_cached_result_feeds_downstream_query():
    spark = SparkSession()          # fresh session: isolated cache/config
    df = spark.createDataFrame({"x": np.arange(100, dtype=np.int64)})
    doubled = df.selectExpr("x * 2 as y").cache()
    from spark_tpu.sql import functions as F
    total = doubled.agg(F.sum("y").alias("t")).collect()[0]["t"]
    assert total == 2 * sum(range(100))
    doubled.unpersist()


def test_join_output_preflight_enforced(spark):
    """r2 weak #5: the reservation now pre-flights the join's STATIC
    output buffer, so a join that cannot fit the budget raises
    HBMOutOfMemoryError BEFORE dispatch — never an XLA allocator crash."""
    import pandas as pd
    from spark_tpu.sql import functions as F
    n = 4096
    left = spark.createDataFrame(pd.DataFrame({
        "k": np.arange(n, dtype=np.int64) % 64,
        "a": np.arange(n, dtype=np.int64)}))
    right = spark.createDataFrame(pd.DataFrame({
        "k2": np.arange(n, dtype=np.int64) % 64,
        "b": np.arange(n, dtype=np.int64)}))
    df = left.join(right, on=F.col("k") == F.col("k2"))
    q = df.agg(F.count("a"))
    old_budget = spark._memory.budget
    try:
        spark._memory.budget = 200_000     # far below the join buffer
        with pytest.raises(HBMOutOfMemoryError, match="query:"):
            q.collect()
    finally:
        spark._memory.budget = old_budget
    (cnt,), = q.collect()                  # restored budget: runs fine
    assert cnt == n * (n // 64)


def test_preflight_estimates_join_buffer(spark):
    """The reservation grows with the planned join output capacity."""
    import pandas as pd
    from spark_tpu.sql import functions as F
    from spark_tpu.sql.planner import QueryExecution, _plan_reserve_bytes
    n = 2048
    left = spark.createDataFrame(pd.DataFrame({
        "k": np.arange(n, dtype=np.int64)}))
    right = spark.createDataFrame(pd.DataFrame({
        "k2": np.arange(n, dtype=np.int64)}))
    plain = QueryExecution(
        spark, left.filter(F.col("k") >= 0)._plan).planned
    joined = QueryExecution(
        spark, left.join(right, on=F.col("k") == F.col("k2"))._plan).planned
    assert _plan_reserve_bytes(joined) > 1.5 * _plan_reserve_bytes(plain)
