"""Datasource IO round-trips (datasources/{parquet,csv,json,text} analog)."""

import os

import numpy as np
import pytest

from spark_tpu.expressions import AnalysisException


def rows(df):
    return sorted((tuple(r) for r in df.collect()),
                  key=lambda t: tuple(str(x) for x in t))


@pytest.fixture()
def sample(spark):
    return spark.createDataFrame({
        "id": np.arange(6, dtype=np.int64),
        "grp": ["a", "b", "a", "c", "b", "a"],
        "x": np.array([0.5, 1.5, 2.5, 3.5, 4.5, 5.5], np.float64),
    })


def test_parquet_roundtrip(spark, sample, tmp_path):
    p = str(tmp_path / "t.parquet")
    sample.write.parquet(p)
    assert os.path.exists(os.path.join(p, "_SUCCESS"))
    back = spark.read.parquet(p)
    assert back.schema.names == ["id", "grp", "x"]
    assert rows(back) == rows(sample)


def test_parquet_overwrite_and_modes(spark, sample, tmp_path):
    p = str(tmp_path / "m.parquet")
    sample.write.parquet(p)
    with pytest.raises(AnalysisException):
        sample.write.parquet(p)
    sample.write.mode("ignore").parquet(p)
    sample.write.mode("overwrite").parquet(p)
    assert len(rows(spark.read.parquet(p))) == 6
    sample.write.mode("append").parquet(p)
    assert len(rows(spark.read.parquet(p))) == 12


def test_csv_roundtrip_header(spark, sample, tmp_path):
    p = str(tmp_path / "t.csv")
    sample.write.option("header", True).csv(p)
    back = spark.read.csv(p, header=True, inferSchema=True)
    assert back.schema.names == ["id", "grp", "x"]
    assert rows(back) == rows(sample)


def test_csv_no_infer_all_strings(spark, sample, tmp_path):
    p = str(tmp_path / "s.csv")
    sample.write.option("header", True).csv(p)
    back = spark.read.csv(p, header=True)
    assert all(dt == "string" for _, dt in back.dtypes)


def test_json_roundtrip(spark, sample, tmp_path):
    p = str(tmp_path / "t.json")
    sample.write.json(p)
    back = spark.read.json(p)
    assert set(back.schema.names) == {"id", "grp", "x"}
    got = rows(back.select("id", "grp", "x"))
    assert got == rows(sample)


def test_text_roundtrip(spark, tmp_path):
    df = spark.createDataFrame({"value": ["hello", "tpu", "world"]})
    p = str(tmp_path / "t.txt")
    df.write.text(p)
    back = spark.read.text(p)
    assert rows(back) == rows(df)


def test_partitioned_write_and_discovery(spark, sample, tmp_path):
    p = str(tmp_path / "part.parquet")
    sample.write.partitionBy("grp").parquet(p)
    assert os.path.isdir(os.path.join(p, "grp=a"))
    back = spark.read.parquet(p)
    assert set(back.schema.names) == {"id", "x", "grp"}
    assert rows(back.select("id", "grp", "x")) == rows(sample)
    # partition pruning via filter works through the normal pipeline
    a = back.filter(back["grp"] == "a")
    assert len(a.collect()) == 3


def test_int_partition_values_inferred(spark, tmp_path):
    df = spark.createDataFrame({"v": [1.0, 2.0, 3.0, 4.0],
                                "year": np.array([2020, 2020, 2021, 2021],
                                                 np.int64)})
    p = str(tmp_path / "byyear")
    df.write.partitionBy("year").parquet(p)
    back = spark.read.parquet(p)
    assert dict(back.dtypes)["year"] == "bigint"
    assert len(back.filter(back["year"] == 2021).collect()) == 2


def test_sql_over_file_relation(spark, sample, tmp_path):
    p = str(tmp_path / "q.parquet")
    sample.write.parquet(p)
    spark.read.parquet(p).createOrReplaceTempView("filetbl")
    out = spark.sql("SELECT grp, count(*) AS c, sum(x) AS s FROM filetbl "
                    "GROUP BY grp ORDER BY grp")
    got = [tuple(r) for r in out.collect()]
    assert got[0][0] == "a" and got[0][1] == 3
    spark.catalog.drop("filetbl")


def test_reader_schema_string(spark):
    r = spark.read.schema("a int, b string")
    assert r._schema.names == ["a", "b"]


def test_nulls_roundtrip(spark, tmp_path):
    df = spark.createDataFrame([(1, "x"), (2, None), (None, "z")], ["a", "b"])
    p = str(tmp_path / "n.parquet")
    df.write.parquet(p)
    back = spark.read.parquet(p)
    assert rows(back) == rows(df)


# ---------------------------------------------------------------------------
# prefetch_iter: the double-buffered scan pipeline
# ---------------------------------------------------------------------------

def test_prefetch_iter_order_and_prep():
    from spark_tpu.io import prefetch_iter
    got = list(prefetch_iter(iter(range(50)), lambda x: x * 2, depth=3))
    assert got == [x * 2 for x in range(50)]


def test_prefetch_iter_depth_zero_synchronous():
    from spark_tpu.io import prefetch_iter
    seen = []

    def gen():
        for i in range(5):
            seen.append(i)
            yield i

    it = prefetch_iter(gen(), None, depth=0)
    assert next(it) == 0
    # synchronous: nothing read ahead of the consumer
    assert seen == [0]
    assert list(it) == [1, 2, 3, 4]


def test_prefetch_iter_exception_propagates():
    from spark_tpu.io import prefetch_iter

    def gen():
        yield 1
        raise ValueError("boom")

    it = prefetch_iter(gen(), None, depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="boom"):
        list(it)


def test_prefetch_iter_prep_exception_propagates():
    from spark_tpu.io import prefetch_iter

    def bad(x):
        if x == 3:
            raise RuntimeError("prep failed")
        return x

    with pytest.raises(RuntimeError, match="prep failed"):
        list(prefetch_iter(iter(range(10)), bad, depth=2))


def test_prefetch_iter_early_break_closes_inner():
    import time
    from spark_tpu.io import prefetch_iter
    closed = []

    def gen():
        try:
            for i in range(10_000):
                yield i
        finally:
            closed.append(True)

    it = prefetch_iter(gen(), None, depth=2)
    for x in it:
        if x >= 3:
            break
    it.close()
    # the worker observes the stop event within its put timeout
    deadline = time.time() + 5
    while not closed and time.time() < deadline:
        time.sleep(0.05)
    assert closed == [True]


def test_prefetch_runs_ahead_bounded():
    import time
    from spark_tpu.io import prefetch_iter

    produced = []

    def gen():
        for i in range(100):
            produced.append(i)
            yield i

    it = prefetch_iter(gen(), None, depth=2)
    first = next(it)
    assert first == 0
    # give the worker time to fill the pipeline, then check the bound:
    # depth in-queue + 1 in-hand + 1 being produced
    deadline = time.time() + 2
    while len(produced) < 3 and time.time() < deadline:
        time.sleep(0.02)
    time.sleep(0.2)
    assert 3 <= len(produced) <= 5
    assert list(it) == list(range(1, 100))


def test_sql_on_file_format_qualified(spark, tmp_path):
    """SELECT ... FROM parquet.`/path` (ResolveSQLOnFile analog)."""
    df = spark.createDataFrame({"a": np.arange(10, dtype=np.int64)})
    p = str(tmp_path / "direct.parquet")
    df.write.parquet(p)
    out = spark.sql(f"SELECT sum(a) AS s FROM parquet.`{p}`").collect()
    assert out[0]["s"] == 45
    with pytest.raises(AnalysisException, match="not found"):
        spark.sql("SELECT 1 FROM parquet.`/no/such/path`").collect()
