"""Multi-tenant serving core: admission control + cross-session plan
cache (serving/admission.py, serving/plancache.py, server.py wiring).

The contract under test: identical (or literal-slotted) statements from
DIFFERENT server sessions share one compiled executable; catalog
mutations and planning-conf changes invalidate affected entries with
oracle-exact results; over-limit submissions fail fast with a structured
429 naming the exhausted limit — never an unbounded queue, never a lost
statement."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from spark_tpu import config as C
from spark_tpu.server import SQLServer
from spark_tpu.serving import (AdmissionController, AdmissionRejected,
                               PlanCache)


@pytest.fixture()
def serve_root(spark, tmp_path):
    """A dedicated root session per test: server-side conf experiments
    (caps, timeouts, warehouse) must not leak into the shared fixture."""
    s = spark.newSession()
    s.conf.set("spark.sql.warehouse.dir", str(tmp_path / "wh"))
    return s


def _req(srv, path, method="GET", body=None, headers=None):
    req = urllib.request.Request(
        f"http://{srv.host}:{srv.port}{path}",
        data=body.encode() if isinstance(body, str) else body,
        method=method, headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, json.loads(r.read())


def _sql(srv, query, sid=None, stmt_id=None):
    body = {"query": query}
    if sid:
        body["session"] = sid
    if stmt_id:
        body["id"] = stmt_id
    return _req(srv, "/sql", "POST", json.dumps(body))[1]


# ---------------------------------------------------------------------------
# plan cache: cross-session sharing + literal slotting
# ---------------------------------------------------------------------------

def test_plan_cache_shared_across_sessions(serve_root):
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s1 = _req(srv, "/session", "POST")
        _, s2 = _req(srv, "/session", "POST")
        q = "SELECT id, id * 2 AS y FROM range(64) ORDER BY id"
        r1 = _sql(srv, q, s1["sessionId"])
        assert r1["cacheHit"] is False
        r2 = _sql(srv, q, s2["sessionId"])
        assert r2["cacheHit"] is True, \
            "session 2 must reuse session 1's compiled plan"
        assert r2["planningSkippedMs"] > 0
        assert r2["rows"] == r1["rows"]
        _, st = _req(srv, "/status")
        assert st["planCache"]["hits"] >= 1
        assert st["planCache"]["entries"] >= 1
        # the gauges ride the session metrics system as a Source
        assert st["metrics"]["serving"]["plan_cache_hits"] >= 1
    finally:
        srv.stop()


def test_literal_variants_share_one_entry(serve_root):
    cache = PlanCache(serve_root.conf_obj)
    s = serve_root.newSession()
    s._plan_cache = cache
    r1 = [tuple(r) for r in
          s.sql("SELECT id FROM range(30) WHERE id < 10").collect()]
    r2 = [tuple(r) for r in
          s.sql("SELECT id FROM range(30) WHERE id < 20").collect()]
    assert len(r1) == 10 and len(r2) == 20
    st = cache.stats()
    # the literal is slotted out of the fingerprint: ONE entry, and the
    # second variant is a hit re-executed with a different parameter
    assert st["entries"] == 1, st
    assert st["hits"] == 1 and st["misses"] == 1, st


def test_plan_cache_invalidation_oracle_exact(serve_root):
    cache = PlanCache(serve_root.conf_obj)
    s1 = serve_root.newSession()
    s2 = serve_root.newSession()
    s1._plan_cache = cache
    s2._plan_cache = cache
    s1.sql("CREATE TABLE pcinv_t AS "
           "SELECT id AS k, id * 3 AS v FROM range(50)")
    q = ("SELECT k % 5 AS g, sum(v) AS sv FROM pcinv_t "
         "WHERE v < 120 GROUP BY k % 5 ORDER BY g")
    a1 = [tuple(r) for r in s1.sql(q).collect()]
    a2 = [tuple(r) for r in s2.sql(q).collect()]
    assert a1 == a2 and cache.stats()["hits"] >= 1

    # INSERT must evict entries scanning the table; the next run over
    # the cache must see the new rows, byte-for-byte vs a fresh session
    s2.sql("INSERT INTO pcinv_t SELECT id AS k, id AS v FROM range(5)")
    assert cache.stats()["invalidations"] >= 1
    a3 = [tuple(r) for r in s1.sql(q).collect()]
    oracle = [tuple(r) for r in serve_root.newSession().sql(q).collect()]
    assert a3 == oracle and a3 != a1

    # a planning-relevant conf change evicts entries built under the
    # old value (the fingerprint's conf component is the backstop)
    before = cache.stats()["invalidations"]
    s1.sql("SET spark.tpu.crossproc.autoBroadcastThreshold=12345")
    assert cache.stats()["invalidations"] > before
    a4 = [tuple(r) for r in s1.sql(q).collect()]
    assert a4 == oracle

    s1.sql("DROP TABLE pcinv_t")
    with pytest.raises(Exception):
        s1.sql(q).collect()


def test_run_codes_conf_invalidates_plan_cache(serve_root):
    """``spark.tpu.shuffle.wire.runCodes`` is a planning conf: SET must
    evict cached entries built under the old value (run-encoded and raw
    wire plans are not interchangeable executables), and the re-planned
    run must stay oracle-equal."""
    cache = PlanCache(serve_root.conf_obj)
    s = serve_root.newSession()
    s._plan_cache = cache
    s.sql("CREATE TABLE pcrun_t AS "
          "SELECT id % 4 AS k, id AS v FROM range(64)")
    q = ("SELECT k, sum(v) AS sv, count(*) AS c FROM pcrun_t "
         "GROUP BY k ORDER BY k")
    a1 = [tuple(r) for r in s.sql(q).collect()]
    assert [tuple(r) for r in s.sql(q).collect()] == a1
    assert cache.stats()["hits"] >= 1
    before = cache.stats()["invalidations"]
    s.sql("SET spark.tpu.shuffle.wire.runCodes=false")
    assert cache.stats()["invalidations"] > before, \
        "runCodes must be fingerprinted as a planning conf"
    a2 = [tuple(r) for r in s.sql(q).collect()]
    oracle = [tuple(r)
              for r in serve_root.newSession().sql(q).collect()]
    assert a2 == oracle == a1
    s.sql("SET spark.tpu.shuffle.wire.runCodes=true")
    s.sql("DROP TABLE pcrun_t")


def test_run_planes_conf_invalidates_plan_cache(serve_root):
    """``spark.tpu.stage.runPlanes`` is a planning conf: it decides the
    stage-boundary leaf form (compressed plane vs dense materialization)
    and with it the traced stage shapes, so SET must evict entries built
    under the old value — and the re-planned run must stay oracle-equal."""
    cache = PlanCache(serve_root.conf_obj)
    s = serve_root.newSession()
    s._plan_cache = cache
    s.sql("CREATE TABLE pcplane_t AS "
          "SELECT id % 8 AS k, id AS v FROM range(128)")
    q = ("SELECT count(*) AS c, sum(v) AS sv FROM pcplane_t "
         "WHERE k < 5")
    a1 = [tuple(r) for r in s.sql(q).collect()]
    assert [tuple(r) for r in s.sql(q).collect()] == a1
    assert cache.stats()["hits"] >= 1
    before = cache.stats()["invalidations"]
    s.sql("SET spark.tpu.stage.runPlanes=false")
    assert cache.stats()["invalidations"] > before, \
        "runPlanes must be fingerprinted as a planning conf"
    a2 = [tuple(r) for r in s.sql(q).collect()]
    oracle = [tuple(r)
              for r in serve_root.newSession().sql(q).collect()]
    assert a2 == oracle == a1
    s.sql("SET spark.tpu.stage.runPlanes=true")
    s.sql("DROP TABLE pcplane_t")


def test_dataframe_write_invalidates_plan_cache(serve_root, tmp_path):
    """Regression: DataFrame-API writes (``df.write...save``) mutate the
    same paths the SQL commands do, but only the SQL commands called the
    cache's path-invalidation hook — a cached plan reading the written
    path replayed STALE rows after an API overwrite.  The writer now
    routes through ``_invalidate_plan_cache``: the entry is evicted and
    the next run matches a fresh-session oracle."""
    cache = PlanCache(serve_root.conf_obj)
    s = serve_root.newSession()
    s._plan_cache = cache
    path = str(tmp_path / "pcw.parquet")
    s.sql("SELECT id AS k, id * 3 AS v FROM range(40)").write.parquet(path)
    q = ("SELECT k % 4 AS g, sum(v) AS sv FROM pcw "
         "GROUP BY k % 4 ORDER BY g")
    s.read.parquet(path).createOrReplaceTempView("pcw")
    a1 = [tuple(r) for r in s.sql(q).collect()]
    assert [tuple(r) for r in s.sql(q).collect()] == a1
    assert cache.stats()["hits"] >= 1 and cache.stats()["entries"] >= 1

    # the DataFrame-API overwrite bypasses every SQL command hook — the
    # writer itself must evict entries whose file leaves read this path
    before = cache.stats()["invalidations"]
    s.sql("SELECT id AS k, id AS v FROM range(60)") \
        .write.mode("overwrite").parquet(path)
    assert cache.stats()["invalidations"] > before, \
        "df.write must evict cached plans scanning the written path"
    a2 = [tuple(r) for r in s.sql(q).collect()]
    f = serve_root.newSession()
    f.read.parquet(path).createOrReplaceTempView("pcw")
    oracle = [tuple(r) for r in f.sql(q).collect()]
    assert a2 == oracle and a2 != a1


def test_response_cache_fields_on_repeat(serve_root):
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        sid = s["sessionId"]
        q = "SELECT sum(id) AS s FROM range(100) WHERE id < 77"
        first = _sql(srv, q, sid)
        again = _sql(srv, q, sid)
        assert first["cacheHit"] is False
        assert again["cacheHit"] is True
        assert again["rows"] == first["rows"] == [[sum(range(77))]]
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_admission_rejects_over_global_cap(serve_root):
    serve_root.conf.set(C.SERVER_MAX_CONCURRENT_STATEMENTS.key, "1")
    srv = SQLServer(serve_root, port=0, workers=2).start()
    try:
        _, sa = _req(srv, "/session", "POST")
        _, sb = _req(srv, "/session", "POST")
        ssa = srv._sessions[sa["sessionId"]]
        ssa.lock.acquire()               # wedge A mid-statement
        try:
            done = {}

            def post_a():
                done["a"] = _sql(srv, "SELECT 1", sa["sessionId"])

            th = threading.Thread(target=post_a)
            th.start()
            time.sleep(0.5)              # let A's statement be admitted
            with pytest.raises(urllib.error.HTTPError) as ei:
                _sql(srv, "SELECT 2", sb["sessionId"])
            assert ei.value.code == 429
            body = json.loads(ei.value.read())
            assert body["limit"] == "maxConcurrentStatements"
            assert body["cap"] == 1 and body["retryAfterSeconds"] >= 1
            assert int(ei.value.headers["Retry-After"]) >= 1
        finally:
            ssa.lock.release()
        th.join(60)
        assert done["a"]["rows"] == [[1]]    # the admitted one completed
        _, st = _req(srv, "/status")
        assert st["admission"]["rejected"] >= 1
        assert st["admission"]["rejectedBy"]["maxConcurrentStatements"] >= 1
        # capacity freed: the next statement is admitted again
        assert _sql(srv, "SELECT 3", sb["sessionId"])["rows"] == [[3]]
    finally:
        srv.stop()


def test_admission_rejects_deep_session_queue(serve_root):
    serve_root.conf.set(C.SERVER_MAX_QUEUED_PER_SESSION.key, "2")
    srv = SQLServer(serve_root, port=0, workers=2).start()
    try:
        _, sa = _req(srv, "/session", "POST")
        sid = sa["sessionId"]
        ssa = srv._sessions[sid]
        ssa.lock.acquire()
        try:
            codes = []

            def post():
                try:
                    _sql(srv, "SELECT 1", sid)
                    codes.append(200)
                except urllib.error.HTTPError as e:
                    codes.append(e.code)

            backlog = [threading.Thread(target=post) for _ in range(2)]
            for t in backlog:
                t.start()
                time.sleep(0.25)         # deterministic queue depths
            with pytest.raises(urllib.error.HTTPError) as ei:
                _sql(srv, "SELECT 9", sid)
            assert ei.value.code == 429
            assert json.loads(ei.value.read())["limit"] == \
                "maxQueuedPerSession"
        finally:
            ssa.lock.release()
        for t in backlog:
            t.join(60)
        assert codes == [200, 200]       # admitted statements all ran
    finally:
        srv.stop()


def test_admission_host_headroom_unit(serve_root):
    class Ledger:
        free = 10

    serve_root.conf.set(C.SERVER_MIN_HOST_HEADROOM.key, "100")
    ac = AdmissionController(serve_root.conf_obj, lambda: Ledger())
    with pytest.raises(AdmissionRejected) as ei:
        ac.admit(0)
    assert ei.value.limit == "hostMemoryHeadroom"
    assert ei.value.observed == 10 and ei.value.cap == 100
    Ledger.free = 1000
    ac.admit(0)                          # headroom restored → admitted
    ac.release(0.01)
    st = ac.stats()
    assert st["admitted"] == 1 and st["rejected"] == 1
    assert st["active"] == 0


# ---------------------------------------------------------------------------
# statement lifecycle: queued cancel, deadlines, idle sessions
# ---------------------------------------------------------------------------

def test_cancel_removes_queued_statement(serve_root):
    srv = SQLServer(serve_root, port=0, workers=2).start()
    try:
        _, sa = _req(srv, "/session", "POST")
        sid = sa["sessionId"]
        ssa = srv._sessions[sid]
        ssa.lock.acquire()               # first statement blocks running
        try:
            codes = {}

            def run(name, stmt_id):
                try:
                    _sql(srv, "SELECT 1", sid, stmt_id)
                    codes[name] = 200
                except urllib.error.HTTPError as e:
                    codes[name] = e.code

            t1 = threading.Thread(target=run, args=("head", "stmt-head"))
            t1.start()
            time.sleep(0.3)
            t2 = threading.Thread(target=run, args=("tail", "stmt-tail"))
            t2.start()
            time.sleep(0.3)              # tail is parked in the FIFO
            _, c = _req(srv, "/cancel", "POST",
                        json.dumps({"id": "stmt-tail"}))
            # a queued statement cancels SYNCHRONOUSLY: status flips
            # in the cancel response, no worker slot is ever spent
            assert c["status"] == "cancelled"
            t2.join(10)
            assert codes["tail"] == 499
            with srv._reg_lock:
                assert all(item[0].id != "stmt-tail"
                           for item in ssa.queue)
        finally:
            ssa.lock.release()
        t1.join(60)
        assert codes["head"] == 200      # the head was untouched
        _, st = _req(srv, "/statement/stmt-tail")
        assert st["status"] == "cancelled"
    finally:
        srv.stop()


def test_statement_deadline_cancels_long_run(serve_root, tmp_path):
    import numpy as np
    import pandas as pd

    p = str(tmp_path / "slow.parquet")
    pd.DataFrame({"x": np.arange(1_500_000, dtype=np.int64)}).to_parquet(
        p, index=False)
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        sid = s["sessionId"]
        _sql(srv, "SET spark.tpu.scan.maxBatchRows=1024", sid)
        _sql(srv, f"CREATE TEMP VIEW slow AS SELECT * FROM parquet.`{p}`",
             sid)
        _sql(srv, "SET spark.tpu.server.statementTimeout=0.3", sid)
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _sql(srv, "SELECT sum(x) FROM slow", sid, "stmt-deadline")
        assert ei.value.code == 499
        assert time.monotonic() - t0 < 45
        _, st = _req(srv, "/statement/stmt-deadline")
        assert st["status"] == "cancelled"
        # the deadline is per-statement: the session still works
        _sql(srv, "SET spark.tpu.server.statementTimeout=0", sid)
        assert _sql(srv, "SELECT 5", sid)["rows"] == [[5]]
    finally:
        srv.stop()


def test_idle_session_ttl_eviction(serve_root):
    serve_root.conf.set(C.SERVER_SESSION_TIMEOUT.key, "10")
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s1 = _req(srv, "/session", "POST")
        _, s2 = _req(srv, "/session", "POST")
        sid1, sid2 = s1["sessionId"], s2["sessionId"]
        _sql(srv, "SELECT 1", sid1)
        # wedge s2 with queued work: busy sessions are never reaped
        ss2 = srv._sessions[sid2]
        ss2.lock.acquire()
        try:
            th = threading.Thread(
                target=lambda: _sql(srv, "SELECT 1", sid2))
            th.start()
            time.sleep(0.3)
            n = srv._expire_idle_sessions(now=time.time() + 60)
            assert n == 1                # only the idle one went
            assert sid2 in srv._sessions
            assert sid1 not in srv._sessions
        finally:
            ss2.lock.release()
        th.join(60)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _sql(srv, "SELECT 1", sid1)
        assert ei.value.code == 404
        _, st = _req(srv, "/status")
        assert st["sessionsExpired"] == 1
        assert st["metrics"]["serving"]["sessions_expired"] == 1
    finally:
        srv.stop()


def _start_json_stream(srv, sid, tmp_path, tag="s"):
    """POST /stream over a one-file json source; returns (streamId, dirs)."""
    import numpy as np
    data = tmp_path / f"{tag}-in"
    data.mkdir(exist_ok=True)
    srv.session.createDataFrame(
        {"x": np.arange(4, dtype=np.int64)}).write.json(
            str(data / "f1"))
    spec = {"session": sid,
            "source": {"format": "json", "path": str(data),
                       "schema": "x bigint"},
            "sink": {"format": "json", "path": str(tmp_path / f"{tag}-out")},
            "checkpoint": str(tmp_path / f"{tag}-ckpt"),
            "interval": 0.1}
    _, r = _req(srv, "/stream", "POST", json.dumps(spec))
    return r["streamId"]


def _wait_stream_commit(srv, stream_id, n=1, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _, st = _req(srv, f"/stream/{stream_id}")
        if st["metrics"]["batches_committed"] >= n:
            return st
        time.sleep(0.05)
    raise AssertionError(f"stream {stream_id} never committed {n} batches")


def test_stream_endpoint_register_status_stop(serve_root, tmp_path):
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        sid = s["sessionId"]
        stream_id = _start_json_stream(srv, sid, tmp_path)
        st = _wait_stream_commit(srv, stream_id)
        assert st["active"] and st["batchId"] >= 1
        assert st["metrics"]["replayed_batches"] == 0
        assert st["lastProgress"]["stageRebuilds"] is not None
        # visible as a serving-tier tenant end to end
        _, status = _req(srv, "/status")
        assert status["standingQueries"][stream_id]["session"] == sid
        assert status["admission"]["standingQueries"] == 1
        assert status["metrics"]["streaming"]["standing_queries"] == 1
        assert status["metrics"]["streaming"]["batches_committed"] >= 1
        # sink really received the batch
        out = tmp_path / "s-out"
        assert any(out.glob("part-*"))
        _, r = _req(srv, f"/stream/{stream_id}", "DELETE")
        assert r["stopped"] == stream_id
        _, status = _req(srv, "/status")
        assert status["admission"]["standingQueries"] == 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            _req(srv, f"/stream/{stream_id}")
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_session_with_standing_query_never_idle_reaped(serve_root,
                                                       tmp_path):
    """Regression: the idle-TTL reaper must skip a session carrying a
    live standing query, however stale its last statement — reaping it
    would orphan the query's admission slot and kill the stream."""
    serve_root.conf.set(C.SERVER_SESSION_TIMEOUT.key, "10")
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s1 = _req(srv, "/session", "POST")
        _, s2 = _req(srv, "/session", "POST")
        sid1, sid2 = s1["sessionId"], s2["sessionId"]
        stream_id = _start_json_stream(srv, sid1, tmp_path)
        _wait_stream_commit(srv, stream_id)
        n = srv._expire_idle_sessions(now=time.time() + 60)
        assert n == 1                       # only the streamless session
        assert sid1 in srv._sessions and sid2 not in srv._sessions
        _, st = _req(srv, f"/stream/{stream_id}")
        assert st["active"]
        # once the query stops, the session is ordinary idle prey again
        _req(srv, f"/stream/{stream_id}", "DELETE")
        assert srv._expire_idle_sessions(now=time.time() + 60) == 1
        assert sid1 not in srv._sessions
    finally:
        srv.stop()


def test_standing_query_cap_rejects_429_with_retry_after(serve_root,
                                                         tmp_path):
    serve_root.conf.set(C.SERVER_MAX_STANDING_QUERIES.key, "1")
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        sid = s["sessionId"]
        stream_id = _start_json_stream(srv, sid, tmp_path, tag="a")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _start_json_stream(srv, sid, tmp_path, tag="b")
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.loads(ei.value.read())
        assert "standing" in json.dumps(body).lower()
        # the slot frees on DELETE and the next registration succeeds
        _req(srv, f"/stream/{stream_id}", "DELETE")
        _start_json_stream(srv, sid, tmp_path, tag="c")
    finally:
        srv.stop()


def test_status_exposes_serving_state(serve_root):
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        sid = s["sessionId"]
        _sql(srv, "SELECT 1", sid)
        _, st = _req(srv, "/status")
        assert st["sessionQueues"][sid] == {"queued": 0, "running": False}
        adm = st["admission"]
        assert adm["admitted"] >= 1 and adm["active"] == 0
        assert "rejectedBy" in adm and "avgStatementMs" in adm
        pc = st["planCache"]
        for k in ("hits", "misses", "evictions", "invalidations",
                  "entries", "bytes"):
            assert k in pc
        serving = st["metrics"]["serving"]
        for k in ("plan_cache_hits", "plan_cache_misses",
                  "plan_cache_bytes", "admission_admitted",
                  "admission_rejected", "sessions_open"):
            assert k in serving
    finally:
        srv.stop()


def test_plan_cache_disabled_by_conf(serve_root):
    serve_root.conf.set(C.SERVER_PLAN_CACHE_ENABLED.key, "false")
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        q = "SELECT id FROM range(8)"
        r1 = _sql(srv, q, s["sessionId"])
        r2 = _sql(srv, q, s["sessionId"])
        assert r1["cacheHit"] is False and r2["cacheHit"] is False
        _, st = _req(srv, "/status")
        assert "planCache" not in st
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# stress: small pool + tight caps under many clients
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_admission_stress_bounded_and_conserving(serve_root):
    """16 clients hammer 4 sessions through a 2-worker pool with tight
    caps: every response is 200 or a structured 429, every 200 is
    correct, no statement runs twice or vanishes, and stop() returns."""
    serve_root.conf.set(C.SERVER_MAX_CONCURRENT_STATEMENTS.key, "4")
    serve_root.conf.set(C.SERVER_MAX_QUEUED_PER_SESSION.key, "2")
    srv = SQLServer(serve_root, port=0, workers=2).start()
    try:
        sids = [_req(srv, "/session", "POST")[1]["sessionId"]
                for _ in range(4)]
        lock = threading.Lock()
        outcomes = []                    # (stmt_id, code, value)

        def client(cid):
            for k in range(6):
                stmt_id = f"stress-{cid}-{k}"
                try:
                    r = _sql(srv,
                             f"SELECT sum(id) + {cid} AS s "
                             f"FROM range(2000)",
                             sids[cid % 4], stmt_id)
                    with lock:
                        outcomes.append((stmt_id, 200, r["rows"][0][0]))
                except urllib.error.HTTPError as e:
                    body = json.loads(e.read())
                    with lock:
                        outcomes.append((stmt_id, e.code,
                                         body.get("limit")))

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not any(t.is_alive() for t in threads), "client hung"
        assert len(outcomes) == 16 * 6
        codes = {code for _sid, code, _v in outcomes}
        assert codes <= {200, 429}, codes
        assert 200 in codes
        ok = [(sid, v) for sid, code, v in outcomes if code == 200]
        expect = sum(range(2000))
        for stmt_id, v in ok:
            cid = int(stmt_id.split("-")[1])
            assert v == expect + cid, (stmt_id, v)
        rejected = [(sid, v) for sid, code, v in outcomes if code == 429]
        for _sid, limit in rejected:
            assert limit in ("maxConcurrentStatements",
                             "maxQueuedPerSession"), limit
        # conservation: exactly the admitted statements are registered,
        # each terminal exactly once; rejected ones left no trace
        ok_ids = {sid for sid, _v in ok}
        reg = {s.id: s.status for s in srv._statements.values()
               if s.id.startswith("stress-")}
        assert set(reg) == ok_ids
        assert all(st == "done" for st in reg.values())
        _, st = _req(srv, "/status")
        assert st["admission"]["rejected"] == len(rejected)
        assert st["admission"]["active"] == 0
    finally:
        t0 = time.monotonic()
        srv.stop()
        assert time.monotonic() - t0 < 10, "stop() must not hang"


# ---------------------------------------------------------------------------
# stage-entry caching: distributed/multibatch statements no longer bail
# ---------------------------------------------------------------------------

def test_multibatch_statement_stage_cached_cross_session(serve_root):
    """The lifted bailout: a MULTIBATCH statement (streamed scan wider
    than one device batch) from a SECOND session reports a cache hit —
    the statement-level stage entry is shared via the plan cache while
    the compiled stage executables come from the process stage cache."""
    from spark_tpu.sql.stagecompile import stage_cache
    serve_root.conf.set(C.SCAN_MAX_BATCH_ROWS.key, "256")
    cache = PlanCache(serve_root.conf_obj)
    s1 = serve_root.newSession()
    s2 = serve_root.newSession()
    s1._plan_cache = cache
    s2._plan_cache = cache
    s1.sql("CREATE TABLE mbst AS SELECT id AS k, id % 7 AS g, "
           "id * 3 AS v FROM range(2000)")
    q = "SELECT g, sum(v) AS sv FROM mbst GROUP BY g ORDER BY g"
    # prove the statement actually routes through the multibatch lane
    from spark_tpu.sql.multibatch import plan_multibatch
    from spark_tpu.sql.planner import QueryExecution
    qe = QueryExecution(s1, s1.sql(q)._plan)
    assert plan_multibatch(s1, qe.optimized) is not None

    a1 = [tuple(r) for r in s1.sql(q).collect()]
    assert s1._last_plan_cache_info["hit"] is False
    assert cache.stats()["stage_misses"] >= 1
    sc0 = stage_cache().stats()
    a2 = [tuple(r) for r in s2.sql(q).collect()]
    sc1 = stage_cache().stats()
    assert a2 == a1
    assert s2._last_plan_cache_info["hit"] is True, \
        "second session's multibatch statement must report cacheHit"
    assert cache.stats()["stage_hits"] >= 1
    assert sc1["hits"] > sc0["hits"], \
        "the warm statement must reuse compiled stage executables"
    assert sc1["builds"] == sc0["builds"], \
        "the warm statement must not compile new stages"

    # DML invalidation: INSERT evicts the stage entry; the next run is
    # a miss and matches a fresh-session oracle
    inv0 = cache.stats()["invalidations"]
    s2.sql("INSERT INTO mbst SELECT id AS k, id % 7 AS g, "
           "id AS v FROM range(10)")
    assert cache.stats()["invalidations"] > inv0
    a3 = [tuple(r) for r in s1.sql(q).collect()]
    assert s1._last_plan_cache_info["hit"] is False
    oracle_s = serve_root.newSession()
    oracle = [tuple(r) for r in oracle_s.sql(q).collect()]
    assert a3 == oracle and a3 != a1

    # SET of a planning conf evicts stage entries built under the old
    # value (same hygiene rule as whole-plan entries)
    assert cache.stats()["stage_entries"] >= 1
    inv1 = cache.stats()["invalidations"]
    s1.sql("SET spark.tpu.crossproc.autoBroadcastThreshold=54321")
    assert cache.stats()["invalidations"] > inv1
    s1.sql("DROP TABLE mbst")


def test_status_reports_stage_cache_occupancy(serve_root):
    srv = SQLServer(serve_root, port=0).start()
    try:
        _, s = _req(srv, "/session", "POST")
        _sql(srv, "SELECT sum(id) AS s FROM range(128)", s["sessionId"])
        _, st = _req(srv, "/status")
        assert "stageCache" in st
        for key in ("entries", "hits", "misses", "compile_ms",
                    "stages_fused", "ops_per_stage"):
            assert key in st["stageCache"], key
        assert st["stageCache"]["entries"] >= 1
        # plan-cache stats now carry the stage-entry occupancy too
        assert "stage_entries" in st["planCache"]
        assert st["metrics"]["serving"]["plan_cache_stage_hits"] >= 0
        assert st["metrics"]["compile"]["stage_dispatches"] >= 1
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# serving-tier StatsFeedback persistence
# ---------------------------------------------------------------------------

def test_stats_feedback_shared_across_server_sessions(serve_root):
    """Observed exchange cardinalities persist across statements AND
    sessions in the serving tier: the server presets ONE StatsFeedback
    on every session it opens (crossproc's _session_feedback finds it
    instead of creating a per-session empty one)."""
    from spark_tpu.parallel.crossproc import _session_feedback
    srv = SQLServer(serve_root, port=0)
    sid1 = srv._open_session()
    sid2 = srv._open_session()
    s1 = srv._sessions[sid1].session
    s2 = srv._sessions[sid2].session
    assert _session_feedback(s1) is srv._stats_feedback
    assert _session_feedback(s2) is srv._stats_feedback
    assert _session_feedback(serve_root) is srv._stats_feedback
    # recorded in one session, visible in the other
    _session_feedback(s1).record("sigX", 4096, 17, "xq000001")
    assert _session_feedback(s2).peek("sigX") == (4096, 17)


def test_repeated_misestimated_join_broadcasts_on_second_run(serve_root):
    """Regression for the serving-tier feedback loop: the probe
    misestimates both join sides as huge (-> hash/range), the first
    run's adaptive replanner records the right side's true tiny
    cardinality, and the SAME join planned again — from a DIFFERENT
    server session — chooses broadcast_right at plan time."""
    from spark_tpu.parallel.crossproc import (StatsFeedback,
                                              _session_feedback,
                                              choose_join_strategy)
    srv = SQLServer(serve_root, port=0)
    s1 = srv._sessions[srv._open_session()].session
    s2 = srv._sessions[srv._open_session()].session
    sig = StatsFeedback.signature  # structural: same plan -> same key

    import spark_tpu.sql.logical as L
    import spark_tpu.types as T
    from spark_tpu.columnar import ColumnBatch
    import numpy as np
    dim = L.LocalRelation(ColumnBatch.from_arrays(
        {"d": np.arange(8, dtype=np.int64)},
        schema=T.StructType([T.StructField("d", T.int64)])))
    r_sig = sig(dim)

    def plan(session):
        return choose_join_strategy(
            "inner", True, True, True,
            broadcast_threshold=1 << 20, n_procs=2,
            left_bytes=1 << 30, right_bytes=1 << 30,   # the misestimate
            feedback=_session_feedback(session), right_sig=r_sig)

    # first run: no feedback yet -> the probe's estimate stands
    assert plan(s1) != "broadcast_right"
    # the adaptive runtime records the observed tiny right side
    _session_feedback(s1).record(r_sig, 2048, 8, "xq000002")
    # second run, other session: plan-time broadcast, no fragmentation —
    # feedback changes the strategy input, never the plan fingerprint
    assert plan(s2) == "broadcast_right"
