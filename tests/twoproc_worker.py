"""Worker for the two-process jax.distributed smoke test (not a test
module itself — launched as a subprocess by test_cluster_twoproc.py).

argv: <process_id> <coordinator_port> <beat_dir>
"""

import os
import sys
import time

pid = int(sys.argv[1])
port = sys.argv[2]
beat_dir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

try:                                   # top-level export landed post-0.4
    from jax import shard_map  # noqa: E402
except ImportError:
    from jax.experimental.shard_map import shard_map  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

from spark_tpu import config as C  # noqa: E402
from spark_tpu.parallel.cluster import (  # noqa: E402
    HeartbeatMonitor, hybrid_mesh, init_cluster,
)

info = init_cluster(f"localhost:{port}", num_processes=2, process_id=pid)
assert info.process_count == 2, info
assert info.process_index == pid, info
assert len(info.global_devices) == 8, info
assert len(info.local_devices) == 4, info
print(f"[p{pid}] {info}", flush=True)

mesh = hybrid_mesh()
assert mesh.axis_names == ("dcn", "data")
assert mesh.devices.shape == (2, 4), mesh.devices.shape

# one cross-process all-reduce: global sum of a (dcn,data)-sharded array.
# Old jaxlib CPU backends refuse multi-process computations outright; the
# DCN data plane under test below is the host shuffle service, not XLA
# collectives, so those two demos skip (visibly) rather than fail there.
sh = NamedSharding(mesh, PartitionSpec(("dcn", "data")))
arr = jax.make_array_from_callback(
    (32,), sh, lambda idx: np.arange(32.0)[idx])
try:
    s = jax.jit(lambda x: x.sum(),
                out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)
    got = float(np.asarray(jax.device_get(s.addressable_shards[0].data)))
    assert got == 496.0, got
    collectives_ok = True
    print(f"[p{pid}] allreduce sum ok", flush=True)
except Exception as e:
    assert "Multiprocess computations aren't implemented" in str(e), e
    collectives_ok = False
    print(f"[p{pid}] allreduce skipped: no multiprocess CPU backend",
          flush=True)

if collectives_ok:
    # one all_to_all exchange over the intra-slice axis through shard_map
    # (the replication-check kwarg was renamed check_rep → check_vma)
    import inspect  # noqa: E402

    _ck = ("check_vma" if "check_vma"
           in inspect.signature(shard_map).parameters else "check_rep")
    f = shard_map(
        lambda x: lax.all_to_all(x.reshape(4, -1), "data", 0, 0).reshape(-1),
        mesh=mesh, in_specs=PartitionSpec(("dcn", "data")),
        out_specs=PartitionSpec(("dcn", "data")), **{_ck: False})
    y = jax.jit(f)(arr)
    assert len(y.addressable_shards) == 4
    print(f"[p{pid}] all_to_all ok", flush=True)
else:
    print(f"[p{pid}] all_to_all skipped: no multiprocess CPU backend",
          flush=True)

# a REAL query through the host shuffle service (VERDICT r3 #6): each
# process holds half the rows of one table; the groupBy's aggregation
# state crosses the process boundary via filesystem blocks
shuffle_dir = sys.argv[4]
from spark_tpu.parallel.crossproc import host_exchange_group_agg  # noqa: E402
from spark_tpu.parallel.hostshuffle import HostShuffleService  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402
import spark_tpu.sql.functions as F  # noqa: E402

rng = np.random.default_rng(47)            # both processes draw the SAME
keys = rng.integers(0, 57, 4000).astype(np.int64)     # full dataset...
vals = rng.integers(0, 1000, 4000).astype(np.int64)
gnames = np.array(["ash", "oak", "fir"])[keys % 3]
half = slice(pid * 2000, (pid + 1) * 2000)            # ...and keep a half

session = SparkSession.builder.appName(f"xproc-{pid}").getOrCreate()
# every ENGINE query in this worker is process-local (shards=1): under
# jax.distributed an engine run on the auto (global) mesh would be a
# collective program that the OTHER process never joins — asymmetric
# work deadlocks the coordination service.  The cross-process hop under
# test is the HostShuffleService, not the in-slice mesh.
session.conf.set(C.MESH_SHARDS.key, "1")
local = session.createDataFrame({
    "k": keys[half], "g": gnames[half], "v": vals[half]})
q = local.groupBy("k", "g").agg(F.sum("v").alias("s"),
                                F.count("*").alias("c"),
                                F.min("v").alias("lo"))
svc = HostShuffleService(shuffle_dir, process_id=pid, n_processes=2,
                         timeout_s=60.0)
mine = host_exchange_group_agg(session, q, svc, "agg-hop-1")
rows = {tuple(r[:2]): tuple(r[2:]) for r in mine.to_pylist()}
print(f"[p{pid}] crossproc agg: {len(rows)} groups", flush=True)

# every process owns a DISJOINT key range; p0 gathers p1's final rows
# through a second hop and checks the UNION against the single-process
# oracle over the full dataset
gathered = svc.exchange("agg-hop-2", {0: [mine]})
if pid == 0:
    both = {}
    for b in gathered:
        for r in b.to_pylist():
            key = tuple(r[:2])
            assert key not in both, f"key {key} owned by both processes"
            both[key] = tuple(r[2:])
    oracle_df = session.createDataFrame({"k": keys, "g": gnames, "v": vals})
    oracle = {
        tuple(r[:2]): tuple(r[2:])
        for r in (oracle_df.groupBy("k", "g")
                  .agg(F.sum("v").alias("s"), F.count("*").alias("c"),
                       F.min("v").alias("lo")).collect())
    }
    assert both == oracle, (
        f"crossproc={len(both)} oracle={len(oracle)} "
        f"diff={set(both) ^ set(oracle)}")
    print("[p0] CROSSPROC-QUERY-OK", flush=True)

# lifted string aggregates cross the process boundary as dictionary
# CODES: the u words are fully DISJOINT per half, so min/max/first can
# only be right if the exchange genuinely unifies the two code spaces
# (and late-materializes the winning words at the output boundary).
# Contiguous halves make the rebased first-rank order equal global row
# order, so first is oracle-exact here, not merely deterministic.
uwords = np.array([f"u{i // 2000}-{keys[i] % 5:02d}" for i in range(4000)])
slocal = session.createDataFrame({"k": keys[half], "u": uwords[half]})
sq = slocal.groupBy("k").agg(F.min("u").alias("lo"), F.max("u").alias("hi"),
                             F.first("u").alias("fv"),
                             F.count("*").alias("c"))
mine_s = host_exchange_group_agg(session, sq, svc, "agg-hop-str")
gathered_s = svc.exchange("agg-hop-str-2", {0: [mine_s]})
if pid == 0:
    got_s = {}
    for b in gathered_s:
        for r in b.to_pylist():
            assert r[0] not in got_s, f"key {r[0]} owned by both processes"
            got_s[r[0]] = tuple(r[1:])
    odf = session.createDataFrame({"k": keys, "u": uwords})
    exp_s = {r[0]: tuple(r[1:])
             for r in odf.groupBy("k").agg(
                 F.min("u").alias("lo"), F.max("u").alias("hi"),
                 F.first("u").alias("fv"), F.count("*").alias("c"))
             .collect()}
    assert got_s == exp_s, (
        f"string agg mismatch on keys "
        f"{[k for k in exp_s if got_s.get(k) != exp_s[k]][:5]}")
    print("[p0] STRING-AGG-OK", flush=True)

# FULL q3 (scan → broadcast join → filter → agg → sort) via the NORMAL
# session.sql path: enableHostShuffle registers the DCN data plane on the
# session and the PLANNER places the cross-process exchange (VERDICT r4
# #5 — the hop is a planner citizen, not a side-door helper).  The fact
# table is partitioned (half per process); the dim table is replicated.
xs = session.newSession()
xs.conf.set(C.MESH_SHARDS.key, "1")
xs.enableHostShuffle(shuffle_dir + "-q3", process_id=pid, n_processes=2,
                     timeout_s=60.0)
rng2 = np.random.default_rng(91)
f_sk = rng2.integers(0, 64, 6000).astype(np.int64)
f_price = rng2.integers(1, 500, 6000).astype(np.int64)
d_sk = np.arange(64, dtype=np.int64)
d_brand = rng2.integers(0, 11, 64).astype(np.int64)
d_year = rng2.integers(1998, 2003, 64).astype(np.int64)
half2 = slice(pid * 3000, (pid + 1) * 3000)
xs.createDataFrame({"sk": f_sk[half2], "price": f_price[half2]}) \
    .createOrReplaceTempView("fact")
xs.createDataFrame({"d_sk": d_sk, "brand": d_brand, "year": d_year}) \
    .createOrReplaceTempView("dim")
Q3 = ("SELECT brand, sum(price) AS rev FROM fact JOIN dim ON sk = d_sk "
      "WHERE year = 2000 GROUP BY brand ORDER BY rev DESC, brand")
got_q3 = [tuple(r) for r in xs.sql(Q3).collect()]

# single-process oracle over the FULL dataset
os_ = session.newSession()
os_.conf.set(C.MESH_SHARDS.key, "1")
os_.createDataFrame({"sk": f_sk, "price": f_price}) \
    .createOrReplaceTempView("fact")
os_.createDataFrame({"d_sk": d_sk, "brand": d_brand, "year": d_year}) \
    .createOrReplaceTempView("dim")
exp_q3 = [tuple(r) for r in os_.sql(Q3).collect()]
assert got_q3 == exp_q3, (
    f"planner-citizen q3 mismatch: got {got_q3[:5]}... exp {exp_q3[:5]}...")
print(f"[p{pid}] PLANNER-CITIZEN-Q3-OK ({len(got_q3)} rows)", flush=True)

# generic path — a shape the old side-door REFUSED (_reject_global_ops):
# DISTINCT over the partitioned fact, then a sort above it.  Partitioned
# leaves gather through the service (the replicated dim is detected
# byte-identical and kept single) and the plan runs locally, identically
# in both processes.
QD = ("SELECT DISTINCT sk FROM fact WHERE sk < 8 ORDER BY sk")
got_d = [tuple(r) for r in xs.sql(QD).collect()]
exp_d = [tuple(r) for r in os_.sql(QD).collect()]
assert got_d == exp_d, (got_d, exp_d)
print(f"[p{pid}] GENERIC-PATH-DISTINCT-OK ({len(got_d)} rows)", flush=True)

# keyed aggregate over an ALL-REPLICATED table: the digest probe must
# reject the fast path (identical partials would merge to n x the truth)
# and the generic dedup gather must return single-copy results
QR = "SELECT year, count(*) AS c FROM dim GROUP BY year ORDER BY year"
got_r = [tuple(r) for r in xs.sql(QR).collect()]
exp_r = [tuple(r) for r in os_.sql(QR).collect()]
assert got_r == exp_r, (got_r, exp_r)
print(f"[p{pid}] REPLICATED-AGG-OK ({len(got_r)} rows)", flush=True)

# a join of TWO partitioned tables: the digest exchange must classify
# both fact leaves as partitioned, reject the fast path (local joins
# would miss every cross-process match), and gather-then-compute exactly
xs.createDataFrame({"k2": f_sk[half2], "bonus": f_price[half2] * 2}) \
    .createOrReplaceTempView("fact2")
os_.createDataFrame({"k2": f_sk, "bonus": f_price * 2}) \
    .createOrReplaceTempView("fact2")
QJ = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
      "JOIN fact2 ON sk = k2 WHERE sk < 4 GROUP BY sk ORDER BY sk")
got_j = [tuple(r) for r in xs.sql(QJ).collect()]
exp_j = [tuple(r) for r in os_.sql(QJ).collect()]
assert got_j == exp_j, (got_j, exp_j)
print(f"[p{pid}] PARTITIONED-JOIN-OK ({len(got_j)} rows)", flush=True)

# heartbeat death detection across REAL process boundaries: both beat,
# then p1 stops beating and exits; p0 must observe host-1 die
conf = C.Conf()
conf.set("spark.tpu.cluster.heartbeatIntervalMs", "100")
conf.set("spark.tpu.cluster.heartbeatTimeoutMs", "1200")
mon = HeartbeatMonitor(beat_dir, conf=conf, clock=time.time)
mon.start()

if pid == 1:
    time.sleep(0.5)                    # a few beats, then vanish
    mon.stop()
    print("[p1] exiting without farewell", flush=True)
    os._exit(0)                        # simulate a crash: no cleanup

deaths = []
mon.on_failure(deaths.append)
deadline = time.time() + 15
while time.time() < deadline:
    dead = mon.dead_hosts()
    if dead:
        break
    time.sleep(0.1)
assert dead == ["host-1"], dead
assert deaths == ["host-1"], deaths
try:
    mon.check_or_raise()
except RuntimeError as e:
    assert "host-1" in str(e)
else:
    raise AssertionError("check_or_raise did not raise for a dead host")
mon.stop()
print("[p0] DEATH-DETECTED-OK", flush=True)
os._exit(0)                            # skip jax.distributed atexit barrier
