"""Worker for the two-process jax.distributed smoke test (not a test
module itself — launched as a subprocess by test_cluster_twoproc.py).

argv: <process_id> <coordinator_port> <beat_dir>
"""

import os
import sys
import time

pid = int(sys.argv[1])
port = sys.argv[2]
beat_dir = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
from jax import lax, shard_map  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

from spark_tpu import config as C  # noqa: E402
from spark_tpu.parallel.cluster import (  # noqa: E402
    HeartbeatMonitor, hybrid_mesh, init_cluster,
)

info = init_cluster(f"localhost:{port}", num_processes=2, process_id=pid)
assert info.process_count == 2, info
assert info.process_index == pid, info
assert len(info.global_devices) == 8, info
assert len(info.local_devices) == 4, info
print(f"[p{pid}] {info}", flush=True)

mesh = hybrid_mesh()
assert mesh.axis_names == ("dcn", "data")
assert mesh.devices.shape == (2, 4), mesh.devices.shape

# one cross-process all-reduce: global sum of a (dcn,data)-sharded array
sh = NamedSharding(mesh, PartitionSpec(("dcn", "data")))
arr = jax.make_array_from_callback(
    (32,), sh, lambda idx: np.arange(32.0)[idx])
s = jax.jit(lambda x: x.sum(),
            out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)
got = float(np.asarray(jax.device_get(s.addressable_shards[0].data)))
assert got == 496.0, got
print(f"[p{pid}] allreduce sum ok", flush=True)

# one all_to_all exchange over the intra-slice axis through shard_map
f = shard_map(
    lambda x: lax.all_to_all(x.reshape(4, -1), "data", 0, 0).reshape(-1),
    mesh=mesh, in_specs=PartitionSpec(("dcn", "data")),
    out_specs=PartitionSpec(("dcn", "data")), check_vma=False)
y = jax.jit(f)(arr)
assert len(y.addressable_shards) == 4
print(f"[p{pid}] all_to_all ok", flush=True)

# a REAL query through the host shuffle service (VERDICT r3 #6): each
# process holds half the rows of one table; the groupBy's aggregation
# state crosses the process boundary via filesystem blocks
shuffle_dir = sys.argv[4]
from spark_tpu.parallel.crossproc import host_exchange_group_agg  # noqa: E402
from spark_tpu.parallel.hostshuffle import HostShuffleService  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402
import spark_tpu.sql.functions as F  # noqa: E402

rng = np.random.default_rng(47)            # both processes draw the SAME
keys = rng.integers(0, 57, 4000).astype(np.int64)     # full dataset...
vals = rng.integers(0, 1000, 4000).astype(np.int64)
gnames = np.array(["ash", "oak", "fir"])[keys % 3]
half = slice(pid * 2000, (pid + 1) * 2000)            # ...and keep a half

session = SparkSession.builder.appName(f"xproc-{pid}").getOrCreate()
# every ENGINE query in this worker is process-local (shards=1): under
# jax.distributed an engine run on the auto (global) mesh would be a
# collective program that the OTHER process never joins — asymmetric
# work deadlocks the coordination service.  The cross-process hop under
# test is the HostShuffleService, not the in-slice mesh.
session.conf.set(C.MESH_SHARDS.key, "1")
local = session.createDataFrame({
    "k": keys[half], "g": gnames[half], "v": vals[half]})
q = local.groupBy("k", "g").agg(F.sum("v").alias("s"),
                                F.count("*").alias("c"),
                                F.min("v").alias("lo"))
svc = HostShuffleService(shuffle_dir, process_id=pid, n_processes=2,
                         timeout_s=60.0)
mine = host_exchange_group_agg(session, q, svc, "agg-hop-1")
rows = {tuple(r[:2]): tuple(r[2:]) for r in mine.to_pylist()}
print(f"[p{pid}] crossproc agg: {len(rows)} groups", flush=True)

# every process owns a DISJOINT key range; p0 gathers p1's final rows
# through a second hop and checks the UNION against the single-process
# oracle over the full dataset
gathered = svc.exchange("agg-hop-2", {0: [mine]})
if pid == 0:
    both = {}
    for b in gathered:
        for r in b.to_pylist():
            key = tuple(r[:2])
            assert key not in both, f"key {key} owned by both processes"
            both[key] = tuple(r[2:])
    oracle_df = session.createDataFrame({"k": keys, "g": gnames, "v": vals})
    oracle = {
        tuple(r[:2]): tuple(r[2:])
        for r in (oracle_df.groupBy("k", "g")
                  .agg(F.sum("v").alias("s"), F.count("*").alias("c"),
                       F.min("v").alias("lo")).collect())
    }
    assert both == oracle, (
        f"crossproc={len(both)} oracle={len(oracle)} "
        f"diff={set(both) ^ set(oracle)}")
    print("[p0] CROSSPROC-QUERY-OK", flush=True)

# heartbeat death detection across REAL process boundaries: both beat,
# then p1 stops beating and exits; p0 must observe host-1 die
conf = C.Conf()
conf.set("spark.tpu.cluster.heartbeatIntervalMs", "100")
conf.set("spark.tpu.cluster.heartbeatTimeoutMs", "1200")
mon = HeartbeatMonitor(beat_dir, conf=conf, clock=time.time)
mon.start()

if pid == 1:
    time.sleep(0.5)                    # a few beats, then vanish
    mon.stop()
    print("[p1] exiting without farewell", flush=True)
    os._exit(0)                        # simulate a crash: no cleanup

deaths = []
mon.on_failure(deaths.append)
deadline = time.time() + 15
while time.time() < deadline:
    dead = mon.dead_hosts()
    if dead:
        break
    time.sleep(0.1)
assert dead == ["host-1"], dead
assert deaths == ["host-1"], deaths
try:
    mon.check_or_raise()
except RuntimeError as e:
    assert "host-1" in str(e)
else:
    raise AssertionError("check_or_raise did not raise for a dead host")
mon.stop()
print("[p0] DEATH-DETECTED-OK", flush=True)
os._exit(0)                            # skip jax.distributed atexit barrier
