"""Adaptive exchanges: measured balancing + hot-key splitting on the mesh.

VERDICT r3 item 3 — replace the static ``skew_factor`` + whole-step retry
with (a) a balanced fine-bucket→shard assignment from psum'd measured
counts (``ExchangeCoordinator.scala:85,118`` re-designed to run INSIDE the
one fused SPMD program) and (b) hot-key splitting for shuffled joins
(probe rows spread round-robin, build rows replicate — the skew handling
SURVEY §2.12 notes Spark 2.3 lacks).

Acceptance here: Zipf-skewed aggregation and a 50%-hot-key join run on
the 8-shard mesh with a MODEST capacity factor and ZERO adaptive
whole-step retries (asserted via the executor's overflow warning log),
matching the pandas oracle exactly.
"""

import logging

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

import spark_tpu.config as C
import spark_tpu.sql.functions as F

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


@pytest.fixture()
def dspark(spark):
    spark.conf.set("spark.tpu.mesh.shards", "8")
    # modest factor: the static hash%n routing overflows under the skew
    # below at this factor; the adaptive path must not
    old = spark.conf.get(C.EXCHANGE_SKEW_FACTOR)
    spark.conf.set(C.EXCHANGE_SKEW_FACTOR.key, "2.0")
    yield spark
    spark.conf.set(C.EXCHANGE_SKEW_FACTOR.key, str(old))
    spark.conf.set("spark.tpu.mesh.shards", "1")


def _no_retry(caplog):
    assert not [r for r in caplog.records
                if "capacity overflow" in r.getMessage()], \
        "adaptive exchange still fell back to whole-step retry"


def test_balanced_assignment_flattens_loads():
    from spark_tpu.parallel.collective import balanced_assignment
    rng = np.random.default_rng(5)
    # zipf-ish bucket histogram: a few heavy buckets, a long tail
    counts = jnp.asarray(
        np.sort(rng.zipf(1.5, 256).astype(np.int64) * 100)[::-1].copy())
    assign, loads = jax.jit(
        balanced_assignment, static_argnums=1)(counts, 8)
    loads = np.asarray(loads)
    assert int(loads.sum()) == int(np.asarray(counts).sum())
    # greedy LPT: max load within max(mean, heaviest bucket) + slack
    mean = loads.sum() / 8
    heaviest = int(np.asarray(counts).max())
    assert loads.max() <= max(mean * 1.35, heaviest * 1.05)


def test_zipf_group_agg_no_retry(dspark, caplog):
    rng = np.random.default_rng(23)
    n = 40_000
    # heavy Zipf over many keys: hash%8 hotspots a shard, balanced
    # assignment must flatten it
    keys = rng.zipf(1.3, n).astype(np.int64) % 997
    vals = rng.integers(0, 100, n).astype(np.int64)
    df = dspark.createDataFrame({"k": keys, "v": vals})
    with caplog.at_level(logging.WARNING, logger="spark_tpu.execution"):
        out = {r.k: (r.s, r.c) for r in
               df.groupBy("k").agg(F.sum("v").alias("s"),
                                   F.count("*").alias("c")).collect()}
    _no_retry(caplog)
    exp = pd.DataFrame({"k": keys, "v": vals}).groupby("k").agg(
        s=("v", "sum"), c=("v", "count"))
    assert out == {int(k): (int(r.s), int(r.c)) for k, r in exp.iterrows()}


def test_hot_key_join_no_retry(dspark, caplog):
    """50% of probe rows share ONE key: with hash%n routing that key's
    shard needs >= n/2 x even capacity (overflow at factor 2); the skew
    join spreads the hot bucket's probe rows and replicates its build
    rows, so per-shard load stays bounded near the even share."""
    rng = np.random.default_rng(29)
    n = 32_768
    n_keys = 512
    keys = rng.integers(0, n_keys, n).astype(np.int64)
    keys[: n // 2] = 7                      # the hot key
    vals = rng.integers(0, 1000, n).astype(np.int64)
    # build side ABOVE the broadcast threshold is unnecessary — force the
    # shuffled path by lowering the threshold instead of inflating data
    old_thr = dspark.conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, "16")
    try:
        fact = dspark.createDataFrame({"k": keys, "v": vals})
        dim = dspark.createDataFrame({
            "dk": np.arange(n_keys, dtype=np.int64),
            "tag": (np.arange(n_keys, dtype=np.int64) * 3) % 11,
        })
        with caplog.at_level(logging.WARNING, logger="spark_tpu.execution"):
            out = (fact.join(dim, fact["k"] == dim["dk"])
                   .groupBy("tag").agg(F.sum("v").alias("s"),
                                       F.count("*").alias("c"))
                   .collect())
        _no_retry(caplog)
    finally:
        dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, str(old_thr))
    got = {r.tag: (r.s, r.c) for r in out}
    pdf = pd.DataFrame({"k": keys, "v": vals}).merge(
        pd.DataFrame({"dk": np.arange(n_keys),
                      "tag": (np.arange(n_keys) * 3) % 11}),
        left_on="k", right_on="dk")
    exp = pdf.groupby("tag").agg(s=("v", "sum"), c=("v", "count"))
    assert got == {int(t): (int(r.s), int(r.c)) for t, r in exp.iterrows()}


def test_hot_key_left_join_matches_oracle(dspark):
    """Left outer with a hot key AND unmatched probe rows: spread probe
    rows must still emit their unmatched-left rows exactly once."""
    rng = np.random.default_rng(31)
    n = 8192
    keys = rng.integers(0, 64, n).astype(np.int64)
    keys[: n // 2] = 3
    keys[n - 256:] = 1000                   # unmatched in dim
    old_thr = dspark.conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, "16")
    try:
        fact = dspark.createDataFrame({"k": keys,
                                       "v": np.arange(n, dtype=np.int64)})
        dim = dspark.createDataFrame({
            "dk": np.arange(64, dtype=np.int64),
            "w": np.arange(64, dtype=np.int64) * 10,
        })
        out = (fact.join(dim, fact["k"] == dim["dk"], "left")
               .agg(F.count("*").alias("c"), F.sum("w").alias("sw"))
               .collect())
    finally:
        dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, str(old_thr))
    pdf = pd.DataFrame({"k": keys, "v": np.arange(n)}).merge(
        pd.DataFrame({"dk": np.arange(64), "w": np.arange(64) * 10}),
        left_on="k", right_on="dk", how="left")
    assert out[0].c == len(pdf)
    assert out[0].sw == int(pdf.w.sum())


def test_full_outer_join_skew_safe(dspark):
    """Full outer takes the balanced-assignment path with spreading OFF
    (replicated build rows would duplicate unmatched-build output)."""
    rng = np.random.default_rng(37)
    n = 4096
    keys = rng.integers(0, 96, n).astype(np.int64)
    keys[: n // 2] = 11
    old_thr = dspark.conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, "16")
    try:
        left = dspark.createDataFrame({"k": keys,
                                       "v": np.arange(n, dtype=np.int64)})
        right = dspark.createDataFrame({
            "rk": np.arange(64, 160, dtype=np.int64),
            "w": np.arange(96, dtype=np.int64),
        })
        out = (left.join(right, left["k"] == right["rk"], "outer")
               .agg(F.count("*").alias("c")).collect())
    finally:
        dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, str(old_thr))
    pdf = pd.DataFrame({"k": keys, "v": np.arange(n)}).merge(
        pd.DataFrame({"rk": np.arange(64, 160), "w": np.arange(96)}),
        left_on="k", right_on="rk", how="outer")
    assert out[0].c == len(pdf)


def test_mixed_type_join_keys_route_together(dspark):
    """int64 fact key vs float64 dim key: Hash64 of 7 and 7.0 differ, so
    routing must hash BOTH sides as float64 (the PJoin search-key rule)
    or every cross-typed match silently vanishes."""
    old_thr = dspark.conf.get(C.AUTO_BROADCAST_JOIN_THRESHOLD)
    dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, "16")
    try:
        n = 4096
        rng = np.random.default_rng(43)
        keys = rng.integers(0, 64, n).astype(np.int64)
        fact = dspark.createDataFrame({"k": keys})
        dim = dspark.createDataFrame({
            "dk": np.arange(64, dtype=np.float64),
            "w": np.arange(64, dtype=np.int64),
        })
        out = (fact.join(dim, fact["k"] == dim["dk"])
               .agg(F.count("*").alias("c")).collect())
        assert out[0].c == n
    finally:
        dspark.conf.set(C.AUTO_BROADCAST_JOIN_THRESHOLD.key, str(old_thr))


def test_adaptive_off_falls_back_to_static(dspark):
    """The escape hatch: adaptive disabled reproduces the old behavior
    (static hash%n + capacity-growth retry) and still gets the answer."""
    old = dspark.conf.get(C.ADAPTIVE_ENABLED)
    dspark.conf.set(C.ADAPTIVE_ENABLED.key, "false")
    try:
        rng = np.random.default_rng(41)
        keys = rng.zipf(1.3, 20_000).astype(np.int64) % 997
        df = dspark.createDataFrame({"k": keys})
        out = {r.k: r.c for r in
               df.groupBy("k").agg(F.count("*").alias("c")).collect()}
        exp = pd.Series(keys).value_counts()
        assert out == {int(k): int(v) for k, v in exp.items()}
    finally:
        dspark.conf.set(C.ADAPTIVE_ENABLED.key, str(old))


def test_join_output_cap_is_actionable(spark):
    """A hot-key fanout join whose adaptively grown output allocation
    explodes past the ABSOLUTE row bound must fail with the out-of-core
    guidance, not attempt the allocation (the q14-under-skew failure
    mode: a 15,000x factor asked XLA for hundreds of GB)."""
    rng = np.random.default_rng(11)
    n = 4096
    left = spark.createDataFrame({
        "k": np.zeros(n, dtype=np.int64),       # ONE key both sides
        "v": rng.integers(0, 9, n)})
    right = spark.createDataFrame({
        "k": np.zeros(n, dtype=np.int64),
        "w": rng.integers(0, 9, n)})
    old = spark.conf.get(C.JOIN_OUTPUT_MAX_ROWS)
    spark.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, str(64 * 1024))
    try:
        with pytest.raises(RuntimeError, match="out-of-core|fans out"):
            left.join(right, on="k").agg(F.count("*").alias("c")).collect()
    finally:
        spark.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, str(old))
