"""Expression tests with the dual-path oracle.

Every expression is evaluated BOTH interpreted (numpy) and compiled
(jax.jit over jax.numpy) and the results must agree — the port of the
reference's ``ExpressionEvalHelper`` pattern, where every expression runs
through eval() and codegen and is cross-checked.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_tpu import types as T
from spark_tpu.columnar import ColumnBatch
from spark_tpu.expressions import (
    Alias, And, Between, Cast, CaseWhen, Coalesce, Col, Concat, EQ, EqNullSafe,
    EvalContext, ExtractDatePart, GE, GT, Greatest, Hash64, If, In, IsNaN,
    IsNull, IsNotNull, LE, LT, Least, Literal, NE, Not, Or, Pow, RoundExpr,
    StringLength, StringPredicate, StringTransform, Substring, UnaryMath,
    col, lit, AnalysisException,
)


def make_batch():
    return ColumnBatch.from_arrays({
        "a": np.array([1, 2, 3, 4, 5], dtype=np.int64),
        "b": np.array([10.0, np.nan, 30.0, 0.5, -2.0]),
        "c": [None, 2, None, 4, 5],
        "s": ["apple", "Banana", None, "cherry", "apple"],
        "flag": np.array([True, False, True, True, False]),
        "d": np.array(["2020-01-31", "2021-03-01", "2019-12-29", "2024-02-29", "1969-07-20"],
                      dtype="datetime64[D]"),
        "t": np.array(["2020-01-31T13:45:21", "2021-03-01T00:00:00",
                       "2019-12-29T23:59:59", "2024-02-29T06:30:00",
                       "1969-07-20T20:17:40"], dtype="datetime64[s]"),
    })


def dual_eval(expr, batch=None):
    """Evaluate interpreted (numpy) and traced (jax.numpy); assert agreement.

    Uses EAGER jnp per expression (a jit compile costs ~0.8s in this build);
    full under-jit compilation of a representative expression battery is
    covered once by ``test_jit_compilation_battery``.
    """
    batch = batch if batch is not None else make_batch()
    ref = expr.eval(EvalContext(batch.to_host(), np))

    dev = batch.to_device()
    out = EvalContext(dev, jnp).broadcast(expr.eval(EvalContext(dev, jnp)))
    assert out.dictionary == ref.dictionary
    n = 5  # live rows in make_batch
    rd = np.broadcast_to(np.asarray(ref.data), (batch.capacity,))[:n]
    jd = np.asarray(out.data)[:n]
    rv = None if ref.valid is None else np.broadcast_to(np.asarray(ref.valid), (batch.capacity,))[:n]
    jv = None if out.valid is None else np.asarray(out.valid)[:n]
    mask = np.ones(n, bool) if rv is None else rv
    if jv is None:
        assert rv is None or bool(rv.all()), "jit lost a null mask"
    else:
        assert rv is not None, "jit invented a null mask"
        np.testing.assert_array_equal(rv, jv)
    if rd.dtype.kind == "f":
        np.testing.assert_allclose(rd[mask], jd[mask], rtol=1e-12, equal_nan=True)
    else:
        np.testing.assert_array_equal(rd[mask], jd[mask])
    return ref, mask, rd


def values(expr, batch=None):
    """Host-visible per-row python values (None where invalid)."""
    ref, mask, rd = dual_eval(expr, batch)
    out = []
    for i in range(len(rd)):
        if not mask[i]:
            out.append(None)
        elif ref.dictionary is not None:
            out.append(ref.dictionary[int(rd[i])])
        else:
            out.append(rd[i].item())
    return out


def test_arithmetic():
    assert values(col("a") + col("a")) == [2, 4, 6, 8, 10]
    assert values(col("a") * 3 - 1) == [2, 5, 8, 11, 14]
    assert values(col("a") / 2) == [0.5, 1.0, 1.5, 2.0, 2.5]
    assert values(1000 - col("a")) == [999, 998, 997, 996, 995]
    assert values(-col("a")) == [-1, -2, -3, -4, -5]


def test_division_by_zero_is_null():
    assert values(col("a") / 0) == [None] * 5
    assert values(col("a") % 0) == [None] * 5
    from spark_tpu.expressions import IntDiv
    assert values(IntDiv(col("a"), lit(2))) == [0, 1, 1, 2, 2]


def test_mod_sign_follows_dividend():
    assert values(-col("a") % 3) == [-1, -2, 0, -1, -2]


def test_null_propagation():
    assert values(col("c") + 1) == [None, 3, None, 5, 6]
    assert values(col("c") * col("a")) == [None, 4, None, 16, 25]


def test_comparisons():
    assert values(col("a") > 3) == [False, False, False, True, True]
    assert values(col("c") >= 4) == [None, False, None, True, True]
    assert values(EqNullSafe(col("c"), lit(2))) == [False, True, False, False, False]
    assert values(EqNullSafe(col("c"), Literal(None))) == [True, False, True, False, False]


def test_kleene_logic():
    p = col("c") > 2    # [None, F, None, T, T]
    q = col("flag")     # [T, F, T, T, F]
    assert values(And(p, q)) == [None, False, None, True, False]
    assert values(Or(p, q)) == [True, False, True, True, True]
    assert values(Not(p)) == [None, True, None, False, False]


def test_null_predicates():
    assert values(IsNull(col("c"))) == [True, False, True, False, False]
    assert values(IsNotNull(col("c"))) == [False, True, False, True, True]
    assert values(IsNull(col("a"))) == [False] * 5
    # NaN in float input became NULL at ingest
    assert values(IsNull(col("b"))) == [False, True, False, False, False]


def test_conditionals():
    e = If(col("a") > 3, col("a") * 10, lit(0))
    assert values(e) == [0, 0, 0, 40, 50]
    cw = CaseWhen([(col("a") <= 2, lit(100)), (col("a") <= 4, lit(200))], lit(300))
    assert values(cw) == [100, 100, 200, 200, 300]
    cw2 = CaseWhen([(col("a") <= 2, lit(100))])  # no ELSE → NULL
    assert values(cw2) == [100, 100, None, None, None]


def test_coalesce():
    assert values(Coalesce(col("c"), col("a"))) == [1, 2, 3, 4, 5]
    assert values(Coalesce(col("c"), Literal(None), lit(-1))) == [-1, 2, -1, 4, 5]


def test_in_between():
    assert values(In(col("a"), [lit(2), lit(5)])) == [False, True, False, False, True]
    assert values(Between(col("a"), 2, 4)) == [False, True, True, True, False]
    assert values(In(col("s"), ["apple", "missing"])) == [True, False, None, False, True]


def test_greatest_least():
    assert values(Greatest(col("a"), lit(3))) == [3, 3, 3, 4, 5]
    assert values(Least(col("a"), lit(3))) == [1, 2, 3, 3, 3]


def test_math_functions():
    assert values(UnaryMath("sqrt", col("a")))[0] == pytest.approx(1.0)
    assert values(UnaryMath("floor", col("b"))) == [10, None, 30, 0, -2]
    # ln of negative → NULL
    assert values(UnaryMath("ln", col("b"))) == [
        pytest.approx(np.log(10.0)), None, pytest.approx(np.log(30.0)),
        pytest.approx(np.log(0.5)), None]
    assert values(RoundExpr(col("b"), 0)) == [10.0, None, 30.0, 1.0, -2.0]
    assert values(Pow(col("a"), lit(2))) == [1.0, 4.0, 9.0, 16.0, 25.0]


def test_string_comparisons():
    # literal comparisons work in code space (sorted dictionary)
    assert values(EQ(col("s"), lit("apple"))) == [True, False, None, False, True]
    # binary (byte) ordering like Spark's UTF8String: "Banana" < "apple"
    assert values(GT(col("s"), lit("apple"))) == [False, False, None, True, False]
    # literal not present in dictionary
    assert values(GT(col("s"), lit("b"))) == [False, False, None, True, False]
    assert values(EQ(col("s"), lit("b"))) == [False, False, None, False, False]


def test_string_transforms():
    assert values(StringTransform("upper", col("s"))) == [
        "APPLE", "BANANA", None, "CHERRY", "APPLE"]
    assert values(StringLength(col("s"))) == [5, 6, None, 6, 5]
    assert values(Substring(col("s"), 1, 3)) == ["app", "Ban", None, "che", "app"]
    assert values(StringTransform("reverse", col("s"))) == [
        "elppa", "ananaB", None, "yrrehc", "elppa"]


def test_string_predicates():
    assert values(StringPredicate("like", col("s"), "%an%")) == [
        False, True, None, False, False]
    assert values(StringPredicate("startswith", col("s"), "a")) == [
        True, False, None, False, True]
    assert values(StringPredicate("contains", col("s"), "err")) == [
        False, False, None, True, False]
    assert values(StringPredicate("rlike", col("s"), "^[ab]")) == [
        True, False, None, False, True]


def test_concat():
    e = Concat(col("s"), lit("!"))
    assert values(e) == ["apple!", "Banana!", None, "cherry!", "apple!"]


def test_cast():
    assert values(Cast(col("a"), T.float64)) == [1.0, 2.0, 3.0, 4.0, 5.0]
    assert values(Cast(col("b"), T.int64)) == [10, None, 30, 0, -2]
    assert values(Cast(col("flag"), T.int32)) == [1, 0, 1, 1, 0]
    b = ColumnBatch.from_arrays({"x": ["1.5", "oops", None, "42", "-3"]})
    assert values(Cast(Col("x"), T.float64), b) == [1.5, None, None, 42.0, -3.0]
    assert values(Cast(Col("x"), T.int64), b) == [1, None, None, 42, -3]


def test_date_extraction():
    assert values(ExtractDatePart("year", col("d"))) == [2020, 2021, 2019, 2024, 1969]
    assert values(ExtractDatePart("month", col("d"))) == [1, 3, 12, 2, 7]
    assert values(ExtractDatePart("day", col("d"))) == [31, 1, 29, 29, 20]
    assert values(ExtractDatePart("quarter", col("d"))) == [1, 1, 4, 1, 3]
    # cross-check dayofweek/dayofyear/weekofyear against python datetime
    import datetime
    dates = [datetime.date(2020, 1, 31), datetime.date(2021, 3, 1),
             datetime.date(2019, 12, 29), datetime.date(2024, 2, 29),
             datetime.date(1969, 7, 20)]
    assert values(ExtractDatePart("dayofweek", col("d"))) == [
        d.isoweekday() % 7 + 1 for d in dates]
    assert values(ExtractDatePart("dayofyear", col("d"))) == [
        d.timetuple().tm_yday for d in dates]
    assert values(ExtractDatePart("weekofyear", col("d"))) == [
        d.isocalendar()[1] for d in dates]


def test_timestamp_extraction():
    assert values(ExtractDatePart("year", col("t"))) == [2020, 2021, 2019, 2024, 1969]
    assert values(ExtractDatePart("hour", col("t"))) == [13, 0, 23, 6, 20]
    assert values(ExtractDatePart("minute", col("t"))) == [45, 0, 59, 30, 17]
    assert values(ExtractDatePart("second", col("t"))) == [21, 0, 59, 0, 40]


def test_hash64_deterministic_and_null_distinct():
    v = values(Hash64(col("a")))
    assert len(set(v)) == 5  # distinct inputs → distinct hashes
    v2 = values(Hash64(col("a")))
    assert v == v2
    vs = values(Hash64(col("s")))
    assert vs[0] == vs[4]  # same word, same hash
    assert vs[1] != vs[0]
    vc = values(Hash64(col("c")))
    assert vc[0] == vc[2]  # nulls hash equal
    assert vc[0] not in (vc[1], vc[3], vc[4])


def test_hash64_string_independent_of_dictionary():
    b1 = ColumnBatch.from_arrays({"s": ["x", "y"]})
    b2 = ColumnBatch.from_arrays({"s": ["a", "x", "z"]})
    h1 = values(Hash64(Col("s")), b1)
    h2 = values(Hash64(Col("s")), b2)
    assert h1[0] == h2[1]  # "x" hashes identically under different dictionaries


def test_analysis_errors():
    batch = make_batch()
    with pytest.raises(AnalysisException):
        Col("missing").data_type(batch.schema)
    with pytest.raises(AnalysisException):
        EQ(col("s"), col("flag")).data_type(batch.schema)  # string vs boolean


def test_type_inference():
    schema = make_batch().schema
    assert (col("a") + col("c")).data_type(schema) is T.int64
    assert (col("a") / lit(2)).data_type(schema) is T.float64
    assert (col("a") > lit(1)).data_type(schema) is T.boolean
    assert Cast(col("a"), T.string).data_type(schema) is T.string
    assert Alias(col("a") + 1, "x").name == "x"


def test_jit_compilation_battery():
    """Compile a representative battery of expressions in ONE jitted program
    and cross-check against the numpy-interpreted path — the real
    WholeStageCodegen analog check (many exprs fused into one XLA program)."""
    batch = make_batch()
    exprs = [
        col("a") * 3 - col("c"),
        col("a") / 0,
        -col("a") % 3,
        Coalesce(col("c"), col("a")),
        If(And(col("a") > 2, col("flag")), col("a") * 10, lit(-1)),
        CaseWhen([(col("a") <= 2, lit(100))], lit(300)),
        EQ(col("s"), lit("apple")),
        GT(col("s"), lit("b")),
        In(col("s"), ["apple", "zzz"]),
        StringTransform("upper", col("s")),
        StringLength(col("s")),
        StringPredicate("like", col("s"), "%an%"),
        Concat(col("s"), lit("!")),
        Cast(col("b"), T.int64),
        ExtractDatePart("year", col("d")),
        ExtractDatePart("weekofyear", col("d")),
        ExtractDatePart("hour", col("t")),
        Hash64(col("a"), col("s")),
        UnaryMath("ln", col("b")),
        RoundExpr(col("b"), 1),
    ]

    @jax.jit
    def run(b):
        ctx = EvalContext(b, jnp)
        out = []
        for e in exprs:
            v = ctx.broadcast(e.eval(ctx))
            out.append((v.data, v.valid))
        return out

    results = run(batch.to_device())
    host_ctx = EvalContext(batch.to_host(), np)
    for e, (jd, jv) in zip(exprs, results):
        ref = host_ctx.broadcast(e.eval(host_ctx))
        rv = np.ones(8, bool) if ref.valid is None else np.asarray(ref.valid)
        jvv = np.ones(8, bool) if jv is None else np.asarray(jv)
        live = np.asarray(batch.row_valid_or_true())
        np.testing.assert_array_equal(rv[live], jvv[live], err_msg=repr(e))
        sel = live & rv
        rd, jdd = np.asarray(ref.data), np.asarray(jd)
        if rd.dtype.kind == "f":
            np.testing.assert_allclose(rd[sel], jdd[sel], rtol=1e-12, err_msg=repr(e))
        else:
            np.testing.assert_array_equal(rd[sel], jdd[sel], err_msg=repr(e))


def test_randomized_dual_path(rng):
    """Fuzz: random int/float/null data through a compound expression tree,
    interpreted vs jitted must agree exactly (RandomDataGenerator analog)."""
    for trial in range(10):
        n = int(rng.integers(1, 50))
        a = rng.integers(-100, 100, n)
        bvals = rng.normal(size=n) * 100
        cm = rng.random(n) < 0.3
        c = [None if cm[i] else int(rng.integers(-5, 5)) for i in range(n)]
        batch = ColumnBatch.from_arrays({
            "a": a.astype(np.int64), "b": bvals, "c": c})
        expr = If(
            And(Col("a") % 7 > 2, IsNotNull(Col("c"))),
            Col("a") * Col("c") + Cast(Col("b"), T.int64),
            Coalesce(Col("c"), Col("a") - 1),
        )
        ref = expr.eval(EvalContext(batch.to_host(), np))
        run = jax.jit(lambda bt: EvalContext(bt, jnp).broadcast(expr.eval(EvalContext(bt, jnp))))
        out = run(batch.to_device())
        live = np.asarray(batch.row_valid_or_true())
        rd = np.broadcast_to(np.asarray(ref.data), (batch.capacity,))
        rv = np.broadcast_to(np.asarray(ref.valid), (batch.capacity,)) if ref.valid is not None else np.ones(batch.capacity, bool)
        jd, jv = np.asarray(out.data), (np.asarray(out.valid) if out.valid is not None else np.ones(batch.capacity, bool))
        sel = live & rv
        np.testing.assert_array_equal(rv[live], jv[live], err_msg=f"trial {trial}")
        np.testing.assert_array_equal(rd[sel], jd[sel], err_msg=f"trial {trial}")
