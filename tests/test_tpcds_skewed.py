"""TPC-DS over dsdgen-like SKEWED marginals (VERDICT r3 item 7).

The uniform generator cannot produce the distributions that break
engines: Zipf item/customer/store popularity, seasonal (holiday-ramped)
dates, category-correlated prices, NULL-pocked measures.  This harness
re-runs query texts against the sqlite oracle over data generated with
``generate(..., skew=1.2, measure_null_frac=0.05)``.

A representative smoke subset (the re-tightened q54, the multi-fact
grace joins, windows, heavy aggregates) always runs; the FULL RUNNABLE
sweep runs with SPARK_TPU_SKEW_SWEEP=1.
"""

import math
import os
import sqlite3

import pytest

from spark_tpu.tpcds import ORACLE_OVERRIDES, QUERIES, RUNNABLE, generate
from spark_tpu.tpcds.oracle import norm_value as _norm, row_key as _key, \
    sqlite_text as _sqlite_text

SF_ROWS = 20_000
SKEW = 1.2
NULL_FRAC = 0.05

FULL = os.environ.get("SPARK_TPU_SKEW_SWEEP", "") == "1"
SMOKE = ["q3", "q7", "q17", "q25", "q29", "q42", "q54", "q55", "q58",
         "q63", "q67", "q83", "q96", "q98"]
SWEEP = RUNNABLE if FULL else SMOKE


@pytest.fixture(scope="module")
def tpcds_skewed(spark):
    tables = generate(SF_ROWS, skew=SKEW, measure_null_frac=NULL_FRAC)
    for name, pdf in tables.items():
        spark.createDataFrame(pdf).createOrReplaceTempView(name)
    con = sqlite3.connect(":memory:")
    for name, pdf in tables.items():
        pdf.to_sql(name, con, index=False)
    yield spark, con
    con.close()
    for name in tables:
        spark.catalog.dropTempView(name)


def test_skew_actually_skews():
    """The generator must produce the hostile marginals it claims."""
    import numpy as np
    t = generate(SF_ROWS, skew=SKEW, measure_null_frac=NULL_FRAC)
    ss = t["store_sales"]
    counts = ss["ss_item_sk"].value_counts()
    top_share = counts.iloc[:10].sum() / len(ss)
    assert top_share > 0.25, f"top-10 items carry {top_share:.2%}"
    # seasonality: holiday-quarter months outsell the others per-day
    dd = t["date_dim"][["d_date_sk", "d_moy"]]
    sold = ss.dropna(subset=["ss_sold_date_sk"]).merge(
        dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    per_moy = sold.groupby("d_moy").size()
    hot = per_moy.loc[[11, 12]].mean()
    cold = per_moy.loc[[3, 4, 5]].mean()
    assert hot > 1.7 * cold, (hot, cold)
    # measure NULL density in the asked-for band
    frac = ss["ss_sales_price"].isna().mean()
    assert 0.03 < frac < 0.08, frac
    # uniform generation unchanged (back-compat with every other suite)
    u = generate(2000)
    assert u["store_sales"]["ss_sales_price"].isna().mean() == 0.0


#: queries whose OUTPUT columns are ROUND(ratio, 2) expressions: their
#: half-ties legitimately land on different cents across engines (the
#: tie direction depends on the binary neighborhood of x.xx5).  The
#: allowance is per-query and one cent — price/min/max/sum columns
#: elsewhere stay exact, so a wrong rounding MODE still fails broadly.
_ROUND2_TIE_OK = {"q78"}


def _round2_tie(a: float, b: float, qname: str) -> bool:
    return (qname in _ROUND2_TIE_OK
            and abs(a - b) <= 0.01 + 1e-9
            and abs(a * 100 - round(a * 100)) < 1e-6
            and abs(b * 100 - round(b * 100)) < 1e-6)


def _compare(got, exp, qname):
    got = sorted((tuple(_norm(v) for v in r) for r in got), key=_key)
    exp = sorted((tuple(_norm(v) for v in r) for r in exp), key=_key)
    assert len(got) == len(exp), \
        f"{qname}: {len(got)} rows != oracle {len(exp)}"
    for i, (g, e) in enumerate(zip(got, exp)):
        for j, (a, b) in enumerate(zip(g, e)):
            if isinstance(a, float) and isinstance(b, float):
                assert math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6) \
                    or _round2_tie(a, b, qname), \
                    f"{qname} row {i} col {j}: {a} != {b}"
            else:
                assert a == b, f"{qname} row {i} col {j}: {a!r} != {b!r}"


@pytest.mark.parametrize("qname", SWEEP)
def test_skewed_query(tpcds_skewed, qname):
    spark, con = tpcds_skewed
    sql = QUERIES[qname]
    got = [tuple(r) for r in spark.sql(sql).collect()]
    oracle_sql = ORACLE_OVERRIDES.get(qname, sql)
    exp = con.execute(_sqlite_text(oracle_sql)).fetchall()
    _compare(got, exp, qname)
