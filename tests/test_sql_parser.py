"""SQL parser + end-to-end SQL execution tests.

Mirrors the reference's golden-query strategy (`SQLQueryTestSuite.scala:82`):
each SQL text is executed and cross-checked against the equivalent
DataFrame-API query or a hand-computed expected answer.
"""

import numpy as np
import pytest

from spark_tpu.expressions import AnalysisException
from spark_tpu.sql.parser import ParseException, parse_expression, parse_query


def rows(df):
    return [tuple(r) for r in df.collect()]


def sorted_rows(df):
    return sorted(rows(df), key=lambda t: tuple(str(x) for x in t))


@pytest.fixture()
def tables(spark):
    t = spark.createDataFrame({
        "k": np.array([1, 2, 1, 3, 2, 1], np.int64),
        "v": np.array([10, 20, 30, 40, 50, 60], np.int64),
        "name": ["a", "b", "a", "c", "b", "d"],
    })
    t.createOrReplaceTempView("t")
    d = spark.createDataFrame({
        "k": np.array([1, 2, 4], np.int64),
        "label": ["one", "two", "four"],
    })
    d.createOrReplaceTempView("d")
    yield spark
    spark.catalog.drop("t")
    spark.catalog.drop("d")


# -- expression parsing ------------------------------------------------------

def test_precedence():
    e = parse_expression("1 + 2 * 3")
    assert repr(e) == "(1 + (2 * 3))"


def test_comparison_and_logic():
    e = parse_expression("a > 1 AND b <= 2 OR NOT c = 3")
    r = repr(e)
    assert "&" in r or "|" in r.lower() or "OR" in r or "or" in r


def test_parse_errors():
    with pytest.raises(ParseException):
        parse_expression("1 +")
    with pytest.raises(ParseException):
        parse_expression("foo(")
    with pytest.raises(ParseException):
        parse_query("SELECT FROM t")
    # unknown function names PARSE (they may be registered UDFs) and fail
    # at analysis instead (FunctionRegistry lookup)
    from spark_tpu.sql.udf import UnresolvedFunction
    e = parse_expression("nosuchfunction(x)")
    assert isinstance(e, UnresolvedFunction)


def test_case_when_searched(tables):
    out = rows(tables.sql(
        "SELECT k, CASE WHEN v >= 40 THEN 'big' ELSE 'small' END AS size "
        "FROM t ORDER BY v"))
    assert out[0] == (1, "small") and out[-1] == (1, "big")


def test_case_when_simple(tables):
    out = rows(tables.sql(
        "SELECT CASE k WHEN 1 THEN 'one' WHEN 2 THEN 'two' ELSE 'many' END "
        "AS w FROM t ORDER BY v LIMIT 3"))
    assert [r[0] for r in out] == ["one", "two", "one"]


def test_cast_and_literals(tables):
    out = rows(tables.sql("SELECT CAST(v AS double) / 4 AS q FROM t ORDER BY v LIMIT 1"))
    assert out[0][0] == pytest.approx(2.5)


def test_select_without_from(spark):
    assert rows(spark.sql("SELECT 1 + 1 AS two, 'x' AS s")) == [(2, "x")]


# -- query shapes ------------------------------------------------------------

def test_select_star(tables):
    assert len(rows(tables.sql("SELECT * FROM t"))) == 6


def test_where_order_limit(tables):
    out = rows(tables.sql(
        "SELECT v FROM t WHERE k = 1 ORDER BY v DESC LIMIT 2"))
    assert out == [(60,), (30,)]


def test_group_by_having(tables):
    out = sorted_rows(tables.sql(
        "SELECT k, sum(v) AS s, count(*) AS c FROM t "
        "GROUP BY k HAVING count(*) > 1 ORDER BY k"))
    assert out == [(1, 100, 3), (2, 70, 2)]


def test_group_by_ordinal(tables):
    out = sorted_rows(tables.sql("SELECT k, sum(v) FROM t GROUP BY 1"))
    assert out == [(1, 100), (2, 70), (3, 40)]


def test_global_agg(tables):
    assert rows(tables.sql("SELECT sum(v) AS s, max(v) AS m FROM t")) == [(210, 60)]


def test_post_agg_arithmetic(tables):
    out = rows(tables.sql(
        "SELECT k, sum(v) / count(v) AS avg_v FROM t GROUP BY k ORDER BY k"))
    assert [r[1] for r in out] == [pytest.approx(100 / 3), 35, 40]


def test_count_distinct(tables):
    assert rows(tables.sql("SELECT count(DISTINCT name) AS c FROM t")) == [(4,)]


def test_select_distinct(tables):
    assert len(rows(tables.sql("SELECT DISTINCT k FROM t"))) == 3


def test_join_on_qualified(tables):
    out = sorted_rows(tables.sql(
        "SELECT t.v, d.label FROM t JOIN d ON t.k = d.k WHERE t.v >= 30 "
        "ORDER BY t.v"))
    assert out == [(30, "one"), (50, "two"), (60, "one")]


def test_join_using(tables):
    out = tables.sql("SELECT k, v, label FROM t JOIN d USING (k)")
    assert len(rows(out)) == 5


def test_left_join(tables):
    out = tables.sql(
        "SELECT t.k, d.label FROM t LEFT JOIN d ON t.k = d.k WHERE t.k = 3")
    assert rows(out) == [(3, None)]


def test_subquery_alias(tables):
    out = rows(tables.sql(
        "SELECT s.k, s.s FROM (SELECT k, sum(v) AS s FROM t GROUP BY k) s "
        "WHERE s.s > 50 ORDER BY s.k"))
    assert out == [(1, 100), (2, 70)]


def test_with_cte(tables):
    out = rows(tables.sql(
        "WITH agg AS (SELECT k, sum(v) AS s FROM t GROUP BY k) "
        "SELECT k FROM agg WHERE s = 70"))
    assert out == [(2,)]


def test_union_all(tables):
    assert len(rows(tables.sql(
        "SELECT k FROM t UNION ALL SELECT k FROM d"))) == 9


def test_union_distinct(tables):
    assert len(rows(tables.sql(
        "SELECT k FROM t UNION SELECT k FROM d"))) == 4


def test_in_between_like(tables):
    assert len(rows(tables.sql("SELECT * FROM t WHERE k IN (1, 3)"))) == 4
    assert len(rows(tables.sql("SELECT * FROM t WHERE v BETWEEN 20 AND 40"))) == 3
    assert len(rows(tables.sql("SELECT * FROM t WHERE name LIKE 'a%'"))) == 2
    assert len(rows(tables.sql("SELECT * FROM t WHERE name NOT LIKE 'a%'"))) == 4


def test_is_null(tables):
    out = tables.sql("SELECT t.k FROM t LEFT JOIN d ON t.k = d.k "
                     "WHERE d.label IS NULL")
    assert rows(out) == [(3,)]


def test_string_functions(tables):
    out = rows(tables.sql(
        "SELECT upper(name) AS u, length(name) AS l FROM t ORDER BY v LIMIT 1"))
    assert out == [("A", 1)]


def test_sql_matches_dataframe_api(tables):
    from spark_tpu.sql import functions as F
    t = tables.table("t")
    api = t.filter(t["v"] > 15).groupBy("k").agg(F.sum("v").alias("s")) \
        .orderBy("k")
    sql = tables.sql(
        "SELECT k, sum(v) AS s FROM t WHERE v > 15 GROUP BY k ORDER BY k")
    assert rows(api) == rows(sql)


# -- commands ----------------------------------------------------------------

def test_create_drop_view(spark):
    spark.createDataFrame({"x": [1, 2, 3]}).createOrReplaceTempView("cv_base")
    spark.sql("CREATE OR REPLACE TEMP VIEW cv AS SELECT x * 2 AS y FROM cv_base")
    assert sorted_rows(spark.sql("SELECT y FROM cv")) == [(2,), (4,), (6,)]
    spark.sql("DROP VIEW cv")
    with pytest.raises(AnalysisException):
        spark.sql("SELECT * FROM cv").collect()
    spark.sql("DROP VIEW IF EXISTS cv")   # no error
    with pytest.raises(AnalysisException):
        spark.sql("DROP VIEW cv")
    spark.catalog.drop("cv_base")


def test_show_tables_describe(spark):
    spark.createDataFrame({"x": [1]}).createOrReplaceTempView("stv")
    names = [r[0] for r in spark.sql("SHOW TABLES").collect()]
    assert "stv" in names
    desc = rows(spark.sql("DESCRIBE stv"))
    assert desc[0][0] == "x"
    spark.catalog.drop("stv")


def test_set_command(spark):
    spark.sql("SET spark.tpu.test.flag=17")
    assert spark.conf.get("spark.tpu.test.flag") == "17"


def test_set_command_raw_value(spark):
    spark.sql("SET spark.tpu.test.path=/a:b;c{d}$e")
    assert spark.conf.get("spark.tpu.test.path") == "/a:b;c{d}$e"


def test_explain(tables):
    out = rows(tables.sql("EXPLAIN SELECT k FROM t"))
    assert "Physical Plan" in out[0][0]
    out = rows(tables.sql("EXPLAIN EXTENDED SELECT k FROM t"))
    assert out[0][0]


# -- code-review regression cases -------------------------------------------

def test_order_limit_applies_to_whole_union(tables):
    out = rows(tables.sql(
        "SELECT v FROM t WHERE k = 1 UNION ALL SELECT v FROM t WHERE k = 2 "
        "ORDER BY v DESC LIMIT 2"))
    assert out == [(60,), (50,)]


def test_qualified_star_over_join(tables):
    df = tables.sql("SELECT t.* FROM t JOIN d ON t.k = d.k")
    assert len(df.columns) == 3          # only t's columns
    assert len(rows(df)) == 5


def test_qualified_star_overlapping_join(tables):
    df = tables.sql("SELECT d.* FROM t JOIN d ON t.k = d.k")
    assert len(df.columns) == 2
    assert set(df.columns) >= {"label"}


def test_null_safe_equality(spark):
    out = rows(spark.sql("SELECT NULL <=> NULL AS a, 1 <=> NULL AS b, "
                         "1 <=> 1 AS c, 1 <=> 2 AS d"))
    assert out == [(True, False, True, False)]


def test_count_null_literal(tables):
    out = rows(tables.sql("SELECT count(NULL) AS n, count(1) AS m FROM t"))
    assert out == [(0, 6)]


def test_range_table_function(spark):
    out = rows(spark.sql("SELECT id * 2 AS x FROM range(2, 5)"))
    assert out == [(4,), (6,), (8,)]


def test_rollup_cube_grouping_sets(spark):
    spark.sql("SELECT 1 AS a, 10 AS b, 5 AS v UNION ALL SELECT 1, 20, 7 "
              "UNION ALL SELECT 2, 10, 1").createOrReplaceTempView("gs_t")
    r = spark.sql("SELECT a, b, SUM(v) AS s, grouping(a) AS ga, "
                  "grouping_id() AS gid FROM gs_t GROUP BY ROLLUP(a, b) "
                  "ORDER BY a NULLS LAST, b NULLS LAST").collect()
    rows = [(x["a"], x["b"], x["s"], x["ga"], x["gid"]) for x in r]
    assert (1, 10, 5, 0, 0) in rows
    assert (1, None, 12, 0, 1) in rows
    assert (None, None, 13, 1, 3) in rows
    # SUM over the rolled-up key keeps ORIGINAL values (review find)
    r2 = spark.sql("SELECT a, SUM(a) AS s FROM gs_t GROUP BY ROLLUP(a) "
                   "ORDER BY a NULLS LAST").collect()
    assert r2[-1]["a"] is None and r2[-1]["s"] == 4
    # CUBE produces all subsets
    r3 = spark.sql("SELECT a, b, COUNT(*) AS c FROM gs_t GROUP BY CUBE(a, b)"
                   ).collect()
    assert len(r3) == 3 + 2 + 2 + 1
    # explicit GROUPING SETS
    r4 = spark.sql("SELECT a, SUM(v) AS s FROM gs_t "
                   "GROUP BY GROUPING SETS ((a), ()) ORDER BY a NULLS LAST"
                   ).collect()
    assert [(x["a"], x["s"]) for x in r4] == [(1, 12), (2, 1), (None, 13)]
