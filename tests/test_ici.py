"""The ICI device-exchange tier (spark_tpu.parallel.ici).

Three rings, innermost out:

* pure units — ``probe_topology`` (the replica-deterministic tier
  split), ``plan_side`` (agreed-inputs activation), ``schema_eligible``
  (the dictionary pin), and a numpy-only pack→transpose→unpack
  round-trip that models exactly what the all-to-all does to the slots;
* a FORCED multi-device CPU mesh (``--xla_force_host_platform_device_
  count``, so a subprocess): ``local_device_exchange`` moves real
  buckets through the real shard_map collective and must return every
  span byte-identical, runs and masks intact, with the second exchange
  of the same shape a StageCache HIT;
* two REAL processes (worker mode ``ici`` from shuffled_join_worker):
  the full parity battery with the tier armed — dict-coded queries stay
  pinned to the host tier, dict-free queries genuinely attempt the
  device tier on BOTH lanes and (no cross-process device world on CPU)
  fold back structured, every result byte-identical to the oracle.

The fault matrix for this tier (injected ``ici_unavailable``, death at
the copy point) lives in chaos_matrix.py like every other fault kind.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_tpu import types as T  # noqa: E402
from spark_tpu.columnar import ColumnBatch, ColumnVector  # noqa: E402
from spark_tpu.parallel import ici  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "shuffled_join_worker.py")


# ---------------------------------------------------------------------------
# probe_topology: the replica-deterministic tier split
# ---------------------------------------------------------------------------

def test_probe_cpu_world_is_all_singletons():
    # no override + single-process jax world (the CPU test reality):
    # every pid is host-tier-only
    t = ici.probe_topology("", 0, 3, [0, 1, 2])
    assert t.domains == ((0,), (1,), (2,))
    assert t.peers() == []


def test_probe_override_groups_and_singleton_rest():
    t = ici.probe_topology("0,1|2,3", 1, 5, [0, 1, 2, 3, 4])
    assert t.domains == ((0, 1), (2, 3), (4,))
    assert t.domain() == (0, 1)
    assert t.peers() == [0]
    assert t.same_domain(0) and not t.same_domain(2)


def test_probe_override_drops_dead_and_out_of_range():
    # pid 9 is out of [0, n); pid 2 is dead → both silently dropped and
    # the dead pid does NOT reappear as a singleton (it is not live)
    t = ici.probe_topology("0,1,9|2", 0, 4, [0, 1, 3])
    assert t.domains == ((0, 1), (3,))


def test_probe_override_duplicate_keeps_first_group():
    t = ici.probe_topology("0,1|1,2", 2, 3, [0, 1, 2])
    assert t.domains == ((0, 1), (2,))
    assert t.peers() == []


def test_probe_malformed_override_degrades_to_singletons():
    # misconfiguration must degrade (host tier everywhere), never abort
    t = ici.probe_topology("0,banana|2", 0, 3, [0, 1, 2])
    assert t.domains == ((0,), (1,), (2,))


def test_probe_fingerprint_identical_across_replicas():
    # the property decision_inputs relies on: every pid derives the
    # SAME fingerprint from the same replicated inputs
    fps = {tuple(ici.probe_topology("1,0|3,2", p, 4, [0, 1, 2, 3])
                 .fingerprint()) for p in range(4)}
    assert fps == {("0,1", "2,3")}


# ---------------------------------------------------------------------------
# plan_side: agreed-inputs activation
# ---------------------------------------------------------------------------

def _mans(l_bytes, l_rows, r_bytes=0, r_rows=0):
    # one plan-round manifest per process, halving the side between them
    return {0: {"sides": {"l": [l_bytes // 2, l_rows],
                          "r": [r_bytes // 2, r_rows]}},
            1: {"sides": {"l": [l_bytes - l_bytes // 2, l_rows // 2],
                          "r": [r_bytes - r_bytes // 2, r_rows]}}}


def test_plan_side_requires_a_tier_with_peers():
    assert ici.plan_side(None, _mans(1 << 20, 100), "l", 0) is None
    solo = ici.probe_topology("", 0, 2, [0, 1])     # all singletons
    assert ici.plan_side(solo, _mans(1 << 20, 100), "l", 0) is None


def test_plan_side_byte_floor_and_pow2_capacity():
    tier = ici.probe_topology("0,1", 0, 2, [0, 1])
    p = ici.plan_side(tier, _mans(4096, 100), "l", 65536)
    assert p is not None and not p.active          # below the floor
    p = ici.plan_side(tier, _mans(70000, 100), "l", 65536, max_runs=7)
    assert p.active and p.agreed_bytes == 70000
    assert p.cap_rows == 128 and p.max_runs == 7   # pow2(max over procs)


def test_plan_side_zero_rows_never_activates():
    tier = ici.probe_topology("0,1", 0, 2, [0, 1])
    p = ici.plan_side(tier, _mans(1 << 20, 0), "l", 0)
    assert p is not None and not p.active


# ---------------------------------------------------------------------------
# schema gate + pack/unpack round-trip (numpy only — models the a2a's
# slot transpose without a device world)
# ---------------------------------------------------------------------------

def _batch(vals, valid=None, row_valid=None, dictionary=None):
    data = np.asarray(vals, np.int64)
    vec = ColumnVector(data, T.LongType(), valid, dictionary)
    return ColumnBatch(["k"], [vec], row_valid, len(data))


def test_schema_eligible_pins_dictionary_columns():
    assert ici.schema_eligible(_batch([1, 2]))
    assert not ici.schema_eligible(_batch([0, 1], dictionary=("a", "b")))
    assert not ici.schema_eligible(None)


def test_pack_transpose_unpack_round_trip():
    members = [0, 1, 2]
    # sender → receiver → runs (run boundaries must survive)
    outboxes = [
        {1: [_batch([10, 11]), _batch([12])], 2: [_batch([13])]},
        {0: [_batch([20], valid=[np.array([False])][0])],
         2: [_batch([21, 22, 23])]},
        {0: [], 1: [_batch([30, 31],
                           row_valid=np.array([True, False]))]},
    ]
    tpl = _batch([0])
    packs = [ici._pack_outbox(ob, members, tpl, cap=4, max_runs=2)
             for ob in outboxes]
    # the all-to-all's observable: receiver r's slot s = sender s's slot r
    for r in members:
        names = packs[0][0]
        cols = [np.stack([packs[s][1][0][r] for s in members])]
        masks = [np.stack([packs[s][2][0][r] for s in members])]
        rowv = np.stack([packs[s][3][r] for s in members])
        runl = np.stack([packs[s][4][r] for s in members])
        inbox = ici._unpack_inbox(names, tpl, cols, masks, rowv, runl,
                                  members, self_pid=r)
        for s in members:
            want = [b for b in (outboxes[s].get(r) or [])
                    if b.capacity > 0]
            if s == r or not want:
                assert s not in inbox
                continue
            got = inbox[s]
            assert len(got) == len(want)           # run boundaries kept
            for gb, wb in zip(got, want):
                assert gb.capacity == wb.capacity
                np.testing.assert_array_equal(gb.vectors[0].data,
                                              wb.vectors[0].data)
                gv, wv = gb.vectors[0].valid, wb.vectors[0].valid
                assert (gv is None) == (wv is None)
                if wv is not None:
                    np.testing.assert_array_equal(gv, wv)
                assert (gb.row_valid is None) == (wb.row_valid is None)
                if wb.row_valid is not None:
                    np.testing.assert_array_equal(gb.row_valid,
                                                  wb.row_valid)


def test_pack_overflow_degrades_structured():
    tpl = _batch([0])
    with pytest.raises(ici.IciUnavailable):
        ici._pack_outbox({1: [_batch([1, 2, 3])]}, [0, 1], tpl,
                         cap=2, max_runs=2)
    with pytest.raises(ici.IciUnavailable):
        ici._pack_outbox({1: [_batch([1]), _batch([2])]}, [0, 1], tpl,
                         cap=8, max_runs=1)


# ---------------------------------------------------------------------------
# the real collective on a forced multi-device CPU mesh (subprocess:
# XLA_FLAGS must be set before jax initializes)
# ---------------------------------------------------------------------------

_MESH_SCRIPT = textwrap.dedent("""
    import numpy as np
    from spark_tpu import types as T
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.parallel import ici
    from spark_tpu.sql.stagecompile import stage_cache

    def batch(vals, valid=None, row_valid=None):
        data = np.asarray(vals, np.int64)
        return ColumnBatch(["k"], [ColumnVector(data, T.LongType(),
                                                valid, None)],
                           row_valid, len(data))

    rng = np.random.default_rng(11)
    n = 4
    outboxes = []
    for s in range(n):
        ob = {}
        for r in range(n):
            runs = []
            for _ in range(int(rng.integers(0, 3))):
                m = int(rng.integers(1, 9))
                vals = rng.integers(-99, 99, m)
                valid = (rng.random(m) < 0.8) if m % 2 else None
                runs.append(batch(vals, valid))
            ob[r] = runs
        outboxes.append(ob)
    tpl = batch([0])

    cache = stage_cache(None)
    inboxes = ici.local_device_exchange(outboxes, tpl, max_runs=2)
    assert cache.misses >= 1
    h0 = cache.hits
    again = ici.local_device_exchange(outboxes, tpl, max_runs=2)
    assert cache.hits > h0, "same shape must be a StageCache HIT"

    for got in (inboxes, again):
        for r in range(n):
            for s in range(n):
                want = [b for b in outboxes[s][r] if b.capacity > 0]
                if not want:
                    assert s not in got[r] or s == r
                    continue
                runs = got[r][s]
                assert len(runs) == len(want)
                for gb, wb in zip(runs, want):
                    np.testing.assert_array_equal(
                        gb.vectors[0].data, wb.vectors[0].data)
                    # None == all-true: the unpack canonicalizes an
                    # all-true mask back to None, so compare effective
                    m = wb.capacity
                    gv, wv = gb.vectors[0].valid, wb.vectors[0].valid
                    gm = np.ones(m, bool) if gv is None else gv
                    wm = np.ones(m, bool) if wv is None else wv
                    np.testing.assert_array_equal(gm, wm)
    print("MESH-PARITY-OK")
""")


def test_local_device_exchange_mesh_parity(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=os.path.join(HERE, ".."))
    p = subprocess.run([sys.executable, "-c", _MESH_SCRIPT], env=env,
                       capture_output=True, text=True, timeout=240)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "MESH-PARITY-OK" in p.stdout, p.stdout + p.stderr


def test_local_device_exchange_needs_enough_devices():
    # in-process jax world: default CPU has one device — structured
    with pytest.raises(ici.IciUnavailable):
        ici.local_device_exchange([{}, {}, {}, {}, {}, {}, {}, {}, {}],
                                  _batch([0]))


# ---------------------------------------------------------------------------
# two REAL processes: the armed tier against the full battery
# ---------------------------------------------------------------------------

def _run_ici_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "ici",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        # dict-coded battery pinned to host, still byte-identical
        assert f"[p{pid}] ALL-OK" in out, out
        # dict-free queries attempted the device tier on both lanes and
        # every attempt folded back structured (CPU: no spanning world)
        assert f"[p{pid}] ICI-FALLBACK-OK" in out, out
        assert out.count(f"[p{pid}] ICI-PARITY-OK") == 3, out
    return outs


def test_ici_parity_two_processes(tmp_path):
    _run_ici_parity(tmp_path, 2)


@pytest.mark.slow
def test_ici_parity_three_processes(tmp_path):
    _run_ici_parity(tmp_path, 3)
