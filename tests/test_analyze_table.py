"""ANALYZE TABLE … COMPUTE STATISTICS — CBO stats for footer-less formats.

Reference: `sql/core/.../command/AnalyzeTableCommand.scala` and
`AnalyzeColumnCommand.scala` (stats into the metastore, consumed by
`statsEstimation/`).  Here: row count + per-column min/max/null/NDV
gathered through the engine's own scan, registered for the optimizer's
selectivity/NDV probes (parquet keeps its exact footer path), persisted
into catalog tables' _meta.json."""

import os
import sqlite3

import numpy as np
import pandas as pd
import pytest

from spark_tpu import io as tio
from spark_tpu.expressions import AnalysisException


@pytest.fixture()
def csv_view(spark, tmp_path):
    pdf = pd.DataFrame({
        "k": np.arange(500, dtype=np.int64) % 40,
        "v": np.arange(500, dtype=np.int64) * 3,
    })
    d = tmp_path / "t.csv"
    d.mkdir()
    pdf.to_csv(d / "part-0.csv", index=False)
    df = (spark.read.option("header", "true")
          .option("inferschema", "true").csv(str(d)))
    df.createOrReplaceTempView("analyze_me")
    return df, pdf


def test_analyze_collects_and_registers(spark, csv_view):
    df, pdf = csv_view
    out = spark.sql(
        "ANALYZE TABLE analyze_me COMPUTE STATISTICS FOR ALL COLUMNS"
    ).collect()
    assert out[0]["rows"] == "500"
    rel = df._plan
    from spark_tpu.sql.logical import SubqueryAlias
    while isinstance(rel, SubqueryAlias):
        rel = rel.children[0]
    st = tio.analyzed_stats(rel)
    assert st["rows"] == 500
    assert st["columns"]["k"]["min"] == 0
    assert st["columns"]["k"]["max"] == 39
    assert st["columns"]["v"]["max"] == 499 * 3
    assert st["columns"]["k"]["null_count"] == 0
    assert abs(st["columns"]["k"]["ndv"] - 40) <= 4       # approx
    # the optimizer's stats probes now see the csv relation
    assert tio.file_column_stats(rel)["k"]["max"] == 39
    assert 30 <= tio.file_column_ndv(rel, ["k"])["k"] <= 50
    assert tio.file_row_count(rel) == 500


def test_analyze_specific_columns(spark, csv_view):
    df, _ = csv_view
    spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS FOR COLUMNS k")
    rel = df._plan
    from spark_tpu.sql.logical import SubqueryAlias
    while isinstance(rel, SubqueryAlias):
        rel = rel.children[0]
    st = tio.analyzed_stats(rel)
    assert list(st["columns"]) == ["k"]
    with pytest.raises(AnalysisException, match="no such column"):
        spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS "
                  "FOR COLUMNS nope")


def test_analyze_persists_with_catalog_table(spark, tmp_path):
    pdf = pd.DataFrame({"a": np.arange(100, dtype=np.int64)})
    df = spark.createDataFrame(pdf)
    spark.catalog.save_table("an_tbl", df, fmt="csv", mode="overwrite",
                             options={"header": "true",
                                      "inferschema": "true"})
    out = spark.sql("ANALYZE TABLE an_tbl COMPUTE STATISTICS "
                    "FOR ALL COLUMNS").collect()
    assert out[0]["persisted"] == "true"
    # a fresh lookup (fresh stats registry) re-registers from _meta.json
    tio._ANALYZED_STATS.clear()
    rel = spark.catalog.lookup("an_tbl")
    st = tio.analyzed_stats(rel)
    assert st is not None and st["rows"] == 100
    assert st["columns"]["a"]["max"] == 99
    spark.catalog.drop_table("an_tbl")


def test_analyze_jdbc_relation(spark, tmp_path):
    db = tmp_path / "an.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (x INTEGER)")
    conn.executemany("INSERT INTO t VALUES (?)",
                     [(i,) for i in range(200)])
    conn.commit(); conn.close()
    spark.read.jdbc(f"jdbc:sqlite:{db}", "t").createOrReplaceTempView(
        "jdbc_t")
    spark.sql("ANALYZE TABLE jdbc_t COMPUTE STATISTICS FOR ALL COLUMNS")
    rel = spark.catalog.lookup("jdbc_t")
    from spark_tpu.sql.logical import SubqueryAlias
    while isinstance(rel, SubqueryAlias):
        rel = rel.children[0]
    st = tio.analyzed_stats(rel)
    assert st["rows"] == 200 and st["columns"]["x"]["max"] == 199
    assert tio.file_column_stats(rel)["x"]["min"] == 0


def test_stale_stats_dropped_on_file_change(spark, tmp_path):
    pdf = pd.DataFrame({"a": np.arange(50, dtype=np.int64)})
    df = spark.createDataFrame(pdf)
    spark.catalog.save_table("an_stale", df, fmt="csv", mode="overwrite",
                             options={"header": "true",
                                      "inferschema": "true"})
    spark.sql("ANALYZE TABLE an_stale COMPUTE STATISTICS FOR ALL COLUMNS")
    # append more data AFTER analyze: files (and mtimes) change
    import time
    time.sleep(0.05)
    spark.createDataFrame(
        pd.DataFrame({"a": np.arange(50, 500, dtype=np.int64)})
    ).write.mode("append").option("header", "true").format("csv").save(
        spark.catalog.table_path("an_stale"))
    tio._ANALYZED_STATS.clear()
    rel = spark.catalog.lookup("an_stale")
    assert tio.analyzed_stats(rel) is None, \
        "stale ANALYZE stats must not be re-registered after file changes"
    spark.catalog.drop_table("an_stale")


def test_rows_only_refresh_preserves_column_stats(spark, csv_view):
    df, _ = csv_view
    spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS FOR COLUMNS k")
    spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS")
    rel = spark.catalog.lookup("analyze_me")
    from spark_tpu.sql.logical import SubqueryAlias
    while isinstance(rel, SubqueryAlias):
        rel = rel.children[0]
    st = tio.analyzed_stats(rel)
    assert st["rows"] == 500 and "k" in st["columns"]


def test_shadow_view_does_not_persist_into_table(spark, tmp_path):
    spark.catalog.save_table(
        "an_shadow", spark.createDataFrame(
            pd.DataFrame({"a": np.arange(10, dtype=np.int64)})),
        fmt="csv", mode="overwrite",
        options={"header": "true", "inferschema": "true"})
    # a temp view SHADOWS the table with different data
    pdf = pd.DataFrame({"z": np.arange(7, dtype=np.int64)})
    d = tmp_path / "shadow.csv"
    d.mkdir()
    pdf.to_csv(d / "p.csv", index=False)
    (spark.read.option("header", "true").option("inferschema", "true")
     .csv(str(d)).createOrReplaceTempView("an_shadow"))
    out = spark.sql("ANALYZE TABLE an_shadow COMPUTE STATISTICS "
                    "FOR ALL COLUMNS").collect()
    assert out[0]["persisted"] == "false"
    import json
    meta = json.load(open(os.path.join(
        spark.catalog.table_path("an_shadow"), "_meta.json")))
    assert "stats" not in meta
    spark.catalog.drop("an_shadow")
    spark.catalog.drop_table("an_shadow")


def test_describe_extended_shows_stats(spark, csv_view):
    spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS FOR ALL COLUMNS")
    rows = spark.sql("DESCRIBE EXTENDED analyze_me").collect()
    by_name = {r["col_name"]: r["comment"] for r in rows}
    assert by_name["# rows"] == "500"
    assert "min=0" in by_name["k"] and "max=39" in by_name["k"]
    plain = spark.sql("DESCRIBE analyze_me").collect()
    assert all(r["comment"] == "" for r in plain)


def test_describe_table_extended_order(spark, csv_view):
    """Both DESCRIBE EXTENDED t and DESCRIBE TABLE EXTENDED t parse."""
    spark.sql("ANALYZE TABLE analyze_me COMPUTE STATISTICS")
    for stmt in ("DESCRIBE EXTENDED analyze_me",
                 "DESCRIBE TABLE EXTENDED analyze_me"):
        rows = spark.sql(stmt).collect()
        assert rows[-1]["col_name"] == "# rows"
