"""ALL 99 TPC-DS queries over PARQUET-backed tables with streamed scans.

The in-memory sweep (`test_tpcds.py`) validates query semantics; this
sweep re-runs every query with the fact tables as parquet files and the
scan batch size forced below their row counts, so each query routes
through pruning/pushdown and — where its shape allows — the out-of-core
stage runner (grace joins, broadcast-fused streams), all against the
same sqlite oracle.

Runtime is several times the in-memory sweep, so the full run is gated:

    SPARK_TPU_FILE_SWEEP=1 python -m pytest tests/test_tpcds_filebacked.py

Ungated, a fixed smoke subset (the streamed-shape representatives) runs
in the suite.
"""

import math
import os
import re
import sqlite3

import numpy as np
import pytest

import spark_tpu.config as C
from spark_tpu.tpcds import ORACLE_OVERRIDES, QUERIES, RUNNABLE, generate

SF_ROWS = 20_000
BATCH = 4096            # facts stream in ~5 batches

FULL = os.environ.get("SPARK_TPU_FILE_SWEEP", "") == "1"
SMOKE = ["q3", "q7", "q17", "q19", "q25", "q42", "q52", "q55", "q68",
         "q79", "q96", "q98"]
SWEEP = RUNNABLE if FULL else SMOKE

FACTS = {"store_sales", "catalog_sales", "web_sales", "store_returns",
         "catalog_returns", "web_returns", "inventory"}


def _sqlite_text(sql: str) -> str:
    return re.sub(
        r"STDDEV_SAMP\((\w+)\)",
        r"(CASE WHEN count(\1) > 1 THEN "
        r"sqrt(max(sum(\1*\1*1.0) - count(\1)*avg(\1)*avg(\1), 0)"
        r" / (count(\1) - 1)) ELSE NULL END)",
        sql, flags=re.IGNORECASE)


@pytest.fixture(scope="module")
def fb(spark, tmp_path_factory):
    tables = generate(SF_ROWS)
    base = tmp_path_factory.mktemp("tpcds_fb")
    for name, pdf in tables.items():
        if name in FACTS:
            d = base / name
            os.makedirs(d)
            parts = 3
            step = (len(pdf) + parts - 1) // parts
            for i in range(parts):
                pdf.iloc[i * step:(i + 1) * step].to_parquet(
                    d / f"part-{i:03d}.parquet", index=False)
            spark.read.parquet(str(d)).createOrReplaceTempView(name)
        else:
            spark.createDataFrame(pdf).createOrReplaceTempView(name)
    con = sqlite3.connect(":memory:")
    for name, pdf in tables.items():
        pdf.to_sql(name, con, index=False)
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    yield spark, con
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))
    con.close()
    for name in tables:
        spark.catalog.dropTempView(name)


def _norm(v):
    if v is None:
        return None
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return str(v)


def _key(row):
    return tuple("\0" if x is None else str(x) for x in row)


@pytest.mark.parametrize("qname", SWEEP)
def test_filebacked_query(fb, qname):
    spark, con = fb
    sql = QUERIES[qname]
    got = [tuple(r) for r in spark.sql(sql).collect()]
    oracle_sql = ORACLE_OVERRIDES.get(qname, sql)
    exp = con.execute(_sqlite_text(oracle_sql)).fetchall()
    assert exp, f"{qname}: oracle returned no rows"
    got = sorted((tuple(_norm(v) for v in r) for r in got), key=_key)
    exp = sorted((tuple(_norm(v) for v in r) for r in exp), key=_key)
    assert len(got) == len(exp), \
        f"{qname}: {len(got)} rows != oracle {len(exp)}"
    for i, (g, e) in enumerate(zip(got, exp)):
        for j, (a, b) in enumerate(zip(g, e)):
            if isinstance(a, float) and isinstance(b, float):
                assert math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6), \
                    f"{qname} row {i} col {j}: {a} != {b}"
            else:
                assert a == b, f"{qname} row {i} col {j}: {a!r} != {b!r}"
