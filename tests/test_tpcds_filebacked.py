"""ALL 99 TPC-DS queries over PARQUET-backed tables with streamed scans.

The in-memory sweep (`test_tpcds.py`) validates query semantics; this
sweep re-runs every query with the fact tables as parquet files and the
scan batch size forced below their row counts, so each query routes
through pruning/pushdown and — where its shape allows — the out-of-core
stage runner (grace joins, broadcast-fused streams), all against the
same sqlite oracle.

Runtime is several times the in-memory sweep, so the full run is gated:

    SPARK_TPU_FILE_SWEEP=1 python -m pytest tests/test_tpcds_filebacked.py

Ungated, a fixed smoke subset (the streamed-shape representatives) runs
in the suite.
"""

import math
import os
import sqlite3

import pytest

import spark_tpu.config as C
from spark_tpu.tpcds import ORACLE_OVERRIDES, QUERIES, RUNNABLE, generate
from spark_tpu.tpcds.oracle import (FACT_TABLES as FACTS,
                                    norm_value as _norm, row_key as _key,
                                    sqlite_text as _sqlite_text)

SF_ROWS = 20_000
BATCH = 4096            # facts stream in ~5 batches

FULL = os.environ.get("SPARK_TPU_FILE_SWEEP", "") == "1"
SMOKE = ["q3", "q7", "q17", "q19", "q23", "q25", "q42", "q52", "q55",
         "q68", "q79", "q96", "q98"]   # q23: empty-streamed-union shape
SWEEP = RUNNABLE if FULL else SMOKE

@pytest.fixture(scope="module")
def fb(spark, tmp_path_factory):
    tables = generate(SF_ROWS)
    base = tmp_path_factory.mktemp("tpcds_fb")
    for name, pdf in tables.items():
        if name in FACTS:
            d = base / name
            os.makedirs(d)
            parts = 3
            step = (len(pdf) + parts - 1) // parts
            for i in range(parts):
                pdf.iloc[i * step:(i + 1) * step].to_parquet(
                    d / f"part-{i:03d}.parquet", index=False)
            spark.read.parquet(str(d)).createOrReplaceTempView(name)
        else:
            spark.createDataFrame(pdf).createOrReplaceTempView(name)
    con = sqlite3.connect(":memory:")
    for name, pdf in tables.items():
        pdf.to_sql(name, con, index=False)
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    yield spark, con
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))
    con.close()
    for name in tables:
        spark.catalog.dropTempView(name)


@pytest.mark.parametrize("qname", SWEEP)
def test_filebacked_query(fb, qname):
    spark, con = fb
    sql = QUERIES[qname]
    got = [tuple(r) for r in spark.sql(sql).collect()]
    oracle_sql = ORACLE_OVERRIDES.get(qname, sql)
    exp = con.execute(_sqlite_text(oracle_sql)).fetchall()
    assert exp, f"{qname}: oracle returned no rows"
    got = sorted((tuple(_norm(v) for v in r) for r in got), key=_key)
    exp = sorted((tuple(_norm(v) for v in r) for r in exp), key=_key)
    assert len(got) == len(exp), \
        f"{qname}: {len(got)} rows != oracle {len(exp)}"
    for i, (g, e) in enumerate(zip(got, exp)):
        for j, (a, b) in enumerate(zip(g, e)):
            if isinstance(a, float) and isinstance(b, float):
                assert math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6), \
                    f"{qname} row {i} col {j}: {a} != {b}"
            else:
                assert a == b, f"{qname} row {i} col {j}: {a!r} != {b!r}"
