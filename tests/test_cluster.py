"""Multi-host runtime: topology, hybrid mesh, heartbeat failure detection.

Heartbeats use a controllable clock — no sleeps, no flakes.
"""
import numpy as np

from spark_tpu import config as C
from spark_tpu.parallel.cluster import (
    ClusterInfo, HeartbeatMonitor, hybrid_mesh, init_cluster,
)


def test_cluster_info_single_process():
    info = init_cluster()
    assert info.process_count == 1
    assert info.process_index == 0
    assert len(info.global_devices) >= 1
    assert "process 0/1" in repr(info)


def test_hybrid_mesh_axes():
    mesh = hybrid_mesh()
    assert mesh.axis_names == ("dcn", "data")
    assert mesh.devices.shape[0] == 1          # single controller
    # sharding over both axes composes
    from jax.sharding import NamedSharding, PartitionSpec
    s = NamedSharding(mesh, PartitionSpec(("dcn", "data")))
    assert s is not None


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _monitor(tmp_path, host, clock):
    conf = C.Conf()
    conf.set("spark.tpu.cluster.heartbeatTimeoutMs", "5000")
    return HeartbeatMonitor(str(tmp_path), host_id=host, conf=conf,
                            clock=clock)


def test_heartbeat_detects_dead_host(tmp_path):
    clock = _Clock()
    a = _monitor(tmp_path, "host-a", clock)
    b = _monitor(tmp_path, "host-b", clock)
    a.beat()
    b.beat()
    assert a.dead_hosts() == []
    clock.t += 10.0              # b stops beating; 10s > 5s timeout
    a.beat()
    assert a.dead_hosts() == ["host-b"]
    # b resumes: no longer dead
    b.beat()
    assert a.dead_hosts() == []


def test_heartbeat_failure_callback_fires_once(tmp_path):
    clock = _Clock()
    a = _monitor(tmp_path, "host-a", clock)
    b = _monitor(tmp_path, "host-b", clock)
    b.beat()
    seen = []
    a.on_failure(seen.append)
    clock.t += 10.0
    a.dead_hosts()
    a.dead_hosts()               # second check: callback must NOT refire
    assert seen == ["host-b"]


def test_check_or_raise_aborts_step(tmp_path):
    import pytest
    clock = _Clock()
    a = _monitor(tmp_path, "host-a", clock)
    b = _monitor(tmp_path, "host-b", clock)
    b.beat()
    clock.t += 10.0
    with pytest.raises(RuntimeError, match="host-b"):
        a.check_or_raise()


def test_heartbeat_background_thread(tmp_path):
    import time
    conf = C.Conf()
    conf.set("spark.tpu.cluster.heartbeatIntervalMs", "20")
    m = HeartbeatMonitor(str(tmp_path), host_id="host-x", conf=conf)
    m.start()
    try:
        time.sleep(0.15)
        snap = m.snapshot()
        assert snap["host-x"]["seq"] >= 2    # beat several times
    finally:
        m.stop()


def test_hybrid_mesh_collective_compiles():
    """A shard_map psum over BOTH hybrid axes compiles and runs on the
    8-virtual-device CPU mesh — the DCN x ICI program shape multi-host
    deployments jit (scaling-book recipe: data-parallel reduce over dcn,
    all-to-all-heavy work inside ici)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        import pytest
        pytest.skip("needs multiple devices")
    from jax.sharding import Mesh
    per = len(devs) // 2
    mesh = Mesh(np.array(devs[:2 * per]).reshape(2, per), ("dcn", "data"))

    def step(x):
        local = x.sum()
        ici = jax.lax.psum(local, "data")     # intra-slice reduce
        return jax.lax.psum(ici, "dcn")       # cross-slice reduce

    x = jnp.arange(2 * per * 4, dtype=jnp.float32).reshape(2 * per, 4)
    out = jax.jit(shard_map(step, mesh=mesh,
                            in_specs=P(("dcn", "data"), None),
                            out_specs=P()))(x)
    assert float(out) == float(x.sum())
