"""Window function tests (`sql/core/.../window/` + DataFrameWindowFunctions
suite shapes).  Cross-checked against hand-computed answers and (for scans)
the numpy dual path implicitly via CPU jax."""

import numpy as np
import pytest

from spark_tpu.sql import functions as F
from spark_tpu.sql.window import Window


def rows(df):
    return [tuple(r) for r in df.collect()]


@pytest.fixture()
def sales(spark):
    return spark.createDataFrame({
        "dept": ["a", "a", "a", "b", "b", "c"],
        "emp": ["e1", "e2", "e3", "e4", "e5", "e6"],
        "salary": np.array([100, 200, 200, 50, 70, 10], np.int64),
    })


def test_row_number(sales):
    w = Window.partitionBy("dept").orderBy(F.desc("salary"))
    out = sales.select("dept", "emp", "salary",
                       F.row_number().over(w).alias("rn"))
    got = {(r[0], r[1]): r[3] for r in rows(out)}
    assert got[("a", "e1")] == 3
    assert got[("b", "e5")] == 1
    assert got[("c", "e6")] == 1
    # rows within a dept get distinct row numbers
    assert {got[("a", "e2")], got[("a", "e3")]} == {1, 2}


def test_rank_dense_rank(sales):
    w = Window.partitionBy("dept").orderBy("salary")
    out = sales.select("dept", "salary",
                       F.rank().over(w).alias("r"),
                       F.dense_rank().over(w).alias("dr"))
    a = sorted([(r[1], r[2], r[3]) for r in rows(out) if r[0] == "a"])
    # salaries 100,200,200 -> rank 1,2,2 dense 1,2,2
    assert a == [(100, 1, 1), (200, 2, 2), (200, 2, 2)]


def test_percent_rank_cume_dist(sales):
    w = Window.partitionBy("dept").orderBy("salary")
    out = sales.select("dept", "salary",
                       F.percent_rank().over(w).alias("pr"),
                       F.cume_dist().over(w).alias("cd"))
    a = sorted([(r[1], r[2], r[3]) for r in rows(out) if r[0] == "a"])
    assert a[0] == (100, 0.0, pytest.approx(1 / 3))
    assert a[1] == (200, pytest.approx(0.5), pytest.approx(1.0))


def test_lag_lead(sales):
    w = Window.partitionBy("dept").orderBy("salary")
    out = sales.select("dept", "salary",
                       F.lag("salary").over(w).alias("lg"),
                       F.lead("salary").over(w).alias("ld"))
    b = sorted([(r[1], r[2], r[3]) for r in rows(out) if r[0] == "b"])
    assert b == [(50, None, 70), (70, 50, None)]


def test_lag_default(sales):
    w = Window.partitionBy("dept").orderBy("salary")
    out = sales.select("dept", "salary",
                       F.lag("salary", 1, -1).over(w).alias("lg"))
    c = [(r[1], r[2]) for r in rows(out) if r[0] == "c"]
    assert c == [(10, -1)]


def test_running_sum(sales):
    w = Window.partitionBy("dept").orderBy("emp")
    out = sales.select("dept", "emp", F.sum("salary").over(w).alias("rs"))
    a = sorted([(r[1], r[2]) for r in rows(out) if r[0] == "a"])
    assert a == [("e1", 100), ("e2", 300), ("e3", 500)]


def test_running_sum_peers_range(spark):
    # default frame is RANGE: peers (equal order values) are included
    df = spark.createDataFrame({
        "g": ["x", "x", "x"],
        "o": np.array([1, 1, 2], np.int64),
        "v": np.array([10, 20, 5], np.int64),
    })
    w = Window.partitionBy("g").orderBy("o")
    out = df.select("o", F.sum("v").over(w).alias("s"))
    got = sorted(rows(out))
    assert got == [(1, 30), (1, 30), (2, 35)]


def test_whole_partition_agg(sales):
    w = Window.partitionBy("dept")
    out = sales.select("dept", "salary",
                       F.sum("salary").over(w).alias("total"),
                       F.count("*").over(w).alias("n"),
                       F.avg("salary").over(w).alias("m"))
    for r in rows(out):
        if r[0] == "a":
            assert r[2] == 500 and r[3] == 3 and r[4] == pytest.approx(500 / 3)
        if r[0] == "c":
            assert r[2] == 10 and r[3] == 1


def test_rows_between_bounded(sales):
    w = Window.partitionBy("dept").orderBy("salary").rowsBetween(-1, 1)
    out = sales.select("dept", "salary", F.sum("salary").over(w).alias("s"))
    a = sorted([(r[1], r[2]) for r in rows(out) if r[0] == "a"])
    # sorted salaries 100,200,200: windows [100+200, 100+200+200, 200+200]
    assert a == [(100, 300), (200, 400), (200, 500)]


def test_min_max_over_partition(sales):
    w = Window.partitionBy("dept")
    out = sales.select("dept", F.min("salary").over(w).alias("lo"),
                       F.max("salary").over(w).alias("hi"))
    for r in rows(out):
        if r[0] == "a":
            assert (r[1], r[2]) == (100, 200)


def test_running_min(sales):
    w = Window.partitionBy("dept").orderBy(F.desc("salary")) \
        .rowsBetween(Window.unboundedPreceding, Window.currentRow)
    out = sales.select("dept", "salary", F.min("salary").over(w).alias("rm"))
    a = sorted([(r[1], r[2]) for r in rows(out) if r[0] == "a"])
    assert a == [(100, 100), (200, 200), (200, 200)]


def test_ntile(spark):
    df = spark.createDataFrame({"g": ["x"] * 7,
                                "v": np.arange(7, dtype=np.int64)})
    w = Window.partitionBy("g").orderBy("v")
    out = df.select("v", F.ntile(3).over(w).alias("t"))
    got = sorted(rows(out))
    assert [t for _, t in got] == [1, 1, 1, 2, 2, 3, 3]


def test_window_sql(spark):
    df = spark.createDataFrame({
        "dept": ["a", "a", "b"],
        "salary": np.array([10, 20, 30], np.int64),
    })
    df.createOrReplaceTempView("wt")
    out = spark.sql(
        "SELECT dept, salary, "
        "row_number() OVER (PARTITION BY dept ORDER BY salary DESC) AS rn, "
        "sum(salary) OVER (PARTITION BY dept) AS tot FROM wt ORDER BY dept, salary")
    assert rows(out) == [("a", 10, 2, 30), ("a", 20, 1, 30), ("b", 30, 1, 30)]
    spark.catalog.drop("wt")


def test_window_sql_rows_between(spark):
    df = spark.createDataFrame({"v": np.array([1, 2, 3, 4], np.int64)})
    df.createOrReplaceTempView("wb")
    out = spark.sql(
        "SELECT v, sum(v) OVER (ORDER BY v ROWS BETWEEN 1 PRECEDING AND "
        "CURRENT ROW) AS s FROM wb ORDER BY v")
    assert rows(out) == [(1, 1), (2, 3), (3, 5), (4, 7)]
    spark.catalog.drop("wb")


def test_global_window_no_partition(spark):
    df = spark.createDataFrame({"v": np.array([3, 1, 2], np.int64)})
    w = Window.orderBy("v")
    out = df.select("v", F.row_number().over(w).alias("rn"))
    assert sorted(rows(out)) == [(1, 1), (2, 2), (3, 3)]


def test_int64_sum_exact_beyond_2_53(spark):
    """Window SUM of int64 must stay bit-exact past float64's 2^53 mantissa
    (Spark's long sums are exact; the prefix-scan sentinel must not promote
    the accumulator to float64)."""
    big = 1 << 60
    vals = np.array([big + 5, big + 2], np.int64)
    df = spark.createDataFrame({"g": ["x", "x"], "v": vals})
    w = Window.partitionBy("g")
    out = rows(df.select(F.sum("v").over(w).alias("s")))
    assert out == [(int(vals.sum()),)] * 2


def test_int64_min_max_stay_integer(spark):
    big = (1 << 60) + 7
    df = spark.createDataFrame({"g": ["x", "x", "y"],
                                "v": np.array([big, 3, 9], np.int64)})
    w = Window.partitionBy("g")
    out = rows(df.select("g", F.min("v").over(w).alias("lo"),
                         F.max("v").over(w).alias("hi")))
    got = {(r[0]): (r[1], r[2]) for r in out}
    assert got["x"] == (3, big)      # exact, not float64-rounded
    assert got["y"] == (9, 9)
    assert all(isinstance(r[1], int) for r in out)


def test_int64_running_sum_exact(spark):
    big = 1 << 60
    df = spark.createDataFrame({
        "g": ["x", "x", "x"],
        "o": np.array([1, 2, 3], np.int64),
        "v": np.array([big + 1, big + 2, big + 4], np.int64),
    })
    w = Window.partitionBy("g").orderBy("o")
    out = rows(df.select("o", F.sum("v").over(w).alias("s")).orderBy("o"))
    assert [r[1] for r in out] == [big + 1, 2 * big + 3, 3 * big + 7]


def test_bool_max_with_null(spark):
    """Max over a boolean column with NULLs: identity must be False (the
    old float64 -inf buffer cast back to bool gave True)."""
    df = spark.createDataFrame([("x", False), ("x", None)], ["g", "b"])
    w = Window.partitionBy("g")
    out = rows(df.select(F.max("b").over(w).alias("m")))
    assert out == [(False,), (False,)]


def test_running_min_includes_order_peers(spark):
    """Default (RANGE) frame with ORDER BY includes the current row's
    peers: min/max must agree with the sum path about frame bounds."""
    df = spark.createDataFrame(
        [("g", 1, 5), ("g", 1, 3), ("g", 2, 9)], ["k", "o", "v"])
    w = Window.partitionBy("k").orderBy("o")
    out = rows(df.select("o", "v",
                         F.min("v").over(w).alias("lo"),
                         F.max("v").over(w).alias("hi")).orderBy("o", "v"))
    # o=1 rows are peers: both see min=3, max=5; o=2 sees the full set
    assert out == [(1, 3, 3, 5), (1, 5, 3, 5), (2, 9, 3, 9)]


def test_rows_frame_min_excludes_peers(spark):
    """ROWS UNBOUNDED PRECEDING..CURRENT ROW is position-based: the peer
    that sorts later does NOT see the one before it excluded."""
    df = spark.createDataFrame(
        [("g", 1, 5), ("g", 2, 3), ("g", 3, 9)], ["k", "o", "v"])
    w = (Window.partitionBy("k").orderBy("o")
         .rowsBetween(Window.unboundedPreceding, Window.currentRow))
    out = rows(df.select("o", F.min("v").over(w).alias("lo")).orderBy("o"))
    assert out == [(1, 5), (2, 3), (3, 3)]
