"""Host-memory ledger + spill-aware exchange staging (ISSUE:
memory-pressure-safe distributed joins).

Unit layer for the tentpole's building blocks:

- ``HostMemoryLedger``: budget discovery, reserve/release accounting,
  peak tracking, the structured ``HostMemoryError`` a failed hard
  reservation raises (naming reserver, exchange, and current holders);
- ``FetchSink``: fetched blocks land in RAM under the ledger or spill
  to wire-format run files, drain preserves the own-first sorted-sender
  batch order and batch boundaries, re-adding a sender is idempotent
  (the refetch contract), and a failed spill surfaces as a structured
  ``HostMemoryError`` — never a partial delivery;
- ``spill_map_partitions`` + ``exchange_spilled``: map output spilled as
  per-partition frames ships receivers their byte spans straight from
  the spill file, byte-identical to the in-memory exchange.

The 2- and 3-process end-to-end parity lives in test_shuffled_join.py
(mode "spill") and the disk-full chaos in test_faults.py.
"""

import os

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu import wire
from spark_tpu.columnar import ColumnBatch
from spark_tpu.memory import (
    HOST_BUDGET, HostMemoryError, HostMemoryLedger, discover_host_budget,
)
from spark_tpu.parallel.hostshuffle import FetchSink, HostShuffleService


def _batch(vals):
    return ColumnBatch.from_arrays({"v": np.asarray(vals, np.int64)})


def _values(batches):
    return [int(x) for b in batches
            for x, ok in zip(np.asarray(b.column("v").data),
                             np.asarray(b.row_valid_or_true())) if ok]


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

def test_discover_host_budget_positive():
    assert discover_host_budget() > 0


def test_ledger_budget_from_conf_and_discovery():
    conf = C.Conf()
    conf.set(HOST_BUDGET.key, "12345")
    assert HostMemoryLedger(conf).budget == 12345
    # unset (0) → discovered machine total
    assert HostMemoryLedger(C.Conf()).budget == discover_host_budget()
    assert HostMemoryLedger(budget=77).budget == 77


def test_ledger_reserve_release_accounting():
    led = HostMemoryLedger(budget=1000)
    assert led.try_reserve("a", 400)
    assert led.try_reserve("b", 500)
    assert not led.try_reserve("c", 200)       # 900 + 200 > 1000
    assert led.used == 900 and led.free == 100
    assert led.held("a") == 400 and led.held("c") == 0
    led.release("a", 150)                      # partial
    assert led.held("a") == 250 and led.used == 750
    led.release("a")                           # remainder
    assert led.held("a") == 0
    led.release("b")
    assert led.used == 0 and led.free == 1000
    assert led.peak == 900                     # high-water mark survives


def test_ledger_release_prefix_scopes_by_query():
    led = HostMemoryLedger(budget=1000)
    led.reserve("shuffle:xq000001:jL-map", 100)
    led.reserve("shuffle:xq000001:jL-fetch", 200)
    led.reserve("shuffle:xq000002:jL-map", 300)
    led.release_prefix("shuffle:xq000001")
    assert led.used == 300
    assert led.held("shuffle:xq000002:jL-map") == 300


def test_hard_reserve_raises_structured_host_memory_error():
    led = HostMemoryLedger(budget=1000)
    led.reserve("shuffle:q:jL-map", 800)
    with pytest.raises(HostMemoryError) as ei:
        led.reserve("shuffle:q:jR-map", 400, exchange="q-jR")
    e = ei.value
    assert isinstance(e, MemoryError)          # catchable as the stdlib kind
    assert e.owner == "shuffle:q:jR-map"
    assert e.requested == 400 and e.budget == 1000
    assert e.exchange == "q-jR"
    assert e.holders == {"shuffle:q:jL-map": 800}
    msg = str(e)
    assert "shuffle:q:jR-map" in msg and "q-jR" in msg and "1000" in msg
    # the failed reserve left no residue
    assert led.used == 800


# ---------------------------------------------------------------------------
# FetchSink: ledger-gated landing zone for fetched blocks
# ---------------------------------------------------------------------------

def _svc(tmp_path, budget, pid=0, n=1):
    return HostShuffleService(str(tmp_path / "root"), pid, n,
                              timeout_s=5.0, poll_s=0.02,
                              ledger=HostMemoryLedger(budget=budget))


def test_fetch_sink_in_memory_order_and_release(tmp_path):
    svc = _svc(tmp_path, budget=1 << 20)
    sink = FetchSink(svc, "shuffle:q:fetch", "q", str(tmp_path))
    sink.add(2, [_batch([20, 21])])
    sink.add(0, [_batch([0])])
    sink.add(-1, [_batch([9, 9])])             # own batches
    assert svc.ledger.used > 0
    out = sink.drain()
    assert _values(out) == [9, 9, 0, 20, 21]   # own first, then senders
    sink.close()
    assert svc.ledger.used == 0
    assert svc.counters["spill_events"] == 0   # everything fit in RAM


def test_fetch_sink_spills_and_drains_identically(tmp_path):
    b_own, b1, b2 = _batch([1, 2, 3]), _batch([10] * 64), _batch([7] * 64)
    raw = wire.raw_nbytes([b1])
    svc = _svc(tmp_path, budget=1 << 20)
    # the force rule: any fetched batch at/above the threshold goes to
    # its sender's run file without ever occupying the ledger
    sink = FetchSink(svc, "shuffle:q:fetch", "q", str(tmp_path),
                     spill_threshold=raw)
    sink.add(1, [b1])                          # forced to disk
    sink.add(2, [b2])                          # forced to disk
    sink.add(-1, [b_own])                      # small → stays in RAM
    assert svc.counters["spill_events"] >= 2
    assert svc.counters["spill_bytes"] > 0
    assert any(f.endswith(".fetch") for f in os.listdir(str(tmp_path)))
    out = sink.drain()                         # disk runs re-reserved hard
    assert svc.ledger.peak >= 2 * raw          # drain accounted the reads
    assert _values(out) == [1, 2, 3] + [10] * 64 + [7] * 64
    # batch boundaries survive the spill round trip
    assert [b.capacity for b in out] \
        == [b_own.capacity, b1.capacity, b2.capacity]
    sink.close()
    # the drained runs stay accounted to the query owner until the
    # query-scope release (crossproc_execute's release_prefix)
    assert svc.ledger.used == 2 * raw
    svc.ledger.release_prefix("shuffle:q")
    assert svc.ledger.used == 0


def test_fetch_sink_drain_over_budget_fails_bounded(tmp_path):
    """When the drained whole no longer fits the budget (a shuffled-hash
    shard must be fully resident to join), the hard reserve at drain
    raises the structured error instead of returning a PARTIAL shard."""
    b1, b2 = _batch([10] * 64), _batch([7] * 64)
    raw = wire.raw_nbytes([b1])
    svc = _svc(tmp_path, budget=raw + raw // 2)   # fits ONE big batch
    sink = FetchSink(svc, "shuffle:q:fetch", "q", str(tmp_path))
    sink.add(1, [b1])                          # reserved in RAM
    sink.add(2, [b2])                          # budget blown → run file
    assert svc.counters["spill_events"] >= 1
    with pytest.raises(HostMemoryError) as ei:
        sink.drain()
    assert ei.value.owner == "shuffle:q:fetch"
    sink.close()
    assert svc.ledger.used == 0


def test_fetch_sink_readd_is_idempotent(tmp_path):
    """The refetch path re-reads a sender after a failed attempt: the
    second delivery must REPLACE the first (reservation and run file),
    not double-count it."""
    svc = _svc(tmp_path, budget=1 << 20)
    sink = FetchSink(svc, "shuffle:q:fetch", "q", str(tmp_path))
    sink.add(1, [_batch([5, 6])])
    held = svc.ledger.used
    sink.add(1, [_batch([5, 6])])
    assert svc.ledger.used == held
    assert _values(sink.drain()) == [5, 6]
    sink.close()


def test_fetch_sink_spill_failure_is_structured(tmp_path):
    svc = _svc(tmp_path, budget=64)            # nothing fits in RAM
    def broken(path, data, append=False, exchange=""):
        raise OSError(28, "No space left on device")
    svc.spill_write = broken
    sink = FetchSink(svc, "shuffle:q:fetch", "q", str(tmp_path))
    with pytest.raises(HostMemoryError) as ei:
        sink.add(1, [_batch([1] * 64)])
    assert "spill failed" in str(ei.value)
    assert ei.value.owner == "shuffle:q:fetch"
    sink.close()
    assert svc.ledger.used == 0


# ---------------------------------------------------------------------------
# map-side spill: per-partition frames, shipped as byte spans
# ---------------------------------------------------------------------------

def test_spill_map_partitions_offsets_and_spans(tmp_path):
    svc = _svc(tmp_path, budget=1 << 20)
    slices = [_batch([0, 1]), None, _batch([7, 8, 9])]
    path = str(tmp_path / "q.map")
    offs = svc.spill_map_partitions("q-x", slices, path)
    assert len(offs) == 4 and offs[0] == 0
    assert offs[1] == offs[2]                  # empty slice: zero-length
    assert offs[3] == os.path.getsize(path)
    # a single partition's span decodes to exactly that slice
    got = svc.decode_spilled("q-x", path, [(offs[2], offs[3] - offs[2])])
    assert _values(got) == [7, 8, 9]
    # a multi-partition span walks both frames
    got2 = svc.decode_spilled("q-x", path, [(0, offs[3])])
    assert _values(got2) == [0, 1, 7, 8, 9]


def test_exchange_spilled_matches_in_memory_exchange(tmp_path):
    b0, b1 = _batch([1, 2, 3]), _batch([40, 50])
    mem = _svc(tmp_path / "m", budget=1 << 20)
    want = mem.exchange("q", {0: [b0, b1]})
    svc = _svc(tmp_path / "s", budget=1 << 20)
    path = str(tmp_path / "s" / "q.map")
    offs = svc.spill_map_partitions("q", [b0, b1], path)
    routed = {0: [(offs[0], offs[2] - offs[0])]}
    got = svc.exchange_spilled("q", path, routed, {})
    assert _values(got) == _values(want) == [1, 2, 3, 40, 50]
    # single-use contract holds for the spilled form too
    with pytest.raises(ValueError):
        svc.exchange_spilled("q", path, routed, {})


def test_exchange_spilled_dictionary_codes_roundtrip(tmp_path):
    """The encoded-execution lane survives the spill: dictionary columns
    spill as codes + sidecar refs, and the own-partition decode resolves
    them from the sender's local ref table."""
    b = ColumnBatch.from_arrays({"s": ["ash", "oak", "ash", "fir"]})
    svc = _svc(tmp_path, budget=1 << 20)
    path = str(tmp_path / "q.map")
    offs = svc.spill_map_partitions("qd", [b], path)
    got = svc.exchange_spilled("qd", path,
                               {0: [(0, offs[1])]}, {})
    assert got[0].column("s").dictionary == ("ash", "fir", "oak")
    assert got[0].to_pylist() == b.to_pylist()
