"""Parquet column pruning + row-group predicate pushdown (VERDICT r2 #4).

The FileSourceStrategy/ParquetFilters story: plans read ONLY the columns
they consume (`execution/datasources/FileSourceStrategy.scala`), and
`col op literal` conjuncts skip row groups by footer min/max stats
(`parquet/ParquetFilters.scala`) — asserted through io.SCAN_STATS, with
results validated against the unpruned path.
"""

import os

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu import io as tio
from spark_tpu.sql import functions as F


@pytest.fixture()
def wide(tmp_path):
    """A 12-column table written with small row groups, sorted by `ord` so
    min/max stats are selective."""
    n = 4000
    rng = np.random.default_rng(3)
    pdf = pd.DataFrame({"ord": np.arange(n, dtype=np.int64)})
    for i in range(8):
        pdf[f"pad{i}"] = rng.normal(size=n)
    pdf["grp"] = rng.choice(["a", "b", "c"], n)
    pdf["val"] = rng.integers(0, 100, n).astype(np.int64)
    d = tmp_path / "wide.parquet"
    os.makedirs(d)
    pdf.to_parquet(d / "part-000.parquet", index=False, row_group_size=500)
    return str(d), pdf


def _reset():
    for k in tio.SCAN_STATS:
        tio.SCAN_STATS[k] = 0


def test_column_pruning_eager(spark, wide):
    path, pdf = wide
    tio._relation_cache.clear()
    _reset()
    df = (spark.read.parquet(path)
          .groupBy("grp").agg(F.sum("val").alias("sv")))
    got = {r[0]: r[1] for r in df.collect()}
    exp = pdf.groupby("grp").val.sum()
    assert got == exp.to_dict()
    # the scan must have read only grp+val, not the 12-column table
    assert tio.SCAN_STATS["columns_read"] == 2


def test_pruned_plan_marks_relation(spark, wide):
    from spark_tpu.sql.logical import FileRelation
    from spark_tpu.sql.planner import QueryExecution
    path, _ = wide
    df = spark.read.parquet(path).select("ord").filter(F.col("ord") < 10)
    qe = QueryExecution(spark, df._plan)

    rels = []

    def walk(n):
        if isinstance(n, FileRelation):
            rels.append(n)
        for c in n.children:
            walk(c)
    walk(qe.optimized)
    assert rels and rels[0].columns == ["ord"]
    assert ("ord", "<", 10) in (rels[0].pushed_filters or [])


def test_rowgroup_skip_eager(spark, wide):
    path, pdf = wide
    tio._relation_cache.clear()
    _reset()
    df = spark.read.parquet(path).filter(F.col("ord") >= 3500) \
        .agg(F.count("ord").alias("n"), F.sum("val").alias("s"))
    (n, s), = df.collect()
    assert n == 500
    assert s == int(pdf[pdf.ord >= 3500].val.sum())
    assert tio.SCAN_STATS["row_groups_skipped"] == 7
    assert tio.SCAN_STATS["rows"] == 500


def test_rowgroup_skip_streamed(spark, wide):
    path, pdf = wide
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, "600")
    try:
        tio._relation_cache.clear()
        _reset()
        df = spark.read.parquet(path).filter(F.col("ord") < 1000) \
            .groupBy("grp").agg(F.count("val").alias("n"))
        got = {r[0]: r[1] for r in df.collect()}
        sub = pdf[pdf.ord < 1000]
        assert got == sub.groupby("grp").val.count().to_dict()
        assert tio.SCAN_STATS["row_groups_skipped"] == 6
        # streamed scan read only the pruned columns
        assert tio.SCAN_STATS["columns_read"] == 3
    finally:
        spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))


def test_pushdown_never_changes_results(spark, wide):
    """Stats skipping is advisory; equality band + string filter survive."""
    path, pdf = wide
    df = (spark.read.parquet(path)
          .filter((F.col("ord") >= 777) & (F.col("ord") < 1234)
                  & (F.col("grp") == "b"))
          .agg(F.count("ord").alias("n")))
    (n,), = df.collect()
    exp = pdf[(pdf.ord >= 777) & (pdf.ord < 1234) & (pdf.grp == "b")]
    assert n == len(exp)


def test_all_groups_skipped(spark, wide):
    path, _ = wide
    df = spark.read.parquet(path).filter(F.col("ord") < 0)
    assert df.count() == 0


def test_window_inputs_survive_pruning(spark, wide):
    """WindowExpression refs live in sub_expressions(), not children —
    pruning must keep the window's partition/order/input columns."""
    from spark_tpu.sql.window import Window
    path, pdf = wide
    df = (spark.read.parquet(path)
          .select(F.col("ord"),
                  F.sum("val").over(
                      Window.partitionBy("grp")).alias("sv"))
          .orderBy("ord").limit(5))
    got = [(r[0], r[1]) for r in df.collect()]
    gsum = pdf.groupby("grp").val.sum()
    exp = [(int(r.ord), int(gsum[r.grp]))
           for r in pdf.sort_values("ord").head(5).itertuples()]
    assert got == exp


def test_count_star_reads_narrow_column(spark, wide):
    path, pdf = wide
    tio._relation_cache.clear()
    _reset()
    assert spark.read.parquet(path).count() == len(pdf)
    assert tio.SCAN_STATS["columns_read"] == 1


def test_footer_column_stats(spark, wide):
    from spark_tpu.io import file_column_stats
    from spark_tpu.sql.logical import FileRelation
    path, pdf = wide
    rel = spark.read.parquet(path)._plan
    assert isinstance(rel, FileRelation)
    st = file_column_stats(rel)
    assert st["ord"]["min"] == 0 and st["ord"]["max"] == len(pdf) - 1
    assert st["ord"]["null_count"] == 0
    assert st["ord"]["total"] == len(pdf)
    assert st["grp"]["min"] == "a" and st["grp"]["max"] == "c"


def test_filter_selectivity_shrinks_estimates(spark, wide):
    from spark_tpu.sql.optimizer import rows_estimate
    from spark_tpu.sql.planner import QueryExecution
    path, pdf = wide
    full = spark.read.parquet(path)
    n = len(pdf)
    filtered = full.filter(F.col("ord") < n // 10)
    est_full = rows_estimate(QueryExecution(spark, full._plan).analyzed)
    qe = QueryExecution(spark, filtered._plan)
    # estimate on the ANALYZED plan (optimizer would push the filter into
    # the pruned relation)
    est_f = rows_estimate(qe.analyzed)
    assert est_full == n
    assert est_f < n // 5            # ~10% with footer-range selectivity
    assert est_f >= 1
