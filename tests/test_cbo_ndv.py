"""CBO depth: sampled NDV statistics + cost-based join ordering
(VERDICT r3 missing #7; `statsEstimation/`, `CostBasedJoinReorder.scala`,
`StarSchemaDetection.scala` roles)."""

import numpy as np
import pandas as pd
import pytest

import spark_tpu.sql.functions as F


@pytest.fixture()
def star(spark, tmp_path):
    """A small star: fact(20k) + a clean PK dim + an EXPLODING dim
    (1000 rows but only 5 distinct join keys — joining it early
    multiplies the fact 200x)."""
    rng = np.random.default_rng(7)
    n = 4096
    fact = pd.DataFrame({
        "k_good": rng.integers(0, 500, n),
        "k_bad": rng.integers(0, 5, n),
        "v": rng.integers(0, 100, n),
    })
    dim_good = pd.DataFrame({
        "g_k": np.arange(500, dtype=np.int64),
        "g_tag": np.arange(500, dtype=np.int64) % 7,
    })
    dim_bad = pd.DataFrame({
        "b_k": rng.integers(0, 5, 60).astype(np.int64),
        "b_w": np.arange(60, dtype=np.int64),
    })
    paths = {}
    for name, pdf in [("fact", fact), ("dim_good", dim_good),
                      ("dim_bad", dim_bad)]:
        p = str(tmp_path / f"{name}.parquet")
        pdf.to_parquet(p, index=False)
        paths[name] = p
        spark.read.parquet(p).createOrReplaceTempView(name)
    return spark, fact, dim_good, dim_bad


def test_ndv_estimates(spark, tmp_path):
    from spark_tpu.io import file_column_ndv
    from spark_tpu.sql import logical as L
    rng = np.random.default_rng(5)
    pdf = pd.DataFrame({
        "unique_id": np.arange(50_000, dtype=np.int64),
        "enum": rng.integers(0, 12, 50_000),
    })
    p = str(tmp_path / "nd.parquet")
    pdf.to_parquet(p, index=False, row_group_size=8192)
    rel = spark.read.parquet(p)._plan
    assert isinstance(rel, L.FileRelation)
    ndv = file_column_ndv(rel, ["unique_id", "enum", "missing"])
    assert 10 <= ndv["enum"] <= 14                       # saturated domain
    assert 25_000 <= ndv["unique_id"] <= 100_000         # scales to total
    assert "missing" not in ndv


def _join_order(spark, sql):
    """Names of base relations in left-deep join order of the optimized
    plan (leftmost/base first)."""
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.planner import QueryExecution
    plan = QueryExecution(spark, spark.sql(sql)._plan).optimized
    order = []

    def walk(n):
        if isinstance(n, L.Join):
            walk(n.children[0])
            walk(n.children[1])
        elif isinstance(n, L.FileRelation):
            path = n.paths[0] if isinstance(n.paths, list) else n.paths
            order.append(path.rsplit("/", 1)[-1].split(".")[0])
        else:
            for c in n.children:
                walk(c)
    walk(plan)
    return order


def test_join_reorder_prefers_low_fanout_dim(star):
    spark, fact, dim_good, dim_bad = star
    sql = """
        SELECT g_tag, SUM(v) AS s, COUNT(*) AS c
        FROM fact, dim_bad, dim_good
        WHERE k_bad = b_k AND k_good = g_k
        GROUP BY g_tag ORDER BY g_tag
    """
    order = _join_order(spark, sql)
    # base = fact (largest); the clean PK dim must attach BEFORE the
    # 200x-fanout dim regardless of FROM-clause order
    assert order[0] == "fact", order
    assert order.index("dim_good") < order.index("dim_bad"), order

    got = [(r.g_tag, r.s, r.c) for r in spark.sql(sql).collect()]
    joined = fact.merge(dim_bad, left_on="k_bad", right_on="b_k") \
                 .merge(dim_good, left_on="k_good", right_on="g_k")
    exp = joined.groupby("g_tag").agg(s=("v", "sum"), c=("v", "count"))
    assert got == [(int(t), int(r.s), int(r.c))
                   for t, r in exp.sort_index().iterrows()]


def test_filtered_dim_attaches_first(star):
    """A dim filtered to a sliver (by footer stats) beats an unfiltered
    one — selective dims shrink the running cardinality earliest."""
    spark, *_ = star
    sql = """
        SELECT SUM(v) AS s
        FROM fact, dim_bad, dim_good
        WHERE k_bad = b_k AND k_good = g_k AND b_w < 3
    """
    order = _join_order(spark, sql)
    assert order[0] == "fact", order
    # dim_bad filtered to ~3 of 1000 rows: est out = cur*3/5 < cur,
    # so it now attaches before dim_good (est cur*1000/1000 = cur)
    assert order.index("dim_bad") < order.index("dim_good"), order
