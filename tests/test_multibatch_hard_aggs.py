"""Streamed (out-of-core) execution of the 'hard' aggregates.

VERDICT r3 item 4: first/last, count/sum DISTINCT, collect_list/set and
percentile used to force the eager single-batch path (multibatch.py
guard); each breaks the moment data exceeds one batch.  Now:

* first/last stream through the (rank, value, valid) value-carry triple of
  ``DPartialAggregate`` with a host-side scan-order rank rebase, merged by
  ``DMergePartial`` (mode=PartialMerge of the reference's AggUtils.scala);
* distinct aggs stream via the analyzer's two-level expansion
  (``RewriteDistinctAggregates.scala`` analog) whose inner aggregate is a
  plain mergeable breaker;
* collect/percentile stream through grace hash aggregation (key-hash spill
  buckets + per-bucket eager host aggregation —
  ``ObjectHashAggregateExec.scala``'s role).

Data is ≥4x batch capacity; every result is checked against a pandas
oracle computed over the full dataset.
"""

import os

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F

BATCH = 256
N = 2000             # ~8 scan batches of BATCH rows


def _pdf(seed=13):
    rng = np.random.default_rng(seed)
    x = rng.normal(50.0, 20.0, N)
    x[rng.random(N) < 0.07] = np.nan          # NULL measures
    return pd.DataFrame({
        "id": np.arange(N, dtype=np.int64),
        "grp": rng.choice(["ash", "beech", "cedar", "doum", "elm"], N),
        "x": x,
        "k": rng.integers(0, 40, N).astype(np.int64),
    })


@pytest.fixture(scope="module")
def bigfile(tmp_path_factory):
    d = tmp_path_factory.mktemp("mbh") / "big.parquet"
    os.makedirs(d)
    pdf = _pdf()
    step = N // 4
    for i in range(4):
        pdf.iloc[i * step:(i + 1) * step].to_parquet(
            d / f"part-{i:03d}.parquet", index=False)
    return str(d), pdf


@pytest.fixture()
def mb(spark):
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    old_len = spark.conf.get(C.COLLECT_MAX_LEN)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    # groups here run ~N/5 elements; raise the static collect cap so the
    # oracle comparison is exact (the cap itself is a documented deviation)
    spark.conf.set(C.COLLECT_MAX_LEN.key, str(1024))
    yield spark
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))
    spark.conf.set(C.COLLECT_MAX_LEN.key, str(old_len))


def _uses_multibatch(session, df) -> bool:
    from spark_tpu.sql.multibatch import plan_multibatch
    from spark_tpu.sql.planner import QueryExecution
    qe = QueryExecution(session, df._plan)
    return plan_multibatch(session, qe.optimized) is not None


# ---------------------------------------------------------------------------
# first / last
# ---------------------------------------------------------------------------

def test_first_last_stream_scan_order(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.first("id").alias("f"), F.last("id").alias("l"))
    assert _uses_multibatch(mb, df)
    got = {r[0]: (r[1], r[2]) for r in df.collect()}
    exp = pdf.groupby("grp").agg(f=("id", "first"), l=("id", "last"))
    assert got == {g: (int(r.f), int(r.l)) for g, r in exp.iterrows()}


def test_first_last_ignore_nulls(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.first("x").alias("f"), F.last("x").alias("l"))
    got = {r[0]: (r[1], r[2]) for r in df.collect()}
    sub = pdf.dropna(subset=["x"])
    exp = sub.groupby("grp").agg(f=("x", "first"), l=("x", "last"))
    for g, r in exp.iterrows():
        np.testing.assert_allclose(got[g], (r.f, r.l), rtol=1e-12)


def test_first_string_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("k").agg(F.first("grp").alias("f"))
    got = {r[0]: r[1] for r in df.collect()}
    exp = pdf.groupby("k").agg(f=("grp", "first"))
    assert got == {int(k): r.f for k, r in exp.iterrows()}


def test_global_first_last(mb, bigfile):
    path, pdf = bigfile
    (f, l), = mb.read.parquet(path).agg(
        F.first("id").alias("f"), F.last("id").alias("l")).collect()
    assert (f, l) == (0, N - 1)


# ---------------------------------------------------------------------------
# distinct aggregates (analyzer two-level expansion over the stream)
# ---------------------------------------------------------------------------

def test_count_distinct_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.countDistinct("k").alias("cd"))
    got = {r[0]: r[1] for r in df.collect()}
    exp = pdf.groupby("grp").k.nunique()
    assert got == {g: int(v) for g, v in exp.items()}


def test_sum_distinct_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.sumDistinct("k").alias("sd"))
    got = {r[0]: r[1] for r in df.collect()}
    exp = pdf.groupby("grp").k.agg(lambda s: s.unique().sum())
    assert got == {g: int(v) for g, v in exp.items()}


# ---------------------------------------------------------------------------
# collect_list / collect_set / percentile (grace hash aggregation)
# ---------------------------------------------------------------------------

def test_collect_list_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.collect_list("k").alias("vals"))
    got = {r[0]: sorted(r[1]) for r in df.collect()}
    exp = pdf.groupby("grp").k.apply(lambda s: sorted(s.tolist()))
    assert got == {g: v for g, v in exp.items()}


def test_collect_set_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.collect_set("k").alias("vals"))
    got = {r[0]: sorted(r[1]) for r in df.collect()}
    exp = pdf.groupby("grp").k.apply(lambda s: sorted(set(s.tolist())))
    assert got == {g: v for g, v in exp.items()}


def test_percentile_stream(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.percentile_approx("k", 0.5).alias("med"))
    got = {r[0]: r[1] for r in df.collect()}
    # engine semantics: nearest-rank at floor(p * (n-1)) over sorted values
    exp = pdf.groupby("grp").k.apply(
        lambda s: int(np.sort(s.to_numpy())[int(0.5 * (len(s) - 1))]))
    assert got == {g: v for g, v in exp.items()}


def test_grace_mixed_with_plain_aggs(mb, bigfile):
    """collect alongside sum/count in one aggregate — the whole slot set
    runs on the grace path, exactly."""
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(
        F.collect_set("k").alias("vals"), F.sum("k").alias("s"),
        F.count("id").alias("c"))
    got = {r[0]: (sorted(r[1]), r[2], r[3]) for r in df.collect()}
    for g, sub in pdf.groupby("grp"):
        vals, s, c = got[g]
        assert vals == sorted(set(sub.k.tolist()))
        assert s == int(sub.k.sum())
        assert c == len(sub)


def test_global_collect(mb, bigfile):
    path, pdf = bigfile
    (vals,), = mb.read.parquet(path).agg(
        F.collect_set("grp").alias("vals")).collect()
    assert sorted(vals) == sorted(pdf.grp.unique())


# ---------------------------------------------------------------------------
# checkpoint safety of the grace store
# ---------------------------------------------------------------------------

def test_bucket_store_pickle_truncates_appended_spills(tmp_path):
    """_BucketStore spill files are APPENDED in place; a restored pickle
    must truncate them back to their pickled sizes or a resumed scan
    double-counts every row spilled after the checkpoint."""
    import pickle as pkl

    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.sql.stages import _BucketStore
    import spark_tpu.types as T

    def batch(vals):
        arr = np.asarray(vals, np.int64)
        return ColumnBatch(["x"], [ColumnVector(arr, T.int64)], None,
                           len(arr))

    store = _BucketStore(2, budget_rows=2, spill_dir=str(tmp_path))
    store.add(batch([1, 2, 3]), np.array([0, 0, 1]))   # spills (3 > 2)
    blob = pkl.dumps(store)
    store.add(batch([4, 5, 6]), np.array([0, 1, 1]))   # appends post-ckpt
    store._spill()
    assert sum(len(np.asarray(b.vectors[0].data))
               for b in store.load(0)) == 3             # 1,2,4

    resumed = pkl.loads(blob)
    rows0 = [int(v) for b in resumed.load(0)
             for v in np.asarray(b.vectors[0].data)]
    rows1 = [int(v) for b in resumed.load(1)
             for v in np.asarray(b.vectors[0].data)]
    assert sorted(rows0 + rows1) == [1, 2, 3]           # post-ckpt rows gone
    store.close()


# ---------------------------------------------------------------------------
# the same shapes through the stage runner (joins force stages.py routing)
# ---------------------------------------------------------------------------

def test_stage_runner_first_and_collect(mb, bigfile, tmp_path):
    path, pdf = bigfile
    dim = pd.DataFrame({
        "grp": ["ash", "beech", "cedar", "doum", "elm"],
        "tag": [1, 2, 3, 4, 5],
    })
    dpath = str(tmp_path / "dim.parquet")
    dim.to_parquet(dpath, index=False)
    fact = mb.read.parquet(path)
    d = mb.read.parquet(dpath)
    df = (fact.join(d, on="grp")
          .groupBy("tag")
          .agg(F.first("id").alias("f"), F.collect_set("k").alias("vals")))
    got = {r[0]: (r[1], sorted(r[2])) for r in df.collect()}
    merged = pdf.merge(dim, on="grp")
    exp_f = merged.groupby("tag").id.first()
    exp_v = merged.groupby("tag").k.apply(lambda s: sorted(set(s.tolist())))
    assert got == {int(t): (int(exp_f[t]), exp_v[t]) for t in exp_f.index}
