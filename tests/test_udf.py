"""Python UDFs: pure_callback slow lane, traced fast lane, SQL registry,
distributed execution (BatchEvalPythonExec analog)."""

import datetime

import numpy as np
import pandas as pd
import pytest

from spark_tpu import types as T
from spark_tpu.expressions import AnalysisException
from spark_tpu.sql import functions as F


@pytest.fixture()
def df(spark):
    return spark.createDataFrame(pd.DataFrame({
        "k": np.arange(6, dtype=np.int64),
        "s": ["a", "bb", None, "dddd", "e", "ff"],
        "x": [1.0, 2.0, 3.0, None, 5.0, 6.0],
    }))


def test_slow_lane_jitted(spark, df):
    plus_one = F.udf(lambda v: v + 1, "bigint")
    got = [r[0] for r in df.select(plus_one(F.col("k")).alias("o")).collect()]
    assert got == [1, 2, 3, 4, 5, 6]


def test_null_in_null_out(spark, df):
    neg = F.udf(lambda v: -v if v is not None else None, "double")
    got = [r[0] for r in df.select(neg(F.col("x")).alias("o")).collect()]
    assert got == [-1.0, -2.0, -3.0, None, -5.0, -6.0]


def test_string_input_decoded(spark, df):
    slen = F.udf(lambda s: len(s) if s is not None else None, "int")
    got = [r[0] for r in df.select(slen(F.col("s")).alias("o")).collect()]
    assert got == [1, 2, None, 4, 1, 2]


def test_multi_arg_and_filter(spark, df):
    both = F.udf(lambda a, b: a * 10 + (b or 0), "double")
    out = (df.select("k", both(F.col("k"), F.col("x")).alias("o"))
           .filter(F.col("o") > 30).collect())
    assert [r[0] for r in out] == [4, 5]   # k=3 has x NULL -> o=30, not >30


def test_fast_lane_vectorized(spark, df):
    import jax.numpy as jnp
    sq = F.udf(lambda v: jnp.where(v % 2 == 0, v * v, -v),
               "bigint", vectorized=True)
    got = [r[0] for r in df.select(sq(F.col("k")).alias("o")).collect()]
    assert got == [0, -1, 4, -3, 16, -5]


def test_decorator_form(spark, df):
    @F.udf(returnType="bigint")
    def triple(v):
        return 3 * v

    got = [r[0] for r in df.select(triple(F.col("k")).alias("o")).collect()]
    assert got == [0, 3, 6, 9, 12, 15]


def test_date_input(spark):
    d = spark.createDataFrame(pd.DataFrame({
        "d": pd.to_datetime(["2024-01-15", "2024-03-01"]).date}))
    year_of = F.udf(lambda v: v.year, "int")
    got = [r[0] for r in d.select(year_of(F.col("d")).alias("y")).collect()]
    assert got == [2024, 2024]


def test_string_return_rejected(spark, df):
    with pytest.raises(AnalysisException):
        F.udf(lambda v: str(v), "string")


def test_sql_registration(spark, df):
    df.createOrReplaceTempView("udf_t")
    spark.udf.register("cube_it", lambda v: v ** 3, "bigint")
    got = [r[0] for r in
           spark.sql("SELECT cube_it(k) AS c FROM udf_t ORDER BY k").collect()]
    assert got == [0, 1, 8, 27, 64, 125]
    got2 = spark.sql(
        "SELECT SUM(cube_it(k)) AS s FROM udf_t WHERE cube_it(k) > 5"
    ).collect()
    assert got2[0][0] == 8 + 27 + 64 + 125
    with pytest.raises(AnalysisException):
        spark.sql("SELECT no_such_fn(k) FROM udf_t").collect()
    spark.catalog.dropTempView("udf_t")


def test_udf_in_aggregation(spark, df):
    bucket = F.udf(lambda v: v % 2, "bigint")
    got = sorted(tuple(r) for r in
                 df.groupBy(bucket(F.col("k")).alias("b"))
                   .agg(F.count("*").alias("c")).collect())
    assert got == [(0, 3), (1, 3)]


def test_backend_without_callbacks_falls_back(spark, df, monkeypatch):
    """On backends without host callbacks (some TPU runtimes), slow-lane
    UDF queries drop to the interpreted host lane but stay correct."""
    import spark_tpu.sql.udf as U
    monkeypatch.setattr(U, "_callback_support", False)
    plus_one = F.udf(lambda v: v + 1, "bigint")
    got = [r[0] for r in df.select(plus_one(F.col("k")).alias("o")).collect()]
    assert got == [1, 2, 3, 4, 5, 6]


def test_udf_distributed(spark):
    """pure_callback inside the shard_map program on the 8-device mesh."""
    pdf = pd.DataFrame({"k": np.arange(64, dtype=np.int64),
                        "v": np.arange(64, dtype=np.float64)})
    d = spark.createDataFrame(pdf)
    plus = F.udf(lambda a: a + 0.5, "double")
    spark.conf.set("spark.tpu.mesh.shards", "8")
    try:
        got = sorted(r[0] for r in
                     d.select(plus(F.col("v")).alias("o")).collect())
    finally:
        spark.conf.set("spark.tpu.mesh.shards", "1")
    np.testing.assert_allclose(got, np.arange(64) + 0.5)
