"""Static program-quality bounds via XLA cost analysis (no TPU needed).

VERDICT r3 item 2 — off-hardware perf insurance: the compiled programs
behind the bench lanes (`bench.py`) are checked for HBM-traffic and flop
regressions using ``jit(...).lower(...).compile().cost_analysis()``.
"The agg program reads its inputs a bounded number of times" is checkable
today, and is exactly the property the Pallas/MXU formulations exist to
preserve — a regression to a materialized one-hot round-trip
(rows x groups bytes in HBM) blows these bounds by an order of magnitude.

Bounds were measured on the XLA:CPU lowering (the platform the suite
runs on) and anchored at ~1.35x the round-5 measurement (VERDICT r4
item 9: a 2x HBM-traffic regression must fail off-hardware).  A bound
tripping after an XLA upgrade with an engine diff that clearly cannot
change traffic IS allowed to be re-anchored — re-measure, update the
recorded value and the bound together.

Reference bench shapes: ``AggregateBenchmark.scala:125-131``,
``JoinBenchmark.scala:42-47``, ``SortBenchmark.scala:120-128``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_tpu.kernels import compact
from spark_tpu.sql import functions as F
from spark_tpu.sql import physical as P
from spark_tpu.sql.planner import QueryExecution


def _cost(session, plan, out_fn):
    pq = QueryExecution(session, plan).planned
    phys = pq.physical

    def step(leaves):
        out = phys.run(P.ExecContext(jnp, leaves))
        return out_fn(out)

    dev = tuple(b.to_device() for b in pq.leaves)
    ca = jax.jit(step).lower(dev).compile().cost_analysis()
    return ca[0] if isinstance(ca, (list, tuple)) else ca


@pytest.fixture()
def one_shard(spark):
    """Single shard + the sort-based aggregation formulation.

    The conftest forces ``MXU_AGG_ENABLED = True`` so the suite exercises
    the MXU lane; these bounds instead pin the PORTABLE sort-based
    formulation — the MXU einsum's one-hot tiles legitimately dominate
    its static byte count (see test_mxu_agg_traffic_ceiling), and its
    HBM-avoiding variant (pallas_agg.py, VMEM-resident one-hot) is
    invisible to cost_analysis."""
    from spark_tpu import kernels as _k
    old = spark.conf._overrides.get("spark.tpu.mesh.shards")
    old_mxu = _k.MXU_AGG_ENABLED
    spark.conf.set("spark.tpu.mesh.shards", "1")
    _k.MXU_AGG_ENABLED = False
    yield spark
    _k.MXU_AGG_ENABLED = old_mxu
    if old is None:
        spark.conf.unset("spark.tpu.mesh.shards")
    else:
        spark.conf.set("spark.tpu.mesh.shards", old)


def test_agg_program_traffic(one_shard):
    """Grouped sum/count (the primary bench lane): input is N x 2 int64
    columns; bytes accessed must stay within a small multiple of that.
    A materialized one-hot (N x GROUPS int8 = 64x input) must fail."""
    session = one_shard
    N, GROUPS = 1 << 18, 1024
    rng = np.random.default_rng(7)
    df = session.createDataFrame({
        "k": rng.integers(0, GROUPS, N).astype(np.int64),
        "v": rng.integers(0, 100, N).astype(np.int64),
    })
    q = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("*").alias("c"))

    d = _cost(session, q._plan,
              lambda out: (compact(jnp, out).vectors[1].data,))
    input_bytes = N * 16
    ratio = d["bytes accessed"] / input_bytes
    flops_per_row = d["flops"] / N
    # measured (XLA:CPU, r5 2026-07-31): ratio 12.6, flops/row 67 —
    # bounds anchored at ~1.35x measured (VERDICT r4 item 9: a 2x HBM
    # regression must fail off-hardware)
    assert ratio <= 17.0, f"agg HBM traffic regressed: {ratio:.1f}x input"
    assert ratio >= 1.0, "inputs not read? cost model broke"
    assert flops_per_row <= 95.0, \
        f"agg flops regressed: {flops_per_row:.0f}/row"


def test_q3_program_traffic(one_shard):
    """q3-shaped fact-dim broadcast join + group + sort: traffic bounded
    relative to the fact table (the dim side is 128x smaller)."""
    session = one_shard
    J_FACT, J_DIM, J_BRANDS = 1 << 18, 2048, 64
    rng = np.random.default_rng(11)
    fact = session.createDataFrame({
        "sk": rng.integers(0, J_DIM, J_FACT).astype(np.int64),
        "price": rng.integers(1, 1000, J_FACT).astype(np.int64),
    })
    dim = session.createDataFrame({
        "d_sk": np.arange(J_DIM, dtype=np.int64),
        "brand": rng.integers(0, J_BRANDS, J_DIM).astype(np.int64),
        "year": rng.integers(1998, 2003, J_DIM).astype(np.int64),
    })
    q = (fact.join(dim, fact["sk"] == dim["d_sk"])
             .filter(dim["year"] == 2000)
             .groupBy("brand").agg(F.sum("price").alias("rev"))
             .orderBy(F.col("rev").desc()))

    d = _cost(session, q._plan,
              lambda out: (compact(jnp, out).vectors[1].data,))
    input_bytes = J_FACT * 16
    ratio = d["bytes accessed"] / input_bytes
    flops_per_row = d["flops"] / J_FACT
    # measured (XLA:CPU, r5 2026-07-31): ratio 52.4, flops/row 225 —
    # ~1.35x anchors (r4 values 58.3/270 improved by the searchsorted
    # and compact work)
    assert ratio <= 71.0, f"q3 HBM traffic regressed: {ratio:.1f}x fact"
    assert flops_per_row <= 305.0, \
        f"q3 flops regressed: {flops_per_row:.0f}/row"


def test_mxu_agg_traffic_ceiling(spark):
    """The MXU one-hot limb-plane einsum DOES round-trip its one-hot
    tiles through memory when lowered by XLA:CPU — that cost is the very
    reason pallas_agg.py keeps the one-hot in VMEM on TPU.  Pin a ceiling
    so the einsum formulation at least never gets WORSE (e.g. a tile-size
    or limb-count regression doubling the traffic)."""
    from spark_tpu import kernels as _k
    if not _k._mxu_agg_on():
        pytest.skip("MXU agg lane disabled")
    old = spark.conf._overrides.get("spark.tpu.mesh.shards")
    spark.conf.set("spark.tpu.mesh.shards", "1")
    try:
        N, GROUPS = 1 << 18, 1024
        rng = np.random.default_rng(7)
        df = spark.createDataFrame({
            "k": rng.integers(0, GROUPS, N).astype(np.int64),
            "v": rng.integers(0, 100, N).astype(np.int64),
        })
        q = df.groupBy("k").agg(F.sum("v").alias("s"),
                                F.count("*").alias("c"))
        d = _cost(spark, q._plan,
                  lambda out: (compact(jnp, out).vectors[1].data,))
        ratio = d["bytes accessed"] / (N * 16)
        # measured (XLA:CPU, 2026-07): 2105x — the one-hot tiles
        assert ratio <= 3200.0, \
            f"MXU agg einsum traffic regressed: {ratio:.0f}x input"
    finally:
        if old is None:
            spark.conf.unset("spark.tpu.mesh.shards")
        else:
            spark.conf.set("spark.tpu.mesh.shards", old)


def test_sort_program_traffic(one_shard):
    """Global int64 sort through the planner: lax.sort traffic is a few
    passes over the data; a quadratic or gather-storm regression trips."""
    session = one_shard
    S = 1 << 20
    rng = np.random.default_rng(13)
    xs = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max, S,
                      dtype=np.int64)
    df = session.createDataFrame({"x": xs}).orderBy(F.col("x"))

    d = _cost(session, df._plan, lambda out: out.vectors[0].data)
    input_bytes = S * 8
    ratio = d["bytes accessed"] / input_bytes
    flops_per_row = d["flops"] / S
    # measured (XLA:CPU, r5 2026-07-31): ratio 6.6, flops/row 23 —
    # ~1.35x anchors
    assert ratio <= 9.0, f"sort HBM traffic regressed: {ratio:.1f}x input"
    assert flops_per_row <= 31.0, \
        f"sort flops regressed: {flops_per_row:.0f}/row"


def test_global_agg_program_has_no_sort(spark):
    """The keyless (global) aggregate program must contain NO sort HLO:
    the whole point of the _global_reduce path (a full bitonic pass per
    streamed batch was the scan lane's dominant cost)."""
    import spark_tpu.kernels as K
    old = K.MXU_AGG_ENABLED
    K.MXU_AGG_ENABLED = False          # force the portable lane
    try:
        df = (spark.createDataFrame(
            {"x": np.arange(1 << 14, dtype=np.int64)})
            .agg(F.sum("x").alias("s"), F.min("x").alias("m")))
        pq = QueryExecution(spark, df._plan).planned
        phys = pq.physical

        def step(leaves):
            out = phys.run(P.ExecContext(jnp, leaves))
            return out.vectors[0].data

        dev = tuple(b.to_device() for b in pq.leaves)
        hlo = jax.jit(step).lower(dev).compile().as_text()
        assert " sort(" not in hlo and "sort.1" not in hlo, \
            "global aggregate re-grew a sort"
    finally:
        K.MXU_AGG_ENABLED = old


def test_multibatch_agg_step_has_no_sort_for_global(spark, tmp_path):
    """The streamed per-batch step for scan→global-agg (the parquet scan
    bench lane) must be sort-free END TO END: no compact (prefix-live
    skip) and no keyless grouping sort."""
    import pandas as pd
    import spark_tpu.config as C
    import spark_tpu.kernels as K
    from spark_tpu.sql import multibatch as mb
    from spark_tpu import io as tio
    p = tmp_path / "t.parquet"
    p.mkdir()
    pd.DataFrame({"x": np.arange(4096, dtype=np.int64)}).to_parquet(
        p / "part-0.parquet", index=False)
    old_batch = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, "1024")
    old_mxu = K.MXU_AGG_ENABLED
    K.MXU_AGG_ENABLED = False
    try:
        df = spark.read.parquet(str(p)).agg(F.sum("x").alias("s"))
        qe = QueryExecution(spark, df._plan)
        ex = mb.plan_multibatch(spark, qe.optimized)
        assert ex is not None
        tmpl = next(iter(tio.scan_file_batches(
            getattr(ex.dec, "relation", getattr(ex.dec, "rel", None)),
            1024)))
        jstep, _schema = ex._build_step(tmpl)
        hlo = jstep.lower(tmpl.to_device()).compile().as_text()
        assert " sort(" not in hlo, \
            "streamed global-agg step re-grew a sort (compact skip or " \
            "keyless fast path regressed)"
    finally:
        K.MXU_AGG_ENABLED = old_mxu
        spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old_batch))


def test_shrunk_agg_bounds_downstream_sort(spark):
    """groupBy→orderBy: the sort must run over the SHRUNK group table
    (spark.sql.agg.outputCapacity), not the input capacity — q3's sort
    was a full-input-capacity bitonic for 64 live groups."""
    import spark_tpu.config as C
    n = 1 << 18                         # input capacity 262144
    cap = spark.conf.get(C.AGG_OUTPUT_ROWS)
    assert cap < n
    rng = np.random.default_rng(5)
    df = (spark.createDataFrame(
        {"k": rng.integers(0, 64, n).astype(np.int64),
         "v": rng.integers(0, 100, n).astype(np.int64)})
        .groupBy("k").agg(F.sum("v").alias("s"))
        .orderBy(F.col("s").desc()))
    import re
    from spark_tpu.sql.planner import Planner

    def full_width_sorts(shrink_aggs: bool) -> tuple:
        pq = Planner(spark, shrink_aggs=shrink_aggs).plan(
            QueryExecution(spark, df._plan).optimized)
        phys = pq.physical

        def step(leaves):
            out = phys.run(P.ExecContext(jnp, leaves))
            return out.vectors[0].data

        dev = tuple(b.to_device() for b in pq.leaves)
        hlo = jax.jit(step).lower(dev).compile().as_text()
        widths = [int(w) for w in
                  re.findall(r"sort\.?\d* = [^\n]*?\[(\d+)", hlo)]
        return widths, sum(1 for w in widths if w >= n)

    # the aggregation itself owns full-width sorts (the cond's compiled
    # slow branch); the SHRUNK plan must run the orderBy at the bounded
    # capacity, removing at least one full-width sort vs the unshrunk
    widths_on, full_on = full_width_sorts(True)
    widths_off, full_off = full_width_sorts(False)
    assert any(w <= cap for w in widths_on), \
        "expected the orderBy sort at the shrunk capacity"
    assert full_on < full_off, \
        (f"agg shrink no longer bounds the downstream sort: "
         f"{widths_on} vs unshrunk {widths_off}")


def test_streamed_scan_step_traffic(spark, tmp_path):
    """The per-batch jitted step of the streamed scan→sum pipeline (the
    parquet bench lane with prefetch overlap): bytes accessed bounded at
    a small multiple of one batch, flops ~1/row.  A compact regrowth or
    accidental wide materialization trips this off-hardware."""
    import pandas as pd
    import spark_tpu.config as C
    import spark_tpu.kernels as K
    from spark_tpu import io as tio
    from spark_tpu.sql import multibatch as mb
    p = tmp_path / "scan.parquet"
    p.mkdir()
    pd.DataFrame({"x": np.arange(8192, dtype=np.int64)}).to_parquet(
        p / "part-0.parquet", index=False)
    old_batch = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, "1024")
    old_mxu = K.MXU_AGG_ENABLED
    K.MXU_AGG_ENABLED = False
    try:
        df = spark.read.parquet(str(p)).agg(F.sum("x").alias("s"))
        qe = QueryExecution(spark, df._plan)
        ex = mb.plan_multibatch(spark, qe.optimized)
        assert ex is not None
        tmpl = next(iter(tio.scan_file_batches(ex.dec.rel, 1024)))
        from spark_tpu.columnar import normalize_valids, pad_to_capacity
        tmpl = normalize_valids(pad_to_capacity(tmpl, ex.capacity))
        jstep, _schema = ex._build_step(tmpl)
        ca = jstep.lower(tmpl.to_device()).compile().cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        batch_bytes = ex.capacity * 8
        ratio = d["bytes accessed"] / batch_bytes
        # measured (XLA:CPU, r5 2026-07-31): ratio 9.8 (tiny 1024-row
        # batch: padded result buffers amortize poorly) — ~1.35x anchor
        assert ratio <= 13.0, \
            f"streamed scan step traffic regressed: {ratio:.1f}x batch"
    finally:
        K.MXU_AGG_ENABLED = old_mxu
        spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old_batch))
