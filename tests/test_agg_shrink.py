"""Adaptive keyed-aggregate output capacity (PAggShrink).

Keyed agg/distinct outputs are sliced to `spark.sql.agg.outputCapacity`
rows so downstream sorts/joins stop paying full-input-capacity work for
a handful of groups (q3: 64 brands in a 4M batch); a traced overflow
flag + adaptive retry grows the bound when the true group count exceeds
it — the join-output-factor discipline applied to aggregation
(`HashAggregateExec` outputs are naturally |groups|-sized; static
shapes force bound-and-grow)."""

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F


@pytest.fixture()
def tiny_cap(spark):
    old = spark.conf.get(C.AGG_OUTPUT_ROWS)
    spark.conf.set(C.AGG_OUTPUT_ROWS.key, "64")
    # adapted capacities are cached per plan: clear so each test measures
    spark._adapted_factors.clear()
    yield spark
    spark.conf.set(C.AGG_OUTPUT_ROWS.key, str(old))


def _table(spark, n=5000, nkeys=500, seed=3):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, nkeys, n).astype(np.int64)
    v = rng.integers(0, 100, n).astype(np.int64)
    return spark.createDataFrame({"k": k, "v": v}), \
        pd.DataFrame({"k": k, "v": v})


def test_shrink_overflow_grows_and_stays_exact(tiny_cap):
    """500 groups against a 64-row bound: the retry loop must grow the
    capacity and deliver the exact group table."""
    df, pdf = _table(tiny_cap)
    got = {r["k"]: r["s"] for r in
           df.groupBy("k").agg(F.sum("v").alias("s")).collect()}
    exp = pdf.groupby("k").v.sum()
    assert len(got) == len(exp)
    assert all(got[k] == v for k, v in exp.items())


def test_shrunk_agg_feeds_sort_and_limit(tiny_cap):
    """The q3 shape: groupBy → orderBy desc → limit over a shrunk (and
    re-grown) group table."""
    df, pdf = _table(tiny_cap)
    got = [(r["k"], r["s"]) for r in
           (df.groupBy("k").agg(F.sum("v").alias("s"))
            .orderBy(F.col("s").desc(), F.col("k")).limit(10).collect())]
    exp = (pdf.groupby("k", as_index=False).v.sum()
           .rename(columns={"v": "s"})
           .sort_values(["s", "k"], ascending=[False, True]).head(10))
    assert got == list(zip(exp.k, exp.s))


def test_distinct_shrinks_and_grows(tiny_cap):
    df, pdf = _table(tiny_cap, n=3000, nkeys=400)
    got = sorted(r["k"] for r in df.select("k").distinct().collect())
    assert got == sorted(pdf.k.unique())


def test_no_overflow_when_groups_fit(spark):
    """Group counts under the default bound must not trigger any retry
    (the shrink is lossless when groups fit)."""
    df, pdf = _table(spark, n=2000, nkeys=30)
    got = {r["k"]: r["s"] for r in
           df.groupBy("k").agg(F.count("*").alias("s")).collect()}
    exp = pdf.groupby("k").size()
    assert all(got[k] == v for k, v in exp.items())


def test_distributed_shrink_grows_and_stays_exact(spark):
    """The same bound-and-grow on the 8-device mesh: per-shard group
    tables shrink, the overflow rides the shard_map's shrink channel,
    and the retry grows the capacity."""
    spark.conf.set("spark.tpu.mesh.shards", "8")
    old = spark.conf.get(C.AGG_OUTPUT_ROWS)
    spark.conf.set(C.AGG_OUTPUT_ROWS.key, "64")
    spark._adapted_factors.clear()
    try:
        df, pdf = _table(spark, n=4000, nkeys=300, seed=9)
        got = {r["k"]: r["s"] for r in
               df.groupBy("k").agg(F.sum("v").alias("s")).collect()}
        exp = pdf.groupby("k").v.sum()
        assert len(got) == len(exp)
        assert all(got[k] == v for k, v in exp.items())
        got_d = sorted(r["k"] for r in df.select("k").distinct().collect())
        assert got_d == sorted(pdf.k.unique())
    finally:
        spark.conf.set(C.AGG_OUTPUT_ROWS.key, str(old))
        spark.conf.set("spark.tpu.mesh.shards", "1")
