"""Worker for the lineage-recovery tests (not a test module itself —
launched as a subprocess by test_recovery.py and bin/chaos).

argv: <process_id> <n_processes> <shuffle_root> <mode> [timeout_s]

Each process WRITES its strided slice of the join tables to parquet
under the shared root and reads it back through ``read.parquet`` — so
every leaf is a partitioned ``FileRelation`` whose re-read recipe the
digest round publishes to peers (the lineage stage recovery re-executes
from).  A FaultInjector armed from SPARK_TPU_FAULT_PLAN kills the
victim process mid-exchange (it exits 43); a per-process
``HeartbeatMonitor`` converts the silence into a blacklist exclusion
and a structured ``ExchangeFetchFailed`` on the survivor.

mode "recover"   — ``maxStageRetries`` left at its default (1): the
    survivor must run the ``{xid}-recover`` agreement round, adopt the
    dead pid's parquet partitions from its published recipes, re-execute
    under epoch 1, and produce the EXACT full-data oracle rows.  Prints
    ``[p<pid>] OK <rows> retries=<n> recovered=<n> epoch=<e>`` after
    asserting ``stage_retries >= 1``, ``recovered_partitions > 0`` and
    a nonzero epoch gauge.
mode "norecover" — ``maxStageRetries=0``: the pre-recovery contract
    byte-for-byte — the survivor fails BOUNDED with the structured
    error naming the lost host: ``[p<pid>] FAILED <elapsed> <lost>``,
    and the recovery counters stay zero.
mode "grace-recover" — the "recover" contract under a host budget
    CAPPED below the reducers' drained working set: the survivor is
    mid-GRACE (sink re-bucketed into spill files) when the victim's
    death surfaces at the -fin merge, so the recovery epoch must replay
    cleanly over partially-spilled grace state — and the replay, now
    holding the whole data on fewer processes, grace-degrades again.
    Additionally asserts nonzero ``grace_buckets_used`` before OK.
mode "bs-*" — the disaggregated-block-service battery: same query with
    ``spark.tpu.blockserver.enabled`` on.
    "bs-zero"    — retry budget forced to ZERO: the survivor must reach
        the exact oracle purely by adopting the dead peer's registered
        blocks (asserts ``stage_retries == 0``, ``epoch == 0`` and
        nonzero adoption counters — zero re-executed map tasks).
    "bs-adopt"   — victim dies post-seal/pre-marker: the sealed
        manifest adopts, the unfinished downstream stages recover
        (asserts ``manifests_adopted >= 1`` AND ``stage_retries >= 1``).
    "bs-recover" — victim dies pre-seal: nothing adoptable, pure r12
        re-execution (asserts ``manifests_adopted == 0``).
    "bs-unavail" — the SURVIVOR's block service is down: adoption
        degrades to a counted event, recovery still lands the oracle
        (asserts ``blockserver_unavailable >= 1``,
        ``blocks_adopted == 0``).

Any partial result prints ``[p<pid>] PARTIAL`` and exits 1 — the
launcher greps for it; it must never appear.
"""

import os
import sys
import time

pid = int(sys.argv[1])
n = int(sys.argv[2])
root = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "recover"
timeout_s = float(sys.argv[5]) if len(sys.argv) > 5 else 20.0

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from spark_tpu import config as C  # noqa: E402
from spark_tpu.parallel.cluster import HeartbeatMonitor  # noqa: E402
from spark_tpu.parallel.faults import FaultInjector  # noqa: E402
from spark_tpu.parallel.hostshuffle import ExchangeFetchFailed  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402

# every process derives the SAME full dataset and owns a strided 1/n
# slice — so the single-process oracle is computable locally, and a
# correct recovery (survivor adopting the victim's partition) yields
# exactly the oracle rows while a silently-partial join cannot
rng = np.random.default_rng(7)
N, M = 900, 600
f_sk = rng.integers(0, 40, N).astype(np.int64)
f_price = rng.integers(1, 200, N).astype(np.int64)
k2 = (rng.integers(0, 20, M) * 2).astype(np.int64)
b2 = rng.integers(1, 100, M).astype(np.int64)
if mode == "grace-recover":
    # 40 distinct keys hash so unevenly across two reducers that one
    # shard stays under any budget the other can survive — widen the
    # key space AND the row counts so EVERY reducer's drained share of
    # EACH side alone overflows the grace-mode cap (the lane trades a
    # side's fetch reservation for its compacted shard, so pressure
    # must arrive within one side's drain)
    N, M = 1500, 1000
    f_sk = rng.integers(0, 200, N).astype(np.int64)
    f_price = rng.integers(1, 200, N).astype(np.int64)
    k2 = (rng.integers(0, 100, M) * 2).astype(np.int64)
    b2 = rng.integers(1, 100, M).astype(np.int64)
mine = slice(pid, None, n)

session = SparkSession.builder.appName(f"recov-{pid}").getOrCreate()

# each process persists ITS OWN partition as parquet on the shared
# filesystem — the leaf files a survivor re-reads for a dead peer
wr = session.newSession()
wr.conf.set(C.MESH_SHARDS.key, "1")
fact_dir = os.path.join(root, "leaves", f"fact-p{pid}")
fact2_dir = os.path.join(root, "leaves", f"fact2-p{pid}")
wr.createDataFrame({"sk": f_sk[mine], "price": f_price[mine]}) \
    .write.parquet(fact_dir)
wr.createDataFrame({"k2": k2[mine], "bonus": b2[mine]}) \
    .write.parquet(fact2_dir)

xs = session.newSession()
xs.conf.set(C.MESH_SHARDS.key, "1")
xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "2048")
xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
# fast failure detection: the victim's silence must become a blacklist
# exclusion well inside one exchange deadline
xs.conf.set("spark.tpu.cluster.heartbeatIntervalMs", "100")
xs.conf.set("spark.tpu.cluster.heartbeatTimeoutMs", "600")
if mode.startswith("bs-"):
    # every process registers its map outputs with the shared block
    # service at manifest-commit time; set BEFORE enableHostShuffle —
    # the client attaches at service construction
    xs.conf.set(C.BLOCKSERVER_ENABLED.key, "true")
    if mode == "bs-zero":
        # the zero-re-execution proof: ANY recovery attempt would blow
        # the zero budget and fail the query, so an oracle-exact OK can
        # only come from adopting the dead peer's registered output
        xs.conf.set(C.RECOVERY_MAX_STAGE_RETRIES.key, "0")
if mode == "norecover":
    xs.conf.set(C.RECOVERY_MAX_STAGE_RETRIES.key, "0")
elif mode == "grace-recover":
    # forced-spill staging plus a budget EVERY reducer's drained share
    # must overflow.  ``plan_reducers`` packs fine buckets greedily to
    # the partition-bytes target, so the 2048 default above would hand
    # reducer 0 a ~2 KiB sliver and the rest to the last reducer —
    # raise the target to ~half the shipped working set (~28 KiB: the
    # fact side prunes to sk at 8 B/row, fact2 ships k2+bonus at
    # 16 B/row) so both reducer shards land near 14 KiB and every
    # per-side drain (~6/8 KiB) alone overflows the 4 KiB budget.  Set
    # BEFORE enableHostShuffle, the ledger reads it at construction.
    # The keys are near-uniform, so grace buckets stay far below the
    # budget in every epoch.
    from spark_tpu.memory import HOST_BUDGET
    xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "14336")
    xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, "1024")
    xs.conf.set(HOST_BUDGET.key, str(4 << 10))
hb = HeartbeatMonitor(os.path.join(root, "beats"),
                      host_id=f"host-{pid}", conf=xs.conf_obj)
hb.start()
svc = xs.enableHostShuffle(root, process_id=pid, n_processes=n,
                           timeout_s=timeout_s, heartbeat=hb)
FaultInjector().attach(svc)          # plan comes from SPARK_TPU_FAULT_PLAN

xs.read.parquet(fact_dir).createOrReplaceTempView("fact")
xs.read.parquet(fact2_dir).createOrReplaceTempView("fact2")

oracle = session.newSession()
oracle.conf.set(C.MESH_SHARDS.key, "1")
oracle.createDataFrame({"sk": f_sk, "price": f_price}) \
    .createOrReplaceTempView("fact")
oracle.createDataFrame({"k2": k2, "bonus": b2}) \
    .createOrReplaceTempView("fact2")

SQL = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
       "JOIN fact2 ON sk = k2 GROUP BY sk ORDER BY sk")
exp = [tuple(r) for r in oracle.sql(SQL).collect()]

t0 = time.time()
try:
    got = [tuple(r) for r in xs.sql(SQL).collect()]
except (ExchangeFetchFailed, TimeoutError) as e:
    lost = sorted(getattr(e, "lost_hosts", []) or [])
    print(f"[p{pid}] FAILED {time.time() - t0:.2f} {lost}", flush=True)
    os._exit(0)

if got != exp:
    print(f"[p{pid}] PARTIAL got={len(got)} exp={len(exp)}", flush=True)
    os._exit(1)
if mode in ("recover", "grace-recover"):
    gauges = svc.metrics_source().snapshot()
    assert svc.counters["stage_retries"] >= 1, svc.counters
    assert svc.counters["recovered_partitions"] > 0, svc.counters
    assert gauges["epoch"] >= 1, gauges
    if mode == "grace-recover":
        # the capped budget really did force the degraded path (before
        # the loss, after it, or both), and the epoch replay over the
        # partially-spilled grace state still reached the exact oracle
        assert svc.counters["grace_buckets_used"] > 0, svc.counters
        assert svc.counters["grace_spill_bytes"] > 0, svc.counters
        assert 0 < gauges["peak_host_bytes"] \
            <= gauges["host_budget_bytes"], gauges
    print(f"[p{pid}] OK {len(got)} "
          f"retries={svc.counters['stage_retries']} "
          f"recovered={svc.counters['recovered_partitions']} "
          f"epoch={gauges['epoch']} "
          f"grace={svc.counters['grace_buckets_used']}", flush=True)
elif mode.startswith("bs-"):
    gauges = svc.metrics_source().snapshot()
    if mode == "bs-zero":
        # the dead peer's registered output was ADOPTED: exact oracle
        # with the recovery machinery never armed — zero re-executed
        # map tasks, zero epochs, and the adoption counters prove the
        # block really came out of service custody
        assert svc.counters["stage_retries"] == 0, svc.counters
        assert gauges["epoch"] == 0, gauges
        assert svc.counters["blocks_adopted"] >= 1, svc.counters
        assert svc.counters["blockserver_fallback_reads"] >= 1, \
            svc.counters
    elif mode == "bs-adopt":
        # sealed-but-unmarked manifest adopted at the barrier; the
        # victim's unfinished downstream stages still needed recovery
        assert svc.counters["manifests_adopted"] >= 1, svc.counters
        assert svc.counters["stage_retries"] >= 1, svc.counters
    elif mode == "bs-recover":
        # death BEFORE the seal: nothing adoptable, pure re-execution
        assert svc.counters["manifests_adopted"] == 0, svc.counters
        assert svc.counters["stage_retries"] >= 1, svc.counters
    elif mode == "bs-unavail":
        # service down on this side: every adoption attempt degraded to
        # a counted event (no hang, no partial), recovery did the rest
        assert svc.counters["blockserver_unavailable"] >= 1, svc.counters
        assert svc.counters["blocks_adopted"] == 0, svc.counters
        assert svc.counters["stage_retries"] >= 1, svc.counters
    print(f"[p{pid}] OK {len(got)} "
          f"retries={svc.counters['stage_retries']} "
          f"adopted={svc.counters['manifests_adopted']}m"
          f"/{svc.counters['blocks_adopted']}b "
          f"fallback={svc.counters['blockserver_fallback_reads']} "
          f"unavail={svc.counters['blockserver_unavailable']}",
          flush=True)
else:
    # norecover with no fault on this process's path: plain success,
    # and the recovery machinery must not have stirred
    assert svc.counters["stage_retries"] == 0, svc.counters
    print(f"[p{pid}] OK {len(got)} retries=0", flush=True)
os._exit(0)
