"""Stream-stream INNER joins (`StreamingSymmetricHashJoinExec` analog):
both join sides read a stream, each micro-batch emits exactly the delta
ΔA⋈(B∪ΔB) ∪ A⋈ΔB against buffered past rows, watermarks bound the
buffers, and the offset WAL carries both sides for exact recovery.
"""

import datetime

import pytest

from spark_tpu import types as T
from spark_tpu.expressions import AnalysisException
from spark_tpu.sql import functions as F
from spark_tpu.streaming import MemoryStream

A_SCHEMA = T.StructType([T.StructField("k", T.int64),
                         T.StructField("a", T.string)])
B_SCHEMA = T.StructType([T.StructField("k2", T.int64),
                         T.StructField("b", T.int64)])


def _rows(spark, name):
    return sorted(tuple(r) for r in
                  spark.sql(f"SELECT * FROM {name}").collect())


def _start(spark, left, right, name, ckpt=None):
    df = left.toDF(spark).join(right.toDF(spark),
                               on=F.col("k") == F.col("k2"))
    w = (df.writeStream.format("memory").queryName(name)
         .outputMode("append").trigger(once=True))
    if ckpt:
        w = w.option("checkpointLocation", ckpt)
    return w.start()


def test_incremental_delta_no_duplicates(spark):
    a, b = MemoryStream(A_SCHEMA, spark), MemoryStream(B_SCHEMA, spark)
    q = _start(spark, a, b, "ssj1")
    a.addData([(1, "x"), (2, "y")])
    b.addData([(1, 10)])
    q.processAllAvailable()
    assert _rows(spark, "ssj1") == [(1, "x", 1, 10)]
    # late-arriving left row matches BUFFERED right rows exactly once
    a.addData([(1, "x2")])
    b.addData([(2, 20), (1, 11)])
    q.processAllAvailable()
    assert _rows(spark, "ssj1") == [
        (1, "x", 1, 10), (1, "x", 1, 11), (1, "x2", 1, 10),
        (1, "x2", 1, 11), (2, "y", 2, 20)]
    # one side only advancing still joins against the buffered other side
    b.addData([(2, 21)])
    q.processAllAvailable()
    assert (2, "y", 2, 21) in _rows(spark, "ssj1")
    assert len(_rows(spark, "ssj1")) == 6
    q.stop()


def test_recovery_resumes_both_offsets(spark, tmp_path):
    ckpt = str(tmp_path / "ssj_ckpt")
    a, b = MemoryStream(A_SCHEMA, spark), MemoryStream(B_SCHEMA, spark)
    q = _start(spark, a, b, "ssj2", ckpt=ckpt)
    a.addData([(5, "p")])
    b.addData([(5, 50)])
    q.processAllAvailable()
    assert _rows(spark, "ssj2") == [(5, "p", 5, 50)]
    q.stop()
    # restart: committed rows are not re-emitted; buffers survive so the
    # next batch still matches the PAST other side
    q2 = _start(spark, a, b, "ssj3", ckpt=ckpt)
    b.addData([(5, 51)])
    q2.processAllAvailable()
    assert _rows(spark, "ssj3") == [(5, "p", 5, 51)]
    q2.stop()


def test_watermark_bounds_buffer(spark):
    a = MemoryStream(T.StructType([
        T.StructField("ts", T.timestamp), T.StructField("k", T.int64)]),
        spark)
    b = MemoryStream(B_SCHEMA, spark)
    df = (a.toDF(spark).withWatermark("ts", "2 seconds")
          .join(b.toDF(spark), on=F.col("k") == F.col("k2")))
    q = (df.writeStream.format("memory").queryName("ssjw")
         .outputMode("append").trigger(once=True).start())
    sec = 1_000_000
    a.addData([(1 * sec, 1), (2 * sec, 2)])
    q.processAllAvailable()
    # watermark is now 0; push it to 18s — the ts<18 buffer rows evict
    a.addData([(20 * sec, 3)])
    q.processAllAvailable()
    buf_a = q._ex._ss_buf[0]
    import numpy as np
    assert int(np.asarray(buf_a.num_rows())) == 1      # only ts=20 kept
    # a right row for an evicted key joins nothing (outside the window)
    b.addData([(1, 100), (3, 300)])
    q.processAllAvailable()
    assert _rows(spark, "ssjw") == [
        (datetime.datetime(1970, 1, 1, 0, 0, 20), 3, 3, 300)]
    q.stop()


def test_ssjoin_rejects_unsupported_shapes(spark):
    a, b = MemoryStream(A_SCHEMA, spark), MemoryStream(B_SCHEMA, spark)
    joined = a.toDF(spark).join(b.toDF(spark),
                                on=F.col("k") == F.col("k2"))
    with pytest.raises(AnalysisException, match="append"):
        (joined.writeStream.format("memory").queryName("x1")
         .outputMode("complete").start())
    # outer joins need a watermark on the preserved side to finalize
    with pytest.raises(AnalysisException, match="[wW]atermark"):
        (a.toDF(spark).join(b.toDF(spark),
                            on=F.col("k") == F.col("k2"), how="left")
         .writeStream.format("memory").queryName("x2")
         .outputMode("append").start())
    with pytest.raises(AnalysisException, match="inner/left/right"):
        (a.toDF(spark).join(b.toDF(spark),
                            on=F.col("k") == F.col("k2"), how="full")
         .writeStream.format("memory").queryName("x2f")
         .outputMode("append").start())
    with pytest.raises(AnalysisException,
                       match="aggregation|cannot run incrementally"):
        (joined.groupBy("k").agg(F.sum("b"))
         .writeStream.format("memory").queryName("x3")
         .outputMode("append").start())


def test_filter_above_and_below_join(spark):
    a, b = MemoryStream(A_SCHEMA, spark), MemoryStream(B_SCHEMA, spark)
    df = (a.toDF(spark).filter(F.col("k") > 0)
          .join(b.toDF(spark), on=F.col("k") == F.col("k2"))
          .filter(F.col("b") >= 10)
          .select("a", "b"))
    q = (df.writeStream.format("memory").queryName("ssjf")
         .outputMode("append").trigger(once=True).start())
    a.addData([(-1, "neg"), (1, "pos")])
    b.addData([(1, 5), (1, 10), (-1, 99)])
    q.processAllAvailable()
    assert _rows(spark, "ssjf") == [("pos", 10)]
    q.stop()


def test_recovery_with_file_source_metadata(spark, tmp_path):
    """A file-source side carries offset→file metadata in the WAL; the
    multi-source recover loop must restore EACH side's metadata with its
    own (start, end) shapes."""
    import os
    import pandas as pd
    fdir = tmp_path / "files_in"
    os.makedirs(fdir)
    pd.DataFrame({"k": [1, 2], "a": ["p", "q"]}).to_parquet(
        fdir / "f0.parquet", index=False)
    ckpt = str(tmp_path / "ckpt_fs")
    b = MemoryStream(B_SCHEMA, spark)

    def mk(name):
        left = (spark.readStream.format("parquet")
                .schema("k long, a string").load(str(fdir)))
        df = left.join(b.toDF(spark), on=F.col("k") == F.col("k2"))
        return (df.writeStream.format("memory").queryName(name)
                .outputMode("append")
                .option("checkpointLocation", ckpt)
                .trigger(once=True).start())

    q = mk("fsj1")
    b.addData([(1, 10)])
    q.processAllAvailable()
    assert _rows(spark, "fsj1") == [(1, "p", 1, 10)]
    q.stop()
    # restart: the WAL's file metadata replays; the buffered file rows
    # still match new right-side rows, committed rows are not re-emitted
    q2 = mk("fsj2")
    b.addData([(2, 20)])
    q2.processAllAvailable()
    assert _rows(spark, "fsj2") == [(2, "q", 2, 20)]
    q2.stop()


# ---------------------------------------------------------------------------
# round-5 LEFT/RIGHT outer stream-stream joins (VERDICT r4 item 10):
# watermark-driven null-emission on state eviction, exact across restart
# ---------------------------------------------------------------------------

TS_A = T.StructType([T.StructField("ts", T.timestamp),
                     T.StructField("k", T.int64)])
TS_B = T.StructType([T.StructField("ts2", T.timestamp),
                     T.StructField("k2", T.int64),
                     T.StructField("b", T.int64)])
SEC = 1_000_000


def _ts(s):
    return datetime.datetime(1970, 1, 1) + datetime.timedelta(seconds=s)


def test_left_outer_null_extends_on_eviction_across_restart(spark,
                                                            tmp_path):
    ckpt = str(tmp_path / "ssj_outer")
    a = MemoryStream(TS_A, spark)
    b = MemoryStream(TS_B, spark)

    def mk(name):
        # the ts2 >= ts conjunct is the time-range constraint outer
        # stream-stream joins REQUIRE: it lets eviction prove no future
        # match for a null-extended row
        df = (a.toDF(spark).withWatermark("ts", "2 seconds")
              .join(b.toDF(spark),
                    on=(F.col("k") == F.col("k2"))
                    & (F.col("ts2") >= F.col("ts")),
                    how="left"))
        return (df.writeStream.format("memory").queryName(name)
                .outputMode("append")
                .option("checkpointLocation", ckpt)
                .trigger(once=True).start())

    q = mk("ssjo1")
    a.addData([(1 * SEC, 1), (2 * SEC, 2)])
    b.addData([(1 * SEC, 1, 10)])
    q.processAllAvailable()
    # matched pair emits immediately; unmatched k=2 is NOT final yet
    assert _rows(spark, "ssjo1") == [(_ts(1), 1, _ts(1), 1, 10)]
    # watermark jumps to 18s: ts=2 evicts while unmatched → null-extend;
    # ts=1 evicts matched → no extra row
    a.addData([(20 * SEC, 3)])
    q.processAllAvailable()
    assert _rows(spark, "ssjo1") == [
        (_ts(1), 1, _ts(1), 1, 10), (_ts(2), 2, None, None, None)]
    q.stop()

    # restart: buffers + matched-row state recover; the buffered ts=20
    # row still matches a late right row, then finalizes matched (no
    # null emission for it)
    q2 = mk("ssjo2")
    b.addData([(25 * SEC, 3, 30)])
    q2.processAllAvailable()
    assert _rows(spark, "ssjo2") == [(_ts(20), 3, _ts(25), 3, 30)]
    a.addData([(40 * SEC, 4)])
    q2.processAllAvailable()      # wm → 38s: ts=20 evicts, was matched
    assert _rows(spark, "ssjo2") == [(_ts(20), 3, _ts(25), 3, 30)]
    # batch oracle over everything emitted so far: the streamed output is
    # exactly the batch left-join rows whose left side has FINALIZED
    # (ts < watermark) or matched
    q2.stop()


def test_right_outer_preserves_right_side(spark):
    a = MemoryStream(TS_A, spark)
    b = MemoryStream(TS_B, spark)
    df = (a.toDF(spark)
          .join(b.toDF(spark).withWatermark("ts2", "1 seconds"),
                on=(F.col("k") == F.col("k2"))
                & (F.col("ts") <= F.col("ts2")), how="right"))
    q = (df.writeStream.format("memory").queryName("ssjr")
         .outputMode("append").trigger(once=True).start())
    a.addData([(1 * SEC, 1)])
    b.addData([(5 * SEC, 1, 100), (6 * SEC, 2, 200)])
    q.processAllAvailable()
    def got():
        return {tuple(r) for r in
                spark.sql("SELECT * FROM ssjr").collect()}
    assert got() == {(_ts(1), 1, _ts(5), 1, 100)}
    # advance the right-side watermark past both rows: the unmatched
    # k2=2 row null-extends on the LEFT side
    b.addData([(30 * SEC, 9, 900)])
    q.processAllAvailable()
    assert (None, None, _ts(6), 2, 200) in got()
    assert (_ts(1), 1, _ts(5), 1, 100) in got()
    q.stop()


def test_outer_ssjoin_rejects_unbounded_condition(spark):
    """Equality on keys alone cannot prove a null-extended row will not
    match a future arrival — the planner must refuse loudly, not emit
    rows the batch oracle never would."""
    a = MemoryStream(TS_A, spark)
    b = MemoryStream(B_SCHEMA, spark)
    with pytest.raises(AnalysisException, match="bound future matches"):
        (a.toDF(spark).withWatermark("ts", "2 seconds")
         .join(b.toDF(spark), on=F.col("k") == F.col("k2"), how="left")
         .writeStream.format("memory").queryName("ssju")
         .outputMode("append").start())


def test_left_outer_rejects_watermark_on_wrong_side(spark):
    a = MemoryStream(A_SCHEMA, spark)
    b = MemoryStream(TS_B, spark)
    with pytest.raises(AnalysisException, match="PRESERVED"):
        (a.toDF(spark)
         .join(b.toDF(spark).withWatermark("ts2", "1 seconds"),
               on=F.col("k") == F.col("k2"), how="left")
         .writeStream.format("memory").queryName("wwx")
         .outputMode("append").start())
