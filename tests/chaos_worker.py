"""Chaos worker: a checkpointed multibatch aggregation that SIGKILLs
itself mid-scan on the first gang attempt (marker file absent), then —
relaunched by the supervising launcher — resumes from the multibatch
checkpoint and completes.  Driven by tests/test_chaos_restart.py."""
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

data_dir, ckpt_dir, marker, out_path = sys.argv[1:5]

os.environ.setdefault("SPARK_TPU_PLATFORM", "cpu")
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
from spark_tpu.sql.session import SparkSession          # noqa: E402
from spark_tpu.sql import functions as F                # noqa: E402
from spark_tpu.sql import multibatch as mb              # noqa: E402

first_attempt = not os.path.exists(marker)

# instrument checkpoint save/load so the harness can assert the resume
orig_save = mb.MultiBatchExecution._ckpt_save
orig_load = mb.MultiBatchExecution._ckpt_load
saves = {"n": 0}


def save(self, path, n_batches, merger):
    orig_save(self, path, n_batches, merger)
    saves["n"] += 1
    print(f"CKPT-SAVE {n_batches}", flush=True)
    if first_attempt and saves["n"] >= 2:
        open(marker, "w").close()
        print("CHAOS-KILL", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)


def load(self, ckpt):
    skip, merger = orig_load(self, ckpt)
    print(f"CKPT-SKIP {skip}", flush=True)
    return skip, merger


mb.MultiBatchExecution._ckpt_save = save
mb.MultiBatchExecution._ckpt_load = load

spark = SparkSession.builder.appName("chaos").getOrCreate()
spark.conf.set("spark.tpu.scan.maxBatchRows", "256")
spark.conf.set("spark.tpu.multibatch.checkpointDir", ckpt_dir)
spark.conf.set("spark.tpu.multibatch.checkpointInterval", "1")

df = (spark.read.parquet(data_dir).groupBy("k")
      .agg(F.sum("v").alias("s"), F.count("*").alias("c")))
rows = sorted((r["k"], r["s"], r["c"]) for r in df.collect())
with open(out_path, "w") as f:
    for k, s, c in rows:
        f.write(f"{k},{s},{c}\n")
print("CHAOS-QUERY-OK", flush=True)
