"""Chaos suite for the fault-tolerant DCN exchange (ISSUE: retrying
host-shuffle fetches, peer blacklisting, bounded-time failure).

Every recovery path of ``parallel/hostshuffle.py`` runs here under the
deterministic fault injector (``parallel/faults.py``) — no hardware, no
uncontrolled timing:

- transiently missing / truncated blocks heal and the retrying reader
  completes the exchange (retry counters prove retries happened);
- permanent loss raises a structured ``ExchangeFetchFailed`` naming the
  lost host and block, within the configured time bound;
- a confirmed-dead peer is excluded from the barrier and blacklisted
  for subsequent exchanges (fast failure, not repeated timeouts);
- a peer killed mid-exchange (real subprocess, ``die_after_put``)
  either completes (it committed first — blocks survive the process)
  or fails structured within 2x the deadline, never hangs;
- the keyed-aggregate refetch path re-reads a recovered peer's blocks
  after a re-barrier;
- counters surface through the session metrics system.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu import wire
from spark_tpu.columnar import ColumnBatch
from spark_tpu.parallel.cluster import HeartbeatMonitor
from spark_tpu.parallel.faults import FAULT_PLAN_ENV, FaultInjector, FaultPlan
from spark_tpu.parallel.hostshuffle import (
    BlockFetchError, ExchangeFetchFailed, HostShuffleService,
    RetryingBlockReader,
)


def _batch(vals):
    return ColumnBatch.from_arrays({"v": np.asarray(vals, np.int64)})


def _values(batches):
    return sorted(int(x) for b in batches
                  for x, ok in zip(np.asarray(b.column("v").data),
                                   np.asarray(b.row_valid_or_true()))
                  if ok)


def _pair(tmp_path, **kw):
    """Two services on one shared root (pids 0/1), test-speed retries."""
    defaults = dict(timeout_s=5.0, poll_s=0.02, max_retries=8,
                    retry_wait_s=0.05, attempt_timeout_s=1.0)
    defaults.update(kw)
    return (HostShuffleService(str(tmp_path), 0, 2, **defaults),
            HostShuffleService(str(tmp_path), 1, 2, **defaults))


# ---------------------------------------------------------------------------
# retrying reader: transient faults heal, permanent loss is structured
# ---------------------------------------------------------------------------

def test_delayed_block_retried_to_success(tmp_path):
    svc0, svc1 = _pair(tmp_path)
    FaultInjector(FaultPlan().delay(0.25, exchange="e")).attach(svc1)
    svc1.put("e", 0, [_batch([7, 8])])   # delay rule hides the block...
    svc1.commit("e")                     # ...but the manifest names it
    got = svc0.exchange("e", {0: [_batch([1])], 1: [_batch([2])]})
    assert _values(got) == [1, 7, 8]
    assert svc0.counters["block_retries"] > 0
    assert svc0.counters["blocks_lost"] == 0


def test_truncated_block_retried_to_success(tmp_path):
    svc0, svc1 = _pair(tmp_path)
    FaultInjector(FaultPlan().truncate(exchange="e",
                                       heal_after_s=0.25)).attach(svc1)
    svc1.put("e", 0, [_batch([5, 6])])
    svc1.commit("e")
    got = svc0.exchange("e", {0: [], 1: []})
    assert _values(got) == [5, 6]
    assert svc0.counters["block_retries"] > 0


def test_permanent_drop_fails_structured_and_bounded(tmp_path):
    svc0, svc1 = _pair(tmp_path, timeout_s=3.0, max_retries=2)
    FaultInjector(FaultPlan().drop(exchange="e")).attach(svc1)
    svc1.put("e", 0, [_batch([9])])
    svc1.commit("e")
    t0 = time.monotonic()
    with pytest.raises(ExchangeFetchFailed) as ei:
        svc0.exchange("e", {0: [], 1: []})
    assert time.monotonic() - t0 < 2 * 3.0       # bounded-time failure
    assert ei.value.lost_hosts == ["host-1"]
    assert ei.value.lost_blocks == ["s0001-r0000.part"]
    assert "host-1" in str(ei.value)             # names the host loudly
    assert svc0.counters["blocks_lost"] == 1
    assert svc0.counters["fetch_failures"] == 1


def test_reader_respects_deadline(tmp_path):
    """With a tight deadline the reader gives up early instead of
    sleeping through all its retries."""
    reader = RetryingBlockReader(max_retries=50, retry_wait_s=0.2)
    t0 = time.monotonic()
    with pytest.raises(BlockFetchError):
        reader.read(str(tmp_path / "never.part"),
                    deadline=time.monotonic() + 0.3)
    assert time.monotonic() - t0 < 1.5


# ---------------------------------------------------------------------------
# wire-format error classes: each transient shape retries, foreign
# frames fail fast (ISSUE 2: checksum-mismatch and short-frame are
# retryable partial writes, same backoff path as EOFError/Unpickling)
# ---------------------------------------------------------------------------

def _wire_frame(vals):
    return wire.encode_batches([_batch(vals).to_host()])


def _healing_reader(path, good, retries):
    """A reader whose backoff sleep 'heals' the block on disk — the
    torn-write-then-completed-write sequence, made deterministic."""
    def heal(_wait):
        with open(path, "wb") as f:
            f.write(good)
    return RetryingBlockReader(max_retries=3, retry_wait_s=0.01,
                               sleep=heal, on_retry=retries.append)


def test_checksum_mismatch_retried_per_class(tmp_path):
    """Size-preserving corruption passes the manifest size check — only
    the frame checksum can see it.  ``wire.ChecksumError`` must ride the
    same backoff path as a missing file."""
    good = _wire_frame([21, 22])
    path = str(tmp_path / "b.part")
    with open(path, "wb") as f:
        f.write(good[:-1] + bytes([good[-1] ^ 0xFF]))
    retries = []
    got = _healing_reader(path, good, retries).read(
        path, expect_size=len(good))
    assert _values(got) == [21, 22]
    assert retries == [path]


def test_short_frame_retried_per_class(tmp_path):
    """A frame cut mid-payload raises ``wire.TruncatedBlockError`` and
    retries even with no manifest size to compare against — the frame's
    own length fields are the classifier."""
    good = _wire_frame([31, 32, 33])
    path = str(tmp_path / "b.part")
    with open(path, "wb") as f:
        f.write(good[:len(good) - 5])
    retries = []
    got = _healing_reader(path, good, retries).read(path)  # expect_size=None
    assert _values(got) == [31, 32, 33]
    assert retries == [path]


def test_foreign_frame_fails_fast_without_retry(tmp_path):
    """Good magic + unsupported version with a full-length file is not a
    partial write; re-reading cannot fix it, so the reader must not burn
    its retry budget (plain ``WireFormatError`` → immediate failure)."""
    good = _wire_frame([1])
    bad = bytearray(good)
    bad[4] = 99                          # version byte; prefix is unchecksummed
    path = str(tmp_path / "b.part")
    with open(path, "wb") as f:
        f.write(bytes(bad))
    retries = []
    reader = RetryingBlockReader(max_retries=5, retry_wait_s=0.01,
                                 on_retry=retries.append)
    with pytest.raises(BlockFetchError) as ei:
        reader.read(path, expect_size=len(good))
    assert ei.value.attempts == 1
    assert retries == []


def test_corrupted_block_detected_by_checksum_and_recovered(tmp_path):
    """End-to-end: the injector's size-preserving ``corrupt`` fault flips
    one payload byte in a committed block.  The manifest size matches, so
    ONLY the wire checksum can detect the tear; the fetch retries and
    completes once the rule heals."""
    svc0, svc1 = _pair(tmp_path)
    FaultInjector(FaultPlan().corrupt(exchange="e",
                                      heal_after_s=0.25)).attach(svc1)
    svc1.put("e", 0, [_batch([51, 52])])
    svc1.commit("e")
    got = svc0.exchange("e", {0: [_batch([1])], 1: [_batch([2])]})
    assert _values(got) == [1, 51, 52]
    assert svc0.counters["block_retries"] > 0
    assert svc0.counters["blocks_lost"] == 0


# ---------------------------------------------------------------------------
# dictionary sidecar faults: the dedup wire's once-per-sender word list
# is a block like any other — transient loss heals through the same
# retrying reader, permanent loss fails structured and bounded
# ---------------------------------------------------------------------------

def _sbatch(words):
    return ColumnBatch.from_arrays({"s": list(words)})


def _swords(batches):
    return sorted(w for b in batches for (w,) in b.to_pylist()
                  if w is not None)


def test_dict_sidecar_dropped_then_heals(tmp_path):
    """The sender's sidecar vanishes after commit (list-after-write lag);
    the receiver's first block decode trips the fingerprint miss, the
    sidecar read retries, the backoff 'heals' the file, and the exchange
    completes with the words intact."""
    svc1 = HostShuffleService(str(tmp_path), 1, 2, timeout_s=5.0,
                              poll_s=0.02, max_retries=8,
                              retry_wait_s=0.05)
    svc1.put("e", 0, [_sbatch(["ash", "oak", "ash"])])
    svc1.commit("e")
    dpath = svc1._dict_path("e", 1)
    good = open(dpath, "rb").read()
    assert good[:4] == wire.MAGIC
    os.remove(dpath)

    def heal(_wait):
        with open(dpath, "wb") as f:
            f.write(good)

    svc0 = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5.0,
                              poll_s=0.02, max_retries=8,
                              retry_wait_s=0.05, sleep=heal)
    got = svc0.exchange("e", {0: [_sbatch(["fir"])], 1: []})
    assert _swords(got) == ["ash", "ash", "fir", "oak"]
    assert svc0.counters["block_retries"] > 0
    assert svc0.counters["blocks_lost"] == 0


def test_dict_sidecar_corrupted_then_heals(tmp_path):
    """Size-preserving corruption of the sidecar: only its adler32 can
    see it (the manifest size still matches); the checksum failure rides
    the ordinary retry path and the heal completes the exchange."""
    svc1 = HostShuffleService(str(tmp_path), 1, 2, timeout_s=5.0,
                              poll_s=0.02, max_retries=8,
                              retry_wait_s=0.05)
    svc1.put("e", 0, [_sbatch(["pear", "fig"])])
    svc1.commit("e")
    dpath = svc1._dict_path("e", 1)
    good = open(dpath, "rb").read()
    with open(dpath, "wb") as f:                 # same size, one bit off
        f.write(good[:-1] + bytes([good[-1] ^ 0xFF]))

    def heal(_wait):
        with open(dpath, "wb") as f:
            f.write(good)

    svc0 = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5.0,
                              poll_s=0.02, max_retries=8,
                              retry_wait_s=0.05, sleep=heal)
    got = svc0.exchange("e", {0: [], 1: []})
    assert _swords(got) == ["fig", "pear"]
    assert svc0.counters["block_retries"] > 0


def test_dict_sidecar_permanently_lost_fails_bounded(tmp_path):
    """No heal: the unreadable sidecar makes the sender's blocks
    undecodable, so the exchange fails with the same structured
    ``ExchangeFetchFailed`` (naming the host) a lost data block raises —
    never a silent fallback to wrong codes, never a hang."""
    svc0, svc1 = _pair(tmp_path, timeout_s=3.0, max_retries=2)
    svc1.put("e", 0, [_sbatch(["lost", "words"])])
    svc1.commit("e")
    os.remove(svc1._dict_path("e", 1))
    t0 = time.monotonic()
    with pytest.raises(ExchangeFetchFailed) as ei:
        svc0.exchange("e", {0: [], 1: []})
    assert time.monotonic() - t0 < 2 * 3.0
    assert ei.value.lost_hosts == ["host-1"]
    assert svc0.counters["blocks_lost"] == 1


# ---------------------------------------------------------------------------
# heartbeat-driven exclusion + blacklist persistence
# ---------------------------------------------------------------------------

def _stale_peer_heartbeat(tmp_path):
    """A monitor for host-0 that sees host-1's only beat as stale."""
    conf = (C.Conf()
            .set("spark.tpu.cluster.heartbeatIntervalMs", "50")
            .set("spark.tpu.cluster.heartbeatTimeoutMs", "100"))
    beats = str(tmp_path / "beats")
    hb1 = HeartbeatMonitor(beats, host_id="host-1", conf=conf,
                           clock=time.time)
    hb1.beat()
    hb0 = HeartbeatMonitor(beats, host_id="host-0", conf=conf,
                           clock=time.time)
    time.sleep(0.15)                    # host-1's beat goes stale
    return hb0


def test_dead_peer_excluded_and_blacklist_persists(tmp_path):
    hb0 = _stale_peer_heartbeat(tmp_path)
    assert hb0.dead_hosts() == ["host-1"]
    svc0 = HostShuffleService(str(tmp_path / "shuf"), 0, 2, timeout_s=5.0,
                              poll_s=0.02, heartbeat=hb0, max_retries=1,
                              retry_wait_s=0.02)
    # peer 1 never commits anything: without the heartbeat this would be
    # a full 5s barrier timeout; with it the dead peer is excluded fast
    t0 = time.monotonic()
    with pytest.raises(ExchangeFetchFailed) as ei:
        svc0.exchange("e1", {0: [_batch([1])], 1: [_batch([2])]})
    first = time.monotonic() - t0
    assert first < 2.5
    assert ei.value.lost_hosts == ["host-1"]
    assert svc0.blacklist == {1: "heartbeat-dead during 'e1'"}
    assert svc0.counters["peers_blacklisted"] == 1

    # the blacklist PERSISTS across exchanges of the query: the second
    # step fails immediately (no re-detection wait at all)
    t0 = time.monotonic()
    with pytest.raises(ExchangeFetchFailed):
        svc0.exchange("e2", {0: [_batch([3])], 1: [_batch([4])]})
    assert time.monotonic() - t0 < 1.0
    assert svc0.counters["fetch_failures"] == 2


def test_dead_but_committed_peer_is_recovered(tmp_path):
    """The property the filesystem data plane exists for: a peer that
    COMMITTED before dying loses nothing — its blocks outlive it."""
    hb0 = _stale_peer_heartbeat(tmp_path)
    root = str(tmp_path / "shuf")
    svc1 = HostShuffleService(root, 1, 2, timeout_s=5.0)
    svc1.put("e", 0, [_batch([41, 42])])
    svc1.commit("e")                     # ...then host-1 "dies"
    svc0 = HostShuffleService(root, 0, 2, timeout_s=5.0, poll_s=0.02,
                              heartbeat=hb0)
    got = svc0.exchange("e", {0: [_batch([1])], 1: [_batch([2])]})
    assert _values(got) == [1, 41, 42]
    assert svc0.counters["blocks_lost"] == 0


def test_blacklist_can_be_disabled_by_conf(tmp_path):
    hb0 = _stale_peer_heartbeat(tmp_path)
    conf = C.Conf().set("spark.tpu.shuffle.blacklistEnabled", "false")
    svc0 = HostShuffleService(str(tmp_path / "shuf"), 0, 2, timeout_s=0.3,
                              poll_s=0.02, conf=conf, heartbeat=hb0)
    svc0.commit("e")
    # without blacklisting, a dead straggler is just a straggler: the
    # barrier stays loud-timeout (the seed behavior, opt-out preserved)
    with pytest.raises(TimeoutError, match=r"senders \[1\]"):
        svc0.barrier("e")
    assert svc0.blacklist == {}


# ---------------------------------------------------------------------------
# refetch: the keyed-aggregate fast path's one re-request
# ---------------------------------------------------------------------------

def test_refetch_recovers_republished_blocks(tmp_path):
    svc0, svc1 = _pair(tmp_path, timeout_s=2.0, max_retries=1,
                       retry_wait_s=0.02)
    FaultInjector(FaultPlan().drop(exchange="e")).attach(svc1)
    svc1.put("e", 0, [_batch([11, 12])])
    svc1.commit("e")
    t0 = time.monotonic()
    per = {0: [_batch([1])], 1: [_batch([2])]}
    with pytest.raises(ExchangeFetchFailed):
        svc0.exchange("e", per)
    # the peer (restarted / fs healed) re-publishes the same block; the
    # single refetch re-barriers and recovers it under a fresh deadline
    svc1.put("e", 0, [_batch([11, 12])])
    got = svc0.refetch("e", per)
    assert time.monotonic() - t0 < 2 * 2.0       # exchange + refetch ≤ 2x
    assert _values(got) == [1, 11, 12]
    assert svc0.counters["refetches"] == 1


def test_refetch_disabled_by_conf(tmp_path):
    conf = C.Conf().set("spark.tpu.shuffle.fetchRetryEnabled", "false")
    svc = HostShuffleService(str(tmp_path), 0, 1, timeout_s=1.0, conf=conf)
    with pytest.raises(ExchangeFetchFailed, match="refetch disabled"):
        svc.refetch("e")


# ---------------------------------------------------------------------------
# injector plumbing
# ---------------------------------------------------------------------------

def test_skip_commit_keeps_barrier_loud(tmp_path):
    svc0, svc1 = _pair(tmp_path, timeout_s=0.3)
    FaultInjector(FaultPlan().skip_commit(exchange="e")).attach(svc1)
    svc1.put("e", 0, [_batch([1])])
    svc1.commit("e")                     # suppressed by the fault
    svc0.commit("e")
    with pytest.raises(TimeoutError, match=r"senders \[1\]"):
        svc0.barrier("e")


def test_disk_full_fails_spill_writes_after_budget(tmp_path):
    """The ``disk_full`` rule models the spill disk filling mid-query:
    ``svc.spill_write`` succeeds until the cumulative injected budget is
    exhausted, then raises ENOSPC on every further write (a full disk
    stays full) — and successful writes still count into the spill
    gauges while failed ones do not."""
    svc = HostShuffleService(str(tmp_path), 0, 1, timeout_s=5.0)
    inj = FaultInjector(FaultPlan().disk_full(after_bytes=150)).attach(svc)
    path = str(tmp_path / "run.spill")
    svc.spill_write(path, b"x" * 100)
    assert svc.counters["spill_bytes"] == 100
    assert svc.counters["spill_events"] == 1
    with pytest.raises(OSError) as ei:
        svc.spill_write(path, b"x" * 100, append=True)
    assert ei.value.errno == 28
    with pytest.raises(OSError):           # still full on the next write
        svc.spill_write(path, b"x" * 10, append=True)
    assert svc.counters["spill_bytes"] == 100, svc.counters
    assert any(f.startswith("disk_full:") for f in inj.injected), \
        inj.injected
    assert os.path.getsize(path) == 100    # no torn partial append


# ---------------------------------------------------------------------------
# run-length/delta encoded frames under faults: the enc tags change the
# payload shape, not the taxonomy — corruption is checksum-detected and
# heals through the same retrying reader; a structurally-bad run table
# is a WireFormatError, never partial rows
# ---------------------------------------------------------------------------

def _run_shaped(lo):
    """A batch whose column RLE-encodes (4 runs of 64) on the run wire."""
    return ColumnBatch.from_arrays(
        {"v": np.repeat(np.arange(lo, lo + 4, dtype=np.int64), 64)})


def test_corrupted_rle_frame_healed_by_refetch(tmp_path):
    svc0, svc1 = _pair(tmp_path)
    assert svc0.run_codes and svc1.run_codes       # default-on conf
    FaultInjector(FaultPlan().corrupt(exchange="e",
                                      heal_after_s=0.25)).attach(svc1)
    svc1.put("e", 0, [_run_shaped(100)])
    svc1.commit("e")
    got = svc0.exchange("e", {0: [_batch([1])], 1: [_batch([2])]})
    assert _values(got) == [1] + sorted([100, 101, 102, 103] * 64)
    assert svc0.counters["block_retries"] > 0
    assert svc0.counters["blocks_lost"] == 0
    assert svc1.counters["rle_columns_encoded"] > 0


def test_truncated_run_frame_healed_by_refetch(tmp_path):
    svc0, svc1 = _pair(tmp_path)
    FaultInjector(FaultPlan().truncate(exchange="e",
                                       heal_after_s=0.25)).attach(svc1)
    svc1.put("e", 0, [_run_shaped(0)])
    svc1.commit("e")
    got = svc0.exchange("e", {0: [], 1: []})
    assert _values(got) == sorted([0, 1, 2, 3] * 64)
    assert svc0.counters["block_retries"] > 0


def test_malformed_run_table_fails_structured_never_partial(tmp_path):
    """A frame whose run lengths do not sum to the declared row count is
    structurally bad, not torn: plain ``WireFormatError``, fail-fast in
    the reader (no retry budget burned), zero rows emitted."""
    import json
    import struct
    import zlib
    buf = wire.encode_batches([_run_shaped(0).to_host()], run_codes=True)
    hlen = struct.unpack_from("<I", buf, 8)[0]
    header = json.loads(buf[wire.PREFIX_LEN:wire.PREFIX_LEN + hlen])
    assert header["batches"][0]["columns"][0]["enc"]["k"] == "rle"
    header["batches"][0]["capacity"] = 300          # lengths sum to 256
    header["batches"][0]["columns"][0]["shape"] = [300]
    hb = json.dumps(header, separators=(",", ":")).encode()
    payload = buf[wire.PREFIX_LEN + hlen:]
    cksum = zlib.adler32(payload, zlib.adler32(hb))
    bad = wire._PREFIX.pack(wire.MAGIC, wire.WIRE_VERSION, len(hb),
                            len(payload), cksum) + hb + payload
    with pytest.raises(wire.WireFormatError, match="run table"):
        wire.decode_batches(bad)
    path = str(tmp_path / "b.part")
    with open(path, "wb") as f:
        f.write(bad)
    retries = []
    reader = RetryingBlockReader(max_retries=5, retry_wait_s=0.01,
                                 on_retry=retries.append)
    with pytest.raises(BlockFetchError) as ei:
        reader.read(path, expect_size=len(bad))
    assert ei.value.attempts == 1                   # not retryable
    assert retries == []


def test_stream_fault_plan_env_roundtrip():
    plan = (FaultPlan()
            .torn_checkpoint(keep_bytes=11, after_entries=2, die=True)
            .die_after_state_commit(after_entries=1))
    back = FaultPlan.from_env({FAULT_PLAN_ENV: plan.to_env()})
    assert [r.to_dict() for r in back.rules] \
        == [r.to_dict() for r in plan.rules]


def _fake_stream(tmp_path):
    """The minimal surface attach_stream arms: a real commit log plus
    the post-state-commit hook slot."""
    import types as pytypes

    from spark_tpu.streaming.core import MetadataLog
    return pytypes.SimpleNamespace(
        commit_log=MetadataLog(str(tmp_path / "commits")),
        _post_state_commit_hook=None)


def test_torn_checkpoint_tears_the_chosen_entry(tmp_path):
    ex = _fake_stream(tmp_path)
    inj = FaultInjector(FaultPlan().torn_checkpoint(keep_bytes=9,
                                                    after_entries=1))
    inj.attach_stream(ex)
    ex.commit_log.add(0, {"off": 0})
    ex.commit_log.add(1, {"off": 1})
    assert inj.injected == ["torn_checkpoint:1"]
    assert os.path.getsize(tmp_path / "commits" / "1") == 9
    # entry 0 landed intact; the torn entry reads as ABSENT, not garbage
    assert ex.commit_log.get(0) == {"off": 0}
    assert ex.commit_log.get(1) is None
    # hook stays unarmed — no die_after_state_commit rule in the plan
    assert ex._post_state_commit_hook is None


def test_torn_checkpoint_die_goes_through_injector_die(tmp_path):
    ex = _fake_stream(tmp_path)
    inj = FaultInjector(FaultPlan().torn_checkpoint(keep_bytes=5,
                                                    die=True))
    died = []
    inj.die = died.append               # battery seam instead of os._exit
    inj.attach_stream(ex)
    ex.commit_log.add(0, {"off": 0})
    assert died == [43]
    assert ex.commit_log.get(0) is None


def test_die_after_state_commit_fires_at_planned_batch(tmp_path):
    ex = _fake_stream(tmp_path)
    inj = FaultInjector(FaultPlan().die_after_state_commit(
        after_entries=1))
    died = []
    inj.die = died.append
    inj.attach_stream(ex)
    assert ex._post_state_commit_hook is not None
    ex._post_state_commit_hook(0)       # batch 0: before the threshold
    assert died == []
    ex._post_state_commit_hook(1)
    assert died == [43]
    assert inj.injected == ["die_after_state_commit:1"]


def test_fault_plan_env_roundtrip(tmp_path):
    plan = (FaultPlan().drop(exchange="a", receiver=1)
            .truncate(heal_after_s=0.5, keep_bytes=3)
            .corrupt(exchange="d", heal_after_s=0.1)
            .delay(0.2, exchange="b")
            .die_after_put(exchange="c", commit_first=True)
            .disk_full(after_bytes=4096, exchange="e"))
    env = {FAULT_PLAN_ENV: plan.to_env()}
    back = FaultPlan.from_env(env)
    assert [r.to_dict() for r in back.rules] \
        == [r.to_dict() for r in plan.rules]
    assert FaultPlan.from_env({}).rules == []


# ---------------------------------------------------------------------------
# observability: counters reach the session metrics system
# ---------------------------------------------------------------------------

def test_counters_visible_via_session_metrics(spark, tmp_path):
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        svc.exchange("e", {0: [_batch([1])]})
        svc.blacklist[7] = "test"
        snap = ms.snapshots()["shuffle"]
        assert snap["exchanges"] == 1
        assert snap["block_retries"] == 0
        assert snap["blacklisted_peers"] == 1
        assert snap["blacklist"] == "host-7"
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


# ---------------------------------------------------------------------------
# the real thing: a peer process killed mid-exchange
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("commit_first", [False, True])
def test_peer_killed_mid_exchange(tmp_path, commit_first):
    """Worker 1 dies (os._exit) right after publishing its block.  If it
    committed first, worker 0 COMPLETES — the blocks survive the
    process.  If not, worker 0 gets a structured ``ExchangeFetchFailed``
    naming host-1 within 2x the deadline.  Either way: no hang."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "faults_worker.py")
    root, beats = str(tmp_path / "shuf"), str(tmp_path / "beats")
    victim_plan = FaultPlan().die_after_put("ex", commit_first=commit_first)

    def spawn(pid, plan):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop(FAULT_PLAN_ENV, None)
        if plan is not None:
            env[FAULT_PLAN_ENV] = plan.to_env()
        return subprocess.Popen(
            [sys.executable, worker, str(pid), root, beats],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)

    t0 = time.monotonic()
    survivor, victim = spawn(0, None), spawn(1, victim_plan)
    out0 = survivor.communicate(timeout=60)[0]
    out1 = victim.communicate(timeout=60)[0]
    elapsed = time.monotonic() - t0
    assert victim.returncode == 43, out1            # died where planned
    assert "dying after put in 'ex'" in out1
    assert survivor.returncode == 0, out0
    line = [ln for ln in out0.splitlines()
            if ln.startswith(("OK", "FAILED"))][-1]
    if commit_first:
        # sender's blocks + marker landed before death → full recovery
        evens = sorted(v for v in list(range(10)) + list(range(100, 110))
                       if v % 2 == 0)
        assert line == f"OK {evens}", out0
    else:
        assert line.startswith("FAILED"), out0
        assert "host-1" in line
        # within 2x the worker's configured deadline (8s), plus heartbeat
        # detection + process startup slack — and far from a hang
        assert elapsed < 2 * 8.0 + 10, elapsed


# ---------------------------------------------------------------------------
# the shuffled-join data exchange under faults: a join-side block lost
# mid-exchange heals through the same retry/refetch machinery, or the
# query fails structured and bounded — NEVER a partial join result
# ---------------------------------------------------------------------------

def _spawn_join_fault_worker(pid, root, plan, timeout_s, mode="fault"):
    """One process of the 2-process shuffled-join fault scenario; the
    join data exchanges have deterministic ids (first query → exchanges
    ``xq000001-jL`` / ``-jR`` on the hash path, ``xq000001-sample`` /
    ``-rL`` / ``-rR`` on the range path), so rules can target one side's
    blocks — or the manifest-only sample round itself."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "shuffled_join_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(FAULT_PLAN_ENV, None)
    if plan is not None:
        env[FAULT_PLAN_ENV] = plan.to_env()
    return subprocess.Popen(
        [sys.executable, worker, str(pid), "2", root, mode,
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)


def test_join_side_block_dropped_then_heals(tmp_path):
    """p1's LEFT-side block for p0 vanishes right after the put
    (list-after-write lag) and reappears 1s later — past the inline
    retry window, inside the refetch re-barrier.  The exchange heals and
    BOTH processes report the oracle-exact join (the worker itself
    asserts result == full-data oracle before printing OK)."""
    plan = FaultPlan().drop(exchange="xq000001-jL", receiver=0,
                            heal_after_s=1.0)
    root = str(tmp_path / "shuf")
    p0 = _spawn_join_fault_worker(0, root, None, 15.0)
    p1 = _spawn_join_fault_worker(1, root, plan, 15.0)
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    assert "[p0] OK " in out0, out0
    assert "[p1] OK " in out1, out1
    assert "PARTIAL" not in out0 + out1


def test_join_side_block_corrupted_fails_bounded(tmp_path):
    """Size-preserving corruption of a join-side block with no heal: the
    wire checksum catches it on every re-read, the victim fails with a
    structured ``ExchangeFetchFailed`` naming the corrupting host, and
    its peer times out at the next barrier — bounded, and neither
    process ever emits a (partial) result."""
    plan = FaultPlan().corrupt(exchange="xq000001-jL", receiver=0)
    root = str(tmp_path / "shuf")
    t0 = time.monotonic()
    p0 = _spawn_join_fault_worker(0, root, None, 6.0)
    p1 = _spawn_join_fault_worker(1, root, plan, 6.0)
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    elapsed = time.monotonic() - t0
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    line0 = [ln for ln in out0.splitlines() if "[p0]" in ln][-1]
    assert "FAILED" in line0 and "host-1" in line0, out0
    assert "FAILED" in out1, out1
    assert "OK" not in out0 and "OK" not in out1
    assert "PARTIAL" not in out0 + out1
    # exchange deadline 6s: victim fails ≤ 2x (exchange + refetch), the
    # peer's follow-up barrier adds ≤ 1x more, plus jit/startup slack
    assert elapsed < 3 * 6.0 + 30, elapsed


# ---------------------------------------------------------------------------
# the RANGE path's manifest-only sample round under faults: the cut-point
# coordination is all-or-nothing — a dropped manifest heals through the
# barrier/strict-reread machinery, a permanently unreadable one fails the
# round on EVERY process (bounded), never lets cut points diverge
# ---------------------------------------------------------------------------

def test_range_sample_manifest_dropped_then_heals(tmp_path):
    """p1's sample manifest vanishes right after the publish
    (list-after-write lag) and reappears 2s later — inside the barrier
    window.  The sample round completes, both processes derive the same
    cut points, and the range join matches the full-data oracle."""
    plan = FaultPlan().drop(exchange="xq000001-sample", heal_after_s=2.0)
    root = str(tmp_path / "shuf")
    p0 = _spawn_join_fault_worker(0, root, None, 20.0, mode="fault-sample")
    p1 = _spawn_join_fault_worker(1, root, plan, 20.0, mode="fault-sample")
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    assert "[p0] OK " in out0, out0
    assert "[p1] OK " in out1, out1
    assert "PARTIAL" not in out0 + out1


def test_range_sample_manifest_corrupted_fails_bounded(tmp_path):
    """p1's sample manifest gets a byte flipped with no heal: it parses
    on no process, the strict gather re-reads until the deadline, then
    BOTH processes fail structured naming host-1 — the round can never
    half-succeed, because asymmetric reads would mean different cut
    points and a desynchronized data exchange."""
    plan = FaultPlan().corrupt(exchange="xq000001-sample")
    root = str(tmp_path / "shuf")
    t0 = time.monotonic()
    p0 = _spawn_join_fault_worker(0, root, None, 6.0, mode="fault-sample")
    p1 = _spawn_join_fault_worker(1, root, plan, 6.0, mode="fault-sample")
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    elapsed = time.monotonic() - t0
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    for pid, out in ((0, out0), (1, out1)):
        line = [ln for ln in out.splitlines() if f"[p{pid}]" in ln][-1]
        assert "FAILED" in line and "host-1" in line, out
    assert "OK" not in out0 and "OK" not in out1
    assert "PARTIAL" not in out0 + out1
    # strict gather holds until the 6s exchange deadline on each side,
    # plus jit/startup slack — bounded, and far from a hang
    assert elapsed < 3 * 6.0 + 30, elapsed


# ---------------------------------------------------------------------------
# memory pressure meets disk pressure: when a forced spill hits ENOSPC
# the query fails with a structured HostMemoryError naming the reserver,
# the peer fails bounded on its exchange deadline — never partial output
# ---------------------------------------------------------------------------

def test_spill_disk_full_fails_bounded(tmp_path):
    """p1 runs with a tiny forced spill threshold AND a disk_full rule:
    its very first map-side spill write raises ENOSPC, so the join
    aborts with ``HostMemoryError`` before p1 publishes anything; p0
    (healthy, also in forced-spill mode) times out at the exchange.
    Both processes fail STRUCTURED and bounded — no partial join rows
    ever reach a client."""
    plan = FaultPlan().disk_full(after_bytes=0)
    root = str(tmp_path / "shuf")
    t0 = time.monotonic()
    p0 = _spawn_join_fault_worker(0, root, None, 8.0, mode="spill-fault")
    p1 = _spawn_join_fault_worker(1, root, plan, 8.0, mode="spill-fault")
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    elapsed = time.monotonic() - t0
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    line1 = [ln for ln in out1.splitlines() if "[p1]" in ln][-1]
    assert "FAILED-HOSTMEM" in line1, out1
    assert "FAILED" in out0, out0
    assert "PARTIAL" not in out0 + out1
    assert "OK" not in out0 and "OK" not in out1
    # p1 fails immediately at the spill; p0 holds only to its exchange
    # deadline (+ refetch), plus jit/startup slack
    assert elapsed < 3 * 8.0 + 30, elapsed

# ---------------------------------------------------------------------------
# the ADAPTIVE stats round under faults: the observed-size manifests that
# drive the re-decision ride the size round, so a lost or corrupt stats
# payload must degrade to the FROZEN plan-time strategy with full parity
# (never a hang, never a partial result), a transient loss must heal and
# still demote, and a peer dying mid-demotion must fail bounded
# ---------------------------------------------------------------------------

def _spawn_adaptive_fault_worker(pid, root, plan, timeout_s):
    """One process of the 2-process adaptive fault scenario: the worker
    runs ONE misestimated join whose frozen plan is a hash shuffle and
    whose observed stats demote it to broadcast.  First query →
    exchanges ``xq000001-plan`` (the size/stats round) and
    ``xq000001-bcast`` (the demotion gather), so rules can target the
    stats payload or the demotion itself."""
    here = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(here, "adaptive_worker.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop(FAULT_PLAN_ENV, None)
    if plan is not None:
        env[FAULT_PLAN_ENV] = plan.to_env()
    return subprocess.Popen(
        [sys.executable, worker, str(pid), "2", root, "fault-adapt",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)


def test_adaptive_stats_corrupted_falls_back_to_frozen(tmp_path):
    """p1's size/stats manifest gets a byte flipped with no heal: the
    lenient gather skips it on EVERY process, so the observed per-side
    stats are incomplete and BOTH processes keep the frozen hash plan —
    the query completes through the full shuffle with oracle parity and
    ZERO demotions.  A lost stats round costs the optimization, never
    the answer."""
    plan = FaultPlan().corrupt(exchange="xq000001-plan")
    root = str(tmp_path / "shuf")
    p0 = _spawn_adaptive_fault_worker(0, root, None, 15.0)
    p1 = _spawn_adaptive_fault_worker(1, root, plan, 15.0)
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    for pid, out in ((0, out0), (1, out1)):
        line = [ln for ln in out.splitlines() if f"[p{pid}] OK" in ln][-1]
        assert "demotions=0" in line, out
        assert "replans=0" in line, out       # stats incomplete → no replan
        assert "shuffled=1" in line and "bcast=0" in line, out
    assert "PARTIAL" not in out0 + out1
    assert "FAILED" not in out0 + out1


def test_adaptive_stats_dropped_then_heals_still_demotes(tmp_path):
    """p1's stats manifest vanishes right after the publish and
    reappears 2s later — inside the size-round barrier window.  The
    round completes with FULL stats, so the demotion still fires on both
    processes: broadcast join, oracle parity, one demotion each."""
    plan = FaultPlan().drop(exchange="xq000001-plan", heal_after_s=2.0)
    root = str(tmp_path / "shuf")
    p0 = _spawn_adaptive_fault_worker(0, root, None, 20.0)
    p1 = _spawn_adaptive_fault_worker(1, root, plan, 20.0)
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    assert p0.returncode == 0, out0
    assert p1.returncode == 0, out1
    for pid, out in ((0, out0), (1, out1)):
        line = [ln for ln in out.splitlines() if f"[p{pid}] OK" in ln][-1]
        assert "demotions=1" in line and "replans=1" in line, out
        assert "bcast=1" in line and "shuffled=0" in line, out
    assert "PARTIAL" not in out0 + out1
    assert "FAILED" not in out0 + out1


def test_peer_killed_mid_demotion_fails_bounded(tmp_path):
    """p1 dies (os._exit) right after putting its share into the
    demotion's broadcast gather, before committing: p0 observes the same
    stats, takes the same demotion, and then times out STRUCTURED at the
    ``xq000001-bcast`` barrier — bounded by the exchange deadline, and
    neither process ever emits a partial result."""
    plan = FaultPlan().die_after_put(exchange="xq000001-bcast")
    root = str(tmp_path / "shuf")
    t0 = time.monotonic()
    p0 = _spawn_adaptive_fault_worker(0, root, None, 6.0)
    p1 = _spawn_adaptive_fault_worker(1, root, plan, 6.0)
    out0 = p0.communicate(timeout=120)[0]
    out1 = p1.communicate(timeout=120)[0]
    elapsed = time.monotonic() - t0
    assert p1.returncode == 43, out1               # died where planned
    assert "dying after put in 'xq000001-bcast'" in out1, out1
    assert p0.returncode == 0, out0
    line0 = [ln for ln in out0.splitlines() if "[p0]" in ln][-1]
    assert "FAILED" in line0, out0
    assert "OK" not in out0, out0
    assert "PARTIAL" not in out0 + out1
    # p0 holds to its exchange deadline (+ refetch re-barrier), plus
    # jit/startup slack — bounded, far from a hang
    assert elapsed < 3 * 6.0 + 30, elapsed
