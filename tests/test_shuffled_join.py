"""Partitioned shuffled hash join over the DCN exchange (tentpole).

Two layers:

- unit tests (single process, service-level): the manifest-only size
  exchange, the deterministic coalescing reducer planner
  (ExchangeCoordinator analog), the equi-key extractor, and the
  single-process degenerate case (flag on, nothing partitioned → the
  generic path, results unchanged);
- subprocess parity harness (2 and 3 REAL processes,
  ``shuffled_join_worker.py``): randomized-but-seeded plans — inner /
  left / semi joins of two partitioned leaves, with and without a keyed
  Aggregate above — run through the shuffled path AND the forced gather
  path, both byte-identical to a full-data single-process oracle; the
  workers also assert the path counters (``shuffled_joins``,
  ``fast_path_aggs``) and that coalescing merged sub-target fine
  partitions without changing any result.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu.parallel.hostshuffle import HostShuffleService

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "shuffled_join_worker.py")


# ---------------------------------------------------------------------------
# reducer planning: deterministic coalescing from manifest byte counts
# ---------------------------------------------------------------------------

def _svc(tmp_path, pid=0, n=2, **kw):
    kw.setdefault("timeout_s", 5.0)
    kw.setdefault("poll_s", 0.02)
    return HostShuffleService(str(tmp_path), pid, n, **kw)


def test_plan_reducers_static_when_target_zero(tmp_path):
    svc = _svc(tmp_path)
    bounds = svc.plan_reducers(np.array([5] * 16, np.int64), 0)
    assert bounds == [0, 8, 16]
    assert svc.counters["partitions_coalesced"] == 0


def test_plan_reducers_coalesces_tiny_partitions(tmp_path):
    svc = _svc(tmp_path)
    sizes = np.array([10, 10, 10, 10, 500, 10, 10, 10], np.int64)
    bounds = svc.plan_reducers(sizes, 100)
    assert bounds[0] == 0 and bounds[-1] == len(sizes)
    assert len(bounds) - 1 <= svc.n                # never more groups than procs
    assert svc.counters["partitions_coalesced"] > 0
    # group bytes land in the skew gauge inputs
    assert sum(svc.last_partition_bytes) == int(sizes.sum())


def test_plan_reducers_flags_skewed_groups(tmp_path):
    svc = _svc(tmp_path, n=4)
    # one hot key range, three near-empty ones → the hot group exceeds
    # SKEW_FACTOR x median and must be flagged (not silently absorbed)
    sizes = np.array([1, 1, 1, 100000, 1, 1, 1, 1], np.int64)
    svc.plan_reducers(sizes, 2)
    assert svc.counters["partitions_skewed"] >= 1


def test_plan_reducers_deterministic_across_processes(tmp_path):
    sizes = np.array([37, 0, 12, 900, 4, 4, 4, 250, 0, 66], np.int64)
    b0 = _svc(tmp_path / "a", pid=0).plan_reducers(sizes, 200)
    b1 = _svc(tmp_path / "b", pid=1).plan_reducers(sizes, 200)
    assert b0 == b1                      # no driver: same inputs, same plan


def test_publish_and_gather_sizes_roundtrip(tmp_path):
    svc0, svc1 = _svc(tmp_path, 0), _svc(tmp_path, 1)
    svc0.publish_sizes("e", {0: 100, 2: 50})
    svc1.publish_sizes("e", {0: 11, 3: 7})
    t0 = svc0.gather_sizes("e", 4)
    t1 = svc1.gather_sizes("e", 4)
    assert t0.tolist() == t1.tolist() == [111, 0, 50, 7]


def test_publish_sizes_is_single_use(tmp_path):
    svc = _svc(tmp_path)
    svc.publish_sizes("e", {0: 1})
    with pytest.raises(ValueError):
        svc.publish_sizes("e", {0: 1})


# ---------------------------------------------------------------------------
# equi-key extraction mirrors the join planner
# ---------------------------------------------------------------------------

def test_equi_join_keys_using_and_condition(spark):
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.joins import equi_join_keys

    a = spark.createDataFrame({"k": np.arange(4), "v": np.arange(4)})
    b = spark.createDataFrame({"k2": np.arange(4), "w": np.arange(4)})
    # explicit equi condition → one (left, right) pair
    j = a.join(b, on=a["k"] == b["k2"])._plan
    assert len(equi_join_keys(j)) == 1
    # USING column → Col(name) on both sides
    c = spark.createDataFrame({"k": np.arange(4), "w": np.arange(4)})
    j2 = a.join(c, on="k")._plan
    [(l2, r2)] = equi_join_keys(j2)
    assert isinstance(j2, L.Join) and l2.name == r2.name == "k"
    # cross join: no hash keys → empty (shuffled path must decline)
    j3 = a.crossJoin(b)._plan
    assert equi_join_keys(j3) == []


def test_shuffled_join_flag_is_safe_single_process(spark, tmp_path):
    """n=1: every leaf is trivially 'replicated', so the flag must leave
    results unchanged (generic path) rather than shuffling with itself."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        spark.createDataFrame(
            {"k": np.arange(8) % 3, "v": np.arange(8)}
        ).createOrReplaceTempView("ta")
        spark.createDataFrame(
            {"k2": np.arange(6) % 3, "w": np.arange(6) * 10}
        ).createOrReplaceTempView("tb")
        got = [tuple(r) for r in spark.sql(
            "SELECT k, count(*) AS c, sum(w) AS s FROM ta "
            "JOIN tb ON k = k2 GROUP BY k ORDER BY k").collect()]
        assert got == [(0, 6, 90), (1, 6, 150), (2, 4, 140)]
        assert svc.counters["shuffled_joins"] == 0
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


# ---------------------------------------------------------------------------
# the real thing: parity across REAL processes, shuffled vs gather vs oracle
# ---------------------------------------------------------------------------

def _run_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "parity",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        assert f"[p{pid}] ALL-OK" in out, out
        # the battery covered both new paths and the coalescer fired
        assert "shuffled=5" in out and "fast=2" in out, out
    return outs


def test_parity_two_processes(tmp_path):
    _run_parity(tmp_path, 2)


@pytest.mark.slow
def test_parity_three_processes(tmp_path):
    _run_parity(tmp_path, 3)
