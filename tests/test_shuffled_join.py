"""Partitioned shuffled hash join over the DCN exchange (tentpole).

Two layers:

- unit tests (single process, service-level): the manifest-only size
  exchange, the deterministic coalescing reducer planner
  (ExchangeCoordinator analog), the equi-key extractor, and the
  single-process degenerate case (flag on, nothing partitioned → the
  generic path, results unchanged);
- subprocess parity harness (2 and 3 REAL processes,
  ``shuffled_join_worker.py``): randomized-but-seeded plans — inner /
  left / semi joins of two partitioned leaves, with and without a keyed
  Aggregate above, with a deliberately skewed hot key — run through the
  RANGE sort-merge path, the shuffled-hash path AND the forced gather
  path, all byte-identical to a full-data single-process oracle; the
  workers also assert the path counters (``range_merge_joins``,
  ``shuffled_joins``, ``fast_path_aggs``), that coalescing merged
  sub-target fine partitions, and that the hot key forced a skew-span
  split — without changing any result.

The range-specific service machinery (the strict manifest round and the
skew-splitting span→reducer planner) gets direct unit tests here too.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu.parallel.hostshuffle import HostShuffleService

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "shuffled_join_worker.py")


# ---------------------------------------------------------------------------
# reducer planning: deterministic coalescing from manifest byte counts
# ---------------------------------------------------------------------------

def _svc(tmp_path, pid=0, n=2, **kw):
    kw.setdefault("timeout_s", 5.0)
    kw.setdefault("poll_s", 0.02)
    return HostShuffleService(str(tmp_path), pid, n, **kw)


def test_plan_reducers_static_when_target_zero(tmp_path):
    svc = _svc(tmp_path)
    bounds = svc.plan_reducers(np.array([5] * 16, np.int64), 0)
    assert bounds == [0, 8, 16]
    assert svc.counters["partitions_coalesced"] == 0


def test_plan_reducers_coalesces_tiny_partitions(tmp_path):
    svc = _svc(tmp_path)
    sizes = np.array([10, 10, 10, 10, 500, 10, 10, 10], np.int64)
    bounds = svc.plan_reducers(sizes, 100)
    assert bounds[0] == 0 and bounds[-1] == len(sizes)
    assert len(bounds) - 1 <= svc.n                # never more groups than procs
    assert svc.counters["partitions_coalesced"] > 0
    # group bytes land in the skew gauge inputs
    assert sum(svc.last_partition_bytes) == int(sizes.sum())


def test_plan_reducers_flags_skewed_groups(tmp_path):
    svc = _svc(tmp_path, n=4)
    # one hot key range, three near-empty ones → the hot group exceeds
    # SKEW_FACTOR x median and must be flagged (not silently absorbed)
    sizes = np.array([1, 1, 1, 100000, 1, 1, 1, 1], np.int64)
    svc.plan_reducers(sizes, 2)
    assert svc.counters["partitions_skewed"] >= 1


def test_plan_reducers_deterministic_across_processes(tmp_path):
    sizes = np.array([37, 0, 12, 900, 4, 4, 4, 250, 0, 66], np.int64)
    b0 = _svc(tmp_path / "a", pid=0).plan_reducers(sizes, 200)
    b1 = _svc(tmp_path / "b", pid=1).plan_reducers(sizes, 200)
    assert b0 == b1                      # no driver: same inputs, same plan


def test_publish_and_gather_sizes_roundtrip(tmp_path):
    svc0, svc1 = _svc(tmp_path, 0), _svc(tmp_path, 1)
    svc0.publish_sizes("e", {0: 100, 2: 50})
    svc1.publish_sizes("e", {0: 11, 3: 7})
    t0 = svc0.gather_sizes("e", 4)
    t1 = svc1.gather_sizes("e", 4)
    assert t0.tolist() == t1.tolist() == [111, 0, 50, 7]


def test_publish_sizes_is_single_use(tmp_path):
    svc = _svc(tmp_path)
    svc.publish_sizes("e", {0: 1})
    with pytest.raises(ValueError):
        svc.publish_sizes("e", {0: 1})


# ---------------------------------------------------------------------------
# range exchange coordination: strict manifest rounds + span planning
# ---------------------------------------------------------------------------

def test_publish_and_gather_manifests_roundtrip(tmp_path):
    svc0, svc1 = _svc(tmp_path, 0), _svc(tmp_path, 1)
    n0 = svc0.publish_manifest("e", {"sample": {"points": [1, 2]}})
    n1 = svc1.publish_manifest("e", {"sample": {"points": [9]}})
    mans, total = svc0.gather_manifests("e")
    assert mans[0]["sample"]["points"] == [1, 2]
    assert mans[1]["sample"]["points"] == [9]
    assert total == n0 + n1 > 0


def test_gather_manifests_strict_rejects_unreadable(tmp_path):
    """The coordination-round contract: a committed-but-unparseable
    manifest must FAIL the round (bounded), never be silently skipped —
    skipping would let processes derive DIFFERENT cut points."""
    from spark_tpu.parallel.hostshuffle import ExchangeFetchFailed
    svc0, svc1 = _svc(tmp_path, 0, timeout_s=0.5), _svc(tmp_path, 1)
    svc0.publish_manifest("e")
    svc1.publish_manifest("e", {"sample": {}})
    with open(svc1._done("e", 1), "wb") as f:   # torn write, size intact
        f.write(b"\x82{ not json")
    with pytest.raises(ExchangeFetchFailed) as ei:
        svc0.gather_manifests("e", strict=True)
    assert ei.value.lost_hosts == ["host-1"]
    # non-strict (size rounds): legacy skip-if-unreadable is preserved
    mans, _ = svc0.gather_manifests("e")
    assert 0 in mans and 1 not in mans


def test_plan_range_reducers_splits_skewed_span(tmp_path):
    svc = _svc(tmp_path, n=2)
    probe = np.array([10, 10, 100000, 10, 10], np.int64)
    build = np.array([5, 5, 50, 5, 5], np.int64)
    owners = svc.plan_range_reducers(probe, build, 2048)
    # hot span 2 is split across BOTH processes, others single-owner
    assert sorted(owners[2]) == [0, 1]
    assert all(len(owners[s]) == 1 for s in (0, 1, 3, 4))
    assert svc.counters["spans_split"] == 1
    # load model: split probe halves + build REPLICATED to each owner
    normal = int((probe + build).sum() - probe[2] - build[2])
    assert sum(svc.last_partition_bytes) \
        == normal + 2 * (int(probe[2]) // 2 + int(build[2]))


def test_plan_range_reducers_coalesces_and_is_deterministic(tmp_path):
    probe = np.array([7, 7, 7, 7, 7, 7, 7, 7], np.int64)
    build = np.zeros(8, np.int64)
    o0 = _svc(tmp_path / "a", pid=0).plan_range_reducers(probe, build, 100)
    o1 = _svc(tmp_path / "b", pid=1).plan_range_reducers(probe, build, 100)
    assert o0 == o1                      # no driver: same inputs, same plan
    assert all(len(ps) == 1 for ps in o0)
    svc = _svc(tmp_path / "c")
    svc.plan_range_reducers(probe, build, 100)
    assert svc.counters["partitions_coalesced"] > 0
    assert svc.counters["spans_split"] == 0   # uniform → nothing to split


def test_range_bucket_spans_and_duplicates():
    from spark_tpu.kernels import range_bucket
    cuts = np.array([10, 20], np.int64)
    keys = np.array([-5, 9, 10, 15, 20, 99, 10, 10], np.int64)
    spans = range_bucket(np, keys, cuts)
    assert spans.dtype == np.int32
    assert spans.tolist() == [0, 0, 1, 1, 2, 2, 1, 1]
    # all duplicates of a value land in ONE span (hot-key cohesion)
    assert len({s for k, s in zip(keys.tolist(), spans.tolist())
                if k == 10}) == 1
    # no cuts → everything in span 0 (single-span degenerate case)
    assert range_bucket(np, keys, np.zeros(0, np.int64)).tolist() == [0] * 8


# ---------------------------------------------------------------------------
# equi-key extraction mirrors the join planner
# ---------------------------------------------------------------------------

def test_equi_join_keys_using_and_condition(spark):
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.joins import equi_join_keys

    a = spark.createDataFrame({"k": np.arange(4), "v": np.arange(4)})
    b = spark.createDataFrame({"k2": np.arange(4), "w": np.arange(4)})
    # explicit equi condition → one (left, right) pair
    j = a.join(b, on=a["k"] == b["k2"])._plan
    assert len(equi_join_keys(j)) == 1
    # USING column → Col(name) on both sides
    c = spark.createDataFrame({"k": np.arange(4), "w": np.arange(4)})
    j2 = a.join(c, on="k")._plan
    [(l2, r2)] = equi_join_keys(j2)
    assert isinstance(j2, L.Join) and l2.name == r2.name == "k"
    # cross join: no hash keys → empty (shuffled path must decline)
    j3 = a.crossJoin(b)._plan
    assert equi_join_keys(j3) == []


def test_shuffled_join_flag_is_safe_single_process(spark, tmp_path):
    """n=1: every leaf is trivially 'replicated', so the flag must leave
    results unchanged (generic path) rather than shuffling with itself."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        spark.createDataFrame(
            {"k": np.arange(8) % 3, "v": np.arange(8)}
        ).createOrReplaceTempView("ta")
        spark.createDataFrame(
            {"k2": np.arange(6) % 3, "w": np.arange(6) * 10}
        ).createOrReplaceTempView("tb")
        got = [tuple(r) for r in spark.sql(
            "SELECT k, count(*) AS c, sum(w) AS s FROM ta "
            "JOIN tb ON k = k2 GROUP BY k ORDER BY k").collect()]
        assert got == [(0, 6, 90), (1, 6, 150), (2, 4, 140)]
        assert svc.counters["shuffled_joins"] == 0
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


# ---------------------------------------------------------------------------
# the real thing: parity across REAL processes, shuffled vs gather vs oracle
# ---------------------------------------------------------------------------

def _run_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "parity",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        assert f"[p{pid}] ALL-OK" in out, out
        # the battery covered every path and the coalescer + skew
        # splitter both fired
        assert "range=5" in out and "shuffled=5" in out, out
        assert "fast=6" in out, out
    return outs


def test_parity_two_processes(tmp_path):
    _run_parity(tmp_path, 2)


@pytest.mark.slow
def test_parity_three_processes(tmp_path):
    _run_parity(tmp_path, 3)


# ---------------------------------------------------------------------------
# spill parity: the same battery forced through the disk-spill path
# ---------------------------------------------------------------------------

def _run_spill_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "spill",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        # the full battery passed against the oracle AND the spill path
        # demonstrably ran under the capped ledger
        assert f"[p{pid}] SPILL-OK" in out, out
        assert "PARITY-FAIL" not in out, out
    return outs


def test_spill_parity_two_processes(tmp_path):
    _run_spill_parity(tmp_path, 2)


@pytest.mark.slow
def test_spill_parity_three_processes(tmp_path):
    _run_spill_parity(tmp_path, 3)


# ---------------------------------------------------------------------------
# grace parity: a host budget CAPPED below the reducers' drained working
# set — every join must still complete byte-identical to the oracle by
# re-bucketing the sink into spill files and joining bucket-by-bucket
# ---------------------------------------------------------------------------

def _run_grace_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "grace",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        assert f"[p{pid}] GRACE-OK" in out, out
        assert "GRACE-PARITY-FAIL" not in out, out
        # the worker itself asserted elastic narrowing, grace activity
        # (both processes at n=2) and peak <= budget before printing OK
        line = [ln for ln in out.splitlines()
                if f"[p{pid}] GRACE-OK" in ln][-1]
        if n == 2:
            assert "buckets=0" not in line, out
            assert "resplits=0" not in line, out
    return outs


def test_grace_parity_two_processes(tmp_path):
    _run_grace_parity(tmp_path, 2)


@pytest.mark.slow
def test_grace_parity_three_processes(tmp_path):
    _run_grace_parity(tmp_path, 3)


# ---------------------------------------------------------------------------
# run-codes parity: run-encoded vs raw wire on BOTH exchange lanes over a
# time-series-shaped workload (sorted key runs + a dictionary+RLE composed
# status column), under the forced-spill conf so encoded frames also stage
# through disk without inflating — every leg oracle-exact
# ---------------------------------------------------------------------------

def _run_runcodes_parity(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "runcodes",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        # the worker asserted the gauge side (rle_columns_encoded,
        # run_bytes_saved, run_aware_op_rows, runs_materialized, spill
        # under the capped ledger) before printing its OK line
        assert f"[p{pid}] RUNCODES-OK" in out, out
        assert "RC-PARITY-FAIL" not in out, out
        line = [ln for ln in out.splitlines()
                if f"[p{pid}] RUNCODES-OK" in ln][-1]
        assert "rle=0" not in line and "runaware=0" not in line, out
    return outs


def test_runcodes_parity_two_processes(tmp_path):
    _run_runcodes_parity(tmp_path, 2)


@pytest.mark.slow
def test_runcodes_parity_three_processes(tmp_path):
    _run_runcodes_parity(tmp_path, 3)
