"""Test bootstrap: force a virtual 8-device CPU platform BEFORE jax import.

Mirrors the reference's `local-cluster[N,...]` testing trick
(`core/src/main/scala/org/apache/spark/deploy/LocalSparkCluster.scala:36`):
distributed code paths are exercised in-process on N virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import jax  # noqa: E402

# The axon TPU plugin (sitecustomize in /root/.axon_site) force-registers
# itself and sets jax_platforms='axon,cpu' BEFORE conftest runs, ignoring the
# env var — and TPU float64 is emulated (double-double, ~1e-15 error), which
# breaks exact dual-path tests. Override back to pure CPU here, before any
# backend initialization.
jax.config.update("jax_platforms", "cpu")

# Persistent compile cache: this jax build pays ~0.8s per jit and ~20ms per
# uncached eager op; caching across pytest runs keeps the suite usable.
# OWN directory, never shared with bench.py/TPU runs: the axon remote
# compile helper emits CPU AOT code for ITS machine's features, and
# loading those artifacts here SIGILLs (cpu_aot_loader feature mismatch).
jax.config.update("jax_compilation_cache_dir", "/tmp/spark_tpu_jax_cache_cpu")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


# the MXU aggregation path auto-disables off-TPU; tests run on the virtual
# CPU mesh as the TPU stand-in, so force it on to keep exercising the
# one-hot-matmul kernel (the suite's dual-path oracle checks depend on it)
from spark_tpu import kernels as _kernels  # noqa: E402

_kernels.MXU_AGG_ENABLED = True


def pytest_configure(config):
    # the tier-1 sweep runs `-m 'not slow'`; heavy subprocess/thread-pool
    # suites (chaos, stress-scale wire round-trips) opt out via this mark
    config.addinivalue_line(
        "markers", "slow: >~5s test, excluded from the tier-1 sweep")
    config.addinivalue_line(
        "markers", "chaos_smoke: multi-process fault-injection scenario "
        "from tests/chaos_matrix.py (also runnable via bin/chaos)")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def spark():
    """Shared session (SharedSparkContext/SharedSQLContext analog).

    Pinned to single-shard local execution; distributed suites opt into the
    8-device mesh via their own fixture (see test_distributed.py).
    """
    from spark_tpu.sql.session import SparkSession
    s = SparkSession.builder.appName("tests").getOrCreate()
    s.conf.set("spark.tpu.mesh.shards", "1")
    return s


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """The full 600+-test suite accumulates thousands of live XLA:CPU
    executables in one process and eventually segfaults inside a CPU
    kernel; dropping compiled programs between modules keeps the working
    set bounded (the persistent on-disk cache makes recompiles cheap).

    ROOT CAUSE (confirmed via the engine-free reproducer
    tests/repro_xla_cpu_segfault.py, 2026-07-31): XLA:CPU's LLVM JIT
    code arena exhausts after ~2,250 live executables —
    `execution_engine.cc:54 LLVM compilation error: Cannot allocate
    memory` repeats, the failure is not surfaced to Python, and the
    next executable use SIGSEGVs (rc=139).  Pure jax + numpy; no
    spark_tpu code involved, so this fixture is a workaround for an
    upstream XLA:CPU condition, not a mask over an engine bug.  If you
    run a custom large subset WITHOUT this conftest, call
    jax.clear_caches() periodically or expect the late segfault."""
    yield
    jax.clear_caches()
