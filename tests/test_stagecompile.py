"""Whole-stage tensor compilation (sql/stagecompile.py): the
process-local stage-executable cache, literal-parameterized sharing,
fusion-vs-per-op parity, and the fused-stage boundary contract.

The claims under test: repeated structurally-equal queries reuse ONE
compiled stage program (no fresh jax.jit per execution); literal
variants share that program with values riding as runtime arguments;
fusion changes dispatch structure only — the per-operator baseline
(`run_per_op`, `spark.tpu.stage.fusion=false`) produces byte-identical
results at >=3x the dispatch count; and a stage whose recorded cut
schemas disagree with the unfused physical tree fails
``verify_stage_contract`` loudly, never misexecutes."""

import numpy as np
import pytest

import spark_tpu.config as C
import spark_tpu.types as T
from spark_tpu.analysis import PlanInvariantError, verify_stage_contract
from spark_tpu.sql import stagecompile as SC
from spark_tpu.sql.planner import Planner, QueryExecution


@pytest.fixture()
def sess(spark):
    s = spark.newSession()
    s.conf.set("spark.tpu.mesh.shards", "1")
    return s


def _mk(s, n=200, seed=5):
    rng = np.random.default_rng(seed)
    s.createDataFrame({
        "k": rng.integers(0, 9, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    }).createOrReplaceTempView("scq")


def _planned(s, sql):
    qe = QueryExecution(s, s.sql(sql)._plan)
    return Planner(s).plan(qe.optimized)


# ---------------------------------------------------------------------------
# executable reuse
# ---------------------------------------------------------------------------

def test_repeated_query_reuses_one_stage_executable(sess):
    _mk(sess)
    cache = SC.stage_cache()
    q = "SELECT k, sum(v) AS sv FROM scq GROUP BY k ORDER BY k"
    a1 = [tuple(r) for r in sess.sql(q).collect()]
    s0 = cache.stats()
    a2 = [tuple(r) for r in sess.sql(q).collect()]
    s1 = cache.stats()
    assert a2 == a1
    assert s1["builds"] == s0["builds"], \
        "second run of an identical query must not compile a new stage"
    assert s1["hits"] > s0["hits"]
    assert s1["dispatches"] > s0["dispatches"]


def test_literal_variants_share_one_stage_executable(sess):
    _mk(sess)
    cache = SC.stage_cache()
    sess.sql("SELECT k, v FROM scq WHERE v < 500").collect()
    s0 = cache.stats()
    got = [tuple(r)
           for r in sess.sql("SELECT k, v FROM scq WHERE v < 100"
                             ).collect()]
    s1 = cache.stats()
    assert s1["builds"] == s0["builds"], \
        "a slotted literal variant must reuse the compiled stage"
    assert s1["hits"] > s0["hits"]
    # and the parameterized run uses the NEW literal, not the baked one
    assert got and all(v < 100 for _k, v in got)


def test_stage_fingerprint_separates_structures(sess):
    _mk(sess)
    pq1 = _planned(sess, "SELECT k + 1 AS a FROM scq")
    pq2 = _planned(sess, "SELECT k * 2 AS a FROM scq")
    k1, _ = SC.stage_fingerprint(pq1.physical)
    k2, _ = SC.stage_fingerprint(pq2.physical)
    assert k1 != k2
    # literal-only variants collapse to one key with aligned slots
    pq3 = _planned(sess, "SELECT k + 2 AS a FROM scq")
    k3, slots3 = SC.stage_fingerprint(pq3.physical)
    k1b, slots1 = SC.stage_fingerprint(pq1.physical)
    assert k3 == k1b
    assert [l.value for l in slots1] != [l.value for l in slots3]


def test_stage_cache_entry_bound_is_lru(sess):
    c = SC.StageCache(max_entries=2)
    for i in range(4):
        c.get_or_build(f"k{i}", lambda: ((lambda x: x), None))
    assert len(c) == 2
    assert c.stats()["builds"] == 4


# ---------------------------------------------------------------------------
# fused vs per-operator dispatch: parity + the >=3x dispatch claim
# ---------------------------------------------------------------------------

def test_per_op_baseline_parity_and_dispatch_count(sess):
    _mk(sess)
    pq = _planned(
        sess, "SELECT k, sum(v) AS sv, count(v) AS c FROM scq "
              "WHERE v < 800 GROUP BY k")
    fused = [tuple(r)
             for r in sess.sql("SELECT k, sum(v) AS sv, count(v) AS c "
                               "FROM scq WHERE v < 800 GROUP BY k "
                               "ORDER BY k").collect()]
    out, n_rows, n_dispatch, flags, caps, _k = SC.run_per_op(
        pq.physical, pq.leaves)
    assert not any(f > 0 for f in flags), "per-op run must not overflow"
    from spark_tpu.sql.planner import _slice_to_host
    host = _slice_to_host(out, n_rows)
    per_op = sorted(zip(*(np.asarray(v.data)[:n_rows]
                          for v in host.vectors)))
    assert per_op == sorted(fused), \
        "fusion may change dispatch structure, never results"
    # the fused stage runs as ONE dispatch; per-op pays one per operator
    assert n_dispatch >= 3, \
        f"scan-filter-project-agg should be >=3 ops, got {n_dispatch}"
    assert n_dispatch >= 3 * 1


def test_stage_fusion_conf_off_matches_fused_results(sess):
    _mk(sess)
    q = ("SELECT k, sum(v) AS sv FROM scq WHERE v < 600 "
         "GROUP BY k ORDER BY k")
    fused = [tuple(r) for r in sess.sql(q).collect()]
    sess.conf.set(C.STAGE_FUSION.key, "false")
    try:
        assert [tuple(r) for r in sess.sql(q).collect()] == fused
    finally:
        sess.conf.set(C.STAGE_FUSION.key, "true")


# ---------------------------------------------------------------------------
# fused-stage boundary contract (analysis.verify_stage_contract)
# ---------------------------------------------------------------------------

def test_stage_contract_holds_for_planned_stage(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v * 2 AS w FROM scq WHERE v < 300")
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves],
                     pq.physical.schema())
    verify_stage_contract(stage)       # no raise
    assert stage.n_ops == SC.count_ops(pq.physical) >= 3


def test_stage_contract_golden_broken_out_schema(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq WHERE v < 300")
    good = pq.physical.schema()
    renamed = T.StructType(
        [T.StructField("WRONG", good.fields[0].dataType)]
        + list(good.fields[1:]))
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves], renamed)
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut-schema" in str(ei.value)


def test_stage_contract_golden_broken_out_dtype(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq WHERE v < 300")
    good = pq.physical.schema()
    retyped = T.StructType(
        [T.StructField(good.fields[0].name, T.float64)]
        + list(good.fields[1:]))
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves], retyped)
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut-dtype" in str(ei.value)


def test_stage_contract_golden_missing_input_cut(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k FROM scq")
    stage = SC.Stage(pq.physical, [], pq.physical.schema())
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-scan-leaf" in str(ei.value)


def test_stage_contract_golden_broken_input_cut(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq")
    bad_in = [T.StructType([T.StructField("zz", T.int64)])
              for _b in pq.leaves]
    stage = SC.Stage(pq.physical, bad_in, pq.physical.schema())
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut" in str(ei.value)


# ---------------------------------------------------------------------------
# observability plumbing
# ---------------------------------------------------------------------------

def test_stage_cache_stats_shape(sess):
    _mk(sess)
    sess.sql("SELECT count(*) AS c FROM scq").collect()
    st = SC.stage_cache().stats()
    for key in ("hits", "misses", "builds", "dispatches", "compile_ms",
                "entries", "stages_fused", "ops_per_stage"):
        assert key in st
    assert st["dispatches"] >= 1 and st["entries"] >= 1
    assert st["ops_per_stage"] >= 1
