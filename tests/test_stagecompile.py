"""Whole-stage tensor compilation (sql/stagecompile.py): the
process-local stage-executable cache, literal-parameterized sharing,
fusion-vs-per-op parity, and the fused-stage boundary contract.

The claims under test: repeated structurally-equal queries reuse ONE
compiled stage program (no fresh jax.jit per execution); literal
variants share that program with values riding as runtime arguments;
fusion changes dispatch structure only — the per-operator baseline
(`run_per_op`, `spark.tpu.stage.fusion=false`) produces byte-identical
results at >=3x the dispatch count; and a stage whose recorded cut
schemas disagree with the unfused physical tree fails
``verify_stage_contract`` loudly, never misexecutes."""

import numpy as np
import pytest

import spark_tpu.config as C
import spark_tpu.types as T
from spark_tpu.analysis import PlanInvariantError, verify_stage_contract
from spark_tpu.sql import stagecompile as SC
from spark_tpu.sql.planner import Planner, QueryExecution


@pytest.fixture()
def sess(spark):
    s = spark.newSession()
    s.conf.set("spark.tpu.mesh.shards", "1")
    return s


def _mk(s, n=200, seed=5):
    rng = np.random.default_rng(seed)
    s.createDataFrame({
        "k": rng.integers(0, 9, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    }).createOrReplaceTempView("scq")


def _planned(s, sql):
    qe = QueryExecution(s, s.sql(sql)._plan)
    return Planner(s).plan(qe.optimized)


# ---------------------------------------------------------------------------
# executable reuse
# ---------------------------------------------------------------------------

def test_repeated_query_reuses_one_stage_executable(sess):
    _mk(sess)
    cache = SC.stage_cache()
    q = "SELECT k, sum(v) AS sv FROM scq GROUP BY k ORDER BY k"
    a1 = [tuple(r) for r in sess.sql(q).collect()]
    s0 = cache.stats()
    a2 = [tuple(r) for r in sess.sql(q).collect()]
    s1 = cache.stats()
    assert a2 == a1
    assert s1["builds"] == s0["builds"], \
        "second run of an identical query must not compile a new stage"
    assert s1["hits"] > s0["hits"]
    assert s1["dispatches"] > s0["dispatches"]


def test_literal_variants_share_one_stage_executable(sess):
    _mk(sess)
    cache = SC.stage_cache()
    sess.sql("SELECT k, v FROM scq WHERE v < 500").collect()
    s0 = cache.stats()
    got = [tuple(r)
           for r in sess.sql("SELECT k, v FROM scq WHERE v < 100"
                             ).collect()]
    s1 = cache.stats()
    assert s1["builds"] == s0["builds"], \
        "a slotted literal variant must reuse the compiled stage"
    assert s1["hits"] > s0["hits"]
    # and the parameterized run uses the NEW literal, not the baked one
    assert got and all(v < 100 for _k, v in got)


def test_stage_fingerprint_separates_structures(sess):
    _mk(sess)
    pq1 = _planned(sess, "SELECT k + 1 AS a FROM scq")
    pq2 = _planned(sess, "SELECT k * 2 AS a FROM scq")
    k1, _ = SC.stage_fingerprint(pq1.physical)
    k2, _ = SC.stage_fingerprint(pq2.physical)
    assert k1 != k2
    # literal-only variants collapse to one key with aligned slots
    pq3 = _planned(sess, "SELECT k + 2 AS a FROM scq")
    k3, slots3 = SC.stage_fingerprint(pq3.physical)
    k1b, slots1 = SC.stage_fingerprint(pq1.physical)
    assert k3 == k1b
    assert [l.value for l in slots1] != [l.value for l in slots3]


def test_stage_cache_entry_bound_is_lru(sess):
    c = SC.StageCache(max_entries=2)
    for i in range(4):
        c.get_or_build(f"k{i}", lambda: ((lambda x: x), None))
    assert len(c) == 2
    assert c.stats()["builds"] == 4


# ---------------------------------------------------------------------------
# fused vs per-operator dispatch: parity + the >=3x dispatch claim
# ---------------------------------------------------------------------------

def test_per_op_baseline_parity_and_dispatch_count(sess):
    _mk(sess)
    pq = _planned(
        sess, "SELECT k, sum(v) AS sv, count(v) AS c FROM scq "
              "WHERE v < 800 GROUP BY k")
    fused = [tuple(r)
             for r in sess.sql("SELECT k, sum(v) AS sv, count(v) AS c "
                               "FROM scq WHERE v < 800 GROUP BY k "
                               "ORDER BY k").collect()]
    out, n_rows, n_dispatch, flags, caps, _k = SC.run_per_op(
        pq.physical, pq.leaves)
    assert not any(f > 0 for f in flags), "per-op run must not overflow"
    from spark_tpu.sql.planner import _slice_to_host
    host = _slice_to_host(out, n_rows)
    per_op = sorted(zip(*(np.asarray(v.data)[:n_rows]
                          for v in host.vectors)))
    assert per_op == sorted(fused), \
        "fusion may change dispatch structure, never results"
    # the fused stage runs as ONE dispatch; per-op pays one per operator
    assert n_dispatch >= 3, \
        f"scan-filter-project-agg should be >=3 ops, got {n_dispatch}"
    assert n_dispatch >= 3 * 1


def test_stage_fusion_conf_off_matches_fused_results(sess):
    _mk(sess)
    q = ("SELECT k, sum(v) AS sv FROM scq WHERE v < 600 "
         "GROUP BY k ORDER BY k")
    fused = [tuple(r) for r in sess.sql(q).collect()]
    sess.conf.set(C.STAGE_FUSION.key, "false")
    try:
        assert [tuple(r) for r in sess.sql(q).collect()] == fused
    finally:
        sess.conf.set(C.STAGE_FUSION.key, "true")


# ---------------------------------------------------------------------------
# fused-stage boundary contract (analysis.verify_stage_contract)
# ---------------------------------------------------------------------------

def test_stage_contract_holds_for_planned_stage(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v * 2 AS w FROM scq WHERE v < 300")
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves],
                     pq.physical.schema())
    verify_stage_contract(stage)       # no raise
    assert stage.n_ops == SC.count_ops(pq.physical) >= 3


def test_stage_contract_golden_broken_out_schema(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq WHERE v < 300")
    good = pq.physical.schema()
    renamed = T.StructType(
        [T.StructField("WRONG", good.fields[0].dataType)]
        + list(good.fields[1:]))
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves], renamed)
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut-schema" in str(ei.value)


def test_stage_contract_golden_broken_out_dtype(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq WHERE v < 300")
    good = pq.physical.schema()
    retyped = T.StructType(
        [T.StructField(good.fields[0].name, T.float64)]
        + list(good.fields[1:]))
    stage = SC.Stage(pq.physical, [b.schema for b in pq.leaves], retyped)
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut-dtype" in str(ei.value)


def test_stage_contract_golden_missing_input_cut(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k FROM scq")
    stage = SC.Stage(pq.physical, [], pq.physical.schema())
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-scan-leaf" in str(ei.value)


def test_stage_contract_golden_broken_input_cut(sess):
    _mk(sess)
    pq = _planned(sess, "SELECT k, v FROM scq")
    bad_in = [T.StructType([T.StructField("zz", T.int64)])
              for _b in pq.leaves]
    stage = SC.Stage(pq.physical, bad_in, pq.physical.schema())
    with pytest.raises(PlanInvariantError) as ei:
        verify_stage_contract(stage)
    assert "stage-cut" in str(ei.value)


# ---------------------------------------------------------------------------
# observability plumbing
# ---------------------------------------------------------------------------

def test_stage_cache_stats_shape(sess):
    _mk(sess)
    sess.sql("SELECT count(*) AS c FROM scq").collect()
    st = SC.stage_cache().stats()
    for key in ("hits", "misses", "builds", "dispatches", "compile_ms",
                "entries", "stages_fused", "ops_per_stage"):
        assert key in st
    assert st["dispatches"] >= 1 and st["entries"] >= 1
    assert st["ops_per_stage"] >= 1


# ---------------------------------------------------------------------------
# run planes on device (ISSUE 20): the compressed stage-input form
# ---------------------------------------------------------------------------

def _run_leaf(n_runs=16, rep=32, heads=None):
    """A one-batch leaf whose 'ts' column is an unmaterialized run table
    over n_runs*rep rows, plus a dense 'v' column."""
    from spark_tpu.columnar import ColumnBatch, ColumnVector, RunColumnVector
    heads = np.arange(n_runs, dtype=np.int64) if heads is None \
        else np.asarray(heads, np.int64)
    lens = np.full(len(heads), rep, dtype=np.int64)
    cap = int(lens.sum())
    rv = RunColumnVector(heads, lens, T.int64)
    vv = ColumnVector(np.arange(cap, dtype=np.int64) % 7, T.int64)
    return ColumnBatch(["ts", "v"], [rv, vv], None, cap)


def test_plan_leaves_builds_planes_and_signature(sess):
    """An eligible run leaf crosses the boundary as a plane, and the
    leaf signature gains the plane-capacity component that re-keys the
    stage away from the dense form."""
    from spark_tpu.columnar import PlaneColumnVector, RunColumnVector
    b = _run_leaf()
    out = SC.plan_leaves(sess, [b])[0]
    assert isinstance(out.column("ts"), PlaneColumnVector)
    assert not isinstance(out.column("v"), PlaneColumnVector)
    sig = SC.leaf_signature([out])
    assert "~r" in sig and SC.leaf_signature([b]) != sig


def test_plane_signature_stable_within_bucket_replans_past_it(sess):
    """Two leaves whose run counts pad to the SAME plane bucket share a
    signature (one trace serves both); growing the run count past the
    bucket re-keys — a bigger plane is a new stage program, never a
    silent shape mismatch."""
    from spark_tpu.columnar import pad_capacity
    small, bigger = 9, 13          # both pad to pad_capacity(9)?
    if pad_capacity(small) != pad_capacity(bigger):
        bigger = small             # degenerate pad fn: same-count case
    s1 = SC.leaf_signature(SC.plan_leaves(sess, [_run_leaf(small, 64)]))
    s2 = SC.leaf_signature(SC.plan_leaves(sess, [_run_leaf(
        bigger, (small * 64) // bigger if bigger != small else 64,
        heads=np.arange(bigger))]))
    # same dense capacity needed for a fair same-bucket comparison
    grown = 4 * pad_capacity(small)
    s3 = SC.leaf_signature(SC.plan_leaves(sess, [_run_leaf(grown, 64)]))
    assert ("~r%d" % pad_capacity(small)) in s1
    assert s3 != s1 and ("~r%d" % pad_capacity(grown)) in s3


def test_plan_leaves_overflow_falls_back_counted(sess):
    """A run table too large for a winning plane (pad bucket over half
    the dense capacity) stays a lazy run vector — the stage input
    materializes counted, exactly the pre-plane behavior — and the
    overflow gauge records the decision."""
    from spark_tpu import columnar as _col
    from spark_tpu.columnar import PlaneColumnVector, RunColumnVector
    n = 300
    lens = np.ones(n, dtype=np.int64); lens[:212] += 1
    rv = RunColumnVector(np.arange(n, dtype=np.int64), lens, T.int64)
    from spark_tpu.columnar import ColumnBatch
    b = ColumnBatch(["x"], [rv], None, int(lens.sum()))
    before = _col.run_plane_overflows()
    out = SC.plan_leaves(sess, [b])[0]
    assert isinstance(out.column("x"), RunColumnVector)
    assert not isinstance(out.column("x"), PlaneColumnVector)
    assert _col.run_plane_overflows() == before + 1
    # the fallback leaf materializes counted, byte-identical
    mat_before = _col.runs_materialized()
    np.testing.assert_array_equal(
        np.asarray(out.column("x").data),
        np.repeat(np.arange(n, dtype=np.int64), lens))
    assert _col.runs_materialized() > mat_before


def test_run_planes_conf_off_keeps_dense_boundary(sess):
    from spark_tpu.columnar import PlaneColumnVector
    sess.conf.set(C.STAGE_RUN_PLANES.key, "false")
    try:
        out = SC.plan_leaves(sess, [_run_leaf()])[0]
        assert not isinstance(out.column("ts"), PlaneColumnVector)
    finally:
        sess.conf.set(C.STAGE_RUN_PLANES.key, "true")


def test_plane_pytree_roundtrip():
    """flatten → unflatten preserves the plane form: two small leaves on
    the wire, the rebuilt vector still an unexpanded plane with the
    dense capacity and run count intact."""
    import jax
    from spark_tpu.columnar import (PlaneColumnVector, RunColumnVector,
                                    pad_capacity, unexpanded_plane)
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    heads = np.array([5, 3, 9], np.int64)
    lens = np.array([100, 20, 8], np.int64)
    rv = RunColumnVector(heads, lens, T.int64)
    pv = PlaneColumnVector.from_runs(rv, pad_capacity(3))
    dense = ColumnVector(np.arange(128, dtype=np.int64), T.int64)
    b = ColumnBatch(["ts", "v"], [pv, dense], None, 128)
    leaves, tree = jax.tree_util.tree_flatten(b)
    assert len(leaves) == 3          # plane_values, plane_lengths, dense
    rb = jax.tree_util.tree_unflatten(tree, leaves)
    rp = unexpanded_plane(rb.column("ts"))
    assert rp is not None
    assert rp.capacity == 128 and rp.plane_capacity == pad_capacity(3)
    np.testing.assert_array_equal(np.asarray(rp.data),
                                  np.repeat(heads, lens))


def test_plane_stage_runs_filter_agg_without_expansion(sess):
    """The tentpole end to end: an eligible filter+aggregate over a run
    leaf executes through the jitted stage lane with the column NEVER
    expanded — zero in-trace expansions, zero host materializations —
    and the answer is oracle-exact."""
    from spark_tpu import columnar as _col
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.dataframe import DataFrame
    b = _run_leaf(32, 16)
    dense = np.repeat(np.arange(32, dtype=np.int64), 16)
    DataFrame(sess, L.LocalRelation(b)).createOrReplaceTempView("rp_ev")
    mat0 = _col.runs_materialized()
    exp0 = _col.run_plane_expansions()
    st0 = _col.run_plane_stages()
    got = sess.sql("SELECT count(*) AS c, sum(ts) AS st FROM rp_ev "
                   "WHERE ts < 20").collect()
    assert got[0]["c"] == int((dense < 20).sum())
    assert got[0]["st"] == int(dense[dense < 20].sum())
    assert _col.run_plane_stages() > st0
    assert _col.run_plane_expansions() == exp0, \
        "eligible filter+agg must never expand the plane"
    assert _col.runs_materialized() == mat0, \
        "the device lane must never charge the host materialization counter"


def test_plane_stage_fallback_matches_plane_result(sess):
    """Planes off vs on over the same run leaf: byte-identical answers
    (the ISSUE's never-wrong contract for the dense fallback)."""
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.dataframe import DataFrame
    b = _run_leaf(16, 32, heads=np.arange(16)[::-1].copy())
    DataFrame(sess, L.LocalRelation(b)).createOrReplaceTempView("rp_fb")
    q = ("SELECT count(*) AS c, sum(ts) AS st, min(ts) AS mn, "
         "max(ts) AS mx FROM rp_fb WHERE ts % 3 != 1")
    on = [tuple(r) for r in sess.sql(q).collect()]
    sess.conf.set(C.STAGE_RUN_PLANES.key, "false")
    try:
        off = [tuple(r) for r in sess.sql(q).collect()]
    finally:
        sess.conf.set(C.STAGE_RUN_PLANES.key, "true")
    assert on == off
