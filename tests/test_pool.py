"""Elastic worker pool: admission-driven spawn/reap over the block
service.

Unit layer: the pure ``decide_target`` policy (eager scale-up,
hysteresis + cooldown on the way down, headroom clamp, min/max bounds),
the controller's typed ``DemandSignal`` (including the consuming
rejection delta), the ``spawn_gang`` all-or-none seam, the changing
world view (``live_view`` + ``parse_host_pid``), and the
scale-down-safety lease handoff (heir chains on the ``BlockStore``).

Process layer: a REAL supervisor under a synthetic burst spawns real
worker subprocesses that serve spooled statements oracle-exactly, then
reaps them through hysteresis; and the tier-1 chaos cell
``pool-reap-mid-fetch`` (tests/pool_worker.py) — a worker reaped
mid-fetch whose sealed output the survivor adopts with ZERO re-executed
map tasks, the reaped lease still fresh through the heir chain.
"""

import os
import time

import pytest

import chaos_matrix as cm
from spark_tpu import config as C
from spark_tpu.parallel.blockserver import BlockStore
from spark_tpu.parallel.cluster import live_view, parse_host_pid
from spark_tpu.serving.admission import AdmissionController, DemandSignal
from spark_tpu.serving.pool import (
    SUPERVISOR_OWNER, PoolDecision, PoolPolicy, WorkerPoolSupervisor,
    decide_target, spawn_gang)


# ---------------------------------------------------------------------------
# the pure policy
# ---------------------------------------------------------------------------

POLICY = PoolPolicy(min_workers=0, max_workers=4,
                    statements_per_worker=2, scale_down_rounds=3,
                    cooldown_s=2.0, min_headroom_bytes=0)


def _sig(**kw):
    return DemandSignal(**kw)


def test_scale_up_is_eager():
    """One burst observation past cooldown grows the pool to
    ceil(demand / statements_per_worker) — a queued client is paying
    latency NOW."""
    d = decide_target(POLICY, _sig(queued=5), live=0,
                      now=100.0, last_scale_ts=0.0, low_rounds=0)
    assert d == PoolDecision(3, "up", d.reason, 0)
    assert "demand 5" in d.reason


def test_scale_up_counts_running_queued_and_rejections():
    d = decide_target(POLICY, _sig(running=1, queued=2,
                                   rejected_recent=3), live=1,
                      now=100.0, last_scale_ts=0.0, low_rounds=0)
    assert d.target == 3 and d.action == "up"   # ceil(6/2)


def test_scale_up_respects_cooldown():
    d = decide_target(POLICY, _sig(queued=5), live=0,
                      now=1.0, last_scale_ts=0.0, low_rounds=0)
    assert d.action == "hold" and d.target == 0
    assert d.reason == "cooldown"


def test_scale_up_clamps_to_max():
    d = decide_target(POLICY, _sig(queued=100), live=0,
                      now=100.0, last_scale_ts=0.0, low_rounds=0)
    assert d.target == POLICY.max_workers


def test_min_workers_floor_holds_under_zero_demand():
    p = POLICY._replace(min_workers=1)
    d = decide_target(p, _sig(), live=1,
                      now=100.0, last_scale_ts=0.0, low_rounds=99)
    assert d.action == "hold" and d.target == 1
    assert d.reason == "steady" and d.low_rounds == 0


def test_scale_down_needs_hysteresis_rounds():
    """Demand must sit below capacity for scale_down_rounds consecutive
    evaluations — callers thread low_rounds through; demand recovery
    voids the streak."""
    lr = 0
    for round_no in (1, 2):
        d = decide_target(POLICY, _sig(), live=2,
                          now=100.0 + round_no, last_scale_ts=0.0,
                          low_rounds=lr)
        assert d.action == "hold" and d.target == 2
        assert f"hysteresis {round_no}/3" in d.reason
        lr = d.low_rounds
    d = decide_target(POLICY, _sig(), live=2,
                      now=103.0, last_scale_ts=0.0, low_rounds=lr)
    assert d.action == "down" and d.target == 0 and d.low_rounds == 0
    # a burst mid-streak resets the counter
    d = decide_target(POLICY, _sig(queued=9), live=2,
                      now=104.0, last_scale_ts=0.0, low_rounds=2)
    assert d.action == "up" and d.low_rounds == 0


def test_scale_down_respects_cooldown_but_keeps_streak():
    d = decide_target(POLICY, _sig(), live=2,
                      now=1.0, last_scale_ts=0.0, low_rounds=2)
    assert d.action == "hold" and d.reason == "cooldown"
    assert d.low_rounds == 3          # streak preserved for the next tick


def test_headroom_clamp_refuses_growth_only():
    """Host memory below the floor blocks scale-UP (spawning there only
    deepens the pressure) but never blocks holding or shrinking."""
    p = POLICY._replace(min_headroom_bytes=1 << 20)
    d = decide_target(p, _sig(queued=9, host_free=1 << 10), live=1,
                      now=100.0, last_scale_ts=0.0, low_rounds=0)
    assert d.action == "hold" and d.target == 1
    assert "headroom clamp" in d.reason
    # same pressure, demand below capacity: the down path still runs
    d = decide_target(p, _sig(host_free=1 << 10), live=2,
                      now=100.0, last_scale_ts=0.0, low_rounds=2)
    assert d.action == "down"
    # no ledger wired (host_free = -1): the clamp never fires
    d = decide_target(p, _sig(queued=9), live=1,
                      now=100.0, last_scale_ts=0.0, low_rounds=0)
    assert d.action == "up"


def test_policy_from_conf_reads_pool_keys():
    conf = C.Conf({C.SERVER_POOL_MIN_WORKERS.key: "1",
                   C.SERVER_POOL_MAX_WORKERS.key: "8",
                   C.SERVER_POOL_STATEMENTS_PER_WORKER.key: "3",
                   C.SERVER_POOL_SCALE_DOWN_ROUNDS.key: "5",
                   C.SERVER_POOL_COOLDOWN.key: "0.5",
                   C.SERVER_POOL_HEADROOM.key: "4096"})
    p = PoolPolicy.from_conf(conf)
    assert p == PoolPolicy(1, 8, 3, 5, 0.5, 4096)


# ---------------------------------------------------------------------------
# the typed demand signal
# ---------------------------------------------------------------------------

def test_demand_signal_snapshot_and_rejection_delta():
    """demand_signal reports running + queued + the rejection delta
    since the PREVIOUS snapshot — burst pressure registers once, not
    forever; stats() exposes a non-consuming view."""
    conf = C.Conf({C.SERVER_MAX_CONCURRENT_STATEMENTS.key: "1"})
    queue_depth = [0]
    ac = AdmissionController(conf, queued_supplier=lambda: queue_depth[0])
    ac.admit(0)
    queue_depth[0] = 2
    for _ in range(3):
        with pytest.raises(Exception):
            ac.admit(0)
    sig = ac.demand_signal()
    assert sig.running == 1 and sig.queued == 2
    assert sig.rejected_recent == 3
    assert sig.demand == 6
    assert sig.backlog_s == pytest.approx(sig.cost_ewma_s * 6)
    assert sig.host_free == -1        # no ledger wired
    # the delta was consumed: a fresh snapshot reports no new rejections
    sig2 = ac.demand_signal()
    assert sig2.rejected_recent == 0 and sig2.demand == 3
    # stats() peeks without consuming
    with pytest.raises(Exception):
        ac.admit(0)
    assert ac.stats()["demand"]["rejectedSinceSignal"] == 1
    assert ac.stats()["demand"]["rejectedSinceSignal"] == 1
    assert ac.demand_signal().rejected_recent == 1


def test_demand_signal_standing_queries_counted():
    ac = AdmissionController(C.Conf())
    ac.register_stream()
    sig = ac.demand_signal()
    assert sig.standing == 1
    assert sig.demand == 0            # standing tenants are not backlog
    ac.unregister_stream()


# ---------------------------------------------------------------------------
# spawn_gang: all-or-none
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self):
        self.terminated = False
        self.waited = False

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        self.waited = True


def test_spawn_gang_kills_and_waits_started_siblings_on_exec_error():
    """The cli.py leak this seam fixes: a partial gang must never
    outlive the exec failure that orphaned it — started siblings are
    terminated AND waited before the error re-raises."""
    started = []

    def popen(cmd, **kw):
        if len(started) == 2:
            raise OSError(8, "Exec format error")
        pr = _FakeProc()
        started.append(pr)
        return pr

    with pytest.raises(OSError):
        spawn_gang([["a"], ["b"], ["c"], ["d"]], popen=popen)
    assert len(started) == 2
    assert all(pr.terminated and pr.waited for pr in started)


def test_spawn_gang_returns_all_on_success():
    procs = spawn_gang([["a"], ["b"]], popen=lambda cmd, **kw: _FakeProc())
    assert len(procs) == 2
    assert not any(pr.terminated for pr in procs)


# ---------------------------------------------------------------------------
# the changing world: pool tenants never enter the exchange world
# ---------------------------------------------------------------------------

def test_parse_host_pid_namespaces():
    assert parse_host_pid("host-3") == 3
    assert parse_host_pid("pool-1") is None
    assert parse_host_pid(SUPERVISOR_OWNER) is None
    assert parse_host_pid("host-x") is None


def test_live_view_unions_joined_hosts():
    """A worker joined mid-stream widens the planned world; pool-scoped
    names are ignored — they are serving tenants, not exchange
    participants."""
    assert live_view(2, joined_hosts=("host-2", "pool-0",
                                      "pool-supervisor")) == [0, 1, 2]
    assert live_view(3, dead_hosts=("host-1",),
                     joined_hosts=("host-4",)) == [0, 2, 4]


# ---------------------------------------------------------------------------
# scale-down safety: the lease heir chain
# ---------------------------------------------------------------------------

def test_lease_handoff_keeps_reaped_owner_fresh(tmp_path):
    """INVARIANTS.md scale-down-safety: after handoff + release, the
    reaped owner's lease answers fresh exactly as long as the heir's
    does — sealed output stays adoptable with no file owned by the dead
    worker."""
    store = BlockStore(str(tmp_path), C.Conf())
    store.touch_lease("pool-3")
    store.handoff_lease("pool-3", SUPERVISOR_OWNER)
    store.release_lease("pool-3")
    now = time.time()
    assert store.lease_fresh("pool-3", now)          # via the heir
    assert store.lease_fresh(SUPERVISOR_OWNER, now)
    # heir goes cold -> the whole chain reads cold
    heir_lease = store._lease_path(SUPERVISOR_OWNER)
    old = now - store.ttl_s - 10
    os.utime(heir_lease, (old, old))
    assert not store.lease_fresh("pool-3", now)
    # heir sidecars are not owners: stats counts live leases only
    store.touch_lease(SUPERVISOR_OWNER)
    assert store.lease_fresh("pool-3", time.time())
    assert "pool-3.heir" not in store._live_owners()


def test_lease_heir_chain_depth_bounded(tmp_path):
    store = BlockStore(str(tmp_path), C.Conf())
    # a -> b -> ... beyond MAX_HEIR_DEPTH, last holder fresh
    names = [f"w{i}" for i in range(store.MAX_HEIR_DEPTH + 2)]
    for a, b in zip(names, names[1:]):
        store.handoff_lease(a, b)
        store.release_lease(a)
    store.touch_lease(names[-1])
    assert not store.lease_fresh(names[0], time.time())
    assert store.lease_fresh(names[-2], time.time())


# ---------------------------------------------------------------------------
# process layer: a real supervisor over real workers
# ---------------------------------------------------------------------------

def test_pool_spawns_serves_and_reaps_real_workers(spark, tmp_path):
    """The elasticity acceptance: a burst raises the target and spawns
    REAL worker processes; one serves a spooled SELECT against the
    shared warehouse oracle-exactly (marked pooled); idle demand then
    reaps every worker through hysteresis, handing each lease to the
    supervisor — and the counters/gauge values tell the same story."""
    wh = str(tmp_path / "wh")
    prev_wh = spark.conf_obj.get(C.WAREHOUSE_DIR)
    spark.conf.set("spark.sql.warehouse.dir", wh)
    conf = spark.conf_obj
    conf.set(C.SERVER_POOL_MAX_WORKERS.key, "2")
    conf.set(C.SERVER_POOL_STATEMENTS_PER_WORKER.key, "2")
    conf.set(C.SERVER_POOL_SCALE_DOWN_ROUNDS.key, "2")
    conf.set(C.SERVER_POOL_COOLDOWN.key, "0.0")
    conf.set(C.SERVER_POOL_POLL.key, "0.1")
    demand = [DemandSignal()]
    sup = WorkerPoolSupervisor(
        str(tmp_path / "pool"), conf, lambda: demand[0],
        warehouse=wh,
        blockstore_root=str(tmp_path / "blocks"))
    try:
        spark.createDataFrame([(1, "a"), (2, "b"), (3, "c")],
                              ["id", "name"]).write.saveAsTable("pool_it")
        sup.start(reconcile=False)

        d = sup.tick()                          # idle: nothing to do
        assert d.action == "hold" and sup.live == 0

        demand[0] = DemandSignal(queued=3)      # burst: wants 2 workers
        d = sup.tick()
        assert d.action == "up" and d.target == 2
        assert sup.live == 2
        assert sup.counters["workers_spawned"] == 2
        assert sup.counters["pool_target"] == 2
        assert sup.counters["pool_live"] == 2

        deadline = time.monotonic() + 60
        res = None
        while res is None and time.monotonic() < deadline:
            res = sup.execute(
                "SELECT id, name FROM pool_it ORDER BY id",
                timeout_s=10.0)
        assert res is not None, sup.counters
        assert res["rows"] == [[1, "a"], [2, "b"], [3, "c"]]
        assert res["pooled"] is True and "poolWorker" in res
        assert sup.counters["pool_statements_served"] == 1

        store = BlockStore(str(tmp_path / "blocks"), conf)
        demand[0] = DemandSignal()              # idle: hysteresis reaps
        deadline = time.monotonic() + 30
        while sup.live > 0 and time.monotonic() < deadline:
            sup.tick()
            time.sleep(0.02)
        assert sup.live == 0, sup.counters
        assert sup.counters["workers_reaped"] == 2
        assert sup.counters["pool_target"] == 0
        # every reaped worker's lease stays fresh through the heir
        now = time.time()
        for wid in (0, 1):
            assert store.lease_fresh(f"pool-{wid}", now), wid
        st = sup.stats()
        assert st["live"] == 0 and st["workers"] == []
        assert st["lastDecision"]["action"] == "down"
    finally:
        sup.stop()
        spark.conf.set("spark.sql.warehouse.dir", prev_wh)
        conf.unset(C.SERVER_POOL_MAX_WORKERS.key)
        conf.unset(C.SERVER_POOL_STATEMENTS_PER_WORKER.key)
        conf.unset(C.SERVER_POOL_SCALE_DOWN_ROUNDS.key)
        conf.unset(C.SERVER_POOL_COOLDOWN.key)
        conf.unset(C.SERVER_POOL_POLL.key)


def test_pool_execute_with_no_workers_falls_back():
    conf = C.Conf()
    sup = WorkerPoolSupervisor("/nonexistent-pool-root", conf,
                               lambda: DemandSignal())
    assert sup.execute("SELECT 1") is None
    assert sup.counters["offload_fallbacks"] == 1


# ---------------------------------------------------------------------------
# the tier-1 chaos cell: reap mid-fetch, adoption, zero re-execution
# ---------------------------------------------------------------------------

def test_reap_mid_fetch_adopts_with_zero_rerun(tmp_path):
    """The scale-down acceptance (pool_worker.py mode "reap"): worker 1
    is cooperatively REAPED the moment its last manifest lands — stops
    beating, hands its lease to the pool supervisor, exits 0 — while
    its shipped jR block is dropped from the raw exchange dir.  Worker
    0, with the stage-retry budget at ZERO, still lands the exact
    oracle by adopting the reaped worker's registered blocks: zero
    re-executed map tasks, zero recovery epochs, retry budget untouched
    — and the reaped lease answers fresh through the heir chain."""
    sc = cm.by_name("pool-reap-mid-fetch")
    assert sc["tier"] == "tier1"
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, {p: (rc, out[-400:])
                           for p, (rc, out) in results.items()})
    out0, out1 = results[0][1], results[1][1]
    assert "retries=0" in out0 and "adopted=1b" in out0, out0
    assert "heir-lease=fresh" in out0, out0
    assert "reaped at xq000001-gather" in out1, out1
    assert f"lease->{SUPERVISOR_OWNER}" in out1, out1
