"""Lineage-based stage recovery: deterministic re-execution over the
live process set after worker loss.

Unit layer: the ``{xid}-recover`` agreement round (union, divergence,
ghost self-abort), epoch-abort ledger release, the shared per-exchange
retry budget, live-set planning, per-shape admission Retry-After, and
the lint gate pinning the chaos matrix to the full fault-kind set.

Process layer (tests/chaos_matrix.py): real multi-process joins with a
FaultInjector killing one worker at a chosen exchange phase — the
survivor either recovers to the exact full-data oracle (with
``stage_retries >= 1``) or aborts structured and bounded.  The
acceptance pair (kill mid-fetch, with and without a retry budget) runs
tier-1; the full matrix is ``slow`` + ``chaos_smoke`` (bin/chaos runs
it too).
"""

import os
import threading
from concurrent.futures import ThreadPoolExecutor

import pytest

import chaos_matrix as cm
from spark_tpu import config as C
from spark_tpu.analysis.errors import PlanInvariantError
from spark_tpu.analysis.runtime import (
    verify_epoch_released, verify_recovery_agreement)
from spark_tpu.memory import HostMemoryLedger
from spark_tpu.parallel.cluster import live_view
from spark_tpu.parallel.hostshuffle import (
    BlockFetchError, ExchangeFetchFailed, HostShuffleService,
    RetryingBlockReader, _RetryBudget)
from spark_tpu.serving.admission import (
    AdmissionController, AdmissionRejected)


def _svc(tmp_path, pid, n, **kw):
    kw.setdefault("timeout_s", 2.0)
    kw.setdefault("poll_s", 0.02)
    return HostShuffleService(str(tmp_path), pid, n, **kw)


# ---------------------------------------------------------------------------
# the {xid}-recover agreement round
# ---------------------------------------------------------------------------

def test_recover_round_agrees_on_lost_union(tmp_path):
    """Two survivors of a 3-process set each observed pid 2 dead: the
    round derives the same agreed set, epoch, and adoption map on both,
    and the recovery-agreement verifier passes."""
    svc0, svc1 = _svc(tmp_path, 0, 3), _svc(tmp_path, 1, 3)
    t = threading.Thread(target=svc1.recover_round,
                         args=("xq9", 1, {2}))
    t.start()
    svc0.recover_round("xq9", 1, {2})
    t.join(timeout=5.0)
    assert not t.is_alive()
    for svc in (svc0, svc1):
        assert svc.recovered_pids == {2}
        assert svc.epoch == 1
        assert svc.live_pids() == [0, 1]
        # deterministic round-robin adoption over the live set
        assert svc.recovery_adopt == {2: 0}
        assert svc.counters["recovery_rounds"] == 1
        verify_recovery_agreement(svc, "xq9", 1)
    # ownership re-derivation: group g belongs to the g-th LIVE pid
    assert svc0.group_owner(0) == 0 and svc0.group_owner(1) == 1
    # the lost pid is blacklisted with the recovery round as the reason
    assert 2 in svc0.blacklist


def test_recover_round_divergence_aborts_structured(tmp_path):
    """A peer that neither participates in the round nor is named lost
    by anyone (it died DURING recovery, pre-publish) means no consistent
    live set exists — a NON-recoverable structured failure, never a
    hang, and the local live view stays untouched."""
    svc0 = _svc(tmp_path, 0, 2, timeout_s=0.5)
    svc0.blacklist[1] = "test: excluded but never agreed"
    with pytest.raises(ExchangeFetchFailed, match="diverged") as ei:
        svc0.recover_round("xq8", 1, set())
    assert ei.value.recoverable is False
    assert "host-1" in ei.value.lost_hosts
    assert svc0.recovered_pids == set()
    assert svc0.epoch == 0


def test_recover_round_ghost_self_abort(tmp_path):
    """A process its peers declared lost must abort instead of
    re-executing as a ghost — its writes under the new epoch would race
    the survivor that adopted its partitions."""
    svc0, svc1 = _svc(tmp_path, 0, 2), _svc(tmp_path, 1, 2)
    svc1.publish_manifest("xq7-recover1", {"epoch": 1, "lost": [0]})
    with pytest.raises(ExchangeFetchFailed, match="declared lost") as ei:
        svc0.recover_round("xq7", 1, set())
    assert ei.value.recoverable is False
    assert svc0.host_name(0) in ei.value.lost_hosts


def test_recovery_agreement_verifier_pins_epoch_monotonicity(tmp_path):
    svc0, svc1 = _svc(tmp_path, 0, 2), _svc(tmp_path, 1, 2)
    svc1.publish_manifest("xq6-recover2", {"epoch": 2, "lost": [1]})
    svc0.recover_round("xq6", 2, {1})
    assert svc0.epoch == 2
    verify_recovery_agreement(svc0, "xq6", 2)
    # an epoch that moved backward past the agreed round must be caught
    svc0.epoch = 1
    with pytest.raises(PlanInvariantError, match="epoch"):
        verify_recovery_agreement(svc0, "xq6", 2)


# ---------------------------------------------------------------------------
# epoch abort releases the dead epoch's host-memory reservations
# ---------------------------------------------------------------------------

def test_epoch_abort_releases_ledger_prefix():
    ledger = HostMemoryLedger(budget=1 << 20)
    ledger.reserve("shuffle:xq5:jL-map", 1000, exchange="xq5-jL")
    ledger.reserve("shuffle:xq5:jL-fetch", 500, exchange="xq5-jL")
    ledger.reserve("shuffle:xq6:jL-map", 300, exchange="xq6-jL")
    with pytest.raises(PlanInvariantError, match="dead-epoch-ledger"):
        verify_epoch_released(ledger, "xq5")
    freed = ledger.release_prefix("shuffle:xq5")
    assert freed == 1500                      # the bugfix: bytes reported
    verify_epoch_released(ledger, "xq5")      # no dead-epoch holders left
    assert ledger.used == 300                 # other statements untouched
    assert ledger.release_prefix("shuffle:xq5") == 0


# ---------------------------------------------------------------------------
# shared per-exchange retry budget: pool width must not multiply backoff
# ---------------------------------------------------------------------------

def test_shared_retry_budget_bounds_pool_backoff(tmp_path):
    """Four pool threads fetching from the SAME dead sender share ONE
    retry budget: total backoff sleeps stay <= the budget (not
    budget x threads), and the losers fail fast with the budget named."""
    sleeps = []
    lock = threading.Lock()

    def record(s):
        with lock:
            sleeps.append(s)

    reader = RetryingBlockReader(max_retries=8, retry_wait_s=0.01,
                                 attempt_timeout_s=0.2, sleep=record)
    budget = _RetryBudget(reader.max_retries)
    missing = str(tmp_path / "never-written.blk")
    errs = []

    def fetch(_):
        try:
            reader.read(missing, budget=budget)
        except BlockFetchError as e:
            with lock:
                errs.append(e)

    with ThreadPoolExecutor(4) as pool:
        list(pool.map(fetch, range(4)))
    assert len(errs) == 4
    # unshared, 4 threads x 8 retries would be 32 sleeps; the shared
    # budget caps the TOTAL at 8
    assert len(sleeps) <= reader.max_retries, sleeps
    assert any("shared retry budget exhausted (8 total)" in e.reason
               for e in errs), [e.reason for e in errs]


# ---------------------------------------------------------------------------
# live-set planning view
# ---------------------------------------------------------------------------

def test_live_view_excludes_dead_and_recovered():
    assert live_view(4) == [0, 1, 2, 3]
    assert live_view(4, dead_hosts=["host-2"]) == [0, 1, 3]
    assert live_view(4, recovered_pids=[1]) == [0, 2, 3]
    assert live_view(4, dead_hosts=["host-0"],
                     recovered_pids=[3]) == [1, 2]
    assert live_view(1) == [0]


# ---------------------------------------------------------------------------
# admission Retry-After from per-query-shape cost estimates
# ---------------------------------------------------------------------------

def test_retry_after_uses_shape_history_with_ewma_fallback():
    conf = C.Conf().set(C.SERVER_MAX_CONCURRENT_STATEMENTS.key, "1")
    ac = AdmissionController(conf)
    ac.admit(0, cost_key="shape-slow")
    ac.release(10.0, cost_key="shape-slow")   # first observation: 10s
    ac.admit(0, cost_key="shape-slow")        # occupies the single slot
    with pytest.raises(AdmissionRejected) as slow:
        ac.admit(0, cost_key="shape-slow")
    # seen shape: its own EWMA (10s) x 1 active statement
    assert slow.value.retry_after_s == pytest.approx(10.0)
    with pytest.raises(AdmissionRejected) as unseen:
        ac.admit(0, cost_key="shape-never-seen")
    # unseen shape: global EWMA fallback — 0.8*0.05 + 0.2*10.0
    assert unseen.value.retry_after_s == pytest.approx(2.04)
    assert unseen.value.retry_after_s < slow.value.retry_after_s
    assert slow.value.to_json()["retryAfterSeconds"] == 10.0
    assert ac.stats()["costShapes"] == 1
    # blending: a faster rerun pulls the shape estimate down
    ac.release(2.0, cost_key="shape-slow")
    ac.admit(0, cost_key="x")
    with pytest.raises(AdmissionRejected) as again:
        ac.admit(0, cost_key="shape-slow")
    assert again.value.retry_after_s == pytest.approx(0.8 * 10.0
                                                      + 0.2 * 2.0)


def test_retry_after_floor_and_shape_table_bound():
    conf = C.Conf().set(C.SERVER_MAX_CONCURRENT_STATEMENTS.key, "1")
    ac = AdmissionController(conf)
    ac.MAX_SHAPES = 4
    ac.admit(0, cost_key="a")
    ac.release(0.001, cost_key="a")           # far below the 1s floor
    ac.admit(0, cost_key="a")
    with pytest.raises(AdmissionRejected) as ei:
        ac.admit(0, cost_key="a")
    assert ei.value.retry_after_s == 1.0      # floor keeps clients civil
    ac.release(0.01, cost_key="a")
    for i in range(10):                       # table stays bounded
        ac.admit(0, cost_key=f"shape-{i}")
        ac.release(0.5, cost_key=f"shape-{i}")
    assert ac.stats()["costShapes"] <= 4


def test_cost_key_normalizes_literals_and_whitespace():
    from spark_tpu.server import _cost_key
    a = _cost_key("SELECT * FROM t WHERE x = 42 AND name = 'bob'")
    b = _cost_key("select  *   from t\nwhere x = 17 and name = 'ali''ce'")
    assert a == b == "select * from t where x = ? and name = ?"
    assert _cost_key("SELECT count(*) FROM t") != a
    assert _cost_key("SELECT x FROM t WHERE y < 1.5") \
        == _cost_key("SELECT x FROM t WHERE y < 2500.125")


# ---------------------------------------------------------------------------
# lint gate: the chaos matrix must cover every injectable fault kind,
# every phase, and stay runnable (worker files exist, verdicts total)
# ---------------------------------------------------------------------------

def test_chaos_matrix_covers_every_fault_kind_and_phase():
    missing = cm.all_kinds() - cm.kinds_covered()
    assert not missing, (
        f"fault kind(s) {sorted(missing)} have no chaos scenario — "
        "extend tests/chaos_matrix.py when adding injectors")
    assert set(cm.PHASES) <= {s["phase"] for s in cm.SCENARIOS}
    # the streaming commit phases each get a real-kill scenario too
    assert set(cm.STREAM_PHASES) \
        <= {s["phase"] for s in cm.STREAM_SCENARIOS}
    for s in cm.SCENARIOS + cm.STREAM_SCENARIOS:
        assert os.path.exists(os.path.join(cm.HERE, s["worker"])), s
        assert set(s["expect"]) == set(range(s["n"])), s["name"]
        assert set(s["plans"]) <= set(range(s["n"])), s["name"]
        assert s["tier"] in ("tier1", "slow"), s["name"]
    # the acceptance pair must stay in the tier-1 sweep
    assert cm.by_name("mid-fetch-kill")["tier"] == "tier1"
    assert cm.by_name("mid-fetch-kill-noretry")["tier"] == "tier1"
    # worker loss over partially-spilled grace state stays tier-1 too
    assert cm.by_name("grace-kill")["tier"] == "tier1"
    # kill-after-register adoption (zero re-execution) stays tier-1
    assert cm.by_name("blockserver-adopt-zero-rerun")["tier"] == "tier1"


# ---------------------------------------------------------------------------
# the real thing: 2-process join, one worker killed mid-exchange
# ---------------------------------------------------------------------------

def test_kill_mid_fetch_recovers_oracle_exact(tmp_path):
    """The tentpole acceptance: worker 1 dies after putting its join map
    output; worker 0 runs the recovery round, adopts the dead worker's
    parquet partitions from its published leaf recipes, re-executes
    under epoch 1, and returns the EXACT full-data oracle rows — the
    worker itself asserts ``stage_retries >= 1``,
    ``recovered_partitions > 0`` and a nonzero epoch before printing
    OK."""
    sc = cm.by_name("mid-fetch-kill")
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, results)
    out0 = results[0][1]
    assert "retries=1" in out0 and "recovered=1" in out0, out0
    assert "epoch=1" in out0, out0
    assert "dying after put in 'xq000001-jL'" in results[1][1]


def test_kill_during_grace_recovers_oracle_exact(tmp_path):
    """Worker loss over partially-spilled grace state: the host budget
    is capped below every reducer's drained share, so the survivor is
    already grace-degraded (sink re-bucketed into spill files, joined
    bucket-by-bucket) when the victim's death surfaces — the recovery
    epoch must replay cleanly over that state and STILL produce the
    exact full-data oracle, grace-degrading again on the replay.  The
    worker asserts nonzero ``grace_buckets_used`` and
    ``peak_host_bytes <= host_budget_bytes`` before printing OK."""
    sc = cm.by_name("grace-kill")
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, results)
    out0 = results[0][1]
    assert "retries=1" in out0 and "recovered=1" in out0, out0
    assert "epoch=1" in out0, out0
    line = [ln for ln in out0.splitlines() if "[p0] OK" in ln][-1]
    grace = int(line.rsplit("grace=", 1)[1])
    assert grace > 0, out0
    assert "dying after manifest in 'xq000001-jR'" in results[1][1]


def test_kill_after_register_adopts_with_zero_rerun(tmp_path):
    """The block-service acceptance: worker 1's jR map output is
    REGISTERED with the block service at manifest-commit time; the
    worker then loses the shipped block from the raw exchange dir and
    dies after its last manifest.  Worker 0 — with the stage-retry
    budget forced to ZERO, so any recovery attempt would fail the
    query — still lands the exact oracle by adopting the dead worker's
    registered blocks: zero re-executed map tasks, zero recovery
    epochs (the worker asserts both, plus nonzero adoption counters,
    before printing OK)."""
    sc = cm.by_name("blockserver-adopt-zero-rerun")
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, results)
    out0 = results[0][1]
    line = [ln for ln in out0.splitlines() if "[p0] OK" in ln][-1]
    assert "retries=0" in line, out0
    assert "fallback=0" not in line, out0        # the adopted-read path ran
    assert "dying after manifest in 'xq000001-gather'" in results[1][1]


def test_kill_mid_fetch_without_budget_aborts_bounded(tmp_path):
    """``maxStageRetries=0`` restores the PR-1 contract byte-for-byte:
    the survivor fails with the structured ExchangeFetchFailed naming
    the lost host, within the exchange deadline — no recovery round, no
    re-execution, no partial rows."""
    sc = cm.by_name("mid-fetch-kill-noretry")
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, results)
    out0 = results[0][1]
    line = [ln for ln in out0.splitlines() if "[p0]" in ln][-1]
    assert "host-1" in line, out0
    assert "retries=" not in line                # recovery never engaged


# ---------------------------------------------------------------------------
# the full kill-at-phase matrix (slow; bin/chaos runs the same table)
# ---------------------------------------------------------------------------

_SLOW = [s["name"] for s in cm.SCENARIOS if s["tier"] != "tier1"]


@pytest.mark.slow
@pytest.mark.chaos_smoke
@pytest.mark.parametrize("name", _SLOW)
def test_chaos_scenario(tmp_path, name):
    sc = cm.by_name(name)
    results, elapsed = cm.run_scenario(sc, str(tmp_path / "shuf"))
    bad = cm.check(sc, results, elapsed)
    assert not bad, (bad, {p: (rc, out[-400:])
                           for p, (rc, out) in results.items()})
