"""TPC-DS through the OUT-OF-CORE paths: parquet-backed tables several
times one batch capacity (VERDICT r2 #5's in-suite slice).

The 99-query sweep (`test_tpcds.py`) runs on in-memory views where every
table fits one device batch; this module writes the fact tables to
parquet and lowers `spark.tpu.scan.maxBatchRows` so real query texts
stream through the stage runner (grace joins, broadcast-fused streams,
pruned scans) and still match the sqlite oracle — the
`TPCDSQueryBenchmark.scala:63` shape at test scale.  The standalone
`examples/tpcds_midscale.py` runs the same harness at 10M+ rows.
"""

import math
import os
import sqlite3

import pytest

import spark_tpu.config as C
from spark_tpu.tpcds import QUERIES, generate
from spark_tpu.tpcds.oracle import (norm_value as _norm, row_key as _key,
                                    sqlite_text as _sqlite_text)

SF_ROWS = 120_000       # store_sales rows; catalog_sales 60k, web 30k
BATCH = 1 << 14         # 16k rows/batch → store_sales streams in 8 batches

#: queries chosen to cover the three streamed shapes: star join over one
#: big fact (q3, q42), fact⋈fact⋈fact grace joins (q17), and a
#: big-fact semi-ish filter pipeline (q55)
MID_QUERIES = ["q3", "q42", "q55", "q17"]


@pytest.fixture(scope="module")
def mid(spark, tmp_path_factory):
    tables = generate(SF_ROWS, seed=20260730)
    base = tmp_path_factory.mktemp("tpcds_mid")
    facts = {"store_sales", "catalog_sales", "web_sales", "store_returns",
             "catalog_returns", "web_returns", "inventory"}
    for name, pdf in tables.items():
        if name in facts:
            d = base / name
            os.makedirs(d)
            parts = 4
            step = (len(pdf) + parts - 1) // parts
            for i in range(parts):
                pdf.iloc[i * step:(i + 1) * step].to_parquet(
                    d / f"part-{i:03d}.parquet", index=False)
            spark.read.parquet(str(d)).createOrReplaceTempView(name)
        else:
            spark.createDataFrame(pdf).createOrReplaceTempView(name)
    con = sqlite3.connect(":memory:")
    for name, pdf in tables.items():
        pdf.to_sql(name, con, index=False)
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    yield spark, con
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))
    con.close()
    for name in tables:
        spark.catalog.dropTempView(name)


@pytest.mark.parametrize("qname", MID_QUERIES)
def test_midscale_query(mid, qname):
    spark, con = mid
    sql = QUERIES[qname]
    got = [tuple(r) for r in spark.sql(sql).collect()]
    exp = con.execute(_sqlite_text(sql)).fetchall()
    assert exp, f"{qname}: oracle returned no rows — weak test, fix params"
    got = sorted((tuple(_norm(v) for v in r) for r in got), key=_key)
    exp = sorted((tuple(_norm(v) for v in r) for r in exp), key=_key)
    assert len(got) == len(exp), \
        f"{qname}: {len(got)} rows != oracle {len(exp)}"
    for i, (g, e) in enumerate(zip(got, exp)):
        for j, (a, b) in enumerate(zip(g, e)):
            if isinstance(a, float) and isinstance(b, float):
                assert math.isclose(a, b, rel_tol=1e-6, abs_tol=1e-6), \
                    f"{qname} row {i} col {j}: {a} != {b}"
            else:
                assert a == b, f"{qname} row {i} col {j}: {a!r} != {b!r}"
