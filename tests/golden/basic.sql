-- golden file: statements separated by semicolons; results recorded in
-- basic.sql.out (SQLQueryTestSuite format analog)
SELECT 1 + 1 AS two;
SELECT CAST('2020-02-29' AS DATE) AS leap;
SELECT upper('mixedCase') AS u, length('abc') AS l;
SELECT CASE WHEN 1 < 2 THEN 'y' ELSE 'n' END AS c;
SELECT coalesce(NULL, 3) AS c, nullif(4, 4) AS n;
SELECT 7 % 3 AS m, 7 / 2 AS d, CAST(7 / 2 AS INT) AS i;
SELECT greatest(1, 5, 3) AS g, least(1, 5, 3) AS l;
SELECT round(2.5) AS r1, round(-2.5) AS r2, round(1.2345, 2) AS r3;
SELECT concat('a', 'b', 'c') AS c, substring('hello', 2, 3) AS s;
SELECT year(CAST('1999-12-31' AS DATE)) AS y, quarter(CAST('1999-12-31' AS DATE)) AS q;
