"""ML pipeline tests (`ml/` suite shapes: fit→transform→evaluate, pipelines,
cross-validation)."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.ml.base import Pipeline
from spark_tpu.ml.classification import LinearSVC, LogisticRegression, NaiveBayes
from spark_tpu.ml.clustering import KMeans
from spark_tpu.ml.evaluation import (
    BinaryClassificationEvaluator, MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_tpu.ml.feature import (
    Binarizer, Bucketizer, MinMaxScaler, OneHotEncoder, PCA, SQLTransformer,
    StandardScaler, StringIndexer, IndexToString, VectorAssembler,
)
from spark_tpu.ml.recommendation import ALS
from spark_tpu.ml.regression import DecisionTreeRegressor, LinearRegression
from spark_tpu.ml.tuning import CrossValidator, ParamGridBuilder


def blob_df(spark, n=200, seed=0):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(0, 1, (n // 2, 2)) + np.array([2.0, 2.0])
    x1 = rng.normal(0, 1, (n // 2, 2)) + np.array([-2.0, -2.0])
    X = np.vstack([x0, x1])
    y = np.array([1.0] * (n // 2) + [0.0] * (n // 2))
    return spark.createDataFrame({
        "features": X, "label": y,
    })


def test_vector_assembler(spark):
    df = spark.createDataFrame({"a": [1.0, 2.0], "b": [3.0, 4.0]})
    out = VectorAssembler(inputCols=["a", "b"], outputCol="f").transform(df)
    rows = out.collect()
    assert rows[0]["f"] == [1.0, 3.0]


def test_standard_scaler(spark):
    df = spark.createDataFrame({"features": np.array([[1.0], [3.0], [5.0]])})
    model = StandardScaler(inputCol="features", outputCol="s",
                           withMean=True).fit(df)
    got = np.array([r["s"] for r in model.transform(df).collect()])
    assert got.mean() == pytest.approx(0.0, abs=1e-9)


def test_minmax_scaler(spark):
    df = spark.createDataFrame({"features": np.array([[0.0], [5.0], [10.0]])})
    m = MinMaxScaler(inputCol="features", outputCol="s").fit(df)
    got = [r["s"][0] for r in m.transform(df).collect()]
    assert got == [0.0, 0.5, 1.0]


def test_string_indexer_roundtrip(spark):
    df = spark.createDataFrame({"cat": ["b", "a", "b", "c", "b"]})
    model = StringIndexer(inputCol="cat", outputCol="idx").fit(df)
    out = model.transform(df)
    rows = out.collect()
    by_cat = {r["cat"]: r["idx"] for r in rows}
    assert by_cat["b"] == 0.0          # most frequent gets 0
    back = IndexToString(inputCol="idx", outputCol="orig",
                         labels=model.getOrDefault("labels")).transform(out)
    assert all(r["cat"] == r["orig"] for r in back.collect())


def test_one_hot(spark):
    df = spark.createDataFrame({"idx": [0.0, 1.0, 2.0]})
    out = OneHotEncoder(inputCol="idx", outputCol="v").transform(df)
    rows = [r["v"] for r in out.collect()]
    assert rows[0] == [1.0, 0.0] and rows[2] == [0.0, 0.0]


def test_binarizer_bucketizer(spark):
    df = spark.createDataFrame({"x": [0.1, 0.6, 2.5]})
    b = Binarizer(inputCol="x", outputCol="b", threshold=0.5).transform(df)
    assert [r["b"] for r in b.collect()] == [0.0, 1.0, 1.0]
    bk = Bucketizer(inputCol="x", outputCol="bk",
                    splits=[0.0, 0.5, 1.0, 10.0]).transform(df)
    assert [r["bk"] for r in bk.collect()] == [0.0, 1.0, 2.0]


def test_pca(spark):
    rng = np.random.default_rng(0)
    base = rng.normal(0, 1, (50, 1))
    X = np.hstack([base, base * 2.0 + rng.normal(0, 0.01, (50, 1))])
    df = spark.createDataFrame({"features": X})
    m = PCA(inputCol="features", outputCol="p", k=1).fit(df)
    out = np.array([r["p"] for r in m.transform(df).collect()])
    # 1 component captures almost all variance of this rank-1-ish data
    assert out.std() > 1.0


def test_logistic_regression(spark):
    df = blob_df(spark)
    model = LogisticRegression(maxIter=15).fit(df)
    out = model.transform(df)
    acc = MulticlassClassificationEvaluator(
        metricName="accuracy").evaluate(out)
    assert acc > 0.95
    auc = BinaryClassificationEvaluator().evaluate(out)
    assert auc > 0.95


def test_linear_svc(spark):
    df = blob_df(spark, seed=3)
    model = LinearSVC(maxIter=200).fit(df)
    acc = MulticlassClassificationEvaluator(metricName="accuracy") \
        .evaluate(model.transform(df))
    assert acc > 0.9


def test_naive_bayes(spark):
    rng = np.random.default_rng(1)
    # multinomial NB separates by feature PROPORTIONS: skew them per class
    x0 = rng.poisson([5.0, 1.0, 1.0], (60, 3)).astype(float)
    x1 = rng.poisson([1.0, 1.0, 5.0], (60, 3)).astype(float)
    df = spark.createDataFrame({
        "features": np.vstack([x0, x1]),
        "label": np.array([0.0] * 60 + [1.0] * 60),
    })
    model = NaiveBayes().fit(df)
    acc = MulticlassClassificationEvaluator(metricName="accuracy") \
        .evaluate(model.transform(df))
    assert acc > 0.85


def test_linear_regression(spark):
    rng = np.random.default_rng(2)
    X = rng.normal(0, 1, (100, 3))
    y = X @ np.array([2.0, -1.0, 0.5]) + 3.0 + rng.normal(0, 0.01, 100)
    df = spark.createDataFrame({"features": X, "label": y})
    model = LinearRegression().fit(df)
    coef = np.asarray(model.getOrDefault("coefficients"))
    assert np.allclose(coef, [2.0, -1.0, 0.5], atol=0.05)
    assert model.getOrDefault("intercept") == pytest.approx(3.0, abs=0.05)
    rmse = RegressionEvaluator().evaluate(model.transform(df))
    assert rmse < 0.1


def test_decision_tree(spark):
    rng = np.random.default_rng(4)
    X = rng.uniform(0, 1, (200, 1))
    y = np.where(X[:, 0] > 0.5, 10.0, 0.0)
    df = spark.createDataFrame({"features": X, "label": y})
    model = DecisionTreeRegressor(maxDepth=3).fit(df)
    rmse = RegressionEvaluator().evaluate(model.transform(df))
    assert rmse < 1.0


def test_kmeans(spark):
    df = blob_df(spark, seed=5)
    model = KMeans(k=2, maxIter=10, seed=1).fit(df)
    centers = np.asarray(model.getOrDefault("clusterCenters"))
    # centers near (2,2) and (-2,-2)
    signs = sorted(np.sign(centers[:, 0]).tolist())
    assert signs == [-1.0, 1.0]
    assert model.computeCost(df) < 1000


def test_als(spark):
    rng = np.random.default_rng(6)
    n_u, n_i, k = 20, 15, 3
    U = rng.normal(0, 1, (n_u, k))
    V = rng.normal(0, 1, (n_i, k))
    users, items = np.meshgrid(np.arange(n_u), np.arange(n_i), indexing="ij")
    ratings = (U @ V.T).ravel()
    df = spark.createDataFrame({
        "user": users.ravel().astype(np.int64),
        "item": items.ravel().astype(np.int64),
        "rating": ratings,
    })
    model = ALS(rank=3, maxIter=12, regParam=0.01).fit(df)
    out = model.transform(df)
    rmse = RegressionEvaluator(labelCol="rating").evaluate(out)
    assert rmse < 0.1


def test_pipeline(spark):
    df = spark.createDataFrame({
        "cat": ["x", "y", "x", "y"] * 10,
        "num": np.linspace(0, 1, 40),
        "label": np.array(([0.0, 1.0] * 20)),
    })
    pipe = Pipeline(stages=[
        StringIndexer(inputCol="cat", outputCol="ci"),
        VectorAssembler(inputCols=["ci", "num"], outputCol="features"),
        LogisticRegression(maxIter=10),
    ])
    model = pipe.fit(df)
    out = model.transform(df)
    assert "prediction" in out.columns


def test_sql_transformer(spark):
    df = spark.createDataFrame({"v": [1.0, 2.0]})
    out = SQLTransformer(
        statement="SELECT v, v * 2 AS v2 FROM __THIS__").transform(df)
    assert [r["v2"] for r in out.collect()] == [2.0, 4.0]


def test_cross_validator(spark):
    df = blob_df(spark, seed=7)
    lr = LogisticRegression()
    grid = ParamGridBuilder().addGrid(lr._params()["regParam"],
                                      [0.0, 0.1]).build()
    cv = CrossValidator(estimator=lr, estimatorParamMaps=grid,
                        evaluator=BinaryClassificationEvaluator(),
                        numFolds=3)
    model = cv.fit(df)
    assert len(model.getOrDefault("avgMetrics")) == 2
    acc = MulticlassClassificationEvaluator(metricName="accuracy") \
        .evaluate(model.transform(df))
    assert acc > 0.9


def test_params_api(spark):
    lr = LogisticRegression()
    lr.setMaxIter(7)
    assert lr.getMaxIter() == 7
    assert "maxIter" in lr.explainParams()
    c = lr.copy({"maxIter": 9})
    assert c.getMaxIter() == 9 and lr.getMaxIter() == 7


def test_model_save(spark, tmp_path):
    df = blob_df(spark)
    model = LogisticRegression(maxIter=5).fit(df)
    p = str(tmp_path / "lrm")
    model.write().overwrite().save(p)
    import json, os
    meta = json.load(open(os.path.join(p, "metadata.json")))
    assert meta["class"] == "LogisticRegressionModel"


# ---------------------------------------------------------------------------
# tree ensembles (RandomForest.scala:82 / GradientBoostedTrees.scala)
# ---------------------------------------------------------------------------

def _nonlinear_reg_df(spark, n=400, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 3))
    y = np.where(X[:, 0] > 0, 5.0, -5.0) + X[:, 1] ** 2 \
        + rng.normal(0, 0.3, n)
    pdf = pd.DataFrame({"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2],
                        "label": y})
    df = spark.createDataFrame(pdf)
    from spark_tpu.ml.feature import VectorAssembler
    return VectorAssembler(inputCols=["f0", "f1", "f2"],
                           outputCol="features").transform(df), pdf


def _mse(df, pdf):
    rows = df.select("label", "prediction").collect()
    err = np.array([r["label"] - r["prediction"] for r in rows])
    return float((err ** 2).mean())


def test_random_forest_regressor_generalizes(spark):
    """Bagging reduces TEST variance vs one tree of the same depth."""
    from spark_tpu.ml.regression import (
        DecisionTreeRegressor, RandomForestRegressor,
    )
    train, _ = _nonlinear_reg_df(spark, n=300, seed=3)
    test, test_pdf = _nonlinear_reg_df(spark, n=300, seed=44)
    tree = DecisionTreeRegressor(maxDepth=4).fit(train)
    rf = RandomForestRegressor(numTrees=30, maxDepth=4,
                               subsamplingRate=0.7,
                               featureSubsetStrategy="all").fit(train)
    tree_mse = _mse(tree.transform(test), test_pdf)
    rf_mse = _mse(rf.transform(test), test_pdf)
    assert rf_mse < tree_mse * 1.02
    assert rf_mse < 2.0                 # and it actually fits the signal


def test_gbt_regressor_improves_with_rounds(spark):
    from spark_tpu.ml.regression import GBTRegressor
    df, pdf = _nonlinear_reg_df(spark)
    short = _mse(GBTRegressor(maxIter=2).fit(df).transform(df), pdf)
    long = _mse(GBTRegressor(maxIter=40).fit(df).transform(df), pdf)
    assert long < short * 0.5           # boosting reduces training error


def _classif_df(spark, n=400, seed=9):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2))
    y = ((X[:, 0] * X[:, 1]) > 0).astype(np.float64)   # XOR-ish quadrants
    pdf = pd.DataFrame({"f0": X[:, 0], "f1": X[:, 1], "label": y})
    df = spark.createDataFrame(pdf)
    from spark_tpu.ml.feature import VectorAssembler
    return VectorAssembler(inputCols=["f0", "f1"],
                           outputCol="features").transform(df), pdf


def _accuracy(df):
    rows = df.select("label", "prediction").collect()
    return float(np.mean([r["label"] == r["prediction"] for r in rows]))


def test_tree_classifiers_solve_xor(spark):
    """Linear models cannot separate XOR quadrants; trees must."""
    from spark_tpu.ml.classification import (
        DecisionTreeClassifier, GBTClassifier, RandomForestClassifier,
    )
    df, _pdf = _classif_df(spark)
    assert _accuracy(DecisionTreeClassifier(maxDepth=4)
                     .fit(df).transform(df)) > 0.9
    assert _accuracy(RandomForestClassifier(numTrees=15, maxDepth=4)
                     .fit(df).transform(df)) > 0.9
    assert _accuracy(GBTClassifier(maxIter=25, maxDepth=3)
                     .fit(df).transform(df)) > 0.9


def test_forest_model_persistence(spark, tmp_path):
    from spark_tpu.ml.regression import RandomForestRegressor
    df, pdf = _nonlinear_reg_df(spark, n=120)
    model = RandomForestRegressor(numTrees=5, maxDepth=3).fit(df)
    path = str(tmp_path / "rf_model")
    model.save(path)
    from spark_tpu.ml.regression import RandomForestRegressionModel
    loaded = RandomForestRegressionModel.load(path)
    a = [r["prediction"] for r in model.transform(df).collect()]
    b = [r["prediction"] for r in loaded.transform(df).collect()]
    np.testing.assert_allclose(a, b)
