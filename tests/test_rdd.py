"""RDD core API tests (mirrors `core/src/test/.../rdd/RDDSuite.scala` and
`PairRDDFunctionsSuite.scala` coverage shapes)."""

import os

import pytest

from spark_tpu.rdd import Accumulator, HashPartitioner, SparkContext


@pytest.fixture(scope="module")
def sc():
    ctx = SparkContext.getOrCreate(master="local[4]", appName="rdd-tests")
    yield ctx


def test_parallelize_partitions(sc):
    r = sc.parallelize(range(10), 3)
    assert r.getNumPartitions() == 3
    assert r.collect() == list(range(10))
    assert sorted(len(p) for p in r.glom().collect()) == [3, 3, 4]


def test_map_filter_flatmap(sc):
    r = sc.parallelize(range(8), 2)
    assert r.map(lambda x: x * 2).collect() == [0, 2, 4, 6, 8, 10, 12, 14]
    assert r.filter(lambda x: x % 2 == 0).collect() == [0, 2, 4, 6]
    assert r.flatMap(lambda x: [x, x]).count() == 16


def test_reduce_fold_aggregate(sc):
    r = sc.parallelize(range(1, 101), 7)
    assert r.reduce(lambda a, b: a + b) == 5050
    assert r.fold(0, lambda a, b: a + b) == 5050
    assert r.aggregate((0, 0),
                       lambda acc, v: (acc[0] + v, acc[1] + 1),
                       lambda a, b: (a[0] + b[0], a[1] + b[1])) == (5050, 100)


def test_tree_aggregate(sc):
    r = sc.parallelize(range(1000), 16)
    total = r.treeAggregate(0, lambda a, v: a + v, lambda a, b: a + b, depth=3)
    assert total == 499500


def test_reduce_by_key(sc):
    r = sc.parallelize([("a", 1), ("b", 2), ("a", 3), ("c", 4), ("b", 5)], 3)
    assert sorted(r.reduceByKey(lambda a, b: a + b).collect()) == \
        [("a", 4), ("b", 7), ("c", 4)]


def test_group_by_key_and_combine(sc):
    r = sc.parallelize([(1, "x"), (2, "y"), (1, "z")], 2)
    got = {k: sorted(v) for k, v in r.groupByKey().collect()}
    assert got == {1: ["x", "z"], 2: ["y"]}
    c = r.combineByKey(lambda v: [v], lambda acc, v: acc + [v],
                       lambda a, b: a + b)
    assert {k: sorted(v) for k, v in c.collect()} == got


def test_joins(sc):
    a = sc.parallelize([("k1", 1), ("k2", 2)], 2)
    b = sc.parallelize([("k1", "x"), ("k3", "y")], 2)
    assert a.join(b).collect() == [("k1", (1, "x"))]
    assert sorted(a.leftOuterJoin(b).collect()) == \
        [("k1", (1, "x")), ("k2", (2, None))]
    assert sorted(b.rightOuterJoin(a).collect()) == \
        [("k1", ("x", 1)), ("k2", (None, 2))]
    assert len(a.fullOuterJoin(b).collect()) == 3


def test_cogroup(sc):
    a = sc.parallelize([("k", 1), ("k", 2)], 2)
    b = sc.parallelize([("k", "x")], 1)
    [(k, (l, r))] = a.cogroup(b).collect()
    assert k == "k" and sorted(l) == [1, 2] and r == ["x"]


def test_sort_by_key_global_order(sc):
    import random
    rng = random.Random(3)
    data = [(rng.randrange(1000), i) for i in range(500)]
    r = sc.parallelize(data, 8).sortByKey()
    keys = [k for k, _ in r.collect()]
    assert keys == sorted(keys)
    desc = sc.parallelize(data, 8).sortByKey(ascending=False)
    dkeys = [k for k, _ in desc.collect()]
    assert dkeys == sorted(dkeys, reverse=True)


def test_sort_by(sc):
    r = sc.parallelize([5, 3, 8, 1], 2).sortBy(lambda x: -x)
    assert r.collect() == [8, 5, 3, 1]


def test_distinct_union_intersection_subtract(sc):
    a = sc.parallelize([1, 2, 2, 3, 3, 3], 3)
    b = sc.parallelize([3, 4], 2)
    assert sorted(a.distinct().collect()) == [1, 2, 3]
    assert sorted(a.union(b).collect()) == [1, 2, 2, 3, 3, 3, 3, 4]
    assert sorted(a.intersection(b).collect()) == [3]
    assert sorted(a.subtract(b).collect()) == [1, 2, 2]


def test_cartesian_zip(sc):
    a = sc.parallelize([1, 2], 2)
    b = sc.parallelize(["x", "y"], 2)
    assert sorted(a.cartesian(b).collect()) == \
        [(1, "x"), (1, "y"), (2, "x"), (2, "y")]
    assert a.zip(b).collect() == [(1, "x"), (2, "y")]
    assert a.zipWithIndex().collect() == [(1, 0), (2, 1)]


def test_take_top_first(sc):
    r = sc.parallelize([7, 2, 9, 1, 5], 3)
    assert r.first() == 7
    assert r.take(3) == [7, 2, 9]
    assert r.top(2) == [9, 7]
    assert r.takeOrdered(2) == [1, 2]
    assert not r.isEmpty()
    assert sc.emptyRDD().isEmpty()


def test_stats(sc):
    r = sc.parallelize([1.0, 2.0, 3.0, 4.0], 2)
    s = r.stats()
    assert s.count() == 4 and s.mean() == 2.5
    assert s.min() == 1.0 and s.max() == 4.0
    assert r.sum() == 10.0
    assert r.mean() == 2.5


def test_partition_by_preserves(sc):
    r = sc.parallelize([(i, i) for i in range(20)], 4)
    p = r.partitionBy(5)
    assert p.getNumPartitions() == 5
    assert p.partitioner == HashPartitioner(5)
    # mapValues preserves partitioner, map does not
    assert p.mapValues(lambda v: v + 1).partitioner == HashPartitioner(5)
    assert p.map(lambda kv: kv).partitioner is None


def test_coalesce_repartition(sc):
    r = sc.parallelize(range(12), 6)
    assert r.coalesce(2).getNumPartitions() == 2
    assert sorted(r.coalesce(2).collect()) == list(range(12))
    assert r.repartition(3).getNumPartitions() == 3
    assert sorted(r.repartition(3).collect()) == list(range(12))


def test_accumulator_broadcast(sc):
    acc = sc.accumulator(0)
    b = sc.broadcast({"offset": 100})
    r = sc.parallelize(range(10), 4)

    def f(x):
        acc.add(1)
        return x + b.value["offset"]
    out = r.map(f).collect()
    assert out[0] == 100 and len(out) == 10
    assert acc.value == 10


def test_count_by_key_value(sc):
    r = sc.parallelize([("a", 1), ("a", 2), ("b", 1)], 2)
    assert r.countByKey() == {"a": 2, "b": 1}
    assert sc.parallelize([1, 1, 2], 2).countByValue() == {1: 2, 2: 1}


def test_sample_deterministic(sc):
    r = sc.parallelize(range(1000), 4)
    s1 = r.sample(False, 0.1, seed=42).collect()
    s2 = r.sample(False, 0.1, seed=42).collect()
    assert s1 == s2
    assert 40 < len(s1) < 200


def test_text_file_roundtrip(sc, tmp_path):
    r = sc.parallelize(["alpha", "beta", "gamma"], 2)
    p = str(tmp_path / "txt")
    r.saveAsTextFile(p)
    assert os.path.exists(os.path.join(p, "_SUCCESS"))
    back = sc.textFile(p)
    assert sorted(back.collect()) == ["alpha", "beta", "gamma"]


def test_cache_and_debug_string(sc):
    r = sc.parallelize(range(4), 2).map(lambda x: x + 1)
    r.cache()
    assert r.collect() == [1, 2, 3, 4]
    assert "MapRDD" in r.toDebugString()


def test_to_df_bridge(sc, spark):
    r = sc.parallelize([(1, "a"), (2, "b")], 2)
    df = r.toDF(["id", "s"])
    assert [tuple(x) for x in df.collect()] == [(1, "a"), (2, "b")]


def test_df_to_rdd_bridge(spark):
    df = spark.createDataFrame({"x": [1, 2, 3]})
    assert spark.sparkContext is not None
    assert sorted(r[0] for r in df.rdd.collect()) == [1, 2, 3]


def test_pipe(sc):
    r = sc.parallelize(["a", "b"], 1)
    assert r.pipe("cat").collect() == ["a", "b"]


def test_histogram(sc):
    r = sc.parallelize([1.0, 2.0, 2.5, 3.0, 9.9], 2)
    edges, counts = r.histogram([0, 5, 10])
    assert edges == [0, 5, 10] and counts == [4, 1]
