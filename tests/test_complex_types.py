"""MapType + struct columns (VERDICT r3 item 5).

Complex values follow the reference's own architecture: maps/structs are
OBJECT-LAYER values (`complexTypeCreator.scala:164` CreateMap/
CreateNamedStruct never joined the Tungsten vectorized layout).  The
optimizer rewrites every consumer into flat array/scalar expressions
(`SimplifyExtractValueOps` over `complexTypeExtractors.scala`); a
top-level map/struct output column materializes as its pair-of-planes /
field columns (docs/DECISIONS.md) and is zipped host-side at collect.
"""

import numpy as np
import pytest

import spark_tpu.sql.functions as F
from spark_tpu.expressions import AnalysisException


@pytest.fixture()
def df(spark):
    return spark.createDataFrame(
        [(1, "a", 2.5), (2, "b", 3.5), (3, "c", 4.5)], ["id", "nm", "x"])


# ---------------------------------------------------------------------------
# struct
# ---------------------------------------------------------------------------

def test_struct_collect_rows(df):
    rows = df.select(F.struct("id", "x").alias("s"), "nm").collect()
    assert [tuple(r.s) for r in rows] == [(1, 2.5), (2, 3.5), (3, 4.5)]
    assert rows[0].s.id == 1 and rows[0].s.x == 2.5
    assert [r.nm for r in rows] == ["a", "b", "c"]


def test_struct_get_field(df):
    got = (df.select(F.struct("id", "x").alias("s"))
           .select(F.col("s").getField("x").alias("sx")).collect())
    assert [r.sx for r in got] == [2.5, 3.5, 4.5]


def test_struct_field_in_filter(df):
    got = (df.select(F.struct("id", "x").alias("s"))
           .filter(F.col("s").getField("id") > 1).collect())
    assert [r.s.id for r in got] == [2, 3]


def test_struct_dot_access_sql(spark, df):
    df.select(F.struct("id", "x").alias("s"), "nm") \
        .createOrReplaceTempView("ct")
    got = spark.sql(
        "SELECT s.id AS i, s.x + 1 AS y FROM ct ORDER BY i").collect()
    assert [r.y for r in got] == [3.5, 4.5, 5.5]
    assert [r.i for r in got] == [1, 2, 3]


def test_named_struct_sql(spark, df):
    df.createOrReplaceTempView("base")
    (r,) = spark.sql(
        "SELECT named_struct('p', id, 'q', id * 2) AS ns FROM base "
        "WHERE id = 2").collect()
    assert tuple(r.ns) == (2, 4) and r.ns.p == 2 and r.ns.q == 4


def test_struct_show_and_pandas(df):
    sdf = df.select(F.struct("id", "nm").alias("s"))
    pdf = sdf.toPandas()
    assert tuple(pdf.s.iloc[0]) == (1, "a")
    sdf.show()                              # must not raise


def test_struct_getitem_string_key(df):
    got = (df.select(F.struct("id", "x").alias("s"))
           .select(F.col("s")["id"].alias("i")).collect())
    assert [r.i for r in got] == [1, 2, 3]


# ---------------------------------------------------------------------------
# maps
# ---------------------------------------------------------------------------

@pytest.fixture()
def mdf(df):
    return df.select(
        F.create_map(F.lit("k1"), F.col("id"),
                     F.lit("k2"), F.col("id") * 10).alias("m"), "id")


def test_create_map_collect(mdf):
    rows = mdf.collect()
    assert rows[0].m == {"k1": 1, "k2": 10}
    assert rows[2].m == {"k1": 3, "k2": 30}


def test_map_keys_values(mdf):
    rows = mdf.select(F.map_keys("m").alias("ks"),
                      F.map_values("m").alias("vs")).collect()
    assert rows[1].ks == ["k1", "k2"]
    assert rows[1].vs == [2, 20]


def test_element_at_map(mdf):
    rows = mdf.select(F.element_at("m", F.lit("k2")).alias("v")).collect()
    assert [r.v for r in rows] == [10, 20, 30]


def test_element_at_missing_key_null(mdf):
    rows = mdf.select(F.element_at("m", F.lit("zz")).alias("v")).collect()
    assert [r.v for r in rows] == [None, None, None]


def test_map_getitem(mdf):
    rows = mdf.select(F.col("m")["k1"].alias("v")).collect()
    assert [r.v for r in rows] == [1, 2, 3]


def test_size_of_map(mdf):
    rows = mdf.select(F.size("m").alias("n")).collect()
    assert [r.n for r in rows] == [2, 2, 2]


def test_map_first_match_wins(spark, df):
    df.createOrReplaceTempView("base")
    rows = spark.sql(
        "SELECT element_at(map('a', id, 'a', id * 100), 'a') AS v "
        "FROM base").collect()
    assert [r.v for r in rows] == [1, 2, 3]     # GetMapValue scan order


def test_map_from_arrays(df):
    rows = (df.select(F.map_from_arrays(
        F.array(F.lit(1), F.lit(2)),
        F.array(F.col("id"), F.col("id") * 5)).alias("m"))
        .select(F.element_at("m", 2).alias("v"),
                F.map_keys("m").alias("ks")).collect())
    assert [r.v for r in rows] == [5, 10, 15]
    assert rows[0].ks == [1, 2]


def test_map_int_keys_int_element_at(spark, df):
    df.createOrReplaceTempView("base")
    rows = spark.sql(
        "SELECT element_at(map(1, id, 2, id * 7), 2) AS v FROM base"
    ).collect()
    assert [r.v for r in rows] == [7, 14, 21]


def test_map_sql_roundtrip_through_view(spark, df):
    df.select(F.create_map(F.lit("a"), F.col("x")).alias("m")) \
        .createOrReplaceTempView("mv")
    rows = spark.sql("SELECT map_values(m) AS vs FROM mv").collect()
    assert [r.vs for r in rows] == [[2.5], [3.5], [4.5]]


def test_negative_dynamic_array_index(df):
    rows = (df.select(F.array(F.col("id"), F.col("id") * 2).alias("a"), "id")
            .select(F.element_at("a", F.lit(-1)).alias("v")).collect())
    assert [r.v for r in rows] == [2, 4, 6]      # -1 = last element


# ---------------------------------------------------------------------------
# dynamic element_at on arrays (the ArrayGather flat form)
# ---------------------------------------------------------------------------

def test_dynamic_array_element_at(df):
    rows = (df.select(F.array(F.col("id"), F.col("id") * 2,
                              F.col("id") * 3).alias("a"), "id")
            .select(F.element_at("a", F.col("id")).alias("v")).collect())
    # row i picks position id: 1 -> 1, 2 -> 4, 3 -> 9
    assert [r.v for r in rows] == [1, 4, 9]


def test_array_getitem_zero_based(df):
    rows = (df.select(F.array(F.col("id"), F.col("id") * 2).alias("a"))
            .select(F.col("a")[1].alias("v")).collect())
    assert [r.v for r in rows] == [2, 4, 6]


def test_nested_struct_collect(df):
    rows = df.select(F.struct(
        F.struct("id", "x").alias("inner"), "nm").alias("outer")).collect()
    assert rows[0].outer.inner.id == 1
    assert rows[0].outer.inner.x == 2.5
    assert rows[0].outer.nm == "a"


def test_struct_of_map_collect(df):
    rows = df.select(F.struct(
        F.create_map(F.lit("k"), F.col("id")).alias("m"),
        "id").alias("s")).collect()
    assert rows[1].s.m == {"k": 2}
    assert rows[1].s.id == 2


def test_getitem_negative_array_index_is_null(df):
    rows = (df.select(F.array(F.col("id"), F.col("id") * 2).alias("a"))
            .select(F.col("a")[-1].alias("v")).collect())
    assert [r.v for r in rows] == [None, None, None]   # GetArrayItem rule


def test_map_int_key_zero(spark, df):
    df.createOrReplaceTempView("base")
    rows = spark.sql(
        "SELECT element_at(map(0, id, 1, id * 2), 0) AS v FROM base"
    ).collect()
    assert [r.v for r in rows] == [1, 2, 3]


def test_count_over_unconsumed_map(mdf):
    """A merely-present complex column must not block aggregation: the
    projection under the aggregate prunes it away."""
    assert mdf.count() == 3
    rows = mdf.groupBy().agg(F.sum("id").alias("s")).collect()
    assert rows[0].s == 6


def test_collect_through_sort_on_plain_column(mdf):
    """ORDER BY a scalar while a map column rides along: the flatten
    projection pushes through the sort to reach the creator."""
    rows = mdf.orderBy(F.col("id").desc()).collect()
    assert [r.id for r in rows] == [3, 2, 1]
    assert rows[0].m == {"k1": 3, "k2": 30}


def test_duplicate_key_collect_first_wins(spark, df):
    df.createOrReplaceTempView("base")
    rows = spark.sql(
        "SELECT map('a', id, 'a', id * 100) AS m FROM base").collect()
    # consistent with element_at's GetMapValue first-match scan order
    assert [r.m for r in rows] == [{"a": 1}, {"a": 2}, {"a": 3}]


# ---------------------------------------------------------------------------
# loud errors, not silent wrongness
# ---------------------------------------------------------------------------

def test_map_as_group_key_raises(mdf):
    with pytest.raises(Exception):
        mdf.groupBy("m").agg(F.count("*").alias("c")).collect()


def test_get_field_missing_raises(df):
    with pytest.raises(AnalysisException):
        df.select(F.struct("id").alias("s")) \
            .select(F.col("s").getField("nope")).collect()


def test_map_odd_args_raises():
    with pytest.raises(AnalysisException):
        F.create_map(F.lit("a"))
