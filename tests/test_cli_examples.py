"""CLI entry points + runnable examples (bin/ + examples/ analogs)."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
       # TPU sitecustomize plugins ignore JAX_PLATFORMS; spark_tpu honors
       # this knob at import (and the examples import spark_tpu first)
       "SPARK_TPU_PLATFORM": "cpu",
       "PYTHONPATH": ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")}


def run(args, **kw):
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=240, env=ENV, cwd=ROOT, **kw)


def test_sql_e():
    r = run(["-m", "spark_tpu.cli", "sql", "-e",
             "SELECT 1 AS one, 'x' AS s"])
    assert r.returncode == 0, r.stderr[-800:]
    assert "one" in r.stdout and "x" in r.stdout


def test_sql_file(tmp_path):
    f = tmp_path / "q.sql"
    f.write_text("CREATE TEMP VIEW v AS SELECT id FROM range(3);\n"
                 "SELECT count(*) AS c FROM v;")
    r = run(["-m", "spark_tpu.cli", "sql", "-f", str(f)])
    assert r.returncode == 0, r.stderr[-800:]
    assert "3" in r.stdout


def test_submit_runs_script(tmp_path):
    app = tmp_path / "app.py"
    app.write_text(
        "import sys\n"
        "from spark_tpu.sql.session import SparkSession\n"
        "spark = SparkSession.builder.getOrCreate()\n"
        "print('ROWS', spark.range(int(sys.argv[1])).count())\n")
    r = run(["-m", "spark_tpu.cli", "submit", str(app), "7"])
    assert r.returncode == 0, r.stderr[-800:]
    assert "ROWS 7" in r.stdout


@pytest.mark.parametrize("example", [
    "pi.py", "sql_basic.py", "streaming_window_agg.py",
    "graphx_pagerank.py", "ml_pipeline.py", "jdbc_etl.py",
])
def test_example(example):
    r = run([os.path.join("examples", example)])
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-1200:])
