"""Multi-stage out-of-core execution: grace joins + broadcast-fused streams.

VERDICT r2 #2: joins and multi-stage plans over datasets several times one
device batch must match a pandas oracle — the DAGScheduler/SortMergeJoin/
ExternalAppendOnlyMap story (`scheduler/DAGScheduler.scala:114`,
`execution/joins/SortMergeJoinExec.scala:36`) at the stage-runner level.
"""

import os

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F

BATCH = 256          # rows per streamed batch (tiny for tests)
NFACT = 1100         # > 4 batches


def _fact(seed=11, n=NFACT):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "sk": np.arange(n, dtype=np.int64),
        "item_k": rng.integers(0, 40, n).astype(np.int64),
        "date_k": rng.integers(0, 30, n).astype(np.int64),
        "qty": rng.integers(1, 9, n).astype(np.int64),
        "price": rng.normal(25.0, 9.0, n),
    })


def _write(dirpath, pdf, parts=4):
    os.makedirs(dirpath)
    step = (len(pdf) + parts - 1) // parts
    for i in range(parts):
        pdf.iloc[i * step:(i + 1) * step].to_parquet(
            os.path.join(dirpath, f"part-{i:03d}.parquet"), index=False)
    return str(dirpath)


@pytest.fixture(scope="module")
def data(tmp_path_factory):
    base = tmp_path_factory.mktemp("stages")
    fact = _fact()
    rng = np.random.default_rng(5)
    item = pd.DataFrame({
        "item_k": np.arange(40, dtype=np.int64),
        "brand": [f"brand#{i % 7}" for i in range(40)],
        "cat": rng.choice(["sports", "music", "home"], 40),
    })
    date = pd.DataFrame({
        "date_k": np.arange(30, dtype=np.int64),
        "moy": (np.arange(30, dtype=np.int64) % 12) + 1,
        "year": 2000 + (np.arange(30, dtype=np.int64) // 12),
    })
    rets = pd.DataFrame({
        "ret_sk": _fact(seed=23, n=900).sk.sample(
            900, random_state=3).to_numpy()[:900],
        "ret_qty": np.random.default_rng(9).integers(1, 5, 900).astype(
            np.int64),
    })
    paths = {
        "fact": _write(base / "fact.parquet", fact),
        "item": _write(base / "item.parquet", item, parts=1),
        "date": _write(base / "date.parquet", date, parts=1),
        "rets": _write(base / "rets.parquet", rets, parts=2),
    }
    return paths, {"fact": fact, "item": item, "date": date, "rets": rets}


@pytest.fixture()
def st(spark):
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    yield spark
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))


def test_uses_stage_path(st, data):
    from spark_tpu.sql.planner import QueryExecution
    from spark_tpu.sql.stages import plan_stages
    paths, _ = data
    fact = st.read.parquet(paths["fact"])
    item = st.read.parquet(paths["item"])
    df = fact.join(item, on="item_k").groupBy("brand").agg(F.sum("qty"))
    qe = QueryExecution(st, df._plan)
    assert plan_stages(st, qe.optimized) is not None


def test_q3_shape_star_join(st, data):
    """fact ⋈ item ⋈ date + filter + group + order/limit — the q3 pattern
    through broadcast-fused streams (TPCDSQueryBenchmark's q3 shape)."""
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    item = st.read.parquet(paths["item"])
    date = st.read.parquet(paths["date"])
    df = (fact.join(item, on="item_k").join(date, on="date_k")
          .filter(F.col("moy") == 11)
          .groupBy("brand", "year")
          .agg(F.sum(F.col("price") * F.col("qty")).alias("rev"))
          .orderBy(F.col("rev").desc())
          .limit(10))
    got = df.collect()

    m = (pdfs["fact"].merge(pdfs["item"], on="item_k")
         .merge(pdfs["date"], on="date_k"))
    m = m[m.moy == 11]
    m["rev"] = m.price * m.qty
    exp = (m.groupby(["brand", "year"], as_index=False).rev.sum()
           .sort_values("rev", ascending=False).head(10))
    assert [(r[0], r[1]) for r in got] == \
        list(zip(exp.brand.tolist(), exp.year.tolist()))
    np.testing.assert_allclose([r[2] for r in got], exp.rev.to_numpy(),
                               rtol=1e-12)


def _grace_sessions(spark):
    """Force the grace path by making every relation oversized."""
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    return spark


@pytest.mark.parametrize("how,phow", [
    ("inner", "inner"), ("left", "left"), ("right", "right"),
    ("full", "outer"),
])
def test_grace_join_big_big(st, data, how, phow):
    """Both sides exceed a batch → grace hash join, all outer variants."""
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    rets = st.read.parquet(paths["rets"])
    df = fact.join(rets, on=F.col("sk") == F.col("ret_sk"), how=how)
    got = sorted(df.collect(), key=lambda r: (
        (r[0] is None, r[0]), (r[5] is None, r[5]), (r[6] is None, r[6])))

    exp = pdfs["fact"].merge(pdfs["rets"], left_on="sk", right_on="ret_sk",
                             how=phow)
    exp = exp.sort_values(
        ["sk", "ret_sk", "ret_qty"], na_position="last",
        key=lambda s: s).reset_index(drop=True)
    assert len(got) == len(exp)
    got_sk = [r[0] for r in got]
    exp_sk = [None if pd.isna(v) else int(v) for v in exp.sk]
    assert got_sk == exp_sk
    got_rq = [r[6] for r in got]
    exp_rq = [None if pd.isna(v) else int(v) for v in exp.ret_qty]
    assert got_rq == exp_rq


@pytest.mark.parametrize("how", ["left_semi", "left_anti"])
def test_grace_semi_anti(st, data, how):
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    rets = st.read.parquet(paths["rets"])
    df = fact.join(rets, on=F.col("sk") == F.col("ret_sk"), how=how)
    got = sorted(r[0] for r in df.collect())
    in_rets = pdfs["fact"].sk.isin(pdfs["rets"].ret_sk)
    exp = pdfs["fact"].sk[in_rets if how == "left_semi" else ~in_rets]
    assert got == sorted(exp.tolist())


def test_grace_join_then_agg(st, data):
    """q17 shape: big ⋈ big ⋈ small dims, then aggregate — the VERDICT r2
    acceptance case (3-way join over >4× batch capacity vs oracle)."""
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    rets = st.read.parquet(paths["rets"])
    item = st.read.parquet(paths["item"])
    df = (fact.join(rets, on=F.col("sk") == F.col("ret_sk"))
          .join(item, on="item_k")
          .groupBy("cat")
          .agg(F.sum("ret_qty").alias("rq"), F.count("sk").alias("n"),
               F.avg("price").alias("ap")))
    got = {r[0]: r[1:] for r in df.collect()}

    m = (pdfs["fact"].merge(pdfs["rets"], left_on="sk", right_on="ret_sk")
         .merge(pdfs["item"], on="item_k"))
    exp = m.groupby("cat").agg(rq=("ret_qty", "sum"), n=("sk", "count"),
                               ap=("price", "mean"))
    assert set(got) == set(exp.index)
    for k, row in exp.iterrows():
        np.testing.assert_allclose(got[k], row.to_numpy(), rtol=1e-12)


def test_grace_skewed_single_key(st, data, tmp_path):
    """Every row shares ONE join key on both sides: salting cannot split,
    the chunked probe/build fallback must engage and stay exact."""
    n = 600
    left = pd.DataFrame({"k": np.zeros(n, np.int64),
                         "a": np.arange(n, dtype=np.int64)})
    right = pd.DataFrame({"k2": np.zeros(300, np.int64),
                          "b": np.arange(300, dtype=np.int64)})
    lp = _write(tmp_path / "skl.parquet", left)
    rp = _write(tmp_path / "skr.parquet", right)
    df = (st.read.parquet(lp)
          .join(st.read.parquet(rp), on=F.col("k") == F.col("k2"))
          .agg(F.count("a").alias("n"), F.sum("b").alias("sb")))
    (cnt, sb), = df.collect()
    assert cnt == n * 300
    assert sb == n * int(right.b.sum())


def test_grace_string_keys(st, data, tmp_path):
    """String join keys across batch-local dictionaries."""
    rng = np.random.default_rng(2)
    n = 700
    left = pd.DataFrame({
        "w": rng.choice([f"word{i:03d}" for i in range(80)], n),
        "a": np.arange(n, dtype=np.int64)})
    right = pd.DataFrame({
        "w2": [f"word{i:03d}" for i in range(0, 120, 2)],
        "b": np.arange(60, dtype=np.int64)})
    right = pd.concat([right] * 12, ignore_index=True)   # 720 rows: big side
    lp = _write(tmp_path / "stl.parquet", left)
    rp = _write(tmp_path / "str.parquet", right, parts=3)
    df = (st.read.parquet(lp)
          .join(st.read.parquet(rp), on=F.col("w") == F.col("w2")))
    got = sorted((r[0], r[3]) for r in df.collect())
    exp = left.merge(right, left_on="w", right_on="w2")
    assert got == sorted(zip(exp.w.tolist(), exp.b.tolist()))


def test_stream_above_breaker_filter(st, data):
    """HAVING-style filter above the aggregation over a joined stream."""
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    item = st.read.parquet(paths["item"])
    df = (fact.join(item, on="item_k").groupBy("brand")
          .agg(F.sum("qty").alias("q"))
          .filter(F.col("q") > 100)
          .orderBy("brand"))
    got = df.collect()
    m = pdfs["fact"].merge(pdfs["item"], on="item_k")
    exp = m.groupby("brand", as_index=False).qty.sum()
    exp = exp[exp.qty > 100].sort_values("brand")
    assert [(r[0], r[1]) for r in got] == \
        list(zip(exp.brand.tolist(), exp.qty.tolist()))


def test_nonmergeable_agg_over_stream(st, data):
    """percentile/collect have no mergeable partial: the stage runner
    streams the spine (filter reduces rows) and aggregates the
    materialized remainder — the query works past one batch instead of
    being rejected (VERDICT r2 #9)."""
    paths, pdfs = data
    fact = st.read.parquet(paths["fact"])
    df = (fact.filter(F.col("qty") >= 3)
          .groupBy("item_k")
          .agg(F.collect_list("qty").alias("qs"),
               F.percentile_approx("price", 0.5).alias("mp")))
    got = {r[0]: (sorted(r[1]), r[2]) for r in df.collect()}
    sub = pdfs["fact"][pdfs["fact"].qty >= 3]
    exp_groups = sub.groupby("item_k")
    assert set(got) == set(exp_groups.groups)
    for k, g in exp_groups:
        assert got[k][0] == sorted(g.qty.tolist())


@pytest.fixture()
def stm(spark):
    """Stage runner COMPOSED with the 8-device mesh."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    spark.conf.set("spark.tpu.mesh.shards", "8")
    yield spark
    spark.conf.set("spark.tpu.mesh.shards", "1")
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))


def test_sharded_stage_star_join(stm, data):
    """Broadcast-fused streamed join with the per-batch step running as
    one shard_map program over the mesh (build sides replicated)."""
    paths, pdfs = data
    fact = stm.read.parquet(paths["fact"])
    item = stm.read.parquet(paths["item"])
    df = (fact.join(item, on="item_k").groupBy("brand")
          .agg(F.sum("qty").alias("q"), F.count("sk").alias("n"))
          .orderBy("brand"))
    got = [tuple(r) for r in df.collect()]
    m = pdfs["fact"].merge(pdfs["item"], on="item_k")
    exp = m.groupby("brand", as_index=False).agg(
        q=("qty", "sum"), n=("sk", "count")).sort_values("brand")
    assert got == list(zip(exp.brand, exp.q, exp.n))


def test_sharded_stage_grace_join(stm, data):
    """Grace join under a distributed session: bucket-pair joins re-enter
    the distributed executor; results match the single-shard path."""
    paths, pdfs = data
    fact = stm.read.parquet(paths["fact"])
    rets = stm.read.parquet(paths["rets"])
    q = (fact.join(rets, on=F.col("sk") == F.col("ret_sk"))
         .agg(F.count("sk").alias("n"), F.sum("ret_qty").alias("s")))
    (n, s), = q.collect()
    exp = pdfs["fact"].merge(pdfs["rets"], left_on="sk", right_on="ret_sk")
    assert (n, s) == (len(exp), int(exp.ret_qty.sum()))


def test_streamed_union_of_big_facts(st, data, tmp_path):
    """UNION ALL of two oversized relations streams (q2/q5/q71 shape)
    instead of falling back to one eager whole-file batch."""
    other = _fact(seed=101, n=900)
    op = _write(tmp_path / "fact2.parquet", other, parts=3)
    paths, pdfs = data
    a = st.read.parquet(paths["fact"])
    b = st.read.parquet(op)
    df = (a.union(b).groupBy("item_k")
          .agg(F.count("sk").alias("n"), F.sum("qty").alias("q"))
          .orderBy("item_k"))
    got = [tuple(r) for r in df.collect()]
    both = pd.concat([pdfs["fact"], other], ignore_index=True)
    exp = both.groupby("item_k", as_index=False).agg(
        n=("sk", "count"), q=("qty", "sum")).sort_values("item_k")
    assert got == list(zip(exp.item_k, exp.n, exp.q))


def test_streamed_union_with_strings_and_join(st, data, tmp_path):
    """Union of streams carrying STRING columns re-encodes onto shared
    dictionaries, then joins a broadcast side downstream."""
    rng = np.random.default_rng(31)
    t1 = pd.DataFrame({"w": rng.choice(["ash", "oak", "elm"], 700),
                       "v": rng.integers(0, 9, 700).astype(np.int64)})
    t2 = pd.DataFrame({"w": rng.choice(["elm", "fir", "yew"], 600),
                       "v": rng.integers(0, 9, 600).astype(np.int64)})
    p1 = _write(tmp_path / "u1.parquet", t1, parts=3)
    p2 = _write(tmp_path / "u2.parquet", t2, parts=3)
    dim = st.createDataFrame(pd.DataFrame(
        {"w": ["ash", "oak", "elm", "fir", "yew"],
         "score": [1, 2, 3, 4, 5]}))
    df = (st.read.parquet(p1).union(st.read.parquet(p2))
          .join(dim, on="w")
          .groupBy("w").agg(F.sum("v").alias("sv"),
                            F.max("score").alias("sc"))
          .orderBy("w"))
    got = [tuple(r) for r in df.collect()]
    both = pd.concat([t1, t2], ignore_index=True)
    dimp = pd.DataFrame({"w": ["ash", "oak", "elm", "fir", "yew"],
                         "score": [1, 2, 3, 4, 5]})
    exp = (both.merge(dimp, on="w").groupby("w", as_index=False)
           .agg(sv=("v", "sum"), sc=("score", "max")).sort_values("w"))
    assert got == list(zip(exp.w, exp.sv, exp.sc))


def test_streamed_union_unknown_words_falls_back(st, data, tmp_path):
    """A union branch COMPUTING strings outside the scan dictionaries
    must fall back loudly-but-correctly, never shift dictionary codes."""
    rng = np.random.default_rng(41)
    t1 = pd.DataFrame({"w": rng.choice(["ash", "oak"], 700),
                       "v": rng.integers(0, 9, 700).astype(np.int64)})
    p1 = _write(tmp_path / "uf1.parquet", t1, parts=3)
    a = st.read.parquet(p1)
    # upper() rewrites the dictionary at trace time: words OUTSIDE the
    # scan-level union ("ASH"/"OAK") flow through the union stream
    b = st.read.parquet(p1).select(F.upper("w").alias("w"), "v")
    df = a.union(b).groupBy("w").agg(F.sum("v").alias("s")).orderBy("w")
    got = {r["w"]: r["s"] for r in df.collect()}
    sv = t1.groupby("w").v.sum()
    assert got == {"ash": sv["ash"], "oak": sv["oak"],
                   "ASH": sv["ash"], "OAK": sv["oak"]}


def test_fanout_intermediate_join_reroutes_to_grace(st, tmp_path, caplog):
    """The q14/q23 failure shape: a join of two MATERIALIZED intermediate
    results whose hot-key fanout exceeds ``spark.sql.join.maxOutputRows``
    on the eager path.  The eager allocation is worst-bucket-factor x the
    whole probe capacity; the fix re-routes the join through the grace
    spill path, where per-bucket static capacities stay small and only
    true matches are emitted (stages.py ``_Builder._join``)."""
    import logging
    nkeys, dup_l, dup_r = 16, 200, 8
    left = pd.DataFrame({
        "k": np.repeat(np.arange(nkeys, dtype=np.int64), dup_l),
        "v": np.tile(np.arange(dup_l, dtype=np.int64), nkeys),
    })
    right = pd.DataFrame({
        "k": np.repeat(np.arange(nkeys, dtype=np.int64), dup_r),
        "w": np.tile(np.arange(dup_r, dtype=np.int64), nkeys),
    })
    lp = _write(tmp_path / "fan_l.parquet", left, parts=4)
    rp = _write(tmp_path / "fan_r.parquet", right, parts=1)
    total = nkeys * dup_l * dup_r          # 25,600 true output rows
    old_cap = st.conf.get(C.JOIN_OUTPUT_MAX_ROWS)
    # eager needs ~dup_r x 3,200 probe rows = 25,600 > cap;
    # grace per-chunk needs <= factor x pad(BATCH) ~ 4k < cap
    st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, "10000")
    try:
        # .distinct() makes each side a materialized breaker result
        # (duplicate keys preserved: (k, v) pairs are unique)
        l = st.read.parquet(lp).distinct()
        r = st.read.parquet(rp).distinct()
        df = l.join(r, on="k")
        with caplog.at_level(logging.WARNING, logger="spark_tpu.stages"):
            got = df.collect()
        assert len(got) == total
        exp = left.merge(right, on="k")
        assert sorted((r["k"], r["v"], r["w"]) for r in got) == \
            sorted(zip(exp.k, exp.v, exp.w))
        assert any("grace spill path" in m for m in caplog.messages)
    finally:
        st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, str(old_cap))


def test_factor_cap_guard_is_typed(st):
    """The adaptive-growth guard raises the TYPED JoinFanoutError (the
    stage builder's reroute depends on catching exactly this class) and
    keeps its actionable guidance.  Non-equi joins plan as static
    cross-products (no adaptive factor), so the guard only ever fires on
    equi joins — where the grace reroute above applies."""
    from spark_tpu.sql.planner import JoinFanoutError, check_factor_cap
    old_cap = st.conf.get(C.JOIN_OUTPUT_MAX_ROWS)
    st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, "10000")
    try:
        check_factor_cap(4.0, 2000, st)                  # 8k rows: fine
        with pytest.raises(JoinFanoutError, match="maxOutputRows"):
            check_factor_cap(8.0, 2000, st)              # 16k > cap
    finally:
        st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, str(old_cap))


def test_grace_bucket_fanout_chunks_instead_of_dying(st, tmp_path, caplog):
    """A grace bucket pair that FITS in a batch but whose join output
    fans out past spark.sql.join.maxOutputRows must chunk the bucket
    (recursive build-side splitting) and still produce the exact result
    — the q14-under-skew failure at the bucket level."""
    import logging
    nkeys, dup = 64, 20
    left = pd.DataFrame({
        "k": np.repeat(np.arange(nkeys, dtype=np.int64), dup),
        "v": np.tile(np.arange(dup, dtype=np.int64), nkeys)})
    right = pd.DataFrame({
        "k": np.repeat(np.arange(nkeys, dtype=np.int64), dup),
        "w": np.tile(np.arange(dup, dtype=np.int64) * 7, nkeys)})
    lp = _write(tmp_path / "bf_l.parquet", left, parts=4)
    rp = _write(tmp_path / "bf_r.parquet", right, parts=4)
    old_cap = st.conf.get(C.JOIN_OUTPUT_MAX_ROWS)
    st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, "4000")
    try:
        df = st.read.parquet(lp).join(st.read.parquet(rp), on="k")
        with caplog.at_level(logging.WARNING, logger="spark_tpu.stages"):
            got = df.collect()
        assert len(got) == nkeys * dup * dup
        exp = left.merge(right, on="k")
        assert sorted((r["k"], r["v"], r["w"]) for r in got) == \
            sorted(zip(exp.k, exp.v, exp.w))
        assert any("chunking the bucket pair" in m for m in caplog.messages)
    finally:
        st.conf.set(C.JOIN_OUTPUT_MAX_ROWS.key, str(old_cap))


def test_empty_streamed_union_global_agg(st, tmp_path):
    """A global aggregate over a streamed UNION whose branches ALL filter
    empty must still emit its one global row (SUM=NULL, COUNT=0) — the
    q23 shape at small scale.  Keyed/sort/limit breakers stay empty."""
    t = pd.DataFrame({"k": np.arange(1100, dtype=np.int64),
                      "v": np.ones(1100, np.int64)})
    pa_ = _write(tmp_path / "ea.parquet", t)
    pb_ = _write(tmp_path / "eb.parquet", t)
    a = st.read.parquet(pa_).filter(F.col("k") < 0)
    b = st.read.parquet(pb_).filter(F.col("k") < 0)
    u = a.union(b)
    got = u.agg(F.sum("v").alias("s"), F.count("*").alias("c")).collect()
    assert len(got) == 1
    assert got[0]["s"] is None and got[0]["c"] == 0
    assert u.groupBy("k").agg(F.sum("v")).collect() == []
    assert u.orderBy("v").limit(5).collect() == []
