"""Per-operator SQL metrics + listener bus + event log
(SQLMetrics.scala:34 / LiveListenerBus / EventLoggingListener analogs)."""

import json
import os
import time

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F


@pytest.fixture()
def mdf(spark):
    return spark.createDataFrame(pd.DataFrame({
        "k": np.arange(100, dtype=np.int64) % 7,
        "v": np.arange(100, dtype=np.float64)}))


def test_operator_metrics(spark, mdf):
    spark.conf.set(C.METRICS_ENABLED.key, "true")
    try:
        mdf.filter(F.col("v") < 50).groupBy("k").agg(
            F.sum("v").alias("s")).collect()
        m = spark._last_qe.metrics
    finally:
        spark.conf.set(C.METRICS_ENABLED.key, "false")
    by_label = {}
    for (oid, label), v in m.items():
        by_label.setdefault(label, []).append(v)
    assert by_label["Filter"] == [50]
    assert by_label["Aggregate"] == [7]
    assert "Scan[0]" in by_label or any(
        lbl.startswith("Scan") for lbl in by_label)


def test_metrics_interpreted_lane(spark, mdf):
    spark.conf.set(C.METRICS_ENABLED.key, "true")
    spark.conf.set(C.CODEGEN_ENABLED.key, "false")
    try:
        mdf.filter(F.col("v") < 10).collect()
        m = spark._last_qe.metrics
    finally:
        spark.conf.set(C.CODEGEN_ENABLED.key, "true")
        spark.conf.set(C.METRICS_ENABLED.key, "false")
    assert any(lbl == "Filter" and v == 10 for (_o, lbl), v in m.items())


def test_listener_bus(spark, mdf):
    events = []
    spark.listenerManager.register(events.append)
    try:
        mdf.count()
    finally:
        spark.listenerManager.unregister(events.append)
    kinds = [e["event"] for e in events]
    assert "SQLExecutionStart" in kinds and "SQLExecutionEnd" in kinds
    end = [e for e in events if e["event"] == "SQLExecutionEnd"][-1]
    assert end["durationMs"] >= 0


def test_listener_failure_does_not_break_query(spark, mdf):
    def bad(_e):
        raise RuntimeError("boom")
    spark.listenerManager.register(bad)
    try:
        assert mdf.count() == 100
    finally:
        spark.listenerManager.unregister(bad)


def test_event_log(spark, mdf, tmp_path):
    d = str(tmp_path / "evlog")
    spark.conf.set(C.EVENT_LOG_DIR.key, d)
    try:
        mdf.filter(F.col("v") > 90).count()
    finally:
        spark.conf.set(C.EVENT_LOG_DIR.key, "")
    lines = [json.loads(x) for x in
             open(os.path.join(d, "eventlog.jsonl"))]
    assert any(e["event"] == "SQLExecutionStart" for e in lines)
    assert any(e["event"] == "SQLExecutionEnd" for e in lines)


def test_history_html_renderer(spark, mdf, tmp_path):
    """FsHistoryProvider analog: the JSON event log replays into one
    static HTML page with query durations, plans, and operator metrics."""
    d = str(tmp_path / "evlog2")
    spark.conf.set(C.EVENT_LOG_DIR.key, d)
    spark.conf.set(C.METRICS_ENABLED.key, "true")
    try:
        mdf.filter(F.col("v") > 50).count()
    finally:
        spark.conf.set(C.EVENT_LOG_DIR.key, "")
        spark.conf.set(C.METRICS_ENABLED.key, "false")
    # a failed execution's Start/End-with-error pair (runtime failures
    # post these through execute(); synthesized here to pin the format)
    with open(os.path.join(d, "eventlog.jsonl"), "a") as f:
        f.write(json.dumps({"event": "SQLExecutionStart", "time": 1.0,
                            "plan": "Project [boom]"}) + "\n")
        f.write(json.dumps({"event": "SQLExecutionEnd", "time": 2.0,
                            "durationMs": 1000.0,
                            "error": "RuntimeError: boom"}) + "\n")
    from spark_tpu.ui import render_history, write_history
    html_text = render_history(d)
    assert "FINISHED" in html_text
    assert "FAILED" in html_text
    assert "metrics" in html_text          # per-operator row counts block
    out = write_history(d)
    assert os.path.exists(out)
    assert open(out).read().startswith("<!doctype html>")


def test_history_cli_main(spark, mdf, tmp_path, capsys):
    d = str(tmp_path / "evlog3")
    spark.conf.set(C.EVENT_LOG_DIR.key, d)
    try:
        mdf.count()
    finally:
        spark.conf.set(C.EVENT_LOG_DIR.key, "")
    from spark_tpu import ui
    assert ui.main([d]) == 0
    printed = capsys.readouterr().out.strip()
    assert printed.endswith("history.html") and os.path.exists(printed)


def test_metrics_system_sources_and_sinks(spark, mdf, tmp_path):
    """MetricsSystem analog: process gauges snapshot on demand, console
    and CSV sinks record them (`metrics/MetricsSystem.scala`)."""
    import io as _io
    from spark_tpu.metrics import ConsoleSink, CsvSink, Source
    ms = spark.metricsSystem
    before = ms.report().get("queries", {}).get("executed", 0)
    mdf.count()
    snaps = ms.report()
    assert snaps["queries"]["executed"] >= before + 1
    assert snaps["memory"]["hbm_budget_bytes"] > 0
    # explicit sinks
    buf = _io.StringIO()
    ms.register_sink(ConsoleSink(buf))
    csv_dir = str(tmp_path / "metrics_csv")
    ms.register_sink(CsvSink(csv_dir))
    ms.report()
    ms.report()
    assert "memory" in buf.getvalue()
    rows = open(os.path.join(csv_dir, "queries.csv")).read().splitlines()
    assert rows[0].startswith("timestamp") and len(rows) == 3
    # custom source
    ms.register_source(Source("custom", {"answer": lambda: 42}))
    assert ms.report()["custom"]["answer"] == 42
    ms._sinks = [s for s in ms._sinks
                 if not isinstance(s, (ConsoleSink, CsvSink))]


def test_shuffle_range_gauges_exported(spark, tmp_path):
    """The range-exchange coordination plane is observable: cut-point
    count, skew-span splits, and sample-round manifest bytes surface as
    gauges on the session's shuffle metrics source."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        svc.publish_manifest("s", {"sample": {"points": [1, 2]}})
        _mans, nbytes = svc.gather_manifests("s")
        svc.counters["sample_bytes"] += nbytes
        svc.last_range_cutpoints = [10, 20]
        svc.plan_range_reducers(np.array([1, 1, 1000, 1], np.int64),
                                np.zeros(4, np.int64), 10)
        snap = ms.snapshots()["shuffle"]
        assert snap["range_cutpoints"] == 2
        assert snap["spans_split"] == 1          # the hot span was split
        assert snap["sample_bytes"] == nbytes > 0
        assert snap["partition_bytes_max"] >= snap["partition_bytes_median"]
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_adaptive_replan_gauges_exported(spark, tmp_path):
    """The adaptive execution plane is observable: stats-barrier
    re-decisions, strategy demotions, skew splits only the observed
    sizes revealed, and feedback-driven plan-time decisions all surface
    as gauges on the shuffle metrics source (zero until the counters
    move, so dashboards can alert on first divergence from the frozen
    plan)."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        snap0 = ms.snapshots()["shuffle"]
        for g in ("adaptive_replans", "strategy_demotions",
                  "post_sample_skew_splits", "stats_feedback_hits"):
            assert snap0[g] == 0, (g, snap0)
        svc.counters["adaptive_replans"] += 2
        svc.counters["strategy_demotions"] += 1
        svc.counters["post_sample_skew_splits"] += 3
        svc.counters["stats_feedback_hits"] += 4
        snap = ms.snapshots()["shuffle"]
        assert snap["adaptive_replans"] == 2
        assert snap["strategy_demotions"] == 1
        assert snap["post_sample_skew_splits"] == 3
        assert snap["stats_feedback_hits"] == 4
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_shuffle_dict_gauges_exported(spark, tmp_path):
    """Encoded execution is observable: dictionary columns framed as
    codes, sidecar bytes saved by the dedup, receiver-side code remaps,
    and output-boundary late materializations all surface as gauges on
    the shuffle metrics source."""
    from spark_tpu.columnar import ColumnBatch
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        snap0 = ms.snapshots()["shuffle"]
        assert snap0["dict_columns_encoded"] == 0
        assert snap0["dict_bytes_saved"] == 0
        assert snap0["codes_remapped"] == 0
        assert snap0["late_materialized_rows"] == 0
        # two blocks sharing one dictionary: the second frame dedups it
        b = ColumnBatch.from_arrays({"s": ["ash", "oak", "ash"]})
        svc.put("dg1", 0, [b])
        svc.put("dg1", 0, [b])
        svc.commit("dg1")
        # an exchange whose own batches disagree on the dictionary:
        # the receiver unifies into one sorted code space
        ba = ColumnBatch.from_arrays({"s": ["ash", "oak"]})
        bb = ColumnBatch.from_arrays({"s": ["fir", "oak"]})
        out = svc.exchange("dg2", {0: [ba, bb]})
        dicts = {v.dictionary for r in out for v in r.vectors}
        assert dicts == {("ash", "fir", "oak")}
        # late materialization: decoding codes to words at the boundary
        out[0].to_pylist()
        snap = ms.snapshots()["shuffle"]
        assert snap["dict_columns_encoded"] == 2
        assert snap["dict_bytes_saved"] > 0
        assert snap["codes_remapped"] > 0
        assert snap["late_materialized_rows"] > 0
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_shuffle_run_gauges_exported(spark, tmp_path):
    """Run-length execution is observable: columns shipped as run/delta
    codes, wire bytes saved, rows the run-aware operators processed
    without expansion, and rows re-inflated at materialization
    boundaries all surface as gauges on the shuffle metrics source."""
    from spark_tpu import types as T
    from spark_tpu.columnar import ColumnBatch, RunColumnVector
    from spark_tpu.expressions import Col, GT, Literal
    from spark_tpu.kernels import apply_filter
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        assert svc.run_codes                       # default-on conf
        snap0 = ms.snapshots()["shuffle"]
        for g in ("rle_columns_encoded", "run_bytes_saved",
                  "run_aware_op_rows", "runs_materialized"):
            assert snap0[g] == 0, (g, snap0)
        # a run-shaped block RLE-encodes on the put path
        b = ColumnBatch.from_arrays(
            {"v": np.repeat(np.arange(4, dtype=np.int64), 64)})
        svc.put("rg", 0, [b])
        svc.commit("rg")
        # a run-aware filter over a lazy run vector, then the explicit
        # materialization boundary
        rv = RunColumnVector(np.asarray([1, 2], np.int64),
                             np.asarray([32, 32], np.int64), T.int64)
        rb = ColumnBatch(["x"], [rv], None, 64)
        apply_filter(np, rb, GT(Col("x"), Literal(1, T.int64)))
        np.asarray(rv.data)
        snap = ms.snapshots()["shuffle"]
        assert snap["rle_columns_encoded"] >= 1
        assert snap["run_bytes_saved"] > 0
        assert snap["run_aware_op_rows"] == 64
        assert snap["runs_materialized"] == 64
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_run_activity_in_status(spark, tmp_path):
    """/status surfaces per-session run-length execution activity the
    same way it surfaces ICI/grace: {} while quiet, live gauges once
    columns ship encoded or run-aware operators fire."""
    import urllib.request

    from spark_tpu import columnar as _col
    from spark_tpu.server import SQLServer
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    srv = None
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        srv = SQLServer(spark, port=0).start()

        def status():
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/status",
                    timeout=30) as r:
                return json.loads(r.read())

        st = status()
        assert st["runActivity"] == {}            # codes never engaged
        svc.counters["rle_columns_encoded"] += 3
        svc.counters["run_bytes_saved"] += 2048
        _col.bump_run_aware(128)
        st = status()
        got = st["runActivity"]["default"]
        assert got["rle_columns_encoded"] == 3
        assert got["run_bytes_saved"] == 2048
        assert got["run_aware_op_rows"] == 128
    finally:
        if srv is not None:
            srv.stop()
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_spill_and_ledger_gauges_exported(spark, tmp_path):
    """Memory-pressure handling is observable: spill bytes/events, fetch
    backpressure waits, and the host ledger's peak/budget surface as
    gauges on the shuffle source — and the session memory source mirrors
    the same ledger."""
    import threading

    from spark_tpu.parallel.hostshuffle import _InflightGate
    prev = getattr(spark, "_crossproc_svc", None)
    prev_ledger = getattr(spark, "_host_ledger", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        snap0 = ms.snapshots()["shuffle"]
        assert snap0["spill_bytes"] == 0
        assert snap0["spill_events"] == 0
        assert snap0["fetch_backpressure_waits"] == 0
        assert snap0["host_budget_bytes"] > 0
        # a spill write counts bytes and events
        svc.spill_write(str(tmp_path / "r.spill"), b"z" * 2048)
        # a ledger reservation moves the peak (and releases cleanly)
        svc.ledger.reserve("shuffle:test", 4096)
        svc.ledger.release("shuffle:test")
        # the in-flight gate reports each wait through the service hook
        gate = _InflightGate(16, on_wait=svc._count_backpressure)
        gate.acquire(10)
        t = threading.Timer(0.05, lambda: gate.release(10))
        t.start()
        gate.acquire(10)                   # must wait for the release
        gate.release(10)
        t.join()
        snap = ms.snapshots()["shuffle"]
        assert snap["spill_bytes"] == 2048
        assert snap["spill_events"] == 1
        assert snap["fetch_backpressure_waits"] == 1
        assert snap["peak_host_bytes"] >= 4096
        # the session memory source reads the SAME ledger
        memsnap = ms.snapshots()["memory"]
        assert memsnap["host_budget_bytes"] == snap["host_budget_bytes"]
        assert memsnap["host_peak_bytes"] == snap["peak_host_bytes"]
        assert memsnap["host_used_bytes"] == 0
    finally:
        spark._crossproc_svc = prev
        spark._host_ledger = prev_ledger
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_memory_leak_check_releases(spark, mdf):
    """Executor.scala's 'managed memory leak detected' idiom: a leaked
    execution reservation is detected and released after the query."""
    from spark_tpu.sql.planner import QueryExecution
    qe = QueryExecution(spark, mdf._plan)
    spark._memory.acquire_execution(f"query:{id(qe)}", 1234)
    qe.execute()
    assert f"query:{id(qe)}" not in spark._memory._execution


def test_analysis_verifier_gauges(spark, mdf):
    """The plan verifier's accounting rides the session metrics system:
    plans_verified increments per verified plan (verifyPlans=auto is ON
    under pytest) and plan_verify_ms accumulates wall time."""
    ms = spark.metricsSystem
    before = ms.report()["analysis"]
    mdf.filter(F.col("v") < 10).count()
    after = ms.report()["analysis"]
    assert after["plans_verified"] > before["plans_verified"]
    assert after["plan_verify_ms"] >= before["plan_verify_ms"]
    assert after["plan_verify_ms"] < 60_000  # sanity: ms, not seconds


def test_decision_trace_gauges_exported(spark):
    """The replica-determinism backstop's accounting rides the same
    analysis Source: every verify_decision_trace call bumps
    decision_trace_checks, a caught divergence bumps
    decision_trace_divergence — the gauge an operator alarms on."""
    from spark_tpu import types as T
    from spark_tpu.analysis import PlanInvariantError
    from spark_tpu.analysis import runtime as az_rt
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.expressions import Col
    from spark_tpu.sql import logical as L

    ms = spark.metricsSystem
    before = ms.report()["analysis"]
    assert before["decision_trace_divergence"] == 0
    inputs = {"frozen": "hash", "epoch": 0, "live": [0, 1], "adopt": []}
    arr = np.asarray([1], dtype=np.int64)
    rel = L.LocalRelation(ColumnBatch(
        ["k"], [ColumnVector(arr, T.LongType())], np.ones(1, bool), 1))
    join = L.Join(rel, rel, "inner", on=Col("k") == Col("k"))
    mans = {0: {"dtrace": {"h": az_rt.decision_trace(inputs),
                           "c": inputs}}}
    az_rt.verify_decision_trace(spark, join, None, "xq000001-plan",
                                mans, inputs)
    theirs = dict(inputs, epoch=1)
    mans[1] = {"dtrace": {"h": az_rt.decision_trace(theirs),
                          "c": theirs}}
    with pytest.raises(PlanInvariantError):
        az_rt.verify_decision_trace(spark, join, None, "xq000001-plan",
                                    mans, inputs)
    after = ms.report()["analysis"]
    assert after["decision_trace_checks"] == \
        before["decision_trace_checks"] + 2
    assert after["decision_trace_divergence"] == 1


def test_stage_compile_gauges_exported(spark, mdf):
    """ISSUE 11 observability: the process stage-executable cache rides
    the session metrics system as the 'compile' Source — compile cost,
    hit/miss counters, fusion width (ops_per_stage) all live gauges."""
    ms = spark.metricsSystem
    before = ms.report()["compile"]
    for key in ("stage_compile_ms", "stage_cache_hits",
                "stage_cache_misses", "stage_cache_entries",
                "stage_dispatches", "stages_fused", "ops_per_stage"):
        assert key in before, key
    mdf.groupBy("k").agg(F.sum("v")).collect()
    mdf.groupBy("k").agg(F.sum("v")).collect()   # second run: warm
    after = ms.report()["compile"]
    assert after["stage_dispatches"] > before["stage_dispatches"]
    assert after["stage_cache_hits"] > before["stage_cache_hits"]
    assert after["stages_fused"] >= 1
    assert after["ops_per_stage"] >= 1.0
    assert after["stage_compile_ms"] >= 0.0
    # warm reuse must not have built a new executable for the repeat
    assert after["stage_cache_entries"] >= 1


def test_grace_and_elastic_gauges_exported(spark, tmp_path):
    """ISSUE 13 observability: graceful-degradation and elastic-reducer
    activity ride the shuffle Source as live gauges — grace bucket
    count, grace spill bytes, salted re-splits, and the planned vs
    observed vs narrowed reducer tallies."""
    prev = getattr(spark, "_crossproc_svc", None)
    prev_ledger = getattr(spark, "_host_ledger", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        snap0 = ms.snapshots()["shuffle"]
        for key in ("grace_buckets_used", "grace_spill_bytes",
                    "grace_salted_resplits", "reducers_planned",
                    "reducers_observed", "reducers_elastic"):
            assert key in snap0, key
            assert snap0[key] == 0, (key, snap0[key])
        svc.counters["grace_buckets_used"] += 3
        svc.counters["grace_spill_bytes"] += 4096
        svc.counters["grace_salted_resplits"] += 1
        svc.counters["reducers_planned"] += 4
        svc.counters["reducers_observed"] += 2
        svc.counters["reducers_elastic"] += 1
        snap = ms.snapshots()["shuffle"]
        assert snap["grace_buckets_used"] == 3
        assert snap["grace_spill_bytes"] == 4096
        assert snap["grace_salted_resplits"] == 1
        assert snap["reducers_planned"] == 4
        assert snap["reducers_observed"] == 2
        assert snap["reducers_elastic"] == 1
    finally:
        spark._crossproc_svc = prev
        spark._host_ledger = prev_ledger
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_ici_tier_gauges_exported(spark, tmp_path):
    """The two-tier exchange is observable: device-tier exchange count
    and HBM bytes moved, host-tier fallbacks, and the agreed tier
    split's peer count all ride the shuffle Source as live gauges —
    zero until the tier engages, so dashboards can alert on the first
    fallback (ICI degraded to DCN) the moment it happens."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        snap0 = ms.snapshots()["shuffle"]
        for key in ("ici_exchanges", "ici_bytes_moved",
                    "dcn_fallback_exchanges", "tier_split_peers"):
            assert key in snap0, key
            assert snap0[key] == 0, (key, snap0[key])
        svc.counters["ici_exchanges"] += 5
        svc.counters["ici_bytes_moved"] += 1 << 20
        svc.counters["dcn_fallback_exchanges"] += 1
        svc.counters["tier_split_peers"] = 3
        snap = ms.snapshots()["shuffle"]
        assert snap["ici_exchanges"] == 5
        assert snap["ici_bytes_moved"] == 1 << 20
        assert snap["dcn_fallback_exchanges"] == 1
        assert snap["tier_split_peers"] == 3
    finally:
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_ici_activity_in_status(spark, tmp_path):
    """/status surfaces per-session device-tier activity the same way
    it surfaces grace degradation: {} while quiet, live counters once
    the tier moves bytes or folds back."""
    import urllib.request

    from spark_tpu.server import SQLServer
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    srv = None
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        srv = SQLServer(spark, port=0).start()

        def status():
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/status",
                    timeout=30) as r:
                return json.loads(r.read())

        st = status()
        assert st["iciActivity"] == {}            # tier never engaged
        svc.counters["ici_exchanges"] += 2
        svc.counters["ici_bytes_moved"] += 4096
        svc.counters["dcn_fallback_exchanges"] += 1
        st = status()
        got = st["iciActivity"]["default"]
        assert got["ici_exchanges"] == 2
        assert got["ici_bytes_moved"] == 4096
        assert got["dcn_fallback_exchanges"] == 1
    finally:
        if srv is not None:
            srv.stop()
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_grace_activity_in_status_and_admission(spark, tmp_path):
    """/status surfaces per-session grace activity, and the admission
    controller both reports the cluster-wide degraded-event total and
    widens its memory headroom floor while degradation is live."""
    import urllib.request

    from spark_tpu.server import SQLServer
    prev = getattr(spark, "_crossproc_svc", None)
    prev_ledger = getattr(spark, "_host_ledger", None)
    ms = spark.metricsSystem
    srv = None
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        srv = SQLServer(spark, port=0).start()

        def status():
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/status",
                    timeout=30) as r:
                return json.loads(r.read())

        st = status()
        assert st["graceActivity"] == {}          # quiet cluster
        assert st["admission"]["graceDegraded"] == 0
        svc.counters["grace_buckets_used"] += 2
        svc.counters["grace_spill_bytes"] += 8192
        st = status()
        got = st["graceActivity"]["default"]
        assert got["grace_buckets_used"] == 2
        assert got["grace_spill_bytes"] == 8192
        assert st["admission"]["graceDegraded"] == 2
        ac = srv._admission
        assert ac._grace() == 2
        assert ac.GRACE_HEADROOM_FACTOR > 1.0
    finally:
        if srv is not None:
            srv.stop()
        spark._crossproc_svc = prev
        spark._host_ledger = prev_ledger
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


# ---------------------------------------------------------------------------
# ISSUE 15 observability: standing-query state/recovery gauges on the
# `streaming` Source — state residency in the host ledger, watermark
# progress, eviction counts, and wire-format spill under a capped budget
# with byte parity against the uncapped run
# ---------------------------------------------------------------------------

@pytest.fixture()
def _single_shard(spark):
    """Streaming micro-batches run local single-shard; pin the shared
    session in case an earlier module leaked a wider mesh conf."""
    prev = spark.conf.get("spark.tpu.mesh.shards")
    spark.conf.set("spark.tpu.mesh.shards", "1")
    yield spark
    spark.conf.set("spark.tpu.mesh.shards", str(prev))


def _stream_feeds(spark, in_dir):
    def s(n):
        return int(n * 1_000_000)
    feeds = [[(s(1), "a", 1), (s(9), "b", 2)],
             [(s(20), "a", 4), (s(21), "b", 1)],
             [(s(35), "c", 8)],
             [(s(50), "a", 3), (s(51), "d", 9)]]
    os.makedirs(in_dir, exist_ok=True)
    for i, rows in enumerate(feeds):
        spark.createDataFrame({
            "ts": np.array([r[0] for r in rows], "datetime64[us]"),
            "k": [r[1] for r in rows],
            "v": np.array([r[2] for r in rows], np.int64),
        }).write.parquet(os.path.join(in_dir, f"f{i}"))


def _stream_lifetime(spark, in_dir, ckpt, out):
    from spark_tpu import types as T
    from spark_tpu.sql.dataframe import DataFrame
    from spark_tpu.streaming.core import (
        FileSink, FileStreamSource, StreamExecution, StreamingRelation)
    schema = T.StructType([
        T.StructField("ts", T.timestamp),
        T.StructField("k", T.string),
        T.StructField("v", T.int64)])
    src = FileStreamSource("parquet", in_dir, schema,
                          {"maxfilespertrigger": "1"})
    df = (DataFrame(spark, StreamingRelation(src))
          .withWatermark("ts", "5 seconds")
          .groupBy(F.window("ts", "10 seconds").alias("w"))
          .agg(F.sum("v").alias("s")))
    return StreamExecution(spark, df._plan, FileSink("json", out, {}),
                           "append", ckpt, 0.1, None)


def test_streaming_gauges_and_ledger_tenancy(_single_shard, spark, tmp_path):
    from spark_tpu.memory import HostMemoryLedger
    prev_ledger = getattr(spark, "_host_ledger", None)
    ms = spark.metricsSystem
    spark._host_ledger = HostMemoryLedger(budget=64 << 20)
    try:
        in_dir = str(tmp_path / "in")
        _stream_feeds(spark, in_dir)
        ex = _stream_lifetime(spark, in_dir, str(tmp_path / "ckpt"),
                              str(tmp_path / "out"))
        ex.process_all_available()
        snap = ms.snapshots()["streaming"]
        assert snap["standing_queries"] == 1
        assert snap["batches_committed"] == 4
        assert snap["replayed_batches"] == 0
        assert snap["stage_rebuilds_last"] == 0    # batch 4 ran cached
        assert snap["state_bytes"] > 0
        assert snap["state_rows"] > 0
        # watermark advanced to max_event - 5s of the last feed
        assert snap["watermark_us"] == 51_000_000 - 5_000_000
        # append mode finalized + evicted the closed windows
        assert snap["evicted_rows"] > 0
        assert snap["spill_events"] == 0           # budget was ample
        assert "state_versions_spilled" in snap
        # the resident state is a ledger tenant under the stream's owner
        owner = f"stream:{ex.id[:8]}:state"
        assert spark._host_ledger.held(owner) == snap["state_bytes"]
        ex.stop()
        # stop() releases the whole tenancy prefix and leaves the Source
        assert spark._host_ledger.held(owner) == 0
        snap = ms.snapshots()["streaming"]
        assert snap["standing_queries"] == 0
        assert snap["state_bytes"] == 0
    finally:
        spark._host_ledger = prev_ledger


def test_streaming_state_spills_under_capped_ledger_with_parity(
        _single_shard, spark, tmp_path):
    """Capping the host ledger BELOW the streaming working set forces
    the state between micro-batches into wire-format spill files — the
    spill gauges light up, and the sink stays byte-identical to the
    uncapped run."""
    import glob

    from spark_tpu.memory import HostMemoryLedger
    prev_ledger = getattr(spark, "_host_ledger", None)
    try:
        in_dir = str(tmp_path / "in")
        _stream_feeds(spark, in_dir)

        def run(tag, budget):
            spark._host_ledger = HostMemoryLedger(budget=budget)
            ex = _stream_lifetime(spark, in_dir,
                                  str(tmp_path / f"{tag}-ckpt"),
                                  str(tmp_path / f"{tag}-out"))
            ex.process_all_available()
            metrics = dict(ex.metrics)
            ex.stop()
            files = {os.path.basename(p): open(p, "rb").read()
                     for p in sorted(glob.glob(
                         os.path.join(tmp_path, f"{tag}-out", "part-*")))}
            return metrics, files

        free_metrics, free_files = run("free", 64 << 20)
        capped_metrics, capped_files = run("capped", 256)  # < working set
        assert free_metrics["spill_events"] == 0
        assert capped_metrics["spill_events"] > 0
        assert capped_metrics["spill_bytes"] > 0
        # pressure changed WHERE state lived, never WHAT was emitted
        assert capped_files == free_files and free_files
    finally:
        spark._host_ledger = prev_ledger


# ---------------------------------------------------------------------------
# elastic-pool observability: the `pool` Source gauges + /status
# poolActivity (spawn/reap/target/live/decisions/failures)
# ---------------------------------------------------------------------------

POOL_GAUGES = ("workers_spawned", "workers_reaped", "pool_target",
               "pool_live", "scale_decisions", "spawn_failures")


def test_pool_source_registered_and_zero_when_pool_off(spark):
    """The `pool` Source exists on every server (gauges read through
    the supervisor handle, 0 until one attaches) and /status carries no
    poolActivity while the pool is disabled."""
    import urllib.request

    from spark_tpu.server import SQLServer
    ms = spark.metricsSystem
    srv = None
    try:
        srv = SQLServer(spark, port=0).start()
        snap = ms.snapshots()["pool"]
        for g in POOL_GAUGES:
            assert snap[g] == 0, (g, snap)
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/status", timeout=30) as r:
            st = json.loads(r.read())
        assert "poolActivity" not in st
        assert "pool" in st["metrics"]
    finally:
        if srv is not None:
            srv.stop()
        ms._sources = [s for s in ms._sources
                       if s.name not in ("serving", "pool")]


def test_pool_gauges_and_status_activity(spark, tmp_path):
    """With the pool enabled the server starts a real supervisor; its
    counters flow through the `pool` Source gauges live, and /status
    surfaces the full poolActivity block (live set, counters, last
    decision)."""
    import urllib.request

    from spark_tpu.server import SQLServer
    ms = spark.metricsSystem
    prev_wh = spark.conf.get("spark.sql.warehouse.dir")
    spark.conf.set("spark.sql.warehouse.dir", str(tmp_path / "wh"))
    spark.conf.set(C.SERVER_POOL_ENABLED.key, "true")
    spark.conf.set(C.SERVER_POOL_POLL.key, "0.05")
    srv = None
    try:
        srv = SQLServer(spark, port=0).start()
        sup = srv._pool_supervisor
        assert sup is not None
        deadline = time.time() + 10
        while sup._last_decision is None and time.time() < deadline:
            time.sleep(0.02)                  # first reconcile tick
        # an idle server: the reconcile loop holds the pool at zero
        snap = ms.snapshots()["pool"]
        assert snap["pool_live"] == 0 and snap["workers_spawned"] == 0
        # counters flow through the gauges with no re-registration
        sup.counters["workers_spawned"] = 3
        sup.counters["workers_reaped"] = 2
        sup.counters["spawn_failures"] = 1
        snap = ms.snapshots()["pool"]
        assert snap["workers_spawned"] == 3
        assert snap["workers_reaped"] == 2
        assert snap["spawn_failures"] == 1
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/status", timeout=30) as r:
            st = json.loads(r.read())
        pa = st["poolActivity"]
        assert pa["live"] == 0 and pa["workers"] == []
        assert pa["counters"]["workers_spawned"] == 3
        assert "lastDecision" in pa           # the loop has ticked
        assert pa["lastDecision"]["action"] == "hold"
        # the admission stats carry the non-consuming demand view the
        # supervisor's signal samples from
        assert st["admission"]["demand"]["running"] == 0
    finally:
        if srv is not None:
            srv.stop()
        spark.conf.set("spark.sql.warehouse.dir", prev_wh)
        spark.conf_obj.unset(C.SERVER_POOL_ENABLED.key)
        spark.conf_obj.unset(C.SERVER_POOL_POLL.key)
        ms._sources = [s for s in ms._sources
                       if s.name not in ("serving", "pool")]


def test_run_plane_gauges_exported(spark):
    """ISSUE 20 observability: run-plane activity rides the compile
    Source — stages entered compressed, dense rows the planes stood in
    for, overflow fallbacks, and in-trace expansions all live gauges
    that move when an eligible run leaf crosses the stage boundary."""
    import spark_tpu.types as T
    from spark_tpu.columnar import ColumnBatch, ColumnVector, RunColumnVector
    from spark_tpu.sql import logical as L
    from spark_tpu.sql.dataframe import DataFrame
    ms = spark.metricsSystem
    before = ms.report()["compile"]
    for key in ("run_plane_stages", "run_plane_rows",
                "run_plane_overflows", "run_plane_expansions"):
        assert key in before, key
    s = spark.newSession()
    s.conf.set("spark.tpu.mesh.shards", "1")
    heads = np.arange(16, dtype=np.int64)
    rv = RunColumnVector(heads, np.full(16, 32, np.int64), T.int64)
    vv = ColumnVector(np.arange(512, dtype=np.int64), T.int64)
    b = ColumnBatch(["ts", "v"], [rv, vv], None, 512)
    DataFrame(s, L.LocalRelation(b)).createOrReplaceTempView("obs_rp")
    got = s.sql("SELECT count(*) AS c, sum(ts) AS st FROM obs_rp "
                "WHERE ts < 9").collect()
    dense = np.repeat(heads, 32)
    assert got[0]["c"] == int((dense < 9).sum())
    assert got[0]["st"] == int(dense[dense < 9].sum())
    after = ms.report()["compile"]
    assert after["run_plane_stages"] > before["run_plane_stages"]
    assert after["run_plane_rows"] >= before["run_plane_rows"] + 512
    assert after["run_plane_overflows"] >= before["run_plane_overflows"]
    # the eligible filter+agg stage never expanded its plane
    assert after["run_plane_expansions"] == before["run_plane_expansions"]


def test_run_plane_activity_in_status(spark, tmp_path):
    """/status runActivity carries the plane gauges next to the run-code
    gauges, diffed against the shuffle service's birth snapshot."""
    import urllib.request

    from spark_tpu import columnar as _col
    from spark_tpu.server import SQLServer
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    srv = None
    try:
        svc = spark.enableHostShuffle(str(tmp_path), process_id=0,
                                      n_processes=1, timeout_s=5.0)
        srv = SQLServer(spark, port=0).start()

        def status():
            with urllib.request.urlopen(
                    f"http://{srv.host}:{srv.port}/status",
                    timeout=30) as r:
                return json.loads(r.read())

        _col.bump_plane_stage()
        _col.bump_plane_rows(4096)
        _col.bump_plane_overflow()
        st = status()
        got = st["runActivity"]["default"]
        assert got["run_plane_stages"] >= 1
        assert got["run_plane_rows"] >= 4096
        assert got["run_plane_overflows"] >= 1
        # and the shuffle Source mirrors the same diffed gauges
        snap = ms.snapshots()["shuffle"]
        assert snap["run_plane_stages"] >= 1
        assert snap["run_plane_rows"] >= 4096
    finally:
        if srv is not None:
            srv.stop()
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]
