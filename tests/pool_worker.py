"""Worker for the elastic-pool chaos scenarios (not a test module —
launched as a subprocess by test_pool.py and ``bin/chaos --pool``).

argv: <process_id> <n_processes> <shared_root> <mode> [timeout_s]

mode "reap" — scale-down safety mid-fetch (2 processes, the
    ``bs-zero`` join with the retry budget at ZERO):
    pid 1 runs the exchange with a ``drop`` fault on its shipped jR
    block, and the moment its LAST manifest (the ``-gather`` round)
    lands it is cooperatively REAPED: it stops beating (the beat file
    stays behind and goes stale — a reaped worker looks exactly like a
    dead one to the survivor's barrier), hands its block-service lease
    to the pool supervisor (``handoff_lease``) and releases its own,
    then exits 0 printing ``[p1] OK``.  No drain barrier, no goodbye
    round.
    pid 0 must land the EXACT oracle purely by adopting the reaped
    peer's registered blocks: asserts ``stage_retries == 0``,
    ``epoch == 0`` (zero re-executed map tasks — any recovery attempt
    would blow the zero budget), nonzero adoption counters, AND that
    the reaped worker's lease still answers fresh through the heir
    chain — the scale-down-safety invariant (INVARIANTS.md): sealed
    output must stay adoptable before the lease may expire.

mode "spawn-fail" — exec failure converges the pool BELOW target,
    structured, never a hang (1 process): a real
    ``WorkerPoolSupervisor`` with ``FaultInjector().attach_pool`` armed
    from SPARK_TPU_FAULT_PLAN (``spawn_exec_error(after_spawns=1)``).
    Demand wants 2 workers; the second exec raises; the pool settles at
    1 live worker, counts ``spawn_failures`` on every retry tick, and
    the one real worker still serves a spooled statement
    oracle-exactly.  Scale-down then reaps it through hysteresis.

mode "scaleup" — scale-up mid-standing-query is invisible to the
    stream (1 process): a windowed-aggregate standing query processes
    two micro-batches, the pool then spawns a REAL worker (which
    serves a statement to prove it is live), the stream processes two
    more batches over the widened world, and the sink must be
    BYTE-identical to an uninterrupted no-pool oracle lifetime.

Any partial result prints ``[p<pid>] PARTIAL`` and exits 1 — the
launcher greps for it; it must never appear.
"""

import glob
import os
import sys
import time

pid = int(sys.argv[1])
n = int(sys.argv[2])
root = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "reap"
timeout_s = float(sys.argv[5]) if len(sys.argv) > 5 else 20.0

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from spark_tpu import config as C  # noqa: E402
from spark_tpu.parallel.cluster import HeartbeatMonitor  # noqa: E402
from spark_tpu.parallel.faults import FaultInjector  # noqa: E402
from spark_tpu.serving.admission import DemandSignal  # noqa: E402
from spark_tpu.serving.pool import (  # noqa: E402
    SUPERVISOR_OWNER, WorkerPoolSupervisor)
from spark_tpu.sql.session import SparkSession  # noqa: E402


# ---------------------------------------------------------------------------
# mode "reap": the bs-zero join with a cooperative scale-down victim
# ---------------------------------------------------------------------------

def run_reap():
    from spark_tpu.parallel.hostshuffle import ExchangeFetchFailed

    rng = np.random.default_rng(7)
    N, M = 900, 600
    f_sk = rng.integers(0, 40, N).astype(np.int64)
    f_price = rng.integers(1, 200, N).astype(np.int64)
    k2 = (rng.integers(0, 20, M) * 2).astype(np.int64)
    b2 = rng.integers(1, 100, M).astype(np.int64)
    mine = slice(pid, None, n)

    session = SparkSession.builder.appName(f"pool-{pid}").getOrCreate()

    wr = session.newSession()
    wr.conf.set(C.MESH_SHARDS.key, "1")
    fact_dir = os.path.join(root, "leaves", f"fact-p{pid}")
    fact2_dir = os.path.join(root, "leaves", f"fact2-p{pid}")
    wr.createDataFrame({"sk": f_sk[mine], "price": f_price[mine]}) \
        .write.parquet(fact_dir)
    wr.createDataFrame({"k2": k2[mine], "bonus": b2[mine]}) \
        .write.parquet(fact2_dir)

    xs = session.newSession()
    xs.conf.set(C.MESH_SHARDS.key, "1")
    xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "2048")
    xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
    xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
    xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
    xs.conf.set("spark.tpu.cluster.heartbeatIntervalMs", "100")
    xs.conf.set("spark.tpu.cluster.heartbeatTimeoutMs", "600")
    xs.conf.set(C.BLOCKSERVER_ENABLED.key, "true")
    # the zero-re-execution proof: ANY recovery attempt would blow the
    # zero budget and fail the query, so an oracle-exact OK can only
    # come from adopting the reaped peer's registered output
    xs.conf.set(C.RECOVERY_MAX_STAGE_RETRIES.key, "0")
    hb = HeartbeatMonitor(os.path.join(root, "beats"),
                          host_id=f"host-{pid}", conf=xs.conf_obj)
    hb.start()
    svc = xs.enableHostShuffle(root, process_id=pid, n_processes=n,
                               timeout_s=timeout_s, heartbeat=hb)
    FaultInjector().attach(svc)      # drop rule from SPARK_TPU_FAULT_PLAN

    if pid == 1:
        # arm the cooperative reap: the moment the LAST manifest (the
        # -gather round) lands, this worker is scaled down — it stops
        # beating (the stale beat, not a goodbye, is what the survivor
        # sees), hands its lease to the pool supervisor so its sealed
        # registered output stays adoptable, and leaves.  Wrapping BOTH
        # commit and publish_manifest covers whichever path publishes
        # the trigger round; the injector's wrappers stay underneath.
        store = svc.blockclient.store
        orig_commit = svc.commit
        orig_publish = svc.publish_manifest

        def _maybe_reap(exchange):
            if not exchange.endswith("-gather"):
                return
            hb.stop()                     # beat file STAYS — goes stale
            store.handoff_lease(f"host-{pid}", SUPERVISOR_OWNER)
            store.release_lease(f"host-{pid}")
            print(f"[p{pid}] OK reaped at {exchange} "
                  f"lease->{SUPERVISOR_OWNER}", flush=True)
            os._exit(0)

        def commit(exchange, extra=None):
            orig_commit(exchange, extra=extra)
            _maybe_reap(exchange)

        def publish_manifest(exchange, payload=None):
            out = orig_publish(exchange, payload)
            _maybe_reap(exchange)
            return out

        svc.commit = commit
        svc.publish_manifest = publish_manifest

    xs.read.parquet(fact_dir).createOrReplaceTempView("fact")
    xs.read.parquet(fact2_dir).createOrReplaceTempView("fact2")

    oracle = session.newSession()
    oracle.conf.set(C.MESH_SHARDS.key, "1")
    oracle.createDataFrame({"sk": f_sk, "price": f_price}) \
        .createOrReplaceTempView("fact")
    oracle.createDataFrame({"k2": k2, "bonus": b2}) \
        .createOrReplaceTempView("fact2")

    SQL = ("SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
           "JOIN fact2 ON sk = k2 GROUP BY sk ORDER BY sk")
    exp = [tuple(r) for r in oracle.sql(SQL).collect()]

    t0 = time.time()
    try:
        got = [tuple(r) for r in xs.sql(SQL).collect()]
    except (ExchangeFetchFailed, TimeoutError) as e:
        lost = sorted(getattr(e, "lost_hosts", []) or [])
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} {lost}",
              flush=True)
        os._exit(1)

    if got != exp:
        print(f"[p{pid}] PARTIAL got={len(got)} exp={len(exp)}",
              flush=True)
        os._exit(1)
    gauges = svc.metrics_source().snapshot()
    # zero re-executed map tasks: the recovery machinery never armed —
    # the reaped worker's output came out of block-service custody
    assert svc.counters["stage_retries"] == 0, svc.counters
    assert gauges["epoch"] == 0, gauges
    assert svc.counters["blocks_adopted"] >= 1, svc.counters
    assert svc.counters["blockserver_fallback_reads"] >= 1, svc.counters
    # scale-down safety: the reaped worker's lease must STILL answer
    # fresh — its own lease file is gone, but the heir sidecar chains
    # to the supervisor lease the handoff touched
    store = svc.blockclient.store
    assert store.lease_fresh("host-1", time.time()), \
        "reaped worker's lease went cold before adoption was safe"
    print(f"[p{pid}] OK {len(got)} retries=0 "
          f"adopted={svc.counters['blocks_adopted']}b "
          f"fallback={svc.counters['blockserver_fallback_reads']} "
          f"heir-lease=fresh", flush=True)
    os._exit(0)


# ---------------------------------------------------------------------------
# shared pool scaffolding for the supervisor modes
# ---------------------------------------------------------------------------

def _pool_session():
    """A session whose warehouse lives under the shared root, with one
    persistent table pool workers reach through the filesystem
    catalog."""
    wh = os.path.join(root, "warehouse")
    session = SparkSession.builder.appName(f"pool-{pid}") \
        .config("spark.sql.warehouse.dir", wh).getOrCreate()
    session.conf.set("spark.sql.warehouse.dir", wh)
    df = session.createDataFrame(
        [(1, "a", 10), (2, "b", 20), (3, "c", 30)], ["id", "name", "v"])
    df.write.saveAsTable("pool_t")
    return session, wh


ORACLE_SQL = "SELECT id, name, v FROM pool_t ORDER BY id"
ORACLE_ROWS = [[1, "a", 10], [2, "b", 20], [3, "c", 30]]


def _make_supervisor(session, wh, demand_box):
    conf = session.conf_obj
    conf.set(C.SERVER_POOL_MAX_WORKERS.key, "4")
    conf.set(C.SERVER_POOL_STATEMENTS_PER_WORKER.key, "2")
    conf.set(C.SERVER_POOL_SCALE_DOWN_ROUNDS.key, "2")
    conf.set(C.SERVER_POOL_COOLDOWN.key, "0.0")
    conf.set(C.SERVER_POOL_POLL.key, "0.1")
    sup = WorkerPoolSupervisor(
        os.path.join(root, "_pool"), conf, lambda: demand_box[0],
        warehouse=wh)
    sup.start(reconcile=False)        # chaos drives tick() itself
    return sup


def _serve_one(sup, deadline):
    """One statement through the spool against the live worker; retried
    because a just-spawned worker needs import+session time."""
    while time.monotonic() < deadline:
        res = sup.execute(ORACLE_SQL, timeout_s=15.0)
        if res is not None:
            assert res["rows"] == ORACLE_ROWS, res
            assert res.get("pooled") is True, res
            return res
        time.sleep(0.2)
    print(f"[p{pid}] FAILED pool never served a statement", flush=True)
    os._exit(1)


# ---------------------------------------------------------------------------
# mode "spawn-fail": exec error converges BELOW target, structured
# ---------------------------------------------------------------------------

def run_spawn_fail():
    deadline = time.monotonic() + 3 * timeout_s
    session, wh = _pool_session()
    demand = [DemandSignal(queued=4)]        # wants ceil(4/2) = 2 workers
    sup = _make_supervisor(session, wh, demand)
    FaultInjector().attach_pool(sup)  # plan from SPARK_TPU_FAULT_PLAN

    d = sup.tick()
    assert d.action == "up" and d.target == 2, d
    assert sup.counters["spawn_failures"] >= 1, sup.counters
    assert sup.live == 1 < d.target, (sup.live, d)
    # the pool keeps converging BELOW target on every retry tick —
    # counted, structured, never a hang
    sup.tick()
    assert sup.counters["spawn_failures"] >= 2, sup.counters
    assert sup.live == 1, sup.live

    _serve_one(sup, deadline)         # the one real worker still serves

    demand[0] = DemandSignal()        # idle: hysteresis then reap
    while sup.live > 0:
        if time.monotonic() > deadline:
            print(f"[p{pid}] FAILED reap never converged", flush=True)
            os._exit(1)
        sup.tick()
        time.sleep(0.05)
    assert sup.counters["workers_reaped"] >= 1, sup.counters
    c = dict(sup.counters)
    sup.stop()
    print(f"[p{pid}] OK spawn_failures={c['spawn_failures']} "
          f"spawned={c['workers_spawned']} reaped={c['workers_reaped']} "
          f"served={c['pool_statements_served']}", flush=True)
    os._exit(0)


# ---------------------------------------------------------------------------
# mode "scaleup": pool growth mid-standing-query is invisible downstream
# ---------------------------------------------------------------------------

def run_scaleup():
    from spark_tpu import types as T
    from spark_tpu.sql import functions as F
    from spark_tpu.sql.dataframe import DataFrame
    from spark_tpu.streaming.core import (
        FileSink, FileStreamSource, StreamExecution, StreamingRelation)

    deadline = time.monotonic() + 3 * timeout_s

    def sec(x):
        return int(x * 1_000_000)

    SCHEMA = T.StructType([
        T.StructField("ts", T.timestamp),
        T.StructField("k", T.string),
        T.StructField("v", T.int64),
    ])
    FEEDS = [
        [(sec(1), "a", 1), (sec(9), "b", 2)],
        [(sec(20), "a", 4), (sec(21), "b", 1)],
        [(sec(35), "c", 8), (sec(35), "c", 8)],
        [(sec(50), "a", 3), (sec(51), "d", 9)],
    ]
    in_dir = os.path.join(root, "in")
    os.makedirs(in_dir, exist_ok=True)

    session, wh = _pool_session()

    def feed(i):
        rows = FEEDS[i]
        session.createDataFrame({
            "ts": np.array([r[0] for r in rows], "datetime64[us]"),
            "k": [r[1] for r in rows],
            "v": np.array([r[2] for r in rows], np.int64),
        }).write.parquet(os.path.join(in_dir, f"f{i}"))

    def lifetime(ckpt, out):
        src = FileStreamSource("parquet", in_dir, SCHEMA,
                               {"maxfilespertrigger": "1"})
        df = (DataFrame(session, StreamingRelation(src))
              .withWatermark("ts", "5 seconds")
              .groupBy(F.window("ts", "10 seconds").alias("w"))
              .agg(F.sum("v").alias("s")))
        ex = StreamExecution(session, df._plan, FileSink("json", out, {}),
                             "append", ckpt, 0.1, None)
        ex.process_all_available()
        return ex

    def sink_files(out):
        return {os.path.basename(p): open(p, "rb").read()
                for p in sorted(glob.glob(os.path.join(out, "part-*")))}

    ckpt, out = os.path.join(root, "ckpt"), os.path.join(root, "out")

    # two micro-batches with the pool EMPTY
    feed(0)
    feed(1)
    lifetime(ckpt, out)

    # burst: the pool scales up mid-standing-query — a REAL worker
    # spawns and proves itself by serving a statement
    demand = [DemandSignal(queued=2)]
    sup = _make_supervisor(session, wh, demand)
    d = sup.tick()
    assert d.action == "up", d
    assert sup.counters["workers_spawned"] >= 1, sup.counters
    _serve_one(sup, deadline)

    # the NEXT micro-batches plan over the widened world
    feed(2)
    feed(3)
    lifetime(ckpt, out)
    got = sink_files(out)

    # uninterrupted no-pool oracle over the same feeds
    lifetime(os.path.join(root, "oracle_ckpt"),
             os.path.join(root, "oracle_out"))
    exp = sink_files(os.path.join(root, "oracle_out"))
    if got != exp or not exp:
        print(f"[p{pid}] PARTIAL got={sorted(got)} exp={sorted(exp)}",
              flush=True)
        os._exit(1)

    demand[0] = DemandSignal()
    while sup.live > 0 and time.monotonic() < deadline:
        sup.tick()
        time.sleep(0.05)
    c = dict(sup.counters)
    sup.stop()
    print(f"[p{pid}] OK {len(got)} spawned={c['workers_spawned']} "
          f"reaped={c['workers_reaped']} "
          f"served={c['pool_statements_served']}", flush=True)
    os._exit(0)


if mode == "reap":
    run_reap()
elif mode == "spawn-fail":
    run_spawn_fail()
elif mode == "scaleup":
    run_scaleup()
else:
    print(f"[p{pid}] FAILED unknown mode {mode!r}", flush=True)
    os._exit(2)
