"""MXU (matmul-based) grouped aggregation vs the sort-based oracle.

The device fast path (`kernels._mxu_grouped_aggregate`) must agree bit-for-
bit with the numpy sort-based path on integer sums (including two's-
complement wraparound, NULL keys, NULL values) and pick its fallback
correctly when key ranges exceed the bucket capacity.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from spark_tpu import types as T
from spark_tpu.aggregates import Avg, Count, CountStar, Sum
from spark_tpu.columnar import ColumnBatch
from spark_tpu.expressions import Col
from spark_tpu.kernels import (
    _mxu_applicable, _sorted_grouped_aggregate, compact, grouped_aggregate,
)


def run_both(data: dict, keys, aggs, valid=None, bucket_cap=4096):
    batch = ColumnBatch.from_arrays(data)
    if valid is not None:
        for name, v in valid.items():
            i = batch.names.index(name)
            vec = batch.vectors[i]
            v = np.asarray(v, bool)
            padded = np.zeros(batch.capacity, bool)
            padded[:len(v)] = v
            batch.vectors[i] = type(vec)(vec.data, vec.dtype, padded,
                                         vec.dictionary)
    key_exprs = [Col(k) for k in keys]
    jx = grouped_aggregate(jnp, batch.to_device(), key_exprs, aggs,
                           bucket_cap=bucket_cap)
    ref = _sorted_grouped_aggregate(np, batch, key_exprs, aggs)
    return compact(jnp, jx), compact(np, ref)


def as_rows(cb):
    n = int(np.asarray(cb.num_rows()))
    cols = []
    for vec in cb.vectors:
        data = np.asarray(vec.data)[:n]
        if vec.dictionary is not None:
            data = np.array([vec.dictionary[c] if c >= 0 else None
                             for c in data], object)
        if vec.valid is not None:
            v = np.asarray(vec.valid)[:n]
            data = np.array([d if ok else None for d, ok in zip(data, v)],
                            object)
        cols.append(data)
    rows = sorted(zip(*[c.tolist() for c in cols]),
                  key=lambda r: tuple(str(x) for x in r))
    return rows


def check(data, keys, aggs, valid=None, bucket_cap=4096):
    got, want = run_both(data, keys, aggs, valid, bucket_cap)
    assert as_rows(got) == as_rows(want)


def test_basic_sum_count():
    rng = np.random.default_rng(1)
    check({"k": rng.integers(0, 50, 1000).astype(np.int64),
           "v": rng.integers(-100, 100, 1000).astype(np.int64)},
          ["k"], [(Sum(Col("v")), "s"), (CountStar(), "c")])


def test_applicability():
    schema = T.StructType([T.StructField("k", T.int64),
                           T.StructField("f", T.float64)])
    assert _mxu_applicable(schema, [Col("k")], [(Sum(Col("k")), "s")])
    # float value -> not applicable
    assert not _mxu_applicable(schema, [Col("k")], [(Sum(Col("f")), "s")])
    # float key -> not applicable
    assert not _mxu_applicable(schema, [Col("f")], [(CountStar(), "c")])


def test_fallback_when_range_too_big():
    rng = np.random.default_rng(2)
    # key range 10^12 >> 4096 buckets: cond must take the sorted branch
    check({"k": (rng.integers(0, 50, 512) * 20_000_000_000).astype(np.int64),
           "v": rng.integers(0, 9, 512).astype(np.int64)},
          ["k"], [(Sum(Col("v")), "s"), (Count(Col("v")), "c")])


def test_multi_key_mixed_radix():
    rng = np.random.default_rng(3)
    check({"a": rng.integers(-3, 4, 2000).astype(np.int64),
           "b": rng.integers(100, 140, 2000).astype(np.int32),
           "v": rng.integers(-1000, 1000, 2000).astype(np.int64)},
          ["a", "b"], [(Sum(Col("v")), "s"), (CountStar(), "c"),
                       (Avg(Col("v")), "m")])


def test_null_keys_and_values():
    rng = np.random.default_rng(4)
    n = 500
    check({"k": rng.integers(0, 8, n).astype(np.int64),
           "v": rng.integers(0, 100, n).astype(np.int64)},
          ["k"], [(Sum(Col("v")), "s"), (Count(Col("v")), "c"),
                  (CountStar(), "n")],
          valid={"k": rng.random(n) > 0.2, "v": rng.random(n) > 0.3})


def test_int64_wraparound_exact():
    # sums overflow int64: both paths must wrap identically (Java long)
    big = np.int64(1 << 62)
    check({"k": np.array([0, 0, 0, 1], np.int64),
           "v": np.array([big, big, big, 7], np.int64)},
          ["k"], [(Sum(Col("v")), "s")])


def test_bool_and_small_int_keys():
    rng = np.random.default_rng(5)
    check({"k": rng.integers(0, 2, 300).astype(bool),
           "j": rng.integers(-128, 127, 300).astype(np.int8),
           "v": rng.integers(0, 5, 300).astype(np.int32)},
          ["k", "j"], [(Sum(Col("v")), "s")])


def test_string_dictionary_keys():
    rng = np.random.default_rng(6)
    words = np.array(["apple", "pear", "plum", "fig"])
    check({"k": words[rng.integers(0, 4, 400)].tolist(),
           "v": rng.integers(0, 50, 400).astype(np.int64)},
          ["k"], [(Sum(Col("v")), "s"), (CountStar(), "c")])


def test_sum_of_bools_and_count_star_only():
    rng = np.random.default_rng(7)
    check({"k": rng.integers(0, 3, 256).astype(np.int64),
           "b": rng.integers(0, 2, 256).astype(bool)},
          ["k"], [(Sum(Col("b")), "s"), (CountStar(), "c")])


def test_tiny_batch_and_single_group():
    check({"k": np.array([5], np.int64), "v": np.array([-9], np.int64)},
          ["k"], [(Sum(Col("v")), "s")])
    check({"k": np.zeros(7, np.int64), "v": np.arange(7, dtype=np.int64)},
          ["k"], [(Sum(Col("v")), "s"), (Avg(Col("v")), "m")])


def test_huge_key_span_overflow_safe():
    # span >= 2^63: int64 range arithmetic wraps; the f64 fit check must
    # still route to the sorted fallback (code-review regression)
    check({"k": np.array([-(1 << 62), 1 << 62, -(1 << 62), 1 << 62], np.int64),
           "v": np.array([1, 10, 2, 20], np.int64)},
          ["k"], [(Sum(Col("v")), "s")])
    check({"k": np.array([np.iinfo(np.int64).min, np.iinfo(np.int64).max],
                         np.int64),
           "v": np.array([3, 4], np.int64)},
          ["k"], [(Sum(Col("v")), "s")])


def test_small_bucket_cap_forces_fallback():
    rng = np.random.default_rng(8)
    check({"k": rng.integers(0, 1000, 4096).astype(np.int64),
           "v": rng.integers(0, 10, 4096).astype(np.int64)},
          ["k"], [(Sum(Col("v")), "s")], bucket_cap=64)
