"""Persistent catalog: databases, CREATE TABLE USING / CTAS / INSERT,
saveAsTable, filesystem-backed metadata (SessionCatalog + InMemoryCatalog
analogs)."""

import os

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.expressions import AnalysisException


@pytest.fixture()
def wh(spark, tmp_path):
    old = spark.conf.get(C.WAREHOUSE_DIR)
    spark.conf.set(C.WAREHOUSE_DIR.key, str(tmp_path / "wh"))
    yield spark
    spark.catalog.current_database = "default"
    spark.conf.set(C.WAREHOUSE_DIR.key, old)


def rows(df):
    return sorted(tuple(r) for r in df.collect())


def test_ctas_roundtrip(wh):
    wh.range(6).createOrReplaceTempView("src")
    wh.sql("CREATE TABLE t1 USING parquet AS SELECT id, id * 2 AS d FROM src")
    assert rows(wh.sql("SELECT * FROM t1")) == [(i, 2 * i) for i in range(6)]
    # survives in a fresh session sharing the warehouse
    from spark_tpu.sql.session import SparkSession
    s2 = SparkSession.builder.getOrCreate()
    # (builder may return the same session; simulate cold catalog instead)
    wh.catalog._views.pop("t1", None)
    assert rows(wh.sql("SELECT d FROM t1")) == [(2 * i,) for i in range(6)]
    wh.sql("DROP TABLE t1")
    with pytest.raises(AnalysisException):
        wh.sql("SELECT * FROM t1").collect()


def test_databases(wh):
    wh.sql("CREATE DATABASE db1")
    assert "db1" in wh.catalog.list_databases()
    wh.range(3).createOrReplaceTempView("src")
    wh.sql("CREATE TABLE db1.t USING parquet AS SELECT id FROM src")
    assert rows(wh.sql("SELECT * FROM db1.t")) == [(0,), (1,), (2,)]
    wh.sql("USE db1")
    assert rows(wh.sql("SELECT * FROM t")) == [(0,), (1,), (2,)]
    wh.sql("USE default")
    wh.sql("DROP DATABASE db1")
    assert "db1" not in wh.catalog.list_databases()
    with pytest.raises(AnalysisException):
        wh.sql("CREATE DATABASE default")
    wh.sql("CREATE DATABASE IF NOT EXISTS default")


def test_empty_table_then_insert(wh):
    wh.sql("CREATE TABLE et (a bigint, b string) USING parquet")
    assert rows(wh.sql("SELECT * FROM et")) == []
    wh.range(3).createOrReplaceTempView("src3")
    wh.sql("INSERT INTO et SELECT id AS a, 'x' AS b FROM src3")
    assert rows(wh.sql("SELECT * FROM et")) == [
        (0, "x"), (1, "x"), (2, "x")]
    wh.sql("INSERT INTO et SELECT id AS a, 'y' AS b FROM src3")
    assert len(rows(wh.sql("SELECT * FROM et"))) == 6
    wh.sql("INSERT OVERWRITE et SELECT id AS a, 'z' AS b FROM src3")
    assert rows(wh.sql("SELECT b FROM et")) == [("z",)] * 3
    wh.sql("DROP TABLE et")


def test_save_as_table_and_show(wh):
    df = wh.createDataFrame(pd.DataFrame({
        "k": np.arange(4, dtype=np.int64), "v": ["a", "b", "c", "d"]}))
    df.write.saveAsTable("sat")
    assert rows(wh.read.table("sat")) == rows(df)
    shown = {tuple(r) for r in wh.sql("SHOW TABLES").collect()}
    assert ("sat", "false") in shown
    with pytest.raises(AnalysisException):
        df.write.saveAsTable("sat")          # errorifexists default
    df.write.mode("overwrite").saveAsTable("sat")
    wh.sql("DROP TABLE sat")


def test_insert_overwrite_self_reference(wh):
    """INSERT OVERWRITE t SELECT ... FROM t must read before clearing."""
    wh.range(3).createOrReplaceTempView("srcio")
    wh.sql("CREATE TABLE io USING parquet AS SELECT id FROM srcio")
    wh.sql("INSERT OVERWRITE io SELECT id + 10 FROM io")
    assert rows(wh.sql("SELECT * FROM io")) == [(10,), (11,), (12,)]
    # a failing overwrite query leaves the table intact
    with pytest.raises(AnalysisException):
        wh.sql("INSERT OVERWRITE io SELECT no_col FROM srcio")
    assert rows(wh.sql("SELECT * FROM io")) == [(10,), (11,), (12,)]
    # arity mismatch rejected before any write
    with pytest.raises(AnalysisException):
        wh.sql("INSERT INTO io SELECT id, id FROM srcio")
    wh.sql("DROP TABLE io")


def test_create_or_replace_table(wh):
    wh.range(2).createOrReplaceTempView("srccr")
    wh.sql("CREATE TABLE cr USING parquet AS SELECT id FROM srccr")
    wh.sql("CREATE OR REPLACE TABLE cr USING parquet "
           "AS SELECT id * 5 AS id FROM srccr")
    assert rows(wh.sql("SELECT * FROM cr")) == [(0,), (5,)]
    wh.sql("DROP TABLE cr")


def test_temp_view_can_shadow_table(wh):
    wh.range(2).createOrReplaceTempView("srctv")
    wh.sql("CREATE TABLE tv USING parquet AS SELECT id FROM srctv")
    wh.sql("CREATE TEMP VIEW tv AS SELECT 42 AS id")   # must not raise
    assert rows(wh.sql("SELECT * FROM tv")) == [(42,)]
    wh.catalog.dropTempView("tv")
    wh.sql("DROP TABLE tv")


def test_temp_view_shadows_table(wh):
    wh.range(2).createOrReplaceTempView("src")
    wh.sql("CREATE TABLE sh USING parquet AS SELECT id FROM src")
    wh.createDataFrame(pd.DataFrame({"id": [99]})) \
        .createOrReplaceTempView("sh")
    assert rows(wh.sql("SELECT * FROM sh")) == [(99,)]
    wh.sql("DROP TABLE sh")                  # drops the VIEW first
    assert rows(wh.sql("SELECT * FROM sh")) == [(0,), (1,)]
    wh.sql("DROP TABLE sh")
