"""Expression-breadth functions vs Python/datetime oracles.

Covers the date-arithmetic family (civil-calendar integer math), the
parameterized string transforms (dictionary rewrite contract), and the
math tail — in both the F.* and SQL registries.
"""
import datetime as dt
import hashlib
import math
import zlib

import numpy as np
import pytest

from spark_tpu.sql import functions as F
from spark_tpu.sql.session import SparkSession


@pytest.fixture(scope="module")
def spark():
    return SparkSession()


@pytest.fixture(scope="module")
def dates_df(spark):
    import pandas as pd
    days = pd.to_datetime([
        "1999-12-31", "2000-01-01", "2000-02-29", "2020-01-31",
        "2020-02-29", "2021-07-30", "1969-07-20", "2024-12-31",
    ])
    return spark.createDataFrame(pd.DataFrame({"d": days.date})), \
        [d.date() for d in days]


def _col(df, name):
    return [r[name] for r in df.collect()]


def test_date_add_sub_datediff(dates_df):
    df, days = dates_df
    out = df.select(F.date_add("d", 40).alias("a"),
                    F.date_sub("d", 40).alias("s"),
                    F.datediff("d", "d").alias("z"))
    got = out.collect()
    for r, d in zip(got, days):
        assert r["a"] == d + dt.timedelta(days=40)
        assert r["s"] == d - dt.timedelta(days=40)
        assert r["z"] == 0


def _add_months_py(d: dt.date, n: int) -> dt.date:
    y, m = divmod(d.year * 12 + (d.month - 1) + n, 12)
    m += 1
    last = [31, 29 if (y % 4 == 0 and y % 100 != 0) or y % 400 == 0 else 28,
            31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m - 1]
    return dt.date(y, m, min(d.day, last))


@pytest.mark.parametrize("n", [-25, -1, 0, 1, 11, 37])
def test_add_months(dates_df, n):
    df, days = dates_df
    got = _col(df.select(F.add_months("d", n).alias("x")), "x")
    assert got == [_add_months_py(d, n) for d in days]


def test_last_day_and_trunc(dates_df):
    df, days = dates_df
    got = df.select(F.last_day("d").alias("l"),
                    F.trunc("d", "month").alias("m"),
                    F.trunc("d", "year").alias("y"),
                    F.trunc("d", "quarter").alias("q")).collect()
    for r, d in zip(got, days):
        assert r["l"] == _add_months_py(d.replace(day=1), 1) \
            - dt.timedelta(days=1)
        assert r["m"] == d.replace(day=1)
        assert r["y"] == d.replace(month=1, day=1)
        assert r["q"] == d.replace(month=((d.month - 1) // 3) * 3 + 1, day=1)


def test_next_day(dates_df):
    df, days = dates_df
    got = _col(df.select(F.next_day("d", "Mon").alias("x")), "x")
    for g, d in zip(got, days):
        assert g > d and g.weekday() == 0 and (g - d).days <= 7


def test_months_between(spark):
    import pandas as pd
    df = spark.createDataFrame(pd.DataFrame({
        "a": pd.to_datetime(["2020-03-31", "2020-03-15", "2020-02-29"]).date,
        "b": pd.to_datetime(["2020-01-31", "2020-01-15", "2020-01-31"]).date,
    }))
    got = _col(df.select(F.months_between("a", "b").alias("x")), "x")
    # both month-ends -> integer; same day-of-month -> integer
    assert got[0] == 2.0
    assert got[1] == 2.0
    assert got[2] == 1.0


def test_unix_timestamp_roundtrip(spark):
    import pandas as pd
    ts = pd.to_datetime(["2020-01-01 12:34:56", "1969-12-31 23:59:59"])
    df = spark.createDataFrame(pd.DataFrame({"t": ts}))
    got = df.select(F.unix_timestamp("t").alias("u"),
                    F.from_unixtime(F.unix_timestamp("t")).alias("b")
                    ).collect()
    for r, t in zip(got, ts):
        assert r["u"] == int(t.timestamp())
        assert r["b"] == t.floor("s")


STRINGS = ["hello world", "", "Robert", "  pad  ", "café", "aaa-bbb-ccc"]


@pytest.fixture(scope="module")
def str_df(spark):
    import pandas as pd
    return spark.createDataFrame(pd.DataFrame({"s": STRINGS}))


@pytest.mark.parametrize("fn,oracle", [
    (lambda c: F.regexp_replace(c, r"[aeiou]", "_"),
     lambda s: __import__("re").sub(r"[aeiou]", "_", s)),
    (lambda c: F.regexp_extract(c, r"(\w+)-(\w+)", 2),
     lambda s: (lambda m: m.group(2) if m else "")(
         __import__("re").search(r"(\w+)-(\w+)", s))),
    (lambda c: F.lpad(c, 8, "*"), lambda s: s.rjust(8, "*")[:8]),
    (lambda c: F.rpad(c, 8, "*"), lambda s: s.ljust(8, "*")[:8]),
    (lambda c: F.translate(c, "lo", "01"),
     lambda s: s.translate(str.maketrans("lo", "01"))),
    (lambda c: F.repeat(c, 2), lambda s: s * 2),
    (lambda c: F.md5(c), lambda s: hashlib.md5(s.encode()).hexdigest()),
    (lambda c: F.sha1(c), lambda s: hashlib.sha1(s.encode()).hexdigest()),
    (lambda c: F.base64(c),
     lambda s: __import__("base64").b64encode(s.encode()).decode()),
    (lambda c: F.hex(c), lambda s: s.encode().hex().upper()),
])
def test_string_transforms(str_df, fn, oracle):
    got = _col(str_df.select(fn(F.col("s")).alias("x")), "x")
    assert got == [oracle(s) for s in STRINGS]


def test_string_to_int(str_df):
    got = str_df.select(F.instr("s", "l").alias("i"),
                        F.locate("l", "s", 4).alias("l"),
                        F.crc32("s").alias("c"),
                        F.levenshtein("s", "hello").alias("d")).collect()
    for r, s in zip(got, STRINGS):
        assert r["i"] == s.find("l") + 1
        assert r["l"] == s.find("l", 3) + 1
        assert r["c"] == zlib.crc32(s.encode()) & 0xFFFFFFFF
    assert got[0]["d"] == 6      # "hello world" vs "hello"
    assert got[1]["d"] == 5      # "" vs "hello"


def test_math_tail(spark):
    df = spark.createDataFrame({"x": np.array([0.5, -0.2, 3.0]),
                                "y": np.array([1.0, 2.0, -4.0])})
    got = df.select(F.hypot("x", "y").alias("h"),
                    F.atan2("x", "y").alias("a"),
                    F.log1p("x").alias("l"),
                    F.expm1("x").alias("e"),
                    F.cbrt("y").alias("c"),
                    F.rint("x").alias("r")).collect()
    for r, (x, y) in zip(got, [(0.5, 1.0), (-0.2, 2.0), (3.0, -4.0)]):
        assert math.isclose(r["h"], math.hypot(x, y))
        assert math.isclose(r["a"], math.atan2(x, y))
        assert math.isclose(r["l"], math.log1p(x))
        assert math.isclose(r["e"], math.expm1(x))
        assert math.isclose(r["c"], math.copysign(abs(y) ** (1 / 3), y))
        assert r["r"] == round(x)


def test_sql_registry_breadth(spark):
    r = spark.sql(
        "SELECT soundex('Robert') AS s, sha2('abc', 256) AS h, "
        "unbase64(base64('hi')) AS b, repeat('ab', 3) AS r, "
        "hypot(3.0, 4.0) AS hy, spark_partition_id() AS p").collect()[0]
    assert r["s"] == "R163"
    assert r["h"] == hashlib.sha256(b"abc").hexdigest()
    assert r["b"] == "hi"
    assert r["r"] == "ababab"
    assert r["hy"] == 5.0
    assert r["p"] == 0


def test_randn_distribution(spark):
    df = spark.range(0, 4000).select(F.randn(7).alias("g"))
    vals = np.array(_col(df, "g"))
    assert abs(vals.mean()) < 0.1
    assert 0.9 < vals.std() < 1.1


def test_dual_path_consistency(spark):
    """numpy-interpreted and jit lanes agree on the new expressions."""
    import pandas as pd
    df = spark.createDataFrame(pd.DataFrame({
        "d": pd.to_datetime(["2020-01-31", "2021-06-15"]).date,
        "s": ["alpha", "beta"]}))
    q = df.select(F.add_months("d", 13).alias("m"),
                  F.regexp_replace("s", "a", "@").alias("r"))
    rows = [(r["m"], r["r"]) for r in q.collect()]
    assert rows == [(dt.date(2021, 2, 28), "@lph@"),
                    (dt.date(2022, 7, 15), "bet@")]


def test_percentile_approx_and_median(spark):
    import pandas as pd
    df = spark.createDataFrame(pd.DataFrame({
        "k": [1] * 5 + [2] * 4,
        "v": [10, 20, 30, 40, 50, 7, 8, 9, 100]}))
    df.createOrReplaceTempView("pct_t")
    out = {r["k"]: (r["p50"], r["p90"]) for r in spark.sql(
        "SELECT k, percentile_approx(v, 0.5) p50, "
        "percentile_approx(v, 0.9) p90 FROM pct_t GROUP BY k").collect()}
    assert out[1] == (30, 40)      # floor(.9*4)=3 -> 4th smallest
    assert out[2] == (8, 9)
    m = spark.sql("SELECT median(v) m FROM pct_t").collect()[0]["m"]
    assert m == 20                 # 9 values, floor(.5*8)=4 -> 5th smallest
    # NULLs skipped; all-null group -> NULL
    from spark_tpu import types as T
    df2 = spark.createDataFrame(
        [(1, 5), (1, None), (2, None)],
        T.StructType([T.StructField("k", T.int64, False),
                      T.StructField("v", T.int64, True)]))
    from spark_tpu.sql import functions as F
    got = {r["k"]: r["p"] for r in df2.groupBy("k").agg(
        F.percentile_approx("v", 0.5).alias("p")).collect()}
    assert got == {1: 5, 2: None}
