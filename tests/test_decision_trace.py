"""The decision-trace golden divergence battery across REAL processes.

Spawns ``adaptive_worker.py`` in two modes:

* mode "trace" (2 procs tier-1, 3 procs slow): unperturbed parity —
  one full hash exchange and one range exchange with the decision-trace
  runtime check pinned on.  Every process must report oracle-identical
  rows, ``decision_trace_checks > 0`` and ZERO divergence; the row
  counts must agree across processes (byte-identical results — each
  worker already compares its rows tuple-for-tuple against the oracle).

* mode "skew-decision": one process's gathered view of the
  ``xq000001-plan`` stats round is perturbed by the ``skew_decision``
  fault kind while the on-disk manifests stay byte-identical — the
  classic silent replica-determinism violation.  The armed process must
  abort STRUCTURED via ``verify_decision_trace`` (property
  ``decision-trace-agreement``, naming the diverging exchange), never
  emit partial rows; the unarmed peer fails bounded at its data
  barrier.  Without the trace check this run would demote one process
  to broadcast while the other ships hash buckets — rows silently lost.
"""

import os
import re
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from spark_tpu.parallel.faults import (  # noqa: E402
    FAULT_PLAN_ENV, FaultPlan)

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "adaptive_worker.py")


def _spawn(tmp_path, n, mode, timeout_s, plans=None):
    root = str(tmp_path / "shuf")
    procs = []
    for pid in range(n):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop(FAULT_PLAN_ENV, None)
        build = (plans or {}).get(pid)
        if build is not None:
            env[FAULT_PLAN_ENV] = build().to_env()
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(n), root, mode,
             str(timeout_s)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env))
    return [p.communicate(timeout=420)[0] for p in procs], procs


def _run_trace_parity(tmp_path, n):
    outs, procs = _spawn(tmp_path, n, "trace", 45.0)
    rows = set()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        assert "PARTIAL" not in out, out
        m = re.search(rf"\[p{pid}\] TRACE-OK rows=(\d+) checks=(\d+) "
                      r"div=(\d+)", out)
        assert m, out
        rows.add(int(m.group(1)))
        assert int(m.group(2)) > 0, f"no decision-trace checks ran:\n{out}"
        assert int(m.group(3)) == 0, f"unexpected divergence:\n{out}"
    # every process produced the same (oracle-verified) result set
    assert len(rows) == 1, rows


def test_trace_parity_two_processes(tmp_path):
    _run_trace_parity(tmp_path, 2)


@pytest.mark.slow
def test_trace_parity_three_processes(tmp_path):
    _run_trace_parity(tmp_path, 3)


def test_skew_decision_divergence_aborts_structured(tmp_path):
    """The armed process must abort via the decision-trace check —
    naming the diverging exchange and decision — and NEVER produce
    partial rows; the peer fails bounded, not hanging."""
    outs, procs = _spawn(
        tmp_path, 2, "skew-decision", 8.0,
        plans={1: lambda: FaultPlan().skew_decision("xq000001-plan")})
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        assert "PARTIAL" not in out, out
        assert "TRACE-OK" not in out, out
    # armed process: structured divergence abort naming the round
    assert "[p1] FAILED-DIVERGED" in outs[1], outs[1]
    assert "prop=decision-trace-agreement" in outs[1], outs[1]
    assert "xq000001-plan" in outs[1], outs[1]
    assert "div=1" in outs[1], outs[1]
    # unarmed peer: bounded structured failure at its data barrier
    assert "[p0] FAILED" in outs[0], outs[0]
