"""Worker for the cross-process join parity and fault tests (not a test
module itself — launched as a subprocess by test_shuffled_join.py and
test_faults.py).

argv: <process_id> <n_processes> <shuffle_root> <mode> [timeout_s]

mode "parity": run a battery of equi-join plans (inner / left / semi,
two partitioned leaves, with and without a keyed Aggregate above, with a
deliberately SKEWED hot key) THREE ways — range-partitioned sort-merge
(``spark.tpu.crossproc.sortMergeJoin``), shuffled hash
(``spark.tpu.crossproc.shuffledJoin``), and the generic gather — and
assert every configuration matches a full-data single-process oracle
exactly.  Also asserts each run took the path it was supposed to
(``range_merge_joins`` / ``shuffled_joins`` / ``fast_path_aggs``
counters), that manifest coalescing merged sub-target fine partitions
(``partitions_coalesced``), and that the hot key actually forced a skew
split (``spans_split``).

mode "fault": arm a FaultInjector from SPARK_TPU_FAULT_PLAN and run ONE
shuffled-hash join (sortMergeJoin pinned off so the exchange ids are the
classic ``-jL``/``-jR``).  Prints ``OK <rows>`` when the exchange healed
(result must equal the oracle — never a partial join), or
``FAILED <elapsed> <lost>`` on a structured, bounded failure.

mode "fault-sample": same contract, but the query runs on the RANGE path
(sortMergeJoin on) so the plan can target the manifest-only
``-sample`` coordination round.

mode "spill": the full parity battery again, but with a tiny forced
``spark.tpu.shuffle.spillThresholdBytes`` and a capped host-memory
budget, so every join exchange stages its map output AND its fetched
blocks through the disk-spill path — spilled results must equal the
in-memory results must equal the oracle, spill gauges must be nonzero,
and the ledger's peak must stay under the budget.  Final line
``SPILL-OK ...``.

mode "spill-fault": forced-spill conf plus a ``disk_full`` FaultInjector
rule from SPARK_TPU_FAULT_PLAN: the spill write fails with ENOSPC, and
the query must fail BOUNDED with a structured ``HostMemoryError`` (the
peer fails bounded on its exchange timeout) — never partial results.

mode "ici": the full parity battery with the ICI device-exchange tier
ARMED (enabled, minBytes=0, tierOverride placing every pid in one
domain).  On CPU a cross-process device collective cannot exist
(single-process jax world), so every device attempt must degrade
STRUCTURED to the host tier — results byte-identical to the plain
parity battery, ``dcn_fallback_exchanges`` > 0, ``ici_exchanges`` == 0,
``tier_split_peers`` == n-1, and the decision-trace checks prove the
tier split itself agreed on every replica (divergence = 0).

mode "ici-fault": the ICI confs armed plus a FaultInjector plan from
SPARK_TPU_FAULT_PLAN aimed at the device tier (``ici_unavailable`` at
the attempt point, or ``die_mid_device_copy`` at the copy point); runs
ONE hash-lane join with the "fault" mode's contract — ``OK <rows>``
(oracle-exact) or ``FAILED`` (structured, bounded), never partial.

mode "grace": a host budget CAPPED BELOW the reducers' drained working
set, so fetching a joined shard raises ``HostMemoryPressure`` and the
join lanes must degrade into grace buckets (re-bucket the sink by join
key hash, join bucket-by-bucket under the budget) instead of aborting.
A battery of keyed-aggregate-above-join queries (inner / left / semi,
plus dictionary-coded string keys) runs on BOTH the range and hash
lanes and must equal the uncapped full-data oracle exactly; then a huge
advisory target forces the ELASTIC planner to narrow the reducer set
below the live set (``reducers_elastic``), still oracle-exact.  Asserts
nonzero ``grace_buckets_used`` / ``grace_spill_bytes`` and
``peak_host_bytes <= host_budget_bytes``.  Final line ``GRACE-OK``.

mode "grace-fault": the grace conf plus a ``disk_full`` rule aimed at
the ``<xid>-grace`` exchange: the grace SPILL hits ENOSPC mid-degrade,
and the query must abort bounded with a structured ``HostMemoryError``
whose detail names the failed grace spill — never partial results.

mode "runcodes": run-encoded vs raw wire parity on BOTH exchange lanes
(``spark.tpu.shuffle.wire.runCodes`` flipped per leg) over a
time-series-shaped workload — a sorted key in long runs, a
dictionary+RLE composed status column (codes are int32 runs) — under
the forced-spill conf, so encoded frames also stage through disk
without inflating (the spill-under-budget cell).  Every leg must equal
the full-data oracle exactly; the encoded legs must bump
``rle_columns_encoded`` / ``run_bytes_saved`` and fire the run-aware
operators (``run_aware_op_rows`` / ``runs_materialized``), the raw
legs must not encode.  Final line ``RUNCODES-OK ...``.
"""

import os
import sys
import time

pid = int(sys.argv[1])
n = int(sys.argv[2])
root = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "parity"
timeout_s = float(sys.argv[5]) if len(sys.argv) > 5 else 45.0

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from spark_tpu import columnar as _col  # noqa: E402
from spark_tpu import config as C  # noqa: E402
from spark_tpu.memory import HOST_BUDGET, HostMemoryError  # noqa: E402
from spark_tpu.parallel.faults import FaultInjector  # noqa: E402
from spark_tpu.parallel.hostshuffle import ExchangeFetchFailed  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402

# Both processes draw the SAME full dataset and keep a strided 1/n slice,
# so every process sees every key range (the worst case for a local join:
# without co-partitioning almost every match is cross-process).  Key 8 is
# a deliberately HOT key (~40% of fact rows): under the small advisory
# target below its span exceeds SKEW_FACTOR x median, so the range
# planner must SPLIT it across reducers (and still match the oracle).
rng = np.random.default_rng(7)
N, M = 900, 600
f_sk = rng.integers(0, 40, N).astype(np.int64)
f_sk[rng.random(N) < 0.4] = 8
f_price = rng.integers(1, 200, N).astype(np.int64)
f_g = np.array(["ash", "oak", "fir", "elm"])[f_sk % 4]
k2 = (rng.integers(0, 20, M) * 2).astype(np.int64)   # even keys only →
b2 = rng.integers(1, 100, M).astype(np.int64)        # LEFT join has misses
g2 = np.array(["ash", "oak", "fir", "pine"])[k2 % 4]  # dicts only overlap
d_sk = np.arange(0, 40, 3, dtype=np.int64)           # sparse dim for SEMI
d_year = (1998 + d_sk % 5).astype(np.int64)

mine = slice(pid, None, n)

session = SparkSession.builder.appName(f"sjoin-{pid}").getOrCreate()

xs = session.newSession()
xs.conf.set(C.MESH_SHARDS.key, "1")
if mode in ("spill", "spill-fault", "runcodes"):
    # a threshold far below any join side's bytes forces the map output
    # of EVERY join exchange (and, via the FetchSink's force rule, every
    # fetched block) through the spill files; the budget cap must be set
    # BEFORE enableHostShuffle (the ledger reads it at construction).
    # "runcodes" rides the same forced-spill conf so its whole battery
    # doubles as the spill-under-budget cell: encoded frames must stage
    # through disk WITHOUT inflating and still match the oracle.
    xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, "1024")
    xs.conf.set(HOST_BUDGET.key, str(32 << 20))
elif mode in ("grace", "grace-fault"):
    # same forced-spill staging, but the budget sits BELOW the bytes a
    # reducer drains for one join (each side lands ~3-5 KiB per process
    # here): the second side's drain must overflow the ledger and the
    # lanes must grace-degrade rather than abort.  Single buckets
    # (~1/32nd of a side, plus the whole hot key) still fit.
    xs.conf.set(C.SHUFFLE_SPILL_THRESHOLD.key, "1024")
    xs.conf.set(HOST_BUDGET.key, str(7 << 10))
svc = xs.enableHostShuffle(root, process_id=pid, n_processes=n,
                           timeout_s=timeout_s)
# small advisory target: the test tables are tiny, and with the 4 MiB
# default every fine partition would coalesce onto process 0 — a few KiB
# keeps BOTH processes joining while still exercising the coalescer (and
# makes the hot key's span split into several reducer shares)
xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "2048")
# strategy choice must be pinned per mode below — a tiny side slipping
# under the broadcast threshold would silently change the path under test
xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
# finer quantiles sharpen skew DETECTION: hot-key duplicates collapse
# into one span either way, but more fine spans shrink the median span
# the 5x-median test compares against (8/proc would leave the hot span
# just under threshold on this small table)
xs.conf.set(C.SHUFFLE_FINE_PARTITIONS.key, "32")
if mode in ("ici", "ici-fault"):
    # arm the device tier with every pid in ONE ICI domain and no byte
    # floor: every eligible exchange must ATTEMPT the device tier, and
    # on CPU every attempt must fold back onto the host tier structured
    xs.conf.set(C.SHUFFLE_ICI_ENABLED.key, "true")
    xs.conf.set(C.SHUFFLE_ICI_MIN_BYTES.key, "0")
    xs.conf.set(C.SHUFFLE_ICI_TIER_OVERRIDE.key,
                ",".join(str(p) for p in range(n)))
# tags has a UNIQUE word per row: each process's slice builds a fully
# DISJOINT dictionary, so the cross-process string min/max below can only
# be right if the exchange genuinely unifies the code spaces
t_words = np.array([f"row{i:04d}" for i in range(N)])

xs.createDataFrame({"sk": f_sk[mine], "price": f_price[mine],
                    "g": f_g[mine]}).createOrReplaceTempView("fact")
xs.createDataFrame({"k2": k2[mine], "bonus": b2[mine],
                    "g2": g2[mine]}).createOrReplaceTempView("fact2")
xs.createDataFrame({"sk2": f_sk[mine], "t": t_words[mine]}) \
    .createOrReplaceTempView("tags")
# dim is REPLICATED: every process holds the identical full table
xs.createDataFrame({"d_sk": d_sk, "year": d_year}) \
    .createOrReplaceTempView("dim")

oracle = session.newSession()
oracle.conf.set(C.MESH_SHARDS.key, "1")
oracle.createDataFrame({"sk": f_sk, "price": f_price, "g": f_g}) \
    .createOrReplaceTempView("fact")
oracle.createDataFrame({"k2": k2, "bonus": b2, "g2": g2}) \
    .createOrReplaceTempView("fact2")
oracle.createDataFrame({"sk2": f_sk, "t": t_words}) \
    .createOrReplaceTempView("tags")
oracle.createDataFrame({"d_sk": d_sk, "year": d_year}) \
    .createOrReplaceTempView("dim")

# (name, sql, expected counter per mode).  String keys ride the range
# exchange too: dictionaries are sorted (codes order like words), the
# sample round agrees on cut WORDS, and each process maps them into its
# local code space — so "range" mode takes the sort-merge path for
# string equi-keys exactly like numeric ones.
QUERIES = [
    ("inner-agg",
     "SELECT sk, count(*) AS c, sum(bonus) AS sb FROM fact "
     "JOIN fact2 ON sk = k2 GROUP BY sk ORDER BY sk",
     {"range": "range_merge_joins", "hash": "shuffled_joins"}),
    ("inner-rows",
     "SELECT sk, price, bonus FROM fact JOIN fact2 ON sk = k2 "
     "WHERE bonus > 40 ORDER BY sk, price, bonus",
     {"range": "range_merge_joins", "hash": "shuffled_joins"}),
    ("left-agg",
     "SELECT sk, count(bonus) AS cb, count(*) AS c FROM fact "
     "LEFT JOIN fact2 ON sk = k2 GROUP BY sk ORDER BY sk",
     {"range": "range_merge_joins", "hash": "shuffled_joins"}),
    ("string-key-agg",
     "SELECT g, count(*) AS c, sum(bonus) AS sb FROM fact "
     "JOIN fact2 ON g = g2 GROUP BY g ORDER BY g",
     {"range": "range_merge_joins", "hash": "shuffled_joins"}),
    # lifted string aggregates: min/max/first on a dictionary column whose
    # per-process dictionaries are fully DISJOINT — correct answers require
    # the receiver-side code-space unification, in every exchange mode
    ("string-minmax-fast",
     "SELECT sk2, min(t) AS tlo, max(t) AS thi, count(*) AS c FROM tags "
     "GROUP BY sk2 ORDER BY sk2",
     {"range": "fast_path_aggs", "hash": "fast_path_aggs",
      "gather": "fast_path_aggs"}),
    ("semi-rows",
     "SELECT sk, price FROM fact LEFT SEMI JOIN fact2 ON sk = k2 "
     "ORDER BY sk, price",
     {"range": "range_merge_joins", "hash": "shuffled_joins"}),
    # widened fast-path guard: LEFT SEMI against a REPLICATED build side
    # under a keyed Aggregate stays on the single-exchange fast path in
    # EVERY mode — exchange strategy flags never reach it
    ("semi-replicated-fast",
     "SELECT sk, count(*) AS c FROM fact LEFT SEMI JOIN dim ON sk = d_sk "
     "GROUP BY sk ORDER BY sk",
     {"range": "fast_path_aggs", "hash": "fast_path_aggs",
      "gather": "fast_path_aggs"}),
]

#: mode → (sortMergeJoin, shuffledJoin) conf values
MODES = [("range", "true", "true"),
         ("hash", "false", "true"),
         ("gather", "false", "false")]


def set_mode(m):
    for name, smj, sh in MODES:
        if name == m:
            xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, smj)
            xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, sh)
            return
    raise ValueError(m)


def run(sess, sql):
    return [tuple(r) for r in sess.sql(sql).collect()]


#: dict-free sides (projected to int columns) — the ONLY shape the ICI
#: device tier accepts: dictionary-coded columns are pinned to the host
#: tier, where the code-space unification lives.  The unprojected
#: QUERIES battery above doubles as the dict-code lane: its string
#: columns keep every exchange on the host path even with the tier
#: armed, results still byte-identical.
ICI_QUERIES = [
    ("ici-inner-agg",
     "SELECT sk, count(*) AS c, sum(bonus) AS sb "
     "FROM (SELECT sk FROM fact) f "
     "JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
     "GROUP BY sk ORDER BY sk"),
    ("ici-inner-rows",
     "SELECT sk, price, bonus FROM (SELECT sk, price FROM fact) f "
     "JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
     "WHERE bonus > 40 ORDER BY sk, price, bonus"),
    ("ici-left-agg",
     "SELECT sk, count(bonus) AS cb, count(*) AS c "
     "FROM (SELECT sk FROM fact) f "
     "LEFT JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
     "GROUP BY sk ORDER BY sk"),
]

if mode in ("fault", "fault-sample", "ici-fault"):
    FaultInjector().attach(svc)        # plan comes from SPARK_TPU_FAULT_PLAN
    set_mode("range" if mode == "fault-sample" else "hash")
    join_counter = ("range_merge_joins" if mode == "fault-sample"
                    else "shuffled_joins")
    if mode == "ici-fault":
        # dict-free sides so the device tier genuinely ATTEMPTS (and
        # the armed fault point actually fires) before degrading
        name, sql = ICI_QUERIES[0]
    else:
        name, sql, _ = QUERIES[0]
    exp = run(oracle, sql)
    t0 = time.time()
    try:
        got = run(xs, sql)
    except (ExchangeFetchFailed, TimeoutError) as e:
        lost = sorted(getattr(e, "lost_hosts", []) or [])
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} {lost}", flush=True)
        os._exit(0)
    assert svc.counters[join_counter] > 0, svc.counters
    if got != exp:
        print(f"[p{pid}] PARTIAL got={len(got)} exp={len(exp)}", flush=True)
        os._exit(1)
    print(f"[p{pid}] OK {len(got)}", flush=True)
    os._exit(0)

if mode == "spill-fault":
    FaultInjector().attach(svc)        # disk_full plan from the env
    set_mode("hash")
    _name, sql, _ = QUERIES[0]
    t0 = time.time()
    try:
        got = run(xs, sql)
    except HostMemoryError as e:
        # the faulted process: spill hit injected ENOSPC, and the error
        # names the reserver and the exchange — structured and bounded
        assert e.owner and "spill failed" in str(e), e
        print(f"[p{pid}] FAILED-HOSTMEM {time.time() - t0:.2f} "
              f"{e.owner}", flush=True)
        os._exit(0)
    except (ExchangeFetchFailed, TimeoutError):
        # the healthy peer: its partner aborted mid-exchange, so it
        # fails bounded on the fetch/barrier timeout — never partial
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} []", flush=True)
        os._exit(0)
    print(f"[p{pid}] PARTIAL rows={len(got)}", flush=True)
    os._exit(1)

# keyed aggregates ABOVE the join: the sides are plain leaves, so RAW
# rows ride the join exchange (nothing pushes down) and the pressure
# lands exactly on the reducer's drain — while the merged group states
# keep every post-join exchange far below the capped budget
GRACE_QUERIES = [
    ("grace-inner",
     "SELECT sk, count(*) AS c, sum(bonus) AS sb "
     "FROM (SELECT sk FROM fact) f "
     "JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
     "GROUP BY sk ORDER BY sk"),
    ("grace-left",
     "SELECT sk, count(bonus) AS cb, count(*) AS c "
     "FROM (SELECT sk FROM fact) f "
     "LEFT JOIN (SELECT k2, bonus FROM fact2) f2 ON sk = k2 "
     "GROUP BY sk ORDER BY sk"),
    ("grace-semi",
     "SELECT sk, count(*) AS c FROM (SELECT sk FROM fact) f "
     "LEFT SEMI JOIN (SELECT k2 FROM fact2) f2 ON sk = k2 "
     "GROUP BY sk ORDER BY sk"),
    ("grace-string",
     "SELECT g, count(*) AS c, sum(bonus) AS sb "
     "FROM (SELECT g FROM fact) f "
     "JOIN (SELECT g2, bonus FROM fact2) f2 ON g = g2 "
     "GROUP BY g ORDER BY g"),
]
#: grace runs BOTH distributed lanes (gather has no reducer drain)
GRACE_MODES = (("range", "range_merge_joins"), ("hash", "shuffled_joins"))

if mode == "grace-fault":
    FaultInjector().attach(svc)    # disk_full on the -grace exchange
    set_mode("hash")
    _name, sql = GRACE_QUERIES[0]
    t0 = time.time()
    try:
        got = run(xs, sql)
    except HostMemoryError as e:
        # the faulted process: the grace SPILL hit injected ENOSPC —
        # the degraded path itself fails structured and bounded
        assert e.owner and "grace spill failed" in str(e), e
        print(f"[p{pid}] FAILED-HOSTMEM {time.time() - t0:.2f} "
              f"{e.owner}", flush=True)
        os._exit(0)
    except (ExchangeFetchFailed, TimeoutError):
        # the healthy peer fails bounded on its exchange timeout
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} []", flush=True)
        os._exit(0)
    print(f"[p{pid}] PARTIAL rows={len(got)}", flush=True)
    os._exit(1)

if mode == "grace":
    for name, sql in GRACE_QUERIES:
        exp = run(oracle, sql)
        for m, want in GRACE_MODES:
            set_mode(m)
            before = dict(svc.counters)
            got = run(xs, sql)
            assert svc.counters[want] > before[want], (
                f"{name}/{m}: expected the {want} path, {svc.counters}")
            if got != exp:
                print(f"[p{pid}] GRACE-PARITY-FAIL {name}/{m} "
                      f"got={got[:4]} exp={exp[:4]}", flush=True)
                os._exit(1)
        print(f"[p{pid}] GRACE-PARITY-OK {name} ({len(exp)} rows)",
              flush=True)
    # elastic narrowing: one reducer's worth of target bytes swallows
    # the whole observed working set, so the plan round must narrow the
    # reducer set below the live set — re-derived deterministically on
    # EVERY process (the runtime invariant cross-checks it against the
    # shared manifests) — and the lone reducer's drain grace-degrades
    xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, str(1 << 20))
    name, sql = GRACE_QUERIES[0]
    exp = run(oracle, sql)
    for m, want in GRACE_MODES:
        set_mode(m)
        before = dict(svc.counters)
        got = run(xs, sql)
        assert svc.counters[want] > before[want], (
            f"elastic/{m}: expected the {want} path, {svc.counters}")
        if got != exp:
            print(f"[p{pid}] GRACE-PARITY-FAIL elastic/{m} "
                  f"got={got[:4]} exp={exp[:4]}", flush=True)
            os._exit(1)
    print(f"[p{pid}] GRACE-PARITY-OK elastic ({len(exp)} rows)",
          flush=True)
    # salted re-split: ONE grace bucket holds a reducer's whole working
    # set, so it cannot fit under the budget and must re-split under a
    # salt — the sub-buckets fit, and results still match the oracle.
    # Two legs so at two processes EACH pressures at least once: at the
    # small advisory target the hot-key owner degrades; at the huge
    # target the elastic plan routes everything to the lone first
    # reducer.  (At other widths a process may own no pressured shard
    # in either leg — the re-split assert then stays with whoever
    # actually graced.)
    xs.conf.set(C.CROSSPROC_GRACE_BUCKETS.key, "1")
    set_mode("hash")
    before = dict(svc.counters)
    for tgt in ("2048", str(1 << 20)):
        xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, tgt)
        got = run(xs, sql)
        if got != exp:
            print(f"[p{pid}] GRACE-PARITY-FAIL resplit@{tgt} "
                  f"got={got[:4]} exp={exp[:4]}", flush=True)
            os._exit(1)
    if n == 2 or svc.counters["grace_buckets_used"] > \
            before["grace_buckets_used"]:
        assert svc.counters["grace_salted_resplits"] > \
            before["grace_salted_resplits"], svc.counters
    print(f"[p{pid}] GRACE-PARITY-OK resplit ({len(exp)} rows)",
          flush=True)
    xs.conf.set(C.CROSSPROC_GRACE_BUCKETS.key,
                str(C.CROSSPROC_GRACE_BUCKETS.default))
    assert svc.counters["reducers_elastic"] > 0, svc.counters
    assert 0 < svc.counters["reducers_observed"] \
        < svc.counters["reducers_planned"], svc.counters
    if n == 2:
        # the budget is tuned so BOTH processes demonstrably grace at
        # two processes; at wider sets a process may own only shards
        # that fit, so the cumulative evidence lives on the pressured
        # peers (parity above still ran everywhere)
        assert svc.counters["grace_buckets_used"] > 0, svc.counters
        assert svc.counters["grace_spill_bytes"] > 0, svc.counters
    gauges = svc.metrics_source().snapshot()
    assert 0 < gauges["peak_host_bytes"] <= gauges["host_budget_bytes"], \
        gauges
    print(f"[p{pid}] GRACE-OK buckets={svc.counters['grace_buckets_used']} "
          f"spill={svc.counters['grace_spill_bytes']} "
          f"resplits={svc.counters['grace_salted_resplits']} "
          f"elastic={svc.counters['reducers_elastic']} "
          f"peak={gauges['peak_host_bytes']}", flush=True)
    os._exit(0)

if mode == "runcodes":
    # run-encoded vs raw wire parity on BOTH exchange lanes.  The
    # workload is time-series shaped: a sorted key in LONG runs, a
    # low-cardinality status string whose dictionary codes are
    # themselves int32 runs (dictionary+RLE composed), and random
    # values.  The strided per-process slice keeps every run shape,
    # just 1/n as long — and the forced-spill conf above makes every
    # exchange stage its encoded frames through disk.
    NRK, REP = 48, 64
    r_ts = np.repeat(np.arange(NRK, dtype=np.int64), REP)
    r_v = rng.integers(1, 100, NRK * REP).astype(np.int64)
    r_s = np.array(["ok", "warn", "err"])[(np.arange(NRK * REP) // 256) % 3]
    r_dk = np.arange(0, NRK, 2, dtype=np.int64)     # even keys → LEFT misses
    r_bonus = (r_dk * 3 + 7).astype(np.int64)
    r_s2 = np.array(["ok", "err", "crit", "ok", "warn", "crit"])
    r_b2 = np.array([11, 23, 37, 5, 41, 2], dtype=np.int64)
    for s, sl in ((xs, mine), (oracle, slice(None))):
        s.createDataFrame({"ts": r_ts[sl], "v": r_v[sl], "s": r_s[sl]}) \
            .createOrReplaceTempView("ev")
        s.createDataFrame({"dk": r_dk[sl], "bonus": r_bonus[sl]}) \
            .createOrReplaceTempView("dm")
        s.createDataFrame({"s2": r_s2[sl], "b2": r_b2[sl]}) \
            .createOrReplaceTempView("dm2")

    RC_QUERIES = [
        ("rc-inner-agg",
         "SELECT ts, count(*) AS c, sum(v) AS sv FROM ev "
         "JOIN dm ON ts = dk GROUP BY ts ORDER BY ts"),
        ("rc-rows-filter",
         "SELECT ts, v, bonus FROM ev JOIN dm ON ts = dk "
         "WHERE bonus > 20 ORDER BY ts, v, bonus"),
        ("rc-left-agg",
         "SELECT ts, count(bonus) AS cb, count(*) AS c FROM ev "
         "LEFT JOIN dm ON ts = dk GROUP BY ts ORDER BY ts"),
        ("rc-dict-rle",
         "SELECT s, count(*) AS c, sum(b2) AS sb FROM ev "
         "JOIN dm2 ON s = s2 GROUP BY s ORDER BY s"),
        # the r20 plane query: filter+agg over the run-shaped key — the
        # reduce-side join shards arrive run-encoded, and on the
        # encoded+jit leg they must cross the stage boundary as device
        # planes, WITHOUT a single host materialization
        ("rc-plane-agg",
         "SELECT ts, count(*) AS c, sum(v) AS sv FROM ev "
         "JOIN dm ON ts = dk WHERE ts < 32 GROUP BY ts ORDER BY ts"),
    ]

    def set_runcodes(on):
        # the service snapshots the conf at construction; the worker
        # flips BOTH (the conf feeds the SpilledRuns constructors, the
        # attribute feeds encode/decode) — identically on every process
        xs.conf.set(C.SHUFFLE_WIRE_RUN_CODES.key,
                    "true" if on else "false")
        svc.run_codes = bool(on)

    # three legs per lane: encoded+jit (eligible run leaves cross the
    # stage boundary as device planes, un-inflated; untaught leaves
    # still materialize counted), encoded+interpreted (the host lane
    # keeps run vectors lazy all the way into the operators — the
    # run-aware join probe and filter paths fire here), and raw+jit
    # (the oracle wire)
    LEGS = (("on", True, True), ("on-host", True, False),
            ("off", False, True))
    for name, sql in RC_QUERIES:
        exp = run(oracle, sql)
        for m, want in (("range", "range_merge_joins"),
                        ("hash", "shuffled_joins")):
            set_mode(m)
            for leg, on, jit in LEGS:
                set_runcodes(on)
                xs.conf.set(C.CODEGEN_ENABLED.key,
                            "true" if jit else "false")
                before = dict(svc.counters)
                mat0 = _col.runs_materialized()
                got = run(xs, sql)
                if name == "rc-plane-agg" and on and jit:
                    # the tentpole acceptance: the fully-eligible
                    # filter+agg pipeline never expands a run on the
                    # host — planes carry the compressed form through
                    # the jitted stage on BOTH exchange lanes
                    assert _col.runs_materialized() == mat0, (
                        f"{name}/{m}/{leg}: runs_materialized moved "
                        f"{_col.runs_materialized() - mat0} on the "
                        "plane leg")
                assert svc.counters[want] > before.get(want, 0), (
                    f"{name}/{m}: expected the {want} path, {svc.counters}")
                if not on:
                    # raw leg: the encoder must not have touched a column
                    assert svc.counters["rle_columns_encoded"] == \
                        before.get("rle_columns_encoded", 0), svc.counters
                if got != exp:
                    print(f"[p{pid}] RC-PARITY-FAIL {name}/{m}/{leg} "
                          f"got={got[:4]} exp={exp[:4]}", flush=True)
                    os._exit(1)
        print(f"[p{pid}] RC-PARITY-OK {name} ({len(exp)} rows)", flush=True)
    xs.conf.set(C.CODEGEN_ENABLED.key, "true")
    set_runcodes(True)
    # the encoded legs demonstrably run-encoded columns and saved bytes
    assert svc.counters["rle_columns_encoded"] > 0, svc.counters
    assert svc.counters["run_bytes_saved"] > 0, svc.counters
    # run-aware operators fired on lazily-decoded run vectors, and the
    # collect() late-materialized at least one of them
    assert _col.run_aware_op_rows() > 0, _col.run_aware_op_rows()
    assert _col.runs_materialized() > 0, _col.runs_materialized()
    # spill-under-budget cell: every exchange staged through disk, the
    # encoded frames never inflated past the capped ledger
    assert svc.counters["spill_bytes"] > 0, svc.counters
    gauges = svc.metrics_source().snapshot()
    assert gauges["rle_columns_encoded"] > 0, gauges
    assert gauges["run_bytes_saved"] > 0, gauges
    assert 0 < gauges["peak_host_bytes"] <= gauges["host_budget_bytes"], \
        gauges
    print(f"[p{pid}] RUNCODES-OK rle={svc.counters['rle_columns_encoded']} "
          f"saved={svc.counters['run_bytes_saved']} "
          f"runaware={_col.run_aware_op_rows()} "
          f"mat={_col.runs_materialized()} "
          f"spill={svc.counters['spill_bytes']}", flush=True)
    os._exit(0)

JOIN_COUNTERS = ("range_merge_joins", "shuffled_joins", "broadcast_joins")
for name, sql, expected in QUERIES:
    exp = run(oracle, sql)
    results = {}
    for m, _smj, _sh in MODES:
        set_mode(m)
        before = dict(svc.counters)
        results[m] = run(xs, sql)
        want = expected.get(m)
        if want is not None:
            assert svc.counters[want] > before[want], (
                f"{name}/{m}: expected the {want} path, {svc.counters}")
        # no OTHER exchange-join path may have run for this query
        for c in JOIN_COUNTERS:
            if c != want:
                assert svc.counters[c] == before[c], (
                    f"{name}/{m}: unexpected {c} bump, {svc.counters}")
    set_mode("range")
    bad = [m for m in results if results[m] != exp]
    if bad:
        print(f"[p{pid}] PARITY-FAIL {name} modes={bad} "
              f"got={results[bad[0]][:4]} exp={exp[:4]}", flush=True)
        os._exit(1)
    print(f"[p{pid}] PARITY-OK {name} ({len(exp)} rows)", flush=True)

# manifest-driven coalescing: the battery above ships tiny fine
# partitions, all far below targetPartitionBytes — the planner must have
# merged them (and the merge demonstrably did not change any result)
assert svc.counters["partitions_coalesced"] > 0, svc.counters
# the hot key forced the range planner to SPLIT its span across reducers
# (the skew mitigation), and the sample round actually moved manifests
assert svc.counters["spans_split"] > 0, svc.counters
assert svc.counters["sample_bytes"] > 0, svc.counters
# per-exchange data-plane accounting: produced >= shipped, and the
# manifest-derived partition-size and cut-point gauges are populated
gauges = svc.metrics_source().snapshot()
assert gauges["bytes_produced_raw"] >= gauges["bytes_shipped_raw"] > 0, gauges
assert gauges["rows_produced"] >= gauges["rows_shipped"] > 0, gauges
assert gauges["partition_bytes_max"] >= gauges["partition_bytes_median"], gauges
assert gauges["range_cutpoints"] > 0, gauges
# encoded execution: dictionary columns crossed the wire as codes with the
# sidecar dedup saving repeat shipments, the disjoint tags dictionaries
# forced receiver-side remaps, and collected strings late-materialized
assert gauges["dict_columns_encoded"] > 0, gauges
assert gauges["dict_bytes_saved"] > 0, gauges
assert gauges["codes_remapped"] > 0, gauges
assert gauges["late_materialized_rows"] > 0, gauges
if mode == "spill":
    # every join exchange was forced through the spill path, results
    # above matched the oracle anyway, and the ledger never exceeded the
    # capped budget
    assert svc.counters["spill_bytes"] > 0, svc.counters
    assert svc.counters["spill_events"] > 0, svc.counters
    assert 0 < gauges["peak_host_bytes"] <= gauges["host_budget_bytes"], \
        gauges
    print(f"[p{pid}] SPILL-OK bytes={svc.counters['spill_bytes']} "
          f"events={svc.counters['spill_events']} "
          f"peak={gauges['peak_host_bytes']}", flush=True)
    os._exit(0)
if mode == "ici":
    # the dict-column battery above kept every exchange on the host
    # path (the code-space gate) — results byte-identical with the
    # tier armed.  Now dict-FREE sides, where the device tier must
    # genuinely attempt every exchange: no CPU process can span the
    # 2-process domain, so each attempt must fold back structured onto
    # the host tier and still match the oracle exactly, on BOTH lanes.
    assert svc.counters["dcn_fallback_exchanges"] == 0, svc.counters
    for name, sql in ICI_QUERIES:
        exp = run(oracle, sql)
        for m, want in (("range", "range_merge_joins"),
                        ("hash", "shuffled_joins")):
            set_mode(m)
            before = dict(svc.counters)
            got = run(xs, sql)
            assert svc.counters[want] > before[want], (
                f"{name}/{m}: expected the {want} path, {svc.counters}")
            assert svc.counters["dcn_fallback_exchanges"] > \
                before["dcn_fallback_exchanges"], (
                f"{name}/{m}: no device-tier attempt, {svc.counters}")
            if got != exp:
                print(f"[p{pid}] ICI-PARITY-FAIL {name}/{m} "
                      f"got={got[:4]} exp={exp[:4]}", flush=True)
                os._exit(1)
        print(f"[p{pid}] ICI-PARITY-OK {name} ({len(exp)} rows)",
              flush=True)
    assert svc.counters["ici_exchanges"] == 0, svc.counters
    assert svc.counters["ici_bytes_moved"] == 0, svc.counters
    assert svc.counters["tier_split_peers"] == n - 1, svc.counters
    print(f"[p{pid}] ICI-FALLBACK-OK "
          f"fallbacks={svc.counters['dcn_fallback_exchanges']} "
          f"peers={svc.counters['tier_split_peers']}", flush=True)
print(f"[p{pid}] ALL-OK range={svc.counters['range_merge_joins']} "
      f"shuffled={svc.counters['shuffled_joins']} "
      f"fast={svc.counters['fast_path_aggs']} "
      f"coalesced={svc.counters['partitions_coalesced']} "
      f"split={svc.counters['spans_split']}", flush=True)
os._exit(0)
