"""Subquery rewrites (subquery.scala analog): scalar/IN/EXISTS -> joins,
INTERSECT/EXCEPT -> semi/anti joins."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu.expressions import AnalysisException


@pytest.fixture()
def tu(spark):
    t = spark.createDataFrame(pd.DataFrame({
        "k": [1, 2, 3, 4, 5], "g": ["a", "a", "b", "b", "c"],
        "v": [1.0, 2.0, 3.0, 4.0, 10.0]}))
    u = spark.createDataFrame(pd.DataFrame({
        "k2": [2, 3, 9], "w": [5.0, 6.0, 7.0]}))
    t.createOrReplaceTempView("t")
    u.createOrReplaceTempView("u")
    yield spark
    spark.catalog.dropTempView("t")
    spark.catalog.dropTempView("u")


def rows(df):
    return [tuple(r) for r in df.collect()]


def test_scalar_uncorrelated(tu):
    got = rows(tu.sql("SELECT k FROM t WHERE v > (SELECT AVG(v) FROM t) "
                      "ORDER BY k"))
    assert got == [(5,)]


def test_scalar_correlated(tu):
    got = rows(tu.sql(
        "SELECT k FROM t t1 WHERE v > "
        "(SELECT AVG(t2.v) FROM t t2 WHERE t2.g = t1.g) ORDER BY k"))
    assert got == [(2,), (4,)]


def test_scalar_in_arithmetic(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE v > 0.5 * (SELECT MAX(v) FROM t) ORDER BY k"))
    assert got == [(5,)]


def test_scalar_missing_group_is_null(tu):
    """Correlated group absent -> NULL -> comparison false (left join)."""
    got = rows(tu.sql(
        "SELECT k2 FROM u WHERE k2 > "
        "(SELECT SUM(t.k) FROM t WHERE t.k = u.k2) ORDER BY k2"))
    assert got == []   # 2 > 2 false, 3 > 3 false, 9 has no group -> NULL


def test_in_subquery(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE k IN (SELECT k2 FROM u) ORDER BY k"))
    assert got == [(2,), (3,)]


def test_not_in_subquery(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE k NOT IN (SELECT k2 FROM u) ORDER BY k"))
    assert got == [(1,), (4,), (5,)]


def test_in_subquery_correlated(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE k IN "
        "(SELECT k2 FROM u WHERE u.w > t.v) ORDER BY k"))
    assert got == [(2,), (3,)]


def test_exists(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE EXISTS "
        "(SELECT * FROM u WHERE u.k2 = t.k) ORDER BY k"))
    assert got == [(2,), (3,)]


def test_not_exists(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE NOT EXISTS "
        "(SELECT * FROM u WHERE u.k2 = t.k) ORDER BY k"))
    assert got == [(1,), (4,), (5,)]


def test_exists_non_equi_residual(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE EXISTS "
        "(SELECT * FROM u WHERE u.k2 = t.k AND u.w > 5.5) ORDER BY k"))
    assert got == [(3,)]


def test_uncorrelated_exists_raises(tu):
    with pytest.raises(AnalysisException):
        tu.sql("SELECT k FROM t WHERE EXISTS (SELECT * FROM u)").collect()


def test_intersect(tu):
    got = sorted(rows(tu.sql("SELECT k FROM t INTERSECT SELECT k2 FROM u")))
    assert got == [(2,), (3,)]


def test_except(tu):
    got = sorted(rows(tu.sql("SELECT k FROM t EXCEPT SELECT k2 FROM u")))
    assert got == [(1,), (4,), (5,)]


def test_intersect_deduplicates(tu):
    got = rows(tu.sql(
        "SELECT g FROM t INTERSECT SELECT 'a' AS x FROM u"))
    assert got == [("a",)]


def test_correlated_count_empty_group_is_zero(tu):
    """COUNT over an empty correlated group reads 0, not NULL."""
    got = rows(tu.sql(
        "SELECT k2 FROM u WHERE "
        "(SELECT COUNT(*) FROM t WHERE t.k = u.k2) = 0 ORDER BY k2"))
    assert got == [(9,)]
    with pytest.raises(AnalysisException):
        tu.sql("SELECT k2 FROM u WHERE "
               "(SELECT COUNT(*) + 1 FROM t WHERE t.k = u.k2) = 1").collect()


def test_intersect_precedence(tu):
    """INTERSECT binds tighter than UNION (standard precedence)."""
    got = sorted(rows(tu.sql(
        "SELECT k FROM t WHERE k = 1 UNION "
        "SELECT k FROM t INTERSECT SELECT k2 FROM u")))
    assert got == [(1,), (2,), (3,)]


def test_intersect_star_and_qualified(tu):
    assert len(rows(tu.sql("SELECT * FROM u INTERSECT SELECT * FROM u"))) == 3
    got = sorted(rows(tu.sql(
        "SELECT t.k FROM t INTERSECT SELECT u.k2 FROM u")))
    assert got == [(2,), (3,)]


def test_nested_subquery(tu):
    got = rows(tu.sql(
        "SELECT k FROM t WHERE k IN "
        "(SELECT k2 FROM u WHERE w > (SELECT AVG(w) FROM u))"))
    assert got == []      # avg(w)=6 -> only k2=9 qualifies, not in t


def test_exists_with_limit(tu):
    got = sorted(rows(tu.sql(
        "SELECT k FROM t WHERE EXISTS "
        "(SELECT 1 FROM u WHERE u.k2 = t.k LIMIT 1)")))
    assert got == [(2,), (3,)]


def test_cte_in_subquery(tu):
    got = rows(tu.sql("""
        WITH big AS (SELECT g, SUM(v) AS sv FROM t GROUP BY g)
        SELECT g FROM big b1
        WHERE b1.sv > (SELECT AVG(sv) FROM big b2) ORDER BY g"""))
    assert got == [("b",), ("c",)]


def test_subquery_in_having(tu):
    got = rows(tu.sql(
        "SELECT g, SUM(v) AS sv FROM t GROUP BY g "
        "HAVING SUM(v) > (SELECT AVG(v) FROM t) ORDER BY g"))
    assert got == [("b", 7.0), ("c", 10.0)]


def test_mixed_distinct_and_sum(tu):
    got = rows(tu.sql(
        "SELECT COUNT(DISTINCT g) AS dg, SUM(v) AS sv, MIN(k) AS mk FROM t"))
    assert got == [(3, 20.0, 1)]


def test_window_over_aggregate(tu):
    got = rows(tu.sql(
        "SELECT g, SUM(v) AS sv, "
        "SUM(SUM(v)) OVER () AS total FROM t GROUP BY g ORDER BY g"))
    assert got == [("a", 3.0, 20.0), ("b", 7.0, 20.0), ("c", 10.0, 20.0)]


def test_scalar_subquery_in_select_list(spark):
    spark.sql("SELECT 1 AS a UNION ALL SELECT 2 AS a"
               ).createOrReplaceTempView("sq_t1")
    spark.sql("SELECT 10 AS b UNION ALL SELECT 20 AS b"
               ).createOrReplaceTempView("sq_t2")
    rows = spark.sql(
        "SELECT a, (SELECT SUM(b) FROM sq_t2) AS s FROM sq_t1 ORDER BY a"
    ).collect()
    assert [(r["a"], r["s"]) for r in rows] == [(1, 30), (2, 30)]


def test_scalar_subquery_inside_case(spark):
    spark.sql("SELECT 5 AS x").createOrReplaceTempView("sq_one")
    rows = spark.sql(
        "SELECT CASE WHEN (SELECT MAX(x) FROM sq_one) > 3 THEN 'big' "
        "ELSE 'small' END AS c FROM sq_one").collect()
    assert rows[0]["c"] == "big"


def test_in_subquery_under_or(spark):
    spark.sql("SELECT 1 AS v UNION ALL SELECT 2 AS v UNION ALL "
               "SELECT 3 AS v UNION ALL SELECT 4 AS v"
               ).createOrReplaceTempView("sq_vals")
    spark.sql("SELECT 2 AS w").createOrReplaceTempView("sq_set")
    rows = spark.sql(
        "SELECT v FROM sq_vals WHERE v = 4 OR v IN (SELECT w FROM sq_set) "
        "ORDER BY v").collect()
    assert [r["v"] for r in rows] == [2, 4]


def test_correlated_in_under_or_rejected(spark):
    import pytest
    from spark_tpu.expressions import AnalysisException
    spark.sql("SELECT 1 AS v").createOrReplaceTempView("sq_a")
    spark.sql("SELECT 1 AS w, 1 AS k").createOrReplaceTempView("sq_b")
    with pytest.raises(AnalysisException, match="correlated IN"):
        spark.sql("SELECT v FROM sq_a WHERE v = 9 OR v IN "
                   "(SELECT w FROM sq_b WHERE k = sq_a.v)").collect()


def test_non_aggregate_scalar_subquery(spark):
    spark.sql("SELECT 7 AS only").createOrReplaceTempView("sq_single")
    rows = spark.sql(
        "SELECT (SELECT only FROM sq_single) + 1 AS r").collect()
    assert rows[0]["r"] == 8


def test_chained_ctes(spark):
    rows = spark.sql("""
        WITH base AS (SELECT 1 AS x UNION ALL SELECT 2 AS x),
             doubled AS (SELECT x * 2 AS y FROM base),
             shifted AS (SELECT y + 10 AS z FROM doubled)
        SELECT z FROM shifted ORDER BY z""").collect()
    assert [r["z"] for r in rows] == [12, 14]
