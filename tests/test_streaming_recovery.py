"""Kill-at-phase chaos battery for exactly-once standing queries.

The in-process half of the streaming chaos matrix: the engine is
"killed" (a ``_SimKill`` raised through the fault injector's ``die``
seam) at each phase of the micro-batch commit protocol —

  mid-batch               offsets WAL'd, nothing else durable
  post-state-commit       state snapshot durable, sink + commit not
  mid-commit              commit entry TORN right after its rename

— then a fresh execution recovers from the same checkpoint and the
final FileSink contents must be BYTE-identical to an uninterrupted
oracle run, for a windowed aggregate and a stateful dedup.  The
subprocess half (real ``os._exit(43)`` kills) lives in
``tests/chaos_matrix.py --streaming``.
"""

import glob
import os

import numpy as np
import pytest

from spark_tpu import types as T
from spark_tpu.parallel.faults import FaultInjector, FaultPlan
from spark_tpu.sql import functions as F
from spark_tpu.streaming.core import (
    CheckpointCorruption, FileSink, FileStreamSource, MetadataLog,
    StreamExecution,
)


@pytest.fixture(autouse=True)
def _single_shard(spark):
    """Micro-batches replay local single-shard; pin the shared session
    in case an earlier module leaked a wider mesh conf."""
    prev = spark.conf.get("spark.tpu.mesh.shards")
    spark.conf.set("spark.tpu.mesh.shards", "1")
    yield
    spark.conf.set("spark.tpu.mesh.shards", str(prev))


def sec(n) -> int:
    return int(n * 1_000_000)     # timestamps are int64 microseconds


SCHEMA = T.StructType([
    T.StructField("ts", T.timestamp),
    T.StructField("k", T.string),
    T.StructField("v", T.int64),
])

# one input FILE per feed; with maxFilesPerTrigger=1 each becomes one
# micro-batch, in the same order, in every lifetime (live or recovered)
FEEDS = [
    [(sec(1), "a", 1), (sec(9), "b", 2)],
    [(sec(20), "a", 4), (sec(21), "b", 1)],
    [(sec(35), "c", 8), (sec(35), "c", 8)],     # in-batch duplicate
    [(sec(50), "a", 3), (sec(51), "d", 9)],
]


def _windowed_agg(df):
    return (df.withWatermark("ts", "5 seconds")
            .groupBy(F.window("ts", "10 seconds").alias("w"))
            .agg(F.sum("v").alias("s")))


def _stateful_dedup(df):
    return (df.withWatermark("ts", "5 seconds")
            .dropDuplicates(["k", "ts"]))


SHAPES = {"windowed_agg": _windowed_agg, "stateful_dedup": _stateful_dedup}

PHASES = ["mid_batch", "post_state_commit", "mid_commit"]


class _SimKill(BaseException):
    """Simulated hard process death (BaseException so no engine-level
    ``except Exception`` can swallow the kill)."""


def _write_inputs(spark, in_dir: str) -> None:
    os.makedirs(in_dir, exist_ok=True)
    for i, rows in enumerate(FEEDS):
        spark.createDataFrame({
            "ts": np.array([r[0] for r in rows], "datetime64[us]"),
            "k": [r[1] for r in rows],
            "v": np.array([r[2] for r in rows], np.int64),
        }).write.parquet(os.path.join(in_dir, f"f{i}"))


def _arm(ex: StreamExecution, phase: str, at_batch: int) -> None:
    if phase == "mid_batch":
        orig = ex._execute_batch

        def execute(batch):
            out = orig(batch)
            if ex.batch_id == at_batch:
                raise _SimKill(f"mid-batch {ex.batch_id}")
            return out

        ex._execute_batch = execute
        return

    def raiser(code):
        raise _SimKill(code)

    if phase == "post_state_commit":
        plan = FaultPlan().die_after_state_commit(after_entries=at_batch)
    else:   # mid_commit: the entry is torn in place, then the kill
        plan = FaultPlan().torn_checkpoint(
            keep_bytes=11, after_entries=at_batch, die=True)
    inj = FaultInjector(plan)
    inj.die = raiser
    inj.attach_stream(ex)


def _lifetime(spark, shape_fn, in_dir: str, ckpt: str, out: str,
              kill=None) -> StreamExecution:
    """One 'process lifetime': fresh source + execution over the shared
    checkpoint, drain everything available (or die trying)."""
    src = FileStreamSource("parquet", in_dir, SCHEMA,
                          {"maxfilespertrigger": "1"})
    from spark_tpu.sql.dataframe import DataFrame
    from spark_tpu.streaming.core import StreamingRelation
    df = shape_fn(DataFrame(spark, StreamingRelation(src)))
    ex = StreamExecution(spark, df._plan, FileSink("json", out, {}),
                         "append", ckpt, 0.1, None)
    if kill is not None:
        _arm(ex, *kill)
    try:
        ex.process_all_available()
    finally:
        # a killed lifetime leaves its durable state exactly as the kill
        # left it; only the in-process registration goes away, as a real
        # process exit would take it
        regs = getattr(spark, "_stream_execs", [])
        if ex in regs:
            regs.remove(ex)
    return ex


def _sink_files(out: str):
    return {os.path.basename(p): open(p, "rb").read()
            for p in sorted(glob.glob(os.path.join(out, "part-*")))}


@pytest.mark.parametrize("phase", PHASES)
@pytest.mark.parametrize("shape", sorted(SHAPES))
def test_kill_at_phase_byte_parity(spark, tmp_path, shape, phase):
    shape_fn = SHAPES[shape]
    in_dir = str(tmp_path / "in")
    _write_inputs(spark, in_dir)

    oracle_out = str(tmp_path / "oracle_out")
    _lifetime(spark, shape_fn, in_dir,
              str(tmp_path / "oracle_ckpt"), oracle_out)
    oracle = _sink_files(oracle_out)
    assert oracle, "the oracle run must emit something to compare"

    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
    with pytest.raises(_SimKill):
        _lifetime(spark, shape_fn, in_dir, ckpt, out, kill=(phase, 1))
    # the engine restarts: a fresh execution over the same checkpoint
    ex = _lifetime(spark, shape_fn, in_dir, ckpt, out)
    assert ex.exception is None
    # no duplicated, no lost rows — byte-for-byte the oracle's files
    assert _sink_files(out) == oracle
    # the killed batch really was replayed from its WAL entry
    assert ex.metrics["replayed_batches"] >= 1
    assert ex.metrics["batches_committed"] >= 1


def test_corrupt_state_snapshot_aborts_structured(spark, tmp_path):
    """A COMMITTED batch whose state snapshot no longer matches the
    fingerprint in its commit entry is unrecoverable: recovery must abort
    naming the batch id, never silently restore divergent state."""
    in_dir = str(tmp_path / "in")
    _write_inputs(spark, in_dir)
    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
    _lifetime(spark, _windowed_agg, in_dir, ckpt, out)

    commits = os.path.join(ckpt, "commits")
    last = max(int(f) for f in os.listdir(commits) if f.isdigit())
    snap = os.path.join(ckpt, "state", f"{last}.snapshot")
    buf = open(snap, "rb").read()
    with open(snap, "wb") as f:           # flip payload bytes in place
        f.write(buf[:-8] + bytes(b ^ 0xFF for b in buf[-8:]))

    with pytest.raises(CheckpointCorruption) as ei:
        _lifetime(spark, _windowed_agg, in_dir, ckpt, out)
    assert ei.value.batch_id == last
    assert str(last) in str(ei.value)


def test_torn_commit_replays_not_crashes(spark, tmp_path):
    """torn_checkpoint WITHOUT the kill: the torn entry simply reads as
    uncommitted and the next drain replays + recommits that batch."""
    in_dir = str(tmp_path / "in")
    _write_inputs(spark, in_dir)
    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")

    # tear the LAST commit entry (batch 3) — the realistic torn tail a
    # mid-write power cut leaves behind
    last = len(FEEDS) - 1
    plan = FaultPlan().torn_checkpoint(keep_bytes=7, after_entries=last)
    src = FileStreamSource("parquet", in_dir, SCHEMA,
                          {"maxfilespertrigger": "1"})
    from spark_tpu.sql.dataframe import DataFrame
    from spark_tpu.streaming.core import StreamingRelation
    df = _stateful_dedup(DataFrame(spark, StreamingRelation(src)))
    ex = StreamExecution(spark, df._plan, FileSink("json", out, {}),
                         "append", ckpt, 0.1, None)
    inj = FaultInjector(plan)
    inj.attach_stream(ex)
    ex.process_all_available()
    assert any(s.startswith("torn_checkpoint:") for s in inj.injected)
    # the torn entry must read as uncommitted, not crash the reader
    assert MetadataLog(os.path.join(ckpt, "commits")).get(last) is None
    ex.stop()

    # recovery replays the torn batch and recommits it intact
    ex2 = _lifetime(spark, _stateful_dedup, in_dir, ckpt, out)
    assert ex2.metrics["replayed_batches"] >= 1
    assert MetadataLog(os.path.join(ckpt, "commits")).get(last) is not None


def test_second_batch_zero_stage_rebuilds(spark, tmp_path):
    """The standing query plans once: batch 2 runs entirely out of the
    stage-executable cache (capacity-padded leaves keep signatures
    stable) and reports zero rebuilds."""
    in_dir = str(tmp_path / "in")
    _write_inputs(spark, in_dir)
    ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
    ex = _lifetime(spark, _windowed_agg, in_dir, ckpt, out)
    assert len(ex.progress) >= 2
    assert ex.progress[1]["stageRebuilds"] == 0
    assert ex.progress[-1]["stageRebuilds"] == 0


def test_metadata_log_torn_entry_regression(tmp_path):
    """Satellite: a truncated entry fails its checksum and reads as
    ABSENT; latest() skips the torn tail; legacy plain-JSON parses."""
    log = MetadataLog(str(tmp_path / "log"))
    log.add(0, {"a": 1})
    log.add(1, {"b": 2})
    p = tmp_path / "log" / "1"
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) // 2])           # torn mid-write
    assert log.get(1) is None
    assert log.latest() == (0, {"a": 1})
    (tmp_path / "log" / "2").write_text('{"c": 3}')   # legacy entry
    assert log.get(2) == {"c": 3}
    (tmp_path / "log" / "3").write_text('{"c": 3')    # torn legacy
    assert log.get(3) is None
    assert log.latest() == (2, {"c": 3})
