"""GraphX analog: Graph/aggregateMessages/Pregel + lib algorithms against
pure-python oracles (Pregel.scala:59, lib/PageRank.scala semantics)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_tpu.graphx import (
    Edge, Graph, connected_components, page_rank, pregel, shortest_paths,
    triangle_count,
)


@pytest.fixture(scope="module")
def g():
    rng = np.random.default_rng(3)
    n, m = 40, 160
    src = rng.integers(0, n, m)
    dst = rng.integers(0, n, m)
    keep = src != dst
    return Graph.from_edge_tuples(
        list(zip((src[keep] + 100).tolist(), (dst[keep] + 100).tolist())))


def _edges(g):
    vids = np.asarray(g.vertex_ids)
    return list(zip(vids[np.asarray(g.src)].tolist(),
                    vids[np.asarray(g.dst)].tolist()))


def test_construction_and_degrees(g):
    assert g.num_vertices <= 40 and g.num_edges > 100
    out_deg = np.asarray(g.out_degrees)
    exp = np.zeros(g.num_vertices, np.int64)
    vids = np.asarray(g.vertex_ids)
    for s, _d in _edges(g):
        exp[np.searchsorted(vids, s)] += 1
    np.testing.assert_array_equal(out_deg, exp)
    np.testing.assert_array_equal(
        np.asarray(g.degrees), np.asarray(g.in_degrees) + out_deg)


def test_from_edges_api():
    gr = Graph.from_edges([Edge(1, 2, 0.5), Edge(2, 3, 1.5)])
    assert gr.num_vertices == 3 and gr.num_edges == 2
    np.testing.assert_allclose(np.asarray(gr.edge_attrs["attr"]), [0.5, 1.5])


def test_aggregate_messages(g):
    """Sum of source out-degrees into each destination == oracle."""
    g2 = Graph(g.vertex_ids,
               {"deg": g.out_degrees.astype(jnp.float64)},
               g.src, g.dst, g.edge_attrs)
    got = np.asarray(g2.aggregate_messages(
        lambda s, d, e: s["deg"], merge="sum"))
    vids = np.asarray(g.vertex_ids)
    out_deg = np.asarray(g.out_degrees)
    exp = np.zeros(g.num_vertices)
    for s, d in _edges(g):
        exp[np.searchsorted(vids, d)] += out_deg[np.searchsorted(vids, s)]
    np.testing.assert_allclose(got, exp)


def test_page_rank_matches_oracle(g):
    got = np.asarray(page_rank(g, num_iter=30))
    # oracle: same GraphX-convention power iteration in numpy
    n = g.num_vertices
    vids = np.asarray(g.vertex_ids)
    out_deg = np.maximum(np.asarray(g.out_degrees), 1)
    ranks = np.ones(n)
    for _ in range(30):
        sums = np.zeros(n)
        for s, d in _edges(g):
            si, di = np.searchsorted(vids, s), np.searchsorted(vids, d)
            sums[di] += ranks[si] / out_deg[si]
        ranks = 0.15 + 0.85 * sums
    np.testing.assert_allclose(got, ranks, rtol=1e-10)


def test_connected_components():
    # two components + an isolated vertex
    gr = Graph.from_edge_tuples(
        [(1, 2), (2, 3), (10, 11), (11, 12), (12, 10)],
        vertex_attrs=None)
    cc = dict(zip(np.asarray(gr.vertex_ids).tolist(),
                  np.asarray(connected_components(gr)).tolist()))
    assert cc[1] == cc[2] == cc[3] == 1
    assert cc[10] == cc[11] == cc[12] == 10


def test_shortest_paths():
    gr = Graph.from_edge_tuples([(1, 2), (2, 3), (3, 4), (1, 5)])
    sp = shortest_paths(gr, [1])
    vids = np.asarray(gr.vertex_ids).tolist()
    d = dict(zip(vids, np.asarray(sp[1]).tolist()))
    assert (d[1], d[2], d[3], d[4], d[5]) == (0, 1, 2, 3, 1)


def test_triangle_count():
    gr = Graph.from_edge_tuples(
        [(1, 2), (2, 3), (3, 1), (3, 4), (4, 5), (5, 3)])
    tc = dict(zip(np.asarray(gr.vertex_ids).tolist(),
                  np.asarray(triangle_count(gr)).tolist()))
    assert (tc[1], tc[2], tc[3], tc[4], tc[5]) == (1, 1, 2, 1, 1)


def test_shortest_paths_isolated_vertex_unreachable():
    from spark_tpu.graphx.lib import UNREACHABLE
    gr = Graph([1, 2, 3], {}, [0], [1])   # vertex 3 isolated
    sp = shortest_paths(gr, [1])
    d = np.asarray(sp[1]).tolist()
    assert d == [0, 1, UNREACHABLE]


def test_pregel_initial_msg():
    """initial_msg runs vprog once for every vertex before superstep 1."""
    gr = Graph.from_edge_tuples([(1, 2)])
    out = pregel(
        gr, {"x": jnp.zeros(2, jnp.int64)},
        vprog=lambda a, m, h: {"x": jnp.where(h, a["x"] + m, a["x"])},
        send=lambda s, d, e: (s["x"], jnp.zeros_like(s["x"], bool)),
        merge="sum", max_iterations=3, initial_msg=7)
    assert np.asarray(out["x"]).tolist() == [7, 7]


def test_pregel_sssp():
    """Classic Pregel SSSP with explicit vprog/send/merge."""
    gr = Graph.from_edge_tuples([(1, 2), (2, 3), (3, 4), (1, 5), (5, 4)])
    n = gr.num_vertices
    vids = np.asarray(gr.vertex_ids)
    INF = np.iinfo(np.int64).max - 1
    init = np.full(n, INF, np.int64)
    init[np.searchsorted(vids, 1)] = 0

    def vprog(attrs, msgs, has_msg):
        return {"d": jnp.where(has_msg,
                               jnp.minimum(attrs["d"], msgs), attrs["d"])}

    def send(srcs, dsts, eattrs):
        cand = srcs["d"] + 1
        return cand, cand < dsts["d"]

    out = pregel(gr, {"d": init}, vprog, send, merge="min",
                 max_iterations=10)
    d = dict(zip(vids.tolist(), np.asarray(out["d"]).tolist()))
    assert (d[1], d[2], d[3], d[4], d[5]) == (0, 1, 2, 2, 1)


def test_to_dataframes(spark, g):
    v, e = g.to_dataframes(spark)
    assert v.count() == g.num_vertices
    assert e.count() == g.num_edges
