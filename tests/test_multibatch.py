"""Multi-batch (out-of-core) execution: streamed scans + cross-batch merge.

The stage-runner analog of FileScanRDD + ExternalSorter + AggUtils
partial/final (VERDICT r1 #2): datasets several times one batch capacity
must produce the same answers as the eager single-batch path / a pandas
oracle, with HBM holding only one batch at a time.
"""

import glob
import os

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F


BATCH = 256          # rows per streamed batch (tiny for tests)
N = 2000             # ~8 batches


def _pdf(seed=7):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "id": np.arange(N, dtype=np.int64),
        "grp": rng.choice(["apple", "pear", "plum", "fig", "kiwi"], N),
        "x": rng.normal(10.0, 5.0, N),
        "k": rng.integers(0, 50, N).astype(np.int64),
    })


@pytest.fixture(scope="module")
def bigfile(tmp_path_factory):
    """A parquet dataset written in several files (multi-file scan)."""
    d = tmp_path_factory.mktemp("mb") / "big.parquet"
    os.makedirs(d)
    pdf = _pdf()
    step = N // 4
    for i in range(4):
        pdf.iloc[i * step:(i + 1) * step].to_parquet(
            d / f"part-{i:03d}.parquet", index=False)
    return str(d), pdf


@pytest.fixture()
def mb(spark):
    """Session configured for streamed scans of BATCH rows."""
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(BATCH))
    yield spark
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))


def test_uses_multibatch_path(mb, bigfile):
    from spark_tpu.sql.multibatch import plan_multibatch
    from spark_tpu.sql.planner import QueryExecution
    path, _ = bigfile
    df = mb.read.parquet(path).groupBy("grp").agg(F.sum("x"))
    qe = QueryExecution(mb, df._plan)
    assert plan_multibatch(mb, qe.optimized) is not None


def test_groupby_agg_matches_pandas(mb, bigfile):
    path, pdf = bigfile
    df = (mb.read.parquet(path)
          .groupBy("grp")
          .agg(F.sum("x").alias("sx"), F.count("x").alias("c"),
               F.avg("k").alias("ak"), F.min("x").alias("mn"),
               F.max("x").alias("mx")))
    got = {r[0]: r[1:] for r in df.collect()}
    exp = pdf.groupby("grp").agg(
        sx=("x", "sum"), c=("x", "count"), ak=("k", "mean"),
        mn=("x", "min"), mx=("x", "max"))
    assert set(got) == set(exp.index)
    for g, row in exp.iterrows():
        np.testing.assert_allclose(got[g], row.to_numpy(), rtol=1e-12)


def test_global_agg_no_keys(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).agg(
        F.sum("k").alias("s"), F.count("x").alias("c"),
        F.min("id").alias("mn"))
    (s, c, mn), = df.collect()
    assert (s, c, mn) == (int(pdf.k.sum()), N, 0)


def test_string_min_max(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).groupBy("k").agg(
        F.min("grp").alias("mn"), F.max("grp").alias("mx"))
    got = {r[0]: (r[1], r[2]) for r in df.collect()}
    exp = pdf.groupby("k").agg(mn=("grp", "min"), mx=("grp", "max"))
    assert got == {k: (r.mn, r.mx) for k, r in exp.iterrows()}


def test_filter_project_concat(mb, bigfile):
    path, pdf = bigfile
    df = (mb.read.parquet(path)
          .filter(F.col("k") < 10)
          .select("id", (F.col("x") * 2).alias("x2")))
    got = sorted(df.collect())
    sub = pdf[pdf.k < 10]
    exp = sorted(zip(sub.id.tolist(), (sub.x * 2).tolist()))
    assert [i for i, _ in got] == [i for i, _ in exp]
    np.testing.assert_allclose([v for _, v in got], [v for _, v in exp])


def test_sort_matches_pandas(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).orderBy(F.col("x").desc())
    got = [r[0] for r in df.select("id").orderBy(F.col("x").desc()).collect()]
    exp = pdf.sort_values("x", ascending=False).id.tolist()
    assert got == exp


def test_topk_order_by_limit(mb, bigfile):
    path, pdf = bigfile
    df = mb.read.parquet(path).orderBy(F.col("x").desc()).limit(17)
    got = [(r[0], r[3]) for r in df.collect()]
    exp = pdf.sort_values("x", ascending=False).head(17)
    assert [i for i, _ in got] == exp.id.tolist()


def test_distinct(mb, bigfile):
    path, pdf = bigfile
    got = sorted(r[0] for r in
                 mb.read.parquet(path).select("grp").distinct().collect())
    assert got == sorted(pdf.grp.unique())


def test_limit_early_exit(mb, bigfile):
    path, _ = bigfile
    assert len(mb.read.parquet(path).limit(40).collect()) == 40


def test_ops_above_breaker(mb, bigfile):
    """HAVING-style filter + order + limit above the aggregation."""
    path, pdf = bigfile
    df = (mb.read.parquet(path)
          .groupBy("k").agg(F.sum("x").alias("sx"))
          .filter(F.col("sx") > 0)
          .orderBy(F.col("sx").desc())
          .limit(5))
    got = [(r[0], r[1]) for r in df.collect()]
    exp = (pdf.groupby("k").x.sum().reset_index()
           .query("x > 0").sort_values("x", ascending=False).head(5))
    assert [k for k, _ in got] == exp.k.tolist()
    np.testing.assert_allclose([v for _, v in got], exp.x.tolist())


def test_matches_eager_path(mb, bigfile):
    path, _ = bigfile
    q = lambda s: (s.read.parquet(path).filter(F.col("k") % 3 == 0)
                   .groupBy("grp").agg(F.avg("x").alias("a"),
                                       F.count("id").alias("c")))
    multi = sorted(q(mb).collect())
    mb.conf.set(C.MULTIBATCH_ENABLED.key, "false")
    try:
        eager = sorted(q(mb).collect())
    finally:
        mb.conf.set(C.MULTIBATCH_ENABLED.key, "true")
    assert [r[0] for r in multi] == [r[0] for r in eager]
    np.testing.assert_allclose(
        np.array([r[1:] for r in multi], float),
        np.array([r[1:] for r in eager], float), rtol=1e-12)


def test_disk_spill(mb, bigfile, tmp_path):
    """Force the sorted-run accumulator over its host budget: runs must
    spill to disk and the merged result stay exact."""
    path, pdf = bigfile
    spill_dir = str(tmp_path / "spill")
    mb.conf.set(C.SPILL_MEMORY_ROWS.key, str(BATCH))
    mb.conf.set(C.SPILL_DIR.key, spill_dir)
    try:
        df = mb.read.parquet(path).orderBy("x")
        got = [r[0] for r in df.select("id").orderBy("x").collect()]
    finally:
        mb.conf.set(C.SPILL_MEMORY_ROWS.key,
                    str(C.SPILL_MEMORY_ROWS.default))
        mb.conf.set(C.SPILL_DIR.key, "")
    assert got == pdf.sort_values("x").id.tolist()
    assert not glob.glob(os.path.join(spill_dir, "*.spill"))  # cleaned up


def test_aggregation_fold_small_threshold(mb, bigfile):
    """Intermediate partial folds triggered every batch stay exact."""
    path, pdf = bigfile
    mb.conf.set(C.AGG_FOLD_ROWS.key, "8")
    try:
        df = mb.read.parquet(path).groupBy("grp").agg(
            F.sum("k").alias("s"))
        got = dict(df.collect())
    finally:
        mb.conf.set(C.AGG_FOLD_ROWS.key, str(C.AGG_FOLD_ROWS.default))
    exp = pdf.groupby("grp").k.sum()
    assert got == exp.to_dict()


def test_count_rows_csv_scan(mb, tmp_path):
    """Non-parquet formats stream via host-cached slices."""
    p = str(tmp_path / "big.csv")
    pdf = _pdf(11)
    df = mb.createDataFrame(pdf)
    df.write.option("header", True).csv(p)
    back = mb.read.csv(p, header=True, inferSchema=True)
    assert back.count() == N
    got = dict(back.groupBy("grp").agg(F.count("id").alias("c")).collect())
    assert got == pdf.groupby("grp").id.count().to_dict()


def test_multibatch_checkpoint_resume(tmp_path, spark):
    """Fault tolerance: a rerun over the same files resumes from the
    checkpointed merger + cursor instead of rescanning from batch 0."""
    import numpy as np
    from spark_tpu.sql import functions as F
    from spark_tpu.sql import multibatch as MB

    rng = np.random.default_rng(9)
    n = 4000
    import pandas as pd
    pdf = pd.DataFrame({
        "k": rng.integers(0, 8, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)})
    data_dir = str(tmp_path / "data")
    spark.createDataFrame(pdf).write.parquet(data_dir)

    ckpt_dir = str(tmp_path / "ckpt")
    spark.conf.set("spark.tpu.multibatch.checkpointDir", ckpt_dir)
    spark.conf.set("spark.tpu.multibatch.enabled", "true")
    spark.conf.set("spark.tpu.scan.maxBatchRows", "256")   # many batches
    spark.conf.set("spark.tpu.multibatch.checkpointInterval", "3")
    try:
        df = spark.read.parquet(data_dir)
        q = df.groupBy("k").agg(F.sum("v").alias("s"))
        expect = {int(k): int(s) for k, s in
                  pdf.groupby("k")["v"].sum().items()}

        # run once fully: leaves no checkpoint behind
        rows = {r["k"]: r["s"] for r in q.collect()}
        assert rows == expect
        import os
        assert not [f for f in (os.listdir(ckpt_dir)
                                if os.path.isdir(ckpt_dir) else [])
                    if f.endswith(".ckpt")]

        # simulate a crash: abort after 5 batches (checkpoint lands at 3)
        from spark_tpu.sql.planner import QueryExecution

        class _Crash(Exception):
            pass

        mb = MB.plan_multibatch(
            spark, QueryExecution(spark, q._plan).optimized)
        assert mb is not None
        real_save = mb._ckpt_save
        calls = {"n": 0}

        def crashing_save(path, n_batches, merger):
            real_save(path, n_batches, merger)
            calls["n"] += 1
            if calls["n"] == 1:
                raise _Crash()

        mb._ckpt_save = crashing_save
        import pytest as _pytest
        with _pytest.raises(_Crash):
            mb.execute()
        import os
        assert [f for f in os.listdir(ckpt_dir) if f.endswith(".ckpt")]

        # fresh execution RESUMES: merger.add must run fewer batches than
        # a full scan (the first 3 are replayed from the checkpoint)
        mb2 = MB.plan_multibatch(
            spark, QueryExecution(spark, q._plan).optimized)
        adds = {"n": 0}
        orig_make = mb2._make_merger

        def counting_make(*a, **k):
            merger = orig_make(*a, **k)
            orig_add = merger.add

            def add(batch):
                adds["n"] += 1
                return orig_add(batch)

            merger.add = add
            return merger

        mb2._make_merger = counting_make
        rows2 = {r[0]: r[1] for r in mb2.execute().to_pylist()}
        assert rows2 == expect
        total_batches = -(-n // 256)
        # resumed merger came from the checkpoint, so counting_make never
        # ran OR ran with fewer adds than a full scan
        assert adds["n"] <= total_batches - 3
    finally:
        spark.conf.unset("spark.tpu.multibatch.checkpointDir")
        spark.conf.unset("spark.tpu.scan.maxBatchRows")
        spark.conf.unset("spark.tpu.multibatch.checkpointInterval")
        spark.conf.unset("spark.tpu.multibatch.enabled")


def test_multibatch_rejects_collect_and_percentile(tmp_path, spark):
    """collect/percentile have no mergeable partial form; big file scans
    must take the eager path, not crash in DPartialAggregate."""
    import numpy as np
    import pandas as pd
    from spark_tpu.sql import functions as F
    pdf = pd.DataFrame({"k": np.arange(600, dtype=np.int64) % 5,
                        "v": np.arange(600, dtype=np.int64)})
    path = str(tmp_path / "p")
    spark.createDataFrame(pdf).write.parquet(path)
    spark.conf.set("spark.tpu.multibatch.enabled", "true")
    spark.conf.set("spark.tpu.scan.maxBatchRows", "100")
    try:
        df = spark.read.parquet(path)
        got = {r["k"]: r["p"] for r in df.groupBy("k").agg(
            F.percentile_approx("v", 0.5).alias("p")).collect()}
        exp = {int(k): int(g["v"].sort_values().iloc[(len(g) - 1) // 2])
               for k, g in pdf.groupby("k")}
        assert got == exp
        lst = df.groupBy("k").agg(F.collect_set("v").alias("s")).collect()
        assert all(len(r["s"]) == 120 for r in lst)
    finally:
        spark.conf.unset("spark.tpu.multibatch.enabled")
        spark.conf.unset("spark.tpu.scan.maxBatchRows")
