"""Golden-file SQL tests (`SQLQueryTestSuite.scala:82` analog).

Each `tests/golden/*.sql` holds semicolon-separated statements; the
expected output lives beside it as `<name>.sql.out` (one block per
statement: the query, then schema + sorted result rows).  Regenerate
after intended changes with:

    python -m tests.test_golden --regen
"""
import os
import sys

import numpy as np
import pytest

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def _statements(path):
    from spark_tpu.cli import split_sql_statements
    with open(path) as f:
        return split_sql_statements(f.read())


def _register_views(spark):
    import pandas as pd
    rng = np.random.default_rng(7)
    t1 = pd.DataFrame({"k": rng.integers(0, 5, 40).astype(np.int64),
                       "v": rng.integers(0, 20, 40).astype(np.int64)})
    t2 = pd.DataFrame({"k": np.arange(3, 8, dtype=np.int64),
                       "w": np.arange(100, 105, dtype=np.int64)})
    spark.createDataFrame(t1).createOrReplaceTempView("t1")
    spark.createDataFrame(t2).createOrReplaceTempView("t2")


def _run_statement(spark, sql):
    df = spark.sql(sql)
    schema = df.schema.simpleString()
    rows = sorted(tuple(r) for r in df.collect())
    lines = [f"-- query\n{sql}", f"-- schema\n{schema}", "-- rows"]
    for r in rows:
        lines.append(repr(tuple(r)))
    return "\n".join(lines)


def _render(spark, path):
    return "\n\n".join(_run_statement(spark, s)
                       for s in _statements(path)) + "\n"


def _files():
    return sorted(f for f in os.listdir(GOLDEN_DIR) if f.endswith(".sql"))


@pytest.mark.parametrize("name", _files())
def test_golden(spark, name):
    _register_views(spark)
    path = os.path.join(GOLDEN_DIR, name)
    expected_path = path + ".out"
    got = _render(spark, path)
    assert os.path.exists(expected_path), \
        f"missing golden output {expected_path}; regenerate with " \
        f"python -m tests.test_golden --regen"
    with open(expected_path) as f:
        expected = f.read()
    assert got == expected, f"golden mismatch for {name}"


def main():
    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_tpu.sql.session import SparkSession
    spark = SparkSession()
    _register_views(spark)
    for name in _files():
        path = os.path.join(GOLDEN_DIR, name)
        with open(path + ".out", "w") as f:
            f.write(_render(spark, path))
        print("wrote", path + ".out")


if __name__ == "__main__" and "--regen" in sys.argv:
    main()
