"""Operator kernel tests: numpy path vs pandas oracle, plus one fused jit
pipeline cross-check (filter → project → group-agg in a single XLA program)."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from spark_tpu import types as T
from spark_tpu.aggregates import (
    Avg, Count, CountStar, First, Last, Max, Min, StddevSamp, Sum, VarSamp,
)
from spark_tpu.columnar import ColumnBatch
from spark_tpu.expressions import Col, col, lit
from spark_tpu.kernels import (
    apply_filter, apply_limit, apply_project, compact, distinct,
    grouped_aggregate, sort_batch, union_all,
)


def make_batch(n=20, seed=0):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 4, n)
    keys = np.array(["a", "b", "c", "d"])[k]
    vals = rng.normal(size=n) * 10
    nulls = rng.random(n) < 0.25
    v2 = [None if nulls[i] else int(rng.integers(0, 100)) for i in range(n)]
    return ColumnBatch.from_arrays({
        "k": list(keys), "v": vals, "c": v2,
        "i": rng.integers(-50, 50, n).astype(np.int64),
    }), pd.DataFrame({"k": keys, "v": vals,
                      "c": [np.nan if x is None else x for x in v2],
                      "i": np.arange(0)[0:0] if False else rng.integers(0, 0, 0)}) if False else None


def to_df(batch):
    return batch.to_pandas()


def test_filter_then_compact():
    b = ColumnBatch.from_arrays({"x": np.arange(10, dtype=np.int64)})
    f = apply_filter(np, b, (col("x") % 2) == 0)
    assert int(np.asarray(f.num_rows())) == 5
    c = compact(np, f)
    assert c.to_pylist()[:5] == [(0,), (2,), (4,), (6,), (8,)]
    # compaction preserved mask count
    assert int(np.asarray(c.num_rows())) == 5


def test_filter_null_pred_drops():
    b = ColumnBatch.from_arrays({"x": [1, None, 3]})
    f = apply_filter(np, b, col("x") > 0)
    assert [r[0] for r in compact(np, f).to_pylist()] == [1, 3]


def test_project():
    b = ColumnBatch.from_arrays({"x": np.arange(5, dtype=np.int64)})
    p = apply_project(np, b, [(col("x") * 2).children and (col("x") * 2), lit(7)])
    rows = p.to_pylist()
    assert rows[0] == (0, 7) and rows[4] == (8, 7)


def test_limit():
    b = ColumnBatch.from_arrays({"x": np.arange(10, dtype=np.int64)})
    f = apply_filter(np, b, col("x") >= 4)
    l = apply_limit(np, f, 3)
    assert [r[0] for r in compact(np, l).to_pylist()] == [4, 5, 6]


def test_sort_asc_desc_nulls():
    b = ColumnBatch.from_arrays({"x": [3, None, 1, None, 2], "y": [1, 2, 3, 4, 5]})
    vec = b.column("x")
    s = sort_batch(np, b, [(vec.data, vec.valid, T.int32, True, True)])
    assert [r[0] for r in s.to_pylist()] == [None, None, 1, 2, 3]
    s2 = sort_batch(np, b, [(vec.data, vec.valid, T.int32, False, False)])
    assert [r[0] for r in s2.to_pylist()] == [3, 2, 1, None, None]


def test_sort_multi_key_stable():
    b = ColumnBatch.from_arrays({
        "a": [1, 2, 1, 2, 1], "b": [9, 8, 7, 6, 5]})
    va, vb = b.column("a"), b.column("b")
    s = sort_batch(np, b, [(va.data, va.valid, T.int32, True, True),
                           (vb.data, vb.valid, T.int32, False, True)])
    assert [r for r in s.to_pylist()] == [(1, 9), (1, 7), (1, 5), (2, 8), (2, 6)]


def test_sort_strings_and_floats():
    b = ColumnBatch.from_arrays({"s": ["pear", "fig", "apple"], "f": [2.5, -1.0, 3.5]})
    vs = b.column("s")
    s = sort_batch(np, b, [(vs.data, vs.valid, T.string, True, True)])
    assert [r[0] for r in s.to_pylist()] == ["apple", "fig", "pear"]
    vf = b.column("f")
    s2 = sort_batch(np, b, [(vf.data, vf.valid, T.float64, False, True)])
    assert [r[1] for r in s2.to_pylist()] == [3.5, 2.5, -1.0]


def agg_oracle(df, group, aggs):
    """pandas oracle for grouped aggregation."""
    g = df.groupby(group, dropna=False)
    out = g.agg(**aggs).reset_index()
    return out.sort_values(group).reset_index(drop=True)


def test_grouped_aggregate_against_pandas():
    rng = np.random.default_rng(7)
    n = 50
    keys = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    vals = rng.normal(size=n) * 10
    batch = ColumnBatch.from_arrays({"k": list(keys), "v": vals})
    out = grouped_aggregate(np, batch, [Col("k")], [
        (Sum(Col("v")), "sum_v"), (Count(Col("v")), "n"),
        (Avg(Col("v")), "avg_v"), (Min(Col("v")), "min_v"),
        (Max(Col("v")), "max_v"), (VarSamp(Col("v")), "var_v"),
    ])
    got = compact(np, out).to_pandas().sort_values("k").reset_index(drop=True)
    df = pd.DataFrame({"k": keys, "v": vals})
    exp = agg_oracle(df, "k", dict(
        sum_v=("v", "sum"), n=("v", "count"), avg_v=("v", "mean"),
        min_v=("v", "min"), max_v=("v", "max"), var_v=("v", "var")))
    assert got["k"].tolist() == exp["k"].tolist()
    for c_ in ["sum_v", "avg_v", "min_v", "max_v", "var_v"]:
        np.testing.assert_allclose(got[c_].to_numpy(), exp[c_].to_numpy(), rtol=1e-10)
    np.testing.assert_array_equal(got["n"].to_numpy(), exp["n"].to_numpy())


def test_grouped_aggregate_null_keys_and_values():
    batch = ColumnBatch.from_arrays({
        "k": ["x", None, "x", None, "y"],
        "v": [1, 2, None, 4, 5],
    })
    out = grouped_aggregate(np, batch, [Col("k")], [
        (Sum(Col("v")), "s"), (Count(Col("v")), "n"), (CountStar(), "all")])
    rows = sorted(compact(np, out).to_pylist(),
                  key=lambda r: (r[0] is None, r[0] or ""))
    # NULL key forms its own group (SQL GROUP BY semantics)
    assert rows == [("x", 1, 1, 2), ("y", 5, 1, 1), (None, 6, 2, 2)]


def test_global_aggregate_no_keys():
    batch = ColumnBatch.from_arrays({"v": [1.0, 2.0, 3.0, 4.0]})
    f = apply_filter(np, batch, col("v") > 1.5)
    out = grouped_aggregate(np, f, [], [(Sum(Col("v")), "s"), (CountStar(), "n")])
    assert compact(np, out).to_pylist() == [(9.0, 3)]


def test_global_aggregate_empty_input():
    batch = ColumnBatch.from_arrays({"v": [1.0, 2.0]})
    f = apply_filter(np, batch, col("v") > 100)
    out = grouped_aggregate(np, f, [], [(Sum(Col("v")), "s"), (CountStar(), "n"),
                                        (Min(Col("v")), "m")])
    assert compact(np, out).to_pylist() == [(None, 0, None)]


def test_first_last():
    batch = ColumnBatch.from_arrays({
        "k": ["a", "a", "b", "b", "b"],
        "v": [None, 10, 20, None, 30],
    })
    out = grouped_aggregate(np, batch, [Col("k")], [
        (First(Col("v")), "f"), (Last(Col("v")), "l")])
    rows = sorted(compact(np, out).to_pylist())
    assert rows == [("a", 10, 10), ("b", 20, 30)]


def test_min_max_strings():
    batch = ColumnBatch.from_arrays({
        "k": [1, 1, 2], "s": ["pear", "apple", "fig"]})
    out = grouped_aggregate(np, batch, [Col("k")], [
        (Min(Col("s")), "lo"), (Max(Col("s")), "hi")])
    rows = sorted(compact(np, out).to_pylist())
    assert rows == [(1, "apple", "pear"), (2, "fig", "fig")]


def test_distinct():
    batch = ColumnBatch.from_arrays({
        "a": [1, 1, 2, 2, 1], "b": ["x", "x", "y", "y", "z"]})
    out = compact(np, distinct(np, batch))
    assert sorted(out.to_pylist()) == [(1, "x"), (1, "z"), (2, "y")]


def test_union_all_merges_dictionaries():
    b1 = ColumnBatch.from_arrays({"s": ["b", "a"], "x": [1, 2]})
    b2 = ColumnBatch.from_arrays({"s": ["c", "a", None], "x": [3, 4, 5]})
    u = union_all([b1, b2])
    rows = compact(np, u).to_pylist()
    assert rows == [("b", 1), ("a", 2), ("c", 3), ("a", 4), (None, 5)]
    assert u.column("s").dictionary == ("a", "b", "c")


def test_fused_pipeline_jit_matches_numpy():
    """filter → project → group agg fused under ONE jit — WholeStageCodegen."""
    rng = np.random.default_rng(3)
    n = 64
    keys = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    vals = rng.normal(size=n)
    batch = ColumnBatch.from_arrays({"k": list(keys), "v": vals})

    def pipeline(xp, b):
        f = apply_filter(xp, b, col("v") > 0)
        p = apply_project(xp, f, [Col("k"), (col("v") * 2).children and (col("v") * 2)])
        # rename: projected expr name is the repr; use Col on it via index
        p.names = ["k", "v2"]
        return grouped_aggregate(xp, p, [Col("k")], [
            (Sum(Col("v2")), "s"), (CountStar(), "n"), (Max(Col("v2")), "mx")])

    ref = compact(np, pipeline(np, batch.to_host()))

    jitted = jax.jit(lambda b: pipeline(jnp, b))
    out = compact(np, jitted(batch.to_device()).to_host())
    rref = sorted(ref.to_pylist())
    rout = sorted(out.to_pylist())
    assert len(rref) == len(rout)
    for a, b2 in zip(rref, rout):
        assert a[0] == b2[0]
        np.testing.assert_allclose(a[1], b2[1], rtol=1e-12)
        assert a[2] == b2[2]
        np.testing.assert_allclose(a[3], b2[3], rtol=1e-12)


def test_sort_jit_matches_numpy():
    rng = np.random.default_rng(5)
    vals = rng.normal(size=32)
    nulls = rng.random(32) < 0.2
    b = ColumnBatch.from_arrays({"v": [None if nulls[i] else vals[i] for i in range(32)],
                                 "i": np.arange(32, dtype=np.int64)})

    def do_sort(xp, bt):
        vec = bt.column("v")
        return sort_batch(xp, bt, [(vec.data, vec.valid, T.float64, True, False)])

    ref = do_sort(np, b.to_host()).to_pylist()
    out = jax.jit(lambda bt: do_sort(jnp, bt))(b.to_device()).to_host().to_pylist()
    assert ref == out


def test_keyless_agg_capacity_zero():
    """Keyless aggregation over a capacity-0 batch (empty streamed
    source): the no-sort global path must behave like segment_reduce did
    — shape-(0,) buffers, one all-NULL/zero output row after finish."""
    import numpy as np
    from spark_tpu import types as T
    from spark_tpu.aggregates import Min, Sum, CountStar
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.expressions import Col
    from spark_tpu.kernels import grouped_aggregate
    empty = ColumnBatch(
        ["v"], [ColumnVector(np.zeros(0, np.int64), T.int64, None, None)],
        np.zeros(0, bool), 0)
    out = grouped_aggregate(np, empty, [],
                            [(Sum(Col("v")), "s"), (Min(Col("v")), "m"),
                             (CountStar(), "c")])
    assert out.capacity == 1
    assert int(np.asarray(out.column("c").data)[0]) == 0
    sv = out.column("s")
    assert sv.valid is not None and not bool(np.asarray(sv.valid)[0])


def test_keyless_first_last_capacity_zero():
    """Keyless first/last partials over a capacity-0 batch (empty shard
    slice) must not crash in the global reduce path."""
    import numpy as np
    from spark_tpu import types as T
    from spark_tpu.aggregates import First
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.expressions import Col
    from spark_tpu.parallel.dist import DPartialAggregate
    from spark_tpu.sql import physical as P

    class _Leaf(P.PhysicalPlan):
        def __init__(self, b):
            self.b = b
            self.children = ()

        def run(self, ctx):
            return self.b

    empty = ColumnBatch(
        ["v"], [ColumnVector(np.zeros(0, np.int64), T.int64, None, None)],
        np.zeros(0, bool), 0)
    node = DPartialAggregate([], [(First(Col("v")), "f")], _Leaf(empty))
    out = node.run(P.ExecContext(np, []))
    assert out.capacity == 0


def test_compact_jax_path_matches_numpy():
    """The DEVICE compact (single-operand bit-packed uint32 sort) must
    agree row-for-row with the numpy reference, including all-dead,
    all-live and interleaved masks."""
    import numpy as np
    import jax.numpy as jnp
    from spark_tpu import types as T
    from spark_tpu.columnar import ColumnBatch, ColumnVector
    from spark_tpu.kernels import compact
    rng = np.random.default_rng(13)
    for mask in (rng.random(257) < 0.4,
                 np.zeros(257, bool),
                 np.ones(257, bool)):
        data = rng.integers(0, 1000, 257).astype(np.int64)
        valid = rng.random(257) < 0.9
        b = ColumnBatch(["x"],
                        [ColumnVector(data, T.int64, valid, None)],
                        mask.copy(), 257)
        ref = compact(np, b)
        dev = compact(jnp, ColumnBatch(
            ["x"], [ColumnVector(jnp.asarray(data), T.int64,
                                 jnp.asarray(valid), None)],
            jnp.asarray(mask), 257))
        n = int(np.asarray(ref.num_rows()))
        assert int(np.asarray(dev.num_rows())) == n
        np.testing.assert_array_equal(
            np.asarray(dev.vectors[0].data)[:n],
            np.asarray(ref.vectors[0].data)[:n])
        np.testing.assert_array_equal(
            np.asarray(dev.vectors[0].valid)[:n],
            np.asarray(ref.vectors[0].valid)[:n])
        np.testing.assert_array_equal(
            np.asarray(dev.row_valid_or_true())[:n],
            np.asarray(ref.row_valid_or_true())[:n])


def test_radix_argsort_matches_lax_sort():
    """Stable LSD radix argsort (the TPU sort-lane candidate): exact
    permutation equality with the stable reference argsort across sign,
    duplicates, and extremes."""
    import jax.numpy as jnp
    from spark_tpu.kernels import radix_argsort
    rng = np.random.default_rng(3)
    for n in (1, 7, 1024, 5000):
        xs = rng.integers(np.iinfo(np.int64).min, np.iinfo(np.int64).max,
                          n, dtype=np.int64)
        xs[rng.integers(0, n, n // 3 or 1)] = 42       # duplicates
        got = np.asarray(radix_argsort(jnp, jnp.asarray(xs)))
        exp = np.argsort(xs, kind="stable")
        np.testing.assert_array_equal(got, exp)
    # numpy lane
    xs = np.array([3, -1, 3, np.iinfo(np.int64).min,
                   np.iinfo(np.int64).max, 0], np.int64)
    np.testing.assert_array_equal(
        np.asarray(radix_argsort(np, xs)), np.argsort(xs, kind="stable"))


def test_partition_bucket_numpy_oracle():
    from spark_tpu.kernels import partition_bucket, slice_rows
    rng = np.random.default_rng(9)
    cap, n_parts = 64, 5
    vals = rng.integers(-100, 100, cap).astype(np.int64)
    rv = rng.random(cap) < 0.6
    pids = rng.integers(0, n_parts, cap).astype(np.int32)
    b = ColumnBatch.from_arrays({"v": vals})
    b = ColumnBatch(b.names, b.vectors, rv, b.capacity)
    bucketed, off, cnt = partition_bucket(np, b, pids, n_parts)
    off, cnt = np.asarray(off), np.asarray(cnt)
    assert cnt.sum() == rv.sum()
    assert off[0] == 0
    np.testing.assert_array_equal(off[1:], np.cumsum(cnt)[:-1])
    data = np.asarray(bucketed.vectors[0].data)
    for p in range(n_parts):
        # partition p's window holds exactly the live rows routed to p,
        # in original order (stable sort)
        want = vals[rv & (pids == p)]
        got = data[off[p]: off[p] + cnt[p]]
        np.testing.assert_array_equal(got, want)
        sl = slice_rows(bucketed, int(off[p]), int(cnt[p]))
        assert sl.capacity == cnt[p] and sl.row_valid is None
        np.testing.assert_array_equal(np.asarray(sl.vectors[0].data), want)
    # everything past the live region is dead padding
    assert np.asarray(bucketed.row_valid)[: cnt.sum()].all()
    assert not np.asarray(bucketed.row_valid)[cnt.sum():].any()


def test_partition_bucket_jit_matches_numpy():
    from spark_tpu.kernels import partition_bucket
    rng = np.random.default_rng(11)
    cap, n_parts = 32, 4
    vals = rng.integers(0, 50, cap).astype(np.int64)
    rv = rng.random(cap) < 0.5
    pids = (vals % n_parts).astype(np.int32)
    host = ColumnBatch.from_arrays({"v": vals})
    host = ColumnBatch(host.names, host.vectors, rv, host.capacity)
    nb, noff, ncnt = partition_bucket(np, host, pids, n_parts)

    dev = host.to_device()
    f = jax.jit(lambda b, p: partition_bucket(jnp, b, p, n_parts))
    jb, joff, jcnt = f(dev, jnp.asarray(pids))
    np.testing.assert_array_equal(np.asarray(jcnt), np.asarray(ncnt))
    np.testing.assert_array_equal(np.asarray(joff), np.asarray(noff))
    live = int(np.asarray(ncnt).sum())
    np.testing.assert_array_equal(
        np.asarray(jb.vectors[0].data)[:live],
        np.asarray(nb.vectors[0].data)[:live])


def test_slice_rows_is_zero_copy_view():
    from spark_tpu.kernels import slice_rows
    b = ColumnBatch.from_arrays({"v": np.arange(16, dtype=np.int64)})
    sl = slice_rows(b, 4, 8)
    assert np.shares_memory(np.asarray(sl.vectors[0].data),
                            np.asarray(b.vectors[0].data))
    assert sl.capacity == 8
    np.testing.assert_array_equal(np.asarray(sl.vectors[0].data),
                                  np.arange(4, 12))


# ---------------------------------------------------------------------------
# remap_codes + code-space range_bucket (encoded execution)
# ---------------------------------------------------------------------------

def test_remap_codes_basic_and_dtype():
    from spark_tpu.kernels import remap_codes
    codes = np.array([0, 2, 1, 0], np.int32)
    table = np.array([3, 5, 9], np.int32)     # monotone merge remap
    out = remap_codes(np, codes, table)
    np.testing.assert_array_equal(out, [3, 9, 5, 3])
    assert out.dtype == np.int32


def test_remap_codes_preserves_null_and_oob_sentinels():
    from spark_tpu.kernels import remap_codes
    hi = np.iinfo(np.int32).max
    codes = np.array([-1, 0, hi, 1, -7], np.int32)
    out = remap_codes(np, codes, np.array([4, 6], np.int32))
    # negatives (NULL) pass through; >= len(table) folds to INT32_MAX
    np.testing.assert_array_equal(out, [-1, 4, hi, 6, -7])


def test_remap_codes_empty_inputs():
    from spark_tpu.kernels import remap_codes
    hi = np.iinfo(np.int32).max
    # empty codes
    out = remap_codes(np, np.zeros(0, np.int32), np.array([1], np.int32))
    assert out.shape == (0,) and out.dtype == np.int32
    # empty table: every non-negative code is out of range
    out = remap_codes(np, np.array([-1, 0, 3], np.int32),
                      np.zeros(0, np.int32))
    np.testing.assert_array_equal(out, [-1, hi, hi])


def test_remap_codes_jit_matches_numpy():
    from spark_tpu.kernels import remap_codes
    codes = np.array([2, -1, 0, 1, 2], np.int32)
    table = np.array([1, 4, 7], np.int32)
    want = remap_codes(np, codes, table)
    got = jax.jit(lambda c, t: remap_codes(jnp, c, t))(codes, table)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_union_all_identical_dictionaries_fast_path():
    # all senders share one dictionary: codes concatenate untouched
    words = ("a", "b")
    b1 = ColumnBatch.from_arrays({"s": ["b", "a"]})
    b2 = ColumnBatch.from_arrays({"s": ["a", "b"]})
    assert b1.column("s").dictionary == words
    u = union_all([b1, b2])
    assert u.column("s").dictionary == words
    rows = compact(np, u).to_pylist()
    assert rows == [("b",), ("a",), ("a",), ("b",)]


def test_range_bucket_code_space_matches_word_space():
    """Mapping shared cut WORDS into each local code space via
    searchsorted(dict, cut, "left") buckets a row by its WORD alone —
    identical spans across processes whose dictionaries differ."""
    from spark_tpu.kernels import range_bucket
    cuts_w = np.asarray(["dd", "mm"], object)          # shared word cuts
    dict_a = ("aa", "cc", "dd", "zz")                  # process A
    dict_b = ("bb", "dd", "ee", "mm", "qq")            # process B
    for kdict in (dict_a, dict_b):
        local_cuts = np.searchsorted(
            np.asarray(kdict, object), cuts_w, side="left").astype(np.int64)
        codes = np.arange(len(kdict), dtype=np.int64)
        spans = range_bucket(np, codes, local_cuts)
        want = [int(np.searchsorted(cuts_w, w, side="right"))
                for w in kdict]
        np.testing.assert_array_equal(spans, want)


def test_range_bucket_code_space_nonmember_and_empty_cuts():
    from spark_tpu.kernels import range_bucket
    kdict = ("ash", "oak")
    # cut word outside the local dictionary's range → all rows one side
    local_cuts = np.searchsorted(np.asarray(kdict, object),
                                 np.asarray(["zzz"], object),
                                 side="left").astype(np.int64)
    spans = range_bucket(np, np.array([0, 1], np.int64), local_cuts)
    np.testing.assert_array_equal(spans, [0, 0])
    # zero cuts: the single span 0
    spans = range_bucket(np, np.array([0, 1], np.int64),
                         np.zeros(0, np.int64))
    np.testing.assert_array_equal(spans, [0, 0])


# ---------------------------------------------------------------------------
# run planes on device (ISSUE 20): segment-scan kernels vs dense oracle
# ---------------------------------------------------------------------------

def _plane_batch(heads, lengths, extra=None, device=True, pad_to=None):
    """A ColumnBatch whose 'ts' column is a run plane over the given run
    table, plus an optional dense int column 'v'."""
    from spark_tpu.columnar import PlaneColumnVector, RunColumnVector
    from spark_tpu.columnar import ColumnVector, pad_capacity
    heads = np.asarray(heads, np.int64)
    lengths = np.asarray(lengths, np.int64)
    cap = int(lengths.sum())
    rv = RunColumnVector(heads, lengths, T.int64)
    pv = PlaneColumnVector.from_runs(
        rv, pad_to or pad_capacity(len(heads)), device=device)
    names, vecs = ["ts"], [pv]
    if extra is not None:
        arr = np.asarray(extra, np.int64)
        assert arr.shape[0] == cap
        from spark_tpu.columnar import ColumnVector as CV
        data = jnp.asarray(arr) if device else arr
        names.append("v")
        vecs.append(CV(data, T.int64))
    return ColumnBatch(names, vecs, None, cap), np.repeat(heads, lengths)


def test_run_expand_matches_repeat_oracle():
    """The searchsorted-gather expansion decodes a zero-padded plane to
    exactly np.repeat(values, lengths) — including single-run, padded
    (zero-length) tails, and a full plane with no padding."""
    from spark_tpu.kernels import run_expand
    cases = [
        ([3, 1, 4, 1, 5], [2, 3, 1, 4, 2], 8),       # padded tail
        ([7], [12], 4),                              # single run
        ([5, 6, 7, 8], [1, 1, 1, 1], 4),             # capacity edge: full
        ([0, -3, 2], [5, 1, 10], 4),                 # negatives, long runs
    ]
    for heads, lens, plane_cap in cases:
        heads = np.asarray(heads, np.int64)
        lens = np.asarray(lens, np.int64)
        cap = int(lens.sum())
        pv = np.zeros(plane_cap, np.int64); pv[:len(heads)] = heads
        pl = np.zeros(plane_cap, np.int64); pl[:len(lens)] = lens
        oracle = np.repeat(heads, lens)
        np.testing.assert_array_equal(run_expand(np, pv, pl, cap), oracle)
        np.testing.assert_array_equal(
            np.asarray(run_expand(jnp, jnp.asarray(pv), jnp.asarray(pl),
                                  cap)), oracle)


def test_plane_filter_matches_dense_oracle_unexpanded():
    """A single-column predicate over a run plane filters by run HEAD —
    same surviving rows as the dense path, and the plane's dense form is
    never built (the data column crossed the stage compressed)."""
    from spark_tpu.columnar import unexpanded_plane
    b, dense = _plane_batch([4, 9, 2, 9, 7], [3, 1, 6, 2, 4])
    out = apply_filter(jnp, b, (col("ts") % 2) == 1)
    keep = np.asarray(out.row_valid_or_true())
    np.testing.assert_array_equal(keep, (dense % 2) == 1)
    assert unexpanded_plane(out.column("ts")) is not None, \
        "plane filter must not expand the data column"
    # and the filtered batch still aggregates exactly
    agg = grouped_aggregate(jnp, out, [], [(CountStar(), "c")])
    assert int(np.asarray(agg.column("c").data)[0]) == int(
        ((dense % 2) == 1).sum())


def test_plane_filter_empty_and_total_survivors():
    b, dense = _plane_batch([1, 2, 3], [4, 4, 4])
    none = apply_filter(jnp, b, col("ts") > 100)
    assert int(np.asarray(none.num_rows())) == 0
    all_ = apply_filter(jnp, b, col("ts") >= 0)
    assert int(np.asarray(all_.num_rows())) == dense.shape[0]


def test_plane_global_aggregate_matches_dense_oracle():
    """Keyless count/sum/min/max over a run plane reduce over
    run_values x run_lengths — value-exact against the dense oracle,
    plane never expanded."""
    from spark_tpu.columnar import unexpanded_plane
    b, dense = _plane_batch([11, -2, 40, 7], [5, 2, 9, 3])
    out = grouped_aggregate(jnp, b, [], [
        (CountStar(), "c"), (Count(col("ts")), "ct"),
        (Sum(col("ts")), "s"), (Min(col("ts")), "mn"),
        (Max(col("ts")), "mx")])
    assert unexpanded_plane(b.column("ts")) is not None
    got = {n: int(np.asarray(out.column(n).data)[0])
           for n in ("c", "ct", "s", "mn", "mx")}
    assert got == {"c": dense.shape[0], "ct": dense.shape[0],
                   "s": int(dense.sum()), "mn": int(dense.min()),
                   "mx": int(dense.max())}


def test_plane_global_aggregate_respects_row_mask():
    """With a dense row mask (a prior filter), the plane aggregate
    segments the LIVE mask per run — masked rows drop from count/sum and
    min/max, exactly as the dense path drops them."""
    b, dense = _plane_batch([11, -2, 40, 7], [5, 2, 9, 3])
    fb = apply_filter(jnp, b, col("ts") != 40)
    out = grouped_aggregate(jnp, fb, [], [
        (CountStar(), "c"), (Sum(col("ts")), "s"),
        (Min(col("ts")), "mn"), (Max(col("ts")), "mx")])
    live = dense[dense != 40]
    got = {n: int(np.asarray(out.column(n).data)[0])
           for n in ("c", "s", "mn", "mx")}
    assert got == {"c": live.shape[0], "s": int(live.sum()),
                   "mn": int(live.min()), "mx": int(live.max())}


def test_plane_global_aggregate_all_dead_is_null():
    """Zero surviving rows: sum/min/max come back NULL (valid false),
    count 0 — same null semantics as the dense keyless kernel."""
    b, _ = _plane_batch([1, 2], [4, 4])
    fb = apply_filter(jnp, b, col("ts") > 10)
    out = grouped_aggregate(jnp, fb, [], [
        (CountStar(), "c"), (Sum(col("ts")), "s"), (Min(col("ts")), "mn")])
    assert int(np.asarray(out.column("c").data)[0]) == 0
    for n in ("s", "mn"):
        v = out.column(n)
        assert v.valid is not None and not bool(np.asarray(v.valid)[0])


def test_plane_project_bare_col_stays_unexpanded():
    """SELECT of a bare plane column re-emits the plane itself; a
    computed expression over it expands in-trace (counted per trace in
    run_plane_expansions, never in runs_materialized)."""
    from spark_tpu import columnar as _col
    from spark_tpu.columnar import unexpanded_plane
    b, dense = _plane_batch([4, 9, 2], [3, 5, 8])
    p = apply_project(jnp, b, [col("ts")])
    assert unexpanded_plane(p.column("ts")) is not None
    before_host = _col.runs_materialized()
    before_exp = _col.run_plane_expansions()
    p2 = apply_project(jnp, b, [col("ts") * 2])
    np.testing.assert_array_equal(np.asarray(p2.vectors[0].data),
                                  dense * 2)
    assert _col.run_plane_expansions() == before_exp + 1
    assert _col.runs_materialized() == before_host, \
        "in-trace plane expansion must not charge the host counter"


def test_plane_capacity_edge_full_plane():
    """A run table that exactly fills its pad bucket (no zero padding at
    all) filters and aggregates exactly."""
    from spark_tpu.columnar import pad_capacity
    n = pad_capacity(6)
    heads = np.arange(n, dtype=np.int64)
    lens = np.full(n, 3, dtype=np.int64)
    b, dense = _plane_batch(heads, lens, pad_to=n)
    fb = apply_filter(jnp, b, col("ts") >= 2)
    out = grouped_aggregate(jnp, fb, [], [(Sum(col("ts")), "s")])
    assert int(np.asarray(out.column("s").data)[0]) == \
        int(dense[dense >= 2].sum())


def test_plane_kernels_jit_match_eager():
    """The segmented filter+aggregate composes under jax.jit with the
    plane riding the pytree: jitted result equals eager equals dense
    oracle."""
    b, dense = _plane_batch([5, 1, 8, 1], [7, 2, 4, 3])

    def prog(batch):
        fb = apply_filter(jnp, batch, col("ts") > 1)
        return grouped_aggregate(jnp, fb, [], [
            (CountStar(), "c"), (Sum(col("ts")), "s")])

    eager = prog(b)
    jitted = jax.jit(prog)(b)
    want_c = int((dense > 1).sum())
    want_s = int(dense[dense > 1].sum())
    for out in (eager, jitted):
        assert int(np.asarray(out.column("c").data)[0]) == want_c
        assert int(np.asarray(out.column("s").data)[0]) == want_s
