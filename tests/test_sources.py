"""Socket streaming source, kafka gating, DStream compat shim."""
import socket
import threading
import time

import pytest

from spark_tpu.expressions import AnalysisException
from spark_tpu.sql.session import SparkSession


def _serve_lines(lines, port_holder, stop_evt):
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port_holder.append(srv.getsockname()[1])
    conn, _ = srv.accept()
    for line in lines:
        conn.sendall(line.encode() + b"\n")
    stop_evt.wait(5)
    conn.close()
    srv.close()


def test_socket_source_reads_lines():
    spark = SparkSession()
    port_holder, stop_evt = [], threading.Event()
    th = threading.Thread(target=_serve_lines,
                          args=(["hello", "world"], port_holder, stop_evt),
                          daemon=True)
    th.start()
    for _ in range(100):
        if port_holder:
            break
        time.sleep(0.01)
    df = (spark.readStream.format("socket")
          .option("host", "127.0.0.1").option("port", port_holder[0]).load())
    q = (df.writeStream.format("memory").queryName("sock")
         .outputMode("append").start())
    try:
        deadline = time.time() + 5
        rows = []
        while time.time() < deadline:
            q.processAllAvailable()
            rows = spark.sql("SELECT * FROM sock").collect()
            if len(rows) >= 2:
                break
            time.sleep(0.05)
        assert sorted(r["value"] for r in rows) == ["hello", "world"]
    finally:
        stop_evt.set()
        q.stop()


def test_kafka_source_gated_with_clear_error():
    spark = SparkSession()
    with pytest.raises(AnalysisException, match="kafka"):
        spark.readStream.format("kafka").load()


def test_dstream_shim_socket_foreach():
    from spark_tpu.streaming.dstream import StreamingContext
    spark = SparkSession()
    port_holder, stop_evt = [], threading.Event()
    th = threading.Thread(target=_serve_lines,
                          args=(["a", "b", "c"], port_holder, stop_evt),
                          daemon=True)
    th.start()
    for _ in range(100):
        if port_holder:
            break
        time.sleep(0.01)
    ssc = StreamingContext(batchDuration=0.05)
    seen = []
    stream = ssc.socketTextStream("127.0.0.1", port_holder[0])
    stream.foreachRDD(lambda bdf: seen.extend(
        r["value"] for r in bdf.collect()))
    ssc.start()
    try:
        deadline = time.time() + 5
        while time.time() < deadline and len(seen) < 3:
            for q in ssc._queries:
                q.processAllAvailable()
            time.sleep(0.05)
        assert sorted(seen) == ["a", "b", "c"]
    finally:
        stop_evt.set()
        ssc.stop()
