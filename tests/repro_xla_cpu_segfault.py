"""Minimal ENGINE-FREE reproducer for the XLA:CPU executable-accumulation
segfault that tests/conftest.py works around (VERDICT r3 item 10).

Pure jax + numpy — no spark_tpu import.  Compiles N structurally distinct
XLA:CPU programs in one process, keeps every executable alive (exactly
what a long pytest session does through per-module jit caches), and runs
each once.  On the image this repo builds against, the process dies in
generated XLA:CPU code (SIGSEGV/SIGILL, no Python traceback) once enough
executables are alive; passing --clear-every K calls jax.clear_caches()
periodically and the same workload completes.

Usage:
    python tests/repro_xla_cpu_segfault.py [N] [--clear-every K]

Exit code 0 = survived; a signal death reproduces the bug.  This script
IS the upstream report artifact: nothing of this engine is involved, so
the fault lies in the XLA:CPU client's code handling, not in spark_tpu.
The engine-side mitigation (bounding live executables per module) lives
in tests/conftest.py and is therefore a WORKAROUND for an upstream
condition, not a mask over an engine bug.

CONFIRMED (2026-07-31, this image): rc=139 (SIGSEGV) after ~2,250 live
executables, immediately preceded by repeated

    execution_engine.cc:54] LLVM compilation error: Cannot allocate memory

from XLA:CPU's JIT engine — the generated-code allocation arena
exhausts, the failed compilation is not surfaced as a Python error, and
the next executable use faults.  Root cause: unhandled LLVM JIT
code-memory exhaustion in the XLA:CPU client under executable
accumulation.  The same workload with ``--clear-every 500`` completes.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def make_fn(i: int):
    """A structurally distinct program per i: distinct constants, shapes
    and op mixes defeat jit/executable dedup, like distinct query plans."""
    k = 2 + (i % 13)

    def fn(x):
        y = x.reshape(k, -1) * np.float32(i + 1)
        z = jnp.sort(y, axis=-1) + jnp.tanh(y).sum(axis=0)
        w = jnp.cumsum(z, axis=-1)[:, :: (1 + i % 3)]
        return w.sum() + jnp.argmax(z, axis=-1).astype(jnp.float32).sum()

    return fn


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() \
        else 4000
    clear_every = 0
    if "--clear-every" in sys.argv:
        clear_every = int(sys.argv[sys.argv.index("--clear-every") + 1])

    keep = []   # live executables, as a pytest session's caches keep them
    for i in range(n):
        size = (2 + (i % 13)) * (8 + i % 7) * 4
        x = jnp.arange(size, dtype=jnp.float32)
        jf = jax.jit(make_fn(i))
        _ = float(jf(x))           # compile + execute once
        keep.append(jf)
        if i and i % 250 == 0:
            print(f"[repro] {i} executables alive", flush=True)
        if clear_every and i % clear_every == 0:
            keep.clear()
            jax.clear_caches()
    print(f"[repro] survived {n} live executables")
    return 0


if __name__ == "__main__":
    sys.exit(main())
