"""Worker for the adaptive-execution parity and fault tests (not a test
module itself — launched as a subprocess by test_adaptive.py and
test_faults.py).

argv: <process_id> <n_processes> <shuffle_root> <mode> [timeout_s]

mode "adaptive": the full adaptive battery against a full-data oracle —
every scenario must match the oracle exactly AND take the path the
observed statistics dictate:

1. demote-to-broadcast (hash lane): both leaves exceed the broadcast
   threshold at plan time, but a selective filter (pushed below the
   join by the optimizer) shrinks one side's OBSERVED map output far
   under it — the stats barrier demotes the frozen hash plan to a
   broadcast before any data block ships (``adaptive_replans`` /
   ``strategy_demotions`` counters, no ``shuffled_joins`` bump);
2. stats-feedback second join: the SAME query again — the recorded
   observed cardinality now decides broadcast at PLAN time
   (``stats_feedback_hits``), gathering the side's executed output;
3. demote-to-broadcast (range lane): a differently-filtered query with
   sortMergeJoin on freezes to range, then demotes at the stats barrier
   (no ``range_merge_joins`` bump);
4. frozen comparison: a second session with adaptiveReplan=false runs
   scenario 1's query through the full hash exchange — same rows, zero
   demotions (adaptive == frozen == oracle);
5. post-sample skew re-split: a probe side whose ROW distribution is
   uniform (the sample round estimates uniform spans) but whose BYTES
   concentrate in one key's fat strings — the observed-size reducer
   plan splits the span the sample could not have flagged
   (``post_sample_skew_splits``);
6. partial-aggregate pushdown: a derived-table keyed aggregate below
   the join ships partial state through the hash exchange
   (``shuffled_joins`` bump) and matches both the oracle and the
   unpushed gather plan.

mode "fault-adapt": arm a FaultInjector from SPARK_TPU_FAULT_PLAN and
run ONE misestimated join (scenario 1's query; first query, so the
stats round is exchange ``xq000001-plan`` and a demotion gather would
be ``xq000001-bcast``).  Prints ``OK ...`` with the path counters when
the query completed (result must equal the oracle — never partial), or
``FAILED <elapsed> <lost>`` on a structured, bounded failure.

mode "trace": the replica-determinism parity run — one full hash
exchange plus one range exchange with the decision-trace runtime check
pinned ON; every process must produce oracle-identical rows and report
``decision_trace_checks > 0`` with ZERO divergence
(``[p<i>] TRACE-OK rows=... checks=... div=0``).

mode "skew-decision": same hash-lane query with a FaultInjector armed
from SPARK_TPU_FAULT_PLAN (the ``skew_decision`` kind): the armed
process's GATHERED view of the ``xq000001-plan`` round is perturbed
while the on-disk manifests stay byte-identical — its adaptive
re-decision diverges from its peers and ``verify_decision_trace`` must
abort it structured (``[p<i>] FAILED-DIVERGED ... prop=decision-trace-
agreement``), never letting a divergently-demoted exchange emit
partial rows; the unarmed peer fails BOUNDED at its data barrier.
"""

import os
import sys
import time

pid = int(sys.argv[1])
n = int(sys.argv[2])
root = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "adaptive"
timeout_s = float(sys.argv[5]) if len(sys.argv) > 5 else 45.0

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from spark_tpu import config as C  # noqa: E402
from spark_tpu.analysis.errors import PlanInvariantError  # noqa: E402
from spark_tpu.parallel.faults import FaultInjector  # noqa: E402
from spark_tpu.parallel.hostshuffle import ExchangeFetchFailed  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402

# Every process draws the SAME full dataset and keeps a strided 1/n
# slice.  fact and fact2 are both far above the broadcast threshold
# below (the plan-time probe sees raw LEAF bytes), but the battery's
# filters cut fact2 to a few dozen rows — the misestimation the
# adaptive stats barrier exists to catch.
rng = np.random.default_rng(11)
NF, NB = 1200, 900
f_sk = rng.integers(0, 48, NF).astype(np.int64)
f_price = rng.integers(1, 500, NF).astype(np.int64)
k2 = rng.integers(0, 48, NB).astype(np.int64)
bonus = rng.integers(0, 100, NB).astype(np.int64)

# skew tables: probe rows are UNIFORM per key (the row-weighted sample
# round estimates uniform spans) but key 3 carries fat unique strings,
# so the observed BYTES of its span dwarf the median — only the
# post-sample size round can see it
NS, NR = 600, 150
s_rk = (np.arange(NS) % 16).astype(np.int64)
s_t = np.array([(f"r{i:04d}" * 56) if s_rk[i] == 3 else f"s{i:04d}"
                for i in range(NS)], dtype=object)
r_rk2 = (np.arange(NR) % 16).astype(np.int64)
r_w2 = rng.integers(1, 50, NR).astype(np.int64)

mine = slice(pid, None, n)

session = SparkSession.builder.appName(f"adapt-{pid}").getOrCreate()


def make_session(shuffle_root, adaptive):
    xs = session.newSession()
    xs.conf.set(C.MESH_SHARDS.key, "1")
    svc = xs.enableHostShuffle(shuffle_root, process_id=pid,
                               n_processes=n, timeout_s=timeout_s)
    xs.conf.set(C.SHUFFLE_TARGET_PARTITION_BYTES.key, "2048")
    xs.conf.set(C.SHUFFLE_FINE_PARTITIONS.key, "32")
    xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "2048")
    xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
    xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
    xs.conf.set(C.CROSSPROC_ADAPTIVE_REPLAN.key,
                "true" if adaptive else "false")
    for name, data in (
            ("fact", {"sk": f_sk[mine], "price": f_price[mine]}),
            ("fact2", {"k2": k2[mine], "bonus": bonus[mine]}),
            ("skl", {"rk": s_rk[mine], "t": s_t[mine]}),
            ("skr", {"rk2": r_rk2[mine], "w2": r_w2[mine]})):
        xs.createDataFrame(data).createOrReplaceTempView(name)
    return xs, svc


oracle = session.newSession()
oracle.conf.set(C.MESH_SHARDS.key, "1")
for name, data in (("fact", {"sk": f_sk, "price": f_price}),
                   ("fact2", {"k2": k2, "bonus": bonus}),
                   ("skl", {"rk": s_rk, "t": s_t}),
                   ("skr", {"rk2": r_rk2, "w2": r_w2})):
    oracle.createDataFrame(data).createOrReplaceTempView(name)

# scenario 1/2: misestimated RIGHT side — the optimizer pushes the
# bonus filter below the join, so the observed map output is tiny while
# the plan-time leaf probe still sees all of fact2
Q_DEMOTE = ("SELECT sk, price, bonus FROM fact JOIN fact2 ON sk = k2 "
            "WHERE bonus < 2 ORDER BY sk, price, bonus")
# scenario 3: a different constant → a different plan signature, so the
# range lane freezes from the probe (no feedback shortcut) and the
# demotion happens at the stats barrier
Q_DEMOTE_R = ("SELECT sk, price, bonus FROM fact JOIN fact2 ON sk = k2 "
              "WHERE bonus < 3 ORDER BY sk, price, bonus")
Q_SKEW = ("SELECT rk, count(*) AS c, min(t) AS tlo, sum(w2) AS sw "
          "FROM skl JOIN skr ON rk = rk2 GROUP BY rk ORDER BY rk")
Q_AGG = ("SELECT sk, price, sb FROM fact JOIN "
         "(SELECT k2, sum(bonus) AS sb FROM fact2 GROUP BY k2) a "
         "ON sk = k2 ORDER BY sk, price, sb")
# trace/skew-decision modes: NO filter, so both observed sides stay far
# above the broadcast threshold and the adaptive re-decision keeps the
# frozen hash lane — the only way the armed process can diverge is the
# injected perturbation of its gathered stats view
Q_HASH = ("SELECT sk, price, bonus FROM fact JOIN fact2 ON sk = k2 "
          "ORDER BY sk, price, bonus")


def run(sess, sql):
    return [tuple(r) for r in sess.sql(sql).collect()]


def delta(svc, before):
    return {k: svc.counters[k] - before[k] for k in svc.counters}


if mode == "fault-adapt":
    xs, svc = make_session(root, adaptive=True)
    FaultInjector().attach(svc)       # plan comes from SPARK_TPU_FAULT_PLAN
    exp = run(oracle, Q_DEMOTE)
    t0 = time.time()
    try:
        got = run(xs, Q_DEMOTE)
    except (ExchangeFetchFailed, TimeoutError) as e:
        lost = sorted(getattr(e, "lost_hosts", []) or [])
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} {lost}", flush=True)
        os._exit(0)
    if got != exp:
        print(f"[p{pid}] PARTIAL got={len(got)} exp={len(exp)}", flush=True)
        os._exit(1)
    c = svc.counters
    print(f"[p{pid}] OK rows={len(got)} replans={c['adaptive_replans']} "
          f"demotions={c['strategy_demotions']} "
          f"bcast={c['broadcast_joins']} shuffled={c['shuffled_joins']}",
          flush=True)
    os._exit(0)

if mode in ("trace", "skew-decision"):
    xs, svc = make_session(root, adaptive=True)
    # the decision-trace backstop must run deterministically here,
    # pytest parent or not (bin/chaos launches this worker too)
    xs.conf.set(C.ANALYSIS_VERIFY_PLANS.key, "true")
    if mode == "skew-decision":
        FaultInjector().attach(svc)   # plan from SPARK_TPU_FAULT_PLAN
    exp = run(oracle, Q_HASH)
    t0 = time.time()
    try:
        got = run(xs, Q_HASH)
    except PlanInvariantError as e:
        st = getattr(xs, "_analysis_stats", {})
        print(f"[p{pid}] FAILED-DIVERGED {time.time() - t0:.2f} "
              f"prop={e.property} div="
              f"{st.get('decision_trace_divergence', 0)} detail={e}",
              flush=True)
        os._exit(0)
    except (ExchangeFetchFailed, TimeoutError) as e:
        lost = sorted(getattr(e, "lost_hosts", []) or [])
        print(f"[p{pid}] FAILED {time.time() - t0:.2f} {lost}",
              flush=True)
        os._exit(0)
    if got != exp:
        print(f"[p{pid}] PARTIAL got={len(got)} exp={len(exp)}",
              flush=True)
        os._exit(1)
    if mode == "trace":
        # the range lane's trace (cut points + skew-split estimate)
        # rides the same check: pin the range lane and run the skew join
        xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")
        xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "true")
        exp_s = run(oracle, Q_SKEW)
        got_s = run(xs, Q_SKEW)
        if got_s != exp_s:
            print(f"[p{pid}] PARTIAL got={len(got_s)} exp={len(exp_s)}",
                  flush=True)
            os._exit(1)
    st = getattr(xs, "_analysis_stats", {})
    print(f"[p{pid}] TRACE-OK rows={len(got)} "
          f"checks={st.get('decision_trace_checks', 0)} "
          f"div={st.get('decision_trace_divergence', 0)}", flush=True)
    os._exit(0)

xs, svc = make_session(root, adaptive=True)

# -- 1. hash lane demotes to broadcast at the stats barrier -----------------
exp = run(oracle, Q_DEMOTE)
before = dict(svc.counters)
got_adaptive = run(xs, Q_DEMOTE)
d = delta(svc, before)
assert got_adaptive == exp, (len(got_adaptive), len(exp))
assert d["adaptive_replans"] == 1, d
assert d["strategy_demotions"] == 1, d
assert d["broadcast_joins"] == 1 and d["shuffled_joins"] == 0, d
assert len(xs.statsFeedback) >= 2, xs.statsFeedback.snapshot()
print(f"[p{pid}] DEMOTE-OK ({len(exp)} rows)", flush=True)

# -- 2. the recorded cardinality decides broadcast at PLAN time -------------
before = dict(svc.counters)
assert run(xs, Q_DEMOTE) == exp
d = delta(svc, before)
assert d["stats_feedback_hits"] >= 1, d
assert d["broadcast_joins"] == 1 and d["shuffled_joins"] == 0, d
assert d["adaptive_replans"] == 0, d      # no exchange, no stats barrier
print(f"[p{pid}] FEEDBACK-OK ({len(exp)} rows)", flush=True)

# -- 3. range lane demotes too ----------------------------------------------
xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "true")
exp_r = run(oracle, Q_DEMOTE_R)
before = dict(svc.counters)
assert run(xs, Q_DEMOTE_R) == exp_r
d = delta(svc, before)
assert d["adaptive_replans"] == 1, d
assert d["strategy_demotions"] == 1, d
assert d["broadcast_joins"] == 1 and d["range_merge_joins"] == 0, d
xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
print(f"[p{pid}] RANGE-DEMOTE-OK ({len(exp_r)} rows)", flush=True)

# -- 4. frozen comparison: same query, adaptiveReplan off -------------------
fz, fsvc = make_session(root + "-frozen", adaptive=False)
before = dict(fsvc.counters)
got_frozen = run(fz, Q_DEMOTE)
d = delta(fsvc, before)
assert got_frozen == exp == got_adaptive
assert d["shuffled_joins"] == 1 and d["broadcast_joins"] == 0, d
assert d["adaptive_replans"] == 0 and d["strategy_demotions"] == 0, d
print(f"[p{pid}] FROZEN-OK ({len(got_frozen)} rows)", flush=True)

# -- 5. post-sample skew re-split -------------------------------------------
xs.conf.set(C.CROSSPROC_AUTO_BROADCAST.key, "0")   # pin the range lane
xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "true")
exp_s = run(oracle, Q_SKEW)
before = dict(svc.counters)
assert run(xs, Q_SKEW) == exp_s
d = delta(svc, before)
assert d["range_merge_joins"] == 1, d
assert d["spans_split"] >= 1, d
assert d["post_sample_skew_splits"] >= 1, d
xs.conf.set(C.CROSSPROC_SORT_MERGE_JOIN.key, "false")
print(f"[p{pid}] SKEW-OK ({len(exp_s)} rows)", flush=True)

# -- 6. partial aggregate pushdown below the join exchange ------------------
exp_a = run(oracle, Q_AGG)
before = dict(svc.counters)
got_pushed = run(xs, Q_AGG)
d = delta(svc, before)
assert got_pushed == exp_a, (len(got_pushed), len(exp_a))
assert d["shuffled_joins"] == 1, d
assert d["strategy_demotions"] == 0, d    # an agg side never demotes
xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "false")
got_unpushed = run(xs, Q_AGG)             # generic gather, same session
xs.conf.set(C.CROSSPROC_SHUFFLED_JOIN.key, "true")
assert got_unpushed == exp_a
print(f"[p{pid}] AGGPUSH-OK ({len(exp_a)} rows)", flush=True)

c = svc.counters
print(f"[p{pid}] ADAPT-OK replans={c['adaptive_replans']} "
      f"demotions={c['strategy_demotions']} "
      f"fbhits={c['stats_feedback_hits']} "
      f"postskew={c['post_sample_skew_splits']} "
      f"bcast={c['broadcast_joins']} shuffled={c['shuffled_joins']} "
      f"range={c['range_merge_joins']}", flush=True)
os._exit(0)
