"""Multi-process launcher (docs/DEPLOY.md; SparkSubmit/Master role on
jax.distributed — VERDICT r3 missing #8): local fan-out spawns N real
worker processes that join one cluster via the SPARK_TPU_* env contract
and run a cross-process collective."""

import os
import subprocess
import sys
import textwrap


def test_launch_fanout_two_workers(tmp_path):
    app = tmp_path / "app.py"
    app.write_text(textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=2"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import sys
        sys.path.insert(0, %r)
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec
        from spark_tpu.parallel.cluster import hybrid_mesh, init_cluster
        from spark_tpu.sql.session import SparkSession

        info = init_cluster()             # coordinates via SPARK_TPU_* env
        assert info.process_count == 2, info
        s = SparkSession.builder.getOrCreate()
        assert s.conf.get("spark.app.name") == "launched"   # --conf rode env
        mesh = hybrid_mesh()
        sh = NamedSharding(mesh, PartitionSpec(("dcn", "data")))
        arr = jax.make_array_from_callback(
            (8,), sh, lambda idx: np.arange(8.0)[idx])
        tot = jax.jit(lambda x: x.sum(),
                      out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)
        got = float(np.asarray(
            jax.device_get(tot.addressable_shards[0].data)))
        assert got == 28.0, got
        print(f"worker {info.process_index} collective ok", flush=True)
        os._exit(0)                       # skip the atexit barrier race
    """ % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # APPEND to PYTHONPATH: overwriting would drop the axon site dir
    # (memory: axon-tpu-environment-gotchas)
    env["PYTHONPATH"] = repo + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    r = subprocess.run(
        [sys.executable, "-m", "spark_tpu.cli", "launch",
         "--processes", "2", "--conf", "spark.app.name=launched",
         str(app)],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert r.stdout.count("collective ok") == 2, r.stdout[-2000:]
