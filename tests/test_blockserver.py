"""Unit battery for the disaggregated block service (PR 16).

Four layers, matching the ownership boundary in docs/DECISIONS.md:

* ``BlockStore`` registration mechanics — stage / seal / adopt
  round-trips, idempotent adoption, size-verified restores, and the
  refusal to adopt a seal whose bytes are incomplete;
* structured DEGRADATION — the fault kinds ``die_during_register``
  (both sides of the seal) and ``blockserver_unavailable`` produce
  bounded, counted outcomes through the degrading client, never a hang
  and never an unhandled raise;
* the TTL orphan reaper — stale sealed exchanges reclaimed once every
  owner's lease goes silent, registered state dirs reclaimed ONLY
  after explicit release + TTL (a crashed owner's checkpoint is never
  reaped), raw swept roots touched only when a directory holds nothing
  but wire-format block files, and the ``orphaned_blocks_reclaimed``
  gauge persisting across store instances;
* the rolling-restart acceptance — a standing query stopped and
  resumed over block-service-registered checkpoint state lands a sink
  BYTE-identical to an uninterrupted oracle run.

The subprocess half (real worker kills, adoption with zero re-executed
map tasks) lives in ``tests/chaos_matrix.py --blockserver``.
"""

import glob
import os
import time
from types import SimpleNamespace

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu import types as T
from spark_tpu.parallel.blockserver import (
    BlockServer, BlockServerUnavailable, BlockServiceClient, BlockStore,
)
from spark_tpu.parallel.faults import FaultInjector, FaultPlan
from spark_tpu.sql import functions as F

TTL = 120.0


def _store(root, ttl=TTL):
    """A store over ``root`` with a settable clock: tests advance
    ``now[0]`` instead of sleeping; file mtimes stay real wall-clock,
    so the base must be ``time.time()``."""
    conf = C.Conf()
    conf.set(C.BLOCKSERVER_ORPHAN_TTL.key, str(int(ttl)))
    now = [time.time()]
    return BlockStore(str(root), conf=conf, clock=lambda: now[0]), now


def _publish(tmp_path, store, exchange="xq000042-jL", sender=0,
             owner="host-0", dict_bytes=0, seal=True):
    """Simulate a live sender's publish: block files on disk, staged
    into the store, then (optionally) sealed with their manifest."""
    src = tmp_path / "live" / exchange
    os.makedirs(src, exist_ok=True)
    blocks = {}
    for r, payload in enumerate((b"alpha-rows", b"beta-rows!!")):
        name = f"s{sender:04d}-r{r:04d}.part"
        (src / name).write_bytes(payload)
        store.stage_block(exchange, name, str(src / name))
        blocks[str(r)] = len(payload)
    man = {"ts": 1.0, "host": owner, "blocks": blocks}
    if dict_bytes:
        name = f"s{sender:04d}.dict"
        (src / name).write_bytes(b"d" * dict_bytes)
        store.stage_block(exchange, name, str(src / name))
        man["dict_bytes"] = dict_bytes
    if seal:
        store.seal(exchange, sender, man, owner)
    return man


# ---------------------------------------------------------------------------
# registration mechanics
# ---------------------------------------------------------------------------

def test_stage_seal_adopt_roundtrip(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    man = _publish(tmp_path, store, dict_bytes=7)
    dest = str(tmp_path / "adopted")

    got = store.adopt("xq000042-jL", 0, dest)
    assert got is not None
    assert got["restored"] == 3                  # 2 parts + dict sidecar
    assert open(os.path.join(dest, "s0000-r0000.part"), "rb").read() \
        == b"alpha-rows"
    assert open(os.path.join(dest, "s0000-r0001.part"), "rb").read() \
        == b"beta-rows!!"
    # commit marker written LAST carries the manifest minus the store's
    # own owner field — readers see exactly a live sender's publish
    import json
    with open(os.path.join(dest, "s0000.done")) as f:
        marker = json.load(f)
    assert marker["blocks"] == man["blocks"]
    assert "owner" not in marker
    # re-adoption (a second surviving reader) is an idempotent no-op
    again = store.adopt("xq000042-jL", 0, dest)
    assert again is not None and again["restored"] == 0


def test_adopt_refuses_unsealed_and_incomplete(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    # staged but never sealed: invisible to adoption
    _publish(tmp_path, store, exchange="xq000001-jL", seal=False)
    assert store.adopt("xq000001-jL", 0, str(tmp_path / "d1")) is None
    # sealed, but the manifest names a block the store never got (a
    # crash between stage and seal): adoption refuses the whole seal
    store.seal("xq000002-jL", 0,
               {"blocks": {"0": 10, "1": 999}}, "host-0")
    assert store.adopt("xq000002-jL", 0, str(tmp_path / "d2")) is None
    assert not os.path.exists(str(tmp_path / "d2" / "s0000.done"))


def test_restore_block_verifies_size(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    _publish(tmp_path, store)
    dest = str(tmp_path / "r0000.part")
    assert store.restore_block("xq000042-jL", "s0000-r0000.part", dest,
                               expect_size=len(b"alpha-rows"))
    assert open(dest, "rb").read() == b"alpha-rows"
    # wrong expected size or never-staged name: a clean False, no file
    assert not store.restore_block("xq000042-jL", "s0000-r0000.part",
                                   str(tmp_path / "x"), expect_size=5)
    assert not store.restore_block("xq000042-jL", "s0099-r0000.part",
                                   str(tmp_path / "y"))
    assert not os.path.exists(str(tmp_path / "x"))


def test_release_exchange_drops_custody(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    _publish(tmp_path, store)
    assert store.stats()["exchangesHeld"] == 1
    store.release_exchange("xq000042-jL")
    assert store.stats()["exchangesHeld"] == 0
    assert store.adopt("xq000042-jL", 0, str(tmp_path / "d")) is None


# ---------------------------------------------------------------------------
# structured degradation: the client and the fault kinds
# ---------------------------------------------------------------------------

def test_unavailable_store_raises_and_client_degrades(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    _publish(tmp_path, store)
    events = []
    client = BlockServiceClient(store, owner="host-0",
                                on_event=lambda name, n=1: events.append(name))
    store.available = False
    with pytest.raises(BlockServerUnavailable):
        store.seal("xq000042-jL", 1, {"blocks": {}}, "host-1")
    # every client verb: structured default, an event, never a raise
    assert client.stage_block("xq000042-jL", "s0001-r0000.part",
                              str(tmp_path / "nope")) is False
    assert client.seal("xq000042-jL", 1, {"blocks": {}}) is False
    assert client.adopt("xq000042-jL", 0, str(tmp_path / "d")) is None
    assert client.restore_block("xq000042-jL", "s0000-r0000.part",
                                str(tmp_path / "r")) is False
    assert client.register_state("k", str(tmp_path), owner="o") is False
    assert events == ["blockserver_unavailable"] * 5
    # the store healing restores full service
    store.available = True
    assert client.adopt("xq000042-jL", 0, str(tmp_path / "d")) is not None


def test_client_degrades_on_filesystem_errors(tmp_path):
    store, _now = _store(tmp_path / "shuf")
    events = []
    client = BlockServiceClient(store, owner="host-0",
                                on_event=lambda name, n=1: events.append(name))
    # staging a source file that vanished (the race adoption exists
    # for): an OSError inside the store, a counted False outside
    assert client.stage_block("xq000001-jL", "s0000-r0000.part",
                              str(tmp_path / "gone.part")) is False
    assert events == ["blockserver_unavailable"]


class _Kill(BaseException):
    """In-process stand-in for the injector's hard exit."""


def _armed_store(tmp_path, plan):
    """A store + degrading client wired through ``FaultInjector.attach``
    the way a real ``HostShuffleService`` would be (the injector only
    needs the ``blockclient`` seam plus put/commit to wrap)."""
    store, _now = _store(tmp_path / "shuf")
    client = BlockServiceClient(store, owner="host-1")
    svc = SimpleNamespace(put=lambda *a: None, commit=lambda *a: None,
                          blockclient=client)
    inj = FaultInjector(plan)
    inj.die = lambda code: (_ for _ in ()).throw(_Kill(code))
    inj.attach(svc)
    return store, inj


def test_die_during_register_before_seal(tmp_path):
    store, inj = _armed_store(
        tmp_path, FaultPlan().die_during_register("xq000001-jL"))
    with pytest.raises(_Kill):
        store.seal("xq000001-jL", 1, {"blocks": {}}, "host-1")
    # death BEFORE the seal: no record — survivors see "never
    # registered" and pay plain lineage recovery
    assert store.sealed_manifest("xq000001-jL", 1) is None
    assert inj.injected == ["die_during_register:xq000001-jL:pre"]


def test_die_during_register_after_seal_is_adoptable(tmp_path):
    store, inj = _armed_store(
        tmp_path,
        FaultPlan().die_during_register("xq000001-jL", after_seal=True))
    src = tmp_path / "blk.part"
    src.write_bytes(b"payload")
    store.stage_block("xq000001-jL", "s0001-r0000.part", str(src))
    with pytest.raises(_Kill):
        store.seal("xq000001-jL", 1, {"blocks": {"0": 7}}, "host-1")
    # death AFTER the seal: the record is durable — exactly the window
    # the adoption fast path exists for
    assert store.sealed_manifest("xq000001-jL", 1) is not None
    got = store.adopt("xq000001-jL", 1, str(tmp_path / "dest"))
    assert got is not None and got["restored"] == 1
    assert inj.injected == ["die_during_register:xq000001-jL:post"]
    # the kill is once-per-rule: a later seal (the recovery epoch's
    # re-publish would use a fresh exchange anyway) must not re-fire
    store.seal("xq000002-jL", 1, {"blocks": {}}, "host-1")


def test_die_during_register_filters_by_exchange(tmp_path):
    store, inj = _armed_store(
        tmp_path, FaultPlan().die_during_register("xq000009-jR"))
    store.seal("xq000001-jL", 1, {"blocks": {}}, "host-1")   # no match
    assert inj.injected == []


def test_blockserver_unavailable_fault_heals_on_timer(tmp_path):
    plan = FaultPlan().blockserver_unavailable(heal_after_s=0.15)
    store, inj = _armed_store(tmp_path, plan)
    assert store.available is False                 # down at attach time
    assert inj.injected == ["blockserver_unavailable"]
    deadline = time.time() + 5.0
    while not store.available and time.time() < deadline:
        time.sleep(0.02)
    assert store.available is True                  # healed, full service
    store.seal("xq000001-jL", 1, {"blocks": {}}, "host-1")


def test_new_fault_kinds_round_trip_env():
    plan = (FaultPlan()
            .die_during_register("xq000001-jR", after_seal=True)
            .blockserver_unavailable(heal_after_s=2.0))
    back = FaultPlan.from_env({"SPARK_TPU_FAULT_PLAN": plan.to_env()})
    kinds = [r.kind for r in back.rules]
    assert kinds == ["die_during_register", "blockserver_unavailable"]
    assert back.rules[0].side == "post"             # the seal-side flag
    assert back.rules[1].heal_after_s == 2.0


# ---------------------------------------------------------------------------
# the TTL orphan reaper
# ---------------------------------------------------------------------------

def test_gc_reclaims_exchange_only_after_owner_silence(tmp_path):
    store, now = _store(tmp_path / "shuf")
    _publish(tmp_path, store, owner="host-0")
    # fresh files + fresh lease: nothing to reap
    assert store.gc(roots=()) == 0
    assert store.stats()["exchangesHeld"] == 1
    # a TTL past: files stale AND the owner's lease stale — reclaimed
    now[0] += TTL + 1
    reclaimed = store.gc(roots=())
    assert reclaimed == 3                           # 2 parts + .reg seal
    assert store.stats()["exchangesHeld"] == 0
    assert store.reclaimed_total() == 3


def test_gc_spares_stale_exchange_while_owner_lease_fresh(tmp_path):
    store, now = _store(tmp_path / "shuf")
    _publish(tmp_path, store, owner="host-0")
    now[0] += TTL + 1
    # the owner is alive (lease renewed at the advanced clock): its
    # stale-looking exchange must survive — only silence reclaims
    os.utime(store._lease_path("host-0"), (now[0], now[0]))
    assert store.gc(roots=()) == 0
    assert store.stats()["exchangesHeld"] == 1


def test_gc_never_reaps_crashed_owner_state(tmp_path):
    store, now = _store(tmp_path / "shuf")
    ckpt = tmp_path / "ckpt"
    os.makedirs(ckpt)
    (ckpt / "0.delta").write_bytes(b"state")
    store.register_state("stream-abc", str(ckpt), "stream-abc")
    # the owner CRASHES: its lease file stays on disk, merely stale.
    # Any amount of time later the checkpoint must still be there —
    # restart recovery needs it; only an explicit release starts the
    # reaper's clock
    now[0] += 100 * TTL
    assert store.gc(roots=()) == 0
    assert os.path.isdir(str(ckpt))
    assert store.state_record("stream-abc") is not None


def test_gc_reclaims_state_after_explicit_release_plus_ttl(tmp_path):
    store, now = _store(tmp_path / "shuf")
    ckpt = tmp_path / "ckpt"
    os.makedirs(ckpt)
    (ckpt / "0.delta").write_bytes(b"state")
    (ckpt / "1.delta").write_bytes(b"more")
    store.register_state("stream-abc", str(ckpt), "stream-abc")
    store.release_state("stream-abc", "stream-abc")   # query stop()
    # released but inside the TTL: still recoverable (an operator
    # restarting the query keeps its state)
    assert store.gc(roots=()) == 0
    assert os.path.isdir(str(ckpt))
    # release + TTL: reclaimed, record dropped
    now[0] += TTL + 1
    rec = store._state_rec("stream-abc")
    os.utime(rec, (now[0] - TTL - 1, now[0] - TTL - 1))
    assert store.gc(roots=()) == 2
    assert not os.path.exists(str(ckpt))
    assert store.state_record("stream-abc") is None


def test_gc_raw_root_sweep_only_touches_block_dirs(tmp_path):
    store, now = _store(tmp_path / "shuf")
    root = str(tmp_path / "shuf")
    # a dead session's exchange dir: wire-format files only
    dead = os.path.join(root, "xq000001-jL")
    os.makedirs(dead)
    open(os.path.join(dead, "s0000-r0000.part"), "wb").write(b"x")
    open(os.path.join(dead, "s0000.done"), "w").write("{}")
    # a directory with a foreign file is NOT an exchange dir — never
    # touched no matter how stale
    mixed = os.path.join(root, "leaves")
    os.makedirs(mixed)
    open(os.path.join(mixed, "notes.txt"), "w").write("keep me")
    open(os.path.join(mixed, "s0000-r0000.part"), "wb").write(b"x")
    now[0] += TTL + 1
    reclaimed = store.gc(roots=(root,))
    assert reclaimed == 2
    assert not os.path.exists(dead)
    assert os.path.exists(os.path.join(mixed, "notes.txt"))
    # the store's own area is skipped by name even under the root
    assert os.path.isdir(store.dir)


def test_reclaimed_gauge_persists_across_store_instances(tmp_path):
    store, now = _store(tmp_path / "shuf")
    _publish(tmp_path, store)
    now[0] += TTL + 1
    assert store.gc(roots=()) == 3
    # a different process constructing its own store over the same root
    # reads the same lifetime total — the gauge survives restarts
    fresh, _now2 = _store(tmp_path / "shuf")
    assert fresh.reclaimed_total() == 3
    assert fresh.stats()["orphanedBlocksReclaimed"] == 3


def test_blockserver_reaper_lifecycle(tmp_path):
    store, now = _store(tmp_path / "shuf")
    _publish(tmp_path, store)
    now[0] += TTL + 1
    server = BlockServer(store, interval_s=3600.0, roots=())
    assert server.run_gc() == 3
    stats = server.stats()
    assert stats["gcRuns"] == 1 and stats["lastReclaimed"] == 3
    # a down store makes the reaper a no-op, not an error
    store.available = False
    assert server.run_gc() == 0
    server.stop()


# ---------------------------------------------------------------------------
# service integration: gauges on the shuffle metrics source
# ---------------------------------------------------------------------------

def test_shuffle_source_exports_blockserver_gauges(spark, tmp_path):
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    xs = spark.newSession()
    xs.conf.set(C.BLOCKSERVER_ENABLED.key, "true")
    try:
        svc = xs.enableHostShuffle(str(tmp_path), process_id=0,
                                   n_processes=1, timeout_s=5.0)
        assert svc.blockclient is not None
        snap = svc.metrics_source().snapshot()
        assert snap["blockserver_enabled"] == 1
        assert snap["orphaned_blocks_reclaimed"] == 0
        for k in ("blocks_registered", "manifests_registered",
                  "manifests_adopted", "blocks_adopted",
                  "blockserver_fallback_reads", "blockserver_unavailable"):
            assert snap[k] == 0, (k, snap)
        # the gauge reads the store's persistent total, not the local
        # counter — reaper activity in ANY process shows up here
        svc.blockclient.store._bump_reclaimed(5)
        assert svc.metrics_source().snapshot()[
            "orphaned_blocks_reclaimed"] == 5
    finally:
        xs._crossproc_svc = None
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


def test_shuffle_source_gauge_off_without_blockserver(spark, tmp_path):
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    xs = spark.newSession()
    try:
        svc = xs.enableHostShuffle(str(tmp_path), process_id=0,
                                   n_processes=1, timeout_s=5.0)
        assert svc.blockclient is None
        snap = svc.metrics_source().snapshot()
        assert snap["blockserver_enabled"] == 0
        assert snap["orphaned_blocks_reclaimed"] == 0
    finally:
        xs._crossproc_svc = None
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]


# ---------------------------------------------------------------------------
# rolling restart: a standing query resumes byte-identically from
# block-service-registered checkpoint state
# ---------------------------------------------------------------------------

def sec(n) -> int:
    return int(n * 1_000_000)


_SCHEMA = T.StructType([
    T.StructField("ts", T.timestamp),
    T.StructField("k", T.string),
    T.StructField("v", T.int64),
])

# one file per feed = one micro-batch per feed in every lifetime
_FEEDS = [
    [(sec(1), "a", 1), (sec(9), "b", 2)],
    [(sec(20), "a", 4), (sec(21), "b", 1)],
    [(sec(50), "a", 3), (sec(51), "d", 9)],
]


def _write_feed(session, in_dir, i):
    rows = _FEEDS[i]
    session.createDataFrame({
        "ts": np.array([r[0] for r in rows], "datetime64[us]"),
        "k": [r[1] for r in rows],
        "v": np.array([r[2] for r in rows], np.int64),
    }).write.parquet(os.path.join(in_dir, f"f{i}"))


def _lifetime(session, in_dir, ckpt, out):
    """One worker lifetime: fresh execution over the shared checkpoint,
    drain everything currently available, stop."""
    from spark_tpu.sql.dataframe import DataFrame
    from spark_tpu.streaming.core import (
        FileSink, FileStreamSource, StreamExecution, StreamingRelation,
    )
    src = FileStreamSource("parquet", in_dir, _SCHEMA,
                           {"maxfilespertrigger": "1"})
    df = (DataFrame(session, StreamingRelation(src))
          .withWatermark("ts", "5 seconds")
          .groupBy(F.window("ts", "10 seconds").alias("w"))
          .agg(F.sum("v").alias("s")))
    ex = StreamExecution(session, df._plan, FileSink("json", out, {}),
                         "append", ckpt, 0.1, None)
    try:
        ex.process_all_available()
        assert ex.exception is None, ex.exception
    finally:
        ex.stop()
    return ex


def _sink_files(out):
    return {os.path.basename(p): open(p, "rb").read()
            for p in sorted(glob.glob(os.path.join(out, "part-*")))}


def test_rolling_restart_resumes_byte_identical(spark, tmp_path):
    """Stop every worker and bring up fresh ones over the same
    checkpoint: the state the block service holds registered ownership
    of carries the query across the restart, and the resumed sink is
    BYTE-identical to an uninterrupted oracle.  Along the way the
    ownership protocol is observable: register at construction (a key
    derived from the checkpoint PATH, stable across lifetimes), a live
    lease while running, explicit release on stop."""
    prev = getattr(spark, "_crossproc_svc", None)
    ms = spark.metricsSystem
    xs = spark.newSession()
    xs.conf.set("spark.tpu.mesh.shards", "1")
    xs.conf.set(C.BLOCKSERVER_ENABLED.key, "true")
    try:
        svc = xs.enableHostShuffle(str(tmp_path / "shuf"), process_id=0,
                                   n_processes=1, timeout_s=10.0)
        store = svc.blockclient.store

        in_all = str(tmp_path / "in_all")
        for i in range(len(_FEEDS)):
            _write_feed(xs, in_all, i)
        oracle_out = str(tmp_path / "oracle_out")
        _lifetime(xs, in_all, str(tmp_path / "oracle_ckpt"), oracle_out)
        oracle = _sink_files(oracle_out)
        assert oracle, "the oracle run must emit something to compare"

        # lifetime 1: only the first two feeds exist yet
        in_dir = str(tmp_path / "in")
        ckpt, out = str(tmp_path / "ckpt"), str(tmp_path / "out")
        for i in range(2):
            _write_feed(xs, in_dir, i)
        ex1 = _lifetime(xs, in_dir, ckpt, out)
        key = ex1._ck_owner
        assert key and key.startswith("stream-")
        rec = store.state_record(key)
        assert rec is not None
        assert rec["path"] == os.path.abspath(ckpt)
        # stop() released ownership: the lease is gone, the record
        # (and the checkpoint itself) stay for the reaper's TTL clock
        assert not os.path.exists(store._lease_path(key))

        # the restarted worker: same checkpoint, the remaining feed
        _write_feed(xs, in_dir, 2)
        ex2 = _lifetime(xs, in_dir, ckpt, out)
        # the checkpoint-path-derived key re-registered the SAME record
        assert ex2._ck_owner == key
        assert store.state_record(key) is not None
        assert _sink_files(out) == oracle
    finally:
        xs._crossproc_svc = None
        spark._crossproc_svc = prev
        ms._sources = [s for s in ms._sources if s.name != "shuffle"]
