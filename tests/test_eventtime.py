"""Event-time streaming: watermark, window buckets, append mode, dedup.

Scripted StreamTest-style scenarios (reference `StreamTest.scala:224`,
`EventTimeWatermarkSuite`, `DeduplicateSuite`): late data dropped,
append-mode windows emitted exactly once, state evicted, and all of it
surviving a stop/restart from the checkpoint.
"""

import datetime

import pandas as pd
import pytest

from spark_tpu import types as T
from spark_tpu.sql import functions as F
from spark_tpu.streaming import MemoryStream


def sec(n) -> int:
    return int(n * 1_000_000)     # timestamps are int64 microseconds


def dt(n) -> datetime.datetime:
    """Decoded timestamp value for second n (collect() yields datetimes)."""
    return datetime.datetime(1970, 1, 1) + datetime.timedelta(seconds=n)


SCHEMA = T.StructType([
    T.StructField("ts", T.timestamp),
    T.StructField("k", T.string),
    T.StructField("v", T.int64),
])


def sink_rows(spark, name):
    return sorted(tuple(r) for r in
                  spark.sql(f"SELECT * FROM {name}").collect())


# ---------------------------------------------------------------------------
# batch semantics of window()
# ---------------------------------------------------------------------------

def test_window_batch(spark):
    df = spark.createDataFrame(pd.DataFrame({
        "ts": [sec(1), sec(9), sec(10), sec(25)],
        "v": [1.0, 2.0, 3.0, 4.0]}))
    out = sorted(tuple(r) for r in
                 df.groupBy(F.window("ts", "10 seconds").alias("w"))
                   .agg(F.sum("v").alias("s")).collect())
    assert out == [(dt(0), 3.0), (dt(10), 3.0), (dt(20), 4.0)]


def test_window_end_and_sliding_rejected(spark):
    df = spark.createDataFrame(pd.DataFrame({"ts": [sec(14)], "v": [1.0]}))
    (w,) = df.select(F.window_end("ts", "10 seconds").alias("we")).collect()
    assert w[0] == dt(20)
    from spark_tpu.expressions import AnalysisException
    with pytest.raises(AnalysisException):
        df.select(F.window("ts", "10 seconds", "5 seconds")).collect()


# ---------------------------------------------------------------------------
# append mode with watermark
# ---------------------------------------------------------------------------

def _windowed_query(spark, src, name, checkpoint=None, mode="append"):
    agg = (src.toDF(spark)
           .withWatermark("ts", "5 seconds")
           .groupBy(F.window("ts", "10 seconds").alias("w"))
           .agg(F.sum("v").alias("s")))
    w = (agg.writeStream.format("memory").queryName(name)
         .outputMode(mode).trigger(once=True))
    if checkpoint:
        w = w.option("checkpointLocation", checkpoint)
    return w.start()


def test_append_requires_watermark(spark):
    src = MemoryStream(SCHEMA, spark)
    agg = src.toDF(spark).groupBy("k").agg(F.sum("v").alias("s"))
    from spark_tpu.expressions import AnalysisException
    with pytest.raises(AnalysisException):
        (agg.writeStream.format("memory").queryName("nope")
         .outputMode("append").start())


def test_append_windows_emit_once(spark):
    src = MemoryStream(SCHEMA, spark)
    q = _windowed_query(spark, src, "ev_app")
    # window [0,10) open: wm = 9-5 = 4 < 10 -> nothing final
    src.addData([(sec(1), "a", 1), (sec(9), "a", 2)])
    q.processAllAvailable()
    assert sink_rows(spark, "ev_app") == []
    # ts=20 -> wm = 15 >= 10: window [0,10) finalizes with sum 3
    src.addData([(sec(20), "a", 4)])
    q.processAllAvailable()
    assert sink_rows(spark, "ev_app") == [(dt(0), 3)]
    # late row (ts=3 < wm=15) is DROPPED, not re-aggregated
    src.addData([(sec(3), "a", 100)])
    q.processAllAvailable()
    assert sink_rows(spark, "ev_app") == [(dt(0), 3)]
    # ts=35 -> wm = 30: window [20,30) finalizes; [0,10) NOT re-emitted
    src.addData([(sec(35), "a", 8)])
    q.processAllAvailable()
    assert sink_rows(spark, "ev_app") == [(dt(0), 3), (dt(20), 4)]
    q.stop()


def test_append_recovery_across_restart(spark, tmp_path):
    ckpt = str(tmp_path / "ckpt")
    src = MemoryStream(SCHEMA, spark)
    q = _windowed_query(spark, src, "ev_rec", checkpoint=ckpt)
    src.addData([(sec(2), "a", 5), (sec(8), "a", 6)])
    q.processAllAvailable()
    assert sink_rows(spark, "ev_rec") == []
    q.stop()
    # restart: state (open window [0,10) sum 11) and watermark recover
    q2 = _windowed_query(spark, src, "ev_rec2", checkpoint=ckpt)
    src.addData([(sec(21), "a", 1)])
    q2.processAllAvailable()
    assert sink_rows(spark, "ev_rec2") == [(dt(0), 11)]
    # late data from before the recovered watermark stays dropped
    src.addData([(sec(5), "a", 50)])
    q2.processAllAvailable()
    assert sink_rows(spark, "ev_rec2") == [(dt(0), 11)]
    q2.stop()


def test_update_mode_evicts_state(spark):
    src = MemoryStream(SCHEMA, spark)
    q = _windowed_query(spark, src, "ev_upd", mode="update")
    src.addData([(sec(1), "a", 1)])
    q.processAllAvailable()
    src.addData([(sec(30), "a", 2)])   # wm=25: [0,10) evicted from state
    q.processAllAvailable()
    state = q._ex._agg_state.state
    import numpy as np
    assert int(np.asarray(state.num_rows())) == 1   # only [30,40) remains
    # update mode emitted each changed group as it changed
    assert sink_rows(spark, "ev_upd") == [(dt(0), 1), (dt(30), 2)]
    q.stop()


def test_open_window_late_rows_kept(spark):
    """A row older than the watermark but whose WINDOW is still open must
    aggregate (dropping keys only when the state is final/evicted)."""
    src = MemoryStream(SCHEMA, spark)
    q = _windowed_query(spark, src, "ev_open")
    src.addData([(sec(19), "a", 1)])   # wm -> 14
    q.processAllAvailable()
    src.addData([(sec(12), "a", 1)])   # [10,20) end 20 > 14: kept
    q.processAllAvailable()
    src.addData([(sec(31), "a", 1)])   # wm -> 26: [10,20) emits
    q.processAllAvailable()
    assert sink_rows(spark, "ev_open")[0] == (dt(10), 2)
    q.stop()


def test_dedup_over_streaming_agg_rejected(spark):
    src = MemoryStream(SCHEMA, spark)
    from spark_tpu.expressions import AnalysisException
    with pytest.raises(AnalysisException):
        (src.toDF(spark).groupBy("k").agg(F.sum("v").alias("x")).distinct()
         .writeStream.format("memory").queryName("bad_dd")
         .outputMode("update").start())


# ---------------------------------------------------------------------------
# streaming deduplication
# ---------------------------------------------------------------------------

def test_drop_duplicates_subset(spark):
    src = MemoryStream(SCHEMA, spark)
    q = (src.toDF(spark).dropDuplicates(["k"])
         .writeStream.format("memory").queryName("dd1")
         .outputMode("append").trigger(once=True).start())
    src.addData([(sec(1), "a", 1), (sec(2), "a", 2), (sec(3), "b", 3)])
    q.processAllAvailable()
    assert sink_rows(spark, "dd1") == [(dt(1), "a", 1), (dt(3), "b", 3)]
    # cross-batch duplicate suppressed, new key passes
    src.addData([(sec(4), "a", 9), (sec(5), "c", 5)])
    q.processAllAvailable()
    assert sink_rows(spark, "dd1") == [
        (dt(1), "a", 1), (dt(3), "b", 3), (dt(5), "c", 5)]
    q.stop()


def test_drop_duplicates_full_row(spark):
    src = MemoryStream(SCHEMA, spark)
    q = (src.toDF(spark).distinct()
         .writeStream.format("memory").queryName("dd2")
         .outputMode("append").trigger(once=True).start())
    src.addData([(sec(1), "a", 1), (sec(1), "a", 1), (sec(1), "a", 2)])
    q.processAllAvailable()
    assert sink_rows(spark, "dd2") == [(dt(1), "a", 1), (dt(1), "a", 2)]
    src.addData([(sec(1), "a", 1), (sec(2), "a", 1)])
    q.processAllAvailable()
    assert sink_rows(spark, "dd2") == [
        (dt(1), "a", 1), (dt(1), "a", 2), (dt(2), "a", 1)]
    q.stop()


def test_dedup_watermark_eviction_and_recovery(spark, tmp_path):
    ckpt = str(tmp_path / "ckpt_dd")
    import numpy as np

    src = MemoryStream(SCHEMA, spark)

    def mk(name):
        return (src.toDF(spark).withWatermark("ts", "5 seconds")
                .dropDuplicates(["k", "ts"])
                .writeStream.format("memory").queryName(name)
                .outputMode("append")
                .option("checkpointLocation", ckpt)
                .trigger(once=True).start())

    q = mk("dd3")
    src.addData([(sec(1), "a", 1), (sec(1), "a", 9)])
    q.processAllAvailable()
    assert sink_rows(spark, "dd3") == [(dt(1), "a", 1)]
    # wm advances to 15: old keys leave the state...
    src.addData([(sec(20), "b", 2)])
    q.processAllAvailable()
    st = q._ex._dedup_state.state
    assert int(np.asarray(st.num_rows())) == 1
    q.stop()
    # ...and a late duplicate cannot sneak back in after restart because
    # the recovered watermark drops it at the input
    q2 = mk("dd4")
    src.addData([(sec(1), "a", 7), (sec(21), "c", 3)])
    q2.processAllAvailable()
    assert sink_rows(spark, "dd4") == [(dt(21), "c", 3)]
    q2.stop()


def test_sliding_window_batch_aggregation(spark):
    """window(ts, '10 min', '5 min'): each event lands in duration/slide
    windows (Expand-style static expansion below the aggregate)."""
    import numpy as np
    import pandas as pd
    from spark_tpu.sql import functions as F
    rng = np.random.default_rng(5)
    secs = rng.integers(0, 3600, 300)
    vals = rng.integers(1, 100, 300)
    df = spark.createDataFrame(pd.DataFrame({
        "ts": pd.to_datetime(secs, unit="s"), "v": vals}))
    out = {r["w"]: r["s"] for r in
           df.groupBy(F.window("ts", "10 minutes", "5 minutes").alias("w"))
             .agg(F.sum("v").alias("s")).collect()}
    import collections
    exp = collections.Counter()
    for t, v in zip(secs.tolist(), vals.tolist()):
        last = (t // 300) * 300
        for i in range(2):
            exp[last - i * 300] += v
    import datetime as dt
    expected = {dt.datetime.utcfromtimestamp(k): v for k, v in exp.items()}
    assert out == expected


def test_sliding_window_end_field_and_sql(spark):
    rows = spark.sql(
        "SELECT window(t, '4 seconds', '2 seconds') AS w, COUNT(*) AS c "
        "FROM (SELECT to_timestamp('1970-01-01 00:00:05') AS t) x "
        "GROUP BY window(t, '4 seconds', '2 seconds') ORDER BY w").collect()
    import datetime as dt
    assert [r["w"] for r in rows] == [
        dt.datetime(1970, 1, 1, 0, 0, 2), dt.datetime(1970, 1, 1, 0, 0, 4)]


def test_sliding_window_rejects_bad_slide(spark):
    import pytest
    from spark_tpu.expressions import AnalysisException
    from spark_tpu.sql import functions as F
    with pytest.raises(AnalysisException, match="divide"):
        F.window("ts", "10 minutes", "3 minutes")


def test_sliding_window_streaming_complete(spark):
    """Sliding window() on a STREAM: the Expand rewrite incrementalizes —
    each event lands in duration/slide windows and sums accumulate across
    micro-batches exactly as the batch path computes them."""
    src = MemoryStream(SCHEMA, spark)
    q = (src.toDF(spark)
         .groupBy(F.window("ts", "4 seconds", "2 seconds").alias("w"))
         .agg(F.sum("v").alias("s"))
         .writeStream.format("memory").queryName("slidec")
         .outputMode("complete").trigger(once=True).start())
    src.addData([(sec(1), "a", 1), (sec(3), "a", 10)])
    q.processAllAvailable()
    # windows: ts=1 → [-2,2),[0,4); ts=3 → [0,4),[2,6)
    assert sink_rows(spark, "slidec") == [
        (dt(-2), 1), (dt(0), 11), (dt(2), 10)]
    src.addData([(sec(2), "b", 100)])     # → [0,4),[2,6)
    q.processAllAvailable()
    assert sink_rows(spark, "slidec") == [
        (dt(-2), 1), (dt(0), 111), (dt(2), 110)]
    q.stop()


def test_sliding_window_streaming_append_watermark(spark):
    """Append mode: a sliding window emits once, when the watermark passes
    its END; late-arriving contributions to open windows still merge."""
    src = MemoryStream(SCHEMA, spark)
    q = (src.toDF(spark).withWatermark("ts", "2 seconds")
         .groupBy(F.window("ts", "4 seconds", "2 seconds").alias("w"))
         .agg(F.sum("v").alias("s"))
         .writeStream.format("memory").queryName("slidea")
         .outputMode("append").trigger(once=True).start())
    src.addData([(sec(1), "a", 1), (sec(3), "a", 10)])
    q.processAllAvailable()
    assert sink_rows(spark, "slidea") == []      # wm=1: nothing final
    src.addData([(sec(9), "a", 5)])              # wm → 7: ends 2,4,6 final
    q.processAllAvailable()
    assert sink_rows(spark, "slidea") == [
        (dt(-2), 1), (dt(0), 11), (dt(2), 10)]
    src.addData([(sec(14), "a", 2)])             # wm → 12: ends ≤12 final
    q.processAllAvailable()
    assert sink_rows(spark, "slidea") == [
        (dt(-2), 1), (dt(0), 11), (dt(2), 10), (dt(6), 5), (dt(8), 5)]
    q.stop()
