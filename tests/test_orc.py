"""ORC datasource (VERDICT r3 item 9; `sql/hive/.../orc/OrcFileFormat.scala`
role via pyarrow.orc): write/read round-trip, schema from metadata only,
and column pruning pushed into the stripe reader."""

import numpy as np
import pandas as pd
import pytest

import spark_tpu.sql.functions as F

paorc = pytest.importorskip("pyarrow.orc")


@pytest.fixture()
def pdf():
    rng = np.random.default_rng(17)
    return pd.DataFrame({
        "id": np.arange(500, dtype=np.int64),
        "g": rng.choice(["x", "y", "z"], 500),
        "v": rng.normal(0.0, 2.0, 500),
        "b": rng.integers(0, 2, 500).astype(bool),
    })


def test_orc_roundtrip(spark, pdf, tmp_path):
    src = spark.createDataFrame(pdf)
    path = str(tmp_path / "t.orc")
    src.write.orc(path)
    back = spark.read.orc(path)
    assert [f.name for f in back.schema.fields] == list(pdf.columns)
    got = back.orderBy("id").collect()
    assert [r.id for r in got] == pdf.id.tolist()
    assert [r.g for r in got] == pdf.g.tolist()
    np.testing.assert_allclose([r.v for r in got], pdf.v.to_numpy(),
                               rtol=1e-12)
    assert [r.b for r in got] == pdf.b.tolist()


def test_orc_matches_parquet_read(spark, pdf, tmp_path):
    src = spark.createDataFrame(pdf)
    op, pp = str(tmp_path / "o.orc"), str(tmp_path / "p.parquet")
    src.write.orc(op)
    src.write.parquet(pp)
    q = lambda df: (df.groupBy("g").agg(F.sum("v").alias("s"),
                                        F.count("*").alias("c"))
                    .orderBy("g").collect())
    assert [(r.g, r.c) for r in q(spark.read.orc(op))] \
        == [(r.g, r.c) for r in q(spark.read.parquet(pp))]
    np.testing.assert_allclose(
        [r.s for r in q(spark.read.orc(op))],
        [r.s for r in q(spark.read.parquet(pp))], rtol=1e-12)


def test_orc_schema_without_reading(spark, pdf, tmp_path, monkeypatch):
    """Referencing an ORC table must not read stripes (metadata only)."""
    path = str(tmp_path / "s.orc")
    spark.createDataFrame(pdf).write.orc(path)
    import spark_tpu.io as tio

    def boom(*a, **k):
        raise AssertionError("stripes were read for schema access")
    monkeypatch.setattr(tio, "_read_orc", boom)
    df = spark.read.orc(path)
    assert df.schema.names == list(pdf.columns)   # no read triggered


def test_orc_partitioned_roundtrip(spark, pdf, tmp_path):
    """partitionBy'd ORC output must read back WITH its partition column
    (schema from metadata + partition directories, like parquet)."""
    path = str(tmp_path / "part.orc")
    spark.createDataFrame(pdf).write.partitionBy("g").orc(path)
    back = spark.read.orc(path)
    assert "g" in back.schema.names
    got = {r.g: r.c for r in
           back.groupBy("g").agg(F.count("*").alias("c")).collect()}
    exp = pdf.groupby("g").size()
    assert got == {g: int(n) for g, n in exp.items()}


def test_orc_column_pruning(spark, pdf, tmp_path, monkeypatch):
    """A query touching one column must push that pruning into the ORC
    reader, not read the full table and drop columns after."""
    path = str(tmp_path / "pr.orc")
    spark.createDataFrame(pdf).write.orc(path)
    import spark_tpu.io as tio
    tio._relation_cache.clear()
    seen = {}
    real = tio._read_orc

    def spy(paths, options, columns=None):
        seen["columns"] = columns
        return real(paths, options, columns=columns)
    monkeypatch.setattr(tio, "_read_orc", spy)
    (s,), = spark.read.orc(path).agg(F.sum("id").alias("s")).collect()
    assert s == int(pdf.id.sum())
    assert seen["columns"] is not None and set(seen["columns"]) == {"id"}
