"""The chaos kill-at-phase matrix: one table of fault scenarios against
REAL multi-process exchanges, shared by the pytest suite
(test_recovery.py) and the ``bin/chaos`` runner.

Every scenario names the exchange PHASE the fault lands in (map
staging, post-publish_sizes, mid-fetch, mid-demotion, during the
recovery round itself), arms a ``FaultPlan`` on one victim process, and
declares the oracle verdict per process:

* ``OK``      — the process printed ``[p<i>] OK`` (oracle-exact result;
                recovery-mode workers additionally self-assert
                ``stage_retries >= 1`` before printing it);
* ``FAILED``  — a structured, bounded abort line;
* ``HOSTMEM`` — the spill-ENOSPC structured abort;
* ``DIED``    — exit code 43, the injector's planned kill.

The invariant across the WHOLE table: a faulted run either recovers to
the exact oracle or aborts structured within ``3 x timeout + slack`` —
never a hang, never a partial result (``PARTIAL`` is grepped out of
every output).  ``kinds_covered()`` backs the lint gate: every fault
kind ``parallel.faults`` can inject must appear somewhere in the
matrix, so adding an injector without a chaos scenario fails a test.
"""

import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from spark_tpu.parallel.faults import (  # noqa: E402
    FAULT_PLAN_ENV, FaultPlan, _KINDS)

HERE = os.path.dirname(os.path.abspath(__file__))

#: the exchange phases the matrix must cover (ISSUE contract)
PHASES = ("map-staging", "post-publish-sizes", "mid-fetch",
          "mid-demotion", "during-recovery", "during-grace",
          "post-register", "mid-device-copy")


def _scenario(name, phase, worker, mode, n, timeout_s, plans, expect,
              tier="slow"):
    return {"name": name, "phase": phase, "worker": worker,
            "mode": mode, "n": n, "timeout_s": timeout_s,
            "plans": plans, "expect": expect, "tier": tier}


#: name → scenario.  ``plans`` maps victim pid → zero-arg FaultPlan
#: builder (fresh plan per run); ``expect`` maps pid → verdict token.
SCENARIOS = [
    # -- the acceptance pair: kill mid-fetch, with and without budget --
    _scenario(
        "mid-fetch-kill", "mid-fetch", "recovery_worker.py", "recover",
        2, 20.0, {1: lambda: FaultPlan().die_after_put("xq000001-jL")},
        {0: "OK", 1: "DIED"}, tier="tier1"),
    _scenario(
        "mid-fetch-kill-noretry", "mid-fetch", "recovery_worker.py",
        "norecover", 2, 8.0,
        {1: lambda: FaultPlan().die_after_put("xq000001-jL")},
        {0: "FAILED", 1: "DIED"}, tier="tier1"),
    # -- kill during map staging: dies right after committing the digest
    #    round (recipes already published) — lineage covers the loss --
    _scenario(
        "map-staging-kill", "map-staging", "recovery_worker.py",
        "recover", 2, 12.0,
        {1: lambda: FaultPlan().die_after_manifest("xq000001-digest")},
        {0: "OK", 1: "DIED"}),
    # -- kill right after publish_sizes: stats manifest landed, data
    #    blocks never did — survivor recovers from recipes --
    _scenario(
        "post-publish-sizes-kill", "post-publish-sizes",
        "recovery_worker.py", "recover", 2, 12.0,
        {1: lambda: FaultPlan().die_after_manifest("xq000001-plan")},
        {0: "OK", 1: "DIED"}),
    # -- kill mid-demotion: the adaptive broadcast gather loses its
    #    peer; in-memory leaves mean no lineage — structured abort --
    _scenario(
        "mid-demotion-kill", "mid-demotion", "adaptive_worker.py",
        "fault-adapt", 2, 6.0,
        {1: lambda: FaultPlan().die_after_put("xq000001-bcast")},
        {0: "FAILED", 1: "DIED"}),
    # -- kill DURING the recovery round: p2 dies mid-fetch, p1 publishes
    #    its recovery manifest and dies; the agreement completes but the
    #    epoch-1 re-run loses p1 past the retry budget — bounded abort --
    _scenario(
        "recovery-round-kill", "during-recovery", "recovery_worker.py",
        "recover", 3, 12.0,
        {2: lambda: FaultPlan().die_after_put("xq000001-jL"),
         1: lambda: FaultPlan().die_after_manifest("xq000001-recover1")},
        {0: "FAILED", 1: "DIED", 2: "DIED"}),
    # -- live-but-faulty peers: declared lost, survivor recovers from
    #    their on-disk lineage while they abort bounded --
    _scenario(
        "block-dropped-alive-peer", "mid-fetch", "recovery_worker.py",
        "recover", 2, 6.0,
        {1: lambda: FaultPlan().drop(exchange="xq000001-jL",
                                     receiver=0)},
        {0: "OK", 1: "FAILED"}),
    _scenario(
        "block-corrupted-alive-peer", "mid-fetch", "recovery_worker.py",
        "recover", 2, 6.0,
        {1: lambda: FaultPlan().corrupt(exchange="xq000001-jL",
                                        receiver=0)},
        {0: "OK", 1: "FAILED"}),
    _scenario(
        "block-truncated-noretry", "mid-fetch", "recovery_worker.py",
        "norecover", 2, 6.0,
        {1: lambda: FaultPlan().truncate(exchange="xq000001-jL",
                                         keep_bytes=3)},
        {0: "FAILED", 1: "FAILED"}),
    # -- a slow peer is NOT a dead peer: the delay heals inside the
    #    retry window, nothing recovers, results stay oracle-exact --
    _scenario(
        "slow-peer-heals", "mid-fetch", "recovery_worker.py",
        "norecover", 2, 8.0,
        {1: lambda: FaultPlan().delay(0.3, exchange="xq000001-jL")},
        {0: "OK", 1: "OK"}),
    # -- a sender that stages but never commits parks the barrier: both
    #    sides time out structured (map staging never finished) --
    _scenario(
        "commit-skipped", "map-staging", "recovery_worker.py",
        "norecover", 2, 5.0,
        {1: lambda: FaultPlan().skip_commit(exchange="xq000001-jL")},
        {0: "FAILED", 1: "FAILED"}),
    # -- disk pressure: the forced spill hits injected ENOSPC --
    _scenario(
        "spill-disk-full", "map-staging", "shuffled_join_worker.py",
        "spill-fault", 2, 8.0,
        {1: lambda: FaultPlan().disk_full(after_bytes=0)},
        {0: "FAILED", 1: "HOSTMEM"}),
    # -- kill a peer while the survivor grace-degrades: the victim
    #    commits its jR map output then dies, so the survivor's capped
    #    budget sends it through grace buckets before the -fin merge
    #    exposes the loss — the recovery epoch must replay cleanly over
    #    the partially-spilled grace state, oracle-exact --
    _scenario(
        "grace-kill", "during-grace", "recovery_worker.py",
        "grace-recover", 2, 20.0,
        {1: lambda: FaultPlan().die_after_manifest("xq000001-jR")},
        {0: "OK", 1: "DIED"}, tier="tier1"),
    _scenario(
        "grace-kill-3proc", "during-grace", "recovery_worker.py",
        "grace-recover", 3, 20.0,
        {2: lambda: FaultPlan().die_after_manifest("xq000001-jR")},
        {0: "OK", 1: "OK", 2: "DIED"}),
    # -- spill-disk exhaustion DURING the grace pass itself: the only
    #    genuinely unspillable shape — a structured bounded abort, the
    #    error detail naming the failed grace spill --
    _scenario(
        "grace-disk-full", "during-grace", "shuffled_join_worker.py",
        "grace-fault", 2, 8.0,
        {1: lambda: FaultPlan().disk_full(after_bytes=0,
                                          exchange="xq000001-grace")},
        {0: "FAILED", 1: "HOSTMEM"}),
    # -- replica-determinism divergence: the victim's GATHERED view of
    #    the stats round is perturbed while the on-disk manifests every
    #    peer reads stay intact — verify_decision_trace aborts the
    #    divergent re-decision structured before any data block ships;
    #    the unarmed peer fails bounded at its data barrier --
    _scenario(
        "skew-decision-divergence", "post-publish-sizes",
        "adaptive_worker.py", "skew-decision", 2, 6.0,
        {1: lambda: FaultPlan().skew_decision("xq000001-plan")},
        {0: "FAILED", 1: "FAILED"}),
    # -- the disaggregated-block-service battery (``--blockserver``) --
    # kill AFTER the map output registered with the block service: the
    # victim drops its shipped jR block from the exchange dir (so the
    # raw-path fetch fails) and dies once its LAST manifest lands — the
    # survivor must finish from block-service custody alone, with the
    # retry budget at ZERO so any recovery attempt would fail the
    # query: OK here is a proof of zero re-executed map tasks
    _scenario(
        "blockserver-adopt-zero-rerun", "post-register",
        "recovery_worker.py", "bs-zero", 2, 20.0,
        {1: lambda: FaultPlan().drop(exchange="xq000001-jR", receiver=0)
            .die_after_manifest("xq000001-gather")},
        {0: "OK", 1: "DIED"}, tier="tier1"),
    # -- die in the register gap, AFTER the seal record committed but
    #    BEFORE the exchange .done marker: the survivor's barrier sees
    #    a dead silent peer, yet adoption re-publishes the sealed
    #    manifest + blocks; the victim's unfinished downstream stages
    #    still need the recovery epoch (asserted manifests_adopted>=1)
    _scenario(
        "blockserver-adopt-sealed-manifest", "post-register",
        "recovery_worker.py", "bs-adopt", 2, 20.0,
        {1: lambda: FaultPlan().die_during_register(
            "xq000001-jR", after_seal=True)},
        {0: "OK", 1: "DIED"}),
    # -- die in the register gap BEFORE the seal: nothing adoptable, the
    #    survivor must fall all the way back to lineage re-execution
    #    (asserted manifests_adopted == 0) --
    _scenario(
        "blockserver-die-mid-register", "post-register",
        "recovery_worker.py", "bs-recover", 2, 20.0,
        {1: lambda: FaultPlan().die_during_register("xq000001-jR")},
        {0: "OK", 1: "DIED"}),
    # -- block service down on the SURVIVOR while a committed peer's
    #    block is missing and the peer dead: adoption degrades to a
    #    counted event (never a hang) and r12 recovery still lands the
    #    exact oracle --
    _scenario(
        "blockserver-unavailable-fallback", "mid-fetch",
        "recovery_worker.py", "bs-unavail", 2, 20.0,
        {0: lambda: FaultPlan().blockserver_unavailable(),
         1: lambda: FaultPlan().drop(exchange="xq000001-jR", receiver=0)
            .die_after_manifest("xq000001-jR")},
        {0: "OK", 1: "DIED"}),
    # -- the ICI device-exchange tier (worker mode ``ici-fault``: tier
    #    armed over a dict-free join, so every exchange genuinely
    #    attempts the device path) --
    # the tier raises IciUnavailable at the attempt point on ONE process
    # only: both replicas still converge — the faulted one counts a
    # dcn_fallback and re-ships the full routed set over the host tier,
    # the clean one merely reaches the same host barrier — oracle-exact
    _scenario(
        "ici-unavailable-fallback", "mid-device-copy",
        "shuffled_join_worker.py", "ici-fault", 2, 8.0,
        {0: lambda: FaultPlan().ici_unavailable()},
        {0: "OK", 1: "OK"}),
    # exit hard at the copy point — spans packed, device transfer about
    # to start: the survivor must see an ordinary peer death at the host
    # commit barrier (bounded ExchangeFetchFailed), never a wedged
    # collective or a partial result
    _scenario(
        "ici-die-mid-device-copy", "mid-device-copy",
        "shuffled_join_worker.py", "ici-fault", 2, 8.0,
        {0: lambda: FaultPlan().die_mid_device_copy()},
        {0: "DIED", 1: "FAILED"}),
    # -- the elastic-pool battery (``--pool``; see pool_worker.py) --
    # scale-down mid-fetch: the peer is cooperatively REAPED once its
    # last manifest lands (stops beating, lease handed to the pool
    # supervisor) while its shipped jR block is dropped — the survivor
    # must land the exact oracle from block-service custody alone, with
    # the retry budget at ZERO (zero re-executed map tasks) and the
    # reaped worker's lease still fresh through the heir chain
    _scenario(
        "pool-reap-mid-fetch", "post-register", "pool_worker.py",
        "reap", 2, 20.0,
        {1: lambda: FaultPlan().drop(exchange="xq000001-jR",
                                     receiver=0)},
        {0: "OK", 1: "OK"}, tier="tier1"),
    # spawn exec failure: demand wants 2 workers, the second exec
    # raises — the pool converges BELOW target (counted spawn_failures,
    # never a hang) and the one real worker still serves
    _scenario(
        "pool-spawn-exec-error", "worker-spawn", "pool_worker.py",
        "spawn-fail", 1, 20.0,
        {0: lambda: FaultPlan().spawn_exec_error(after_spawns=1)},
        {0: "OK"}),
    # scale-up mid-standing-query: a real worker joins between
    # micro-batches; the stream's sink must stay BYTE-identical to an
    # uninterrupted no-pool oracle lifetime
    _scenario(
        "pool-scaleup-midstream", "mid-standing-query", "pool_worker.py",
        "scaleup", 1, 60.0, {}, {0: "OK"}),
]


#: the streaming micro-batch commit phases the --streaming group must
#: kill at (ISSUE 15 contract); the in-process battery
#: (test_streaming_recovery.py) additionally covers mid-batch, which
#: needs no injector at all — nothing durable has happened yet
STREAM_PHASES = ("post-state-commit", "mid-commit")

#: kill-at-phase against a REAL standing query: pid 1 runs the stream
#: and dies hard at the planned commit phase; pid 0 restarts over the
#: same checkpoint and byte-compares the sink to an uninterrupted
#: oracle (see streaming_worker.py).  ``bin/chaos --streaming``.
STREAM_SCENARIOS = [
    # -- die between the state snapshot and the sink write: replay must
    #    re-emit the batch, not trust the orphaned snapshot --
    _scenario(
        "stream-die-post-state-commit", "post-state-commit",
        "streaming_worker.py", "wagg", 2, 60.0,
        {1: lambda: FaultPlan().die_after_state_commit(after_entries=1)},
        {0: "OK", 1: "DIED"}),
    _scenario(
        "stream-die-post-state-commit-dedup", "post-state-commit",
        "streaming_worker.py", "dedup", 2, 60.0,
        {1: lambda: FaultPlan().die_after_state_commit(after_entries=1)},
        {0: "OK", 1: "DIED"}),
    # -- die mid-commit with the entry TORN on disk: the checksum makes
    #    the torn entry read as uncommitted and the batch replays --
    _scenario(
        "stream-torn-commit-kill", "mid-commit",
        "streaming_worker.py", "wagg", 2, 60.0,
        {1: lambda: FaultPlan().torn_checkpoint(
            keep_bytes=11, after_entries=1, die=True)},
        {0: "OK", 1: "DIED"}),
    _scenario(
        "stream-torn-commit-kill-dedup", "mid-commit",
        "streaming_worker.py", "dedup", 2, 60.0,
        {1: lambda: FaultPlan().torn_checkpoint(
            keep_bytes=11, after_entries=1, die=True)},
        {0: "OK", 1: "DIED"}),
]


def by_name(name):
    for s in SCENARIOS + STREAM_SCENARIOS:
        if s["name"] == name:
            return s
    raise KeyError(name)


def kinds_covered():
    """Every fault kind some scenario injects (backs the lint gate that
    compares this against ``faults._KINDS``)."""
    kinds = set()
    for s in SCENARIOS + STREAM_SCENARIOS:
        for build in s["plans"].values():
            kinds.update(r.kind for r in build().rules)
    return kinds


def all_kinds():
    return set(_KINDS)


def run_scenario(scenario, root):
    """Launch the scenario's n processes against a fresh ``root``;
    returns ``(results, elapsed_s)`` with ``results[pid] = (rc, out)``.
    Never raises on process failure — ``check`` renders the verdict."""
    worker = os.path.join(HERE, scenario["worker"])
    procs = {}
    t0 = time.monotonic()
    for pid in range(scenario["n"]):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop(FAULT_PLAN_ENV, None)
        build = scenario["plans"].get(pid)
        if build is not None:
            env[FAULT_PLAN_ENV] = build().to_env()
        procs[pid] = subprocess.Popen(
            [sys.executable, worker, str(pid), str(scenario["n"]),
             root, scenario["mode"], str(scenario["timeout_s"])],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)
    results = {}
    for pid, p in procs.items():
        out = p.communicate(timeout=60 + 6 * scenario["timeout_s"])[0]
        results[pid] = (p.returncode, out)
    return results, time.monotonic() - t0


def main(argv=None):
    """The ``bin/chaos`` entry point: run the matrix (or a filtered
    subset) in a SEEDED deterministic order and print a verdict table.
    Exit 0 only if every scenario meets its oracle."""
    import argparse
    import random
    import tempfile

    ap = argparse.ArgumentParser(
        prog="chaos", description="kill-at-phase fault-injection matrix "
        "over real multi-process exchanges")
    ap.add_argument("--seed", type=int, default=0,
                    help="schedule seed: shuffles scenario order "
                    "deterministically (default 0 = table order)")
    ap.add_argument("--only", action="append", default=[],
                    help="run only scenarios whose name contains this "
                    "substring (repeatable)")
    ap.add_argument("--tier", choices=("tier1", "slow", "all"),
                    default="all", help="restrict to one tier")
    ap.add_argument("--root", default=None,
                    help="shuffle root parent dir (default: a fresh "
                    "temp dir per scenario)")
    ap.add_argument("--streaming", action="store_true",
                    help="run the standing-query kill/restart group "
                    "(supervised exactly-once recovery) instead of the "
                    "exchange matrix")
    ap.add_argument("--blockserver", action="store_true",
                    help="run only the disaggregated block-service "
                    "battery: kill-after-register adoption (zero "
                    "re-execution), register-gap deaths, and the "
                    "service-unavailable degradation path")
    ap.add_argument("--pool", action="store_true",
                    help="run only the elastic worker-pool battery: "
                    "reap-mid-fetch adoption (zero re-execution), "
                    "spawn exec-error convergence, and scale-up "
                    "mid-standing-query byte-identity")
    args = ap.parse_args(argv)

    table = STREAM_SCENARIOS if args.streaming else SCENARIOS
    todo = [s for s in table
            if args.tier in ("all", s["tier"])
            and (not args.only
                 or any(pat in s["name"] for pat in args.only))]
    if args.blockserver:
        todo = [s for s in todo if s["name"].startswith("blockserver-")]
    if args.pool:
        todo = [s for s in todo if s["name"].startswith("pool-")]
    if args.seed:
        random.Random(args.seed).shuffle(todo)
    if not todo:
        print("no scenarios matched")
        return 2

    rows, failed = [], 0
    for i, sc in enumerate(todo):
        parent = args.root or tempfile.mkdtemp(prefix="chaos-")
        root = os.path.join(parent, f"{i:02d}-{sc['name']}")
        print(f"[chaos] {i + 1}/{len(todo)} {sc['name']} "
              f"(phase {sc['phase']}, n={sc['n']}) ...", flush=True)
        try:
            results, elapsed = run_scenario(sc, root)
            bad = check(sc, results, elapsed)
        except Exception as e:               # runner plumbing, not verdict
            results, elapsed, bad = {}, 0.0, [f"runner error: {e!r}"]
        rows.append((sc, elapsed, bad))
        failed += bool(bad)
        for b in bad:
            print(f"  !! {b}", flush=True)
            for pid, (rc, out) in results.items():
                print(f"  -- p{pid} rc={rc} tail: "
                      f"{out.splitlines()[-3:]}", flush=True)

    name_w = max(len(s["name"]) for s, _e, _b in rows)
    phase_w = max(len(s["phase"]) for s, _e, _b in rows)
    print(f"\n{'scenario':<{name_w}}  {'phase':<{phase_w}}  "
          f"{'tier':<5}  {'s':>6}  verdict")
    for sc, elapsed, bad in rows:
        verdict = "PASS" if not bad else f"FAIL ({'; '.join(bad)})"
        print(f"{sc['name']:<{name_w}}  {sc['phase']:<{phase_w}}  "
              f"{sc['tier']:<5}  {elapsed:>6.1f}  {verdict}")
    print(f"\n{len(rows) - failed}/{len(rows)} scenarios passed "
          f"(seed {args.seed})")
    return 1 if failed else 0


def check(scenario, results, elapsed):
    """The oracle verdict: list of violation strings (empty = pass)."""
    bad = []
    bound = 3 * scenario["timeout_s"] + 30
    if elapsed >= bound:
        bad.append(f"elapsed {elapsed:.1f}s >= bound {bound:.1f}s")
    for pid, want in scenario["expect"].items():
        rc, out = results[pid]
        lines = [ln for ln in out.splitlines() if f"[p{pid}]" in ln]
        last = lines[-1] if lines else ""
        if "PARTIAL" in out:
            bad.append(f"p{pid}: PARTIAL result surfaced")
        if want == "DIED":
            if rc != 43:
                bad.append(f"p{pid}: rc {rc} != 43 (planned kill)")
        elif rc != 0:
            bad.append(f"p{pid}: rc {rc} != 0 ({last!r})")
        elif want == "OK" and f"[p{pid}] OK" not in last:
            bad.append(f"p{pid}: expected OK, got {last!r}")
        elif want == "FAILED" and "FAILED" not in last:
            bad.append(f"p{pid}: expected FAILED, got {last!r}")
        elif want == "HOSTMEM" and "FAILED-HOSTMEM" not in last:
            bad.append(f"p{pid}: expected FAILED-HOSTMEM, got {last!r}")
    return bad


if __name__ == "__main__":
    sys.exit(main())
