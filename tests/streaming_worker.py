"""Worker for the streaming chaos scenarios (not a test module itself —
launched as a subprocess by bin/chaos --streaming and test_recovery.py).

argv: <process_id> <n_processes> <shared_root> <mode> [timeout_s]

A 2-process supervised kill/restart pair over ONE shared checkpoint:

pid 1 (victim)     — writes its OS pid to ``root/victim.pid``, runs the
    standing query over the shared inputs with the ``FaultInjector``
    armed from SPARK_TPU_FAULT_PLAN (``die_after_state_commit`` or
    ``torn_checkpoint(..., die=True)``), and REALLY dies: exit 43 via
    ``os._exit`` at the planned commit phase.
pid 0 (supervisor) — writes the input feeds + a ready sentinel, waits
    for the victim process to disappear, then (a) runs an uninterrupted
    ORACLE lifetime against private ckpt/out dirs and (b) a RECOVERY
    lifetime over the victim's checkpoint and sink.  Prints
    ``[p0] OK <files> replayed=<n>`` only if the recovered sink is
    BYTE-identical to the oracle's and at least one batch was replayed;
    a mismatch prints ``[p0] PARTIAL`` (grepped out of every run).

mode "wagg"  — windowed aggregate (watermark + tumbling-window sum);
mode "dedup" — stateful dropDuplicates over (k, ts).
"""

import glob
import os
import sys
import time

pid = int(sys.argv[1])
n = int(sys.argv[2])
root = sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "wagg"
timeout_s = float(sys.argv[5]) if len(sys.argv) > 5 else 30.0

os.environ["JAX_PLATFORMS"] = "cpu"
# persistent jit cache (same dir + policy as conftest.py): worker
# subprocesses otherwise recompile every program on every test run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/spark_tpu_jax_cache_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from spark_tpu import types as T  # noqa: E402
from spark_tpu.parallel.faults import FaultInjector  # noqa: E402
from spark_tpu.sql import functions as F  # noqa: E402
from spark_tpu.sql.dataframe import DataFrame  # noqa: E402
from spark_tpu.sql.session import SparkSession  # noqa: E402
from spark_tpu.streaming.core import (  # noqa: E402
    FileSink, FileStreamSource, StreamExecution, StreamingRelation)


def sec(x):
    return int(x * 1_000_000)


SCHEMA = T.StructType([
    T.StructField("ts", T.timestamp),
    T.StructField("k", T.string),
    T.StructField("v", T.int64),
])
FEEDS = [
    [(sec(1), "a", 1), (sec(9), "b", 2)],
    [(sec(20), "a", 4), (sec(21), "b", 1)],
    [(sec(35), "c", 8), (sec(35), "c", 8)],
    [(sec(50), "a", 3), (sec(51), "d", 9)],
]

in_dir = os.path.join(root, "in")
ready = os.path.join(root, "inputs_ready")
pidfile = os.path.join(root, "victim.pid")

spark = SparkSession.builder.appName(f"stream-chaos-{pid}").getOrCreate()


def shape(df):
    if mode == "dedup":
        return (df.withWatermark("ts", "5 seconds")
                .dropDuplicates(["k", "ts"]))
    return (df.withWatermark("ts", "5 seconds")
            .groupBy(F.window("ts", "10 seconds").alias("w"))
            .agg(F.sum("v").alias("s")))


def lifetime(ckpt, out, arm=False):
    src = FileStreamSource("parquet", in_dir, SCHEMA,
                          {"maxfilespertrigger": "1"})
    df = shape(DataFrame(spark, StreamingRelation(src)))
    ex = StreamExecution(spark, df._plan, FileSink("json", out, {}),
                         "append", ckpt, 0.1, None)
    if arm:
        FaultInjector().attach_stream(ex)   # plan from SPARK_TPU_FAULT_PLAN
    ex.process_all_available()
    return ex


def sink_files(out):
    return {os.path.basename(p): open(p, "rb").read()
            for p in sorted(glob.glob(os.path.join(out, "part-*")))}


deadline = time.monotonic() + timeout_s

if pid == 1:                                             # -- victim --
    os.makedirs(root, exist_ok=True)
    with open(pidfile, "w") as f:
        f.write(str(os.getpid()))
    while not os.path.exists(ready):
        if time.monotonic() > deadline:
            print("[p1] FAILED inputs never appeared", flush=True)
            os._exit(1)
        time.sleep(0.05)
    # the armed plan kills this process (os._exit(43)) mid-protocol;
    # reaching the end means the plan never fired — that is a failure
    lifetime(os.path.join(root, "ckpt"), os.path.join(root, "out"),
             arm=True)
    print("[p1] FAILED planned kill never fired", flush=True)
    os._exit(1)

# -- supervisor (pid 0) --
os.makedirs(in_dir, exist_ok=True)
for i, rows in enumerate(FEEDS):
    spark.createDataFrame({
        "ts": np.array([r[0] for r in rows], "datetime64[us]"),
        "k": [r[1] for r in rows],
        "v": np.array([r[2] for r in rows], np.int64),
    }).write.parquet(os.path.join(in_dir, f"f{i}"))
open(ready, "w").close()

def _dead(p):
    # the victim is the RUNNER's child, not ours: after the kill it
    # lingers as a zombie until the runner reaps it, so liveness has to
    # come from /proc state, not os.kill(p, 0)
    try:
        with open(f"/proc/{p}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] == "Z"
    except OSError:
        return True


victim = None
while time.monotonic() < deadline:
    if victim is None and os.path.exists(pidfile):
        victim = int(open(pidfile).read())
    if victim is not None and _dead(victim):
        break                               # the kill landed
    time.sleep(0.05)
else:
    print("[p0] FAILED victim never died", flush=True)
    os._exit(1)

oracle_out = os.path.join(root, "oracle_out")
lifetime(os.path.join(root, "oracle_ckpt"), oracle_out)
oracle = sink_files(oracle_out)

ex = lifetime(os.path.join(root, "ckpt"), os.path.join(root, "out"))
got = sink_files(os.path.join(root, "out"))
if got != oracle or not oracle:
    print(f"[p0] PARTIAL got={sorted(got)} exp={sorted(oracle)}",
          flush=True)
    os._exit(1)
if ex.metrics["replayed_batches"] < 1:
    print(f"[p0] FAILED nothing replayed: {ex.metrics}", flush=True)
    os._exit(1)
print(f"[p0] OK {len(got)} replayed={ex.metrics['replayed_batches']}",
      flush=True)
os._exit(0)
