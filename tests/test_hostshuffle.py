"""Cross-slice host shuffle service over a shared directory (VERDICT r2
missing #5 — the ExternalShuffleBlockResolver role for the DCN hop).

Two real OS processes exchange hash-partitioned batches through the
filesystem protocol; contents round-trip exactly, and stragglers fail
the barrier loudly instead of hanging.
"""

import os
import pickle
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from spark_tpu import config as C
from spark_tpu import wire
from spark_tpu.columnar import ColumnBatch
from spark_tpu.parallel.hostshuffle import HostShuffleService


def _batch(vals):
    return ColumnBatch.from_arrays(
        {"v": np.asarray(vals, np.int64)})


def test_single_process_roundtrip(tmp_path):
    svc = HostShuffleService(str(tmp_path), 0, 1, timeout_s=5)
    got = svc.exchange("e0", {0: [_batch([1, 2, 3])]})
    assert [int(x) for x in np.asarray(got[0].column("v").data)[:3]] \
        == [1, 2, 3]
    svc.cleanup("e0")
    assert not os.path.exists(os.path.join(str(tmp_path), "e0"))


def test_straggler_barrier_is_loud(tmp_path):
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=0.3)
    svc.commit("e1")
    with pytest.raises(TimeoutError, match=r"senders \[1\]"):
        svc.barrier("e1")


_WORKER = textwrap.dedent("""
    import sys, pickle
    import numpy as np
    sys.path.insert(0, {repo!r})
    from spark_tpu.columnar import ColumnBatch
    from spark_tpu.parallel.hostshuffle import HostShuffleService

    pid = int(sys.argv[1]); root = sys.argv[2]
    svc = HostShuffleService(root, pid, 2, timeout_s=60)
    # each process holds rows pid*100 .. pid*100+9 and routes by parity:
    # receiver 0 gets evens, receiver 1 gets odds
    rows = np.arange(pid * 100, pid * 100 + 10, dtype=np.int64)
    per = {{r: [ColumnBatch.from_arrays({{"v": rows[rows % 2 == r]}})]
           for r in (0, 1)}}
    mine = svc.exchange(f"ex", per)
    got = sorted(int(x) for b in mine
                 for x, ok in zip(np.asarray(b.column("v").data),
                                  np.asarray(b.row_valid_or_true()))
                 if ok)
    print("GOT", pid, got, flush=True)
""")


# ---------------------------------------------------------------------------
# wire data plane: no pickle on disk, no padding on disk, overlapped I/O
# ---------------------------------------------------------------------------

def _block_path(root, exchange, sender, receiver):
    return os.path.join(str(root), exchange,
                        f"s{sender:04d}-r{receiver:04d}.part")


def test_blocks_on_disk_are_wire_format(tmp_path):
    """Shuffle blocks are framed columnar buffers, not pickle: the file
    leads with the wire magic, the pickle module rejects it, and the
    codec alone round-trips the contents."""
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5)
    svc.put("e", 1, [_batch([1, 2, 3])])
    svc.flush("e")
    with open(_block_path(tmp_path, "e", 0, 1), "rb") as f:
        data = f.read()
    assert data[:4] == wire.MAGIC
    assert not data.startswith(b"\x80")      # pickle protocol-2+ prelude
    with pytest.raises(pickle.UnpicklingError):
        pickle.loads(data)
    got = wire.decode_batches(data)
    assert [int(x) for x in np.asarray(got[0].column("v").data)] == [1, 2, 3]


def test_padding_never_written(tmp_path):
    """A static-capacity batch (64 slots, 5 live rows) is compacted
    before encode: the on-disk frame holds exactly the live rows and
    carries no row mask at all."""
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5)
    b = ColumnBatch.from_arrays({"v": np.arange(5, dtype=np.int64)},
                                capacity=64)
    assert b.capacity == 64
    svc.put("e", 1, [b])
    svc.flush("e")
    with open(_block_path(tmp_path, "e", 0, 1), "rb") as f:
        info = wire.frame_info(f.read())
    (meta,) = info["batches"]
    assert meta["capacity"] == 5
    assert meta["row_valid"] is None


def test_async_write_roundtrip_and_data_plane_counters(tmp_path):
    """The default background-writer path: puts return before the disk
    write, commit() drains, and the byte/time observability the bench
    and metrics Source read is populated."""
    svc0, svc1 = (HostShuffleService(str(tmp_path), p, 2, timeout_s=5)
                  for p in (0, 1))
    assert svc0.async_write
    svc1.put("e", 0, [_batch([9])])
    svc1.commit("e")
    got = svc0.exchange("e", {0: [_batch([1, 2])], 1: [_batch([3])]})
    vals = sorted(int(x) for b in got
                  for x, ok in zip(np.asarray(b.column("v").data),
                                   np.asarray(b.row_valid_or_true())) if ok)
    assert vals == [1, 2, 9]
    c = svc0.counters
    assert c["blocks_written"] >= 1 and c["blocks_read"] >= 1
    assert c["bytes_written"] > 0 and c["bytes_read"] > 0
    assert c["bytes_raw"] > 0
    assert svc0.timers["encode_s"] > 0 and svc0.timers["decode_s"] > 0
    snap = {g: fn() for g, fn in svc0.metrics_source().gauges.items()}
    assert snap["compression_ratio"] > 0


def test_sync_write_conf_path(tmp_path):
    """asyncWrite=false keeps every put synchronous — no writer thread
    is ever started and the block is on disk when put() returns."""
    conf = C.Conf().set("spark.tpu.shuffle.io.asyncWrite", "false")
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5, conf=conf)
    assert not svc.async_write
    svc.put("e", 1, [_batch([4, 5])])
    assert svc._writer is None
    assert os.path.exists(_block_path(tmp_path, "e", 0, 1))


def test_concurrent_fetch_many_senders_keeps_sender_order(tmp_path):
    """Four senders' blocks stream through the fetch pool; the merged
    output is still deterministic sender order (0,1,2,3) regardless of
    which thread finishes first."""
    root = str(tmp_path)
    svcs = [HostShuffleService(root, p, 4, timeout_s=10) for p in range(4)]
    for p in (1, 2, 3):
        svcs[p].put("e", 0, [_batch([p * 10, p * 10 + 1])])
        svcs[p].commit("e")
    got = svcs[0].exchange(
        "e", {0: [_batch([0, 1])], 1: [], 2: [], 3: []})
    order = [int(np.asarray(b.column("v").data)[0]) for b in got]
    assert order == [0, 10, 20, 30]
    assert svcs[0].counters["blocks_read"] == 3


def test_legacy_pickle_block_still_readable(tmp_path):
    """A pre-wire-format block (raw pickle payload) left on disk by an
    older sender is sniffed by magic and decoded via the fallback."""
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=5)
    path = _block_path(tmp_path, "e", 1, 0)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump([_batch([6, 7]).to_host()], f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    got = svc.collect("e")
    assert [int(x) for x in np.asarray(got[0].column("v").data)[:2]] == [6, 7]


def test_two_process_all_to_all(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo="/root/repo"))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(tmp_path / "shuf")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = [p.communicate(timeout=90)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    # receiver 0 = all evens from both hosts, receiver 1 = all odds
    expect = {0: sorted(v for v in list(range(0, 10)) +
                        list(range(100, 110)) if v % 2 == 0),
              1: sorted(v for v in list(range(0, 10)) +
                        list(range(100, 110)) if v % 2 == 1)}
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("GOT")][0]
        got = eval(line.split(" ", 2)[2])
        assert got == expect[pid], (pid, got)
