"""Cross-slice host shuffle service over a shared directory (VERDICT r2
missing #5 — the ExternalShuffleBlockResolver role for the DCN hop).

Two real OS processes exchange hash-partitioned batches through the
filesystem protocol; contents round-trip exactly, and stragglers fail
the barrier loudly instead of hanging.
"""

import os
import pickle
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from spark_tpu.columnar import ColumnBatch
from spark_tpu.parallel.hostshuffle import HostShuffleService


def _batch(vals):
    return ColumnBatch.from_arrays(
        {"v": np.asarray(vals, np.int64)})


def test_single_process_roundtrip(tmp_path):
    svc = HostShuffleService(str(tmp_path), 0, 1, timeout_s=5)
    got = svc.exchange("e0", {0: [_batch([1, 2, 3])]})
    assert [int(x) for x in np.asarray(got[0].column("v").data)[:3]] \
        == [1, 2, 3]
    svc.cleanup("e0")
    assert not os.path.exists(os.path.join(str(tmp_path), "e0"))


def test_straggler_barrier_is_loud(tmp_path):
    svc = HostShuffleService(str(tmp_path), 0, 2, timeout_s=0.3)
    svc.commit("e1")
    with pytest.raises(TimeoutError, match=r"senders \[1\]"):
        svc.barrier("e1")


_WORKER = textwrap.dedent("""
    import sys, pickle
    import numpy as np
    sys.path.insert(0, {repo!r})
    from spark_tpu.columnar import ColumnBatch
    from spark_tpu.parallel.hostshuffle import HostShuffleService

    pid = int(sys.argv[1]); root = sys.argv[2]
    svc = HostShuffleService(root, pid, 2, timeout_s=60)
    # each process holds rows pid*100 .. pid*100+9 and routes by parity:
    # receiver 0 gets evens, receiver 1 gets odds
    rows = np.arange(pid * 100, pid * 100 + 10, dtype=np.int64)
    per = {{r: [ColumnBatch.from_arrays({{"v": rows[rows % 2 == r]}})]
           for r in (0, 1)}}
    mine = svc.exchange(f"ex", per)
    got = sorted(int(x) for b in mine
                 for x, ok in zip(np.asarray(b.column("v").data),
                                  np.asarray(b.row_valid_or_true()))
                 if ok)
    print("GOT", pid, got, flush=True)
""")


def test_two_process_all_to_all(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo="/root/repo"))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(tmp_path / "shuf")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = [p.communicate(timeout=90)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    # receiver 0 = all evens from both hosts, receiver 1 = all odds
    expect = {0: sorted(v for v in list(range(0, 10)) +
                        list(range(100, 110)) if v % 2 == 0),
              1: sorted(v for v in list(range(0, 10)) +
                        list(range(100, 110)) if v % 2 == 1)}
    for pid, out in enumerate(outs):
        line = [ln for ln in out.splitlines() if ln.startswith("GOT")][0]
        got = eval(line.split(" ", 2)[2])
        assert got == expect[pid], (pid, got)
