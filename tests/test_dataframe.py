"""DataFrame API end-to-end tests (jit execution path) vs pandas oracles."""

import numpy as np
import pandas as pd
import pytest

from spark_tpu import types as T
import spark_tpu.sql.functions as F
from spark_tpu.expressions import AnalysisException


@pytest.fixture()
def people(spark):
    return spark.createDataFrame(
        [(1, "alice", 30, 50.5), (2, "bob", None, 80.0), (3, "carol", 25, 10.0),
         (4, "dave", 35, None), (5, "eve", 25, 99.0)],
        ["id", "name", "age", "score"])


def test_create_and_collect(people):
    rows = people.collect()
    assert len(rows) == 5
    assert rows[0].name == "alice"
    assert rows[1].age is None
    assert rows[0].asDict()["id"] == 1


def test_schema(people):
    assert people.columns == ["id", "name", "age", "score"]
    assert dict(people.dtypes)["name"] == "string"
    assert dict(people.dtypes)["score"] == "double"


def test_select_expr_arithmetic(people):
    df = people.select((people.id * 10).alias("x"), F.col("name"))
    rows = df.collect()
    assert rows[0].x == 10 and rows[4].x == 50
    assert rows[2].name == "carol"


def test_filter_chain(people):
    out = people.filter(F.col("age") >= 25).filter(people.score > 20).collect()
    assert [r.name for r in out] == ["alice", "eve"]


def test_with_column_and_drop(people):
    df = people.withColumn("double_score", people.score * 2).drop("age")
    assert df.columns == ["id", "name", "score", "double_score"]
    rows = df.collect()
    assert rows[0].double_score == 101.0


def test_group_by_agg(people):
    out = (people.groupBy("age")
           .agg(F.count("*").alias("n"), F.avg("score").alias("avg_s"))
           .orderBy("age")
           .collect())
    # ages: 25 (carol 10.0, eve 99.0), 30 (alice), 35 (dave, null score), null (bob)
    assert [(r.age, r.n) for r in out] == [(None, 1), (25, 2), (30, 1), (35, 1)]
    d = {r.age: r.avg_s for r in out}
    assert d[25] == pytest.approx(54.5)
    assert d[35] is None  # avg of all-null


def test_agg_compound_expression(people):
    out = people.groupBy().agg(
        (F.sum("score") / F.count("score")).alias("manual_avg"),
        F.max(F.col("score") + 1).alias("mp1"),
    ).collect()
    assert out[0].manual_avg == pytest.approx((50.5 + 80.0 + 10.0 + 99.0) / 4)
    assert out[0].mp1 == pytest.approx(100.0)


def test_distinct_count(people, spark):
    df = spark.createDataFrame([(1, "a"), (1, "a"), (2, "b")], ["x", "y"])
    assert df.distinct().count() == 2
    assert df.count() == 3


def test_count_distinct(spark):
    df = spark.createDataFrame([(1, "a"), (1, "b"), (2, "a"), (1, "a")], ["k", "v"])
    out = (df.groupBy("k").agg(F.countDistinct("v").alias("nv"))
           .orderBy("k").collect())
    assert [(r.k, r.nv) for r in out] == [(1, 2), (2, 1)]


def test_order_by_desc_nulls(people):
    out = people.orderBy(people.age.desc_nulls_last()).collect()
    assert [r.age for r in out] == [35, 30, 25, 25, None]
    out2 = people.orderBy("age", ascending=False).collect()
    assert out2[-1].age is None  # DESC default nulls last


def test_limit_after_sort(people):
    out = people.orderBy(people.score.desc()).limit(2).collect()
    assert [r.name for r in out] == ["eve", "bob"]


def test_union(spark):
    a = spark.createDataFrame([(1, "x")], ["i", "s"])
    b = spark.createDataFrame([(2, "y"), (3, "x")], ["i", "s"])
    out = a.union(b).orderBy("i").collect()
    assert [(r.i, r.s) for r in out] == [(1, "x"), (2, "y"), (3, "x")]


def test_inner_join_using(spark):
    emp = spark.createDataFrame(
        [(1, "alice", 10), (2, "bob", 20), (3, "carol", 10), (4, "dan", 99)],
        ["id", "name", "dept_id"])
    dept = spark.createDataFrame(
        [(10, "eng"), (20, "sales")], ["dept_id", "dept"])
    out = (emp.join(dept, "dept_id").orderBy("id").collect())
    assert [(r.id, r.name, r.dept) for r in out] == [
        (1, "alice", "eng"), (2, "bob", "sales"), (3, "carol", "eng")]
    assert out[0].__fields__ == ["dept_id", "id", "name", "dept"] or \
           "dept_id" in out[0].__fields__


def test_left_join_nulls(spark):
    emp = spark.createDataFrame(
        [(1, 10), (2, 99)], ["id", "dept_id"])
    dept = spark.createDataFrame([(10, "eng")], ["dept_id", "dept"])
    out = emp.join(dept, "dept_id", "left").orderBy("id").collect()
    assert [(r.id, r.dept) for r in out] == [(1, "eng"), (2, None)]


def test_right_and_full_join(spark):
    a = spark.createDataFrame([(1, "a1"), (2, "a2")], ["k", "av"])
    b = spark.createDataFrame([(2, "b2"), (3, "b3")], ["k", "bv"])
    r = a.join(b, "k", "right").orderBy("k").collect()
    assert [(x.k, x.av, x.bv) for x in r] == [(2, "a2", "b2"), (3, None, "b3")]
    f = a.join(b, "k", "full").orderBy("k").collect()
    assert [(x.k, x.av, x.bv) for x in f] == [
        (1, "a1", None), (2, "a2", "b2"), (3, None, "b3")]


def test_semi_anti_join(spark):
    a = spark.createDataFrame([(1,), (2,), (3,)], ["k"])
    b = spark.createDataFrame([(2,), (2,), (4,)], ["k"])
    semi = a.join(b, "k", "left_semi").orderBy("k").collect()
    assert [r.k for r in semi] == [2]
    anti = a.join(b, "k", "left_anti").orderBy("k").collect()
    assert [r.k for r in anti] == [1, 3]


def test_join_duplicate_keys_expansion(spark):
    a = spark.createDataFrame([(1, "l1"), (1, "l2"), (2, "l3")], ["k", "lv"])
    b = spark.createDataFrame([(1, "r1"), (1, "r2")], ["k", "rv"])
    out = a.join(b, "k").collect()
    pairs = sorted((r.lv, r.rv) for r in out)
    assert pairs == [("l1", "r1"), ("l1", "r2"), ("l2", "r1"), ("l2", "r2")]


def test_join_string_keys_different_dictionaries(spark):
    a = spark.createDataFrame([("apple", 1), ("fig", 2)], ["s", "x"])
    b = spark.createDataFrame([("fig", 20), ("pear", 30)], ["s", "y"])
    out = a.join(b, "s").collect()
    assert [(r.s, r.x, r.y) for r in out] == [("fig", 2, 20)]


def test_join_condition_expr(spark):
    a = spark.createDataFrame([(1, 5)], ["ida", "va"])
    b = spark.createDataFrame([(1, 3), (1, 9)], ["idb", "vb"])
    out = a.join(b, (F.col("ida") == F.col("idb")) & (F.col("vb") > F.col("va"))).collect()
    assert [(r.ida, r.vb) for r in out] == [(1, 9)]


def test_join_overflow_auto_recovery_small(spark):
    a = spark.createDataFrame([(1,)] * 8, ["k"])
    b = spark.createDataFrame([(1, i) for i in range(8)], ["k", "v"])
    # 8×8 = 64 output rows ≫ 8×factor(1.0) capacity → the adaptive retry
    # must grow the factor and return all 64 rows (never truncate)
    out = a.join(b, "k").collect()
    assert len(out) == 64


def test_cross_join(spark):
    a = spark.createDataFrame([(1,), (2,)], ["x"])
    b = spark.createDataFrame([("p",), ("q",)], ["y"])
    out = a.crossJoin(b).collect()
    assert sorted((r.x, r.y) for r in out) == [
        (1, "p"), (1, "q"), (2, "p"), (2, "q")]


def test_range(spark):
    assert spark.range(5).count() == 5
    rows = spark.range(2, 10, 3).collect()
    assert [r.id for r in rows] == [2, 5, 8]


def test_dropna_fillna(people):
    assert people.dropna(subset=["age"]).count() == 4
    filled = people.fillna(0, subset=["age"]).collect()
    assert [r.age for r in filled] == [30, 0, 25, 35, 25]


def test_drop_duplicates_subset(spark):
    df = spark.createDataFrame(
        [(1, "a"), (1, "b"), (2, "c")], ["k", "v"])
    out = df.dropDuplicates(["k"]).orderBy("k").collect()
    assert [r.k for r in out] == [1, 2]
    assert out[0].v in ("a", "b")


def test_sample_deterministic(spark):
    df = spark.range(1000)
    n1 = df.sample(0.3, seed=1).count()
    n2 = df.sample(0.3, seed=1).count()
    assert n1 == n2
    assert 200 < n1 < 400


def test_temp_view_and_table(people, spark):
    people.createOrReplaceTempView("people")
    df = spark.table("people")
    assert df.count() == 5


def test_cache(people):
    df = people.filter(F.col("id") <= 3).cache()
    assert df.count() == 3
    assert len(df.collect()) == 3


def test_unresolved_column_error(people):
    with pytest.raises(AnalysisException, match="cannot resolve"):
        people.select(F.col("nope")).collect()


def test_union_type_mismatch_error(spark):
    a = spark.createDataFrame([(1,)], ["x"])
    b = spark.createDataFrame([("s",)], ["x"])
    with pytest.raises(AnalysisException, match="union"):
        a.union(b).schema


def test_explain_smoke(people, capsys):
    people.filter(people.id > 1).select("name").explain(extended=True)
    out = capsys.readouterr().out
    assert "Filter" in out and "Physical" in out


def test_toPandas_roundtrip(people):
    pdf = people.toPandas()
    assert list(pdf.columns) == ["id", "name", "age", "score"]
    assert len(pdf) == 5


def test_optimizer_pushes_filter_through_project(people, spark):
    from spark_tpu.sql.planner import QueryExecution
    df = people.select((F.col("id") * 2).alias("x")).filter(F.col("x") > 4)
    qe = QueryExecution(spark, df._plan)
    s = qe.optimized.tree_string()
    # Filter must sit below Project after pushdown
    assert s.index("Project") < s.index("Filter")
    assert [r.x for r in df.collect()] == [6, 8, 10]


def test_constant_folding(spark):
    from spark_tpu.sql.planner import QueryExecution
    df = spark.range(3).select((F.lit(2) + F.lit(3) * F.lit(4)).alias("c"))
    qe = QueryExecution(spark, df._plan)
    assert "14" in qe.optimized.tree_string()
    assert [r.c for r in df.collect()] == [14, 14, 14]


def test_join_output_overflow_auto_recovery(spark):
    """High key multiplicity overflows the static join output buffer; the
    executor must replan with a factor sized from the measured overflow
    and return the exact result instead of erroring."""
    import numpy as np
    left = spark.createDataFrame({"k": np.zeros(100, np.int64),
                                  "i": np.arange(100, dtype=np.int64)})
    right = spark.createDataFrame({"k": np.zeros(100, np.int64),
                                   "j": np.arange(100, dtype=np.int64)})
    out = left.join(right, "k")
    assert len(out.collect()) == 100 * 100
