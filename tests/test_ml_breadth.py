"""Round-5 ML breadth: MLP (ann), Word2Vec, CountVectorizer, stat
(Correlation / ChiSquareTest), FPGrowth — each through the Pipeline API
with a sklearn/scipy/brute-force oracle (VERDICT r4 item 7)."""

import itertools

import numpy as np
import pytest

from spark_tpu.ml.ann import MultilayerPerceptronClassifier
from spark_tpu.ml.base import Pipeline
from spark_tpu.ml.feature import (
    CountVectorizer, Tokenizer, Word2Vec,
)
from spark_tpu.ml.fpm import FPGrowth
from spark_tpu.ml.stat import ChiSquareTest, Correlation


# ---------------------------------------------------------------------------
# MLP
# ---------------------------------------------------------------------------

def _xor_df(spark, n=400, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, (n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.float64)
    return spark.createDataFrame({"features": X, "label": y}), X, y


def test_mlp_learns_xor(spark):
    """XOR is not linearly separable: a hidden layer must do real work."""
    df, X, y = _xor_df(spark)
    mlp = MultilayerPerceptronClassifier(layers=[2, 8, 2], maxIter=400,
                                         stepSize=0.05, seed=7)
    model = mlp.fit(df)
    got = np.array([r["prediction"] for r in model.transform(df).collect()])
    acc = (got == y).mean()
    assert acc >= 0.95, acc


def test_mlp_matches_sklearn_on_blobs(spark):
    from sklearn.neural_network import MLPClassifier
    rng = np.random.default_rng(0)
    n = 300
    X = np.vstack([rng.normal(0, 0.6, (n // 3, 2)) + c
                   for c in ([2, 2], [-2, 2], [0, -2])])
    y = np.repeat([0.0, 1.0, 2.0], n // 3)
    df = spark.createDataFrame({"features": X, "label": y})
    model = MultilayerPerceptronClassifier(
        layers=[2, 16, 3], maxIter=300, seed=1).fit(df)
    ours = np.array([r["prediction"]
                     for r in model.transform(df).collect()])
    sk = MLPClassifier(hidden_layer_sizes=(16,), max_iter=2000,
                       random_state=1).fit(X, y).predict(X)
    assert (ours == y).mean() >= 0.95
    assert (sk == y).mean() >= 0.95            # same problem, same bar
    # probability column is a proper distribution
    probs = np.array([r["probability"]
                      for r in model.transform(df).collect()])
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)


def test_mlp_in_pipeline_and_validation_errors(spark):
    df, X, y = _xor_df(spark, n=120)
    pipe = Pipeline(stages=[MultilayerPerceptronClassifier(
        layers=[2, 6, 2], maxIter=150, seed=5)])
    out = pipe.fit(df).transform(df)
    assert "prediction" in out.columns
    with pytest.raises(ValueError, match="layers"):
        MultilayerPerceptronClassifier(layers=[2]).fit(df)
    with pytest.raises(ValueError, match="feature dim"):
        MultilayerPerceptronClassifier(layers=[3, 4, 2]).fit(df)


# ---------------------------------------------------------------------------
# CountVectorizer
# ---------------------------------------------------------------------------

def test_count_vectorizer_vs_sklearn(spark):
    from sklearn.feature_extraction.text import CountVectorizer as SkCV
    docs = ["the cat sat on the mat",
            "the dog sat on the log",
            "cats and dogs and cats"]
    df = spark.createDataFrame({"text": docs})
    out = Pipeline(stages=[
        Tokenizer(inputCol="text", outputCol="toks"),
        CountVectorizer(inputCol="toks", outputCol="counts"),
    ]).fit(df).transform(df)
    rows = out.collect()
    model = CountVectorizer(inputCol="toks", outputCol="counts").fit(
        Tokenizer(inputCol="text", outputCol="toks").transform(df))
    vocab = model.getOrDefault("vocabulary")

    sk = SkCV(token_pattern=r"\S+").fit(docs)
    got = {w: np.array([r["counts"][vocab.index(w)] for r in rows])
           for w in vocab}
    mat = sk.transform(docs).toarray()
    for w, col in got.items():
        np.testing.assert_array_equal(col, mat[:, sk.vocabulary_[w]])
    # vocab ordering: corpus frequency descending
    assert vocab[0] == "the"


def test_count_vectorizer_mindf_binary(spark):
    df = spark.createDataFrame({"text": ["a a b", "a c", "a d"]})
    toks = Tokenizer(inputCol="text", outputCol="t").transform(df)
    model = CountVectorizer(inputCol="t", outputCol="v", minDF=2,
                            binary=True).fit(toks)
    assert model.getOrDefault("vocabulary") == ["a"]
    rows = model.transform(toks).collect()
    assert [r["v"][0] for r in rows] == [1.0, 1.0, 1.0]


# ---------------------------------------------------------------------------
# Word2Vec
# ---------------------------------------------------------------------------

def test_word2vec_clusters_contexts(spark):
    """Words sharing contexts embed closer than unrelated words."""
    rng = np.random.default_rng(5)
    animals = ["cat", "dog", "cow"]
    tools = ["hammer", "wrench", "drill"]
    docs = []
    for _ in range(150):
        a = rng.choice(animals, 3, replace=True)
        docs.append(" ".join(["the", a[0], "chased", "the", a[1], "and",
                              a[2]]))
        t = rng.choice(tools, 3, replace=True)
        docs.append(" ".join(["use", "the", t[0], "with", "the", t[1],
                              "and", t[2]]))
    df = spark.createDataFrame({"text": docs})
    toks = Tokenizer(inputCol="text", outputCol="toks").transform(df)
    model = Word2Vec(inputCol="toks", outputCol="vec", vectorSize=16,
                     minCount=2, maxIter=3, seed=2).fit(toks)
    syn = model.findSynonyms("cat", 2)
    assert {w for w, _ in syn} <= set(animals) | {"chased"}, syn
    # document vectors exist and have the right width
    rows = model.transform(toks).collect()
    assert len(rows[0]["vec"]) == 16
    # getVectors round-trips through the engine
    vocab_df = model.getVectors(spark)
    words = {r["word"] for r in vocab_df.collect()}
    assert set(animals) | set(tools) <= words


def test_word2vec_deterministic_under_seed(spark):
    df = spark.createDataFrame({"text": ["a b c d e"] * 30})
    toks = Tokenizer(inputCol="text", outputCol="t").transform(df)
    m1 = Word2Vec(inputCol="t", outputCol="v", vectorSize=8, minCount=1,
                  seed=9).fit(toks)
    m2 = Word2Vec(inputCol="t", outputCol="v", vectorSize=8, minCount=1,
                  seed=9).fit(toks)
    np.testing.assert_array_equal(
        np.asarray(m1.getOrDefault("vectors")),
        np.asarray(m2.getOrDefault("vectors")))


# ---------------------------------------------------------------------------
# stat: Correlation + ChiSquareTest
# ---------------------------------------------------------------------------

def test_correlation_pearson_vs_numpy(spark):
    rng = np.random.default_rng(7)
    X = rng.normal(0, 1, (200, 4))
    X[:, 1] = 2 * X[:, 0] + rng.normal(0, 0.1, 200)   # strongly correlated
    df = spark.createDataFrame({"features": X})
    rows = Correlation.corr(df, "features").collect()
    got = np.array([r[0] for r in rows])
    np.testing.assert_allclose(got, np.corrcoef(X, rowvar=False),
                               atol=1e-12)


def test_correlation_spearman_vs_scipy(spark):
    from scipy.stats import spearmanr
    rng = np.random.default_rng(8)
    X = rng.normal(0, 1, (150, 3))
    X[:, 2] = np.exp(X[:, 0])            # monotone, nonlinear
    df = spark.createDataFrame({"features": X})
    rows = Correlation.corr(df, "features", "spearman").collect()
    got = np.array([r[0] for r in rows])
    exp = spearmanr(X).statistic
    np.testing.assert_allclose(got, exp, atol=1e-12)


def test_chisquare_vs_scipy(spark):
    from scipy.stats import chi2_contingency
    rng = np.random.default_rng(9)
    n = 500
    y = rng.integers(0, 2, n).astype(np.float64)
    f0 = np.where(rng.uniform(size=n) < 0.3 + 0.4 * y, 1.0, 0.0)  # dependent
    f1 = rng.integers(0, 3, n).astype(np.float64)                 # independent
    X = np.stack([f0, f1], axis=1)
    df = spark.createDataFrame({"features": X, "label": y})
    row, = ChiSquareTest.test(df, "features", "label").collect()
    pvals, dofs, stats = row["pValues"], row["degreesOfFreedom"], \
        row["statistics"]
    for j in range(2):
        obs = np.zeros((len(np.unique(X[:, j])), 2))
        for fi, yi in zip(X[:, j], y):
            obs[int(np.searchsorted(np.unique(X[:, j]), fi)), int(yi)] += 1
        ref = chi2_contingency(obs, correction=False)
        assert stats[j] == pytest.approx(ref.statistic, rel=1e-10)
        assert dofs[j] == ref.dof
        assert pvals[j] == pytest.approx(ref.pvalue, abs=1e-10)
    assert pvals[0] < 0.01 < pvals[1]


# ---------------------------------------------------------------------------
# FPGrowth
# ---------------------------------------------------------------------------

def _brute_itemsets(transactions, min_count, max_len=4):
    items = sorted({i for t in transactions for i in t})
    out = {}
    for k in range(1, max_len + 1):
        for combo in itertools.combinations(items, k):
            sup = sum(1 for t in transactions if set(combo) <= set(t))
            if sup >= min_count:
                out[combo] = sup
    return out


def test_fpgrowth_vs_bruteforce(spark):
    transactions = [
        ["bread", "milk"],
        ["bread", "diapers", "beer", "eggs"],
        ["milk", "diapers", "beer", "cola"],
        ["bread", "milk", "diapers", "beer"],
        ["bread", "milk", "diapers", "cola"],
    ]
    df = spark.createDataFrame({"items": ["\x00".join(t)
                                          for t in transactions]})
    model = FPGrowth(itemsCol="items", minSupport=0.6,
                     minConfidence=0.7).fit(df)
    got = {tuple(r["items"].split("\x00")): r["freq"]
           for r in model.freqItemsets(spark).collect()}
    exp = _brute_itemsets(transactions, min_count=3)
    assert got == exp

    rules = model.associationRules(spark).collect()
    for r in rules:
        ant = set(r["antecedent"].split("\x00"))
        sup_ant = sum(1 for t in transactions if ant <= set(t))
        sup_both = sum(1 for t in transactions
                       if ant | {r["consequent"]} <= set(t))
        assert r["confidence"] == pytest.approx(sup_both / sup_ant)
        assert r["confidence"] >= 0.7


def test_fpgrowth_transform_predicts_consequents(spark):
    df = spark.createDataFrame({"items": [
        "a\x00b", "a\x00b", "a\x00b", "a\x00b\x00c", "a\x00c",
    ]})
    model = FPGrowth(itemsCol="items", minSupport=0.4,
                     minConfidence=0.6).fit(df)
    pred_df = model.transform(
        spark.createDataFrame({"items": ["a", "b", "a\x00b"]}))
    preds = [r["prediction"] for r in pred_df.collect()]
    # {a} -> b holds with confidence 4/5; row already holding b gets
    # nothing new from it
    assert "b" in (preds[0] or "").split("\x00")
    assert "a" in (preds[1] or "").split("\x00")
    assert "b" not in (preds[2] or "").split("\x00")


def test_fpgrowth_association_rules_confidence_filter(spark):
    df = spark.createDataFrame({"items": ["x\x00y"] * 8 + ["x"] * 2})
    m_low = FPGrowth(itemsCol="items", minSupport=0.1,
                     minConfidence=0.9).fit(df)
    rules = {(r["antecedent"], r["consequent"]): r["confidence"]
             for r in m_low.associationRules(spark).collect()}
    # y -> x has confidence 1.0; x -> y only 0.8 and must be filtered
    assert ("y", "x") in rules
    assert rules[("y", "x")] == pytest.approx(1.0)
    assert ("x", "y") not in rules


# ---------------------------------------------------------------------------
# GaussianMixture / IsotonicRegression / AFTSurvivalRegression (round-5
# second wave of ml/ breadth)
# ---------------------------------------------------------------------------

def test_gaussian_mixture_vs_sklearn(spark):
    from sklearn.mixture import GaussianMixture as SkGMM
    from spark_tpu.ml.clustering import GaussianMixture
    rng = np.random.default_rng(4)
    X = np.vstack([rng.normal([-3, 0], [0.5, 0.5], (150, 2)),
                   rng.normal([3, 1], [0.7, 0.3], (150, 2))])
    df = spark.createDataFrame({"features": X})
    model = GaussianMixture(k=2, maxIter=80, seed=3).fit(df)
    ours = np.array([r["prediction"]
                     for r in model.transform(df).collect()])
    sk = SkGMM(2, random_state=0).fit(X)
    skp = sk.predict(X)
    # same partition up to label permutation
    agree = max((ours == skp).mean(), (ours == 1 - skp).mean())
    assert agree >= 0.98, agree
    # means match the true centers (sorted by x)
    mu = np.asarray(model.getOrDefault("means"))
    mu = mu[np.argsort(mu[:, 0])]
    np.testing.assert_allclose(mu[0], [-3, 0], atol=0.2)
    np.testing.assert_allclose(mu[1], [3, 1], atol=0.2)
    probs = np.array([r["probability"]
                      for r in model.transform(df).collect()])
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-9)


def test_isotonic_vs_sklearn(spark):
    from sklearn.isotonic import IsotonicRegression as SkIso
    from spark_tpu.ml.regression import IsotonicRegression
    rng = np.random.default_rng(6)
    x = np.sort(rng.uniform(0, 10, 120))
    y = np.log1p(x) + rng.normal(0, 0.15, 120)
    df = spark.createDataFrame({"features": x[:, None], "label": y})
    model = IsotonicRegression().fit(df)
    got = np.array([r["prediction"]
                    for r in model.transform(df).collect()])
    sk = SkIso(out_of_bounds="clip").fit(x, y).predict(x)
    np.testing.assert_allclose(got, sk, atol=1e-9)
    # monotone by construction
    assert np.all(np.diff(got) >= -1e-12)


def test_isotonic_decreasing(spark):
    from spark_tpu.ml.regression import IsotonicRegression
    x = np.arange(10, dtype=np.float64)
    y = -x + np.array([0.5, -0.5] * 5)
    df = spark.createDataFrame({"features": x[:, None], "label": y})
    got = np.array([r["prediction"] for r in
                    IsotonicRegression(isotonic=False).fit(df)
                    .transform(df).collect()])
    assert np.all(np.diff(got) <= 1e-12)


def test_aft_survival_recovers_scale(spark):
    """Weibull AFT on synthetic censored data: the fitted acceleration
    coefficients recover the generating model's direction and the
    prediction is monotone in the covariate."""
    from spark_tpu.ml.regression import AFTSurvivalRegression
    rng = np.random.default_rng(8)
    n = 600
    x = rng.normal(0, 1, (n, 1))
    # true: log T = 1.0 + 0.8 x + 0.5 * Gumbel(min)
    eps = np.log(rng.exponential(1.0, n))       # extreme-value noise
    logt = 1.0 + 0.8 * x[:, 0] + 0.5 * eps
    t = np.exp(logt)
    cens_time = rng.exponential(np.e ** 2.2, n)
    y = np.minimum(t, cens_time)
    c = (t <= cens_time).astype(np.float64)
    assert 0.2 < c.mean() < 0.95                # real censoring present
    df = spark.createDataFrame({"features": x, "label": y, "censor": c})
    model = AFTSurvivalRegression(maxIter=800).fit(df)
    coef = np.asarray(model.getOrDefault("coefficients"))
    assert coef[0] == pytest.approx(0.8, abs=0.15)
    assert model.getOrDefault("intercept") == pytest.approx(1.0, abs=0.2)
    assert model.getOrDefault("scale") == pytest.approx(0.5, abs=0.15)
    rows = model.transform(df).collect()
    preds = np.array([r["prediction"] for r in rows])
    assert np.corrcoef(preds, np.exp(1.0 + 0.8 * x[:, 0]))[0, 1] > 0.99


def test_isotonic_ties_pool_like_sklearn(spark):
    from sklearn.isotonic import IsotonicRegression as SkIso
    from spark_tpu.ml.regression import IsotonicRegression
    x = np.array([1.0, 1.0, 2.0, 2.0, 3.0])
    y = np.array([0.0, 1.0, 2.0, 0.0, 3.0])
    df = spark.createDataFrame({"features": x[:, None], "label": y})
    got = np.array([r["prediction"] for r in
                    IsotonicRegression().fit(df).transform(df).collect()])
    sk = SkIso(out_of_bounds="clip").fit(x, y).predict(x)
    np.testing.assert_allclose(got, sk, atol=1e-9)


def test_aft_rejects_nonpositive_labels(spark):
    from spark_tpu.ml.regression import AFTSurvivalRegression
    df = spark.createDataFrame({
        "features": np.ones((3, 1)), "label": np.array([1.0, 0.0, 2.0]),
        "censor": np.ones(3)})
    with pytest.raises(ValueError, match="positive"):
        AFTSurvivalRegression().fit(df)


def test_lda_recovers_topics(spark):
    """Two disjoint vocabularies: LDA must separate them into two topics
    and assign each doc's dominant topic correctly (same contract a
    sklearn LatentDirichletAllocation run satisfies on this corpus)."""
    from spark_tpu.ml.clustering import LDA
    rng = np.random.default_rng(12)
    V = 20
    n = 120
    C = np.zeros((n, V))
    truth = []
    for i in range(n):
        topic = i % 2
        words = rng.integers(0, 10, 30) + (10 * topic)
        np.add.at(C[i], words, 1.0)
        truth.append(topic)
    truth = np.array(truth)
    df = spark.createDataFrame({"features": C})
    model = LDA(k=2, maxIter=40, seed=5).fit(df)

    # topic-word: each learned topic concentrates on one half-vocab
    tm = model.topicsMatrix()                      # (V, k)
    mass_low = tm[:10].sum(axis=0)                 # per-topic mass on 0..9
    assert (mass_low.max() > 0.9) and (mass_low.min() < 0.1), mass_low
    low_topic = int(np.argmax(mass_low))

    # describeTopics exposes the top terms of the right half
    topics = model.describeTopics(5)
    top_terms = set(topics[low_topic][1])
    assert top_terms <= set(range(10)), topics

    # per-doc topic distribution puts docs on their generating topic
    rows = model.transform(df).collect()
    dist = np.array([r["topicDistribution"] for r in rows])
    np.testing.assert_allclose(dist.sum(axis=1), 1.0, atol=1e-9)
    pred_is_low = dist[:, low_topic] > 0.5
    want_low = truth == 0
    assert (pred_is_low == want_low).mean() >= 0.95

    # sklearn on the same corpus meets the same separation bar
    from sklearn.decomposition import LatentDirichletAllocation as SkLDA
    sk = SkLDA(2, random_state=0).fit(C)
    sk_low = sk.components_[:, :10].sum(1) / sk.components_.sum(1)
    assert sk_low.max() > 0.9 and sk_low.min() < 0.1


# ---------------------------------------------------------------------------
# round-5 feature-stage parity wave: IDF, Normalizer, MaxAbsScaler,
# StopWordsRemover, NGram, QuantileDiscretizer, Imputer,
# PolynomialExpansion, ElementwiseProduct, VectorSlicer
# ---------------------------------------------------------------------------

def test_idf_vs_sklearn(spark):
    from sklearn.feature_extraction.text import TfidfTransformer
    from spark_tpu.ml.feature import IDF
    C = np.array([[3.0, 0, 1], [2, 0, 0], [3, 0, 2], [4, 0, 3]])
    df = spark.createDataFrame({"tf": C})
    model = IDF(inputCol="tf", outputCol="tfidf").fit(df)
    got = np.array([r["tfidf"] for r in model.transform(df).collect()])
    sk = TfidfTransformer(norm=None, smooth_idf=True, sublinear_tf=False)
    exp = sk.fit_transform(C).toarray() - C          # sklearn idf = log+1
    np.testing.assert_allclose(got, exp, atol=1e-12)


def test_normalizer_and_maxabs(spark):
    from sklearn.preprocessing import MaxAbsScaler as SkMA, normalize
    from spark_tpu.ml.feature import MaxAbsScaler, Normalizer
    rng = np.random.default_rng(3)
    X = rng.normal(0, 3, (40, 4))
    df = spark.createDataFrame({"features": X})
    got = np.array([r["norm"] for r in Normalizer(
        inputCol="features", outputCol="norm").transform(df).collect()])
    np.testing.assert_allclose(got, normalize(X, "l2"), atol=1e-12)
    got1 = np.array([r["n1"] for r in Normalizer(
        inputCol="features", outputCol="n1", p=1.0)
        .transform(df).collect()])
    np.testing.assert_allclose(got1, normalize(X, "l1"), atol=1e-12)
    m = MaxAbsScaler(inputCol="features", outputCol="s").fit(df)
    got2 = np.array([r["s"] for r in m.transform(df).collect()])
    np.testing.assert_allclose(got2, SkMA().fit_transform(X), atol=1e-12)


def test_stopwords_and_ngram(spark):
    from spark_tpu.ml.feature import NGram, StopWordsRemover, Tokenizer
    df = spark.createDataFrame({"text": ["the quick brown fox",
                                         "I saw the saw"]})
    toks = Tokenizer(inputCol="text", outputCol="t").transform(df)
    out = StopWordsRemover(inputCol="t", outputCol="f").transform(toks)
    rows = [r["f"].split("\x00") for r in out.collect()]
    assert rows[0] == ["quick", "brown", "fox"]
    assert rows[1] == ["saw", "saw"]
    custom = StopWordsRemover(inputCol="t", outputCol="f2",
                              stopWords=["fox"]).transform(toks)
    assert [r["f2"].split("\x00") for r in custom.collect()][0] == \
        ["the", "quick", "brown"]
    grams = NGram(inputCol="t", outputCol="g", n=2).transform(toks)
    assert [r["g"] for r in grams.collect()][0] == \
        "the quick\x00quick brown\x00brown fox"


def test_quantile_discretizer(spark):
    from spark_tpu.ml.feature import QuantileDiscretizer
    x = np.arange(100, dtype=np.float64)
    df = spark.createDataFrame({"v": x})
    buck = QuantileDiscretizer(inputCol="v", outputCol="b",
                               numBuckets=4).fit(df)
    got = np.array([r["b"] for r in buck.transform(df).collect()])
    # near-equal mass per bucket
    counts = np.bincount(got.astype(int))
    assert len(counts) == 4 and counts.min() >= 20


def test_imputer_mean_median(spark):
    from spark_tpu.ml.feature import Imputer
    df = spark.createDataFrame({
        "a": np.array([1.0, np.nan, 3.0, np.nan]),
        "b": np.array([10.0, 20.0, np.nan, 40.0])})
    m = Imputer(inputCols=["a", "b"], outputCols=["ai", "bi"]).fit(df)
    rows = m.transform(df).collect()
    ai = [r["ai"] for r in rows]
    bi = [r["bi"] for r in rows]
    assert ai == [1.0, 2.0, 3.0, 2.0]
    assert bi == [10.0, 20.0, pytest.approx(70.0 / 3), 40.0]
    med = Imputer(inputCols=["a"], outputCols=["am"],
                  strategy="median").fit(df)
    assert [r["am"] for r in med.transform(df).collect()][1] == 2.0


def test_polynomial_expansion_vs_sklearn(spark):
    from sklearn.preprocessing import PolynomialFeatures
    from spark_tpu.ml.feature import PolynomialExpansion
    rng = np.random.default_rng(5)
    X = rng.normal(0, 1, (20, 3))
    df = spark.createDataFrame({"features": X})
    got = np.array([r["p"] for r in PolynomialExpansion(
        inputCol="features", outputCol="p", degree=3)
        .transform(df).collect()])
    exp = PolynomialFeatures(3, include_bias=False).fit_transform(X)
    # same monomial set — compare as sorted columns per row
    np.testing.assert_allclose(np.sort(got, axis=1), np.sort(exp, axis=1),
                               atol=1e-12)


def test_elementwise_product_and_slicer(spark):
    from spark_tpu.ml.feature import ElementwiseProduct, VectorSlicer
    X = np.arange(12, dtype=np.float64).reshape(4, 3)
    df = spark.createDataFrame({"features": X})
    got = np.array([r["e"] for r in ElementwiseProduct(
        inputCol="features", outputCol="e",
        scalingVec=[2.0, 0.0, -1.0]).transform(df).collect()])
    np.testing.assert_allclose(got, X * np.array([2.0, 0.0, -1.0]))
    got2 = np.array([r["s"] for r in VectorSlicer(
        inputCol="features", outputCol="s",
        indices=[2, 0]).transform(df).collect()])
    np.testing.assert_allclose(got2, X[:, [2, 0]])


def test_chisq_selector_keeps_dependent_features(spark):
    from spark_tpu.ml.feature import ChiSqSelector
    rng = np.random.default_rng(11)
    n = 400
    y = rng.integers(0, 2, n).astype(np.float64)
    dep = np.where(rng.uniform(size=n) < 0.2 + 0.6 * y, 1.0, 0.0)
    noise1 = rng.integers(0, 2, n).astype(np.float64)
    noise2 = rng.integers(0, 3, n).astype(np.float64)
    X = np.stack([noise1, dep, noise2], axis=1)
    df = spark.createDataFrame({"features": X, "label": y})
    model = ChiSqSelector(numTopFeatures=1, outputCol="sel").fit(df)
    assert model.getOrDefault("selectedFeatures") == [1]
    got = np.array([r["sel"] for r in model.transform(df).collect()])
    np.testing.assert_allclose(got[:, 0], dep)


def test_rformula_numeric_string_interaction(spark):
    from spark_tpu.ml.feature import RFormula
    df = spark.createDataFrame({
        "y": np.array([1.0, 2.0, 3.0, 4.0]),
        "a": np.array([10.0, 20.0, 30.0, 40.0]),
        "b": np.array([2.0, 3.0, 4.0, 5.0]),
        "g": ["x", "y", "x", "z"],
    })
    model = RFormula(formula="y ~ a + g + a:b").fit(df)
    rows = model.transform(df).collect()
    feats = np.array([r["features"] for r in rows])
    labels = [r["label"] for r in rows]
    assert labels == [1.0, 2.0, 3.0, 4.0]
    # columns: a, g one-hot (k-1 dummy, frequency-then-alpha order), a*b
    np.testing.assert_allclose(feats[:, 0], [10, 20, 30, 40])
    np.testing.assert_allclose(feats[:, -1], [20, 60, 120, 200])
    # g: labels ordered x(2), then y/z(1 each alphabetical) → dummies
    # for (x, y); z encodes as all-zeros
    np.testing.assert_allclose(feats[:, 1], [1, 0, 1, 0])
    np.testing.assert_allclose(feats[:, 2], [0, 1, 0, 0])


def test_rformula_dot_minus_and_string_label(spark):
    from spark_tpu.ml.feature import RFormula
    df = spark.createDataFrame({
        "cls": ["p", "q", "p", "p"],
        "u": np.array([1.0, 2.0, 3.0, 4.0]),
        "v": np.array([5.0, 6.0, 7.0, 8.0]),
        "w": np.array([9.0, 9.0, 9.0, 9.0]),
    })
    model = RFormula(formula="cls ~ . - w").fit(df)
    rows = model.transform(df).collect()
    feats = np.array([r["features"] for r in rows])
    assert feats.shape == (4, 2)            # u, v — w removed
    labels = [r["label"] for r in rows]
    assert labels == [0.0, 1.0, 0.0, 0.0]   # p most frequent → 0


def test_rformula_transform_without_label_and_rejections(spark):
    from spark_tpu.ml.feature import RFormula
    from spark_tpu.expressions import AnalysisException
    train = spark.createDataFrame({
        "y": np.array([1.0, 2.0]), "a": np.array([3.0, 4.0]),
        "g": ["u", "v"]})
    model = RFormula(formula="y ~ a + g").fit(train)
    test = spark.createDataFrame({"a": np.array([5.0]), "g": ["u"]})
    rows = model.transform(test).collect()      # unlabeled scoring works
    assert "label" not in model.transform(test).columns
    assert len(rows[0]["features"]) == 2
    with pytest.raises(AnalysisException, match="interaction"):
        RFormula(formula="y ~ g:a").fit(train)
    # duplicated terms collapse
    m2 = RFormula(formula="y ~ a + a").fit(train)
    assert len(m2.transform(train).collect()[0]["features"]) == 1
