"""Static-analysis subsystem: the plan-invariant verifier (golden broken
plans rejected with structured ``PlanInvariantError``), the crossproc
runtime invariant checks, the hazard linter's rules on synthetic
snippets, the planning-conf coverage rule against the live planner code,
and the repo's own lint-clean status (tier-1 gate for bin/planlint)."""

import os
import textwrap

import numpy as np
import pytest

from spark_tpu import types as T
from spark_tpu.analysis import PlanInvariantError, verify_plan
from spark_tpu.analysis import runtime as az_rt
from spark_tpu.analysis.confcheck import (missing_planning_confs,
                                          planning_conf_reads)
from spark_tpu.analysis.lint import lint_paths, lint_source, main
from spark_tpu.analysis.waivers import is_waived, load_waivers
from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.expressions import Col
from spark_tpu.sql import logical as L

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "spark_tpu")
WAIVERS = os.path.join(REPO, "tools", "lint_waivers.toml")


def _batch(values, name="k", dtype=None, dictionary=None, valid=None):
    arr = np.asarray(values)
    v = ColumnVector(arr, dtype or T.LongType(), valid, dictionary)
    return ColumnBatch([name], [v], np.ones(len(arr), bool), len(arr))


def _rel(values, **kw):
    return L.LocalRelation(_batch(values, **kw))


# ---------------------------------------------------------------------------
# golden broken plans → verify_plan rejects each, naming the property
# ---------------------------------------------------------------------------

def test_broken_plan_leaf_dtype():
    """Wrong dtype propagation: a leaf whose vector no longer matches
    the schema it claims (the classic hand-mutated-plan accident)."""
    rel = _rel([1, 2, 3])
    rel.batch.vectors[0].data = rel.batch.vectors[0].data.astype(np.int32)
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(rel)
    assert e.value.property == "leaf-dtype"
    assert "LocalRelation" in str(e.value)


def test_broken_plan_filter_condition_not_boolean():
    plan = L.Filter(Col("k"), _rel([1, 2, 3]))
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(plan)
    assert e.value.property == "filter-condition-dtype"


def test_broken_plan_project_unresolvable_column():
    plan = L.Project([Col("nope")], _rel([1, 2]))
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(plan)
    assert e.value.property in ("expr-dtype", "schema-propagation")


def test_broken_plan_unknown_join_type():
    j = L.Join(_rel([1]), _rel([1]), "inner",
               on=Col("k") == Col("k"))
    j.how = "sideways"                       # post-construction mutation
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(j)
    assert e.value.property == "join-type"


def test_valid_plans_pass_end_to_end(spark):
    """ZERO false positives on real optimized plans: verify_plan is on
    under pytest (verifyPlans=auto) and these queries must not trip it,
    while the session accounting proves it actually ran."""
    before = dict(getattr(spark, "_analysis_stats", {}))
    df = spark.createDataFrame(
        [(1, "a", 1.5), (2, "b", -0.5), (3, "a", 2.25)], ["k", "w", "x"])
    df.createOrReplaceTempView("az_t")
    spark.sql("SELECT w, count(*) c, sum(x) sx FROM az_t "
              "GROUP BY w ORDER BY w").collect()
    spark.sql("SELECT a.k, b.w FROM az_t a JOIN az_t b ON a.k = b.k "
              "WHERE a.x > 0").collect()
    st = spark._analysis_stats
    assert st["plans_verified"] > before.get("plans_verified", 0)
    assert st["plan_verify_ms"] >= before.get("plan_verify_ms", 0.0)


# ---------------------------------------------------------------------------
# crossproc runtime invariants on synthetic exchange state
# ---------------------------------------------------------------------------

def _join(how="inner"):
    return L.Join(_rel([1]), _rel([1]), how, on=Col("k") == Col("k"))


def test_runtime_hash_copartition_rejects_foreign_rows():
    """Un-co-partitioned hash join: received rows hashing outside this
    process's fine range mean the sides disagreed on the assignment."""
    shard = _batch([1, 2, 3, 4, 5])
    pairs = [(Col("k"), Col("k"))]
    # bounds [0,0,4]: process 0 owns the EMPTY range, so every live row
    # is foreign
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_hash_copartition(_join(), pairs, [0, 0, 4], 4, 0,
                                      shard, shard)
    assert e.value.property == "hash-co-partitioning"
    # the true owner's view of the same shards passes
    az_rt.verify_hash_copartition(_join(), pairs, [0, 0, 4], 4, 1,
                                  shard, shard)


def test_runtime_reducer_bounds_malformed():
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_hash_copartition(_join(), [(Col("k"), Col("k"))],
                                      [0, 3], 4, 0, _batch([1]),
                                      _batch([1]))
    assert e.value.property == "reducer-bounds"


def test_runtime_range_cutpoints_unsorted():
    az_rt.verify_range_cutpoints(_join(), [1, 5, 9], False)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_range_cutpoints(_join(), [1, 5, 5], False)
    assert e.value.property == "range-cutpoints"
    with pytest.raises(PlanInvariantError):
        az_rt.verify_range_cutpoints(_join(), ["b", "a"], True)


def test_runtime_span_owners():
    az_rt.verify_span_owners(_join(), [[0], [1], [0, 1]], 3, 2)
    for bad, prop in (([[0], [1]], "span-ownership"),         # count
                      ([[0], [], [1]], "span-ownership"),     # empty
                      ([[0], [1, 1], [0]], "span-ownership"), # dup
                      ([[0], [5], [1]], "span-ownership")):   # range
        with pytest.raises(PlanInvariantError) as e:
            az_rt.verify_span_owners(_join(), bad, 3, 2)
        assert e.value.property == prop


def test_runtime_skew_split_legality():
    az_rt.verify_skew_split(_join("left"), [[0], [0, 1]])
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_skew_split(_join("full"), [[0], [0, 1]])
    assert e.value.property == "skew-split-legality"


def test_runtime_presorted_build_unsorted_span():
    """The range lane's sorted-run claim: an unsorted build shard would
    make PMergeJoin silently drop matches."""
    az_rt.verify_presorted_build(_join(), _batch([1, 2, 9]),
                                 Col("k"), False)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_presorted_build(_join(), _batch([3, 1, 2]),
                                     Col("k"), False)
    assert e.value.property == "presorted-build"


def test_runtime_dictionary_invariants():
    good = _batch([0, 1, 0], dtype=T.StringType(),
                  dictionary=("apple", "pear"))
    az_rt.verify_unified_dictionaries(_join(), [good])
    unsorted = _batch([0, 1], dtype=T.StringType(),
                      dictionary=("pear", "apple"))
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_unified_dictionaries(_join(), [unsorted])
    assert e.value.property == "dictionary-order"
    oob = _batch([0, 7], dtype=T.StringType(), dictionary=("a", "b"))
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_unified_dictionaries(_join(), [oob])
    assert e.value.property == "dictionary-code-space"


def test_runtime_ledger_scope_pairing():
    from spark_tpu.memory import HostMemoryLedger
    ledger = HostMemoryLedger(budget=1 << 20)
    ledger.reserve("shuffle:xq000001:jL-map", 100)
    az_rt.verify_ledger_scope(ledger, set(), "xq000001")   # scoped: fine
    ledger.reserve("stray-owner", 50)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_ledger_scope(ledger, set(), "xq000001")
    assert e.value.property == "ledger-scope-pairing"
    assert "stray-owner" in str(e.value)
    # pre-existing owners (another query's cache) are not strays
    az_rt.verify_ledger_scope(ledger, {"stray-owner"}, "xq000001")


# ---------------------------------------------------------------------------
# hazard-lint rules on synthetic snippets
# ---------------------------------------------------------------------------

def _lint(src):
    return lint_source(textwrap.dedent(src))


def _rules(src):
    return sorted({f.rule for f in _lint(src)})


def test_lint_jit_host_materialization():
    bad = """
        import numpy as np
        from jax import jit

        @jit
        def f(x):
            return np.asarray(x) + x.item()
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ101"]
    assert len(fs) == 2 and fs[0].symbol == "f"
    ok = """
        import numpy as np

        def g(x):
            return np.asarray(x)
    """
    assert "HZ101" not in _rules(ok)


def test_lint_jit_detects_partial_form():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=0)
        def f(n, x):
            return x.item()
    """
    assert "HZ101" in _rules(src)


def test_lint_reserve_without_release():
    bad = """
        def stage(svc):
            svc.ledger.reserve("owner", 100)
            return 1
    """
    assert "HZ102" in _rules(bad)
    ok = """
        def stage(svc):
            svc.ledger.reserve("owner", 100)
            try:
                return 1
            finally:
                svc.ledger.release("owner")
    """
    assert "HZ102" not in _rules(ok)


def test_lint_unlocked_shared_state():
    bad = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                self.count += 1
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ103"]
    assert len(fs) == 1 and fs[0].symbol == "S.bump"
    ok = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1
    """
    assert "HZ103" not in _rules(ok)


def test_lint_condition_attr_counts_as_lock():
    src = """
        import threading

        class S:
            def __init__(self):
                self._drained = threading.Condition()
                self.pending = 0

            def bump(self):
                with self._drained:
                    self.pending += 1
    """
    assert "HZ103" not in _rules(src)


def test_lint_blocking_io_under_lock():
    bad = """
        import time

        def f(lock):
            with lock:
                time.sleep(1)
    """
    assert "HZ104" in _rules(bad)
    ok = """
        import time

        def f(lock):
            with lock:
                pass
            time.sleep(1)
    """
    assert "HZ104" not in _rules(ok)


def test_lint_unused_import():
    assert "HZ106" in _rules("import os\n\nx = 1\n")
    assert "HZ106" not in _rules("import os\n\nx = os.getpid()\n")
    # __all__ re-exports are used
    assert "HZ106" not in _rules(
        "from collections import OrderedDict\n"
        "__all__ = ['OrderedDict']\n")


def test_lint_shadowed_builtin():
    assert "HZ107" in _rules("def f(id):\n    return id\n")
    assert "HZ107" in _rules("type = 'x'\n")
    assert "HZ107" not in _rules("def f(uid):\n    return uid\n")


def test_lint_jit_outside_stage_cache():
    # a fresh jit object per call inside an execution path: flagged
    bad = """
        import jax

        def run(step, leaves):
            return jax.jit(step)(leaves)
    """
    assert "HZ108" in _rules(bad)
    # the bare `jit(` spelling too
    assert "HZ108" in _rules(
        "from jax import jit\n\ndef run(f, x):\n    return jit(f)(x)\n")
    # module-level jit (built once at import) is fine
    ok_module = """
        import jax

        def _step(x):
            return x + 1

        STEP = jax.jit(_step)
    """
    assert "HZ108" not in _rules(ok_module)
    # the @jit decorator form is a definition, not a per-call build
    ok_decorator = """
        import jax

        @jax.jit
        def step(x):
            return x + 1
    """
    assert "HZ108" not in _rules(ok_decorator)
    # routing through the stage cache carries no bare jit( at the site
    ok_cached = """
        def run(cache, key, make, leaves):
            entry = cache.get_or_build(key, make)
            return cache.dispatch(entry, leaves)
    """
    assert "HZ108" not in _rules(ok_cached)


def test_waiver_file_parses_and_matches():
    waivers = load_waivers(WAIVERS)
    assert waivers and all(w.get("reason") for w in waivers)
    f = lint_source("def f(lock):\n    with lock:\n        open('x')\n")[0]
    assert not is_waived(f, waivers)      # synthetic path never waived


def test_waiver_requires_reason(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text('[[waiver]]\nrule = "HZ104"\n')
    with pytest.raises(ValueError, match="reason"):
        load_waivers(str(p))


def test_waiver_rejects_unsupported_syntax(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text("[[waiver]]\nrule = [1, 2]\n")
    with pytest.raises(ValueError, match="unsupported"):
        load_waivers(str(p))


# ---------------------------------------------------------------------------
# the repo itself: conf coverage + lint-clean (tier-1 gates)
# ---------------------------------------------------------------------------

def test_planning_conf_coverage_complete():
    """Every conf the planning files read is in the plan cache's
    fingerprint — the silently-stale-cache bug class, closed statically
    against the LIVE planner code."""
    reads = planning_conf_reads()
    assert reads, "conf-read scan found nothing: scanner broken?"
    assert missing_planning_confs() == []


def test_repo_is_lint_clean():
    unwaived, waived = lint_paths([PKG], WAIVERS)
    assert unwaived == [], "\n".join(str(f) for f in unwaived)
    # waivers stay justified, not a dumping ground (the 9 HZ108 entries
    # are the catalogued intentional jit sites: the stage cache itself,
    # the per-op bench baseline, one-shot ml fits and probes)
    assert len(waived) <= 24


def test_lint_cli_main_exit_codes(tmp_path, capsys):
    assert main([PKG, "--waivers", WAIVERS]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n\nx = 1\n")
    assert main([str(bad), "--no-waivers"]) == 1
    out = capsys.readouterr().out
    assert "HZ106" in out


# ---------------------------------------------------------------------------
# satellite: SET of a newly-covered planning conf invalidates the cache
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("key,val", [
    ("spark.tpu.shuffle.finePartitionsPerProc", "9"),
    ("spark.tpu.crossproc.dedupReplicated", "false"),
])
def test_set_planning_conf_invalidates_plan_cache(spark, key, val):
    from spark_tpu.serving.plancache import PlanCache
    s = spark.newSession()
    cache = PlanCache(s.conf_obj)
    s._plan_cache = cache
    q = ("SELECT id % 7 AS g, count(*) AS c FROM range(64) "
         "GROUP BY id % 7 ORDER BY g")
    r1 = [tuple(r) for r in s.sql(q).collect()]
    assert cache.stats()["entries"] >= 1
    before = cache.stats()["invalidations"]
    s.sql(f"SET {key}={val}")
    assert cache.stats()["invalidations"] > before, \
        f"SET {key} must evict entries built under the old value"
    assert [tuple(r) for r in s.sql(q).collect()] == r1
