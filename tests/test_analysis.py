"""Static-analysis subsystem: the plan-invariant verifier (golden broken
plans rejected with structured ``PlanInvariantError``), the crossproc
runtime invariant checks, the hazard linter's rules on synthetic
snippets, the planning-conf coverage rule against the live planner code,
and the repo's own lint-clean status (tier-1 gate for bin/planlint)."""

import os
import textwrap

import numpy as np
import pytest

from spark_tpu import types as T
from spark_tpu.analysis import PlanInvariantError, verify_plan
from spark_tpu.analysis import runtime as az_rt
from spark_tpu.analysis.confcheck import (missing_planning_confs,
                                          planning_conf_reads)
from spark_tpu.analysis.lint import lint_paths, lint_source, main
from spark_tpu.analysis.protocol import lint_protocol_sources
from spark_tpu.analysis.waivers import (dead_waivers, is_waived,
                                        load_waivers)
from spark_tpu.columnar import ColumnBatch, ColumnVector
from spark_tpu.expressions import Col
from spark_tpu.sql import logical as L

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "spark_tpu")
WAIVERS = os.path.join(REPO, "tools", "lint_waivers.toml")


def _batch(values, name="k", dtype=None, dictionary=None, valid=None):
    arr = np.asarray(values)
    v = ColumnVector(arr, dtype or T.LongType(), valid, dictionary)
    return ColumnBatch([name], [v], np.ones(len(arr), bool), len(arr))


def _rel(values, **kw):
    return L.LocalRelation(_batch(values, **kw))


# ---------------------------------------------------------------------------
# golden broken plans → verify_plan rejects each, naming the property
# ---------------------------------------------------------------------------

def test_broken_plan_leaf_dtype():
    """Wrong dtype propagation: a leaf whose vector no longer matches
    the schema it claims (the classic hand-mutated-plan accident)."""
    rel = _rel([1, 2, 3])
    rel.batch.vectors[0].data = rel.batch.vectors[0].data.astype(np.int32)
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(rel)
    assert e.value.property == "leaf-dtype"
    assert "LocalRelation" in str(e.value)


def test_broken_plan_filter_condition_not_boolean():
    plan = L.Filter(Col("k"), _rel([1, 2, 3]))
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(plan)
    assert e.value.property == "filter-condition-dtype"


def test_broken_plan_project_unresolvable_column():
    plan = L.Project([Col("nope")], _rel([1, 2]))
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(plan)
    assert e.value.property in ("expr-dtype", "schema-propagation")


def test_broken_plan_unknown_join_type():
    j = L.Join(_rel([1]), _rel([1]), "inner",
               on=Col("k") == Col("k"))
    j.how = "sideways"                       # post-construction mutation
    with pytest.raises(PlanInvariantError) as e:
        verify_plan(j)
    assert e.value.property == "join-type"


def test_valid_plans_pass_end_to_end(spark):
    """ZERO false positives on real optimized plans: verify_plan is on
    under pytest (verifyPlans=auto) and these queries must not trip it,
    while the session accounting proves it actually ran."""
    before = dict(getattr(spark, "_analysis_stats", {}))
    df = spark.createDataFrame(
        [(1, "a", 1.5), (2, "b", -0.5), (3, "a", 2.25)], ["k", "w", "x"])
    df.createOrReplaceTempView("az_t")
    spark.sql("SELECT w, count(*) c, sum(x) sx FROM az_t "
              "GROUP BY w ORDER BY w").collect()
    spark.sql("SELECT a.k, b.w FROM az_t a JOIN az_t b ON a.k = b.k "
              "WHERE a.x > 0").collect()
    st = spark._analysis_stats
    assert st["plans_verified"] > before.get("plans_verified", 0)
    assert st["plan_verify_ms"] >= before.get("plan_verify_ms", 0.0)


# ---------------------------------------------------------------------------
# crossproc runtime invariants on synthetic exchange state
# ---------------------------------------------------------------------------

def _join(how="inner"):
    return L.Join(_rel([1]), _rel([1]), how, on=Col("k") == Col("k"))


def test_runtime_hash_copartition_rejects_foreign_rows():
    """Un-co-partitioned hash join: received rows hashing outside this
    process's fine range mean the sides disagreed on the assignment."""
    shard = _batch([1, 2, 3, 4, 5])
    pairs = [(Col("k"), Col("k"))]
    # bounds [0,0,4]: process 0 owns the EMPTY range, so every live row
    # is foreign
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_hash_copartition(_join(), pairs, [0, 0, 4], 4, 0,
                                      shard, shard)
    assert e.value.property == "hash-co-partitioning"
    # the true owner's view of the same shards passes
    az_rt.verify_hash_copartition(_join(), pairs, [0, 0, 4], 4, 1,
                                  shard, shard)


def test_runtime_reducer_bounds_malformed():
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_hash_copartition(_join(), [(Col("k"), Col("k"))],
                                      [0, 3], 4, 0, _batch([1]),
                                      _batch([1]))
    assert e.value.property == "reducer-bounds"


def test_runtime_range_cutpoints_unsorted():
    az_rt.verify_range_cutpoints(_join(), [1, 5, 9], False)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_range_cutpoints(_join(), [1, 5, 5], False)
    assert e.value.property == "range-cutpoints"
    with pytest.raises(PlanInvariantError):
        az_rt.verify_range_cutpoints(_join(), ["b", "a"], True)


def test_runtime_span_owners():
    az_rt.verify_span_owners(_join(), [[0], [1], [0, 1]], 3, 2)
    for bad, prop in (([[0], [1]], "span-ownership"),         # count
                      ([[0], [], [1]], "span-ownership"),     # empty
                      ([[0], [1, 1], [0]], "span-ownership"), # dup
                      ([[0], [5], [1]], "span-ownership")):   # range
        with pytest.raises(PlanInvariantError) as e:
            az_rt.verify_span_owners(_join(), bad, 3, 2)
        assert e.value.property == prop


def test_runtime_skew_split_legality():
    az_rt.verify_skew_split(_join("left"), [[0], [0, 1]])
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_skew_split(_join("full"), [[0], [0, 1]])
    assert e.value.property == "skew-split-legality"


def test_runtime_presorted_build_unsorted_span():
    """The range lane's sorted-run claim: an unsorted build shard would
    make PMergeJoin silently drop matches."""
    az_rt.verify_presorted_build(_join(), _batch([1, 2, 9]),
                                 Col("k"), False)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_presorted_build(_join(), _batch([3, 1, 2]),
                                     Col("k"), False)
    assert e.value.property == "presorted-build"


def test_runtime_dictionary_invariants():
    good = _batch([0, 1, 0], dtype=T.StringType(),
                  dictionary=("apple", "pear"))
    az_rt.verify_unified_dictionaries(_join(), [good])
    unsorted = _batch([0, 1], dtype=T.StringType(),
                      dictionary=("pear", "apple"))
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_unified_dictionaries(_join(), [unsorted])
    assert e.value.property == "dictionary-order"
    oob = _batch([0, 7], dtype=T.StringType(), dictionary=("a", "b"))
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_unified_dictionaries(_join(), [oob])
    assert e.value.property == "dictionary-code-space"


def test_runtime_ledger_scope_pairing():
    from spark_tpu.memory import HostMemoryLedger
    ledger = HostMemoryLedger(budget=1 << 20)
    ledger.reserve("shuffle:xq000001:jL-map", 100)
    az_rt.verify_ledger_scope(ledger, set(), "xq000001")   # scoped: fine
    ledger.reserve("stray-owner", 50)
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_ledger_scope(ledger, set(), "xq000001")
    assert e.value.property == "ledger-scope-pairing"
    assert "stray-owner" in str(e.value)
    # pre-existing owners (another query's cache) are not strays
    az_rt.verify_ledger_scope(ledger, {"stray-owner"}, "xq000001")


# ---------------------------------------------------------------------------
# hazard-lint rules on synthetic snippets
# ---------------------------------------------------------------------------

def _lint(src):
    return lint_source(textwrap.dedent(src))


def _rules(src):
    return sorted({f.rule for f in _lint(src)})


def test_lint_jit_host_materialization():
    bad = """
        import numpy as np
        from jax import jit

        @jit
        def f(x):
            return np.asarray(x) + x.item()
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ101"]
    assert len(fs) == 2 and fs[0].symbol == "f"
    ok = """
        import numpy as np

        def g(x):
            return np.asarray(x)
    """
    assert "HZ101" not in _rules(ok)


def test_lint_jit_detects_partial_form():
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=0)
        def f(n, x):
            return x.item()
    """
    assert "HZ101" in _rules(src)


def test_lint_reserve_without_release():
    bad = """
        def stage(svc):
            svc.ledger.reserve("owner", 100)
            return 1
    """
    assert "HZ102" in _rules(bad)
    ok = """
        def stage(svc):
            svc.ledger.reserve("owner", 100)
            try:
                return 1
            finally:
                svc.ledger.release("owner")
    """
    assert "HZ102" not in _rules(ok)


def test_lint_unlocked_shared_state():
    bad = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                self.count += 1
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ103"]
    assert len(fs) == 1 and fs[0].symbol == "S.bump"
    ok = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1
    """
    assert "HZ103" not in _rules(ok)


def test_lint_condition_attr_counts_as_lock():
    src = """
        import threading

        class S:
            def __init__(self):
                self._drained = threading.Condition()
                self.pending = 0

            def bump(self):
                with self._drained:
                    self.pending += 1
    """
    assert "HZ103" not in _rules(src)


def test_lint_blocking_io_under_lock():
    bad = """
        import time

        def f(lock):
            with lock:
                time.sleep(1)
    """
    assert "HZ104" in _rules(bad)
    ok = """
        import time

        def f(lock):
            with lock:
                pass
            time.sleep(1)
    """
    assert "HZ104" not in _rules(ok)


def test_lint_unused_import():
    assert "HZ106" in _rules("import os\n\nx = 1\n")
    assert "HZ106" not in _rules("import os\n\nx = os.getpid()\n")
    # __all__ re-exports are used
    assert "HZ106" not in _rules(
        "from collections import OrderedDict\n"
        "__all__ = ['OrderedDict']\n")


def test_lint_shadowed_builtin():
    assert "HZ107" in _rules("def f(id):\n    return id\n")
    assert "HZ107" in _rules("type = 'x'\n")
    assert "HZ107" not in _rules("def f(uid):\n    return uid\n")


def test_lint_jit_outside_stage_cache():
    # a fresh jit object per call inside an execution path: flagged
    bad = """
        import jax

        def run(step, leaves):
            return jax.jit(step)(leaves)
    """
    assert "HZ108" in _rules(bad)
    # the bare `jit(` spelling too
    assert "HZ108" in _rules(
        "from jax import jit\n\ndef run(f, x):\n    return jit(f)(x)\n")
    # module-level jit (built once at import) is fine
    ok_module = """
        import jax

        def _step(x):
            return x + 1

        STEP = jax.jit(_step)
    """
    assert "HZ108" not in _rules(ok_module)
    # the @jit decorator form is a definition, not a per-call build
    ok_decorator = """
        import jax

        @jax.jit
        def step(x):
            return x + 1
    """
    assert "HZ108" not in _rules(ok_decorator)
    # routing through the stage cache carries no bare jit( at the site
    ok_cached = """
        def run(cache, key, make, leaves):
            entry = cache.get_or_build(key, make)
            return cache.dispatch(entry, leaves)
    """
    assert "HZ108" not in _rules(ok_cached)


def test_lint_nonatomic_durable_write():
    # a commit method of a log class writing the final file in place:
    # a crash mid-write leaves a torn entry recovery will read
    bad = """
        class MetadataLog:
            def add(self, batch_id, payload):
                with open(self.path(batch_id), "w") as f:
                    f.write(payload)
    """
    assert "HZ112" in _rules(bad)
    # the tmp + os.replace discipline in the same method is clean
    ok_atomic = """
        import os

        class MetadataLog:
            def add(self, batch_id, payload):
                tmp = self.path(batch_id) + ".tmp"
                with open(tmp, "w") as f:
                    f.write(payload)
                    os.fsync(f.fileno())
                os.replace(tmp, self.path(batch_id))
    """
    assert "HZ112" not in _rules(ok_atomic)
    # write-mode opens outside durable classes / commit methods: not ours
    assert "HZ112" not in _rules(
        "class Report:\n"
        "    def render(self):\n"
        "        with open('r.html', 'w') as f:\n"
        "            f.write('x')\n")
    assert "HZ112" not in _rules(
        "class FileSink:\n"
        "    def describe(self):\n"
        "        with open('d.txt', 'w') as f:\n"
        "            f.write('x')\n")
    # read-mode opens in commit methods are fine
    assert "HZ112" not in _rules(
        "class FileSink:\n"
        "    def add_batch(self, b):\n"
        "        with open('d.txt') as f:\n"
        "            return f.read()\n")


def test_lint_block_path_outside_resolver():
    # spelling a block wire-format name outside the resolver seam: the
    # block service can neither register nor reap a path it never sees
    bad = """
        import os

        def peek(root, pid):
            return os.path.join(root, f"s{pid:04d}.done")
    """
    assert "HZ113" in _rules(bad)
    found = [f for f in _lint(bad) if f.rule == "HZ113"]
    assert found[0].symbol == "peek"
    assert "`.done`" in found[0].message
    # the f-string TAIL decides: a suffix mid-string is prose, not a path
    assert "HZ113" in _rules("def f(b):\n    return f'{b}.snapshot'\n")
    assert "HZ113" not in _rules(
        "def f(x):\n    return f'.part of {x}'\n")
    # docstrings and bare-expression strings are prose
    assert "HZ113" not in _rules(
        'def f():\n    "reads the s0000.part"\n    return 1\n')
    # the resolver modules themselves are the seam — exempt by path
    from spark_tpu.analysis.lint import lint_source as _ls
    owner = _ls(textwrap.dedent(bad),
                path="spark_tpu/parallel/hostshuffle.py")
    assert not [f for f in owner if f.rule == "HZ113"]


# ---------------------------------------------------------------------------
# HZ109/HZ110: replica-determinism rules on synthetic snippets
# ---------------------------------------------------------------------------

def test_lint_nondet_source_in_decision_root():
    bad = """
        import os

        def plan_reducers(sizes, n):
            seed = os.getpid()
            return [seed % n]
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ109"]
    assert len(fs) == 1 and fs[0].symbol == "plan_reducers"
    assert "os.getpid" in fs[0].message
    # the same source OUTSIDE the decision registry is not our business
    ok = """
        import os

        def temp_file_name(n):
            return f"part-{os.getpid()}-{n}"
    """
    assert "HZ109" not in _rules(ok)


def test_lint_nondet_source_through_call_closure():
    """The registry closes over same-module calls: a helper a decision
    root delegates to is held to the same standard."""
    bad = """
        import random

        def _pick(xs):
            return xs[random.randrange(len(xs))]

        def adaptive_join_decision(frozen, options):
            return _pick(options)
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ109"]
    assert len(fs) == 1 and fs[0].symbol == "_pick"
    assert "adaptive_join_decision" in fs[0].message
    # the identical helper with no decision root calling it: clean
    ok = """
        import random

        def _pick(xs):
            return xs[random.randrange(len(xs))]
    """
    assert "HZ109" not in _rules(ok)


def test_lint_clock_flags_decision_values_not_deadlines():
    bad = """
        import time

        def elastic_reducer_width(total, target, n):
            w = time.time()
            return int(w) % n
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ109"]
    assert len(fs) == 1 and "wall-clock" in fs[0].message
    # deadline/timer use of the clock inside a decision root is the
    # protocol's business — only values REACHING the return are hazards
    ok = """
        import time

        def recover_round(svc, timeout):
            deadline = time.time() + timeout
            while time.time() < deadline:
                svc.poll()
            return []
    """
    assert "HZ109" not in _rules(ok)


def test_lint_unordered_iteration_in_decision():
    bad = """
        def group_owner(pids):
            owners = set(pids)
            out = []
            for p in owners:
                out.append(p)
            return out
    """
    fs = [f for f in _lint(bad) if f.rule == "HZ110"]
    assert len(fs) == 1 and fs[0].symbol == "group_owner"
    assert "sorted" in fs[0].message
    # iterating sorted(...) is the prescribed fix
    ok = """
        def group_owner(pids):
            owners = set(pids)
            out = []
            for p in sorted(owners):
                out.append(p)
            return out
    """
    assert "HZ110" not in _rules(ok)


def test_lint_unordered_consumers_and_order_free_folds():
    # list() over a set exposes its order...
    assert "HZ110" in _rules("""
        def live_pids(procs):
            alive = {p for p in procs}
            return list(alive)
    """)
    # ...while order-insensitive folds never do
    assert "HZ110" not in _rules("""
        def live_pids(procs):
            alive = {p for p in procs}
            return max(alive) if alive else 0
    """)


def test_lint_set_returning_helper_propagates():
    """A module helper that syntactically returns a set taints its call
    sites inside the decision closure (the ``skew_spans`` shape)."""
    assert "HZ110" in _rules("""
        def _candidates(xs):
            return {x for x in xs}

        def plan_range_reducers(xs):
            out = []
            for c in _candidates(xs):
                out.append(c)
            return out
    """)


# ---------------------------------------------------------------------------
# HZ111: exchange-protocol conformance on synthetic protocol sources
# ---------------------------------------------------------------------------

def _protocol(sources):
    return lint_protocol_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()})


def test_protocol_one_sided_round_flagged():
    pub = """
        def stage(svc, xid):
            svc.publish_manifest(f"{xid}-plan", {})
    """
    fs = _protocol({"a.py": pub})
    assert len(fs) == 1 and fs[0].rule == "HZ111"
    assert "published but never gathered" in fs[0].message
    # pairing is cross-file: the gather may live in the other protocol
    # file
    gath = """
        def read(svc, xid, n):
            return svc.gather_manifests(f"{xid}-plan", n)
    """
    assert _protocol({"a.py": pub, "b.py": gath}) == []
    assert "gathered but never published" in \
        _protocol({"b.py": gath})[0].message


def test_protocol_single_use_discipline():
    fs = _protocol({"a.py": """
        def stage(svc, xid, n):
            svc.publish_manifest(f"{xid}-plan", {})
            svc.publish_manifest(f"{xid}-plan", {})
            svc.gather_manifests(f"{xid}-plan", n)
    """})
    assert len(fs) == 1 and fs[0].rule == "HZ111"
    assert "published more than once" in fs[0].message


def test_protocol_epoch_fencing():
    unfenced = """
        def run(svc, xid, n):
            epoch = 0
            while True:
                run_id = f"{xid}e{epoch}"
                svc.publish_manifest(f"{xid}-fin", {})
                svc.gather_manifests(f"{xid}-fin", n)
                epoch += 1
    """
    fs = _protocol({"a.py": unfenced})
    assert fs and all("un-fenced" in f.message for f in fs)
    fenced = """
        def run(svc, xid, n):
            epoch = 0
            while True:
                run_id = f"{xid}e{epoch}"
                svc.publish_manifest(f"{run_id}-fin", {})
                svc.gather_manifests(f"{run_id}-fin", n)
                epoch += 1
    """
    assert _protocol({"a.py": fenced}) == []


def test_waiver_file_parses_and_matches():
    waivers = load_waivers(WAIVERS)
    assert waivers and all(w.get("reason") for w in waivers)
    f = lint_source("def f(lock):\n    with lock:\n        open('x')\n")[0]
    assert not is_waived(f, waivers)      # synthetic path never waived


def test_waiver_requires_reason(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text('[[waiver]]\nrule = "HZ104"\n')
    with pytest.raises(ValueError, match="reason"):
        load_waivers(str(p))


def test_waiver_rejects_unsupported_syntax(tmp_path):
    p = tmp_path / "w.toml"
    p.write_text("[[waiver]]\nrule = [1, 2]\n")
    with pytest.raises(ValueError, match="unsupported"):
        load_waivers(str(p))


def test_dead_waiver_detection():
    findings = lint_source("import os\n\nx = 1\n")
    live = {"rule": "HZ106", "reason": "kept"}
    dead = {"rule": "HZ104", "path": "never/matches.py",
            "reason": "the code this excused is long gone"}
    assert dead_waivers(findings, [live, dead]) == [dead]


def test_stale_waiver_fails_default_lint(tmp_path, capsys):
    """A waiver matching no finding fails the default full-repo lint
    (a stale waiver would silently swallow the next REAL finding that
    happens to match it) — and the checked-in file carries none."""
    with open(WAIVERS, encoding="utf-8") as f:
        body = f.read()
    stale = tmp_path / "w.toml"
    stale.write_text(body + '\n[[waiver]]\nrule = "HZ104"\n'
                     'path = "parallel/never_written.py"\n'
                     'reason = "left behind after a refactor"\n')
    assert main(["--waivers", str(stale)]) == 1
    out = capsys.readouterr().out
    assert "remove dead waiver" in out and "never_written.py" in out
    # the repo's own waiver file is dead-weight-free
    assert main([]) == 0


# ---------------------------------------------------------------------------
# the repo itself: conf coverage + lint-clean (tier-1 gates)
# ---------------------------------------------------------------------------

def test_planning_conf_coverage_complete():
    """Every conf the planning files read is in the plan cache's
    fingerprint — the silently-stale-cache bug class, closed statically
    against the LIVE planner code."""
    reads = planning_conf_reads()
    assert reads, "conf-read scan found nothing: scanner broken?"
    assert missing_planning_confs() == []


def test_repo_is_lint_clean():
    unwaived, waived = lint_paths([PKG], WAIVERS)
    assert unwaived == [], "\n".join(str(f) for f in unwaived)
    # waivers stay justified, not a dumping ground (the 9 HZ108 entries
    # are the catalogued intentional jit sites: the stage cache itself,
    # the per-op bench baseline, one-shot ml fits and probes; the 3
    # streaming entries cover lock-serialized metrics writes and the
    # state-store accounting's deliberate release/re-reserve cycle; the
    # 3 HZ113 entries are the injector's deliberate manifest tampering
    # and the pre-seam AggregationState snapshot naming)
    assert len(waived) <= 31


def test_lint_cli_main_exit_codes(tmp_path, capsys):
    assert main([PKG, "--waivers", WAIVERS]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("import os\n\nx = 1\n")
    assert main([str(bad), "--no-waivers"]) == 1
    out = capsys.readouterr().out
    assert "HZ106" in out


# ---------------------------------------------------------------------------
# satellite: SET of a newly-covered planning conf invalidates the cache
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("key,val", [
    ("spark.tpu.shuffle.finePartitionsPerProc", "9"),
    ("spark.tpu.crossproc.dedupReplicated", "false"),
])
def test_set_planning_conf_invalidates_plan_cache(spark, key, val):
    from spark_tpu.serving.plancache import PlanCache
    s = spark.newSession()
    cache = PlanCache(s.conf_obj)
    s._plan_cache = cache
    q = ("SELECT id % 7 AS g, count(*) AS c FROM range(64) "
         "GROUP BY id % 7 ORDER BY g")
    r1 = [tuple(r) for r in s.sql(q).collect()]
    assert cache.stats()["entries"] >= 1
    before = cache.stats()["invalidations"]
    s.sql(f"SET {key}={val}")
    assert cache.stats()["invalidations"] > before, \
        f"SET {key} must evict entries built under the old value"
    assert [tuple(r) for r in s.sql(q).collect()] == r1


# ---------------------------------------------------------------------------
# the decision-trace runtime backstop (analysis.runtime.
# verify_decision_trace) on synthetic exchange state
# ---------------------------------------------------------------------------

class _Sess:
    pass


def _trace_inputs(**over):
    d = {"frozen": "hash", "epoch": 0, "live": [0, 1], "adopt": []}
    d.update(over)
    return d


def test_decision_trace_hash_is_canonical():
    a = az_rt.decision_trace({"frozen": "hash", "epoch": 0})
    b = az_rt.decision_trace({"epoch": 0, "frozen": "hash"})
    assert a == b                         # key order never matters
    assert a != az_rt.decision_trace({"frozen": "hash", "epoch": 1})


def test_decision_trace_peer_divergence_names_component():
    inputs = _trace_inputs()
    theirs = _trace_inputs(epoch=1)
    mans = {0: {"dtrace": {"h": az_rt.decision_trace(inputs),
                           "c": inputs}},
            1: {"dtrace": {"h": az_rt.decision_trace(theirs),
                           "c": theirs}}}
    sess = _Sess()
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_decision_trace(sess, _join(), None, "xq000001-plan",
                                    mans, inputs)
    assert e.value.property == "decision-trace-agreement"
    assert "xq000001-plan" in str(e.value) and "epoch" in str(e.value)
    st = sess._analysis_stats
    assert st["decision_trace_checks"] == 1
    assert st["decision_trace_divergence"] == 1
    # agreeing peers pass; a sender without a dtrace payload degrades
    # lenient, same as observed_side_stats
    ok = {0: mans[0], 1: {"partitions": {}}}
    az_rt.verify_decision_trace(sess, _join(), None, "xq000001-plan",
                                ok, inputs)
    assert st["decision_trace_checks"] == 2
    assert st["decision_trace_divergence"] == 1


class _DiskSvc:
    """A service whose on-disk manifests are fixed — the shared bytes
    every peer read."""

    def __init__(self, mans):
        self._m = mans

    def _read_manifest(self, exchange, sender):
        return self._m.get(sender)


def test_decision_trace_local_recompute_catches_split_view():
    """This process 'decided' a demotion its peers' shared bytes do not
    imply — the asymmetric in-memory perturbation a symmetric file
    check can never see."""
    disk = {0: {"sides": {"l": [9000, 90], "r": [9000, 90]}},
            1: {"sides": {"l": [9000, 90], "r": [9000, 90]}}}
    inputs = _trace_inputs()
    base = {"frozen": "hash", "how": "inner", "adaptive": True,
            "broadcast_threshold": 2048, "n_live": 2}
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_decision_trace(
            None, _join(), _DiskSvc(disk), "xq000001-plan", disk, inputs,
            local=dict(base, decision="broadcast_right"))
    assert e.value.property == "decision-trace-agreement"
    assert "broadcast_right" in str(e.value)
    # the decision the disk bytes imply passes
    az_rt.verify_decision_trace(
        None, _join(), _DiskSvc(disk), "xq000001-plan", disk, inputs,
        local=dict(base, decision="hash"))


def test_decision_trace_local_recompute_checks_width():
    disk = {0: {"sides": {"l": [9000, 90], "r": [9000, 90]}},
            1: {"sides": {"l": [9000, 90], "r": [9000, 90]}}}
    inputs = _trace_inputs()
    az_rt.verify_decision_trace(
        None, _join(), _DiskSvc(disk), "xq000001-plan", disk, inputs,
        local={"frozen": "hash", "n_live": 2, "width": 2, "target": 0})
    with pytest.raises(PlanInvariantError) as e:
        az_rt.verify_decision_trace(
            None, _join(), _DiskSvc(disk), "xq000001-plan", disk, inputs,
            local={"frozen": "hash", "n_live": 2, "width": 1,
                   "target": 0})
    assert e.value.property == "decision-trace-agreement"


# ---------------------------------------------------------------------------
# satellite: the lenient-gather fallback still asserts frozen-strategy
# legality (the adaptive-agreement check used to skip this path whole)
# ---------------------------------------------------------------------------

def test_adaptive_redecide_checks_frozen_on_lost_stats_round():
    from spark_tpu.parallel.crossproc import (_adaptive_redecide,
                                              _AdaptiveCtx)

    class _Svc:
        def live_pids(self):
            return [0, 1]

    ctx = _AdaptiveCtx(1024, None, None, None,
                       [(Col("k"), Col("k"))], True)
    # manifests without a 'sides' payload: observed stats incomplete,
    # so the frozen strategy stands — but its legality is still checked
    mans = {0: {"partitions": {}}, 1: {"partitions": {}}}
    assert _adaptive_redecide(_join(), _Svc(), "xq000001", ctx,
                              "hash", mans) == "hash"
    with pytest.raises(PlanInvariantError) as e:
        _adaptive_redecide(_join(), _Svc(), "xq000001", ctx,
                           "sideways", mans)
    assert e.value.property == "join-strategy"
