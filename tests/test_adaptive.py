"""Adaptive query execution across REAL processes.

Spawns ``adaptive_worker.py`` under 2 (tier-1) and 3 (slow) processes.
The worker batters the adaptive re-planning layer against a full-data
oracle: hash→broadcast demotion at the stats barrier, the
stats-feedback plan-time shortcut on a repeated query, range→broadcast
demotion, a frozen-plan control session, the post-sample skew
re-split, and partial-aggregate pushdown — every scenario must return
oracle-identical rows AND take the path the observed statistics
dictate (asserted inside the worker via path counters; this spawner
checks the per-scenario OK markers and exit codes).

Fault-injection coverage for the stats round itself lives in
test_faults.py.
"""

import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "adaptive_worker.py")

MARKERS = ("DEMOTE-OK", "FEEDBACK-OK", "RANGE-DEMOTE-OK", "FROZEN-OK",
           "SKEW-OK", "AGGPUSH-OK", "ADAPT-OK")


def _run_adaptive(tmp_path, n, timeout_s=90.0):
    root = str(tmp_path / "shuf")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SPARK_TPU_FAULT_PLAN", None)
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(n), root, "adaptive",
         str(timeout_s)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n)]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid}:\n{out}"
        for m in MARKERS:
            assert f"[p{pid}] {m}" in out, (m, out)
        # one demotion per lane (hash + range), the repeat answered
        # from feedback, and the skew span re-split from observed bytes
        assert "demotions=2" in out, out
        assert "fbhits=" in out and "fbhits=0" not in out, out
        assert "postskew=" in out and "postskew=0" not in out, out
    return outs


def test_adaptive_parity_two_processes(tmp_path):
    _run_adaptive(tmp_path, 2)


@pytest.mark.slow
def test_adaptive_parity_three_processes(tmp_path):
    _run_adaptive(tmp_path, 3)
