"""JDBC-analog datasource: DB-API reads/writes through the columnar scan.

Reference parity targets: `sql/core/.../datasources/jdbc/JDBCRDD.scala`
(scanTable: pruned SELECT, pushed WHERE, per-partition predicates),
`JDBCRelation.scala` (columnPartition stride clauses), `JdbcUtils.scala`
(createTable/saveTable).  The driver here is stdlib sqlite3 — the DB-API
2.0 stand-in for the JVM driver manager (docstring in spark_tpu/jdbc.py).
"""

import sqlite3

import numpy as np
import pandas as pd
import pytest

import spark_tpu.config as C
from spark_tpu.sql import functions as F


@pytest.fixture(scope="module")
def db(tmp_path_factory):
    path = tmp_path_factory.mktemp("jdbc") / "store.db"
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE emp (id INTEGER, dept TEXT, salary REAL, "
                 "age INTEGER)")
    rng = np.random.default_rng(7)
    rows = []
    for i in range(500):
        dept = ["eng", "sales", "hr"][i % 3]
        rows.append((i if i % 11 else None,          # NULL ids
                     dept if i % 7 else None,         # NULL depts
                     float(rng.normal(50.0, 12.0)),
                     int(rng.integers(21, 65))))
    conn.executemany("INSERT INTO emp VALUES (?,?,?,?)", rows)
    conn.commit()
    conn.close()
    pdf = pd.DataFrame(rows, columns=["id", "dept", "salary", "age"])
    return f"jdbc:sqlite:{path}", pdf


def test_read_whole_table(spark, db):
    url, pdf = db
    df = spark.read.jdbc(url, "emp")
    assert set(df.columns) == {"id", "dept", "salary", "age"}
    got = df.collect()
    assert len(got) == len(pdf)
    assert sorted(r["age"] for r in got) == sorted(pdf.age.tolist())
    # NULLs survive the trip
    assert sum(r["id"] is None for r in got) == int(pdf.id.isna().sum())


def test_partitioned_read_matches_unpartitioned(spark, db):
    """Stride partitions must cover every row exactly once — including
    NULL partition-column rows (they ride the first clause) and rows
    outside [lowerBound, upperBound) (open-ended first/last clauses)."""
    url, pdf = db
    df = spark.read.jdbc(url, "emp", column="id", lowerBound=100,
                         upperBound=400, numPartitions=4)
    got = sorted((r["id"] is None, r["id"], r["age"]) for r in df.collect())
    exp = sorted((pd.isna(i), None if pd.isna(i) else int(i), int(a))
                 for i, a in zip(pdf.id, pdf.age))
    assert got == exp


def test_explicit_predicates(spark, db):
    url, pdf = db
    df = spark.read.jdbc(url, "emp", predicates=[
        "age < 40", "age >= 40"])
    assert len(df.collect()) == len(pdf)


def test_pruning_and_pushdown(spark, db):
    """A filtered, projected query over jdbc plans with pushed_filters on
    the relation (JDBCRDD.compileFilter role) and still matches the
    pandas oracle exactly — the in-plan Filter stays authoritative."""
    from spark_tpu.sql.planner import QueryExecution
    from spark_tpu.sql.logical import FileRelation
    url, pdf = db
    df = (spark.read.jdbc(url, "emp")
          .filter((F.col("age") >= 30) & (F.col("dept") == "eng"))
          .groupBy("dept").agg(F.sum("age").alias("s")))
    qe = QueryExecution(spark, df._plan)

    def rels(n, out):
        if isinstance(n, FileRelation):
            out.append(n)
        for c in n.children:
            rels(c, out)
        return out
    rel = rels(qe.optimized, [])[0]
    assert rel.pushed_filters, "expected WHERE pushdown into the jdbc scan"
    assert ("age", ">=", 30) in rel.pushed_filters
    assert ("dept", "==", "eng") in rel.pushed_filters
    got = df.collect()
    exp = pdf[(pdf.age >= 30) & (pdf.dept == "eng")]
    assert got[0]["s"] == int(exp.age.sum())


def test_query_option(spark, db):
    url, pdf = db
    df = (spark.read.format("jdbc").option("url", url)
          .option("query", "SELECT dept, COUNT(*) AS n FROM emp "
                           "WHERE dept IS NOT NULL GROUP BY dept")
          .load(url).orderBy("dept"))
    got = [(r["dept"], r["n"]) for r in df.collect()]
    exp = (pdf[pdf.dept.notna()].groupby("dept").size()
           .sort_index())
    assert got == list(zip(exp.index, exp))


def test_jdbc_joins_with_files(spark, db, tmp_path):
    """A jdbc relation is an ordinary relation: joinable against parquet."""
    url, pdf = db
    bonus = pd.DataFrame({"dept": ["eng", "sales", "hr"],
                          "bonus": [3, 2, 1]})
    p = tmp_path / "bonus.parquet"
    p.mkdir()
    bonus.to_parquet(p / "part-0.parquet", index=False)
    df = (spark.read.jdbc(url, "emp").join(
        spark.read.parquet(str(p)), on="dept")
        .groupBy("dept").agg(F.count("*").alias("n"),
                             F.max("bonus").alias("b"))
        .orderBy("dept"))
    got = [(r["dept"], r["n"], r["b"]) for r in df.collect()]
    exp = (pdf.merge(bonus, on="dept").groupby("dept")
           .agg(n=("age", "size"), b=("bonus", "max")).sort_index())
    assert got == list(zip(exp.index, exp.n, exp.b))


def test_write_modes_roundtrip(spark, db, tmp_path):
    url, pdf = db
    out_db = tmp_path / "out.db"
    sqlite3.connect(out_db).close()          # empty db file must exist
    out_url = f"jdbc:sqlite:{out_db}"
    src = spark.read.jdbc(url, "emp").filter(F.col("age") < 30)
    src.write.jdbc(out_url, "young", mode="overwrite")
    back = spark.read.jdbc(out_url, "young")
    exp = pdf[pdf.age < 30]
    assert len(back.collect()) == len(exp)
    # append doubles, overwrite resets, errorifexists raises
    src.write.jdbc(out_url, "young", mode="append")
    assert len(spark.read.jdbc(out_url, "young").collect()) == 2 * len(exp)
    src.write.jdbc(out_url, "young", mode="overwrite")
    assert len(spark.read.jdbc(out_url, "young").collect()) == len(exp)
    from spark_tpu.expressions import AnalysisException
    with pytest.raises(AnalysisException, match="already exists"):
        src.write.jdbc(out_url, "young", mode="errorifexists")
    # values survive the roundtrip (float + NULL columns)
    got = spark.read.jdbc(out_url, "young").collect()
    assert sorted(round(r["salary"], 6) for r in got) == \
        sorted(round(v, 6) for v in exp.salary)


def test_streamed_scan_over_jdbc(spark, db):
    """A jdbc relation larger than one device batch streams through the
    multibatch runner like any file relation."""
    url, pdf = db
    old = spark.conf.get(C.SCAN_MAX_BATCH_ROWS)
    spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, "128")
    try:
        df = (spark.read.jdbc(url, "emp").groupBy("dept")
              .agg(F.count("*").alias("n")).orderBy("dept"))
        got = {r["dept"]: r["n"] for r in df.collect()}
        exp = pdf.groupby("dept", dropna=False).size()
        for k, v in exp.items():
            assert got[None if pd.isna(k) else k] == v
    finally:
        spark.conf.set(C.SCAN_MAX_BATCH_ROWS.key, str(old))


def test_all_null_partition_concats(spark, tmp_path):
    """One stride partition holding only NULLs in a numeric column must
    concat with typed partitions (pa.null promotion) AND deliver the
    relation-schema dtype (scan casts to the resolved schema)."""
    db = tmp_path / "nulls.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (k INTEGER, v INTEGER)")
    # k<5 rows: v all NULL; k>=5 rows: v integers
    conn.executemany("INSERT INTO t VALUES (?,?)",
                     [(i, None) for i in range(5)] +
                     [(i, i * 10) for i in range(5, 10)])
    conn.commit()
    conn.close()
    url = f"jdbc:sqlite:{db}"
    df = spark.read.jdbc(url, "t", column="k", lowerBound=0,
                         upperBound=10, numPartitions=2)
    got = sorted((r["k"], r["v"]) for r in df.collect())
    assert got == [(i, None) for i in range(5)] + \
        [(i, i * 10) for i in range(5, 10)]
    assert df.schema["v"].dataType.is_numeric


def test_declared_schema_reaches_scan(spark, tmp_path):
    """.schema(...) on the reader must become the scan's cast target —
    a column NULL throughout the inference sample still arrives with the
    declared dtype (JDBCRDD fixes the schema at resolveTable time)."""
    db = tmp_path / "sparse.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE s (k INTEGER, v INTEGER)")
    # v is NULL for the first 300 rows (inference sample sees only NULLs)
    conn.executemany("INSERT INTO s VALUES (?,?)",
                     [(i, None) for i in range(300)] +
                     [(i, i) for i in range(300, 320)])
    conn.commit(); conn.close()
    url = f"jdbc:sqlite:{db}"
    df = (spark.read.format("jdbc").option("url", url)
          .option("dbtable", "s").schema("k long, v long").load(url))
    assert df.schema["v"].dataType.is_numeric
    got = sorted((r["k"], r["v"]) for r in df.collect())
    assert got[:3] == [(0, None), (1, None), (2, None)]
    assert got[-1] == (319, 319)
    assert isinstance(got[-1][1], int)


def test_write_bootstraps_new_database(spark, tmp_path):
    """DataFrameWriter.jdbc must create a brand-new sqlite file (the
    read path's missing-file guard must not leak into writes)."""
    out = tmp_path / "fresh.db"           # does NOT exist
    df = spark.createDataFrame([(1, "a"), (2, "b")], ["n", "s"])
    df.write.jdbc(f"jdbc:sqlite:{out}", "t", mode="overwrite")
    back = spark.read.jdbc(f"jdbc:sqlite:{out}", "t")
    assert sorted((r["n"], r["s"]) for r in back.collect()) == \
        [(1, "a"), (2, "b")]


def test_error_discipline(spark, db, tmp_path):
    """User mistakes surface as AnalysisException with context, never raw
    driver exceptions; :memory: urls are rejected up front."""
    from spark_tpu.expressions import AnalysisException
    url, _ = db
    with pytest.raises(AnalysisException, match="no such table"):
        spark.read.jdbc(url, "emp_typo")
    with pytest.raises(AnalysisException, match="memory"):
        spark.read.jdbc("jdbc:sqlite::memory:", "t")


def test_append_binds_by_column_name(spark, tmp_path):
    """Append into a pre-existing table whose column ORDER differs from
    the DataFrame's must bind by name, not position."""
    db = tmp_path / "order.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (id INTEGER, name TEXT)")
    conn.execute("INSERT INTO t VALUES (1, 'a')")
    conn.commit(); conn.close()
    url = f"jdbc:sqlite:{db}"
    # DataFrame columns deliberately reversed: (name, id)
    df = spark.createDataFrame([("b", 2)], ["name", "id"])
    df.write.jdbc(url, "t", mode="append")
    got = sorted((r["id"], r["name"])
                 for r in spark.read.jdbc(url, "t").collect())
    assert got == [(1, "a"), (2, "b")]
