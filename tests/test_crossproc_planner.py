"""Planner-citizen cross-process execution, single-process degenerate
form (n=1: every exchange is a self-loop).  The REAL two-process
validation lives in test_cluster_twoproc.py (PLANNER-CITIZEN-Q3-OK /
GENERIC-PATH-DISTINCT-OK); this file keeps the routing, fast-path /
generic-path split, and above-op replay covered in the plain suite."""

import numpy as np
import pytest

import spark_tpu.sql.functions as F


@pytest.fixture()
def xs(spark, tmp_path):
    s = spark.newSession()
    s.conf.set("spark.tpu.mesh.shards", "1")
    s.enableHostShuffle(str(tmp_path / "hs"), process_id=0, n_processes=1,
                        timeout_s=30.0)
    yield s
    s.disableHostShuffle()


def _mk(xs):
    rng = np.random.default_rng(3)
    xs.createDataFrame({
        "sk": rng.integers(0, 16, 500).astype(np.int64),
        "price": rng.integers(1, 100, 500).astype(np.int64),
    }).createOrReplaceTempView("fact")
    xs.createDataFrame({
        "d_sk": np.arange(16, dtype=np.int64),
        "brand": (np.arange(16, dtype=np.int64) % 5),
        "year": np.where(np.arange(16) % 2 == 0, 2000, 2001).astype(np.int64),
    }).createOrReplaceTempView("dim")


def test_fast_path_full_q3(xs, spark):
    _mk(xs)
    q = ("SELECT brand, sum(price) AS rev FROM fact JOIN dim ON sk = d_sk "
         "WHERE year = 2000 GROUP BY brand ORDER BY rev DESC, brand")
    got = [tuple(r) for r in xs.sql(q).collect()]
    _mk(spark)  # same data, no crossproc routing
    exp = [tuple(r) for r in spark.sql(q).collect()]
    assert got == exp and len(got) > 0


def test_generic_path_distinct_window_limit(xs, spark):
    _mk(xs)
    _mk(spark)
    for q in [
        "SELECT DISTINCT sk FROM fact WHERE sk < 6 ORDER BY sk",
        ("SELECT sk, price, rank() OVER "
         "(PARTITION BY sk ORDER BY price) AS r FROM fact "
         "WHERE sk = 3 ORDER BY price, r LIMIT 5"),
        "SELECT sk FROM fact ORDER BY sk LIMIT 7",
    ]:
        got = [tuple(r) for r in xs.sql(q).collect()]
        exp = [tuple(r) for r in spark.sql(q).collect()]
        assert got == exp, q


def test_global_agg_routes(xs, spark):
    _mk(xs)
    _mk(spark)
    q = "SELECT sum(price) AS s, count(*) AS c FROM fact"
    assert [tuple(r) for r in xs.sql(q).collect()] == \
        [tuple(r) for r in spark.sql(q).collect()]


def test_disable_restores_local_path(xs):
    _mk(xs)
    xs.disableHostShuffle()
    out = xs.sql("SELECT count(*) AS c FROM fact").collect()
    assert out[0]["c"] == 500
